# Launch targets mirroring the reference's Makefile (Makefile:25-47) and
# run_approx_coding.sh — same target names, one per collection scheme —
# with `mpirun -np N python main.py <13 args>` replaced by the TPU CLI
# (no MPI: schemes run as SPMD collectives over the device mesh).
#
# The reference's Makefile passes a stale 10-arg signature (SURVEY.md §2.5);
# these targets use the supported named-flag form instead. The legacy
# 13-positional-arg form also works:
#   python -m erasurehead_tpu.cli $(N_PROCS) $(N_ROWS) $(N_COLS) $(DATA_DIR) \
#       0 artificial 1 $(N_STRAGGLERS) 0 3 $(N_COLLECT) 1 AGD

# bash: the tier1 recipe needs pipefail, which POSIX sh lacks
SHELL         := /bin/bash
PY            ?= python
# canonical run shape (run_approx_coding.sh:2-9): 31 procs = 30 workers + master.
# The reference's own s=3 there violates its FRC guard (s+1) | W for the
# replication-family schemes (src/replication.py:24-26; 30 % 4 != 0), so the
# default here is the nearest valid s=2 (10 groups of 3).
N_WORKERS     ?= 30
N_STRAGGLERS  ?= 2
N_COLLECT     ?= 15
DEADLINE      ?= 1.0
ROUNDS        ?= 100
UPDATE_RULE   ?= AGD
# synthetic GMM shape (reference Makefile:19-20 uses 54000x100-class sizes)
N_ROWS        ?= 54000
N_COLS        ?= 100
DATASET       ?= artificial
DATA_DIR      ?= ./straggdata
# partial schemes: partitions held per worker = n_separate + s + 1
# (src/partial_coded.py:20-22). 5 with s=2 -> (5-2)*30 = 90 data partitions,
# which divides N_ROWS=54000.
N_PARTITIONS  ?= 5
ADD_DELAY     ?= --add-delay

RUN = $(PY) -m erasurehead_tpu.cli --workers $(N_WORKERS) \
	--stragglers $(N_STRAGGLERS) --rounds $(ROUNDS) \
	--update-rule $(UPDATE_RULE) --rows $(N_ROWS) --cols $(N_COLS) \
	--dataset $(DATASET) --input-dir $(DATA_DIR) $(ADD_DELAY)

.PHONY: naive cyccoded repcoded avoidstragg approxcoded \
	partialrepcoded partialcyccoded randreg deadline \
	generate_random_data arrange_real_data \
	test lint tier1 bench sweep rehearse watch compare real_data dryrun \
	telemetry-smoke sweep-batch-smoke chaos-smoke roofline-smoke \
	serve-smoke serve-load-smoke serve-chaos-smoke adapt-smoke \
	deep-smoke elastic-smoke whatif-smoke outofcore-smoke \
	pipeline-smoke obs-smoke tune-smoke fleet-smoke clean

naive:            ## uncoded wait-for-all baseline (src/naive.py)
	$(RUN) --scheme naive

cyccoded:         ## exact gradient coding, cyclic MDS (src/coded.py)
	$(RUN) --scheme cyccoded

repcoded:         ## exact gradient coding, FRC groups (src/replication.py)
	$(RUN) --scheme repcoded

approxcoded:      ## approximate gradient coding — the paper (src/approximate_coding.py)
	$(RUN) --scheme approx --num-collect $(N_COLLECT)

avoidstragg:      ## ignore-stragglers baseline (src/avoidstragg.py)
	$(RUN) --scheme avoidstragg

partialcyccoded:  ## two-part partial MDS scheme (src/partial_coded.py)
	$(RUN) --scheme partialcyccoded --partitions-per-worker $(N_PARTITIONS)

partialrepcoded:  ## two-part partial FRC scheme (src/partial_replication.py)
	$(RUN) --scheme partialrepcoded --partitions-per-worker $(N_PARTITIONS)

randreg:          ## beyond-reference: random-regular code + optimal decode
	$(RUN) --scheme randreg --num-collect $(N_COLLECT)

deadline:         ## beyond-reference: fixed per-round deadline collection
	$(RUN) --scheme deadline --deadline $(DEADLINE)

generate_random_data:  ## synthetic GMM partitions (src/generate_data.py)
	$(PY) -m erasurehead_tpu.data.prepare synthetic --rows $(N_ROWS) \
		--cols $(N_COLS) --workers $(N_WORKERS) --out $(DATA_DIR)

arrange_real_data:     ## real-dataset partitions (src/arrange_real_data.py); set DATASET + SOURCE
	$(PY) -m erasurehead_tpu.data.prepare real --dataset $(DATASET) \
		--source $(SOURCE) --workers $(N_WORKERS) --out $(DATA_DIR)

compare:          ## AGC vs EGC vs uncoded sweep (BASELINE.json north star)
	$(PY) -m erasurehead_tpu.train.experiments

real_data:        ## canonical comparison on genuinely real (UCI) data
	$(PY) tools/real_data_run.py

test:
	$(PY) -m pytest tests/ -x -q

lint:             ## AST invariant analyzer (erasurehead_tpu/analysis/): trace/cache/telemetry contracts
	$(PY) -m erasurehead_tpu.analysis --strict erasurehead_tpu/ tools/

tier1: lint       ## the ROADMAP tier-1 verify line (what CI gates on)
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
		-m 'not slow' --continue-on-collection-errors \
		-p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
		| tee /tmp/_t1.log; rc=$$?; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

bench:
	$(PY) bench.py

TELEMETRY_SMOKE_DIR ?= /tmp/eh-telemetry-smoke
telemetry-smoke:  ## tiny CPU run with --telemetry on, then schema-check + render the event log
	rm -rf $(TELEMETRY_SMOKE_DIR)
	JAX_PLATFORMS=cpu $(PY) -m erasurehead_tpu.cli --scheme approx \
		--workers 4 --stragglers 1 --num-collect 3 --rounds 3 \
		--rows 64 --cols 8 --lr 1.0 --add-delay --compute-mode deduped \
		--telemetry on --output-dir $(TELEMETRY_SMOKE_DIR) --quiet
	$(PY) tools/validate_events.py $(TELEMETRY_SMOKE_DIR)/events.jsonl
	$(PY) -m erasurehead_tpu.cli report $(TELEMETRY_SMOKE_DIR)/events.jsonl

sweep-batch-smoke:  ## CPU 7-scheme x 2-seed cohort compare; asserts dispatches <= cohorts via telemetry counters
	JAX_PLATFORMS=cpu $(PY) tools/sweep_batch_smoke.py

chaos-smoke:      ## CPU kill->resume + cohort-degradation cycle: chaos-killed sweep resumes from its journal with identical rows (tools/chaos_sweep.py)
	JAX_PLATFORMS=cpu $(PY) tools/chaos_sweep.py

roofline-smoke:   ## CPU ring+pipelined+int8 sweep: asserts bytes accounting, dispatch counts, and the f32 bitwise pins (tools/roofline_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/roofline_smoke.py

serve-smoke:      ## CPU serve daemon race: 4 clients pack into shared dispatches, rows bitwise vs sequential (tools/serve_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/serve_smoke.py

serve-load-smoke: ## CPU HTTP-front load harness: closed-loop fleet, 2x-capacity backpressure (0 lost/dup), fairness >= 0.5x under a flooding tenant, warm restart with 0 recompiles (tools/serve_load_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/serve_load_smoke.py

serve-chaos-smoke: ## CPU restart-under-load with REAL kills: daemon dies mid-dispatch (chaos serve_dispatch), restarts, WAL replays, rows rehydrate bitwise, 0 recompiles of warm signatures (tools/serve_chaos_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/serve_chaos_smoke.py

fleet-smoke:      ## CPU serve-fleet drill: 3 replicas + router, one replica REALLY killed mid-dispatch (chaos fleet_replica), K-streak death + WAL adoption replays bitwise on a peer, rolling deploy under 2x load with 0 lost/dup (tools/fleet_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/fleet_smoke.py

outofcore-smoke:  ## CPU shard-store->streamed sweep->kill mid-prefetch->resume: journal rehydrates completed rows bitwise (tools/outofcore_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/outofcore_smoke.py

adapt-smoke:      ## CPU regime-shift drive of the adaptive controller: policy switches, adapt events validate, decisions replay bitwise (tools/adapt_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/adapt_smoke.py

deep-smoke:       ## CPU W=8 attention cohort with per-layer coding: 1 dispatch, bitwise layer-decode pin, layer-tagged events validate (tools/deep_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/deep_smoke.py

elastic-smoke:    ## CPU chaos-driven die-then-rejoin + kill->resume row rehydration through the elastic membership controller (tools/elastic_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/elastic_smoke.py

whatif-smoke:     ## CPU what-if cycle: tiny grid -> surface artifact -> adapt priors + serve ETA round-trips, events validate, identical-spec rerun bitwise (tools/whatif_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/whatif_smoke.py

pipeline-smoke:   ## CPU sync vs tau=1 pipelined race at exp(2.0): pipelined time-to-target <= sync, bitwise replay, tau=0 collapse, typed events validate (tools/pipeline_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/pipeline_smoke.py

obs-smoke:        ## CPU live-telemetry drive: critical-path ledgers close, reducer tails the log, regime shift detected in budget, /metrics exposition valid, bitwise dark rerun (tools/obs_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/obs_smoke.py

tune-smoke:       ## CPU autotuning-plane drive: cold race -> byte-identical re-race, auto resolves from cache (<1ms, bitwise vs forced), chaos kill leaves no cache (tools/tune_smoke.py)
	JAX_PLATFORMS=cpu $(PY) tools/tune_smoke.py

sweep:            ## the full on-TPU measurement program (resumable, tagged)
	bash tools/tpu_measurements.sh
	bash tools/tpu_measurements_flat.sh

rehearse:         ## CPU rehearsal of every queued sweep entry (light form)
	bash tools/sweep_rehearsal.sh

watch:            ## probe the relay; run the sweep in the first healthy window
	bash tools/relay_watch.sh

dryrun:           ## validate the multi-chip sharding on a virtual 8-device CPU mesh
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
		$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

clean:
	rm -rf build/ $(DATA_DIR)/artificial-data
