"""utils/tracing.py coverage: the profiling/tracing helpers.

``annotate`` must be safe both eagerly and under jit (it names the scan
phases inside the compiled training step, parallel/step.py — an op-name
scope can never change the math), ``device_trace(None)`` must be a no-op,
and StepTimer's aggregates must handle the empty-laps case.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from erasurehead_tpu.utils import tracing


def test_device_trace_none_is_noop():
    """No log dir -> no profiler session; computation inside unaffected."""
    with tracing.device_trace(None):
        out = jnp.sum(jnp.arange(4.0))
    assert float(out) == 6.0
    with tracing.device_trace(""):  # falsy string: same contract
        pass


def test_annotate_round_trips_under_cpu_jit():
    """annotate inside a jitted function must not change results — the
    named scope is op metadata only. Pin eager == jit == unannotated."""

    def plain(x):
        return x * 2.0 + 1.0

    def annotated(x):
        with tracing.annotate("eh_test/phase"):
            y = x * 2.0
        with tracing.annotate("eh_test/other"):
            return y + 1.0

    x = jnp.arange(6.0).reshape(2, 3)
    expected = np.asarray(plain(x))
    np.testing.assert_array_equal(np.asarray(annotated(x)), expected)
    np.testing.assert_array_equal(
        np.asarray(jax.jit(annotated)(x)), expected
    )


def test_annotate_under_grad_and_scan():
    """The training scan wraps its body phases in annotate; differentiation
    and scan tracing must pass through the scopes untouched."""

    def loss(p, x):
        with tracing.annotate("eh_test/grad_region"):
            return jnp.sum((p * x) ** 2)

    g = jax.grad(loss)(2.0, jnp.ones(3))
    assert np.isclose(float(g), 12.0)

    def body(c, x):
        with tracing.annotate("eh_test/scan_body"):
            return c + x, c

    @jax.jit
    def run(xs):
        return jax.lax.scan(body, 0.0, xs)

    final, hist = run(jnp.arange(4.0))
    assert float(final) == 6.0
    np.testing.assert_array_equal(np.asarray(hist), [0.0, 0.0, 1.0, 3.0])


def test_steptimer_empty_laps():
    t = tracing.StepTimer()
    assert t.laps == []
    assert t.total == 0.0
    assert t.mean == 0.0  # no ZeroDivisionError on the empty case


def test_steptimer_accumulates():
    t = tracing.StepTimer()
    for _ in range(3):
        with t:
            time.sleep(0.001)
    assert len(t.laps) == 3
    assert all(lap > 0.0 for lap in t.laps)
    assert np.isclose(t.total, sum(t.laps))
    assert np.isclose(t.mean, t.total / 3)
