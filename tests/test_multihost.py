"""Real multi-controller test: a 2-process CPU cluster via jax.distributed.

This drives the actual multi-host code path — ``jax.distributed.initialize``
(parallel/backend.py), a worker mesh spanning both processes' devices, and
``put_global``'s make_array_from_callback sharding (data/sharding.py) — the
TPU-pod analogue of the reference's mpirun+hostfile bring-up (SURVEY.md
§2.3/§3.5). Each process owns 2 virtual CPU devices; the 4-device mesh spans
them; the AGC trajectory must equal the single-process run bit-for-bit.
"""

import os
import subprocess
import sys

from conftest import CPU_CLUSTER_SUPPORTED, cpu_cluster_env, free_port
import textwrap

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    not CPU_CLUSTER_SUPPORTED,
    reason="this jaxlib's CPU backend cannot compile multiprocess "
    "computations (see conftest.CPU_CLUSTER_SUPPORTED)",
)

W, ROUNDS, COLS = 4, 3, 16

_CHILD = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    import jax

    jax.distributed.initialize(
        coordinator_address=os.environ["EH_COORD"],
        num_processes=2,
        process_id=int(os.environ["EH_PID"]),
    )
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel import backend
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    info = backend.topology_info()
    assert info["process_count"] == 2, info
    assert info["global_devices"] == 4, info

    cfg = RunConfig(
        scheme="approx", n_workers=%(W)d, n_stragglers=1, rounds=%(ROUNDS)d,
        n_rows=8 * %(W)d, n_cols=%(COLS)d, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=%(W)d, seed=0)
    res = trainer.train(cfg, data, mesh=worker_mesh(4), measure=False)
    hist = np.asarray(res.params_history)

    # sparse PaddedRows stacks sharded across BOTH processes (the
    # covtype/amazon one-hot path under multi-controller put_global)
    from erasurehead_tpu.data.synthetic import generate_onehot

    sdata = generate_onehot(
        cfg.n_rows, cfg.n_cols, n_partitions=%(W)d, n_fields=4, seed=0
    )
    sres = trainer.train(cfg, sdata, mesh=worker_mesh(4), measure=False)
    shist = np.asarray(sres.params_history)

    # FieldOnehot pair-table stacks under multi-controller put_global
    import dataclasses
    fcfg = dataclasses.replace(cfg, sparse_format="fields")
    fres = trainer.train(fcfg, sdata, mesh=worker_mesh(4), measure=False)
    fhist = np.asarray(fres.params_history)

    # SP x DP with the seq axis SPANNING the process boundary: a 1x4
    # (workers, seq) mesh puts ring attention's ppermute hops on the
    # cross-process link — the DCN analogue of a multi-host pod
    from erasurehead_tpu.parallel.mesh import worker_seq_mesh
    acfg = dataclasses.replace(
        cfg, model="attention", seq_shards=4, n_cols=32,
        update_rule="GD", lr_schedule=0.1,
    )
    adata = generate_gmm(acfg.n_rows, 32, n_partitions=%(W)d, seed=0)
    ares = trainer.train(
        acfg, adata, mesh=worker_seq_mesh(4, 1), measure=False
    )
    aleaves = [np.asarray(l) for l in jax.tree.leaves(ares.params_history)]

    if info["process_index"] == 0:
        np.save(os.environ["EH_OUT"], hist)
        np.save(os.environ["EH_OUT_SPARSE"], shist)
        np.save(os.environ["EH_OUT_FIELDS"], fhist)
        np.savez(os.environ["EH_OUT_ATTN"], *aleaves)
    """
    % {"W": W, "ROUNDS": ROUNDS, "COLS": COLS}
)


# 4-process cluster, 2 devices each, COMPOSED 2-D mesh (VERDICT r2 item 7):
# the 4x2 (workers, model) grid puts coded-DP across the process boundary
# (the DCN axis on a real pod) with tensor parallelism inside each process
# — exactly a v4-32 deployment's layout (k8s jobset: tools/k8s/).
_CHILD_4P = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    import jax

    jax.distributed.initialize(
        coordinator_address=os.environ["EH_COORD"],
        num_processes=4,
        process_id=int(os.environ["EH_PID"]),
    )
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel import backend
    from erasurehead_tpu.parallel.mesh import worker_tp_mesh
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    info = backend.topology_info()
    assert info["process_count"] == 4, info
    assert info["global_devices"] == 8, info

    cfg = RunConfig(
        scheme="approx", model="mlp", tp_shards=2, n_workers=4,
        n_stragglers=1, rounds=3, n_rows=32, n_cols=16,
        lr_schedule=0.5, update_rule="GD", add_delay=True, seed=0,
    )
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=4, seed=0)
    res = trainer.train(cfg, data, mesh=worker_tp_mesh(2, 4), measure=False)
    leaves = [np.asarray(l) for l in jax.tree.leaves(res.params_history)]

    if info["process_index"] == 0:
        np.savez(os.environ["EH_OUT"], *leaves)
    """
)


def test_four_process_composed_tp_dp_mesh_matches_single_process(tmp_path):
    """4 controllers x 2 devices: the workers axis crosses all four
    processes while the MLP's hidden dim shards inside each — the
    trajectory must match the 8-device single-process run bit-for-bit
    (same mesh shape, same shardings, only the process topology differs)."""
    out = str(tmp_path / "hist_4p.npz")
    env = cpu_cluster_env(
        local_devices=2,
        EH_COORD=f"127.0.0.1:{free_port()}",
        EH_OUT=out,
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD_4P],
            env={**env, "EH_PID": str(pid)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for pid in range(4)
    ]
    try:
        logs = [p.communicate(timeout=300)[0].decode() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"child failed:\n{log}"

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel.mesh import worker_tp_mesh
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    cfg = RunConfig(
        scheme="approx", model="mlp", tp_shards=2, n_workers=4,
        n_stragglers=1, rounds=3, n_rows=32, n_cols=16,
        lr_schedule=0.5, update_rule="GD", add_delay=True, seed=0,
    )
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=4, seed=0)
    res = trainer.train(cfg, data, mesh=worker_tp_mesh(2, 4), measure=False)
    want = [np.asarray(l) for l in __import__("jax").tree.leaves(
        res.params_history)]
    with np.load(out) as got:
        got_leaves = [got[k] for k in got.files]
    assert len(got_leaves) == len(want)
    for g, w in zip(got_leaves, want):
        np.testing.assert_allclose(g, w, rtol=1e-6, atol=1e-7)


def test_two_process_cpu_cluster_matches_single_process(tmp_path):
    out = str(tmp_path / "hist.npy")
    out_sparse = str(tmp_path / "hist_sparse.npy")
    out_fields = str(tmp_path / "hist_fields.npy")
    out_attn = str(tmp_path / "hist_attn.npz")
    env = cpu_cluster_env(
        local_devices=2,
        EH_COORD=f"127.0.0.1:{free_port()}",
        EH_OUT=out,
        EH_OUT_SPARSE=out_sparse,
        EH_OUT_FIELDS=out_fields,
        EH_OUT_ATTN=out_attn,
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD],
            env={**env, "EH_PID": str(pid)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for pid in (0, 1)
    ]
    try:
        logs = [p.communicate(timeout=300)[0].decode() for p in procs]
    finally:
        for p in procs:  # a timeout must not orphan the other child
            if p.poll() is None:
                p.kill()
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"child failed:\n{log}"

    # single-process oracle on the 8-device conftest mesh, trimmed to 4
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    cfg = RunConfig(
        scheme="approx", n_workers=W, n_stragglers=1, rounds=ROUNDS,
        n_rows=8 * W, n_cols=COLS, lr_schedule=1.0, update_rule="AGD",
        add_delay=True, seed=0,
    )
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=W, seed=0)
    res = trainer.train(cfg, data, mesh=worker_mesh(4), measure=False)
    want = np.asarray(res.params_history)

    got = np.load(out)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)

    # sparse path: same cluster, PaddedRows stacks spanning both processes
    from erasurehead_tpu.data.synthetic import generate_onehot

    sdata = generate_onehot(cfg.n_rows, cfg.n_cols, n_partitions=W,
                            n_fields=4, seed=0)
    sres = trainer.train(cfg, sdata, mesh=worker_mesh(4), measure=False)
    np.testing.assert_allclose(
        np.load(out_sparse), np.asarray(sres.params_history),
        rtol=1e-6, atol=1e-7,
    )

    # FieldOnehot stacks: cluster == single-process
    import dataclasses

    fcfg = dataclasses.replace(cfg, sparse_format="fields")
    fres = trainer.train(fcfg, sdata, mesh=worker_mesh(4), measure=False)
    np.testing.assert_allclose(
        np.load(out_fields), np.asarray(fres.params_history),
        rtol=1e-6, atol=1e-7,
    )

    # SP x DP with cross-process ring hops == the unsharded trajectory
    # (looser tolerance: the ring's online softmax reassociates f32)
    import jax

    acfg = dataclasses.replace(
        cfg, model="attention", n_cols=32, update_rule="GD",
        lr_schedule=0.1,
    )
    adata = generate_gmm(acfg.n_rows, 32, n_partitions=W, seed=0)
    ares = trainer.train(acfg, adata, mesh=worker_mesh(4), measure=False)
    with np.load(out_attn) as got_attn:
        got_leaves = [got_attn[k] for k in got_attn.files]
    want_leaves = [np.asarray(l) for l in jax.tree.leaves(ares.params_history)]
    assert len(got_leaves) == len(want_leaves)
    for g, w in zip(got_leaves, want_leaves):
        np.testing.assert_allclose(g, w, rtol=2e-4, atol=2e-5)


# The production preemption drill (the JobSet deployment's failure story):
# a 2-process cluster training WITH checkpointing is SIGKILLed after its
# first checkpoint lands on the shared volume, then the identical command
# relaunches with resume — k8s restarting the Job — and the resumed
# trajectory must land exactly where an uninterrupted cluster run does.
_CHILD_CKPT = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    import jax

    jax.distributed.initialize(
        coordinator_address=os.environ["EH_COORD"],
        num_processes=2,
        process_id=int(os.environ["EH_PID"]),
    )
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    cfg = RunConfig(
        scheme="approx", n_workers=4, n_stragglers=1, num_collect=3,
        rounds=12, n_rows=32, n_cols=16, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=4, seed=0)
    kw = {}
    if os.environ.get("EH_CKPT"):
        kw = dict(
            checkpoint_dir=os.environ["EH_CKPT"],
            checkpoint_every=2,
            resume=os.environ.get("EH_RESUME") == "1",
        )
    res = trainer.train(cfg, data, mesh=worker_mesh(4), measure=False, **kw)
    if jax.process_index() == 0 and os.environ.get("EH_OUT"):
        np.save(os.environ["EH_OUT"], np.asarray(res.final_params))
    """
)


def _launch_ckpt_pair(env, extra):
    return [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD_CKPT],
            env={**env, **extra, "EH_PID": str(pid)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for pid in (0, 1)
    ]


def test_pod_cluster_preemption_resume_matches_uninterrupted(tmp_path):
    import time

    # reference trajectory: uninterrupted 2-process cluster run
    out_ref = str(tmp_path / "final_ref.npy")
    env = cpu_cluster_env(
        local_devices=2, EH_COORD=f"127.0.0.1:{free_port()}", EH_OUT=out_ref
    )
    procs = _launch_ckpt_pair(env, {})
    logs = [p.communicate(timeout=300)[0].decode() for p in procs]
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"reference run failed:\n{log[-3000:]}"

    # preempted run: kill both pods once the first checkpoint is complete
    ckdir = str(tmp_path / "shared_ckpt")  # the shared-volume analogue
    env = cpu_cluster_env(
        local_devices=2, EH_COORD=f"127.0.0.1:{free_port()}", EH_CKPT=ckdir
    )
    procs = _launch_ckpt_pair(env, {})
    from erasurehead_tpu.train import checkpoint as ckpt_lib

    deadline = time.time() + 300
    while time.time() < deadline:
        if ckpt_lib.latest(ckdir) is not None or all(
            p.poll() is not None for p in procs
        ):
            break
        time.sleep(0.05)
    preempted = False
    for p in procs:
        if p.poll() is None:
            p.kill()  # SIGKILL: no cleanup, like a node preemption
            preempted = True
    killed_logs = [p.communicate(timeout=60)[0].decode() for p in procs]
    assert ckpt_lib.latest(ckdir) is not None, (
        "no checkpoint before exit:\n"
        + "\n".join(log[-2000:] for log in killed_logs)
    )

    # relaunch the identical command with resume (k8s Job restart)
    out_res = str(tmp_path / "final_resumed.npy")
    env = cpu_cluster_env(
        local_devices=2, EH_COORD=f"127.0.0.1:{free_port()}",
        EH_CKPT=ckdir, EH_RESUME="1", EH_OUT=out_res,
    )
    procs = _launch_ckpt_pair(env, {})
    logs = [p.communicate(timeout=300)[0].decode() for p in procs]
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"resumed run failed:\n{log[-3000:]}"

    np.testing.assert_allclose(
        np.load(out_res), np.load(out_ref), rtol=1e-6, atol=1e-7
    )
    # the drill is only meaningful if the kill usually lands mid-run; log
    # when it degenerated to a completed first run (still a valid resume)
    if not preempted:
        print("note: first run completed before the kill landed")


# The fully on-device control plane and elastic recovery across a REAL
# process boundary: train_dynamic's jitted-scan collection and
# train_elastic's mid-run re-shard both run in a 2-process cluster and
# must match the same-mesh single-process trajectories exactly.
_CHILD_DYNAMIC = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    import jax

    jax.distributed.initialize(
        coordinator_address=os.environ["EH_COORD"],
        num_processes=2,
        process_id=int(os.environ["EH_PID"]),
    )
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel import failures
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    # on-device MDS-table collection in one scan, workers axis spanning
    # both processes
    dcfg = RunConfig(
        scheme="cyccoded", n_workers=4, n_stragglers=1, rounds=6,
        n_rows=16 * 4, n_cols=16, lr_schedule=1.0, update_rule="AGD",
        add_delay=True, seed=0,
    )
    ddata = generate_gmm(dcfg.n_rows, dcfg.n_cols, n_partitions=4, seed=0)
    dres = trainer.train_dynamic(dcfg, ddata, mesh=worker_mesh(4))

    # elastic death mid-run under the on-device deadline control plane:
    # the survivor re-shard moves shards across the process boundary
    W = 8
    ecfg = RunConfig(
        scheme="deadline", deadline=0.8, n_workers=W, n_stragglers=1,
        rounds=12, n_rows=32 * W, n_cols=24, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    edata = generate_gmm(ecfg.n_rows, ecfg.n_cols, n_partitions=W, seed=0)
    eres, erep = failures.train_elastic(
        ecfg, edata, {3: 5}, mesh=worker_mesh(4), dynamic=True,
        measure=False,
    )
    assert erep.n_workers_after == W - 1, erep

    # chained restart with a SUBMESH donor: the survivor phase ran on a
    # 1-device mesh (7 workers, 4 devices); continuing from its final
    # state on the FULL mesh forces replicate() to broadcast the donor
    import dataclasses
    ccfg = dataclasses.replace(ecfg, n_workers=4, rounds=13)
    cres = trainer.train(
        ccfg, edata, mesh=worker_mesh(4),
        initial_state=eres.final_state, initial_round=12, measure=False,
    )

    # np_global: params_history comes straight from the jitted scan and
    # XLA may leave it partitioned across the processes
    from erasurehead_tpu.data.sharding import np_global

    if jax.process_index() == 0:
        np.save(os.environ["EH_OUT_DYN"], np_global(dres.params_history))
        np.save(os.environ["EH_OUT_ELA"], np.asarray(eres.params_history))
        np.save(os.environ["EH_OUT_CHAIN"], np_global(cres.params_history))
    else:
        # collectives: all processes join the fetches pid 0 performs
        np_global(dres.params_history)
        np_global(cres.params_history)
    """
)


def test_dynamic_and_elastic_cluster_match_single_process(tmp_path):
    out_dyn = str(tmp_path / "dyn.npy")
    out_ela = str(tmp_path / "ela.npy")
    out_chain = str(tmp_path / "chain.npy")
    env = cpu_cluster_env(
        local_devices=2,
        EH_COORD=f"127.0.0.1:{free_port()}",
        EH_OUT_DYN=out_dyn,
        EH_OUT_ELA=out_ela,
        EH_OUT_CHAIN=out_chain,
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD_DYNAMIC],
            env={**env, "EH_PID": str(pid)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for pid in (0, 1)
    ]
    try:
        logs = [p.communicate(timeout=420)[0].decode() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"child failed:\n{log[-3000:]}"

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel import failures
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    dcfg = RunConfig(
        scheme="cyccoded", n_workers=4, n_stragglers=1, rounds=6,
        n_rows=16 * 4, n_cols=16, lr_schedule=1.0, update_rule="AGD",
        add_delay=True, seed=0,
    )
    ddata = generate_gmm(dcfg.n_rows, dcfg.n_cols, n_partitions=4, seed=0)
    dres = trainer.train_dynamic(dcfg, ddata, mesh=worker_mesh(4))
    np.testing.assert_allclose(
        np.load(out_dyn), np.asarray(dres.params_history),
        rtol=1e-6, atol=1e-7,
    )

    W = 8
    ecfg = RunConfig(
        scheme="deadline", deadline=0.8, n_workers=W, n_stragglers=1,
        rounds=12, n_rows=32 * W, n_cols=24, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    edata = generate_gmm(ecfg.n_rows, ecfg.n_cols, n_partitions=W, seed=0)
    eres, _ = failures.train_elastic(
        ecfg, edata, {3: 5}, mesh=worker_mesh(4), dynamic=True,
        measure=False,
    )
    np.testing.assert_allclose(
        np.load(out_ela), np.asarray(eres.params_history),
        rtol=1e-6, atol=1e-7,
    )

    import dataclasses
    ccfg = dataclasses.replace(ecfg, n_workers=4, rounds=13)
    cres = trainer.train(
        ccfg, edata, mesh=worker_mesh(4),
        initial_state=eres.final_state, initial_round=12, measure=False,
    )
    np.testing.assert_allclose(
        np.load(out_chain), np.asarray(cres.params_history),
        rtol=1e-6, atol=1e-7,
    )


# Measured-arrival mode in a cluster: every process is a replica master
# timing only its local devices' worker queues; arrival rows and partial
# decoded gradients meet via host allgathers. The replicas must agree
# EXACTLY (identical schedules + identical updates), and every worker
# must have been timed by exactly one process.
_CHILD_MEASURED = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    import jax

    jax.distributed.initialize(
        coordinator_address=os.environ["EH_COORD"],
        num_processes=2,
        process_id=int(os.environ["EH_PID"]),
    )
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    W = 4
    cfg = RunConfig(
        scheme="approx", n_workers=W, n_stragglers=1, num_collect=3,
        rounds=3, n_rows=16 * W, n_cols=16, lr_schedule=1.0,
        update_rule="AGD", add_delay=False, seed=0,
        arrival_mode="measured",
    )
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=W, seed=0)
    mult = np.ones(W, np.int64)
    mult[0] = 40  # one genuinely slow worker
    res = trainer.train_measured(cfg, data, work_multiplier=mult)

    hist = np.asarray(res.params_history)
    assert np.isfinite(hist).all(), "non-finite history"
    # every worker's compute was really timed somewhere: the slow
    # worker's arrival must exceed a fast worker's in every round
    # (worker_times carries -1 for uncollected; compare collected only)
    assert res.worker_times.shape == (cfg.rounds, W)
    np.save(os.environ[f"EH_OUT_{jax.process_index()}"], hist)
    np.save(os.environ[f"EH_WT_{jax.process_index()}"], res.worker_times)
    """
)


def test_measured_mode_cluster_replicas_agree(tmp_path):
    outs = {f"EH_OUT_{i}": str(tmp_path / f"hist{i}.npy") for i in (0, 1)}
    wts = {f"EH_WT_{i}": str(tmp_path / f"wt{i}.npy") for i in (0, 1)}
    env = cpu_cluster_env(
        local_devices=2,
        EH_COORD=f"127.0.0.1:{free_port()}",
        **outs, **wts,
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD_MEASURED],
            env={**env, "EH_PID": str(pid)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for pid in (0, 1)
    ]
    try:
        logs = [p.communicate(timeout=420)[0].decode() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"child failed:\n{log[-3000:]}"

    # replica masters agree bit-for-bit: same schedules, same updates
    h0, h1 = np.load(outs["EH_OUT_0"]), np.load(outs["EH_OUT_1"])
    np.testing.assert_array_equal(h0, h1)
    wt0, wt1 = np.load(wts["EH_WT_0"]), np.load(wts["EH_WT_1"])
    np.testing.assert_array_equal(wt0, wt1)
    # measured heterogeneity is visible: the work-multiplied worker 0
    # arrives later than every fast collected worker, every round
    for r in range(wt0.shape[0]):
        fast = wt0[r, 1:][wt0[r, 1:] >= 0]
        if wt0[r, 0] >= 0 and fast.size:
            assert wt0[r, 0] > fast.min(), (r, wt0[r])


# The canonical W=30 shape on a REAL uneven topology: 30 logical workers
# fold onto 6 of the cluster's 8 devices (auto mesh), leaving process 3
# with NO devices in the run's mesh — the strongest submesh case: data
# upload (put_global zero-shard), compute (a jit whose mesh excludes a
# process), and history fetch must all hold together, and the trajectory
# must equal the same-mesh single-process run.
_CHILD_W30 = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    import jax

    jax.distributed.initialize(
        coordinator_address=os.environ["EH_COORD"],
        num_processes=4,
        process_id=int(os.environ["EH_PID"]),
    )
    from erasurehead_tpu.data.sharding import np_global
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    W = 30
    cfg = RunConfig(
        scheme="approx", n_workers=W, n_stragglers=2, num_collect=15,
        rounds=3, n_rows=16 * W, n_cols=24, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=W, seed=0)
    # pin the premise: the auto mesh must be the 6-device uneven fold
    # that EXCLUDES process 3 — the coverage this test exists for
    mesh = trainer._auto_mesh(W)
    assert mesh.devices.size == 6, mesh
    mine = [d for d in mesh.devices.flat
            if d.process_index == jax.process_index()]
    if jax.process_index() == 3:
        assert not mine, mine

    res = trainer.train(cfg, data, measure=False)  # auto mesh: 6 devices
    assert res.layout.n_workers == W
    hist = np_global(res.params_history)
    if jax.process_index() == 0:
        np.save(os.environ["EH_OUT"], hist)
    """
)


def test_canonical_w30_uneven_fold_cluster_matches_single_process(tmp_path):
    out = str(tmp_path / "w30.npy")
    env = cpu_cluster_env(
        local_devices=2,
        EH_COORD=f"127.0.0.1:{free_port()}",
        EH_OUT=out,
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD_W30],
            env={**env, "EH_PID": str(pid)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for pid in range(4)
    ]
    try:
        logs = [p.communicate(timeout=420)[0].decode() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"child failed:\n{log[-3000:]}"

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    W = 30
    cfg = RunConfig(
        scheme="approx", n_workers=W, n_stragglers=2, num_collect=15,
        rounds=3, n_rows=16 * W, n_cols=24, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=W, seed=0)
    res = trainer.train(cfg, data, mesh=worker_mesh(6), measure=False)
    np.testing.assert_allclose(
        np.load(out), np.asarray(res.params_history), rtol=1e-6, atol=1e-7
    )
