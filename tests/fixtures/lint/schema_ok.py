"""Clean counterpart: required fields present, optional fields riding
along, a **splat payload (runtime-validated), and a local helper that
happens to be named emit (not the event sink)."""

from erasurehead_tpu.obs import events as events_lib


def emit_run(run_id, fields):
    events_lib.emit(
        "compile", run_id=run_id, seconds=1.0, cache_hit=False,
        chunk_rounds=10,  # optional extras ride along
    )
    events_lib.emit("rounds", **fields)  # dynamic payload: runtime's job
    events_lib.emit(  # membership record, full required set + extras
        "membership", round=5, action="relayout", n_workers=6,
        workers=[0, 1, 2, 3, 4, 5], epoch=1,
    )
    events_lib.emit(  # whatif record, full required set + extras
        "whatif", spec_hash="abc123", kind="point",
        label="approx:c4@W8s1/exp0.5", feasible=True,
    )
    events_lib.emit(  # tune record: known race + source, full field set
        "tune", race="block_decode", device_kind="cpu",
        shape="model=DeepMLPModel|nl=4", choice="fused", source="cache",
    )


def write_artifacts(paths):
    def emit(name, data):  # a local helper named emit, not the event sink
        paths[name] = data

    emit("training_loss", [1.0])
