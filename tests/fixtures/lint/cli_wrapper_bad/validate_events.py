"""A drifted CLI wrapper: re-implements record-type validation with its
own table instead of delegating to obs.events — the exact drift the
shared-validator design exists to prevent (flagged twice: no delegation,
independent type table)."""

import json
import sys

MY_SCHEMA = {
    "run_start": ("run_id",),
    "run_end": ("run_id",),
    "compile": ("run_id", "seconds"),
}


def main(path):
    errors = 0
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") not in MY_SCHEMA:
                errors += 1
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
