"""event-schema violations against the ISSUE-17 window-plan contract: a
``prefetch`` emit carrying the byte account but missing the ``ranges``
list (the staged ``[lo, hi)`` spans an assignment-aware window plan
stages in ring-hop order — data/sharding.StreamWindowPlan), and a
logger-object ``prefetch`` emit missing both ``ranges`` and ``bytes`` —
the contracts the windowed prefetcher (data/prefetch.py) must satisfy."""

from erasurehead_tpu.obs import events as events_lib


def emit_window_plan(logger):
    # missing ranges (the window-plan field)
    events_lib.emit("prefetch", run_id="r", window=0, bytes=4096)
    logger.emit("prefetch", run_id="r", window=1)  # missing bytes, ranges
