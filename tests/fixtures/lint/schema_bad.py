"""event-schema violations: a missing required field, an unknown record
type, and a logger-object emit missing a required field."""

from erasurehead_tpu.obs import events as events_lib


def emit_run(run_id, logger):
    events_lib.emit("compile", run_id=run_id)  # missing seconds, cache_hit
    events_lib.emit("not_in_schema", run_id=run_id)  # unknown type
    logger.emit("run_end", run_id=run_id)  # missing wall_time_s et al.
