"""event-schema violations against the elastic `membership` record: a
missing required field (action/n_workers absent), and a journal-logger
emit missing the round — the contract the elastic driver's decision
journal (elastic/driver.py) must satisfy."""

from erasurehead_tpu.obs import events as events_lib


def emit_membership(logger):
    events_lib.emit("membership", round=0)  # missing action, n_workers
    logger.emit("membership", action="death", n_workers=4)  # missing round
