"""whatif event-schema violations: an events-module emit missing the
required spec_hash, and a logger-object emit missing both required
fields — the what-if engine's record type is lint-enforced like every
other (the fixture for ISSUE 12's `whatif` SCHEMA entry)."""

from erasurehead_tpu.obs import events as events_lib


def emit_whatif(logger):
    events_lib.emit("whatif", kind="grid", n_points=4)  # missing spec_hash
    logger.emit("whatif", n_rows=9)  # missing spec_hash AND kind
