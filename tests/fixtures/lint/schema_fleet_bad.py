"""event-schema violations against the fleet records (serve/fleet.py,
serve/router.py): a ``fleet`` emit missing its action, one missing the
replica it concerns, and a logger-object emit missing both — the
contracts the supervisor's probe/death/adoption/deploy telemetry must
satisfy for `make fleet-smoke`'s validation leg to mean anything."""

from erasurehead_tpu.obs import events as events_lib


def emit_fleet(logger):
    events_lib.emit("fleet", replica="r0")  # missing action
    events_lib.emit("fleet", action="suspect")  # missing replica
    logger.emit("fleet", streak=3)  # missing action AND replica
