"""Clean counterpart of purity_bad: the same effects, host-side — outside
any traced body — plus traced-pure jax.random, which stays legal."""

import time

import jax
import numpy as np
from erasurehead_tpu.obs import events as obs_events


def scan_body(carry, x):
    noise = jax.random.normal(jax.random.PRNGKey(0))
    return carry + x + noise, None


def run(xs):
    t0 = time.time()  # host-side: fine
    out, _ = jax.lax.scan(scan_body, 0.0, xs)
    obs_events.emit(
        "warning", kind="timing", message=f"{time.time() - t0}"
    )  # host-side, after the dispatch: fine
    print("done", np.random.normal())  # host-side: fine
    return out
