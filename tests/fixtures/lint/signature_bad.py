"""signature-completeness violation: the PR 2 mutation — a jitted closure
reads RunConfig fields (delay_mean, num_collect) that are NOT in
static_signature_fields(), so the executable cache cannot key on them and
a changed value silently hits a stale compiled program."""

import jax


def train(cfg, xs):
    def body(carry, x):
        # delay_mean and num_collect are real RunConfig fields, absent
        # from the static signature -> both flagged
        step = carry * cfg.delay_mean + cfg.num_collect
        return step + x, None

    def _run(state, chunk):
        return jax.lax.scan(body, state, chunk, unroll=cfg.scan_unroll)

    run = jax.jit(_run)
    return run(0.0, xs)
