"""registry-dispatch violations, including the classic if/elif spine the
old grep test already caught."""

from erasurehead_tpu.utils.config import Scheme


def stop_count(cfg):
    if cfg.scheme == Scheme.APPROX:  # enum compare in an if: dispatch
        return cfg.num_collect
    elif cfg.scheme == "avoidstragg":  # string compare: dispatch
        return cfg.n_workers - cfg.n_stragglers
    return cfg.n_workers


def is_partial(scheme):
    return scheme in ("partialcyccoded", "partialrepcoded")
