"""Suppression syntax exercise: a reasoned line allow, a reasoned
file-wide allow, and one reason-less allow (which is itself a finding)."""

# lint: allow-file(registry-dispatch): fixture exercises file-wide allows

import jax
from erasurehead_tpu.obs import events as obs_events


def body(carry, x):
    # lint: allow(trace-purity): fixture proves line suppression works
    obs_events.emit("warning", kind="k", message="suppressed emit")
    print("also suppressed")  # lint: allow(trace-purity)
    return carry + x, None


def run(cfg, xs):
    if cfg.scheme == "naive":  # suppressed by the file-wide allow above
        return xs
    return jax.lax.scan(body, 0.0, xs)
