"""event-schema violations against the out-of-core records: a
``prefetch`` emit missing its window/bytes accounting, an ``io`` emit
missing its byte count, and a logger-object ``io`` emit missing the
kind — the contracts the shard store and prefetcher byte-accounting
telemetry (data/store.py, data/prefetch.py) must satisfy."""

from erasurehead_tpu.obs import events as events_lib


def emit_outofcore(logger):
    events_lib.emit("prefetch", run_id="r")  # missing window, bytes
    events_lib.emit("io", kind="shard_read")  # missing bytes
    logger.emit("io", bytes=4096)  # missing kind
