"""Schema/validator drift: a module shaped like obs/events.py whose
validate_lines checks a record type its own SCHEMA does not declare."""

SCHEMA = {
    "run_start": ("run_id",),
    "run_end": ("run_id", "wall_time_s"),
}


def validate_lines(lines):
    errors = []
    for i, rec in enumerate(lines):
        rtype = rec.get("type")
        if rtype == "run_start":
            pass
        if rtype == "checkpointed":  # not in SCHEMA above: drift
            errors.append(f"line {i}: bad checkpoint record")
    return errors
