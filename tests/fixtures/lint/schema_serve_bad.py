"""event-schema violations against the PR-13 serve records: a ``reject``
emit missing its reason, a ``stream`` emit missing its lifecycle event,
and a logger-object ``restart`` emit missing the rehydrated count — the
contracts the network fronts' backpressure/streaming/warm-restart
telemetry (serve/server.py, serve/http_front.py, serve/wal.py) must
satisfy."""

from erasurehead_tpu.obs import events as events_lib


def emit_serve(logger):
    events_lib.emit("reject", tenant="a")  # missing reason
    events_lib.emit("stream", tenant="a")  # missing event
    logger.emit("restart", wal_records=3, resubmitted=2)  # no rehydrated
