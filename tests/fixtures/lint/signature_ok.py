"""Clean counterpart of signature_bad: traced closures read only
signature-keyed fields (scan_unroll, dtype), shape-captured fields
(rounds), or read non-signature fields HOST-SIDE before the dispatch."""

import jax


def train(cfg, xs):
    collect = cfg.num_collect  # host-side read, becomes a traced argument

    def body(carry, x):
        return carry + x * collect, None

    def _run(state, chunk):
        return jax.lax.scan(body, state, chunk, unroll=cfg.scan_unroll)

    run = jax.jit(_run)
    return run(float(cfg.rounds), xs)
