"""Clean counterpart: scheme handling through the registry, plus the
comparisons that are NOT dispatch (two scheme VALUES compared for
compatibility; subscripting by non-scheme keys)."""

TABLE = {0: "cyccoded", 1: "repcoded"}


def compatible(a, b):
    return a.scheme == b.scheme  # value-to-value: compatibility, not dispatch


def legacy_scheme(coded_ver):
    return TABLE[coded_ver]  # keyed by coded_ver, not by a scheme


def stop_count(cfg):
    from erasurehead_tpu import schemes

    desc = schemes.get(cfg.scheme)  # the sanctioned lookup
    if desc.needs_num_collect:
        return cfg.num_collect
    return cfg.n_workers
