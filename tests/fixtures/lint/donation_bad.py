"""donation-safety violation: the PR 6 _donate_copy bug class — a donated
carry read after the donating call (works on CPU, RuntimeErrors on TPU)."""

import jax


def train(state0, xs, weights):
    run = jax.jit(lambda s, w: (s, w), donate_argnums=(0, 1))
    final, _ = run(state0, weights)
    return final, state0  # state0's buffer was donated: invalid read


def train_aot(state0, xs):
    run = jax.jit(lambda s, x: s, donate_argnums=(0,))
    ex = run.lower(state0, xs).compile()
    out = ex(state0, xs)  # executes with the jit's aliasing
    return out + state0  # read after donation through the AOT chain
