"""Clean counterpart: the sanctioned donation idioms — consume-and-
replace rebinding, _donate_copy clones for warm-ups, and conditional
donation with fresh per-call expressions."""

import jax


def _donate_copy(tree):
    return jax.tree.map(lambda l: l.copy(), tree)


def train(state0, xs, weights, donate=True):
    run = jax.jit(
        lambda s, w: (s, w), donate_argnums=(0, 1) if donate else ()
    )
    run(_donate_copy(state0), _donate_copy(weights))  # warm-up on clones
    state = state0
    for chunk in (xs, xs):
        state, _ = run(state, weights[: len(chunk)])  # rebind from result
    return state
