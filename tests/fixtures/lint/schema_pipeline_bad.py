"""pipeline event-schema violations: a dispatch_ahead emit missing the
required pipeline_depth, and a logger-object stale_decode emit missing
the staleness_share decomposition field — the pipelined-training record
types (ISSUE 16) are lint-enforced like every other."""

from erasurehead_tpu.obs import events as events_lib


def emit_pipeline(logger):
    events_lib.emit(
        "dispatch_ahead", run_id="r", first_round=0, n_rounds=8,
        ahead_mean_s=0.1, ahead_max_s=0.5, overlap_total_s=1.0,
    )  # missing pipeline_depth
    logger.emit(
        "stale_decode", run_id="r", first_round=0, n_rounds=8,
        staleness_error_mean=0.1, coding_error_mean=0.2,
    )  # missing staleness_share
