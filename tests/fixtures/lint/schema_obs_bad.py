"""telemetry-plane event-schema violations: a critical_path emit missing
the required sim_components ledger, and a logger-object regime emit
missing the shifted change-point flag — the live-telemetry record types
(ISSUE 18) are lint-enforced like every other."""

from erasurehead_tpu.obs import events as events_lib


def emit_obs(logger):
    events_lib.emit(
        "critical_path", run_id="r", wall_s=1.0, sim_total_s=2.0,
        components={"decode_update_s": 1.0, "prefetch_stall_s": 0.0},
        fractions={"decode_update": 1.0},
    )  # missing sim_components
    logger.emit(
        "regime", round=4, kind="exp", rate=2.0, n=24,
    )  # missing shifted
