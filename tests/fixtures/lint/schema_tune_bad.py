"""autotune-plane event-schema violations (ISSUE 19): a ``tune`` emit
missing its resolution provenance, a logger-object tune emit missing the
device dimension, a race name outside obs/events.TUNE_RACES, a source
outside TUNE_SOURCES, and a TUNE_CHOICES declaration that drifts from
the schema's race vocabulary — the decision-plane records are
lint-enforced like every other."""

from erasurehead_tpu.obs import events as events_lib

# drift: declares a race the event schema does not know
TUNE_CHOICES = {
    "block_decode": ("fused", "treewise"),
    "margin_lowering": ("flat", "cols"),
}


def emit_tune(logger):
    events_lib.emit(
        "tune", race="block_decode", device_kind="cpu",
        shape="s", choice="fused",
    )  # missing source
    logger.emit(
        "tune", race="glm_fused", shape="s", choice="xla", source="race",
    )  # missing device_kind
    events_lib.emit(
        "tune", race="margin_lowering", device_kind="cpu", shape="s",
        choice="flat", source="race",
    )  # race not in TUNE_RACES
    events_lib.emit(
        "tune", race="stack_mode", device_kind="cpu", shape="s",
        choice="ring", source="guess",
    )  # source not in TUNE_SOURCES
