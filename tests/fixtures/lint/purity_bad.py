"""trace-purity violations: host effects inside traced bodies — the
jit-interior emit() is the PR 3 observation-only-contract mutation."""

import time

import jax
import numpy as np
from erasurehead_tpu.obs import events as obs_events
from erasurehead_tpu.obs.metrics import REGISTRY
from erasurehead_tpu.utils.compat import shard_map


def _helper(carry):
    # reachable from the traced scan body below -> still flagged
    obs_events.emit("warning", kind="k", message="inside jit")
    return carry + np.random.normal()


def scan_body(carry, x):
    t = time.time()
    print("round", x)
    REGISTRY.counter("bad.counter").inc()
    return _helper(carry) + t, None


def run(xs):
    return jax.lax.scan(scan_body, 0.0, xs)


def make_grad(mesh):
    def local(params, X):
        with open("/tmp/leak.txt", "w") as f:
            f.write("host I/O")
        return params
    return shard_map(local, mesh=mesh, in_specs=(), out_specs=None)


@jax.jit
def jitted(x):
    obs_events.emit("warning", kind="k", message="direct jit interior")
    return x * 2
