"""Dispatch forms the OLD grep test could not see (its regex required an
``if``/``elif`` line with ``scheme`` directly followed by a comparator).
Every site here is real dispatch; the grep finds none of them — the
regression fixture for the AST-grade checker."""

PREFIX = {"naive": "naive_acc", "approx": "approx_acc"}


def run_prefix(cfg):
    # dict-keyed dispatch: an if/elif spine in data clothing, and it
    # KeyErrors for every scheme registered after the table was written
    stem = PREFIX[cfg.scheme.value]
    # ternary dispatch on .value: the ".value ==" form the grep regex
    # missed (scheme is not directly followed by the comparator)
    label = "uncoded" if cfg.scheme.value == "naive" else "coded"
    return stem, label


def pick_weights(scheme, w_exact, w_approx):
    # comparison inside a comprehension filter, not an if statement
    return [w_exact if scheme.value == "cyccoded" else w_approx]
