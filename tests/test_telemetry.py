"""Run-telemetry subsystem (erasurehead_tpu/obs): event log, decode error,
metrics registry, recompile detector, sentinel-masked arrival stats.

The two contracts that matter most are pinned here:
  - telemetry is OBSERVATION-ONLY: with a capture installed vs not,
    ``params_history`` is bitwise identical across schemes (incl. an
    approximate one) and the executable cache records zero extra compiles;
  - the per-round decode-error norm reads exactly 0 for exact schemes
    (cyclic MDS, FRC, naive) and > 0 for approximate decodes (AGC,
    randreg, avoidstragg) under nonzero straggling.
"""

import json
import os
import sys

import jax
import numpy as np
import pytest

from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.obs import decode as obs_decode
from erasurehead_tpu.obs import detect as obs_detect
from erasurehead_tpu.obs import events as obs_events
from erasurehead_tpu.obs import metrics as obs_metrics
from erasurehead_tpu.obs import report as obs_report
from erasurehead_tpu.train import cache, trainer
from erasurehead_tpu.utils.config import RunConfig, resolve_telemetry

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
)

W = 6
ROWS, COLS, ROUNDS = 240, 12, 5


def _dataset():
    return generate_gmm(ROWS, COLS, n_partitions=W, seed=0)


def _cfg(scheme, **kw):
    base = dict(
        scheme=scheme, n_workers=W, n_stragglers=1, rounds=ROUNDS,
        n_rows=ROWS, n_cols=COLS, lr_schedule=1.0, add_delay=True,
        compute_mode="deduped", seed=0,
    )
    base.update(kw)
    return RunConfig(**base)


def _flat_history(res):
    return np.concatenate(
        [np.asarray(l).ravel() for l in jax.tree.leaves(res.params_history)]
    )


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# decode error: the papers' central quantity, test-pinned per scheme


def test_decode_error_exact_schemes_zero_approx_positive():
    """Exact decodes read EXACTLY 0.0; approximate decodes are > 0 under
    nonzero straggling. (Scheme.APPROX is the paper's FRC-layout AGC —
    the 'FRC/AGC' approximate scheme; Scheme.FRC waits for every group
    and is exact.)"""
    ds = _dataset()
    exact = {
        "cyccoded": _cfg("cyccoded"),
        "repcoded": _cfg("repcoded"),
        "naive": _cfg("naive"),
    }
    for name, cfg in exact.items():
        res = trainer.train(cfg, ds)
        assert res.decode_error is not None
        assert (res.decode_error == 0.0).all(), (name, res.decode_error)

    # num_collect=2 of 3 FRC groups: >= 1 group erased EVERY round
    agc = trainer.train(_cfg("approx", num_collect=2), ds)
    assert (agc.decode_error > 0.0).all(), agc.decode_error
    # randreg at 3 of 6 rows: lstsq over an underdetermined receive set
    rr = trainer.train(_cfg("randreg", num_collect=3), ds)
    assert (rr.decode_error > 0.0).all(), rr.decode_error
    # avoidstragg's W/(W-s) rescale is biased per round
    avoid = trainer.train(_cfg("avoidstragg"), ds)
    assert (avoid.decode_error > 0.0).all(), avoid.decode_error


def test_decode_error_series_matches_manual():
    """decode_error_series == ||fold(expand(weights)) - 1|| / sqrt(P)."""
    from erasurehead_tpu.parallel import collect, step as step_lib

    cfg = _cfg("approx", num_collect=2)
    layout = trainer.build_layout(cfg)
    arrivals = trainer.default_arrivals(cfg)
    sched = collect.build_schedule(
        cfg.scheme, arrivals, layout, num_collect=cfg.num_collect
    )
    err = obs_decode.decode_error_series(layout, sched.message_weights)
    slot_w = np.asarray(
        step_lib.expand_slot_weights(
            sched.message_weights,
            np.asarray(layout.coeffs),
            np.asarray(layout.slot_is_coded),
        )
    )
    pw = layout.fold_slot_weights(slot_w)
    manual = np.linalg.norm(pw - 1.0, axis=-1) / np.sqrt(layout.n_partitions)
    np.testing.assert_allclose(err, manual, atol=obs_decode.EXACT_TOL)


# ---------------------------------------------------------------------------
# observation-only: bitwise identity + zero extra compiles (acceptance)


@pytest.mark.parametrize(
    "scheme,extra",
    [
        ("approx", {"num_collect": 2}),  # approximate
        ("cyccoded", {}),  # exact MDS
        ("randreg", {"num_collect": 3}),  # approximate, optimal decode
    ],
)
def test_telemetry_is_observation_only(tmp_path, scheme, extra):
    cache.clear()
    ds = _dataset()
    cfg = _cfg(scheme, **extra)
    off = trainer.train(cfg, ds)
    assert off.run_id is None  # no capture -> no event identity

    path = str(tmp_path / "events.jsonl")
    with obs_events.capture(path):
        on = trainer.train(cfg, ds)
    # bitwise identical trajectory
    np.testing.assert_array_equal(_flat_history(off), _flat_history(on))
    # zero extra compiles: the telemetry-on run hit the executable (and
    # data) caches populated by the telemetry-off run — emission changed
    # neither the signature nor the lowering
    assert on.cache_info["exec_misses"] == 0
    assert on.cache_info["exec_hits"] >= 1
    assert on.cache_info["data_hit"] is True
    assert obs_events.validate_file(path) == []


# ---------------------------------------------------------------------------
# event log + report: the 2-scheme compare acceptance


def test_event_log_and_report_two_scheme_compare(tmp_path, capsys):
    from erasurehead_tpu.train import experiments

    cache.clear()
    ds = _dataset()
    path = str(tmp_path / "events.jsonl")
    # batch='off' pins the per-run event shape (one run_start/run_end
    # pair per scheme); the cohort-mode event shape is pinned in
    # tests/test_cohort.py
    with obs_events.capture(path):
        summaries = experiments.compare(
            {
                "cyccoded": _cfg("cyccoded"),
                "agc": _cfg("approx", num_collect=2),
            },
            ds,
            batch="off",
        )
    # sweep rows carry the decode-error column
    by_label = {s.label: s for s in summaries}
    assert by_label["cyccoded"].decode_error_mean == 0.0
    assert by_label["agc"].decode_error_mean > 0.0
    assert "decode_error_mean" in by_label["agc"].row()

    assert obs_events.validate_file(path) == []
    recs = _events(path)
    types = [r["type"] for r in recs]
    for required in ("run_start", "compile", "data_upload", "rounds",
                     "decode", "run_end", "metrics"):
        assert required in types, (required, types)
    # two runs, each bracketed
    assert types.count("run_start") == 2
    assert types.count("run_end") == 2
    # decode events: exact scheme all-zero, AGC positive
    decode_by_run = {}
    scheme_by_run = {
        r["run_id"]: r["scheme"] for r in recs if r["type"] == "run_start"
    }
    for r in recs:
        if r["type"] == "decode":
            decode_by_run[scheme_by_run[r["run_id"]]] = r
    assert decode_by_run["cyccoded"]["exact"] is True
    assert decode_by_run["cyccoded"]["error_max"] == 0.0
    assert decode_by_run["approx"]["exact"] is False
    assert decode_by_run["approx"]["error_mean"] > 0.0

    # the report command renders one row per run with both schemes
    from erasurehead_tpu import cli

    assert cli.main(["report", path]) == 0
    out = capsys.readouterr().out
    assert "cyccoded" in out and "approx" in out
    assert "steps/s" in out and "decode err" in out


def test_validator_catches_malformed_logs(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    good = {"type": "rounds", "seq": 0, "t": 0.0, "run_id": "r1",
            "first_round": 0, "n_rounds": 2, "sim_time_s": 1.0}
    lines = [
        json.dumps(good),
        json.dumps({**good, "seq": 1, "type": "nosuchtype"}),
        json.dumps({"type": "compile", "seq": 2, "t": 0.0, "run_id": "r1"}),
        "{not json",
        json.dumps({**good, "seq": 1, "first_round": 0}),  # seq + round
    ]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    errors = obs_events.validate_file(path)
    msgs = "\n".join(errors)
    assert "unknown record type" in msgs
    assert "missing required" in msgs  # compile lacks seconds/cache_hit
    assert "not JSON" in msgs
    assert "first_round" in msgs  # non-monotonic round index
    assert "non-monotonic seq" in msgs

    # the tools/ CLI wrapper agrees (same logic, exit code contract)
    import validate_events as validate_tool

    assert validate_tool.main([path]) == 1
    ok_path = str(tmp_path / "ok.jsonl")
    with open(ok_path, "w") as f:
        f.write(json.dumps(good) + "\n")
    assert validate_tool.main([ok_path]) == 0


def test_emit_requires_known_type_and_keys(tmp_path):
    with obs_events.capture(str(tmp_path / "e.jsonl")) as logger:
        with pytest.raises(ValueError, match="unknown event type"):
            logger.emit("bogus", x=1)
        with pytest.raises(ValueError, match="missing required"):
            logger.emit("compile", run_id="r1")  # no seconds/cache_hit
        assert obs_events.current() is logger
    assert obs_events.current() is None


# ---------------------------------------------------------------------------
# arrival sentinel masking (satellite): never average -1 into latency stats


def test_arrival_summary_masks_sentinel():
    wt = np.array([[0.5, -1.0, 1.5], [-1.0, -1.0, 2.0]])
    s = obs_events.arrival_summary(wt)
    assert s["n_never"] == 3 and s["n_arrivals"] == 3
    arrived = np.array([0.5, 1.5, 2.0])
    assert np.isclose(s["mean"], arrived.mean())
    assert s["p50"] >= 0.0 and s["p99"] <= 2.0
    empty = obs_events.arrival_summary(np.full((2, 3), -1.0))
    assert empty["n_arrivals"] == 0 and empty["p50"] is None


def test_artifacts_mask_never_arrived_sentinel(tmp_path, capsys):
    """Deadline run where some workers never arrive: the manifest's
    arrival stats and the per-iteration log lines must exclude the -1
    sentinel (regression: averaging it in silently lowers latencies)."""
    from erasurehead_tpu.train import artifacts, evaluate

    ds = _dataset()
    cfg = _cfg("deadline", deadline=0.3, delay_mean=0.5)
    res = trainer.train(cfg, ds)
    assert (res.worker_times == -1.0).any(), "need never-arrived workers"
    assert (res.worker_times[res.worker_times != -1.0] >= 0).all()

    model = trainer.build_model(cfg)
    n = res.n_train
    ev = evaluate.replay(
        model, cfg.model, res.params_history, ds.X_train[:n],
        ds.y_train[:n], ds.X_test, ds.y_test,
    )
    out_dir = str(tmp_path / "results")
    paths = artifacts.write_run_artifacts(res, ev, out_dir)
    with open(paths["manifest"]) as f:
        manifest = json.load(f)
    arr = manifest["arrival"]
    wt = res.worker_times
    arrived = wt[wt >= 0.0]
    assert arr["n_never"] == int((wt == -1.0).sum())
    assert np.isclose(arr["mean"], arrived.mean(), atol=1e-6)
    assert arr["p50"] >= 0.0  # a sentinel-polluted quantile could go < 0
    assert np.isclose(arr["p90"], np.quantile(arrived, 0.9), atol=1e-6)
    # decode-error fields ride along (deadline rescale is approximate)
    assert manifest["decode_error_mean"] > 0.0

    artifacts.print_iteration_table(res, ev)
    table = capsys.readouterr().out
    assert "Mean arrival" in table or "no arrivals" in table
    assert "-1.0" not in table
    for line in table.splitlines():
        if "Mean arrival = " in line:
            val = float(line.split("Mean arrival = ")[1].split("s ")[0])
            assert val >= 0.0


# ---------------------------------------------------------------------------
# recompile detector


def test_recompile_detector_names_changed_fields():
    obs_detect.reset()
    a = {"kind": "scan", "dtype": "float32", "scan_unroll": 1,
         "chunk_rounds": 5}
    assert obs_detect.observe(dict(a)) is None  # first compile: no prior
    diff = obs_detect.observe({**a, "scan_unroll": 2})
    assert diff is not None and diff["changed"] == ["scan_unroll"]
    assert "1 -> 2" in diff["detail"]["scan_unroll"]
    # expected-to-vary fields alone (chunk length) do not warn
    assert obs_detect.observe({**a, "chunk_rounds": 3}) is None
    # identical signature recompiled -> empty diff (eviction/disabled)
    diff = obs_detect.observe(dict(a))
    assert diff is not None and diff["changed"] == []


def test_recompile_warning_event_from_trainer(tmp_path):
    """Two runs differing only in scan_unroll: the second compile's
    warning event names the knob."""
    cache.clear()
    ds = _dataset()
    path = str(tmp_path / "events.jsonl")
    with obs_events.capture(path):
        trainer.train(_cfg("approx", num_collect=2), ds)
        trainer.train(_cfg("approx", num_collect=2, scan_unroll=2), ds)
    warnings = [r for r in _events(path) if r["type"] == "warning"]
    assert warnings, "expected a recompile warning"
    w = warnings[-1]
    assert w["kind"] == "recompile"
    assert "scan_unroll" in w["changed"]
    assert obs_events.validate_file(path) == []


def test_recompile_detector_names_memory_system_knobs(tmp_path):
    """The PR-6 memory-system knobs are named signature fields
    (RunConfig.static_signature_fields): a cache miss caused by flipping
    stack_dtype, ring_pipeline, or donate produces a recompile warning
    that NAMES the differing knob, not just "something changed"."""
    ds = _dataset()

    def changed_fields(cfg_a, cfg_b, tag):
        cache.clear()
        path = str(tmp_path / f"events_{tag}.jsonl")
        with obs_events.capture(path):
            trainer.train(cfg_a, ds)
            trainer.train(cfg_b, ds)
        warnings = [
            r for r in _events(path)
            if r["type"] == "warning" and r["kind"] == "recompile"
        ]
        assert warnings, f"expected a recompile warning for {tag}"
        assert obs_events.validate_file(path) == []
        return warnings[-1]["changed"]

    base = dict(num_collect=2)
    assert "stack_dtype" in changed_fields(
        _cfg("approx", **base),
        _cfg("approx", stack_dtype="int8", **base),
        "stack_dtype",
    )
    ring = dict(num_collect=2, compute_mode="faithful", stack_mode="ring")
    assert "ring_pipeline" in changed_fields(
        _cfg("approx", ring_pipeline="off", **ring),
        _cfg("approx", ring_pipeline="on", **ring),
        "ring_pipeline",
    )
    assert "donate" in changed_fields(
        _cfg("approx", donate="on", **base),
        _cfg("approx", donate="off", **base),
        "donate",
    )


# ---------------------------------------------------------------------------
# metrics registry (tentpole: cache_info plumbing now reports through it)


def test_metrics_registry_basics():
    reg = obs_metrics.MetricsRegistry()
    c = reg.counter("x.hits")
    c.inc()
    c.inc(2)
    assert c.value == 3
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("x.rate")
    g.set(1.5)
    h = reg.histogram("x.lat")
    for v in (1.0, 2.0, 3.0, 4.0):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["x.hits"] == 3
    assert snap["x.rate"] == 1.5
    assert snap["x.lat"]["count"] == 4
    assert snap["x.lat"]["mean"] == 2.5
    assert snap["x.lat"]["min"] == 1.0 and snap["x.lat"]["max"] == 4.0
    # same-name different-kind is a loud error, not silent aliasing
    with pytest.raises(TypeError):
        reg.gauge("x.hits")
    reg.reset()
    assert reg.snapshot()["x.hits"] == 0
    assert reg.counter("x.hits") is c  # names persist across reset


def test_cache_stats_are_registry_backed():
    cache.clear()
    s = cache.stats()
    assert s.exec_misses == 0 and s.data_misses == 0
    before = obs_metrics.REGISTRY.snapshot()
    assert before.get("sweep_cache.exec_misses", 0) == 0
    ds = _dataset()
    trainer.train(_cfg("cyccoded"), ds)
    after = obs_metrics.REGISTRY.snapshot()
    assert after["sweep_cache.exec_misses"] == 1
    assert after["sweep_cache.data_misses"] == 1
    assert cache.stats().snapshot()["exec_misses"] == 1


# ---------------------------------------------------------------------------
# CLI flag / env resolution (satellite; integration lives in test_cli.py)


def test_resolve_telemetry_precedence():
    # explicit flag wins over everything
    assert resolve_telemetry("on", out_dir_set=False, env="off") is True
    assert resolve_telemetry("off", out_dir_set=True, env="on") is False
    # env when no flag
    assert resolve_telemetry(None, out_dir_set=False, env="on") is True
    assert resolve_telemetry(None, out_dir_set=False, env="0") is False
    assert resolve_telemetry(None, out_dir_set=False, env="1") is True
    # default off
    assert resolve_telemetry(None, out_dir_set=True, env="") is False
    # auto keys off the explicit output dir
    assert resolve_telemetry("auto", out_dir_set=True) is True
    assert resolve_telemetry("auto", out_dir_set=False) is False
    assert resolve_telemetry(None, out_dir_set=True, env="auto") is True
    assert resolve_telemetry(None, out_dir_set=False, env="auto") is False
    with pytest.raises(ValueError, match="telemetry"):
        resolve_telemetry(None, env="sometimes")


def test_report_renders_measured_style_minimal(tmp_path, capsys):
    """The report degrades gracefully on partial logs (no run_end)."""
    path = str(tmp_path / "partial.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({
            "type": "run_start", "seq": 0, "t": 0.0, "run_id": "r9",
            "scheme": "approx", "platform": "cpu", "config_hash": "x",
            "mesh": [], "lowering": "()",
        }) + "\n")
    out = obs_report.render([path])
    assert "approx" in out and "r9" in out
