"""Measured-arrival mode (trainer.train_measured): real per-worker compute
timing feeds the collection rules — SURVEY §7.4's "real delay" mode, making
worker_timeset a measurement again (src/naive.py:106)."""

import numpy as np
import pytest

import jax.numpy as jnp

from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.models.glm import LogisticModel
from erasurehead_tpu.train import trainer
from erasurehead_tpu.utils.config import RunConfig

W, S, R = 8, 2, 6
MULT = 400  # slow workers do 400x the gradient work — dwarfs timing noise


def _cfg(**kw):
    base = dict(
        scheme="avoidstragg", n_workers=W, n_stragglers=S, rounds=R,
        n_rows=32 * W, n_cols=32, lr_schedule=1.0, update_rule="AGD",
        add_delay=False, seed=0,
    )
    base.update(kw)
    return RunConfig(**base)


@pytest.fixture(scope="module")
def data():
    return generate_gmm(32 * W, 32, n_partitions=W, seed=0)


def test_measured_mode_reacts_to_real_imbalance(data):
    """avoidstragg drops the s slowest arrivals. With workers 0 and 1 doing
    400x real compute, measured mode must exclude them — while the
    simulated schedule (no delays -> index-order ties) excludes the LAST
    two workers instead. The collected sets must differ: that is the whole
    point of the mode.

    Assertions are majority-over-rounds, not every-round: a shared CI host
    can deschedule a fast worker's thread for longer than the induced
    imbalance in any single round, and that noise is exactly what measured
    mode is designed to pick up — it must not fail the test."""
    mult = np.ones(W, dtype=np.int64)
    mult[:2] = MULT
    res = trainer.train_measured(_cfg(), data, work_multiplier=mult)
    # the slow workers' measured arrivals dominate in a clear majority of
    # rounds (excluded workers carry the reference's -1 sentinel)
    slow_excluded = (res.worker_times[:, :2] == -1.0).all(axis=1)
    assert slow_excluded.sum() > R // 2, res.worker_times
    # avoidstragg drops exactly S=2: in every round where both slow workers
    # were excluded, all fast workers must have been collected
    assert res.collected[slow_excluded][:, 2:].all()
    # simulated mode on the same config collects by index tie-break instead
    sim = trainer.train(_cfg(), data)
    assert sim.collected[:, : W - S].all()
    assert not np.array_equal(res.collected, sim.collected)
    # measured times are real seconds: positive, slow >> fast
    fast = res.timeset  # stop time = (W-S)-th arrival, a fast worker
    assert (fast > 0).all()


def test_measured_mode_trains(data):
    """With no induced imbalance the run must still train and emit the full
    artifact set (history, timeset, worker_times) with coherent shapes."""
    # (s+1) | W FRC guard: use s=1 for the AGC run on W=8
    cfg = _cfg(scheme="approx", n_stragglers=1, num_collect=W)
    res = trainer.train_measured(cfg, data)
    hist = np.asarray(res.params_history)
    assert hist.shape == (R, 32) and np.isfinite(hist).all()
    assert res.timeset.shape == (R,) and (res.timeset > 0).all()
    assert res.worker_times.shape == (R, W)
    assert res.sim_total_time > 0 and res.wall_time > 0
    model = LogisticModel()
    Xt, yt = jnp.asarray(data.X_test), jnp.asarray(data.y_test)
    first = float(model.loss_mean(jnp.asarray(hist[0]), Xt, yt))
    last = float(model.loss_mean(jnp.asarray(hist[-1]), Xt, yt))
    assert last < first


def test_measured_mode_delay_injection(data):
    """add_delay composes: arrivals = measured compute + injected seeded
    exponential sleep, matching the reference's compute-then-sleep order
    (src/naive.py:140-149). The injected part dominates microsecond CPU
    compute, so collection follows the delay schedule."""
    from erasurehead_tpu.parallel import straggler

    cfg = _cfg(add_delay=True)
    res = trainer.train_measured(cfg, data)
    delays = straggler.arrival_schedule(R, W, True, cfg.delay_mean)
    # each round's excluded (slowest-s) workers match the delay schedule's.
    # Majority-over-rounds, like the imbalance test above: when a round's
    # s-th/(s+1)-th delay gap is tight, real compute jitter can legitimately
    # flip the measured ordering — that sensitivity is the mode working.
    want_excluded = np.argsort(delays, axis=1, kind="stable")[:, -S:]
    agree = sum(
        not res.collected[r, want_excluded[r]].any() for r in range(R)
    )
    assert agree > R // 2, (agree, R)


def test_measured_multidevice_imbalance_changes_collection(data):
    """VERDICT r2 item 6: on a >1-device mesh, workers are pinned
    round-robin to devices and dispatched concurrently; overloading one
    DEVICE (both workers sharing it) must push exactly its workers out of
    the collected set. Majority-over-rounds for the same noise reasons as
    the single-device imbalance test."""
    from erasurehead_tpu.parallel.mesh import worker_mesh

    mesh = worker_mesh(4)  # workers 0..7 -> devices 0..3, 0..3
    mult = np.ones(W, dtype=np.int64)
    mult[[0, 4]] = MULT  # device 0 carries 2*MULT units; others carry 2
    res = trainer.train_measured(
        _cfg(), data, mesh=mesh, work_multiplier=mult
    )
    slow_excluded = (res.worker_times[:, [0, 4]] == -1.0).all(axis=1)
    assert slow_excluded.sum() > R // 2, res.worker_times
    fast = [w for w in range(W) if w not in (0, 4)]
    assert res.collected[slow_excluded][:, fast].all()


def test_measured_multidevice_queue_contention(data):
    """The observation single-device serialization could NOT make: a LIGHT
    worker sharing a device with a heavy one arrives late because its
    dispatch queues behind the heavy worker's — real chip contention, not
    its own compute. Worker 0 is heavy; worker 4 (mult=1, same device,
    dispatched after) must be excluded alongside it in most rounds."""
    from erasurehead_tpu.parallel.mesh import worker_mesh

    mesh = worker_mesh(4)
    mult = np.ones(W, dtype=np.int64)
    mult[0] = MULT  # only worker 0 is heavy
    res = trainer.train_measured(
        _cfg(), data, mesh=mesh, work_multiplier=mult
    )
    both_excluded = (res.worker_times[:, [0, 4]] == -1.0).all(axis=1)
    assert both_excluded.sum() > R // 2, res.worker_times


def test_work_multiplier_validation(data):
    with pytest.raises(ValueError, match="work_multiplier"):
        trainer.train_measured(
            _cfg(), data, work_multiplier=np.zeros(W, dtype=np.int64)
        )
    with pytest.raises(ValueError, match="work_multiplier"):
        trainer.train_measured(_cfg(), data, work_multiplier=np.ones(3))


def test_measured_mode_rejects_unsupported_knobs(data):
    """Knobs with no measured-mode implementation must refuse, not
    silently run something different from what was configured."""
    with pytest.raises(ValueError, match="simulated heterogeneity"):
        trainer.train_measured(_cfg(worker_speed_spread=0.5), data)
    with pytest.raises(ValueError, match="faithful"):
        trainer.train_measured(_cfg(compute_mode="deduped"), data)
    with pytest.raises(ValueError, match="fused-kernel"):
        trainer.train_measured(_cfg(use_pallas="on"), data)
    with pytest.raises(ValueError, match="flat-stack"):
        trainer.train_measured(_cfg(flat_grad="on"), data)
    with pytest.raises(ValueError, match="flat-margin"):
        trainer.train_measured(_cfg(margin_flat="on"), data)
    with pytest.raises(ValueError, match="scan_unroll"):
        trainer.train_measured(_cfg(scan_unroll=4), data)


def test_measured_mode_refuses_partial_schemes(data):
    """VERDICT r5 #4: the reference's partial worker sends its uncoded
    first part BEFORE computing the coded second
    (src/partial_coded.py:226-234); measured mode times ONE combined
    message per worker and therefore cannot observe the staggered
    two-part arrival. The contract is a documented refusal — pinned here
    so the error (and its reasoning) can't silently regress into a
    wrong-protocol measurement."""
    for scheme in ("partialcyccoded", "partialrepcoded"):
        cfg = _cfg(
            scheme=scheme, n_stragglers=1, partitions_per_worker=3,
        )
        with pytest.raises(ValueError, match="two-part"):
            trainer.train_measured(cfg, data)
    # the ring stack transport likewise has no measured-mode body; the
    # config layer refuses the combination before any trainer runs
    with pytest.raises(ValueError, match="measured"):
        _cfg(stack_mode="ring", arrival_mode="measured")
