"""Pipelined staleness-tolerant training (ISSUE 16): the bounded-
staleness (tau=1) mode that breaks the round barrier.

Pins the contracts the mode ships under:

  - the tau=0 pipelined schedule is BITWISE the synchronous
    CollectionSchedule across schemes (depth 0 is not "approximately"
    synchronous — it is the same schedule);
  - depth-1 schedule invariants: completion clock monotone, dispatch-
    ahead non-negative, and exactly zero everywhere at depth 0;
  - the refusal matrix: every unsound/untested path refuses with a
    typed PipelineRefusal whose ``reason`` tag is stable;
  - pipelined runs are deterministic (stale, not async-racy): reruns
    are bitwise in params history and simulated clock, and a chaos-
    killed journaled sweep resumes to identical rows;
  - telemetry: dispatch_ahead rides the run, the staleness-vs-coding
    decomposition validates, and a tau=0 run decomposes to pure coding
    error (staleness_share exactly 0.0);
  - serve-admission honesty: the pipelined footprint estimate charges
    exactly one extra params slot.
"""

import json

import numpy as np
import pytest

from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.obs import decode as decode_lib
from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.parallel import collect, pipeline as pipeline_lib
from erasurehead_tpu.train import experiments, trainer
from erasurehead_tpu.train import journal as journal_lib
from erasurehead_tpu.utils import chaos
from erasurehead_tpu.utils.config import PipelineRefusal, RunConfig

W = 4
R = 6


@pytest.fixture(scope="module")
def gmm():
    return generate_gmm(64, 8, n_partitions=W, seed=0)


def _cfg(**kw):
    # avoidstragg + GD: the staleness-tolerant reference combination.
    # lr_schedule is EXPLICIT — the default schedule sits at GD's
    # stability edge and tau=1 shrinks the stable region
    d = dict(
        scheme="avoidstragg", n_workers=W, n_stragglers=1, rounds=R,
        n_rows=64, n_cols=8, update_rule="GD", lr_schedule=1.0,
        add_delay=True, seed=0, compute_mode="deduped",
    )
    d.update(kw)
    return RunConfig(**d)


def _bitwise(a, b):
    import jax

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# schedule (parallel/pipeline.py)


def test_staleness_schedule_values():
    np.testing.assert_array_equal(
        pipeline_lib.staleness_schedule(5, 1), [0, 1, 1, 1, 1]
    )
    np.testing.assert_array_equal(
        pipeline_lib.staleness_schedule(4, 0), [0, 0, 0, 0]
    )


@pytest.mark.parametrize(
    "kw",
    [
        {"scheme": "naive"},
        {"scheme": "avoidstragg"},
        {"scheme": "approx", "num_collect": 3},
        {"scheme": "cyccoded"},
        {"scheme": "deadline", "deadline": 1.5},
    ],
)
def test_tau0_schedule_bitwise_synchronous(kw):
    """depth 0 collapses exactly: same weights, clocks, arrivals and
    collection masks as collect.build_schedule — float-associativity
    included (relative quantities never round-trip through the absolute
    clock)."""
    cfg = _cfg(**kw)
    t = trainer.default_arrivals(cfg)
    layout = trainer.build_layout(cfg)
    sync = collect.build_schedule(
        cfg.scheme, t, layout, num_collect=cfg.num_collect,
        deadline=cfg.deadline, decode=cfg.decode,
    )
    pipe = pipeline_lib.pipelined_schedule(cfg, t, layout)
    np.testing.assert_array_equal(pipe.message_weights, sync.message_weights)
    np.testing.assert_array_equal(pipe.sim_time, sync.sim_time)
    np.testing.assert_array_equal(pipe.worker_times, sync.worker_times)
    np.testing.assert_array_equal(pipe.collected, sync.collected)
    assert np.all(pipe.dispatch_ahead == 0.0)
    assert np.all(pipe.staleness == 0)


def test_tau1_schedule_invariants():
    cfg = _cfg(pipeline_depth=1, rounds=20)
    t = trainer.default_arrivals(cfg)
    layout = trainer.build_layout(cfg)
    sched = pipeline_lib.pipelined_schedule(cfg, t, layout)
    assert np.all(np.diff(sched.done) >= 0.0)  # completion clock monotone
    assert np.all(sched.dispatch_ahead >= 0.0)
    assert np.all(sched.sim_time >= 0.0)
    np.testing.assert_array_equal(
        sched.staleness, pipeline_lib.staleness_schedule(20, 1)
    )
    # dispatch-ahead engages somewhere under exp straggling, and the
    # pipelined completion clock never trails the per-round stop sum
    assert float(sched.dispatch_ahead.sum()) > 0.0
    summary = pipeline_lib.overlap_summary(sched)
    assert set(summary) == {"ahead_mean_s", "ahead_max_s", "overlap_total_s"}
    assert summary["overlap_total_s"] > 0.0


# ---------------------------------------------------------------------------
# refusal matrix


@pytest.mark.parametrize(
    "kw,reason",
    [
        ({"scheme": "cyccoded"}, "exact_decode"),
        ({"scheme": "repcoded"}, "exact_decode"),
        ({"scheme": "naive"}, "exact_decode"),
        ({"update_rule": "AGD"}, "momentum_unproven"),
        ({"arrival_mode": "measured"}, "measured_arrivals"),
    ],
)
def test_refusals_at_config(kw, reason):
    with pytest.raises(PipelineRefusal) as ei:
        _cfg(pipeline_depth=1, **kw)
    assert ei.value.reason == reason


def test_refusals_are_valueerrors():
    # every feasibility filter (whatif enumerator, serve admission, CLI)
    # classifies a refusal like any other config error
    with pytest.raises(ValueError):
        _cfg(pipeline_depth=1, scheme="cyccoded")
    with pytest.raises(ValueError):
        _cfg(pipeline_depth=2)


def test_refusals_at_train(gmm, tmp_path):
    cfg = _cfg(pipeline_depth=1)
    with pytest.raises(PipelineRefusal) as ei:
        trainer.train(cfg, gmm, checkpoint_dir=str(tmp_path / "ck"))
    assert ei.value.reason == "checkpoint_restart"
    with pytest.raises(PipelineRefusal) as ei:
        trainer.train(cfg, gmm, resume=True)
    assert ei.value.reason == "checkpoint_restart"
    with pytest.raises(PipelineRefusal) as ei:
        trainer.train(cfg, gmm, initial_state=object(), initial_round=2)
    assert ei.value.reason == "elastic_restart"
    sync_cfg = _cfg()
    sched = collect.build_schedule(
        sync_cfg.scheme, trainer.default_arrivals(sync_cfg),
        trainer.build_layout(sync_cfg),
    )
    with pytest.raises(PipelineRefusal) as ei:
        trainer.train(cfg, gmm, schedule=sched)
    assert ei.value.reason == "custom_schedule"
    with pytest.raises(PipelineRefusal) as ei:
        trainer.train_cohort([cfg, cfg], gmm)
    assert ei.value.reason == "cohort_batch"
    with pytest.raises(PipelineRefusal) as ei:
        trainer.train_dynamic(_cfg(pipeline_depth=1), gmm)
    assert ei.value.reason == "dynamic_rule"


def test_cohort_planner_routes_pipelined_singletons():
    cfgs = {
        "sync0": _cfg(seed=0),
        "sync1": _cfg(seed=1),
        "pipe0": _cfg(seed=0, pipeline_depth=1),
        "pipe1": _cfg(seed=1, pipeline_depth=1),
    }
    plan = experiments.plan_cohorts(cfgs)
    assert (["sync0", "sync1"], True) in plan
    assert (["pipe0"], False) in plan
    assert (["pipe1"], False) in plan
    assert not trainer.cohort_eligible(cfgs["pipe0"])


# ---------------------------------------------------------------------------
# determinism (stale, not async-racy)


def test_pipelined_run_deterministic(gmm):
    a = trainer.train(_cfg(pipeline_depth=1), gmm, measure=False)
    b = trainer.train(_cfg(pipeline_depth=1), gmm, measure=False)
    _bitwise(a.params_history, b.params_history)
    np.testing.assert_array_equal(a.timeset, b.timeset)
    np.testing.assert_array_equal(a.decode_error, b.decode_error)


def test_pipelined_trajectory_actually_stale(gmm):
    """tau=1 changes the trajectory after warm-up (rounds 0 and 1 both
    differentiate at p0, so histories agree through round 1 and diverge
    after) — the staleness slot is live, not decorative."""
    import jax

    sync = trainer.train(_cfg(), gmm, measure=False)
    pipe = trainer.train(_cfg(pipeline_depth=1), gmm, measure=False)
    for a, b in zip(
        jax.tree.leaves(sync.params_history),
        jax.tree.leaves(pipe.params_history),
    ):
        a, b = np.asarray(a), np.asarray(b)
        np.testing.assert_array_equal(a[0], b[0])  # both step from g(p0)
        assert not np.array_equal(a[-1], b[-1])


def test_pipelined_kill_resume_rows_identical(gmm, tmp_path, monkeypatch):
    """The journal kill->resume invariance extends to pipelined runs: a
    sweep chaos-killed after its 2nd trajectory resumes to rows bitwise-
    identical to the uninterrupted sweep, and the journal validates."""
    configs = {
        "pipe_a": _cfg(pipeline_depth=1, seed=0),
        "pipe_b": _cfg(pipeline_depth=1, seed=1),
        "sync": _cfg(seed=0),
    }
    baseline = experiments.compare(dict(configs), gmm)

    jdir = str(tmp_path / "journal")
    monkeypatch.setenv(chaos.CHAOS_ENV, "raise:trajectory:2")
    chaos.reset()
    j = journal_lib.SweepJournal(jdir, resume=False)
    with pytest.raises(chaos.ChaosInjection):
        experiments.compare(dict(configs), gmm, journal=j)
    j.close()
    monkeypatch.delenv(chaos.CHAOS_ENV)
    chaos.reset()

    j2 = journal_lib.SweepJournal(jdir, resume=True)
    assert len(j2) == 2
    resumed = experiments.compare(dict(configs), gmm, journal=j2)
    j2.close()

    base_rows = [journal_lib.science_row(s.row()) for s in baseline]
    res_rows = [journal_lib.science_row(s.row()) for s in resumed]
    assert base_rows == res_rows
    for a, b in zip(baseline, resumed):
        assert np.array_equal(
            np.asarray(a.training_loss), np.asarray(b.training_loss)
        )
        np.testing.assert_array_equal(a.timeset, b.timeset)
    assert events_lib.validate_file(j2.path) == []


# ---------------------------------------------------------------------------
# telemetry + admission


def test_dispatch_ahead_event_and_staleness_split(gmm, tmp_path):
    path = str(tmp_path / "events.jsonl")
    with events_lib.capture(path):
        pipe = trainer.train(_cfg(pipeline_depth=1), gmm, measure=False)
        split = decode_lib.emit_staleness_split("test-run", pipe, gmm)
    assert events_lib.validate_file(path) == []
    with open(path) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    ahead = [r for r in recs if r["type"] == "dispatch_ahead"]
    assert len(ahead) == 1
    assert ahead[0]["pipeline_depth"] == 1
    assert ahead[0]["n_rounds"] == R
    stale = [r for r in recs if r["type"] == "stale_decode"]
    assert len(stale) == 1
    assert 0.0 <= split["staleness_share"] <= 1.0
    assert split["staleness_error_mean"] > 0.0  # tau=1 engaged
    assert pipe.cache_info["pipeline_depth"] == 1
    assert pipe.cache_info["pipeline_params_slot_bytes"] > 0


def test_tau0_split_is_pure_coding_error(gmm):
    sync = trainer.train(_cfg(), gmm, measure=False)
    split = decode_lib.emit_staleness_split("tau0", sync, gmm)
    assert split["staleness_error_mean"] == 0.0
    assert split["staleness_share"] == 0.0  # pure coding error, exactly
    assert split["coding_error_mean"] > 0.0
    assert sync.cache_info["pipeline_params_slot_bytes"] == 0


def test_admission_charges_one_extra_params_slot(gmm):
    base = trainer.estimate_stack_bytes(_cfg(), gmm)
    pipe = trainer.estimate_stack_bytes(_cfg(pipeline_depth=1), gmm)
    F = gmm.X_train.shape[1]
    assert pipe - base == (F + 1) * 4  # one f32 (weights, bias) slot
