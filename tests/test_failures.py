"""Failure injection / detection / failover (parallel/failures.py).

The reference hangs forever on any worker death (src/naive.py:103-110 waits
for all W; README.md:120-122 concedes real failures are unhandled). These
tests pin the feasibility rules to that semantics and check the failover
decode stays unbiased / erasure-correct per layout.
"""

import numpy as np
import pytest

from erasurehead_tpu.ops import codes
from erasurehead_tpu.parallel import collect, failures, straggler
from erasurehead_tpu.utils.config import RunConfig, Scheme

R, W, S = 6, 12, 2


@pytest.fixture(scope="module")
def arrivals():
    return straggler.arrival_schedule(R, W, add_delay=True)


def test_inject_worker_death(arrivals):
    t = failures.inject_worker_death(arrivals, {3: 2, 7: 0})
    assert np.isinf(t[2:, 3]).all() and np.isfinite(t[:2, 3]).all()
    assert np.isinf(t[:, 7]).all()
    assert np.isfinite(np.delete(t, [3, 7], axis=1)).all()
    # input untouched
    assert np.isfinite(arrivals).all()


def test_detect_dead_timeout(arrivals):
    t = failures.inject_worker_death(arrivals, {0: 1})
    dead = failures.detect_dead(t, timeout=1e9)
    assert dead[:, 0].tolist() == [False] + [True] * (R - 1)
    assert not dead[:, 1:].any()
    # a finite but too-slow arrival also detects
    slow = np.array(arrivals, copy=True)
    slow[0, 5] = 1e6
    assert failures.detect_dead(slow, timeout=100.0)[0, 5]


def test_detect_dead_sentinel_columns(arrivals):
    """detect_dead on TELEMETRY (worker_times with the reference's -1
    never-collected sentinel): an all--1 column is dead every round — the
    sentinel must never read as 'arrived at t=-1', which would pass any
    timeout — while a transiently-slow column is dead only in the rounds
    its (finite, positive) arrival overran the timeout."""
    wt = np.array(arrivals, copy=True)
    wt[:, 3] = -1.0  # never collected, every round
    wt[2, 5] = 500.0  # transiently slow: one round beyond the timeout
    dead = failures.detect_dead(wt, timeout=100.0)
    assert dead[:, 3].all()  # all--1 column: dead throughout
    assert dead[2, 5] and not dead[np.arange(R) != 2, 5].any()
    # the rest of the cluster is alive everywhere
    others = np.delete(dead, [3, 5], axis=1)
    assert not others.any()
    # sentinel masking matches obs/events.arrival_summary's rule: the
    # same entries arrival_summary masks out are the ones detect_dead
    # calls dead at any timeout
    from erasurehead_tpu.obs.events import arrival_summary

    assert arrival_summary(wt[:, 3])["n_arrivals"] == 0
    assert failures.detect_dead(wt[:, 3:4], timeout=np.inf)[:, 0].all()


def test_survivor_config_validates_divisibility_up_front():
    """Bugfix regression: an unlucky W' violating FRC's (s+1) | W' used to
    raise deep inside layout construction; survivor_config (and
    train_elastic through it) now fails at config-build time with an
    error naming survivor_overrides — BEFORE any phase-1 compute."""
    cfg = RunConfig(
        scheme="approx", n_workers=8, n_stragglers=1, num_collect=6,
        rounds=10, n_rows=256, n_cols=8, lr_schedule=1.0, add_delay=True,
    )
    # W'=5: (1+1) does not divide 5
    with pytest.raises(ValueError, match="survivor_overrides"):
        failures.survivor_config(cfg, 5)
    # the clear error also names the violated constraint
    with pytest.raises(ValueError, match="n_stragglers"):
        failures.survivor_config(cfg, 5)
    # a valid override passes and clamps num_collect to W'
    cfg2 = failures.survivor_config(
        cfg, 5, survivor_overrides={"n_stragglers": 0}
    )
    assert cfg2.n_workers == 5 and cfg2.num_collect == 5


def test_train_elastic_divisibility_error_before_training():
    """train_elastic with 3 deaths out of W=8 leaves W'=5, which breaks
    approx's FRC layout at s=1: the ValueError must name
    survivor_overrides and fire before any training happens."""
    from erasurehead_tpu.data.synthetic import generate_gmm

    ds = generate_gmm(64, 8, n_partitions=8, seed=0)
    cfg = RunConfig(
        scheme="approx", n_workers=8, n_stragglers=1, num_collect=6,
        rounds=10, n_rows=64, n_cols=8, lr_schedule=1.0, add_delay=True,
    )
    with pytest.raises(ValueError, match="survivor_overrides"):
        failures.train_elastic(cfg, ds, {5: 4, 6: 4, 7: 4})
    # with the override, the same deaths recover fine
    res, rep = failures.train_elastic(
        cfg, ds, {5: 4, 6: 4, 7: 4},
        survivor_overrides={"n_stragglers": 0},
    )
    assert rep.n_workers_after == 5
    assert np.isfinite(np.asarray(res.params_history)).all()


def test_frc_config_divisibility_validated_at_config_time():
    """The registry descriptor's validate_config carries the reference
    guard (src/replication.py:24-26) for the FRC-family schemes, so the
    violation surfaces at RunConfig construction, not layout time."""
    for scheme in ("repcoded", "approx"):
        with pytest.raises(ValueError, match="n_stragglers"):
            RunConfig(
                scheme=scheme, n_workers=10, n_stragglers=2,
                num_collect=5, rounds=4, n_rows=64, n_cols=8,
                lr_schedule=1.0,
            )


@pytest.mark.parametrize(
    "scheme,layout_fn,kw,deaths,expect_feasible",
    [
        # naive: ANY death kills it
        ("naive", lambda: codes.uncoded_layout(W), {}, {0: 0}, False),
        # MDS tolerates s deaths, not s+1
        ("cyccoded", lambda: codes.cyclic_mds_layout(W, S, seed=0), {},
         {0: 0, 1: 0}, True),
        ("cyccoded", lambda: codes.cyclic_mds_layout(W, S, seed=0), {},
         {0: 0, 1: 0, 2: 0}, False),
        # FRC: deaths in distinct groups fine; a whole group dead is not
        ("repcoded", lambda: codes.frc_layout(W, S), {}, {0: 0, 3: 0}, True),
        ("repcoded", lambda: codes.frc_layout(W, S), {}, {0: 0, 1: 0, 2: 0},
         False),
        # AGC: group 0 fully dead but num_collect=6 still reachable
        ("approx", lambda: codes.frc_layout(W, S), {"num_collect": 6},
         {0: 0, 1: 0, 2: 0}, True),
        # AGC: group dead AND alive < num_collect
        ("approx", lambda: codes.frc_layout(W, S), {"num_collect": 10},
         {0: 0, 1: 0, 2: 0}, False),
    ],
)
def test_feasibility_rules(arrivals, scheme, layout_fn, kw, deaths, expect_feasible):
    t = failures.inject_worker_death(arrivals, deaths)
    rep = failures.analyze(Scheme(scheme), layout_fn(), t, **kw)
    assert rep.all_feasible == expect_feasible
    if not expect_feasible:
        assert rep.first_infeasible == 0


def test_plan_run_error_mode_raises(arrivals):
    t = failures.inject_worker_death(arrivals, {0: 3})
    with pytest.raises(failures.InfeasibleRunError, match="round 3"):
        failures.plan_run(Scheme.NAIVE, codes.uncoded_layout(W), t)


def test_failover_uncoded_unbiased_rescale(arrivals):
    """Dead worker from round 2: failover collects the 11 alive and rescales
    by W/11 — the avoidstragg estimator (src/avoidstragg.py:116)."""
    layout = codes.uncoded_layout(W)
    t = failures.inject_worker_death(arrivals, {4: 2})
    sched, rep = failures.plan_run(
        Scheme.NAIVE, layout, t, timeout=50.0, on_infeasible="failover"
    )
    # feasible rounds untouched
    ref = collect.collect_all(t)
    np.testing.assert_array_equal(sched.message_weights[:2], np.ones((2, W)))
    np.testing.assert_array_equal(sched.sim_time[:2], ref.sim_time[:2])
    # failover rounds: dead worker excluded, survivors rescaled, clock=timeout
    assert (sched.message_weights[2:, 4] == 0).all()
    np.testing.assert_allclose(
        sched.message_weights[2:, :4], W / (W - 1), rtol=0, atol=0
    )
    assert (sched.sim_time[2:] == 50.0).all()
    assert (sched.worker_times[2:, 4] == collect.NEVER).all()


def test_failover_frc_erases_dead_group(arrivals):
    """Group 0 (workers 0..2) fully dead: its partitions are erased
    (AGC semantics); other groups decode via their first alive member."""
    layout = codes.frc_layout(W, S)
    t = failures.inject_worker_death(arrivals, {0: 0, 1: 0, 2: 0})
    sched, rep = failures.plan_run(
        Scheme.FRC, layout, t, timeout=50.0, on_infeasible="failover"
    )
    assert not rep.all_feasible
    assert (sched.message_weights[:, :3] == 0).all()
    # exactly one winner in each surviving group each round
    for g in range(1, layout.n_groups):
        members = layout.groups == g
        np.testing.assert_array_equal(
            sched.message_weights[:, members].sum(axis=1), np.ones(R)
        )


def test_failover_mds_exact_within_budget(arrivals):
    """s workers dead: MDS failover decode weights must still satisfy the
    exact-recovery identity w^T B = 1 (every partition exactly once)."""
    layout = codes.cyclic_mds_layout(W, S, seed=0)
    t = failures.inject_worker_death(arrivals, {0: 0, 1: 0, 5: 2})
    sched, rep = failures.plan_run(
        Scheme.CYCLIC_MDS, layout, t, timeout=50.0, on_infeasible="failover"
    )
    for r in np.flatnonzero(~rep.feasible):
        recon = sched.message_weights[r] @ layout.B
        if (~rep.dead[r]).sum() >= W - S:
            np.testing.assert_allclose(recon, np.ones(W), atol=1e-8)


def test_failover_training_still_converges(arrivals):
    """End-to-end: AGC run with a group wiped out mid-run keeps training."""
    import jax.numpy as jnp

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.models.glm import LogisticModel
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    cfg = RunConfig(
        scheme="approx", n_workers=W, n_stragglers=S, num_collect=10,
        rounds=12, n_rows=24 * W, n_cols=16, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    layout = codes.frc_layout(W, S)
    t = straggler.arrival_schedule(cfg.rounds, W, True)
    t = failures.inject_worker_death(t, {0: 4, 1: 4, 2: 4})
    sched, rep = failures.plan_run(
        cfg.scheme, layout, t, num_collect=cfg.num_collect, timeout=20.0,
        on_infeasible="failover",
    )
    assert not rep.all_feasible
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=W, seed=0)
    res = trainer.train(
        cfg, data, mesh=worker_mesh(4), arrivals=t, schedule=sched
    )
    hist = np.asarray(res.params_history)
    assert np.isfinite(hist).all()
    model = LogisticModel()
    Xt, yt = jnp.asarray(data.X_test), jnp.asarray(data.y_test)
    first = float(model.loss_mean(jnp.asarray(hist[0]), Xt, yt))
    last = float(model.loss_mean(jnp.asarray(hist[-1]), Xt, yt))
    assert last < first * 0.7


def test_partial_layouts_refuse_failover(arrivals):
    layout = codes.partial_cyclic_layout(W, S + 2, S, seed=0)
    t = failures.inject_worker_death(arrivals, {0: 0})
    with pytest.raises(failures.InfeasibleRunError):
        failures.plan_run(
            Scheme.PARTIAL_CYCLIC, layout, t, timeout=50.0,
            on_infeasible="failover",
        )


def test_failover_requires_finite_timeout(arrivals):
    """failover stamps sim_time = timeout on rewritten rounds; an infinite
    timeout would silently corrupt every simulated-time view downstream."""
    from erasurehead_tpu.ops import codes

    t = failures.inject_worker_death(arrivals, {0: 0})
    with pytest.raises(ValueError, match="finite timeout"):
        failures.plan_run(
            Scheme.NAIVE, codes.uncoded_layout(W), t,
            on_infeasible="failover",
        )


def test_failover_all_dead_round_raises(arrivals):
    """The k == 0 path: a round where EVERY worker is presumed dead has no
    survivors to rescale over — failover must raise InfeasibleRunError,
    not divide by zero or emit a zero-weight round masquerading as
    progress."""
    layout = codes.uncoded_layout(W)
    t = failures.inject_worker_death(arrivals, {w: 2 for w in range(W)})
    rep = failures.analyze(Scheme.NAIVE, layout, t, timeout=50.0)
    assert not rep.all_feasible
    sched = collect.build_schedule(Scheme.NAIVE, t, layout)
    with pytest.raises(failures.InfeasibleRunError):
        failures.failover_schedule(sched, layout, t, rep, timeout=50.0)


def test_failover_schedule_rejects_partial_layout_directly(arrivals):
    """failover_schedule itself (not just plan_run) refuses partial
    layouts: their uncoded first-parts are structurally required, so no
    best-effort decode exists."""
    layout = codes.partial_cyclic_layout(W, S + 2, S, seed=0)
    t = failures.inject_worker_death(arrivals, {0: 0})
    rep = failures.analyze(Scheme.PARTIAL_CYCLIC, layout, t, timeout=50.0)
    assert not rep.all_feasible
    sched = collect.build_schedule(Scheme.PARTIAL_CYCLIC, t, layout)
    with pytest.raises(failures.InfeasibleRunError):
        failures.failover_schedule(sched, layout, t, rep, timeout=50.0)


def test_failover_finite_timeout_rule_applies_to_deadline_scheme(arrivals):
    """The finite-timeout requirement interacts with the deadline scheme:
    deadline collection is ALWAYS feasible (a dead worker just never
    arrives), yet on_infeasible='failover' still demands a finite timeout
    up front — the check guards the sim-clock contract, not a particular
    schedule. With a finite timeout, the deadline schedule sails through
    untouched."""
    t = failures.inject_worker_death(arrivals, {0: 0, 1: 0})
    layout = codes.uncoded_layout(W)
    # infinite timeout refused regardless of feasibility
    with pytest.raises(ValueError, match="finite timeout"):
        failures.plan_run(
            Scheme.DEADLINE, layout, t, deadline=1.0,
            on_infeasible="failover",
        )
    # finite timeout: all rounds feasible, schedule identical to plain
    sched, rep = failures.plan_run(
        Scheme.DEADLINE, layout, t, deadline=1.0, timeout=50.0,
        on_infeasible="failover",
    )
    assert rep.all_feasible
    ref = collect.build_schedule(Scheme.DEADLINE, t, layout, deadline=1.0)
    np.testing.assert_array_equal(
        sched.message_weights, ref.message_weights
    )
    np.testing.assert_array_equal(sched.sim_time, ref.sim_time)
    # every round's protocol cost is bounded by the deadline, dead workers
    # included (they simply never arrive)
    assert (sched.sim_time <= 1.0 + 1e-9).all()


def test_elastic_restart_continues_training():
    """train_elastic: full-W phase until the earliest death, re-shard onto
    survivors, optimizer state carries over, loss curve stays continuous
    and keeps decreasing — the capability the reference's README concedes
    it lacks (README.md:120-122: any death hangs the master forever)."""
    import jax.numpy as jnp

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.models.glm import LogisticModel
    from erasurehead_tpu.train import trainer

    W, R = 8, 24
    ds = generate_gmm(48 * W, 24, n_partitions=W, seed=0)
    cfg = RunConfig(
        scheme="approx", n_workers=W, n_stragglers=1, num_collect=6,
        rounds=R, n_rows=48 * W, n_cols=24, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    # workers 6 and 7 die at round 10 -> survivors W'=6, (s+1)|6 holds
    res, rep = failures.train_elastic(cfg, ds, {6: 10, 7: 12})
    assert rep.death_round == 10
    assert rep.n_workers_after == 6 and rep.dead_workers == (6, 7)
    hist = np.asarray(res.params_history)
    assert hist.shape[0] == R and np.isfinite(hist).all()
    # loss continuity + progress: strictly better after recovery than at
    # the failure point, and better than the phase-1 start
    model = LogisticModel()
    Xt, yt = jnp.asarray(ds.X_train), jnp.asarray(ds.y_train)
    losses = [
        float(model.loss_mean(jnp.asarray(hist[r]), Xt, yt))
        for r in (0, 9, R - 1)
    ]
    assert losses[2] < losses[1] < losses[0]
    # original worker numbering: dead columns carry -1 after the restart
    assert (res.worker_times[10:, 6:] == -1.0).all()
    assert not res.collected[10:, 6:].any()
    assert res.collected[:10, :].shape == (10, W)
    # phase-1 rounds kept the full-W clocks
    assert (res.worker_times[:10] > -1).any()
    assert res.timeset.shape == (R,)


def test_elastic_restart_validation():
    from erasurehead_tpu.data.synthetic import generate_gmm

    ds = generate_gmm(64, 8, n_partitions=4, seed=0)
    cfg = RunConfig(
        scheme="naive", n_workers=4, n_stragglers=0, rounds=6,
        n_rows=64, n_cols=8, lr_schedule=1.0, add_delay=True, seed=0,
    )
    with pytest.raises(ValueError, match="empty"):
        failures.train_elastic(cfg, ds, {})
    with pytest.raises(ValueError, match="outside"):
        failures.train_elastic(cfg, ds, {9: 2})
    with pytest.raises(ValueError, match="must be in"):
        failures.train_elastic(cfg, ds, {1: 0})


def test_elastic_restart_with_array_lr_schedule():
    """A per-round lr array stays continuous through the restart: phase 1
    takes its prefix, phase 2 the full array (regression: the truncated
    phase-1 config previously failed resolve_lr_schedule's shape check)."""
    from erasurehead_tpu.data.synthetic import generate_gmm

    W2, R2 = 4, 8
    ds = generate_gmm(32 * W2, 12, n_partitions=W2, seed=0)
    lr = np.linspace(1.0, 0.1, R2)
    cfg = RunConfig(
        scheme="naive", n_workers=W2, n_stragglers=0, rounds=R2,
        n_rows=32 * W2, n_cols=12, lr_schedule=lr, add_delay=True, seed=0,
    )
    res, rep = failures.train_elastic(cfg, ds, {3: 4}, measure=False)
    assert rep.n_workers_after == 3
    hist = np.asarray(res.params_history)
    assert hist.shape[0] == R2 and np.isfinite(hist).all()


def test_elastic_ignores_deaths_beyond_horizon():
    """A death scheduled at round >= cfg.rounds never happens inside the
    run: that worker must NOT be evicted (regression: it used to be)."""
    from erasurehead_tpu.data.synthetic import generate_gmm

    ds = generate_gmm(32 * 4, 12, n_partitions=4, seed=0)
    cfg = RunConfig(
        scheme="naive", n_workers=4, n_stragglers=0, rounds=8,
        n_rows=32 * 4, n_cols=12, lr_schedule=1.0, add_delay=True, seed=0,
    )
    res, rep = failures.train_elastic(cfg, ds, {3: 4, 2: 100}, measure=False)
    assert rep.dead_workers == (3,)  # worker 2 outlives the run
    assert rep.n_workers_after == 3
    with pytest.raises(ValueError, match="no death occurs"):
        failures.train_elastic(cfg, ds, {2: 100})


def test_elastic_restart_mlp():
    """Elastic recovery with an autodiff (pytree-params) model: the
    optimizer state's leaves are worker-count independent, so the MLP's
    params+momentum must carry across the re-shard exactly like the GLM
    beta — and the post-fix sharded gradients (step._weighted_loss_grad)
    must hold on the survivor mesh too. Loss continuous through the death."""
    import jax

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.models.mlp import MLPModel

    W = 8
    ds = generate_gmm(64 * W, 32, n_partitions=W, seed=0)
    cfg = RunConfig(
        scheme="approx", model="mlp", n_workers=W, n_stragglers=1,
        num_collect=6, rounds=16, n_rows=64 * W, n_cols=32,
        lr_schedule=0.5, update_rule="GD", add_delay=True, seed=0,
    )
    # two deaths so the 6 survivors still satisfy (s+1) | W
    res, rep = failures.train_elastic(cfg, ds, {6: 8, 7: 10})
    assert rep.death_round == 8 and rep.n_workers_after == 6
    hist = res.params_history
    leaves = jax.tree.leaves(hist)
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
    assert all(l.shape[0] == cfg.rounds for l in leaves)
    # training kept improving after the re-shard
    model = MLPModel()
    Xt, yt = ds.X_train, ds.y_train
    l_at_death = float(model.loss_mean(
        jax.tree.map(lambda l: l[8], hist), Xt, yt))
    l_end = float(model.loss_mean(
        jax.tree.map(lambda l: l[-1], hist), Xt, yt))
    assert l_end < l_at_death, (l_at_death, l_end)


def test_elastic_dynamic_deadline_telemetry_feeds_detection():
    """train_elastic(dynamic=True) x deadline interplay, round 2: the
    on-device rule's telemetry must itself be usable as the membership
    detector's input — detect_dead over the merged worker_times (sentinel
    + deadline semantics) flags exactly the dead worker's post-death
    rounds, and no alive worker accumulates a death-length streak. This
    is the contract the elastic/ controller builds on."""
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel.mesh import worker_mesh

    Wd, Rd, DEATH = 8, 12, 5
    ds = generate_gmm(32 * Wd, 16, n_partitions=Wd, seed=0)
    cfg = RunConfig(
        scheme="deadline", deadline=0.8, n_workers=Wd, n_stragglers=1,
        rounds=Rd, n_rows=32 * Wd, n_cols=16, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    res, rep = failures.train_elastic(
        cfg, ds, {2: DEATH}, mesh=worker_mesh(4), dynamic=True
    )
    # the dead column reads dead from the telemetry alone, every
    # post-death round (sentinel), at any detection timeout
    dead = failures.detect_dead(res.worker_times, timeout=cfg.deadline)
    assert dead[DEATH:, 2].all()
    # no surviving worker shows a K=3-round consecutive dead streak under
    # a timeout at the deadline (a deadline miss stamps the sentinel, but
    # the seeded exponential stream never misses 3 in a row here)
    K = 3
    alive_cols = [w for w in range(Wd) if w != 2]
    for w in alive_cols:
        col = dead[:, w]
        streak = longest = 0
        for s in col:
            streak = streak + 1 if s else 0
            longest = max(longest, streak)
        assert longest < K, (w, longest)


def test_elastic_dynamic_deadline_death_midrun():
    """VERDICT r3 #7: elastic recovery under the fully on-device control
    plane (trainer.train_dynamic) with the DEADLINE scheme — a worker dies
    mid-run while collection decisions live inside the jitted scan, the
    combination an online pod scheduler actually needs. Loss continuous
    through the death; dead column carries the -1 sentinel afterwards."""
    import jax.numpy as jnp

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.models.glm import LogisticModel
    from erasurehead_tpu.parallel.mesh import worker_mesh

    W, R, DEATH = 8, 12, 5
    ds = generate_gmm(32 * W, 24, n_partitions=W, seed=0)
    cfg = RunConfig(
        scheme="deadline", deadline=0.8, n_workers=W, n_stragglers=1,
        rounds=R, n_rows=32 * W, n_cols=24, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    res, rep = failures.train_elastic(
        cfg, ds, {3: DEATH}, mesh=worker_mesh(4), dynamic=True
    )
    assert rep.death_round == DEATH
    assert rep.n_workers_before == W and rep.n_workers_after == W - 1
    hist = np.asarray(res.params_history)
    assert hist.shape[0] == R and np.isfinite(hist).all()
    # dead worker's column: -1 / never collected after the restart,
    # real clocks before it
    assert (res.worker_times[DEATH:, 3] == -1.0).all()
    assert not res.collected[DEATH:, 3].any()
    assert (res.worker_times[:DEATH, 3] > -1.0).any()
    # deadline telemetry: every round's protocol time is bounded by the
    # deadline (the rule costs the full deadline when someone misses it)
    assert (res.timeset <= cfg.deadline + 1e-6).all()  # f32 sim clock
    # loss continuity + progress through the restart
    model = LogisticModel()
    Xt, yt = jnp.asarray(ds.X_train), jnp.asarray(ds.y_train)
    losses = [
        float(model.loss_mean(jnp.asarray(hist[r]), Xt, yt))
        for r in (0, DEATH - 1, DEATH, R - 1)
    ]
    assert losses[3] < losses[1] < losses[0]       # still converging
    assert losses[2] < losses[1] * 1.25            # no blow-up at restart
