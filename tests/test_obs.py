"""Live telemetry plane (ISSUE 18): streaming aggregation, critical-path
attribution, arrival-regime estimation, and the /metrics scrape surface.

The contracts pinned here:

  - the telemetry plane is OBSERVATION-ONLY like everything before it:
    with the reducer attached + a capture installed vs neither,
    ``params_history`` is bitwise identical across the sync, pipelined
    and streamed trainers, with zero extra compiles;
  - critical-path attribution CLOSES its ledgers: the sim buckets sum
    to the simulated clock and the host buckets to the measured wall,
    re-verified by the event validator within events.CRITICAL_PATH_TOL
    on every emitted line;
  - the regime estimator detects an exp(0.05) -> exp(2.0) arrival-rate
    shift within its short-window round budget, masks the -1 sentinel,
    and its verdict drives the adaptive controller on the flagged
    ``shift_source="regime"`` path (same decisions as the chunk-mean
    rule on the existing shift scenario);
  - /metrics is valid Prometheus text exposition (escaped labels,
    deterministic ordering, consistent under concurrent writers) even
    while a serve dispatch is in flight.
"""

import http.client
import json
import re
import threading
import time

import numpy as np
import pytest

from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.obs import critical_path as cpath_lib
from erasurehead_tpu.obs import events as obs_events
from erasurehead_tpu.obs import exporter as exporter_lib
from erasurehead_tpu.obs import regime as regime_lib
from erasurehead_tpu.obs.metrics import MetricsRegistry
from erasurehead_tpu.obs.timeseries import TimeseriesReducer, tail_path
from erasurehead_tpu.train import cache, trainer
from erasurehead_tpu.utils.config import RunConfig

W = 6
ROWS, COLS, ROUNDS = 240, 12, 5


def _dataset():
    return generate_gmm(ROWS, COLS, n_partitions=W, seed=0)


def _cfg(scheme, **kw):
    base = dict(
        scheme=scheme, n_workers=W, n_stragglers=1, rounds=ROUNDS,
        n_rows=ROWS, n_cols=COLS, lr_schedule=1.0, add_delay=True,
        compute_mode="deduped", seed=0,
    )
    base.update(kw)
    return RunConfig(**base)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _flat_history(res):
    import jax

    return np.concatenate(
        [np.asarray(l).ravel() for l in jax.tree.leaves(res.params_history)]
    )


# ---------------------------------------------------------------------------
# streaming reducer: windowed series from the typed stream


def _rec(rtype, t=100.0, **fields):
    return {"type": rtype, "seq": 0, "t": t, **fields}


def test_reducer_folds_typed_records_into_windows():
    red = TimeseriesReducer(window_s=5.0)
    red.consume(_rec(
        "rounds", t=101.0, run_id="r", first_round=0, n_rounds=10,
        sim_time_s=4.0,
        arrival={"p50": 0.4, "p90": 0.9, "p99": 1.4, "mean": 0.5,
                 "n_arrivals": 60},
    ))
    red.consume(_rec(
        "decode", t=102.0, run_id="r", first_round=0, n_rounds=10,
        error_mean=0.25, error_max=0.5, exact=False,
    ))
    red.consume(_rec("compile", t=103.0, run_id="r", cache_hit=True))
    red.consume(_rec("compile", t=103.5, run_id="r", cache_hit=False))
    red.consume(_rec(
        "request", t=104.0, tenant="alice", request_id="q1", label="a",
    ))
    red.consume(_rec(
        "request", t=104.5, tenant="alice", request_id="q1", label="a",
        phase="done", status="ok",
    ))
    red.consume(_rec("reject", t=104.6, tenant="bob", reason="quota"))
    snap = red.snapshot()
    assert snap["consumed"] == 7 and snap["malformed"] == 0
    [w] = snap["windows"]
    assert w["rounds"] == 10
    assert w["rounds_per_wall_sec"] == pytest.approx(10 / 5.0)
    assert w["rounds_per_sim_sec"] == pytest.approx(10 / 4.0)
    assert w["arrival"]["p90"] == pytest.approx(0.9)
    assert w["decode_error_mean"] == pytest.approx(0.25)
    assert w["decode_exact_share"] == 0.0
    assert w["compile_cache_hit_rate"] == pytest.approx(0.5)
    # per-tenant: intake vs done/rows_ok vs rejects all split out
    assert w["tenants"]["alice"] == {
        "requests": 1, "done": 1, "rows_ok": 1, "rejects": 0,
    }
    assert w["tenants"]["bob"]["rejects"] == 1


def test_reducer_memory_is_bounded():
    red = TimeseriesReducer(window_s=1.0, max_windows=3)
    for i in range(10):
        red.consume(_rec("rounds", t=float(i), run_id="r", first_round=0,
                         n_rounds=1, sim_time_s=0.1, arrival={}))
    snap = red.snapshot()
    assert len(snap["windows"]) == 3
    assert snap["windows"][0]["t0"] == 7.0  # oldest evicted first
    # malformed lines are counted, never raised
    assert red.consume_line("{not json") is False
    assert red.consume_line('"a bare string"') is False
    assert red.snapshot()["malformed"] == 2


def test_reducer_tail_and_attach(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(_rec(
            "rounds", run_id="r", first_round=0, n_rounds=3,
            sim_time_s=1.0, arrival={},
        )) + "\n")
        f.write("{partial garbage\n")
    red = tail_path(path)
    snap = red.snapshot()
    assert snap["consumed"] == 1 and snap["malformed"] == 1
    assert snap["windows"][0]["rounds"] == 3

    # in-process attach: records emitted under a capture ALSO reach the
    # reducer; detach stops the flow
    red2 = TimeseriesReducer()
    handle = red2.attach()
    try:
        with obs_events.capture(str(tmp_path / "live.jsonl")):
            obs_events.emit(
                "rounds", run_id="x", first_round=0, n_rounds=2,
                sim_time_s=0.5, arrival={},
            )
    finally:
        handle.detach()
    obs_events.emit(
        "rounds", run_id="x", first_round=2, n_rounds=2,
        sim_time_s=0.5, arrival={},
    )  # post-detach: not observed (and no capture -> not written)
    assert red2.snapshot()["consumed"] == 1


def test_observer_plane_works_without_capture():
    """The serve daemon scrapes /metrics with no events file: module
    emit() must still feed observers when no capture is installed."""
    seen = []
    obs_events.add_observer(seen.append)
    try:
        assert obs_events.current() is None
        obs_events.emit(
            "rounds", run_id="n", first_round=0, n_rounds=1,
            sim_time_s=0.1, arrival={},
        )
    finally:
        obs_events.remove_observer(seen.append)
    assert len(seen) == 1 and seen[0]["type"] == "rounds"
    assert "seq" in seen[0] and "t" in seen[0]


# ---------------------------------------------------------------------------
# critical-path attribution: the ledgers close


def test_attribute_ledgers_sum_exactly():
    timeset = np.array([2.0, 3.0, 1.5])
    wt = np.array([
        [0.5, 2.0, -1.0],
        [1.0, 3.0, 2.5],
        [0.25, 1.5, -1.0],
    ])
    coll = np.array([
        [True, True, False],
        [True, True, True],
        [True, True, False],
    ])
    cp = cpath_lib.attribute(timeset, wt, coll, wall_s=0.8,
                             prefetch_stall_s=0.3)
    assert cp.sim_total_s == pytest.approx(timeset.sum())
    assert sum(cp.sim_components.values()) == pytest.approx(cp.sim_total_s)
    assert sum(cp.components.values()) == pytest.approx(cp.wall_s)
    # fastest collected arrival is the compute floor of each round
    assert cp.sim_components["compute_s"] == pytest.approx(0.5 + 1.0 + 0.25)
    assert cp.components["prefetch_stall_s"] == pytest.approx(0.3)
    # every fraction in [0, 1]; each ledger's fractions sum to ~1
    fr = cp.fractions()
    assert all(0.0 <= v <= 1.0 for v in fr.values())
    sim_frac = fr["compute"] + fr["straggler_wait"] + fr["dispatch_gap"]
    host_frac = fr["decode_update"] + fr["prefetch_stall"]
    assert sim_frac == pytest.approx(1.0, abs=1e-5)
    assert host_frac == pytest.approx(1.0, abs=1e-5)


def test_attribute_dispatch_gap_from_pipelined_clocks():
    timeset = np.array([2.0, 2.0])
    wt = np.array([[1.0, 2.0], [1.0, 2.0]])
    coll = np.ones((2, 2), dtype=bool)
    # round 1 dispatched 0.5s after round 0 closed -> a master gap
    cp = cpath_lib.attribute(
        timeset, wt, coll, wall_s=0.1,
        dispatch=np.array([0.0, 2.5]), done=np.array([2.0, 4.5]),
    )
    assert cp.sim_components["dispatch_gap_s"] == pytest.approx(0.5)
    assert sum(cp.sim_components.values()) == pytest.approx(4.0)


def test_critical_path_events_validate_across_trainers(tmp_path):
    """Every trainer flavor emits a critical_path record whose ledgers
    the validator reconciles (the 5%% acceptance is enforced per line by
    events.validate_file — an empty problem list IS the <=5%% pin)."""
    cache.clear()
    ds = _dataset()
    runs = {
        "sync": _cfg("cyccoded"),
        "pipelined": _cfg(
            "avoidstragg", pipeline_depth=1, update_rule="GD"
        ),
    }
    for name, cfg in runs.items():
        path = str(tmp_path / f"{name}.jsonl")
        with obs_events.capture(path):
            res = trainer.train(cfg, ds)
        assert obs_events.validate_file(path) == [], name
        cps = [r for r in _events(path) if r["type"] == "critical_path"]
        assert len(cps) == 1, name
        cp = cps[0]
        assert cp["wall_s"] == pytest.approx(res.wall_time, abs=1e-5)
        assert sum(cp["sim_components"].values()) == pytest.approx(
            cp["sim_total_s"], rel=0.05
        )
        assert sum(cp["components"].values()) == pytest.approx(
            cp["wall_s"], rel=0.05, abs=1e-6
        )
    # the pipelined run's overlap is reported (a win, outside ledgers)
    pipe = [r for r in _events(str(tmp_path / "pipelined.jsonl"))
            if r["type"] == "critical_path"][0]
    assert pipe["overlap_hidden_s"] >= 0.0


def test_critical_path_streamed_carries_prefetch_stall(tmp_path):
    """The streamed trainer attributes its staging waits: the host
    ledger's prefetch_stall_s is the prefetcher's blocked_s."""
    cache.clear()
    ds = generate_gmm(128, 8, n_partitions=4, seed=0)
    cfg = RunConfig(
        scheme="repcoded", n_workers=4, n_stragglers=1,
        partitions_per_worker=2, rounds=2, n_rows=128, n_cols=8,
        lr_schedule=0.5, update_rule="GD", add_delay=True, seed=0,
        compute_mode="deduped", stack_residency="streamed",
        stream_window=1,
    )
    path = str(tmp_path / "streamed.jsonl")
    with obs_events.capture(path):
        res = trainer.train(cfg, ds)
    assert obs_events.validate_file(path) == []
    [cp] = [r for r in _events(path) if r["type"] == "critical_path"]
    stall = res.cache_info["prefetch"]["blocked_s"]
    assert stall > 0.0  # the scenario actually exercised staging waits
    # blocked_s counts prefetch-thread blocking too, which can exceed a
    # tiny timed region; attribute() clamps so the host ledger closes
    assert cp["components"]["prefetch_stall_s"] == pytest.approx(
        min(stall, cp["wall_s"]), abs=1e-5
    )
    assert sum(cp["components"].values()) == pytest.approx(
        cp["wall_s"], rel=0.05, abs=1e-6
    )


def test_report_renders_critical_path_section(tmp_path):
    from erasurehead_tpu.obs import report as obs_report

    cache.clear()
    path = str(tmp_path / "ev.jsonl")
    with obs_events.capture(path):
        trainer.train(_cfg("cyccoded"), _dataset())
    out = obs_report.render([path])
    assert "critical path (wall-clock attribution):" in out
    assert "straggler-wait" in out
    assert "decode+update" in out


# ---------------------------------------------------------------------------
# observation-only: the telemetry PLANE (capture + attached reducer) is
# bitwise invisible to the trajectory


@pytest.mark.parametrize(
    "name,kw",
    [
        ("sync", {}),
        ("pipelined", {"pipeline_depth": 1, "update_rule": "GD"}),
    ],
)
def test_telemetry_plane_is_observation_only(tmp_path, name, kw):
    cache.clear()
    ds = _dataset()
    cfg = _cfg("avoidstragg", **kw)
    off = trainer.train(cfg, ds)

    red = TimeseriesReducer()
    handle = red.attach()
    path = str(tmp_path / "events.jsonl")
    try:
        with obs_events.capture(path):
            on = trainer.train(cfg, ds)
    finally:
        handle.detach()
    np.testing.assert_array_equal(_flat_history(off), _flat_history(on))
    assert on.cache_info["exec_misses"] == 0
    assert obs_events.validate_file(path) == []
    # the reducer really watched the run (rounds + the attribution)
    snap = red.snapshot()
    assert sum(w["rounds"] for w in snap["windows"]) == ROUNDS
    assert snap["critical_path"] is not None


# ---------------------------------------------------------------------------
# arrival-regime estimation


def _exp_rows(rng, n_rounds, scale, w=W):
    return rng.exponential(scale, size=(n_rounds, w))


def test_hill_index_separates_exp_from_heavy_tail():
    rng = np.random.default_rng(0)
    exp = rng.exponential(0.5, size=2000)
    pareto = rng.pareto(1.2, size=2000) + 1.0
    h_exp = regime_lib.hill_index(exp)
    h_pareto = regime_lib.hill_index(pareto)
    assert h_exp > 2.0, h_exp  # light tail: well above the threshold
    assert h_pareto < 2.0, h_pareto  # converges near the true 1.2
    assert regime_lib.hill_index([1.0, 2.0]) is None  # too few


def test_regime_estimator_detects_rate_shift_within_budget(tmp_path):
    """The acceptance pin: an exp(0.05) -> exp(2.0) shift is flagged
    within the estimator's short-window budget (detect_rounds rounds
    after the change), across seeds, and the emitted regime events
    validate."""
    for seed in range(3):
        rng = np.random.default_rng(seed)
        est = regime_lib.ArrivalRegimeEstimator(detect_rounds=4)
        pre = _exp_rows(rng, 20, 0.05)
        post = _exp_rows(rng, 10, 2.0)
        for r in range(20):
            e = est.update(r, pre[r])
            assert not e.shifted
        detected = None
        for r in range(10):
            e = est.update(20 + r, post[r])
            if e.shifted:
                detected = 20 + r
                break
        assert detected is not None and detected < 20 + 4, (seed, detected)
        assert est.poll_shift() is True
        assert est.poll_shift() is False  # one-shot per change-point

    # emitted snapshots are schema-valid typed events
    path = str(tmp_path / "regime.jsonl")
    rng = np.random.default_rng(0)
    with obs_events.capture(path):
        est = regime_lib.ArrivalRegimeEstimator(emit_every=8)
        est.update_rounds(0, _exp_rows(rng, 20, 0.05))
        est.update_rounds(20, _exp_rows(rng, 8, 2.0))
    assert obs_events.validate_file(path) == []
    recs = [r for r in _events(path) if r["type"] == "regime"]
    assert recs and any(r["shifted"] for r in recs)
    assert all(r["kind"] in obs_events.REGIME_KINDS for r in recs)


def test_regime_estimator_masks_sentinel():
    """-1 never-arrived entries and non-finite values never enter the
    statistics (the arrival_summary discipline)."""
    rng = np.random.default_rng(1)
    clean = regime_lib.ArrivalRegimeEstimator()
    dirty = regime_lib.ArrivalRegimeEstimator()
    rows = _exp_rows(rng, 12, 0.5)
    for r in range(12):
        clean.update(r, rows[r])
        poisoned = np.concatenate([rows[r], [-1.0, np.inf, np.nan]])
        dirty.update(r, poisoned)
    a, b = clean.estimate(), dirty.estimate()
    assert a.n == b.n
    assert a.mean == pytest.approx(b.mean)
    assert a.kind == b.kind


def test_regime_estimate_unknown_below_min_samples():
    est = regime_lib.ArrivalRegimeEstimator(min_samples=8)
    est.update(0, [0.5, 0.6])  # 2 samples
    e = est.estimate()
    assert e.kind == "unknown" and e.rate is None
    # the payload still type-checks against the required schema
    assert obs_events.validate_lines([json.dumps(
        {"type": "regime", "seq": 0, "t": 0.0, **e.payload()}
    )]) == []


# ---------------------------------------------------------------------------
# adaptive controller: the flagged regime-verdict shift path


def _stats(sim, mean=1.0, err=0.0):
    from erasurehead_tpu.adapt.controller import ChunkStats

    return ChunkStats(
        n_rounds=5, sim_time=sim, decode_error_mean=err,
        arrival_mean=mean, arrival_p90=mean * 2,
    )


def test_controller_regime_source_uses_the_verdict():
    from erasurehead_tpu.adapt.controller import (
        AdaptiveController, Arm, ControllerConfig,
    )

    arms = [Arm("naive"), Arm("avoidstragg")]
    ctl = AdaptiveController(
        arms, ControllerConfig(shift_source="regime", seed=0)
    )
    ctl.choose()
    # a huge arrival jump with verdict=False: the estimator's word wins
    assert ctl.observe(0, _stats(9.0, mean=1.0), regime_shift=False) is None
    ctl.choose()
    assert ctl.observe(0, _stats(9.0, mean=50.0), regime_shift=False) is None
    # no jump at all but verdict=True: shift fires, values reset
    ctl.choose()
    shift = ctl.observe(0, _stats(9.0, mean=50.0), regime_shift=True)
    assert shift == "regime_shift"
    snap = ctl.snapshot()
    assert snap["weights"][1] == 0.0  # the other arm restarts from zero
    # verdict=None degrades to the chunk-mean jump rule, not blindness
    ctl2 = AdaptiveController(
        arms, ControllerConfig(shift_source="regime", seed=0)
    )
    ctl2.choose()
    ctl2.observe(0, _stats(9.0, mean=1.0))
    ctl2.choose()
    assert ctl2.observe(0, _stats(9.0, mean=10.0)) == "regime_shift"


def test_controller_rejects_unknown_shift_source():
    from erasurehead_tpu.adapt.controller import ControllerConfig

    with pytest.raises(ValueError, match="shift_source"):
        ControllerConfig(shift_source="tea_leaves")


def test_train_adaptive_regime_path_detects_the_existing_shift(tmp_path):
    """Satellite regression: the scenario the chunk-mean rule detects
    (tests/test_adapt.py) is also detected on the shift_source='regime'
    path — the estimator consumes the same raw arrival stream through
    the driver and its verdict reaches the controller."""
    from erasurehead_tpu import adapt
    from erasurehead_tpu.adapt.controller import Arm, ControllerConfig
    from erasurehead_tpu.parallel import straggler

    rounds = 60
    ds = generate_gmm(96, 8, W, seed=0)
    shift = straggler.RegimeShift(
        kind="adversary", round=30, worker=0, slowdown=8.0
    )
    arr = straggler.arrival_schedule(rounds, W, add_delay=True, regime=shift)
    arms = [Arm("naive"), Arm("avoidstragg"), Arm("deadline", deadline=1.5)]
    cfg = RunConfig(
        scheme="naive", n_workers=W, n_stragglers=1, rounds=rounds,
        n_rows=96, n_cols=8, lr_schedule=1.0, add_delay=True,
        compute_mode="deduped", update_rule="GD", seed=0,
    )
    path = str(tmp_path / "events.jsonl")
    with obs_events.capture(path):
        res = adapt.train_adaptive(
            cfg, ds, arms=arms,
            controller=ControllerConfig(
                chunk_rounds=5, seed=0, shift_source="regime"
            ),
            arrivals=arr,
        )
    reasons = [d["reason"] for d in res.decisions]
    assert "regime_shift" in reasons
    # the shift lands in the chunk covering round 30 (or the next)
    shift_chunk = reasons.index("regime_shift")
    assert 30 // 5 <= shift_chunk <= 30 // 5 + 2
    assert obs_events.validate_file(path) == []
    # the estimator's own regime events rode along in the same log
    regs = [r for r in _events(path) if r["type"] == "regime"]
    assert regs and any(r["shifted"] for r in regs)


# ---------------------------------------------------------------------------
# Prometheus exporter hygiene


def test_prometheus_rendering_escapes_and_sorts():
    reg = MetricsRegistry()
    reg.counter("serve.results").inc(3)
    reg.gauge("train.steps_per_sec").set(12.5)
    h = reg.histogram("serve.ttlr_seconds")
    for v in (0.1, 0.2, 0.3, 0.4):
        h.observe(v)
    gauges = {
        exporter_lib.prom_key(
            "tenant_requests", tenant='we"ird\\ten\nant'
        ): 2.0,
        "rounds_per_wall_sec": 1.5,
    }
    out = exporter_lib.render_prometheus(reg, gauges)
    assert out == exporter_lib.render_prometheus(reg, gauges)  # stable
    assert out.endswith("\n")
    # names sanitized under the prefix; label escaping per the spec
    assert "erasurehead_serve_results 3" in out
    assert 'tenant="we\\"ird\\\\ten\\nant"' in out
    # histograms export as summaries with quantiles + sum/count
    assert 'erasurehead_serve_ttlr_seconds{quantile="0.50"}' in out
    assert "erasurehead_serve_ttlr_seconds_count 4" in out
    # deterministic global ordering: families sorted
    families = [
        line.split()[2] for line in out.splitlines()
        if line.startswith("# TYPE")
    ]
    assert families == sorted(families)
    # every sample line parses as <name>[{labels}] <value>
    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? (-?\d[\d.e+-]*|NaN)$"
    )
    for line in out.splitlines():
        if not line.startswith("#"):
            assert sample.match(line), line


def test_prometheus_render_is_safe_under_concurrent_writers():
    reg = MetricsRegistry()
    stop = threading.Event()

    def writer(i):
        c = reg.counter(f"w{i}.events")
        while not stop.is_set():
            c.inc()
            reg.histogram(f"w{i}.lat").observe(0.1)

    threads = [
        threading.Thread(target=writer, args=(i,)) for i in range(4)
    ]
    for t in threads:
        t.start()
    try:
        for _ in range(50):
            out = exporter_lib.render_prometheus(reg)
            assert out.endswith("\n")
    finally:
        stop.set()
        for t in threads:
            t.join()
    # the typed export saw a consistent set each time: final render
    # carries every writer's family exactly once
    out = exporter_lib.render_prometheus(reg)
    for i in range(4):
        assert f"erasurehead_w{i}_events " in out


def test_slo_tracker_burn_rate_and_events(tmp_path):
    path = str(tmp_path / "slo.jsonl")
    with obs_events.capture(path):
        slo = exporter_lib.SloTracker(1.0, budget=0.25, window_s=60.0)
        # alice: 4 requests, 2 breach the 1s TTLR
        for i, ttlr in enumerate((0.5, 2.0, 0.8, 3.0)):
            slo.observe_submit(f"a{i}", "alice", t=100.0)
            slo.observe_done(f"a{i}", t=100.0 + ttlr)
        rows = slo.evaluate(now=105.0)
    [row] = rows
    assert row["tenant"] == "alice"
    assert row["window_requests"] == 4 and row["breaches"] == 2
    # breach fraction 0.5 over budget 0.25 -> burning 2x too fast
    assert row["burn_rate"] == pytest.approx(2.0)
    assert obs_events.validate_file(path) == []
    # completions older than the window age out
    assert slo.evaluate(now=1000.0) == []


def test_slo_tracker_pairs_request_records():
    slo = exporter_lib.SloTracker(1.0, budget=0.5)
    slo.observe({"type": "request", "request_id": "q", "tenant": "t",
                 "label": "x", "t": 10.0})
    slo.observe({"type": "request", "request_id": "q", "tenant": "t",
                 "label": "x", "t": 13.0, "phase": "done",
                 "status": "ok"})
    [row] = slo.evaluate(now=14.0)
    assert row["breaches"] == 1 and row["worst_ttlr_s"] == pytest.approx(3.0)


# ---------------------------------------------------------------------------
# the serve scrape surface: /metrics + /v1/stats during live load


@pytest.mark.slow
def test_metrics_endpoint_live_under_dispatch(tmp_path):
    from erasurehead_tpu.serve import server as serve_server
    from erasurehead_tpu.serve.client import HttpServeClient
    from erasurehead_tpu.serve.http_front import HttpFront

    cache.clear()
    cfg = {
        "scheme": "naive", "n_workers": 4, "n_stragglers": 1,
        "rounds": 2, "n_rows": 64, "n_cols": 8, "lr_schedule": 0.5,
        "add_delay": True, "compute_mode": "deduped",
    }

    def scrape(host, port, path):
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read().decode()
        ctype = resp.getheader("Content-Type")
        conn.close()
        return resp.status, ctype, body

    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? (-?\d[\d.e+-]*|NaN)$"
    )
    with serve_server.serving(window_s=0.05) as srv:
        front = HttpFront(srv, slo_ttlr_s=300.0)
        try:
            client = HttpServeClient(front.host, front.port, "alice")
            client.submit("job", cfg)
            # scrape WHILE the dispatch is in flight: must be valid
            # exposition, not an error or a half-rendered body
            status, ctype, body = scrape(front.host, front.port, "/metrics")
            assert status == 200
            assert ctype == exporter_lib.PROM_CONTENT_TYPE
            for line in body.splitlines():
                if line and not line.startswith("#"):
                    assert sample.match(line), line
            res = client.result(timeout=180)
            assert res["status"] == "ok"
            # post-completion scrape carries the tenant series and the
            # request counters the dispatch just bumped
            status, _, body = scrape(front.host, front.port, "/metrics")
            assert status == 200
            # the per-tenant series exists (its value is whatever landed
            # in the reducer's CURRENT window — don't pin the count)
            assert re.search(
                r'erasurehead_tenant_requests\{tenant="alice"\} \d', body
            ), body
            assert "erasurehead_serve_requests" in body
            # per-tenant stats: reducer windows + queue state
            status, _, stats = scrape(
                front.host, front.port, "/v1/stats?tenant=alice"
            )
            assert status == 200
            stats = json.loads(stats)
            assert stats["tenant"] == "alice"
            assert stats["requests"] >= 1 and stats["done"] >= 1
            assert stats["queued"] == 0
            client.close()
        finally:
            front.close()
    # observers detached on close: later emits don't reach the reducer
    before = front.reducer.snapshot()["consumed"]
    obs_events.emit(
        "rounds", run_id="z", first_round=0, n_rounds=1,
        sim_time_s=0.1, arrival={},
    )
    assert front.reducer.snapshot()["consumed"] == before


# ---------------------------------------------------------------------------
# the `top` renderer


def test_top_main_renders_one_frame(tmp_path, capsys):
    path = str(tmp_path / "ev.jsonl")
    with obs_events.capture(path):
        obs_events.emit(
            "rounds", run_id="r", first_round=0, n_rounds=4,
            sim_time_s=2.0,
            arrival={"p50": 0.5, "p90": 0.9, "p99": 1.2, "mean": 0.6,
                     "n_arrivals": 24},
        )
        obs_events.emit(
            "request", tenant="alice", request_id="q1", label="a",
        )
        obs_events.emit(
            "request", tenant="alice", request_id="q1", label="a",
            phase="done", status="ok",
        )
    rc = exporter_lib.top_main([path, "--slo-ttlr", "10"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "erasurehead-tpu top" in out
    assert "alice" in out
    assert "slo[alice]" in out

    assert exporter_lib.top_main([str(tmp_path / "missing.jsonl")]) == 1


def test_cli_dispatches_top(tmp_path, capsys):
    from erasurehead_tpu import cli

    path = str(tmp_path / "ev.jsonl")
    with obs_events.capture(path):
        obs_events.emit(
            "rounds", run_id="r", first_round=0, n_rounds=1,
            sim_time_s=0.5, arrival={},
        )
    assert cli.main(["top", path]) == 0
    assert "erasurehead-tpu top" in capsys.readouterr().out
