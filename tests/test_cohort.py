"""Trajectory-batched cohort dispatch (trainer.train_cohort, PR 4).

The load-bearing invariants:
  - the cohort structural fact: deduped partition-major stacks are
    BITWISE identical across all 7 reference schemes at fixed
    (n_partitions, dataset, dtype), and the sweep data cache serves ONE
    upload for the whole cohort;
  - cohort-batched trajectories match sequential train() to float
    tolerance across schemes, lowerings, and dtypes, with IDENTICAL
    control-plane artifacts (timeset / collected / decode_error);
  - a deduped 7-scheme x 4-seed compare() executes as <= 2 compiled scan
    dispatches, telemetry-verified (the ISSUE 4 acceptance bar);
  - batched event emission keeps the -1 never-arrived sentinel masked.
"""

import dataclasses
import json

import numpy as np
import pytest

import jax

from erasurehead_tpu.data.sharding import partition_stack
from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.obs.metrics import REGISTRY
from erasurehead_tpu.train import cache, experiments, trainer
from erasurehead_tpu.utils.config import (
    RunConfig,
    resolve_batch_trajectories,
)

W, ROUNDS = 8, 6
N_ROWS, N_COLS = 512, 24

SCHEME_EXTRAS = {
    "naive": {},
    "cyccoded": {},
    "repcoded": {},
    "approx": {"num_collect": 6},
    "avoidstragg": {},
    "randreg": {"num_collect": 6},
    "deadline": {"deadline": 1.0},
}


@pytest.fixture(scope="module")
def gmm():
    return generate_gmm(N_ROWS, N_COLS, n_partitions=W, seed=0)


@pytest.fixture(autouse=True)
def fresh_cache():
    cache.clear()
    cache.set_enabled(True)
    for name in ("cohort.dispatches", "cohort.trajectories",
                 "cohort.sequential_runs"):
        REGISTRY.counter(name).reset()
    yield
    cache.clear()


def _cfg(**kw):
    base = dict(
        scheme="approx",
        n_workers=W,
        n_stragglers=1,
        num_collect=6,
        rounds=ROUNDS,
        n_rows=N_ROWS,
        n_cols=N_COLS,
        update_rule="AGD",
        lr_schedule=0.5,
        add_delay=True,
        seed=3,
    )
    base.update(kw)
    return RunConfig(**base)


def _seven(**common_kw):
    common = dict(compute_mode="deduped")
    common.update(common_kw)
    return {
        scheme: _cfg(scheme=scheme, **{**common, **extra})
        for scheme, extra in SCHEME_EXTRAS.items()
    }


def _assert_traj_close(res, single, rtol=2e-5, atol=1e-6):
    for a, b in zip(
        jax.tree.leaves(res.params_history),
        jax.tree.leaves(single.params_history),
    ):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=rtol, atol=atol,
        )
    # control plane is computed per trajectory on host: IDENTICAL
    np.testing.assert_array_equal(res.timeset, single.timeset)
    np.testing.assert_array_equal(res.worker_times, single.worker_times)
    np.testing.assert_array_equal(res.collected, single.collected)
    np.testing.assert_array_equal(res.decode_error, single.decode_error)


# ---------------------------------------------------------------------------
# the cohort structural invariant


class TestCohortStackInvariant:
    def test_deduped_partition_stacks_bitwise_identical_across_schemes(
        self, gmm
    ):
        """The fact the tentpole rests on: the partition-major stack
        depends only on (n_partitions, dataset, dtype) — every one of the
        7 reference schemes sees the SAME bytes."""
        ref_X, ref_y = partition_stack(gmm, W)
        for scheme, extra in SCHEME_EXTRAS.items():
            cfg = _cfg(scheme=scheme, compute_mode="deduped", **extra)
            lay = trainer.build_layout(cfg)
            assert lay.n_partitions == W, scheme
            Xp, yp = partition_stack(gmm, lay.n_partitions)
            assert np.asarray(Xp).tobytes() == np.asarray(ref_X).tobytes()
            assert np.asarray(yp).tobytes() == np.asarray(ref_y).tobytes()

    def test_cohort_signature_groups_all_seven_schemes(self):
        keys = {
            trainer.cohort_signature(cfg)
            for cfg in _seven().values()
        }
        assert len(keys) == 1
        # faithful mode groups by assignment content instead: FRC and AGC
        # share one, cyclic MDS differs
        faithful = {
            s: trainer.cohort_signature(_cfg(scheme=s, **e))
            for s, e in SCHEME_EXTRAS.items()
        }
        assert faithful["approx"] == faithful["repcoded"]
        assert faithful["approx"] != faithful["cyccoded"]

    def test_one_upload_serves_the_whole_cohort(self, gmm):
        trainer.train_cohort(list(_seven().values()), gmm)
        s = cache.stats()
        assert s.data_misses == 1, s.snapshot()
        assert s.exec_misses == 1, s.snapshot()

    def test_ineligible_configs_have_no_signature(self):
        assert trainer.cohort_signature(_cfg(use_pallas="on")) is None
        assert (
            trainer.cohort_signature(
                _cfg(arrival_mode="measured", compute_mode="faithful")
            )
            is None
        )


# ---------------------------------------------------------------------------
# cross-scheme batch equivalence


class TestCohortEquivalence:
    SCHEMES = ("approx", "cyccoded", "repcoded", "randreg")

    @pytest.mark.parametrize(
        "lowering_kw",
        [{}, {"flat_grad": "on"}, {"margin_flat": "on"}],
        ids=["default", "flat", "margin-flat"],
    )
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_cross_scheme_matches_sequential(self, gmm, lowering_kw, dtype):
        cfgs = [
            _cfg(
                scheme=s, compute_mode="deduped", dtype=dtype,
                **{**SCHEME_EXTRAS[s], **lowering_kw},
            )
            for s in self.SCHEMES
        ]
        batch = trainer.train_cohort(cfgs, gmm)
        assert len(batch) == len(cfgs)
        tol = dict(rtol=2e-5, atol=1e-6)
        if dtype == "bfloat16":
            # bf16 margins round per reduction order (eps = 2^-8): the
            # cohort matmul vs the per-slot matvecs legitimately differ
            # at the ~1e-2 relative level after several AGD rounds
            tol = dict(rtol=5e-2, atol=5e-3)
        for c, res in zip(cfgs, batch):
            _assert_traj_close(res, trainer.train(c, gmm), **tol)

    def test_faithful_and_ring_cohorts(self, gmm):
        """Faithful cohorts (shared assignment): materialized and ring
        transports both match sequential train()."""
        for stack in ("materialized", "ring"):
            cfgs = [
                _cfg(scheme="repcoded", stack_mode=stack, seed=s)
                for s in (0, 1)
            ]
            for c, res in zip(cfgs, trainer.train_cohort(cfgs, gmm)):
                _assert_traj_close(res, trainer.train(c, gmm))

    def test_lr_and_alpha_variants_are_trajectory_axes(self, gmm):
        cfgs = [
            _cfg(compute_mode="deduped", lr_schedule=lr, alpha=a, seed=s)
            for (lr, a, s) in (
                (0.5, None, 0), (0.2, 0.01, 0), (1.0, 0.001, 7),
            )
        ]
        for c, res in zip(cfgs, trainer.train_cohort(cfgs, gmm)):
            _assert_traj_close(res, trainer.train(c, gmm))

    def test_grads_via_loss_model_batches(self, gmm):
        """Autodiff families (MLP) ride the vmapped local body."""
        cfgs = [
            _cfg(
                compute_mode="deduped", model="mlp", update_rule="GD",
                lr_schedule=0.1, seed=s,
            )
            for s in (0, 1)
        ]
        results = trainer.train_cohort(cfgs, gmm)
        assert results[0].cache_info["cohort_lowering"] == "per_slot_vmap"
        for c, res in zip(cfgs, results):
            _assert_traj_close(
                res, trainer.train(c, gmm), rtol=5e-4, atol=5e-5
            )

    def test_seeds_expansion_and_shared_arrivals(self, gmm):
        from erasurehead_tpu.parallel import straggler

        arr = straggler.arrival_schedule(ROUNDS, W, add_delay=True, mean=0.5)
        cfgs = [_cfg(compute_mode="deduped")]
        batch = trainer.train_cohort(cfgs, gmm, seeds=[0, 5], arrivals=arr)
        assert [r.config.seed for r in batch] == [0, 5]
        for res in batch:
            single = trainer.train(res.config, gmm, arrivals=arr)
            _assert_traj_close(res, single)

    def test_mixed_static_signature_refused(self, gmm):
        with pytest.raises(ValueError, match="static lowering signature"):
            trainer.train_cohort(
                [_cfg(), _cfg(dtype="bfloat16")], gmm
            )

    def test_mixed_stack_refused(self, gmm):
        # faithful cyccoded vs repcoded: different assignments, one cohort
        with pytest.raises(ValueError, match="different device data stack"):
            trainer.train_cohort(
                [_cfg(scheme="repcoded"), _cfg(scheme="cyccoded")], gmm
            )

    def test_cohort_exec_cache_reuse(self, gmm):
        cfgs = list(_seven().values())
        b1 = trainer.train_cohort(cfgs, gmm)
        assert b1[0].cache_info["exec_misses"] == 1
        b2 = trainer.train_cohort(cfgs, gmm)
        assert b2[0].cache_info["exec_hits"] == 1
        for a, b in zip(b1, b2):
            assert np.array_equal(
                np.asarray(a.params_history), np.asarray(b.params_history)
            )


# ---------------------------------------------------------------------------
# the acceptance bar: compare() collapses into <= 2 dispatches


class TestCompareBatched:
    def test_seven_scheme_four_seed_compare_two_dispatches_max(self):
        W30 = 30
        data = generate_gmm(W30 * 16, N_COLS, n_partitions=W30, seed=0)
        common = dict(
            n_workers=W30, n_stragglers=2, rounds=3, n_rows=W30 * 16,
            n_cols=N_COLS, update_rule="AGD", lr_schedule=0.5,
            add_delay=True, compute_mode="deduped",
        )
        extras = dict(SCHEME_EXTRAS, approx={"num_collect": 15},
                      randreg={"num_collect": 15})
        configs = {
            f"{s}_seed{seed}": RunConfig(
                scheme=s, seed=seed, **{**common, **extras[s]}
            )
            for s in SCHEME_EXTRAS
            for seed in range(4)
        }
        assert len(configs) == 28
        rows = experiments.compare(configs, data, batch="auto")
        assert len(rows) == 28
        # telemetry-verified dispatch count (the acceptance criterion)
        assert REGISTRY.counter("cohort.dispatches").value <= 2
        assert REGISTRY.counter("cohort.trajectories").value == 28
        s = cache.stats()
        assert s.exec_misses <= 2, s.snapshot()
        assert s.data_misses <= 2, s.snapshot()
        # and the batched rows carry the cohort telemetry
        assert all(r.cache.get("cohort_dispatches") == 1 for r in rows)

    def test_compare_batched_matches_sequential(self, gmm):
        configs = {
            s: _cfg(scheme=s, compute_mode="deduped", **SCHEME_EXTRAS[s])
            for s in ("approx", "repcoded", "naive")
        }
        batched = experiments.compare(dict(configs), gmm, batch="auto")
        cache.clear()
        sequential = experiments.compare(dict(configs), gmm, batch="off")
        by_b = {r.label: r for r in batched}
        by_s = {r.label: r for r in sequential}
        assert set(by_b) == set(by_s)
        for label in configs:
            np.testing.assert_allclose(
                by_b[label].training_loss, by_s[label].training_loss,
                rtol=2e-5, atol=1e-6,
            )
            assert (
                by_b[label].decode_error_mean
                == by_s[label].decode_error_mean
            )

    def test_plan_cohorts_orders_and_flags(self, gmm):
        configs = {
            "a": _cfg(scheme="approx", compute_mode="deduped"),
            "m": _cfg(arrival_mode="measured", compute_mode="faithful"),
            "b": _cfg(scheme="repcoded", compute_mode="deduped"),
        }
        plan = experiments.plan_cohorts(configs)
        assert plan[0] == (["a", "b"], True)
        assert plan[1] == (["m"], False)

    def test_plan_cohorts_memory_knobs_never_pack(self, gmm):
        """Negative packing: trajectories differing in stack_dtype,
        stack_mode, or ring_pipeline key DIFFERENT data caches / compiled
        scans (PR 6 grew the signature) and must land in different
        cohorts — a serve daemon packing them together would train an
        int8 client's request on an f32 stack (or vice versa)."""
        base = dict(scheme="approx", compute_mode="deduped")
        variants = {
            "f32": _cfg(**base),
            "int8": _cfg(**base, stack_dtype="int8"),
            "bf16_stack": _cfg(**base, stack_dtype="bfloat16"),
        }
        ring_base = dict(scheme="cyccoded", compute_mode="faithful")
        variants.update(
            {
                "mat": _cfg(**ring_base),
                "ring": _cfg(**ring_base, stack_mode="ring"),
                "ring_pipe": _cfg(
                    **ring_base, stack_mode="ring", ring_pipeline="on"
                ),
            }
        )
        plan = experiments.plan_cohorts(variants)
        # every variant is its own cohort: no two of these may share a
        # dispatch, even though schemes/shapes agree within each family
        assert sorted(labels for labels, _ in plan) == sorted(
            [[v] for v in variants]
        )
        # and the sanity inverse: agreeing knobs DO pack
        same = {
            "a": _cfg(**base, seed=0),
            "b": _cfg(**base, seed=1),
        }
        assert experiments.plan_cohorts(same)[0] == (["a", "b"], True)

    def test_batch_off_never_dispatches_cohorts(self, gmm):
        configs = {
            s: _cfg(scheme=s, compute_mode="deduped", **SCHEME_EXTRAS[s])
            for s in ("approx", "repcoded")
        }
        experiments.compare(configs, gmm, batch="off")
        assert REGISTRY.counter("cohort.dispatches").value == 0
        assert REGISTRY.counter("cohort.sequential_runs").value == 2

    def test_resolve_batch_trajectories(self):
        assert resolve_batch_trajectories(None, env="") == "auto"
        assert resolve_batch_trajectories("on") == "on"
        assert resolve_batch_trajectories(None, env="0") == "off"
        assert resolve_batch_trajectories(None, env="true") == "on"
        with pytest.raises(ValueError, match="on/off/auto"):
            resolve_batch_trajectories("sometimes")


# ---------------------------------------------------------------------------
# telemetry: cohort events, per-trajectory series, sentinel masking


class TestCohortTelemetry:
    def test_cohort_event_and_per_trajectory_series(self, gmm, tmp_path):
        path = str(tmp_path / "events.jsonl")
        cfgs = [
            _cfg(scheme=s, compute_mode="deduped", seed=sd,
                 **SCHEME_EXTRAS[s])
            for s in ("approx", "repcoded")
            for sd in (0, 1)
        ]
        with events_lib.capture(path):
            trainer.train_cohort(cfgs, gmm)
        assert events_lib.validate_file(path) == []
        recs = [json.loads(l) for l in open(path)]
        cohort = [r for r in recs if r["type"] == "cohort"]
        assert len(cohort) == 1
        assert cohort[0]["n_trajectories"] == 4
        assert cohort[0]["schemes"] == ["approx", "repcoded"]
        assert cohort[0]["dispatches"] == 1
        # one tagged rounds/decode stream per trajectory
        tags = {
            r.get("trajectory") for r in recs if r["type"] == "rounds"
        }
        assert len(tags) == 4 and None not in tags
        decode_tags = {
            r.get("trajectory") for r in recs if r["type"] == "decode"
        }
        assert decode_tags == tags
        # report renders the composition line
        from erasurehead_tpu.obs import report

        txt = report.render([path])
        assert "2 scheme(s) x 2 seed(s) = 4 trajectories in 1 dispatch" in txt

    def test_never_arrived_sentinel_masked_in_batched_emission(
        self, gmm, tmp_path
    ):
        """Deadline trajectories leave -1 sentinels in worker_times; every
        arrival stat in the cohort's batched emission must mask them."""
        path = str(tmp_path / "events.jsonl")
        cfgs = [
            _cfg(scheme="deadline", compute_mode="deduped", deadline=0.2,
                 delay_mean=2.0, seed=s)
            for s in (0, 1)
        ]
        with events_lib.capture(path):
            results = trainer.train_cohort(cfgs, gmm)
        # the run genuinely produced never-arrived workers
        assert any((r.worker_times == -1).any() for r in results)
        recs = [json.loads(l) for l in open(path)]
        arrival_blocks = [
            r["arrival"] for r in recs if r["type"] in ("rounds", "run_end")
        ]
        assert any(a["n_never"] > 0 for a in arrival_blocks)
        for a in arrival_blocks:
            for q in ("p50", "p90", "p99", "mean"):
                if a[q] is not None:
                    assert a[q] >= 0.0, a

    def test_telemetry_off_is_observation_only(self, gmm):
        cfgs = [
            _cfg(scheme=s, compute_mode="deduped", **SCHEME_EXTRAS[s])
            for s in ("approx", "repcoded")
        ]
        plain = trainer.train_cohort(cfgs, gmm)
        cache.clear()
        with events_lib.capture("/dev/null"):
            logged = trainer.train_cohort(cfgs, gmm)
        for a, b in zip(plain, logged):
            assert np.array_equal(
                np.asarray(a.params_history), np.asarray(b.params_history)
            )


# ---------------------------------------------------------------------------
# train_batch compatibility wrapper


def test_train_batch_delegates_to_cohort(gmm):
    batch = trainer.train_batch(_cfg(), gmm, [3, 11])
    info = batch[0].cache_info
    assert info["batch_size"] == 2 and info["batch_dispatches"] == 1
    assert info["cohort_size"] == 2 and info["cohort_dispatches"] == 1
    # the historical refusal contract survives the rewrite
    with pytest.raises(ValueError, match="seed-dependent"):
        trainer.train_batch(_cfg(scheme="cyccoded"), gmm, [0, 1])


def test_cohort_empty_and_pallas_refused(gmm):
    with pytest.raises(ValueError, match="at least one"):
        trainer.train_cohort([], gmm)
    with pytest.raises(ValueError, match="fused-kernel"):
        trainer.train_cohort([_cfg(use_pallas="on")], gmm)
