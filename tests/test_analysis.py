"""`erasurehead-tpu lint` tests (ISSUE 10): per-checker positive/negative
AST fixtures (tests/fixtures/lint/), the zero-findings pin on the shipped
tree (the tier-1 gate: re-introducing a PR 2-style missing signature
field or a jit-interior emit() fails here), report determinism, the
suppression contract, and the schema cross-check drift fixtures.

Pure AST — no jax import anywhere on the analysis path, so this module
also pins the <5 s full-tree wall-time budget that keeps lint inside the
tier-1 loop.
"""

import os
import re
import subprocess
import sys
import time

import pytest

from erasurehead_tpu import analysis
from erasurehead_tpu.analysis import core, runner

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(TESTS_DIR, "fixtures", "lint")
REPO_ROOT = os.path.dirname(TESTS_DIR)
PKG_ROOT = os.path.join(REPO_ROOT, "erasurehead_tpu")
TOOLS_DIR = os.path.join(REPO_ROOT, "tools")


def _lint(path, checkers=None):
    return runner.lint_paths([path], checkers=checkers)


def _unsup(report, checker=None):
    out = [f for f in report.findings if not f.suppressed]
    if checker is not None:
        out = [f for f in out if f.checker == checker]
    return out


def _fx(name):
    return os.path.join(FIXTURES, name)


# ---------------------------------------------------------------------------
# per-checker fixtures: each checker flags its seeded violations and stays
# silent on the clean counterpart


def test_purity_fixture_flags_seeded_violations():
    findings = _unsup(_lint(_fx("purity_bad.py")), "trace-purity")
    msgs = "\n".join(f.message for f in findings)
    # the jit-interior emit() mutation (direct AND via a reachable helper)
    assert msgs.count("emit") >= 2
    for marker in ("time.time", "print", "np.random", ".inc", "open"):
        assert marker in msgs, f"{marker} not flagged:\n{msgs}"
    assert len(findings) >= 6


def test_purity_fixture_clean_counterpart():
    assert _unsup(_lint(_fx("purity_ok.py"))) == []


def test_signature_fixture_flags_missing_fields():
    findings = _unsup(
        _lint(_fx("signature_bad.py")), "signature-completeness"
    )
    attrs = {re.search(r"cfg\.(\w+)", f.message).group(1) for f in findings}
    assert attrs == {"delay_mean", "num_collect"}


def test_signature_fixture_clean_counterpart():
    assert _unsup(_lint(_fx("signature_ok.py"))) == []


def test_dispatch_fixture_flags_if_elif_spine():
    findings = _unsup(_lint(_fx("dispatch_bad.py")), "registry-dispatch")
    assert len(findings) >= 3  # enum ==, string ==, membership test


def test_dispatch_fixture_clean_counterpart():
    assert _unsup(_lint(_fx("dispatch_ok.py"))) == []


def test_schema_fixture_flags_drifted_emits():
    findings = _unsup(_lint(_fx("schema_bad.py")), "event-schema")
    msgs = "\n".join(f.message for f in findings)
    assert "seconds" in msgs and "cache_hit" in msgs  # missing fields
    assert "not_in_schema" in msgs  # unknown type
    assert "wall_time_s" in msgs  # logger-object emit checked too
    assert len(findings) == 3


def test_schema_fixture_clean_counterpart():
    assert _unsup(_lint(_fx("schema_ok.py"))) == []


def test_schema_membership_fixture():
    """The elastic `membership` record is lint-enforced like every other
    type: emits missing required fields (round/action/n_workers) are
    findings, and the clean counterpart's full-field membership emit in
    schema_ok.py stays silent."""
    findings = _unsup(
        _lint(_fx("schema_membership_bad.py")), "event-schema"
    )
    msgs = "\n".join(f.message for f in findings)
    assert "action" in msgs and "n_workers" in msgs
    assert "round" in msgs  # the logger-object emit is checked too
    assert len(findings) == 2


def test_schema_serve_fixture():
    """The PR-13 serve records (reject/stream/restart) are lint-enforced
    like every other type: emits missing required fields are findings —
    a drifted backpressure or warm-restart emit fails `erasurehead-tpu
    lint`, not the first overloaded daemon in production."""
    findings = _unsup(_lint(_fx("schema_serve_bad.py")), "event-schema")
    msgs = "\n".join(f.message for f in findings)
    assert "reason" in msgs
    assert "event" in msgs
    assert "rehydrated" in msgs  # the logger-object emit is checked too
    assert len(findings) == 3


def test_schema_fleet_fixture():
    """The fleet records (probe/suspect/declare_dead/adopt/deploy_phase)
    are lint-enforced like every other type: emits missing required
    fields are findings — a drifted death-declaration or adoption emit
    fails `erasurehead-tpu lint`, not the first replica kill in
    production."""
    findings = _unsup(_lint(_fx("schema_fleet_bad.py")), "event-schema")
    msgs = "\n".join(f.message for f in findings)
    assert "action" in msgs
    assert "replica" in msgs
    assert len(findings) == 3  # the logger-object emit is checked too


def test_schema_io_fixture():
    """The out-of-core records (prefetch/io) are lint-enforced like
    every other type: emits missing required fields are findings — a
    drifted shard-read or prefetch-window byte account fails
    `erasurehead-tpu lint`, not the first streamed run in production."""
    findings = _unsup(_lint(_fx("schema_io_bad.py")), "event-schema")
    msgs = "\n".join(f.message for f in findings)
    assert "window" in msgs
    assert "bytes" in msgs
    assert "kind" in msgs  # the logger-object emit is checked too
    assert len(findings) == 3


def test_schema_window_fixture():
    """The ISSUE-17 window-plan contract is lint-enforced: a ``prefetch``
    emit that accounts bytes but drops the staged ``ranges`` list (the
    assignment-aware window plan's [lo, hi) spans) is a finding — a
    drifted windowed-prefetch emit fails `erasurehead-tpu lint`, not the
    first composed streamed+ring run in production."""
    findings = _unsup(_lint(_fx("schema_window_bad.py")), "event-schema")
    msgs = "\n".join(f.message for f in findings)
    assert "ranges" in msgs
    assert "bytes" in msgs  # the logger-object emit is checked too
    assert len(findings) == 2


def test_schema_whatif_fixture():
    """The what-if engine's `whatif` record (ISSUE 12) is lint-enforced
    like every other type: emits missing spec_hash/kind are findings,
    and schema_ok.py's full-field whatif emit stays silent."""
    findings = _unsup(_lint(_fx("schema_whatif_bad.py")), "event-schema")
    msgs = "\n".join(f.message for f in findings)
    assert "spec_hash" in msgs
    assert "kind" in msgs  # the logger-object emit is checked too
    assert len(findings) == 2


def test_schema_pipeline_fixture():
    """The pipelined-training records (ISSUE 16: dispatch_ahead /
    stale_decode) are lint-enforced like every other type: emits missing
    the staleness bookkeeping fields are findings."""
    findings = _unsup(_lint(_fx("schema_pipeline_bad.py")), "event-schema")
    msgs = "\n".join(f.message for f in findings)
    assert "pipeline_depth" in msgs
    assert "staleness_share" in msgs  # the logger-object emit is checked
    assert len(findings) == 2


def test_schema_obs_fixture():
    """The live-telemetry records (ISSUE 18: critical_path / regime /
    slo) are lint-enforced like every other type: emits missing the
    attribution ledger or the change-point flag are findings."""
    findings = _unsup(_lint(_fx("schema_obs_bad.py")), "event-schema")
    msgs = "\n".join(f.message for f in findings)
    assert "sim_components" in msgs
    assert "shifted" in msgs  # the logger-object emit is checked too
    assert len(findings) == 2


def test_schema_tune_fixture():
    """The autotune-plane `tune` record (ISSUE 19) is lint-enforced like
    every other type: emits missing required fields are findings, a
    constant race/source outside TUNE_RACES/TUNE_SOURCES is a finding
    (the runtime validator's membership check at lint time), and a
    TUNE_CHOICES declaration that drifts from the schema's race
    vocabulary is a finding — schema_ok.py's full-field tune emit stays
    silent."""
    findings = _unsup(_lint(_fx("schema_tune_bad.py")), "event-schema")
    msgs = "\n".join(f.message for f in findings)
    assert "source" in msgs
    assert "device_kind" in msgs  # the logger-object emit is checked too
    assert "margin_lowering" in msgs and "TUNE_RACES" in msgs
    assert "guess" in msgs and "TUNE_SOURCES" in msgs
    assert "TUNE_CHOICES" in msgs  # the vocabulary-drift check
    assert len(findings) == 5


def test_schema_validator_drift_fixture():
    findings = _unsup(_lint(_fx("schema_drift_bad.py")), "event-schema")
    assert len(findings) == 1
    assert "checkpointed" in findings[0].message


def test_schema_cli_wrapper_drift_fixture():
    findings = _unsup(_lint(_fx("cli_wrapper_bad")), "event-schema")
    msgs = "\n".join(f.message for f in findings)
    assert "does not delegate" in msgs
    assert "independent record-type table" in msgs


def test_donation_fixture_flags_read_after_donate():
    findings = _unsup(_lint(_fx("donation_bad.py")), "donation-safety")
    assert len(findings) >= 2  # direct jit call + the AOT lower/compile chain
    assert all("state0" in f.message for f in findings)


def test_donation_fixture_clean_counterpart():
    assert _unsup(_lint(_fx("donation_ok.py"))) == []


# ---------------------------------------------------------------------------
# the tier-1 gate: the shipped tree is clean, and stays clean


def test_shipped_tree_zero_unsuppressed_findings():
    """THE acceptance pin: `erasurehead-tpu lint erasurehead_tpu/ tools/`
    exits 0. Re-introducing a PR 2-style signature omission, a
    jit-interior emit(), an out-of-registry scheme branch, a SCHEMA
    drift, or a donated-buffer reuse anywhere in the tree fails here."""
    report = runner.lint_paths([PKG_ROOT, TOOLS_DIR])
    assert _unsup(report) == [], report.render(strict=True)


def test_shipped_tree_lint_budget():
    """Full-tree wall time stays well inside the 5 s tier-1 budget."""
    t0 = time.perf_counter()
    runner.lint_paths([PKG_ROOT, TOOLS_DIR])
    assert time.perf_counter() - t0 < 5.0


def test_report_determinism():
    """Two runs over the full tree + fixtures render byte-identically."""
    paths = [PKG_ROOT, FIXTURES]
    a = runner.lint_paths(paths).render(strict=True)
    b = runner.lint_paths(paths).render(strict=True)
    assert a == b
    assert a.encode() == b.encode()


def test_traced_graph_resolves_factory_idiom():
    """The shared visitor infra resolves the step.py factory idiom:
    shard_map(_dq(_factory(model))) traces the factory's returned
    closure, not just direct function references."""
    path = os.path.join(PKG_ROOT, "parallel", "step.py")
    with open(path) as f:
        mod = core.SourceModule(path, f.read())
    names = {
        getattr(fn, "name", "<lambda>")
        for fn, _ in mod.traced_functions().values()
    }
    assert "_ring_fill" in names  # called from inside a traced body
    assert any(n == "local" for n in names)  # factory-returned closures


# ---------------------------------------------------------------------------
# suppression contract


def test_suppressions_apply_and_count():
    report = _lint(_fx("suppressed.py"))
    # the seeded effects are all suppressed...
    assert _unsup(report, "trace-purity") == []
    assert _unsup(report, "registry-dispatch") == []
    counts = report.suppression_counts()
    assert counts.get("trace-purity", 0) == 2
    assert counts.get("registry-dispatch", 0) == 1
    # ...but the reason-less allow is itself a finding
    problems = _unsup(report, "suppression")
    assert len(problems) == 1
    assert "no reason" in problems[0].message


def test_strict_report_renders_suppression_counts():
    text = _lint(_fx("suppressed.py")).render(strict=True)
    assert "suppressions by checker:" in text
    assert "trace-purity: 2" in text


def test_unknown_checker_rejected():
    with pytest.raises(ValueError, match="unknown checker"):
        runner.lint_paths([FIXTURES], checkers=["definitely-not-a-checker"])


def test_checker_registry_names():
    assert set(analysis.CHECKERS) == {
        "trace-purity",
        "signature-completeness",
        "registry-dispatch",
        "event-schema",
        "donation-safety",
    }


# ---------------------------------------------------------------------------
# mutation coverage: doctored context sources prove the cross-file checks
# key on the REAL config/schema, not on hardcoded copies


def test_signature_mutation_detected():
    """The PR 2 mutation test: deleting scan_unroll from
    static_signature_fields() makes the real trainer.py fail lint."""
    cfg_path = os.path.join(PKG_ROOT, "utils", "config.py")
    with open(cfg_path) as f:
        cfg_src = f.read()
    assert '"scan_unroll": self.scan_unroll,' in cfg_src
    mutated = cfg_src.replace('"scan_unroll": self.scan_unroll,', "")
    ctx = runner.LintContext.load(config_source=mutated)
    trainer_path = os.path.join(PKG_ROOT, "train", "trainer.py")
    report = runner.lint_paths(
        [trainer_path], checkers=["signature-completeness"], context=ctx
    )
    findings = _unsup(report)
    assert findings, "mutated signature not detected"
    assert any("scan_unroll" in f.message for f in findings)


def test_schema_mutation_detected():
    """Deleting the `compile` record type from SCHEMA makes the real
    trainer.py's emit sites fail lint."""
    ev_path = os.path.join(PKG_ROOT, "obs", "events.py")
    with open(ev_path) as f:
        ev_src = f.read()
    schema = runner.schema.parse_schema(ev_src)
    assert "compile" in schema
    mutated = dict(schema)
    del mutated["compile"]
    ctx = runner.LintContext.load()
    ctx.schema = mutated
    trainer_path = os.path.join(PKG_ROOT, "train", "trainer.py")
    report = runner.lint_paths(
        [trainer_path], checkers=["event-schema"], context=ctx
    )
    assert any(
        "compile" in f.message for f in _unsup(report)
    ), "mutated schema not detected"


def test_parsed_schema_matches_runtime_schema():
    """The AST-parsed SCHEMA (what lint checks against) is exactly the
    runtime SCHEMA (what validate_lines enforces) — the checker can
    never drift from the validator it fronts."""
    from erasurehead_tpu.obs import events as events_lib

    ev_path = os.path.join(PKG_ROOT, "obs", "events.py")
    with open(ev_path) as f:
        parsed = runner.schema.parse_schema(f.read())
    assert parsed == {k: tuple(v) for k, v in events_lib.SCHEMA.items()}


def test_parsed_config_matches_runtime_config():
    """AST-parsed RunConfig fields/signature keys == the runtime ones."""
    import dataclasses as dc

    from erasurehead_tpu.utils.config import RunConfig

    ctx = runner.LintContext.load()
    runtime_fields = {f.name for f in dc.fields(RunConfig)}
    assert ctx.config_fields == frozenset(runtime_fields)
    runtime_keys = set(RunConfig().static_signature_fields())
    assert ctx.signature_keys == frozenset(runtime_keys)


# ---------------------------------------------------------------------------
# CLI surface


def test_cli_lint_module_entry_exit_codes(tmp_path):
    """python -m erasurehead_tpu.analysis: clean tree -> 0, findings -> 1,
    and the report lands on stdout."""
    proc = subprocess.run(
        [sys.executable, "-m", "erasurehead_tpu.analysis", PKG_ROOT],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout
    proc = subprocess.run(
        [
            sys.executable, "-m", "erasurehead_tpu.analysis",
            _fx("dispatch_grep_miss.py"),
        ],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120,
    )
    assert proc.returncode == 1
    assert "registry-dispatch" in proc.stdout


def test_cli_lint_subcommand_wired():
    """`erasurehead-tpu lint` routes through cli.main without touching
    the training entry points."""
    from erasurehead_tpu import cli

    rc = cli.main(["lint", _fx("purity_ok.py")])
    assert rc == 0
    rc = cli.main(["lint", _fx("purity_bad.py")])
    assert rc == 1
