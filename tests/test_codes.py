"""Unit tests for the coding-theory core (erasurehead_tpu.ops.codes).

The central property (SURVEY.md §4): for every (W, s) and every straggler
pattern of size <= s, the decode weights recovered from the surviving workers
reconstruct the exact full-batch gradient (sum of all partition gradients).
"""

import itertools

import jax.numpy as jnp
import numpy as np
import pytest

from erasurehead_tpu.ops import codes


def _all_live_masks(W, s):
    """Every completion mask with exactly W-s live workers."""
    for stragglers in itertools.combinations(range(W), s):
        mask = np.ones(W, dtype=bool)
        mask[list(stragglers)] = False
        yield mask


# ---------------------------------------------------------------------------
# Generator matrix & MDS decode
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("W,s", [(4, 1), (6, 2), (6, 3), (9, 2), (10, 3)])
def test_mds_exact_recovery_all_patterns(W, s):
    B = codes.cyclic_generator_matrix(W, s, seed=1)
    ones = np.ones(W)
    for mask in _all_live_masks(W, s):
        a = np.asarray(codes.mds_decode_weights(jnp.asarray(B), jnp.asarray(mask)))
        # support only on live workers
        assert np.allclose(a[~mask], 0.0)
        # a @ B == all-ones => decoded gradient == sum of partition gradients
        assert np.allclose(a @ B, ones, atol=2e-3), (mask, a @ B)


@pytest.mark.parametrize("W,s", [(10, 3), (30, 3)])
def test_mds_host_decode_exact_at_scale(W, s):
    """The float64 host path must stay exact at the canonical W=30 scale,
    where the fp32 on-device solve demonstrably cannot (see
    mds_decode_weights_host docstring)."""
    B = codes.cyclic_generator_matrix(W, s, seed=1)
    rng = np.random.default_rng(0)
    masks = np.ones((50, W), dtype=bool)
    for r in range(50):
        masks[r, rng.choice(W, size=s, replace=False)] = False
    A = codes.mds_decode_weights_host(B, masks)
    assert np.allclose(A[~masks], 0.0)
    err = np.abs(A @ B - 1.0).max()
    assert err < 1e-6, err


@pytest.mark.parametrize("W,s", [(4, 1), (6, 2), (9, 3), (12, 2), (14, 3)])
def test_decode_table_matches_host_across_shapes(W, s):
    """MdsDecodeTable (full 0..s range AND exact-only) == the f64 host
    solve for EVERY <=s / exactly-s straggler pattern at each shape — the
    exhaustive small-shape sweep behind the W=30 spot checks in
    test_dynamic."""
    B = codes.cyclic_generator_matrix(W, s, seed=2)
    table = codes.build_decode_table(B, s)
    exact = codes.build_decode_table(B, s, exact_only=True)
    for k in range(s + 1):
        for mask in _all_live_masks(W, k):
            want = codes.mds_decode_weights_host(B, mask[None])[0]
            got = np.asarray(table.lookup(jnp.asarray(mask)))
            np.testing.assert_allclose(
                got, want.astype(np.float32), rtol=2e-4, atol=1e-4,
                err_msg=f"full table {mask}",
            )
            if k == s:
                got_e = np.asarray(exact.lookup(jnp.asarray(mask)))
                np.testing.assert_allclose(
                    got_e, want.astype(np.float32), rtol=2e-4, atol=1e-4,
                    err_msg=f"exact table {mask}",
                )


def test_mds_recovery_of_actual_gradients():
    W, s, F = 8, 2, 5
    rng = np.random.default_rng(0)
    G = rng.standard_normal((W, F))  # per-partition gradients
    layout = codes.cyclic_mds_layout(W, s, seed=3)
    E = layout.effective_matrix()
    msgs = E @ G  # what each worker transmits
    full = G.sum(axis=0)
    masks = np.stack(list(itertools.islice(_all_live_masks(W, s), 10)))
    A = codes.mds_decode_weights_host(layout.B, masks)
    assert np.allclose(A @ msgs, np.broadcast_to(full, (10, F)), atol=1e-6)


def test_generator_matrix_cyclic_support():
    W, s = 7, 2
    B = codes.cyclic_generator_matrix(W, s, seed=0)
    for i in range(W):
        support = set((i + np.arange(s + 1)) % W)
        off = [j for j in range(W) if j not in support]
        assert np.allclose(B[i, off], 0.0)
        assert abs(B[i, i]) > 0  # diagonal always in the support
        assert np.isclose(np.linalg.norm(B[i]), 1.0)  # unit rows (conditioning)


def test_generator_matrix_no_stragglers_is_identity():
    assert np.array_equal(codes.cyclic_generator_matrix(5, 0), np.eye(5))


def test_decode_table_matches_online_solve():
    W, s = 6, 2
    B = codes.cyclic_generator_matrix(W, s, seed=2)
    table = codes.enumerate_decode_table(B, s)
    assert table.shape == (15, W)
    for k, stragglers in enumerate(itertools.combinations(range(W), s)):
        mask = np.ones(W, dtype=bool)
        mask[list(stragglers)] = False
        a = np.asarray(codes.mds_decode_weights(jnp.asarray(B), jnp.asarray(mask)))
        assert np.allclose(table[k], a, atol=1e-3)
        idx = codes.straggler_pattern_index(~mask)
        assert idx == k


# ---------------------------------------------------------------------------
# Layouts
# ---------------------------------------------------------------------------


def test_uncoded_layout():
    lay = codes.uncoded_layout(6)
    assert lay.n_partitions == 6
    assert np.array_equal(lay.assignment[:, 0], np.arange(6))
    assert lay.storage_overhead == 1.0
    E = lay.effective_matrix()
    assert np.array_equal(E, np.eye(6))


def test_frc_layout_groups_and_rotation():
    W, s = 6, 2
    lay = codes.frc_layout(W, s)
    assert lay.n_groups == 2
    # every member of a group holds exactly the group's s+1 partitions
    for w in range(W):
        a = w // (s + 1)
        assert set(lay.assignment[w]) == set(range((s + 1) * a, (s + 1) * (a + 1)))
    # rotation: member b starts at partition (s+1)a + b (reference
    # src/approximate_coding.py:47-50)
    assert lay.assignment[1, 0] == 1
    # any single member's message is the full group gradient
    E = lay.effective_matrix()
    for w in range(W):
        g_mask = np.zeros(W)
        a = w // (s + 1)
        g_mask[(s + 1) * a : (s + 1) * (a + 1)] = 1.0
        assert np.array_equal(E[w], g_mask)
    assert lay.storage_overhead == s + 1


def test_frc_layout_divisibility_guard():
    with pytest.raises(ValueError):
        codes.frc_layout(7, 2)


def test_frc_one_per_group_decodes_exactly():
    W, s, F = 6, 2, 4
    rng = np.random.default_rng(1)
    G = rng.standard_normal((W, F))
    lay = codes.frc_layout(W, s)
    E = lay.effective_matrix()
    msgs = E @ G
    # pick an arbitrary representative per group: sum of their messages is exact
    for reps in itertools.product(range(s + 1), repeat=W // (s + 1)):
        chosen = [g * (s + 1) + r for g, r in enumerate(reps)]
        assert np.allclose(msgs[chosen].sum(axis=0), G.sum(axis=0))


def test_partial_cyclic_layout():
    W, p, s = 4, 4, 1  # n_sep = 2
    lay = codes.partial_cyclic_layout(W, p, s, seed=0)
    n_sep = p - s - 1
    assert lay.n_partitions == n_sep * W + W
    # separate slots are globally unique and cover partitions 0..n_sep*W-1
    sep = lay.assignment[:, : n_sep].reshape(-1)
    assert sorted(sep.tolist()) == list(range(n_sep * W))
    assert not lay.slot_is_coded[:n_sep].any()
    assert lay.slot_is_coded[n_sep:].all()
    # coded band: worker w holds band partitions (w..w+s) mod W
    band = lay.assignment[:, n_sep:] - n_sep * W
    for w in range(W):
        assert set(band[w]) == set((w + np.arange(s + 1)) % W)
    # coded slots carry the generator-matrix coefficients
    for w in range(W):
        for j in range(s + 1):
            assert lay.coeffs[w, n_sep + j] == lay.B[w, (w + j) % W]
    # decode: all separate + MDS-decoded band == full gradient
    rng = np.random.default_rng(2)
    G = rng.standard_normal((lay.n_partitions, 3))
    E = lay.effective_matrix()  # coded slots only
    band_msgs = E @ G
    mask = np.ones(W, dtype=bool)
    mask[2] = False
    a = np.asarray(codes.mds_decode_weights(jnp.asarray(lay.B), jnp.asarray(mask)))
    decoded = G[: n_sep * W].sum(axis=0) + a @ band_msgs
    assert np.allclose(decoded, G.sum(axis=0), atol=1e-4)


def test_partial_frc_layout():
    W, p, s = 6, 4, 1  # n_sep = 2, 3 groups
    lay = codes.partial_frc_layout(W, p, s)
    n_sep = p - s - 1
    assert lay.n_partitions == n_sep * W + W
    # band: all members of group a hold the same partitions, in the same order
    # (reference src/partial_replication.py:44-50)
    band = lay.assignment[:, n_sep:]
    for a in range(W // (s + 1)):
        members = [w for w in range(W) if lay.groups[w] == a]
        for m in members[1:]:
            assert np.array_equal(band[m], band[members[0]])
    # one coded message per group + all separate slots == full gradient
    rng = np.random.default_rng(3)
    G = rng.standard_normal((lay.n_partitions, 2))
    E = lay.effective_matrix()
    msgs = E @ G
    reps = [0, 3, 5]  # one member of each group
    decoded = G[: n_sep * W].sum(axis=0) + msgs[reps].sum(axis=0)
    assert np.allclose(decoded, G.sum(axis=0))


# ---------------------------------------------------------------------------
# partition_weights (deduped-mode correctness)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "make",
    [
        lambda: codes.uncoded_layout(6),
        lambda: codes.cyclic_mds_layout(6, 2, seed=0),
        lambda: codes.frc_layout(6, 2),
    ],
)
def test_partition_weights_equal_message_decode(make):
    lay = make()
    W, S = lay.assignment.shape
    rng = np.random.default_rng(4)
    G = rng.standard_normal((lay.n_partitions, 3))
    slot_w = rng.standard_normal((2, W, S))  # FINAL weights, 2 "rounds"
    # message-space decode per round
    decoded = np.zeros((2, 3))
    for r in range(2):
        for w in range(W):
            for s_ in range(S):
                decoded[r] += slot_w[r, w, s_] * G[lay.assignment[w, s_]]
    # partition-space decode (batched host fold)
    pw = lay.fold_slot_weights(slot_w)
    assert pw.shape == (2, lay.n_partitions)
    assert np.allclose(pw @ G, decoded, atol=1e-10)
