"""Packaging smoke tests (VERDICT r5 Missing #5): the project must be
installable with `pip install -e .` and expose the `erasurehead-tpu`
console entry point — the first step MIGRATION.md asks a reference user to
take. The editable install runs offline (--no-deps --no-build-isolation;
every dependency is already in the image) into a throwaway --prefix so the
test never mutates the environment's site-packages."""

import os
import subprocess
import sys
import sysconfig

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_pyproject():
    try:
        import tomllib  # py >= 3.11
    except ModuleNotFoundError:
        tomllib = pytest.importorskip("tomli")
    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        return tomllib.load(f)


def test_pyproject_metadata():
    meta = _load_pyproject()
    proj = meta["project"]
    assert proj["name"] == "erasurehead-tpu"
    # the console entry point the README/MIGRATION Install sections promise
    assert proj["scripts"]["erasurehead-tpu"] == "erasurehead_tpu.cli:main"
    deps = " ".join(proj["dependencies"])
    # the reference's pre_run.sh role: the runtime deps are declared
    for pkg in ("jax", "numpy", "scipy", "scikit-learn", "orbax"):
        assert pkg in deps, f"{pkg} missing from dependencies"


def test_console_entry_resolves():
    """The entry-point target must exist and be callable before any pip
    machinery runs — a typo'd `module:attr` would otherwise only surface
    at install time."""
    from erasurehead_tpu import cli

    assert callable(cli.main)


def test_pip_editable_install_smoke(tmp_path):
    """`pip install -e .` into a scratch prefix: metadata parses, the
    build backend accepts the project, and the installed console script +
    package import from OUTSIDE the repo root (the failure mode the
    packaging fixes: the CLI used to run only from the checkout cwd via
    implicit path)."""
    prefix = tmp_path / "prefix"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU tunnel from pip's children
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable, "-m", "pip", "install", "-e", REPO,
            "--no-deps", "--no-build-isolation", "--quiet",
            "--prefix", str(prefix), "--no-warn-script-location",
        ],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]

    # the console script landed in <prefix>/bin
    script = prefix / "bin" / "erasurehead-tpu"
    assert script.exists(), list((prefix / "bin").iterdir())

    # the editable hook resolves the package from a NEUTRAL cwd (purelib
    # holds the __editable__ .pth/finder pointing back at the checkout;
    # .pth processing needs a SITE dir, not a PYTHONPATH entry)
    purelib = sysconfig.get_paths(vars={"base": str(prefix)})["purelib"]
    probe = subprocess.run(
        [
            sys.executable, "-c",
            f"import site; site.addsitedir({str(purelib)!r}); "
            "import erasurehead_tpu, erasurehead_tpu.cli; "
            "print(erasurehead_tpu.cli.main is not None)",
        ],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=str(tmp_path),
    )
    assert probe.returncode == 0, probe.stderr[-2000:]
    assert probe.stdout.strip().endswith("True")
