"""On-device dynamic collection (parallel/dynamic.py).

Every jnp rule is pinned against parallel/collect.py's numpy event replay
on the same arrival matrices, then the fully on-device training scan is
exercised end-to-end on the mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from erasurehead_tpu.ops import codes
from erasurehead_tpu.parallel import collect, dynamic, straggler
from erasurehead_tpu.utils.config import RunConfig, Scheme

R, W, S = 8, 12, 2


@pytest.fixture(scope="module")
def arrivals():
    return straggler.arrival_schedule(R, W, add_delay=True)


def _per_round(rule, t):
    outs = [rule(jnp.asarray(t[r], jnp.float32)) for r in range(R)]
    return (
        np.stack([np.asarray(o.message_weights) for o in outs]),
        np.array([float(o.sim_time) for o in outs]),
        np.stack([np.asarray(o.collected) for o in outs]),
    )


def test_all_matches_host(arrivals):
    w, sim, col = _per_round(dynamic.collect_all_jnp, arrivals)
    ref = collect.collect_all(arrivals)
    np.testing.assert_allclose(w, ref.message_weights)
    np.testing.assert_allclose(sim, ref.sim_time, rtol=1e-6)
    np.testing.assert_array_equal(col, ref.collected)


def test_avoidstragg_matches_host(arrivals):
    w, sim, col = _per_round(
        lambda t: dynamic.collect_avoidstragg_jnp(t, S), arrivals
    )
    ref = collect.collect_avoidstragg(arrivals, S)
    np.testing.assert_allclose(w, ref.message_weights, rtol=1e-6)
    np.testing.assert_allclose(sim, ref.sim_time, rtol=1e-6)
    np.testing.assert_array_equal(col, ref.collected)


def test_frc_matches_host(arrivals):
    layout = codes.frc_layout(W, S)
    onehot = jnp.asarray(dynamic._group_onehot(np.asarray(layout.groups)))
    w, sim, col = _per_round(
        lambda t: dynamic.collect_frc_jnp(t, onehot), arrivals
    )
    ref = collect.collect_frc(arrivals, layout.groups)
    np.testing.assert_allclose(w, ref.message_weights)
    np.testing.assert_allclose(sim, ref.sim_time, rtol=1e-6)
    np.testing.assert_array_equal(col, ref.collected)


@pytest.mark.parametrize("num_collect", [4, 7, 10])
def test_agc_matches_host(arrivals, num_collect):
    layout = codes.frc_layout(W, S)
    onehot = jnp.asarray(dynamic._group_onehot(np.asarray(layout.groups)))
    w, sim, col = _per_round(
        lambda t: dynamic.collect_agc_jnp(t, onehot, num_collect), arrivals
    )
    ref = collect.collect_agc(arrivals, layout.groups, num_collect)
    np.testing.assert_allclose(w, ref.message_weights)
    np.testing.assert_allclose(sim, ref.sim_time, rtol=1e-6)
    np.testing.assert_array_equal(col, ref.collected)


def test_mds_decode_exactness(arrivals):
    """On-device fp32 decode must reconstruct the all-ones vector on the
    collected support (small W keeps fp32 conditioning safe — see
    ops/codes.mds_decode_weights docstring)."""
    layout = codes.cyclic_mds_layout(W, S, seed=0)
    rule = lambda t: dynamic.collect_first_k_mds_jnp(
        t, jnp.asarray(layout.B, jnp.float32), S
    )
    w, sim, col = _per_round(rule, arrivals)
    ref = collect.collect_first_k_mds(arrivals, layout.B, S)
    np.testing.assert_array_equal(col, ref.collected)
    np.testing.assert_allclose(sim, ref.sim_time, rtol=1e-6)
    recon = w @ layout.B
    np.testing.assert_allclose(recon, np.ones((R, W)), atol=5e-3)


class TestDecodeTableW30:
    """The f64-precomputed decode table at the reference's canonical W=30
    (VERDICT r2 item 4): the on-device fp32 solve fails outright on
    ill-conditioned straggler patterns at this scale; the table gather must
    match the host float64 control plane."""

    W30 = 30

    @pytest.mark.parametrize("s", [2, 3])
    def test_pattern_ranking_matches_host(self, s):
        table = codes.build_decode_table(np.eye(self.W30), s)
        rng = np.random.default_rng(s)
        for _ in range(25):
            k = rng.integers(0, s + 1)
            stragglers = np.zeros(self.W30, bool)
            stragglers[rng.choice(self.W30, size=k, replace=False)] = True
            got = int(
                codes.straggler_pattern_index_jnp(
                    jnp.asarray(stragglers), s, table.comb
                )
            )
            assert got == codes.straggler_pattern_index(stragglers)

    @pytest.mark.parametrize("s", [2, 3])
    def test_table_lookup_matches_host_f64(self, s):
        layout = codes.cyclic_mds_layout(self.W30, s, seed=0)
        table = codes.build_decode_table(layout.B, s)
        assert table is not None
        rng = np.random.default_rng(7 + s)
        masks = np.ones((40, self.W30), bool)
        for r in range(40):
            k = rng.integers(0, s + 1)  # up to s stragglers (partial sets)
            masks[r, rng.choice(self.W30, size=k, replace=False)] = False
        want = codes.mds_decode_weights_host(layout.B, masks)
        got = np.stack(
            [np.asarray(table.lookup(jnp.asarray(m))) for m in masks]
        )
        np.testing.assert_allclose(got, want.astype(np.float32), rtol=2e-4,
                                   atol=1e-4)
        # and the reconstruction is exact where fp32 pinv measured ~1.0 off
        np.testing.assert_allclose(got @ layout.B, np.ones((40, self.W30)),
                                   atol=5e-3)

    def test_exact_only_fits_cap_where_full_range_does_not(self):
        """First-k schemes index only the exactly-s block; building just
        that block keeps e.g. randreg W=27, s=4 (C(27,4)=17,550 <= cap,
        0..4 sum 20,854 > cap) on the f64 table instead of the fp32
        fallback."""
        W, s = 27, 4
        layout = codes.cyclic_mds_layout(W, s, seed=0)
        assert codes.build_decode_table(layout.B, s) is None
        table = codes.build_decode_table(layout.B, s, exact_only=True)
        assert table is not None
        rng = np.random.default_rng(0)
        mask = np.ones(W, bool)
        mask[rng.choice(W, size=s, replace=False)] = False
        got = np.asarray(table.lookup(jnp.asarray(mask)))
        want = codes.mds_decode_weights_host(layout.B, mask[None])[0]
        np.testing.assert_allclose(got, want.astype(np.float32),
                                   rtol=2e-4, atol=1e-4)

    def test_mds_rule_uses_table_at_w30(self):
        s = 3
        layout = codes.cyclic_mds_layout(self.W30, s, seed=0)
        table = codes.build_decode_table(layout.B, s)
        arrivals = straggler.arrival_schedule(R, self.W30, add_delay=True)
        rule = lambda t: dynamic.collect_first_k_mds_jnp(
            t, jnp.asarray(layout.B, jnp.float32), s, decode_table=table
        )
        w, sim, col = _per_round(rule, arrivals)
        ref = collect.collect_first_k_mds(arrivals, layout.B, s)
        np.testing.assert_array_equal(col, ref.collected)
        np.testing.assert_allclose(sim, ref.sim_time, rtol=1e-6)
        np.testing.assert_allclose(
            w, ref.message_weights.astype(np.float32), rtol=2e-4, atol=1e-4
        )

    def test_train_dynamic_cyccoded_w30_converges(self):
        """End-to-end at canonical scale: before the table, the fp32 decode
        corrupted exactly this configuration."""
        from erasurehead_tpu.data.synthetic import generate_gmm
        from erasurehead_tpu.models.glm import LogisticModel
        from erasurehead_tpu.parallel.mesh import worker_mesh
        from erasurehead_tpu.train import trainer

        W30 = self.W30
        cfg = RunConfig(
            scheme="cyccoded", n_workers=W30, n_stragglers=3, rounds=10,
            n_rows=16 * W30, n_cols=16, lr_schedule=1.0, update_rule="AGD",
            add_delay=True, seed=0,
        )
        data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=W30, seed=0)
        res = trainer.train_dynamic(cfg, data, mesh=worker_mesh(2))
        hist = np.asarray(res.params_history)
        assert np.isfinite(hist).all()
        model = LogisticModel()
        Xt, yt = jnp.asarray(data.X_test), jnp.asarray(data.y_test)
        first = float(model.loss_mean(jnp.asarray(hist[0]), Xt, yt))
        last = float(model.loss_mean(jnp.asarray(hist[-1]), Xt, yt))
        assert last < first * 0.8, (first, last)


def test_train_dynamic_flat_lowering_matches_per_slot():
    """cfg.flat_grad='on' routes train_dynamic through
    step.make_flat_grad_fn (per-round traced weights fold into the
    residual) — trajectory allclose to the per-slot lowering."""
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer

    data = generate_gmm(16 * W, 12, n_partitions=W, seed=0)
    hists = {}
    for flat in ("off", "on"):
        cfg = RunConfig(
            scheme="approx", n_workers=W, n_stragglers=2, num_collect=8,
            rounds=8, n_rows=16 * W, n_cols=12, lr_schedule=0.5,
            update_rule="AGD", add_delay=True, seed=0, flat_grad=flat,
        )
        res = trainer.train_dynamic(cfg, data, mesh=worker_mesh(4))
        hists[flat] = np.asarray(res.params_history, np.float32)
    np.testing.assert_allclose(hists["on"], hists["off"], rtol=2e-4, atol=2e-5)


def test_train_dynamic_margin_flat_matches_per_slot():
    """cfg.margin_flat='on' routes train_dynamic through the hybrid dense
    margin lowering (step.make_margin_flat_grad_fn) — trajectory allclose
    to the per-slot lowering. Before round 4 the knob was silently ignored
    here (ADVICE r3)."""
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer

    data = generate_gmm(16 * W, 12, n_partitions=W, seed=0)
    hists = {}
    for margin in ("off", "on"):
        cfg = RunConfig(
            scheme="approx", n_workers=W, n_stragglers=2, num_collect=8,
            rounds=8, n_rows=16 * W, n_cols=12, lr_schedule=0.5,
            update_rule="AGD", add_delay=True, seed=0, margin_flat=margin,
        )
        res = trainer.train_dynamic(cfg, data, mesh=worker_mesh(4))
        hists[margin] = np.asarray(res.params_history, np.float32)
    np.testing.assert_allclose(hists["on"], hists["off"], rtol=2e-4, atol=2e-5)


def test_train_dynamic_split_restart_matches_unsplit():
    """The restart contract (initial_state/initial_round): splitting a
    dynamic run at any round and resuming from the carried state must
    reproduce the unsplit trajectory EXACTLY — per-round randomness is
    fold_in(key, absolute_round) and lr is absolutely indexed, so the
    resumed scan replays the identical per-round programs."""
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer

    R, SPLIT = 10, 4
    data = generate_gmm(16 * W, 12, n_partitions=W, seed=0)

    def cfg(rounds, lr):
        return RunConfig(
            scheme="approx", n_workers=W, n_stragglers=2, num_collect=8,
            rounds=rounds, n_rows=16 * W, n_cols=12, lr_schedule=lr,
            update_rule="AGD", add_delay=True, seed=0,
        )

    mesh = worker_mesh(4)
    full = trainer.train_dynamic(cfg(R, 0.5), data, mesh=mesh)
    lr_full = cfg(R, 0.5).resolve_lr_schedule()
    p1 = trainer.train_dynamic(
        cfg(SPLIT, lr_full[:SPLIT]), data, mesh=mesh
    )
    p2 = trainer.train_dynamic(
        cfg(R, lr_full), data, mesh=mesh,
        initial_state=p1.final_state, initial_round=SPLIT,
    )
    np.testing.assert_array_equal(
        np.asarray(p2.params_history),
        np.asarray(full.params_history)[SPLIT:],
    )
    # padded telemetry: donor rows carry the sentinels, live rows match
    assert (p2.worker_times[:SPLIT] == -1.0).all()
    assert (p2.timeset[:SPLIT] == 0.0).all()
    np.testing.assert_allclose(
        p2.timeset[SPLIT:], full.timeset[SPLIT:], rtol=1e-6
    )
    assert p2.start_round == SPLIT


def test_train_dynamic_initial_round_without_state_rejected():
    """A bare initial_round (no donor state) must fail loudly instead of
    silently running the full horizon from round 0 (ADVICE r4)."""
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer

    data = generate_gmm(16 * W, 12, n_partitions=W, seed=0)
    cfg = RunConfig(
        scheme="approx", n_workers=W, n_stragglers=2, num_collect=8,
        rounds=6, n_rows=16 * W, n_cols=12, lr_schedule=0.5, seed=0,
    )
    mesh = worker_mesh(4)
    with pytest.raises(ValueError, match="requires initial_state"):
        trainer.train_dynamic(cfg, data, mesh=mesh, initial_round=3)
    with pytest.raises(ValueError, match="requires initial_state"):
        trainer.train(cfg, data, mesh=mesh, initial_round=3)


def test_ranks_tie_break_matches_order():
    t = jnp.asarray([0.0, 0.0, 1.0, 0.0])
    ranks = np.asarray(dynamic._ranks(t))
    assert ranks.tolist() == [0, 1, 3, 2]  # index order among ties


def test_partial_frc_matches_host(arrivals):
    layout = codes.partial_frc_layout(W, S + 2, S)
    frac = layout.uncoded_frac
    onehot = jnp.asarray(dynamic._group_onehot(np.asarray(layout.groups)))
    gids = jnp.asarray(np.asarray(layout.groups))
    w, sim, col = _per_round(
        lambda t: dynamic.collect_partial_jnp(
            t, variant="frc", frac=frac, onehot=onehot, group_ids=gids
        ),
        arrivals,
    )
    ref = collect.collect_partial(arrivals, layout, "frc")
    np.testing.assert_allclose(w, ref.message_weights)
    np.testing.assert_allclose(sim, ref.sim_time, rtol=1e-6)
    np.testing.assert_array_equal(col, ref.collected)


def test_partial_mds_matches_host(arrivals):
    """Collection/stop/completion must match the host replay exactly; the
    decode weights go through the on-device fp32 solve, so they are checked
    by reconstruction quality instead of bitwise equality."""
    layout = codes.partial_cyclic_layout(W, S + 2, S, seed=0)
    frac = layout.uncoded_frac
    rule = lambda t: dynamic.collect_partial_jnp(
        t, variant="mds", frac=frac, n_stragglers=layout.n_stragglers,
        B=jnp.asarray(layout.B, jnp.float32),
    )
    w, sim, col = _per_round(rule, arrivals)
    ref = collect.collect_partial(arrivals, layout, "mds")
    np.testing.assert_array_equal(col, ref.collected)
    np.testing.assert_allclose(sim, ref.sim_time, rtol=1e-6)
    recon = w @ layout.B
    np.testing.assert_allclose(recon, np.ones((R, W)), atol=5e-3)


def test_partial_mds_with_decode_table_matches_pinv_path(arrivals):
    """The partial scheme's completed sets have <= s stragglers (not
    exactly s); the 0..s multi-pattern table must agree with the on-device
    solve at small W and reconstruct all-ones on every round."""
    layout = codes.partial_cyclic_layout(W, S + 2, S, seed=0)
    table = codes.build_decode_table(np.asarray(layout.B), S)
    rule = lambda t: dynamic.collect_partial_jnp(
        t, variant="mds", frac=layout.uncoded_frac,
        n_stragglers=layout.n_stragglers,
        B=jnp.asarray(layout.B, jnp.float32), decode_table=table,
    )
    w, sim, col = _per_round(rule, arrivals)
    ref = collect.collect_partial(arrivals, layout, "mds")
    np.testing.assert_array_equal(col, ref.collected)
    np.testing.assert_allclose(sim, ref.sim_time, rtol=1e-6)
    # the table IS the f64 host solve: pin the weights tightly (the recon
    # check alone would accept any nearby-but-wrong table row)
    np.testing.assert_allclose(
        w, ref.message_weights.astype(np.float32), rtol=2e-4, atol=1e-4
    )
    np.testing.assert_allclose(w @ layout.B, np.ones((R, W)), atol=5e-3)


@pytest.mark.parametrize("scheme,kw", [
    ("approx", dict(num_collect=8)),
    ("cyccoded", {}),
    ("naive", {}),
    ("deadline", dict(deadline=1.5)),
    ("partialrepcoded", dict(partitions_per_worker=S + 2)),
    ("partialcyccoded", dict(partitions_per_worker=S + 2)),
])
def test_train_dynamic_end_to_end(scheme, kw):
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.models.glm import LogisticModel
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer

    cfg = RunConfig(
        scheme=scheme, n_workers=W, n_stragglers=S, rounds=10,
        n_rows=16 * W, n_cols=16, lr_schedule=1.0, update_rule="AGD",
        add_delay=True, seed=0, **kw,
    )
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=W, seed=0)
    res = trainer.train_dynamic(cfg, data, mesh=worker_mesh(4))
    hist = np.asarray(res.params_history)
    assert hist.shape == (10, 16) and np.isfinite(hist).all()
    assert res.timeset.shape == (10,) and (res.timeset > 0).all()
    assert res.worker_times.shape == (10, W)
    model = LogisticModel()
    Xt, yt = jnp.asarray(data.X_test), jnp.asarray(data.y_test)
    first = float(model.loss_mean(jnp.asarray(hist[0]), Xt, yt))
    last = float(model.loss_mean(jnp.asarray(hist[-1]), Xt, yt))
    assert last < first * 0.8


def test_deadline_rule_matches_host_control_plane():
    """collect_deadline_jnp pinned per-round against collect_deadline."""
    rng = np.random.default_rng(3)
    arrivals = rng.exponential(0.5, size=(R, W))
    arrivals[2] += 10.0  # a round where nobody makes the cutoff
    rule = lambda t: dynamic.collect_deadline_jnp(t, 1.0)
    w, sim, col = _per_round(rule, arrivals)
    ref = collect.collect_deadline(arrivals, 1.0)
    np.testing.assert_array_equal(col, ref.collected)
    np.testing.assert_allclose(sim, ref.sim_time, rtol=1e-6)
    np.testing.assert_allclose(w, ref.message_weights, rtol=1e-6)


def test_train_dynamic_autodiff_model_multidevice():
    """The fully on-device trainer with a jax.grad (pytree-params) model on
    a multi-device mesh — the combination the per-slot-grad-under-vmap bug
    silently corrupted before step._weighted_loss_grad. Dynamic and host
    control planes share the grad path, so the MLP trajectory must track
    the host trainer's loss behavior (both converge on the same data)."""
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.models.mlp import MLPModel
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer

    cfg = RunConfig(
        scheme="approx", model="mlp", n_workers=W, n_stragglers=S,
        num_collect=8, rounds=12, n_rows=16 * W, n_cols=16,
        lr_schedule=1.0, update_rule="GD", add_delay=True, seed=0,
    )
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=W, seed=0)
    res = trainer.train_dynamic(cfg, data, mesh=worker_mesh(4))
    model = MLPModel()
    Xt, yt = jnp.asarray(data.X_test), jnp.asarray(data.y_test)
    leaves = jax.tree.leaves(res.params_history)
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
    first = jax.tree.map(lambda l: l[0], res.params_history)
    last = jax.tree.map(lambda l: l[-1], res.params_history)
    l0 = float(model.loss_mean(first, Xt, yt))
    l1 = float(model.loss_mean(last, Xt, yt))
    assert l1 < l0 * 0.9, (l0, l1)
