"""Fused pallas gradient kernel (ops/kernels.py) vs the XLA oracle.

Interpret mode on CPU; the same kernel compiles via Mosaic on TPU. The
trainer-level test pins a full coded run with use_pallas="on" to the
default XLA path — gradient fusion must not change the science.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from erasurehead_tpu.ops import kernels

rng = np.random.default_rng(7)


def _case(M, R, F):
    X = jnp.asarray(rng.standard_normal((M, R, F)), jnp.float32)
    y = jnp.asarray(np.sign(rng.standard_normal((M, R))), jnp.float32)
    b = jnp.asarray(rng.standard_normal(F), jnp.float32)
    w = jnp.asarray(rng.standard_normal(M), jnp.float32)
    return b, X, y, w


@pytest.mark.parametrize("kind", kernels.GLM_KINDS)
@pytest.mark.parametrize(
    "shape",
    [(6, 40, 32), (3, 17, 128), (1, 8, 64)],  # incl. rows % block != 0
)
def test_fused_matches_oracle(kind, shape):
    b, X, y, w = _case(*shape)
    got = kernels.fused_glm_grad(
        b, X, y, w, kind, interpret=True, block_rows=16
    )
    want = kernels.reference_glm_grad(b, X, y, w, kind)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


@pytest.mark.parametrize("kind", kernels.GLM_KINDS)
def test_fused_bf16_stream_matches_f32_oracle(kind):
    """bf16-stored stacks stream at half the HBM bytes but the kernel
    upcasts each block once and contracts in exact f32 — so the result
    must match the f32 oracle on the bf16-rounded data exactly (to f32
    reduction tolerance), not to bf16 tolerance."""
    b, X, y, w = _case(4, 33, 64)
    Xb = X.astype(jnp.bfloat16)
    got = kernels.fused_glm_grad(
        b, Xb, y, w, kind, interpret=True, block_rows=16
    )
    want = kernels.reference_glm_grad(
        b, Xb.astype(jnp.float32), y, w, kind
    )
    assert got.dtype == jnp.float32
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_zero_weight_slots_drop_out():
    """A slot with weight 0 (an erased/uncollected message) contributes
    nothing — the erasure semantics the decode weights encode."""
    b, X, y, w = _case(4, 24, 32)
    w = w.at[2].set(0.0)
    got = kernels.fused_glm_grad(b, X, y, w, "logistic", interpret=True)
    want = kernels.reference_glm_grad(
        b, X[jnp.array([0, 1, 3])], y[jnp.array([0, 1, 3])],
        w[jnp.array([0, 1, 3])], "logistic",
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_choose_block_rows_bounds():
    assert kernels.choose_block_rows(4400, 128) % 8 == 0
    assert kernels.choose_block_rows(5, 128) == 8  # padded-up tiny R
    big = kernels.choose_block_rows(10_000, 32_768)
    assert big >= 8 and big * 32_768 * 4 <= 2 * kernels._X_BLOCK_BYTES
    # bf16 stacks need 16-row tile alignment (sublane=16): every shape,
    # including non-multiples, must come back 16-aligned
    for R, F in ((4400, 128), (40, 64), (17, 128), (5, 128)):
        assert kernels.choose_block_rows(R, F, sublane=16) % 16 == 0, (R, F)


def test_fused_bf16_auto_block_selection():
    """The bf16 auto path (no explicit block_rows) must pick a 16-aligned
    block and still match the f32 oracle — guards the Mosaic-retiling
    hazard the sublane parameter exists to avoid."""
    b, X, y, w = _case(3, 40, 64)  # R=40: 8-aligned but NOT 16-aligned
    Xb = X.astype(jnp.bfloat16)
    got = kernels.fused_glm_grad(b, Xb, y, w, "logistic", interpret=True)
    want = kernels.reference_glm_grad(
        b, Xb.astype(jnp.float32), y, w, "logistic"
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_supports_fused_gating(tmp_path, monkeypatch):
    from erasurehead_tpu import tune as tune_lib

    # isolate the tune decision cache: since ISSUE 19 the ideal-case
    # verdict below is re-raceable, and a developer's cached glm_fused win
    # must not flip this test
    monkeypatch.setenv(tune_lib.ENV_PATH, str(tmp_path / "tune.json"))
    tune_lib.reset()
    X = jnp.zeros((2, 8, 128), jnp.float32)
    from erasurehead_tpu.ops.features import PaddedRows

    sparse = PaddedRows(
        jnp.zeros((4, 2), jnp.int32), jnp.zeros((4, 2), jnp.float32), 128
    )
    assert not kernels.supports_fused(X, "mlp", "tpu")
    assert not kernels.supports_fused(sparse, "logistic", "tpu")
    assert not kernels.supports_fused(X, "logistic", "cpu")
    # the hardcoded race verdict: XLA won on v5e (docstring numbers), so
    # absent a cached tune win "auto" declines even the ideal dense GLM
    # TPU case — and the decline names its reason (never silent)
    verdict = kernels.supports_fused(X, "logistic", "tpu")
    assert not verdict and "race" in verdict.reason
    # a cached glm_fused race win at THIS shape flips the gate via data
    tune_lib.get_cache().record(
        tune_lib.default_device_kind(), "glm_fused",
        tune_lib.glm_fused_signature(X.shape, str(X.dtype), "logistic"),
        "pallas",
    )
    assert kernels.supports_fused(X, "logistic", "tpu")
    tune_lib.reset()


@pytest.mark.parametrize("scheme", ["approx", "cyccoded", "naive"])
@pytest.mark.parametrize("compute_mode", ["faithful", "deduped"])
def test_trainer_pallas_path_matches_xla(scheme, compute_mode):
    """Full coded training with the fused kernel == default XLA path."""
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    W = 8
    mesh = worker_mesh(4)
    data = generate_gmm(16 * W, 32, n_partitions=W, seed=0)
    histories = {}
    for use in ("off", "on"):
        cfg = RunConfig(
            scheme=scheme, n_workers=W, n_stragglers=1, rounds=4,
            n_rows=16 * W, n_cols=32, lr_schedule=1.0, update_rule="AGD",
            add_delay=True, seed=0, compute_mode=compute_mode,
            use_pallas=use,
        )
        res = trainer.train(cfg, data, mesh=mesh)
        histories[use] = np.asarray(res.params_history)
    np.testing.assert_allclose(
        histories["on"], histories["off"], rtol=2e-4, atol=1e-5
    )


def test_trainer_pallas_bf16_data_matches_xla():
    """use_pallas=on composed with dtype=bfloat16 (the half-traffic
    streaming combination the kernel's bf16 path exists for): the fused
    trajectory must track the XLA bf16 trajectory."""
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    W = 8
    mesh = worker_mesh(4)
    data = generate_gmm(16 * W, 32, n_partitions=W, seed=0)
    histories = {}
    for use in ("off", "on"):
        cfg = RunConfig(
            scheme="approx", n_workers=W, n_stragglers=1, num_collect=6,
            rounds=4, n_rows=16 * W, n_cols=32, lr_schedule=1.0,
            update_rule="AGD", add_delay=True, seed=0,
            dtype="bfloat16", use_pallas=use,
        )
        res = trainer.train(cfg, data, mesh=mesh)
        histories[use] = np.asarray(res.params_history, np.float32)
    assert np.isfinite(histories["on"]).all()
    # both paths stream bf16-rounded data; the kernel contracts in exact
    # f32 while XLA's bf16 MXU pass rounds intermediates -> bf16-level drift
    np.testing.assert_allclose(
        histories["on"], histories["off"], rtol=2e-2, atol=2e-3
    )


def test_trainer_pallas_on_rejects_mlp():
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    cfg = RunConfig(
        scheme="naive", model="mlp", n_workers=4, n_stragglers=0, rounds=1,
        n_rows=32, n_cols=16, lr_schedule=0.1, use_pallas="on",
    )
    data = generate_gmm(32, 16, n_partitions=4, seed=0)
    with pytest.raises(ValueError, match="use_pallas"):
        trainer.train(cfg, data, mesh=worker_mesh(4))


# ---------------------------------------------------------------------------
# fused blockwise decode (ISSUE 19): the per-leaf decode contraction


def _decode_case(M, D, dtype=jnp.float32):
    g = jnp.asarray(rng.standard_normal((M, D)), dtype)
    w = jnp.asarray(rng.standard_normal(M), jnp.float32)
    return w, g


def _einsum_decode(w, g):
    """The treewise table decode's contraction, per leaf (the oracle the
    fused path must match BITWISE, not to tolerance)."""
    return jnp.einsum(
        "m,md->d", w.astype(g.dtype), g,
        precision=jax.lax.Precision.HIGHEST,
    )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize(
    "shape",
    [(6, 200), (3, 128), (1, 7), (9, 515)],  # incl. D % 128 != 0
)
def test_fused_block_decode_bitwise_vs_einsum(dtype, shape):
    """Both lowerings of the fused decode — the XLA dot_general and the
    pallas interpret kernel — must equal the einsum decode bitwise: the
    decode moves values through one HIGHEST-precision contraction, and
    any reduction reorder would break the tier-1 trajectory pins."""
    w, g = _decode_case(*shape, dtype=dtype)
    want = np.asarray(_einsum_decode(w, g))
    xla = np.asarray(kernels.fused_block_decode(w, g))
    pal = np.asarray(
        kernels.fused_block_decode(w, g, use_pallas=True, interpret=True)
    )
    assert xla.dtype == want.dtype
    assert xla.tobytes() == want.tobytes()
    assert pal.tobytes() == want.tobytes()


def test_fused_block_decode_multiblock_grid_bitwise():
    """An explicit small column block forces a multi-step grid (with a
    padded tail block): accumulation across grid steps must still be
    bitwise against the single-dot oracle."""
    w, g = _decode_case(5, 300)
    want = np.asarray(_einsum_decode(w, g))
    got = np.asarray(
        kernels.fused_block_decode(
            w, g, use_pallas=True, interpret=True, block_cols=128
        )
    )
    assert got.tobytes() == want.tobytes()


def test_fused_block_decode_zero_weight_slots_drop_out():
    w, g = _decode_case(4, 64)
    w = w.at[1].set(0.0)
    keep = jnp.array([0, 2, 3])
    want = np.asarray(_einsum_decode(w[keep], g[keep]))
    got = np.asarray(kernels.fused_block_decode(w, g))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_choose_block_cols_bounds():
    assert kernels.choose_block_cols(90, 4400) % 128 == 0
    assert kernels.choose_block_cols(6, 40) == 128  # padded-up tiny D
    big = kernels.choose_block_cols(4, 1 << 20)
    assert big >= 128 and big * 4 * 4 <= 2 * kernels._X_BLOCK_BYTES
    # a padded-up D never exceeds what the block needs
    assert kernels.choose_block_cols(8, 130) == 256
