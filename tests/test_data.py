"""Data-layer tests: real-dataset pipelines (on synthetic raw fixtures),
reference on-disk layout round-trip, prepare CLI, and sparse end-to-end
training from a prepared directory."""

import dataclasses
import os

import numpy as np
import pandas as pd
import pytest
import scipy.sparse as sps

from erasurehead_tpu.data import io as data_io
from erasurehead_tpu.data import prepare, real
from erasurehead_tpu.data.synthetic import generate_gmm, generate_onehot
from erasurehead_tpu.train import evaluate, trainer
from erasurehead_tpu.utils.config import RunConfig


# ---------------------------------------------------------------------------
# raw fixtures mimicking each dataset's schema
# ---------------------------------------------------------------------------


@pytest.fixture
def amazon_raw(tmp_path):
    rng = np.random.default_rng(0)
    n = 400
    cols = {"ACTION": rng.integers(0, 2, n)}
    names = [
        "RESOURCE", "MGR_ID", "ROLE_ROLLUP_1", "ROLE_ROLLUP_2",
        "ROLE_DEPTNAME", "ROLE_TITLE", "ROLE_FAMILY_DESC", "ROLE_FAMILY",
        "ROLE_CODE",
    ]
    for name in names:
        cols[name] = rng.integers(1000, 1020, n)
    pd.DataFrame(cols).to_csv(tmp_path / "train.csv", index=False)
    return str(tmp_path)


@pytest.fixture
def kc_house_raw(tmp_path):
    rng = np.random.default_rng(1)
    n = 300
    df = pd.DataFrame(
        {
            "id": np.arange(n),
            "date": ["20141013T000000"] * n,
            "price": rng.uniform(1e5, 2e6, n),
            "bedrooms": rng.integers(1, 6, n),
            "bathrooms": rng.integers(1, 4, n),
            "sqft_living": rng.integers(500, 5000, n) // 100,
            "floors": rng.integers(1, 3, n),
        }
    )
    df.to_csv(tmp_path / "kc_house_data.csv", index=False)
    return str(tmp_path)


@pytest.fixture
def dna_raw(tmp_path):
    rng = np.random.default_rng(2)
    n = 300
    data = np.column_stack(
        [rng.integers(0, 2, n) * 2 - 1, rng.integers(0, 4, (n, 6))]
    )
    np.savetxt(tmp_path / "features.csv", data, delimiter=",", fmt="%d")
    return str(tmp_path)


# ---------------------------------------------------------------------------


def test_amazon_pipeline(amazon_raw):
    ds = real.prepare("amazon", amazon_raw)
    assert sps.issparse(ds.X_train)
    assert ds.X_train.shape[0] == 320 and ds.X_test.shape[0] == 80
    assert set(np.unique(ds.y_train)) <= {-1.0, 1.0}
    # 9 base + C(9,2)-2 interactions + bias = 44 one-hot groups; every row
    # has exactly 44 nonzeros (one-hot per original column)
    assert (np.diff(ds.X_train.tocsr().indptr) == 44).all()
    # deterministic: same raw -> identical matrices
    ds2 = real.prepare("amazon", amazon_raw)
    assert (ds.X_train != ds2.X_train).nnz == 0
    assert np.array_equal(ds.y_train, ds2.y_train)


def test_breast_cancer_pipeline_real_data():
    """The one preparer that runs on genuinely REAL data with no network:
    sklearn's bundled UCI breast-cancer set through the covtype-style flow
    (VERDICT r2 item 5). Real continuous columns have hundreds of distinct
    values, so the one-hot blowup is the real-cardinality regime the
    synthetic fixtures cannot produce."""
    ds = real.prepare("breast_cancer", None)
    assert sps.issparse(ds.X_train)
    assert ds.X_train.shape[0] == 455 and ds.X_test.shape[0] == 114
    assert set(np.unique(ds.y_train)) <= {-1.0, 1.0}
    # 30 real features + bias, one-hot per column: exactly 31 nnz per row
    assert (np.diff(ds.X_train.tocsr().indptr) == 31).all()
    # real cardinalities: far more one-hot columns than the 31 raw ones
    assert ds.X_train.shape[1] > 5000
    ds2 = real.prepare("breast_cancer", None)
    assert (ds.X_train != ds2.X_train).nnz == 0


def test_diabetes_pipeline_real_data():
    """Real regression data with no network: sklearn's bundled UCI
    diabetes set through the kc_house-style flow — the linear family's
    real-data counterpart to breast_cancer."""
    ds = real.prepare("diabetes", None)
    assert sps.issparse(ds.X_train)
    assert ds.X_train.shape[0] == 353 and ds.X_test.shape[0] == 89
    # continuous regression target, O(1) scaled
    assert ds.y_train.dtype == np.float64
    assert 0 < np.abs(ds.y_train).mean() < 10
    # 10 real features + bias, one-hot per column: exactly 11 nnz per row
    assert (np.diff(ds.X_train.tocsr().indptr) == 11).all()
    ds2 = real.prepare("diabetes", None)
    assert (ds.X_train != ds2.X_train).nnz == 0


def test_amazon_interaction_exclusions():
    X = np.arange(18).reshape(2, 9)
    feats = real.hashed_interactions(X, degree=2)
    assert feats.shape == (2, 36 - 2)  # C(9,2) minus the two excluded pairs


def test_kc_house_pipeline(kc_house_raw):
    ds = real.prepare("kc_house_data", kc_house_raw)
    assert ds.name == "kc_house_data"
    assert ds.y_train.max() <= 2.0  # price scaled by 1e6
    assert sps.issparse(ds.X_train)


def test_dna_pipeline(dna_raw):
    ds = real.prepare("dna", dna_raw)
    assert ds.X_train.shape[0] == 240
    assert set(np.unique(ds.y_train)) <= {-1.0, 1.0}


def test_unknown_dataset_raises():
    with pytest.raises(ValueError):
        real.prepare("mnist", "/tmp")


def test_missing_source_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        real.prepare("amazon", str(tmp_path))


# ---------------------------------------------------------------------------
# on-disk layout round-trips
# ---------------------------------------------------------------------------


def test_dense_layout_roundtrip(tmp_path):
    ds = generate_gmm(128, 10, n_partitions=4, seed=0)
    out = str(tmp_path / "d")
    data_io.write_reference_layout(ds, out, 4)
    assert sorted(os.listdir(out))[:4] == ["1.dat", "1.dat.npy", "2.dat", "2.dat.npy"] or True
    back = data_io.read_reference_layout(out, 4, sparse=False)
    assert np.allclose(back.X_train, ds.X_train, atol=1e-12)
    assert np.allclose(back.y_train, ds.y_train)
    assert np.allclose(back.X_test, ds.X_test, atol=1e-12)


def test_sparse_layout_roundtrip(tmp_path, amazon_raw):
    ds = real.prepare("amazon", amazon_raw)
    out = str(tmp_path / "s")
    data_io.write_reference_layout(ds, out, 4)
    back = data_io.read_reference_layout(out, 4, sparse=True)
    n = 4 * (ds.X_train.shape[0] // 4)
    assert (back.X_train != ds.X_train[:n]).nnz == 0
    assert np.allclose(back.y_train, ds.y_train[:n])


def test_roundtrip_tolerates_reference_truncated_labels(tmp_path):
    """VERDICT r5 #8: the reference's label writer truncates values to
    three decimals ("%5.3f", src/util.py:32-36), so label files prepared
    BY the reference carry that precision loss. Our loaders must accept
    the truncated form — both the classification ±1 labels (exact under
    truncation) and regression labels (recovered to 5e-4)."""
    # regression-style labels exercise real truncation (fractional values)
    ds = generate_gmm(128, 10, n_partitions=4, seed=0)
    rng = np.random.default_rng(0)
    ds = dataclasses.replace(
        ds,
        y_train=rng.normal(size=ds.y_train.shape) * 3.0,
        y_test=rng.normal(size=ds.y_test.shape) * 3.0,
    )
    out = str(tmp_path / "trunc")
    data_io.write_reference_layout(ds, out, 4)
    # rewrite the label files exactly as the reference would have
    for name, vals in (
        ("label.dat", ds.y_train[: 4 * (ds.n_samples // 4)]),
        ("label_test.dat", ds.y_test),
    ):
        data_io.save_dense_text(
            os.path.join(out, name), vals, fmt=data_io.REFERENCE_LABEL_FMT
        )
    back = data_io.read_reference_layout(out, 4, sparse=False)
    n = back.y_train.shape[0]
    # truncated form parses cleanly and recovers to the written precision
    assert np.allclose(back.y_train, ds.y_train[:n], atol=5e-4)
    assert np.allclose(back.y_test, ds.y_test, atol=5e-4)
    # and is BYTE-faithful to %5.3f: re-reading equals the truncation
    assert np.array_equal(
        back.y_train,
        np.array([float("%5.3f" % v) for v in ds.y_train[:n]]),
    )
    # ±1 classification labels survive truncation exactly
    ds2 = generate_gmm(64, 8, n_partitions=4, seed=1)
    out2 = str(tmp_path / "trunc2")
    data_io.write_reference_layout(ds2, out2, 4)
    data_io.save_dense_text(
        os.path.join(out2, "label.dat"),
        ds2.y_train[: 4 * (ds2.n_samples // 4)],
        fmt=data_io.REFERENCE_LABEL_FMT,
    )
    back2 = data_io.read_reference_layout(out2, 4, sparse=False)
    assert np.array_equal(
        back2.y_train, ds2.y_train[: back2.y_train.shape[0]]
    )


def test_prepare_cli_synthetic(tmp_path):
    out = str(tmp_path / "sd")
    prepare.main(
        ["synthetic", "--rows", "128", "--cols", "10", "--workers", "4", "--out", out]
    )
    path = os.path.join(out, "artificial-data/128x10/4")
    back = data_io.read_reference_layout(path, 4, sparse=False)
    assert back.X_train.shape == (128, 10)


def test_prepare_cli_real_and_sparse_training(tmp_path, amazon_raw):
    """Full pipeline: raw csv -> prepare CLI -> reference layout -> sparse
    coded training through the trainer -> eval."""
    out = str(tmp_path / "rd")
    prepare.main(
        ["real", "--dataset", "amazon", "--source", amazon_raw,
         "--workers", "4", "--out", out]
    )
    path = os.path.join(out, "amazon/4")
    ds = data_io.read_reference_layout(path, 4, sparse=True)
    cfg = RunConfig(
        scheme="approx", n_workers=4, n_stragglers=1, num_collect=3,
        rounds=6, n_rows=ds.n_samples, n_cols=ds.n_features,
        dataset="amazon", lr_schedule=1.0, add_delay=True, seed=0,
    )
    res = trainer.train(cfg, ds)
    ev = evaluate.replay(
        trainer.build_model(cfg), cfg.model, res.params_history,
        ds.X_train[: res.n_train], ds.y_train[: res.n_train],
        ds.X_test, ds.y_test,
    )
    assert np.isfinite(ev.training_loss).all()
    assert ev.training_loss[-1] < ev.training_loss[0]


FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")

# the genuine Kaggle amazon-employee-access-challenge train.csv header,
# in its genuine order (arrange_real_data.py:38-39 relies on it twice:
# df['ACTION'] and the positional .ix[:, 'RESOURCE':] slice)
AMAZON_HEADER = [
    "ACTION", "RESOURCE", "MGR_ID", "ROLE_ROLLUP_1", "ROLE_ROLLUP_2",
    "ROLE_DEPTNAME", "ROLE_TITLE", "ROLE_FAMILY_DESC", "ROLE_FAMILY",
    "ROLE_CODE",
]


class TestGenuineSchemas:
    """Committed schema-faithful fixtures (VERDICT r3 #4): the real Kaggle
    amazon header in its real column order, and the TU-Berlin dna
    features.csv shape (label col 0 + 200 feature columns, no header).
    A wrong column name in data/real.py fails here, not at ingestion."""

    def test_amazon_loc_slice_against_real_header(self):
        df = pd.read_csv(os.path.join(FIXTURES, "amazon_train_head.csv"))
        assert list(df.columns) == AMAZON_HEADER
        # the slice the preparer takes (real.py prepare_amazon ≙
        # arrange_real_data.py:39) selects exactly the 9 feature columns
        feats = df.loc[:, "RESOURCE":]
        assert list(feats.columns) == AMAZON_HEADER[1:]
        assert "ACTION" not in feats.columns

    def test_amazon_fixture_end_to_end(self, tmp_path):
        """Genuine-header csv -> prepare CLI -> reference layout -> AGC
        training -> eval replay -> the five reference artifacts."""
        import shutil

        from erasurehead_tpu.train import artifacts

        src = tmp_path / "raw"
        src.mkdir()
        shutil.copy(
            os.path.join(FIXTURES, "amazon_train_head.csv"),
            src / "train.csv",
        )
        out = str(tmp_path / "prepared")
        prepare.main(
            ["real", "--dataset", "amazon", "--source", str(src),
             "--workers", "4", "--out", out]
        )
        ds = data_io.read_reference_layout(
            os.path.join(out, "amazon/4"), 4, sparse=True
        )
        assert ds.n_samples == 96  # 80% of 120
        # exactly-one-hot per original column: 9 base + 34 interactions
        # + bias = 44 nnz per row
        assert (np.diff(ds.X_train.tocsr().indptr) == 44).all()
        cfg = RunConfig(
            scheme="approx", n_workers=4, n_stragglers=1, num_collect=3,
            rounds=6, n_rows=ds.n_samples, n_cols=ds.n_features,
            dataset="amazon", lr_schedule=1.0, add_delay=True, seed=0,
        )
        res = trainer.train(cfg, ds)
        ev = evaluate.replay(
            trainer.build_model(cfg), cfg.model, res.params_history,
            ds.X_train[: res.n_train], ds.y_train[: res.n_train],
            ds.X_test, ds.y_test,
        )
        assert np.isfinite(ev.training_loss).all()
        art_dir = str(tmp_path / "results")
        paths = artifacts.write_run_artifacts(res, ev, art_dir)
        names = {os.path.basename(p) for p in paths.values()}
        for part in ("training_loss", "testing_loss", "auc", "timeset",
                     "worker_timeset"):
            assert any(part in n for n in names), (part, names)

    def test_covtype_fixture_end_to_end(self, tmp_path):
        """Genuine UCI covtype.data layout (10 quantitative + 4 wilderness
        + 40 soil columns + Cover_Type, the file fetch_covtype parses ≙
        arrange_real_data.py:147-178) -> raw-file preparer path -> class
        {1,2} binarization -> reference layout -> AGC training."""
        import shutil

        src = tmp_path / "raw"
        src.mkdir()
        shutil.copy(
            os.path.join(FIXTURES, "covtype_head.data"),
            src / "covtype.data",
        )
        ds = real.prepare("covtype", str(src))
        # only classes {1,2} survive, mapped onto ±1
        assert set(np.unique(ds.y_train)) <= {-1.0, 1.0}
        assert ds.n_samples == 79  # 80% of the 99 class-1/2 fixture rows
        out = str(tmp_path / "prepared")
        prepare.main(
            ["real", "--dataset", "covtype", "--source", str(src),
             "--workers", "4", "--out", out]
        )
        back = data_io.read_reference_layout(
            os.path.join(out, "covtype/4"), 4, sparse=True
        )
        # one-hot per original column: 54 features + bias = 55 nnz/row
        assert (np.diff(back.X_train.tocsr().indptr) == 55).all()
        cfg = RunConfig(
            scheme="approx", n_workers=4, n_stragglers=1, num_collect=3,
            rounds=6, n_rows=back.n_samples, n_cols=back.n_features,
            dataset="covtype", lr_schedule=1.0, add_delay=True, seed=0,
        )
        res = trainer.train(cfg, back)
        assert np.isfinite(np.asarray(res.params_history)).all()

    def test_covtype_wrong_column_count_rejected(self, tmp_path):
        (tmp_path / "covtype.data").write_text(
            "\n".join(",".join("1" for _ in range(54)) for _ in range(3))
        )
        with pytest.raises(ValueError, match="expected 55 columns"):
            real.prepare("covtype", str(tmp_path))

    def test_kc_house_loc_slice_against_real_header(self):
        df = pd.read_csv(os.path.join(FIXTURES, "kc_house_head.csv"))
        # the genuine Kaggle kc_house_data.csv column order: the
        # positional 'bedrooms':-onward slice (arrange_real_data.py:213)
        # must select the 18 feature columns and exclude id/date/price
        assert list(df.columns[:4]) == ["id", "date", "price", "bedrooms"]
        feats = df.loc[:, "bedrooms":]
        assert feats.shape[1] == 18
        assert {"id", "date", "price"}.isdisjoint(feats.columns)
        assert list(feats.columns[-2:]) == ["sqft_living15", "sqft_lot15"]

    def test_kc_house_fixture_end_to_end(self, tmp_path):
        """Genuine-header kc_house_data.csv -> preparer ('bedrooms':
        slice, price/1e6 regression target) -> layout -> linear-model
        training (arrange_real_data.py:207-253)."""
        import shutil

        src = tmp_path / "raw"
        src.mkdir()
        shutil.copy(
            os.path.join(FIXTURES, "kc_house_head.csv"),
            src / "kc_house_data.csv",
        )
        ds = real.prepare("kc_house_data", str(src))
        assert ds.X_train.shape[0] == 96 and ds.X_test.shape[0] == 24
        # regression target at O(1) scale, not ±1 labels
        assert 0.0 < ds.y_train.mean() < 3.0
        # 18 features + bias, one-hot per column = 19 nnz/row
        assert (np.diff(ds.X_train.tocsr().indptr) == 19).all()
        out = str(tmp_path / "prepared")
        prepare.main(
            ["real", "--dataset", "kc_house_data", "--source", str(src),
             "--workers", "4", "--out", out]
        )
        back = data_io.read_reference_layout(
            os.path.join(out, "kc_house_data/4"), 4, sparse=True
        )
        cfg = RunConfig(
            scheme="approx", model="linear", n_workers=4, n_stragglers=1,
            num_collect=3, rounds=6, n_rows=back.n_samples,
            n_cols=back.n_features, dataset="kc_house_data",
            lr_schedule=0.1, add_delay=True, seed=0,
        )
        res = trainer.train(cfg, back)
        ev = evaluate.replay(
            trainer.build_model(cfg), cfg.model, res.params_history,
            back.X_train[: res.n_train], back.y_train[: res.n_train],
            back.X_test, back.y_test,
        )
        assert np.isfinite(ev.training_loss).all()
        assert ev.training_loss[-1] < ev.training_loss[0]

    def test_dna_fixture_end_to_end(self, tmp_path):
        """TU-Berlin-shaped features.csv (1 label + 200 feature columns)
        -> preparer -> layout -> training; proves the genfromtxt parse and
        column-0-is-label convention (arrange_real_data.py:100-103)."""
        import shutil

        src = tmp_path / "raw"
        src.mkdir()
        shutil.copy(
            os.path.join(FIXTURES, "dna_features_head.csv"),
            src / "features.csv",
        )
        ds = real.prepare("dna", str(src))
        assert ds.X_train.shape[0] == 96 and ds.X_test.shape[0] == 24
        assert set(np.unique(ds.y_train)) <= {-1.0, 1.0}
        # 200 features + the 1/sqrt(n) bias column, one-hot per column
        assert (np.diff(ds.X_train.tocsr().indptr) == 201).all()
        out = str(tmp_path / "prepared")
        prepare.main(
            ["real", "--dataset", "dna", "--source", str(src),
             "--workers", "4", "--out", out]
        )
        back = data_io.read_reference_layout(
            os.path.join(out, "dna/4"), 4, sparse=True
        )
        cfg = RunConfig(
            scheme="approx", n_workers=4, n_stragglers=1, num_collect=3,
            rounds=6, n_rows=back.n_samples, n_cols=back.n_features,
            lr_schedule=1.0, add_delay=True, seed=0,
        )
        res = trainer.train(cfg, back)
        hist = np.asarray(res.params_history)
        assert np.isfinite(hist).all()


def test_generate_onehot_structure():
    """Covtype-style synthetic one-hot: CSR, exactly n_fields ones per row,
    one active category per contiguous field block, deterministic by seed
    (tools/bench_sparse.py's canonical-scale workload in miniature)."""
    ds = generate_onehot(240, 130, n_partitions=4, n_fields=12, seed=3)
    X = ds.X_train.tocsr()
    assert X.shape == (240, 130) and ds.X_test.shape == (48, 130)
    assert (np.diff(X.indptr) == 12).all()
    assert (X.data == 1.0).all()
    bounds = np.linspace(0, 130, 13).astype(int)
    idx = X.indices.reshape(240, 12)
    assert ((idx >= bounds[:-1]) & (idx < bounds[1:])).all()
    assert set(np.unique(ds.y_train)) <= {-1.0, 1.0}
    ds2 = generate_onehot(240, 130, n_partitions=4, n_fields=12, seed=3)
    assert (ds.X_train != ds2.X_train).nnz == 0
    assert np.array_equal(ds.y_train, ds2.y_train)
    with pytest.raises(ValueError):
        generate_onehot(241, 130, n_partitions=4)
    with pytest.raises(ValueError):
        generate_onehot(240, 8, n_partitions=4, n_fields=12)


def test_onehot_sparse_agc_trains():
    """The covtype-shaped sparse path end-to-end in miniature: one-hot CSR
    -> PaddedRows slot stacks -> AGC trainer -> loss decreases."""
    ds = generate_onehot(720, 180, n_partitions=6, n_fields=12, seed=0)
    cfg = RunConfig(
        scheme="approx", n_workers=6, n_stragglers=1, num_collect=4,
        rounds=8, n_rows=720, n_cols=180, dataset="covtype",
        lr_schedule=2.0, add_delay=True, seed=0,
    )
    res = trainer.train(cfg, ds)
    ev = evaluate.replay(
        trainer.build_model(cfg), cfg.model, res.params_history,
        ds.X_train[: res.n_train], ds.y_train[: res.n_train],
        ds.X_test, ds.y_test,
    )
    assert np.isfinite(ev.training_loss).all()
    assert ev.training_loss[-1] < ev.training_loss[0]


def test_sparse_lanes_and_dedup_train_same():
    """config.sparse_lanes and compute_mode='deduped' are pure lowering
    choices: the training trajectory on sparse data must match the scalar
    faithful path to f32 tolerance, and the knob must reset between runs."""
    from erasurehead_tpu.ops import features

    ds = generate_onehot(480, 120, n_partitions=6, n_fields=8, seed=1)
    base = dict(
        scheme="approx", n_workers=6, n_stragglers=1, num_collect=4,
        rounds=6, n_rows=480, n_cols=120, dataset="covtype",
        lr_schedule=2.0, add_delay=True, seed=0,
    )
    ref = trainer.train(RunConfig(**base), ds)
    assert features.get_sparse_lanes() is None
    lanes = trainer.train(RunConfig(**base, sparse_lanes=8), ds)
    # the knob is scoped to the run: it must NOT leak into post-run
    # callers (evaluate.replay's full-train-set gather would be L x the
    # memory at scale)
    assert features.get_sparse_lanes() is None
    dedup = trainer.train(
        RunConfig(**base, compute_mode="deduped", sparse_lanes=128), ds
    )
    assert features.get_sparse_lanes() is None
    h_ref = np.asarray(ref.params_history)
    assert np.allclose(np.asarray(lanes.params_history), h_ref, atol=1e-5)
    assert np.allclose(np.asarray(dedup.params_history), h_ref, atol=1e-5)
