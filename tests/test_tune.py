"""The measured autotuning plane (ISSUE 19): race, cache, resolve.

The load-bearing invariants:
  - the decision cache is DETERMINISTIC: it stores choices only
    (no timings/timestamps), serializes canonically (insertion-order
    independent, byte-identical for equal decisions), survives corrupt
    files as "empty", and is re-read across instances via the
    (mtime_ns, size) stamp;
  - the racer's verdicts are exact under an injected fake clock:
    min-over-repeats, a challenger only unseats the fallback by beating
    it past TIE_MARGIN, ties keep the fallback (timer noise cannot flip
    decisions);
  - every resolver (resolve_block_decode / resolve_layer_coding /
    resolve_ring_pipeline, supports_fused, resolve_ring_stack) walks the
    ladder explicit > env > cached decision > hardcoded constant, and a
    cached verdict actually flips the lowering;
  - resolutions emit typed ``tune`` events (schema-validated, per-process
    deduped) and emission is observation-only;
  - the race-side shape signature equals the resolve-side signature
    (trainer.resolved_stack agreement) — a persisted verdict is actually
    FOUND by the run it was raced for;
  - supports_fused declines carry a reason string, surfaced once as a
    ``warning`` event by trainer's use_pallas="auto" gate.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from erasurehead_tpu import tune as tune_lib
from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.parallel import step as step_lib
from erasurehead_tpu.tune import cache as cache_lib
from erasurehead_tpu.tune import racer as racer_lib
from erasurehead_tpu.tune import races as races_lib
from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.train import trainer
from erasurehead_tpu.utils.config import RunConfig

W = 8


@pytest.fixture(autouse=True)
def isolated_cache(tmp_path, monkeypatch):
    """Every test gets its own decision cache file and a clean event
    dedup set; the memoized cache map is dropped on both sides."""
    path = str(tmp_path / "tune.json")
    monkeypatch.setenv(cache_lib.ENV_PATH, path)
    tune_lib.reset()
    tune_lib.reset_emitted()
    yield path
    tune_lib.reset()
    tune_lib.reset_emitted()


@pytest.fixture(scope="module")
def gmm():
    return generate_gmm(256, 32, n_partitions=W, seed=0)


def _cfg(**kw):
    base = dict(
        scheme="approx", model="deepmlp", n_workers=W, n_stragglers=1,
        num_collect=6, rounds=3, n_rows=256, n_cols=32,
        update_rule="AGD", lr_schedule=0.5, add_delay=True, seed=0,
    )
    base.update(kw)
    return RunConfig(**base)


class FakeTimer:
    """Scripted clock: returns the next value per call."""

    def __init__(self, values):
        self._vals = iter(values)

    def __call__(self):
        return next(self._vals)


# ---------------------------------------------------------------------------
# decision cache


class TestDecisionCache:
    def test_roundtrip_and_canonical_bytes(self, isolated_cache):
        c = tune_lib.get_cache()
        assert c.lookup("cpu", "block_decode", "sig") is None
        c.record("cpu", "block_decode", "sig", "fused")
        c.record("cpu", "layer_coding", "sig", "treewise")
        assert c.lookup("cpu", "block_decode", "sig") == "fused"
        # canonical serialization is insertion-order independent
        d = cache_lib.DecisionCache(isolated_cache + ".b")
        d.record("cpu", "layer_coding", "sig", "treewise")
        d.record("cpu", "block_decode", "sig", "fused")
        assert c.to_bytes() == d.to_bytes()
        doc = json.loads(c.to_bytes())
        assert doc["version"] == cache_lib.VERSION
        assert "choice" in doc["decisions"]["cpu|block_decode|sig"]
        # no timings, timestamps, or hostnames anywhere in the file
        assert set(doc["decisions"]["cpu|block_decode|sig"]) == {"choice"}

    def test_fresh_instance_reads_persisted_file(self, isolated_cache):
        tune_lib.get_cache().record("tpu v5e", "glm_fused", "s1", "pallas")
        fresh = cache_lib.DecisionCache(isolated_cache)
        assert fresh.lookup("tpu v5e", "glm_fused", "s1") == "pallas"

    def test_corrupt_file_is_empty_cache(self, isolated_cache):
        with open(isolated_cache, "w") as f:
            f.write("{not json")
        assert tune_lib.get_cache().lookup("cpu", "block_decode", "x") is None
        # and recording over the corrupt file heals it
        tune_lib.get_cache().record("cpu", "block_decode", "x", "treewise")
        assert (
            cache_lib.DecisionCache(isolated_cache).lookup(
                "cpu", "block_decode", "x"
            )
            == "treewise"
        )

    def test_stamp_refresh_sees_external_writes(self, isolated_cache):
        c = tune_lib.get_cache()
        assert c.lookup("cpu", "block_decode", "x") is None
        other = cache_lib.DecisionCache(isolated_cache)
        other.record("cpu", "block_decode", "x", "fused")
        # same path, different instance: the stamp moves, c re-reads
        assert c.lookup("cpu", "block_decode", "x") == "fused"

    def test_missing_file_and_default_path_env(self, isolated_cache):
        assert cache_lib.default_path() == isolated_cache
        assert tune_lib.get_cache().decisions() == {}


# ---------------------------------------------------------------------------
# racer


class TestRacer:
    def _candidates(self):
        return {"treewise": lambda: None, "fused": lambda: None}

    def test_decisive_challenger_wins(self):
        # sorted order times "fused" first: fused dt=1, treewise dt=10
        timer = FakeTimer([0.0, 1.0, 10.0, 20.0])
        res = racer_lib.race(
            "block_decode", "sig", self._candidates(),
            fallback="treewise", reps=1, timer=timer, record=False,
        )
        assert res.choice == "fused" and res.decisive
        assert res.timings == {"fused": 1.0, "treewise": 10.0}

    def test_tie_keeps_fallback(self):
        # fused dt=0.95, treewise dt=1.0: inside the 10% margin -> tie
        timer = FakeTimer([0.0, 0.95, 0.0, 1.0])
        res = racer_lib.race(
            "block_decode", "sig", self._candidates(),
            fallback="treewise", reps=1, timer=timer, record=False,
        )
        assert res.choice == "treewise" and not res.decisive

    def test_fallback_winning_is_not_decisive(self):
        timer = FakeTimer([0.0, 10.0, 0.0, 1.0])
        res = racer_lib.race(
            "block_decode", "sig", self._candidates(),
            fallback="treewise", reps=1, timer=timer, record=False,
        )
        assert res.choice == "treewise" and not res.decisive

    def test_min_over_reps(self):
        # fused reps: 5.0 then 1.0 -> min 1.0; treewise reps: 10, 10
        timer = FakeTimer([0.0, 5.0, 10.0, 11.0, 0.0, 10.0, 20.0, 30.0])
        res = racer_lib.race(
            "block_decode", "sig", self._candidates(),
            fallback="treewise", reps=2, timer=timer, record=False,
        )
        assert res.timings["fused"] == 1.0
        assert res.choice == "fused" and res.decisive

    def test_unknown_fallback_raises(self):
        with pytest.raises(ValueError, match="fallback"):
            racer_lib.race(
                "block_decode", "sig", self._candidates(),
                fallback="nope", reps=1, record=False,
            )

    def test_race_records_choice_and_emits_event(self, isolated_cache):
        timer = FakeTimer([0.0, 1.0, 10.0, 20.0])
        seen = []
        events_lib.add_observer(seen.append)
        try:
            racer_lib.race(
                "block_decode", "shape-sig", self._candidates(),
                fallback="treewise", reps=1, timer=timer,
                device_kind="cpu",
            )
        finally:
            events_lib.remove_observer(seen.append)
        assert (
            tune_lib.get_cache().lookup("cpu", "block_decode", "shape-sig")
            == "fused"
        )
        tune = [r for r in seen if r["type"] == "tune"]
        assert len(tune) == 1
        assert tune[0]["choice"] == "fused"
        assert tune[0]["source"] == "race"


# ---------------------------------------------------------------------------
# lookup: sources, dedup, schema


class TestLookup:
    def test_cache_hit_emits_cache_source(self, isolated_cache):
        dk = tune_lib.default_device_kind()
        tune_lib.get_cache().record(dk, "block_decode", "s", "fused")
        seen = []
        events_lib.add_observer(seen.append)
        try:
            assert tune_lib.lookup("block_decode", "s") == "fused"
            # second resolve of the identical decision is deduped
            assert tune_lib.lookup("block_decode", "s") == "fused"
        finally:
            events_lib.remove_observer(seen.append)
        tune = [r for r in seen if r["type"] == "tune"]
        assert len(tune) == 1 and tune[0]["source"] == "cache"

    def test_miss_emits_default_with_fallback(self):
        seen = []
        events_lib.add_observer(seen.append)
        try:
            assert (
                tune_lib.lookup("block_decode", "s", fallback="treewise")
                is None
            )
        finally:
            events_lib.remove_observer(seen.append)
        tune = [r for r in seen if r["type"] == "tune"]
        assert len(tune) == 1
        assert tune[0]["source"] == "default"
        assert tune[0]["choice"] == "treewise"

    def test_tune_events_pass_validator(self, isolated_cache, tmp_path):
        dk = tune_lib.default_device_kind()
        tune_lib.get_cache().record(dk, "glm_fused", "shape", "pallas")
        path = str(tmp_path / "events.jsonl")
        with events_lib.capture(path):
            tune_lib.lookup("glm_fused", "shape")
            tune_lib.lookup("ring_pipeline", "shape", fallback="sequential")
        assert events_lib.validate_lines(open(path)) == []
        recs = [json.loads(x) for x in open(path) if x.strip()]
        assert sum(r["type"] == "tune" for r in recs) == 2

    def test_validator_rejects_unknown_race_and_source(self):
        line = json.dumps({
            "type": "tune", "seq": 0, "t": 0.0, "race": "bogus",
            "device_kind": "cpu", "shape": "s", "choice": "x",
            "source": "vibes",
        })
        errors = events_lib.validate_lines([line])
        assert any("race" in e for e in errors)
        assert any("source" in e for e in errors)

    def test_races_constant_matches_events_constant(self):
        assert tuple(sorted(tune_lib.TUNE_CHOICES)) == events_lib.TUNE_RACES
        assert events_lib.TUNE_SOURCES == ("race", "cache", "default")


# ---------------------------------------------------------------------------
# resolvers walk the ladder


class TestResolvers:
    def _stack(self, gmm, **kw):
        return trainer.resolved_stack(_cfg(**kw), gmm)

    def test_block_decode_explicit_beats_everything(self):
        assert step_lib.resolve_block_decode("fused") is True
        assert step_lib.resolve_block_decode("treewise") is False

    def test_block_decode_env_beats_cache(self, gmm, monkeypatch):
        model, X = self._stack(gmm)
        dk = tune_lib.default_device_kind()
        sig = tune_lib.run_shape_signature(model, X)
        tune_lib.get_cache().record(dk, "block_decode", sig, "treewise")
        monkeypatch.setenv("ERASUREHEAD_BLOCK_DECODE", "fused")
        assert step_lib.resolve_block_decode("auto", model, X) is True
        monkeypatch.delenv("ERASUREHEAD_BLOCK_DECODE")
        assert step_lib.resolve_block_decode("auto", model, X) is False

    def test_block_decode_cached_decision_flips_auto(self, gmm):
        model, X = self._stack(gmm)
        # no cached verdict: the hardcoded constant stands
        assert (
            step_lib.resolve_block_decode("auto", model, X)
            is step_lib.BLOCK_DECODE_FUSED_DEFAULT
        )
        tune_lib.get_cache().record(
            tune_lib.default_device_kind(), "block_decode",
            tune_lib.run_shape_signature(model, X), "fused",
        )
        assert step_lib.resolve_block_decode("auto", model, X) is True

    def test_layer_coding_cached_decision_flips_auto(self, gmm):
        model, X = self._stack(gmm)
        assert (
            step_lib.resolve_layer_coding("auto", model, X)
            is step_lib.LAYER_CODING_DEFAULT
        )
        tune_lib.get_cache().record(
            tune_lib.default_device_kind(), "layer_coding",
            tune_lib.run_shape_signature(model, X), "blockwise",
        )
        assert step_lib.resolve_layer_coding("auto", model, X) is True
        # explicit still forces
        assert step_lib.resolve_layer_coding("off", model, X) is False

    def test_ring_pipeline_cached_decision_flips_auto(self, gmm):
        model, X = self._stack(gmm)
        assert (
            step_lib.resolve_ring_pipeline("auto", model, X)
            is step_lib.RING_PIPELINE_DEFAULT
        )
        tune_lib.get_cache().record(
            tune_lib.default_device_kind(), "ring_pipeline",
            tune_lib.run_shape_signature(model, X), "pipelined",
        )
        assert step_lib.resolve_ring_pipeline("auto", model, X) is True
        assert step_lib.resolve_ring_pipeline("off", model, X) is False

    def test_ring_stack_cached_decision_overrides_footprint(self, gmm):
        from erasurehead_tpu.data import sharding as sharding_lib

        cfg = _cfg(
            scheme="repcoded", compute_mode="faithful", model="mlp"
        )
        layout = trainer.build_layout(cfg)
        assert layout.storage_overhead > 1.0
        # small data: the footprint gate says materialized
        assert (
            sharding_lib.resolve_ring_stack(
                "auto", layout, gmm, 1, np.float32
            )
            is False
        )
        rows = gmm.n_samples // layout.n_partitions
        sig = tune_lib.stack_mode_signature(
            layout, rows, gmm.X_train.shape[1], np.dtype(np.float32).name
        )
        tune_lib.get_cache().record(
            tune_lib.default_device_kind(), "stack_mode", sig, "ring"
        )
        assert (
            sharding_lib.resolve_ring_stack(
                "auto", layout, gmm, 1, np.float32
            )
            is True
        )
        # structural gates still dominate the measured verdict
        assert (
            sharding_lib.resolve_ring_stack(
                "auto", layout, gmm, 1, np.float32, supported=False
            )
            is False
        )
        # and explicit still forces
        assert (
            sharding_lib.resolve_ring_stack(
                "materialized", layout, gmm, 1, np.float32
            )
            is False
        )

    def test_lowering_signature_forks_on_block_decode(self, gmm):
        cfg_t = _cfg(layer_coding="on", block_decode="treewise")
        cfg_f = _cfg(layer_coding="on", block_decode="fused")
        model, X = self._stack(gmm, layer_coding="on")
        assert step_lib.lowering_signature(
            cfg_t, model, X
        ) != step_lib.lowering_signature(cfg_f, model, X)


# ---------------------------------------------------------------------------
# end-to-end: race -> cache -> warm resolution, deterministic + bitwise


class TestRaceToResolution:
    def test_race_block_decode_cache_is_deterministic(
        self, gmm, tmp_path, monkeypatch
    ):
        """Two races at the same shape with the same scripted clock
        serialize to byte-identical cache files."""
        cfg = _cfg(rounds=2)
        blobs = []
        for name in ("a", "b"):
            path = str(tmp_path / f"cache_{name}.json")
            monkeypatch.setenv(cache_lib.ENV_PATH, path)
            tune_lib.reset()
            tune_lib.reset_emitted()
            races_lib.race_block_decode(
                cfg, gmm, reps=1,
                timer=FakeTimer([0.0, 1.0, 10.0, 20.0]),
            )
            blobs.append(open(path, "rb").read())
        assert blobs[0] == blobs[1]
        doc = json.loads(blobs[0])
        assert len(doc["decisions"]) == 1
        (key,) = doc["decisions"]
        assert "|block_decode|" in key

    def test_raced_verdict_resolves_next_auto_run(self, gmm, tmp_path):
        """Signature agreement: the shape key the race persists is the
        key the next training run's resolver computes."""
        cfg = _cfg(rounds=2, layer_coding="on")
        races_lib.race_block_decode(
            cfg, gmm, reps=1, timer=FakeTimer([0.0, 1.0, 10.0, 20.0])
        )
        model, X = trainer.resolved_stack(cfg, gmm)
        assert (
            step_lib.resolve_block_decode("auto", model, X) is True
        ), "raced 'fused' verdict was not found at resolve time"

    def test_tuned_auto_run_is_bitwise_and_telemetry_invariant(
        self, gmm, tmp_path
    ):
        """The tuned lowering is observation-only: auto (resolved fused
        from the cache) == forced fused == forced treewise, with and
        without an events capture."""
        cfg = _cfg(rounds=2, layer_coding="on")
        races_lib.race_block_decode(
            cfg, gmm, reps=1, timer=FakeTimer([0.0, 1.0, 10.0, 20.0])
        )

        def leaves(r):
            return [np.asarray(x) for x in jax.tree.leaves(r.final_params)]

        auto_cfg = dataclasses.replace(cfg, block_decode="auto")
        path = str(tmp_path / "events.jsonl")
        with events_lib.capture(path):
            r_auto = trainer.train(auto_cfg, gmm)
        r_dark = trainer.train(auto_cfg, gmm)
        r_fused = trainer.train(
            dataclasses.replace(cfg, block_decode="fused"), gmm
        )
        r_tree = trainer.train(
            dataclasses.replace(cfg, block_decode="treewise"), gmm
        )
        for other in (r_dark, r_fused, r_tree):
            for a, b in zip(leaves(r_auto), leaves(other)):
                assert a.tobytes() == b.tobytes()
        assert events_lib.validate_lines(open(path)) == []
        recs = [json.loads(x) for x in open(path) if x.strip()]
        cached = [
            r for r in recs
            if r["type"] == "tune" and r["source"] == "cache"
        ]
        assert cached and cached[0]["choice"] == "fused"

    def test_glm_fused_race_rejects_non_glm(self, gmm):
        with pytest.raises(ValueError, match="dense GLM"):
            races_lib.race_glm_fused(_cfg(model="deepmlp"), gmm, reps=1)

    def test_ring_races_skip_on_single_device(self, gmm):
        if len(jax.devices()) >= 2:
            pytest.skip("multi-device host: the race would actually run")
        assert races_lib.race_ring_pipeline(_cfg(), gmm) is None
        assert races_lib.race_stack_mode(_cfg(), gmm) is None
        assert tune_lib.get_cache().decisions() == {}


# ---------------------------------------------------------------------------
# supports_fused reasons + the trainer's one-time warning


class TestSupportsFusedReasons:
    def test_declines_carry_reasons(self):
        from erasurehead_tpu.ops import kernels

        X = jnp.zeros((2, 8, 128), jnp.float32)
        for verdict, needle in (
            (kernels.supports_fused(X, "mlp", "tpu"), "dense GLM"),
            (kernels.supports_fused(X, "logistic", "cpu"), "Mosaic"),
            (kernels.supports_fused(X, "logistic", "tpu"), "race"),
        ):
            assert not verdict
            assert needle in verdict.reason

    def test_cached_pallas_verdict_accepts(self):
        from erasurehead_tpu.ops import kernels

        X = jnp.zeros((2, 8, 128), jnp.float32)
        tune_lib.get_cache().record(
            tune_lib.default_device_kind(), "glm_fused",
            tune_lib.glm_fused_signature(X.shape, str(X.dtype), "logistic"),
            "pallas",
        )
        verdict = kernels.supports_fused(X, "logistic", "tpu")
        assert verdict
        assert "pallas" in verdict.reason

    def test_trainer_emits_decline_warning_once(self, gmm, tmp_path):
        trainer._pallas_declined_seen.clear()
        cfg = _cfg(model="logistic", rounds=2, use_pallas="auto")
        path = str(tmp_path / "events.jsonl")
        with events_lib.capture(path):
            trainer.train(cfg, gmm)
            trainer.train(cfg, gmm)  # second run: deduped, no second event
        recs = [json.loads(x) for x in open(path) if x.strip()]
        declines = [
            r for r in recs
            if r["type"] == "warning"
            and r.get("kind") == "use_pallas_declined"
        ]
        assert len(declines) == 1
        assert declines[0]["message"]
        assert events_lib.validate_lines(open(path)) == []
