"""Sweep engine (train/cache.py): run-to-run executable/data caching,
the seed-vmapped batched runner, and the ADVICE r5 bugfix regressions.

The load-bearing invariants:
  - cached and fresh runs are BITWISE identical (the cached executable was
    compiled from an identical lowering — anything less means the cache
    key is missing a knob);
  - the key covers everything that changes the lowering: dtype, resolved
    grad lowering, mesh, shapes — each change must MISS;
  - a multi-scheme compare() at one shape compiles once and uploads once
    in deduped mode (partition stacking is scheme-independent);
  - train_batch() over seeds matches per-seed train() and dispatches once.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.parallel.mesh import worker_mesh
from erasurehead_tpu.train import cache, experiments, trainer
from erasurehead_tpu.utils.config import RunConfig

W, ROUNDS = 8, 8
N_ROWS, N_COLS = 512, 24


@pytest.fixture(scope="module")
def gmm():
    return generate_gmm(N_ROWS, N_COLS, n_partitions=W, seed=0)


@pytest.fixture(autouse=True)
def fresh_cache():
    """Every test starts and ends with empty caches and zero counters."""
    cache.clear()
    cache.set_enabled(True)
    yield
    cache.clear()


def _cfg(**kw):
    base = dict(
        scheme="approx",
        n_workers=W,
        n_stragglers=1,
        num_collect=6,
        rounds=ROUNDS,
        n_rows=N_ROWS,
        n_cols=N_COLS,
        update_rule="AGD",
        lr_schedule=0.5,
        add_delay=True,
        seed=3,
    )
    base.update(kw)
    return RunConfig(**base)


# ---------------------------------------------------------------------------
# executable + data cache


class TestRunToRunCache:
    def test_second_run_hits_and_is_bitwise_identical(self, gmm):
        r1 = trainer.train(_cfg(), gmm)
        assert r1.cache_info["exec_misses"] == 1
        assert r1.cache_info["data_hit"] is False
        r2 = trainer.train(_cfg(), gmm)
        assert r2.cache_info["exec_hits"] == 1
        assert r2.cache_info["exec_misses"] == 0
        assert r2.cache_info["data_hit"] is True
        assert r2.cache_info["compile_seconds_saved"] > 0
        assert r2.cache_info["bytes_reused"] > 0
        # the hard correctness bar: BITWISE equality, not allclose
        assert np.array_equal(
            np.asarray(r1.params_history), np.asarray(r2.params_history)
        )
        assert np.array_equal(
            np.asarray(r1.final_params), np.asarray(r2.final_params)
        )

    def test_cached_matches_cache_disabled_bitwise(self, gmm):
        """A cache-served run == the same run with the engine off."""
        trainer.train(_cfg(), gmm)  # populate
        cached = trainer.train(_cfg(), gmm)
        assert cached.cache_info["exec_hits"] == 1
        cache.set_enabled(False)
        fresh = trainer.train(_cfg(), gmm)
        assert fresh.cache_info["enabled"] is False
        assert np.array_equal(
            np.asarray(cached.params_history),
            np.asarray(fresh.params_history),
        )

    def test_weight_tables_are_arguments_not_keys(self, gmm):
        """Different scheme, same shapes/lowering -> executable HIT (the
        per-round weight tables are traced arguments; sharing across them
        is the engine's whole point). FRC shares AGC's assignment, so the
        data upload is shared too."""
        trainer.train(_cfg(scheme="approx"), gmm)
        r = trainer.train(_cfg(scheme="repcoded"), gmm)
        assert r.cache_info["exec_hits"] == 1
        assert r.cache_info["data_hit"] is True

    @pytest.mark.parametrize(
        "change",
        [
            dict(dtype="bfloat16"),
            dict(flat_grad="on"),
            dict(update_rule="GD"),
            dict(scan_unroll=2),
            dict(compute_mode="deduped"),
        ],
    )
    def test_lowering_changes_invalidate(self, gmm, change):
        trainer.train(_cfg(), gmm)
        r = trainer.train(_cfg(**change), gmm)
        assert r.cache_info["exec_hits"] == 0, change
        assert r.cache_info["exec_misses"] == 1, change

    def test_mesh_change_invalidates(self, gmm):
        trainer.train(_cfg(), gmm, mesh=worker_mesh(8))
        r = trainer.train(_cfg(), gmm, mesh=worker_mesh(4))
        assert r.cache_info["exec_hits"] == 0
        assert r.cache_info["data_hit"] is False

    def test_dataset_identity_keys_data_cache(self, gmm):
        """A different dataset object of the same shape must re-upload."""
        other = generate_gmm(N_ROWS, N_COLS, n_partitions=W, seed=9)
        trainer.train(_cfg(), gmm)
        r = trainer.train(_cfg(), other)
        assert r.cache_info["data_hit"] is False
        # but the executable is shape-keyed and hits
        assert r.cache_info["exec_hits"] == 1

    def test_two_scheme_compare_accounting(self, gmm):
        """Sequential compare() (batch='off') across two schemes: one
        compile + one upload total, telemetry carried into the experiment
        rows. The batched default collapses this into ONE cohort dispatch
        instead — that contract is pinned in tests/test_cohort.py."""
        configs = {
            "approx": _cfg(scheme="approx"),
            "repcoded": _cfg(scheme="repcoded"),
        }
        rows = experiments.compare(configs, gmm, batch="off")
        assert len(rows) == 2
        by_label = {r.label: r.cache for r in rows}
        assert by_label["approx"]["exec_misses"] == 1
        assert by_label["repcoded"]["exec_hits"] == 1
        assert by_label["repcoded"]["exec_misses"] == 0
        assert by_label["repcoded"]["data_hit"] is True
        assert "cache" in rows[1].row()
        s = cache.stats()
        assert s.exec_misses == 1 and s.data_misses == 1

    def test_seven_scheme_compare_one_compile_one_upload(self):
        """The sweep-CACHE acceptance bar: seven schemes at the canonical
        W=30 shape, deduped mode (partition stacking is
        scheme-independent), run SEQUENTIALLY (batch='off') perform
        exactly ONE scan compile and ONE data upload. The trajectory-
        batched default goes further — one cohort DISPATCH — pinned in
        tests/test_cohort.py."""
        W30 = 30
        data = generate_gmm(W30 * 16, N_COLS, n_partitions=W30, seed=0)
        common = dict(
            n_workers=W30, n_stragglers=2, rounds=4, n_rows=W30 * 16,
            n_cols=N_COLS, update_rule="AGD", lr_schedule=0.5,
            add_delay=True, seed=0, compute_mode="deduped",
        )
        configs = {
            "naive": RunConfig(scheme="naive", **common),
            "cyccoded": RunConfig(scheme="cyccoded", **common),
            "repcoded": RunConfig(scheme="repcoded", **common),
            "approx": RunConfig(
                scheme="approx", **{**common, "num_collect": 15}
            ),
            "avoidstragg": RunConfig(scheme="avoidstragg", **common),
            "randreg": RunConfig(
                scheme="randreg", **{**common, "num_collect": 15}
            ),
            "deadline": RunConfig(
                scheme="deadline", **{**common, "deadline": 1.0}
            ),
        }
        assert len(configs) == 7
        rows = experiments.compare(configs, data, batch="off")
        assert len(rows) == 7
        s = cache.stats()
        assert s.exec_misses == 1, s.snapshot()
        assert s.data_misses == 1, s.snapshot()
        assert s.exec_hits == 6 and s.data_hits == 6

    def test_disabled_cache_never_counts(self, gmm):
        cache.set_enabled(False)
        trainer.train(_cfg(), gmm)
        trainer.train(_cfg(), gmm)
        s = cache.stats()
        assert s.exec_hits == s.exec_misses == 0
        assert s.data_hits == s.data_misses == 0

    def test_lru_eviction_bounds_memory(self, gmm):
        for r in range(cache.DATA_CACHE_MAX + 2):
            trainer.train(_cfg(rounds=2, seed=r, dtype="float32"), gmm)
        assert len(cache._data_cache) <= cache.DATA_CACHE_MAX
        assert len(cache._exec_cache) <= cache.EXEC_CACHE_MAX


# ---------------------------------------------------------------------------
# seed-vmapped batched runner


class TestTrainBatch:
    def test_matches_per_seed_train(self, gmm):
        seeds = [3, 11, 42, 123]
        batch = trainer.train_batch(_cfg(), gmm, seeds)
        assert len(batch) == len(seeds)
        info = batch[0].cache_info
        assert info["batch_size"] == 4 and info["batch_dispatches"] == 1
        for s, res in zip(seeds, batch):
            single = trainer.train(_cfg(seed=s), gmm)
            np.testing.assert_allclose(
                np.asarray(res.params_history),
                np.asarray(single.params_history),
                rtol=2e-5, atol=1e-6,
            )
            assert res.config.seed == s
            # per-seed control plane flows through: same simulated clocks
            np.testing.assert_array_equal(res.timeset, single.timeset)
            np.testing.assert_array_equal(res.collected, single.collected)

    def test_single_dispatch_and_cache_reuse(self, gmm):
        seeds = [0, 1, 2, 3]
        b1 = trainer.train_batch(_cfg(), gmm, seeds)
        assert b1[0].cache_info["exec_misses"] == 1
        b2 = trainer.train_batch(_cfg(), gmm, seeds)
        assert b2[0].cache_info["exec_hits"] == 1
        # batch results share the one dispatch's wall clock
        assert len({r.wall_time for r in b2}) == 1
        for a, b in zip(b1, b2):
            assert np.array_equal(
                np.asarray(a.params_history), np.asarray(b.params_history)
            )

    def test_deduped_mode_batches(self, gmm):
        seeds = [5, 6]
        batch = trainer.train_batch(
            _cfg(compute_mode="deduped"), gmm, seeds
        )
        for s, res in zip(seeds, batch):
            single = trainer.train(
                _cfg(compute_mode="deduped", seed=s), gmm
            )
            np.testing.assert_allclose(
                np.asarray(res.params_history),
                np.asarray(single.params_history),
                rtol=2e-5, atol=1e-6,
            )

    def test_seed_dependent_layout_refused(self, gmm):
        with pytest.raises(ValueError, match="seed-dependent"):
            trainer.train_batch(_cfg(scheme="cyccoded"), gmm, [0, 1])

    def test_measured_mode_refused(self, gmm):
        with pytest.raises(ValueError, match="measured"):
            trainer.train_batch(
                _cfg(arrival_mode="measured", compute_mode="faithful"),
                gmm, [0, 1],
            )

    def test_empty_seeds_refused(self, gmm):
        with pytest.raises(ValueError, match="at least one"):
            trainer.train_batch(_cfg(), gmm, [])


# ---------------------------------------------------------------------------
# ADVICE r5 bugfix regressions


class TestAdviceFixes:
    def test_partial_gather_tree_fixed_dtype_both_branches(self):
        """ADVICE r5 #1: a worker-holding process's (possibly bf16/f32
        mixed) weighted leaves and a workerless process's zero leaves must
        reach process_allgather in ONE identical dtype."""
        weighted = {
            "a": jnp.ones((3,), jnp.bfloat16),
            "b": jnp.ones((2, 2), jnp.float32),
        }
        zero_g = {
            "a": jnp.zeros((3,), jnp.bfloat16),
            "b": jnp.zeros((2, 2), jnp.float32),
        }
        holding = trainer._partial_gather_tree(weighted, zero_g)
        empty = trainer._partial_gather_tree(None, zero_g)
        for tree in (holding, empty):
            dtypes = {l.dtype for l in jax.tree.leaves(tree)}
            assert dtypes == {np.dtype(np.float32)}, dtypes
        for k in ("a", "b"):
            assert holding[k].shape == empty[k].shape
        assert (empty["a"] == 0).all()
        np.testing.assert_array_equal(
            holding["b"], np.ones((2, 2), np.float32)
        )

    def test_np_global_rejects_unaddressable_single_device(self, monkeypatch):
        """ADVICE r5 #2: SingleDeviceSharding + not fully addressable (an
        explicit placement on another host's device) must raise, not do a
        local read of a value this process does not hold."""
        from unittest import mock

        from jax.sharding import SingleDeviceSharding

        from erasurehead_tpu.data import sharding as sharding_lib

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        x = mock.MagicMock(spec=jax.Array)
        x.sharding = SingleDeviceSharding(jax.devices()[0])
        x.is_fully_addressable = False
        with pytest.raises(ValueError, match="does not own"):
            sharding_lib.np_global(x)
        # the host-local case still reads locally
        ok = jax.device_put(jnp.arange(3.0), jax.devices()[0])
        np.testing.assert_array_equal(
            sharding_lib.np_global(ok), np.arange(3.0)
        )

    def test_backend_rank_without_num_processes_raises(self, monkeypatch):
        """ADVICE r5 #3: a consumed rank env var with no process count
        must raise a ValueError naming JAX_NUM_PROCESSES, not forward the
        partial pair to jax.distributed.initialize."""
        from erasurehead_tpu.parallel import backend

        monkeypatch.setattr(backend, "_initialized", False)
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:9999")
        monkeypatch.setenv("JOB_COMPLETION_INDEX", "1")
        monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
        monkeypatch.delenv("MEGASCALE_COORDINATOR_ADDRESS", raising=False)
        called = []
        monkeypatch.setattr(
            jax.distributed, "initialize",
            lambda *a, **k: called.append((a, k)),
        )
        with pytest.raises(ValueError, match="JAX_NUM_PROCESSES"):
            backend.initialize_distributed()
        assert not called  # raised BEFORE touching jax.distributed
        # the full pair still initializes
        monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
        info = backend.initialize_distributed()
        assert called and called[0][1]["num_processes"] == 2
        assert info["process_count"] >= 1
        monkeypatch.setattr(backend, "_initialized", False)


# ---------------------------------------------------------------------------
# key-builder unit behavior


def test_dataset_token_is_stable_per_object(gmm):
    t1 = cache.dataset_token(gmm)
    t2 = cache.dataset_token(gmm)
    assert t1 == t2
    other = generate_gmm(64, 8, n_partitions=4, seed=1)
    assert cache.dataset_token(other) != t1


def test_tree_signature_distinguishes_shape_and_dtype():
    a = {"x": jnp.zeros((2, 3), jnp.float32)}
    b = {"x": jnp.zeros((2, 3), jnp.bfloat16)}
    c = {"x": jnp.zeros((3, 2), jnp.float32)}
    sigs = {cache.tree_signature(t) for t in (a, b, c)}
    assert len(sigs) == 3
