"""FieldOnehot: the fused pair-table lowering for one-hot field-structured
sparse data (ops/features.py) — the structure of the reference's real
workloads (src/arrange_real_data.py:145-205 covtype one-hot binning,
:34-91 amazon one-hot interactions).

Pins: structure inference, pair/single planning under the table cap,
matvec/rmatvec equality against dense for vector and matrix operands, the
sharding integration, and end-to-end trainer equality against the
PaddedRows path in both compute modes.
"""

import numpy as np
import pytest
import scipy.sparse as sps

import jax.numpy as jnp

from erasurehead_tpu.ops import features
from erasurehead_tpu.ops.features import (
    FieldOnehot,
    PaddedRows,
    _greedy_pairing,
    infer_field_sizes,
    matvec,
    rmatvec,
)


def _onehot_csr(n, sizes, seed=0, values=None):
    """Random exactly-one-hot-per-field CSR with the given block sizes."""
    rng = np.random.default_rng(seed)
    offs = np.concatenate([[0], np.cumsum(sizes)])
    local = np.stack(
        [rng.integers(0, b, n) for b in sizes], axis=1
    ).astype(np.int64)
    cols = (local + offs[:-1][None, :]).reshape(-1)
    rows = np.repeat(np.arange(n), len(sizes))
    data = np.ones(cols.size, np.float32) if values is None else values
    return sps.csr_matrix(
        (data, (rows, cols)), shape=(n, int(offs[-1]))
    )


class TestInference:
    def test_infers_block_sizes(self):
        csr = _onehot_csr(64, (5, 1, 9, 3))
        sizes = infer_field_sizes(csr)
        assert sizes is not None and len(sizes) == 4
        # observed blocks tile [0, max_col]; each inferred block is no
        # wider than the true one and the representation round-trips
        fo = FieldOnehot.from_scipy(csr, field_sizes=sizes)
        np.testing.assert_array_equal(
            np.asarray(fo.to_dense()), csr.toarray()
        )

    def test_rejects_nonuniform_rows(self):
        csr = _onehot_csr(16, (4, 4))
        csr.data[0] = 0.0
        csr.eliminate_zeros()  # row 0 loses an entry
        assert infer_field_sizes(csr) is None

    def test_rejects_non_unit_values(self):
        csr = _onehot_csr(16, (4, 4))
        csr.data[3] = 2.0
        assert infer_field_sizes(csr) is None

    def test_rejects_overlapping_blocks(self):
        # two "fields" drawing from the same column range
        rng = np.random.default_rng(0)
        n, B = 32, 6
        c1, c2 = rng.integers(0, B, n), rng.integers(0, B, n)
        c2 = np.where(c2 == c1, (c2 + 1) % B, c2)  # keep entries distinct
        rows = np.repeat(np.arange(n), 2)
        cols = np.stack([c1, c2], 1).reshape(-1)
        csr = sps.csr_matrix(
            (np.ones(2 * n, np.float32), (rows, cols)), shape=(n, B)
        )
        assert infer_field_sizes(csr) is None

    def test_from_scipy_raises_on_unstructured(self):
        rng = np.random.default_rng(1)
        csr = sps.random(
            32, 40, density=0.1, format="csr", random_state=np.random.RandomState(1)
        )
        with pytest.raises(ValueError):
            FieldOnehot.from_scipy(csr)


class TestPairing:
    def test_pairs_small_fields(self):
        plan = _greedy_pairing((4, 4, 4, 4))
        assert plan == (("pair", 0, 1), ("pair", 2, 3))

    def test_odd_field_count_leaves_a_single(self):
        plan = _greedy_pairing((4, 4, 4))
        assert plan == (("pair", 0, 1), ("single", 2))

    def test_cap_forces_singles(self):
        big = int(np.sqrt(features.PAIR_TABLE_CAP)) + 1
        # adjacent oversized pair splits; the greedy plan may still fuse a
        # big field with a small neighbor (big*4 fits the cap)
        plan = _greedy_pairing((big, big, 4, 4))
        assert plan == (("single", 0), ("pair", 1, 2), ("single", 3))
        assert _greedy_pairing((big, big)) == (("single", 0), ("single", 1))

    def test_every_field_covered_once_and_cap_respected(self):
        rng = np.random.default_rng(2)
        for _ in range(20):
            sizes = tuple(int(s) for s in rng.integers(1, 3000, rng.integers(1, 9)))
            seen = []
            for e in _greedy_pairing(sizes):
                seen.extend(e[1:])
                if e[0] == "pair":
                    assert sizes[e[1]] * sizes[e[2]] <= features.PAIR_TABLE_CAP
            assert sorted(seen) == list(range(len(sizes)))


class TestOps:
    @pytest.mark.parametrize(
        "sizes", [(7, 3, 5, 1, 8, 2), (4, 4, 4), (11,), (1, 1, 6000, 5)]
    )
    def test_matvec_rmatvec_match_dense(self, sizes):
        n = 48
        csr = _onehot_csr(n, sizes, seed=3)
        fo = FieldOnehot.from_scipy(csr)
        dense = jnp.asarray(csr.toarray())
        rng = np.random.default_rng(4)
        v = jnp.asarray(rng.standard_normal(csr.shape[1]).astype(np.float32))
        r = jnp.asarray(rng.standard_normal(n).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(matvec(fo, v)), np.asarray(matvec(dense, v)),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(rmatvec(fo, r)), np.asarray(rmatvec(dense, r)),
            rtol=1e-5, atol=1e-5,
        )

    def test_matrix_operands(self):
        sizes = (5, 3, 4)
        n, H = 32, 6
        csr = _onehot_csr(n, sizes, seed=5)
        fo = FieldOnehot.from_scipy(csr)
        dense = jnp.asarray(csr.toarray())
        rng = np.random.default_rng(6)
        V = jnp.asarray(
            rng.standard_normal((csr.shape[1], H)).astype(np.float32)
        )
        R = jnp.asarray(rng.standard_normal((n, H)).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(matvec(fo, V)), np.asarray(matvec(dense, V)),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(rmatvec(fo, R)), np.asarray(rmatvec(dense, R)),
            rtol=1e-5, atol=1e-5,
        )

    def test_grad_through_pair_tables_matches_closed_form(self):
        # grad_sum_auto differentiates THROUGH the fused gather; it must
        # agree with the hand-written rmatvec gradient
        from erasurehead_tpu.models.glm import LogisticModel

        sizes = (7, 3, 5, 4)
        n = 40
        csr = _onehot_csr(n, sizes, seed=11)
        fo = FieldOnehot.from_scipy(csr)
        rng = np.random.default_rng(12)
        beta = jnp.asarray(
            rng.standard_normal(csr.shape[1]).astype(np.float32)
        )
        y = jnp.asarray(np.sign(rng.standard_normal(n)).astype(np.float32))
        m = LogisticModel()
        np.testing.assert_allclose(
            np.asarray(m.grad_sum(beta, fo, y)),
            np.asarray(m.grad_sum_auto(beta, fo, y)),
            rtol=1e-5, atol=1e-5,
        )

    def test_matches_padded_rows(self):
        sizes = (9, 2, 6)
        csr = _onehot_csr(40, sizes, seed=7)
        fo = FieldOnehot.from_scipy(csr)
        pr = PaddedRows.from_scipy(csr)
        rng = np.random.default_rng(8)
        v = jnp.asarray(rng.standard_normal(csr.shape[1]).astype(np.float32))
        r = jnp.asarray(rng.standard_normal(40).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(matvec(fo, v)), np.asarray(matvec(pr, v)),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(rmatvec(fo, r)), np.asarray(rmatvec(pr, r)),
            rtol=1e-5, atol=1e-5,
        )

    def test_pytree_roundtrip_and_vmap(self):
        import jax

        sizes = (4, 6)
        csr = _onehot_csr(24, sizes, seed=9)
        fo = FieldOnehot.from_scipy(csr)
        leaves, treedef = jax.tree.flatten(fo)
        fo2 = jax.tree.unflatten(treedef, leaves)
        assert fo2.field_sizes == fo.field_sizes
        # batched leaves + vmap'd matvec: the trainer's per-slot pattern
        batched = FieldOnehot(
            jnp.stack([fo.local, fo.local]), fo.field_sizes, fo.n_cols
        )
        v = jnp.ones(fo.n_cols, jnp.float32)
        out = jax.vmap(lambda X: matvec(X, v))(batched)
        np.testing.assert_allclose(
            np.asarray(out[0]), np.asarray(matvec(fo, v)), rtol=1e-6
        )


class TestTrainingIntegration:
    def _cfg(self, **kw):
        from erasurehead_tpu.utils.config import RunConfig

        base = dict(
            scheme="approx",
            n_workers=6,
            n_stragglers=1,
            num_collect=4,
            rounds=6,
            dataset="artificial",
            update_rule="AGD",
            add_delay=True,
            seed=0,
        )
        base.update(kw)
        return RunConfig(**base)

    def _data(self, n_parts=6):
        from erasurehead_tpu.data.synthetic import generate_onehot

        return generate_onehot(240, 60, n_parts, n_fields=6, seed=0)

    @pytest.mark.parametrize("mode", ["faithful", "deduped"])
    def test_fields_matches_padded_trajectory(self, mode):
        from erasurehead_tpu.train import trainer

        ds = self._data()
        n, c = ds.X_train.shape
        pad = trainer.train(
            self._cfg(compute_mode=mode, n_rows=n, n_cols=c), ds
        )
        fld = trainer.train(
            self._cfg(
                compute_mode=mode, n_rows=n, n_cols=c,
                sparse_format="fields",
            ),
            ds,
        )
        np.testing.assert_allclose(
            np.asarray(pad.params_history[-1]),
            np.asarray(fld.params_history[-1]),
            rtol=2e-4, atol=2e-5,
        )

    def test_auto_falls_back_on_unstructured(self):
        from erasurehead_tpu.data import sharding
        from erasurehead_tpu.data.synthetic import Dataset

        rng = np.random.default_rng(10)
        X = sps.random(
            48, 30, density=0.2, format="csr",
            random_state=np.random.RandomState(10),
        )
        ds = Dataset(
            X_train=X,
            y_train=np.sign(rng.standard_normal(48)).astype(np.float32),
            X_test=X[:8],
            y_test=np.ones(8, np.float32),
        )
        Xp, _ = sharding.partition_stack(ds, 4, sparse_format="auto")
        assert isinstance(Xp, PaddedRows)
        with pytest.raises(ValueError, match="one-hot"):
            sharding.partition_stack(ds, 4, sparse_format="fields")

    def test_fields_selected_for_onehot(self):
        from erasurehead_tpu.data import sharding

        ds = self._data()
        Xp, _ = sharding.partition_stack(ds, 6, sparse_format="auto")
        assert isinstance(Xp, FieldOnehot)
        assert Xp.local.shape[0] == 6  # partition-major leading dim

    def test_fields_on_dense_data_rejected(self):
        from erasurehead_tpu.data import sharding
        from erasurehead_tpu.data.synthetic import generate_gmm

        ds = generate_gmm(64, 8, 4, seed=0)  # dense features
        with pytest.raises(ValueError, match="dense"):
            sharding.partition_stack(ds, 4, sparse_format="fields")
        Xp, _ = sharding.partition_stack(ds, 4, sparse_format="auto")
        assert isinstance(Xp, np.ndarray)

    def test_one_cap_governs_both_directions(self):
        # the shared cap budgets the per-slot scatter accumulators that
        # BOTH the hand-written rmatvec and jax.grad of the forward matvec
        # materialize (ops/features.py cap rationale): a pair over the cap
        # must go single in the matvec plan too
        sizes = (2048, 1200)
        assert sizes[0] * sizes[1] > features.PAIR_TABLE_CAP
        assert _greedy_pairing(sizes) == (("single", 0), ("single", 1))
        # covtype-class fields stay fused
        assert _greedy_pairing((1292, 1292))[0][0] == "pair"

    def test_flat_grad_singles_fallback_matches_per_slot(self):
        """The flat lowering (step.make_flat_grad_fn) on an amazon-class
        FieldOnehot whose pair table exceeds the cap — the singles-plan
        branch must agree with the per-slot vmap too."""
        import jax

        from erasurehead_tpu.models.glm import LogisticModel
        from erasurehead_tpu.parallel import step as step_lib
        from erasurehead_tpu.parallel.mesh import worker_mesh

        sizes = (2048, 1200)
        assert _greedy_pairing(sizes) == (("single", 0), ("single", 1))
        rng = np.random.default_rng(0)
        Wl, S, R = 4, 2, 16
        local = rng.integers(0, sizes, size=(Wl, S, R, 2)).astype(np.int32)
        X = FieldOnehot(jnp.asarray(local), sizes, int(sum(sizes)))
        y = jnp.asarray(
            np.sign(rng.standard_normal((Wl, S, R))), jnp.float32
        )
        w = jnp.asarray(rng.uniform(0.5, 1.5, (Wl, S)), jnp.float32)
        mesh = worker_mesh(4)
        model = LogisticModel()
        params = model.init_params(jax.random.key(1), int(sum(sizes)))
        base = step_lib.make_faithful_grad_fn(model, mesh)(params, X, y, w)
        flat = step_lib.make_flat_grad_fn(model, mesh)(params, X, y, w)
        np.testing.assert_allclose(
            np.asarray(flat), np.asarray(base), rtol=1e-5, atol=1e-5
        )

    def test_from_scipy_returns_host_arrays(self):
        csr = _onehot_csr(16, (4, 4))
        fo = FieldOnehot.from_scipy(csr)
        assert isinstance(fo.local, np.ndarray)  # no device round-trip in prep

    def test_from_scipy_does_not_mutate_caller(self):
        # two 0.5 entries at one position: canonicalization must happen on
        # a copy, not the caller's matrix
        rows = np.array([0, 0, 0, 1, 1])
        cols = np.array([1, 1, 3, 0, 2])
        data = np.array([0.5, 0.5, 1.0, 1.0, 1.0], np.float32)
        csr = sps.csr_matrix((data, (rows, cols)), shape=(2, 4))
        nnz_before = csr.nnz
        FieldOnehot.from_scipy(csr, field_sizes=(2, 2))
        assert csr.nnz == nnz_before

    def test_lanes_compose_with_fields(self):
        # fields + lanes is the composed lowering (lane-replicated pair
        # tables, ops/features._fields_matvec), not a conflict
        cfg = self._cfg(sparse_format="fields", sparse_lanes=8)
        assert cfg.sparse_format == "fields" and cfg.sparse_lanes == 8

    def test_auto_with_lanes_resolves_to_padded(self):
        # lanes pin the PaddedRows lowering — auto must not silently
        # swallow the lane request by picking FieldOnehot
        cfg = self._cfg(sparse_format="auto", sparse_lanes=8)
        assert cfg.sparse_format == "padded"
        assert cfg.sparse_lanes == 8

    def test_infer_rejects_zero_nnz(self):
        assert infer_field_sizes(sps.csr_matrix((5, 10))) is None

    def test_bad_format_rejected(self):
        with pytest.raises(ValueError, match="sparse_format"):
            self._cfg(sparse_format="pairs")


# hypothesis is optional in this image: gate the fuzz class so the rest
# of the module still collects without it
try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    _HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover
    _HAVE_HYPOTHESIS = False


class _Hyp:
    """Pass-through stand-ins so the class body parses without hypothesis
    (the skipif keeps its tests from ever running)."""

    def __getattr__(self, name):
        return self

    def __call__(self, *a, **k):
        return lambda f: f


if not _HAVE_HYPOTHESIS:
    given = settings = st = _Hyp()


@pytest.mark.skipif(not _HAVE_HYPOTHESIS, reason="hypothesis not installed")
class TestInferenceProperty:
    """Hypothesis fuzz: inference + construction round-trips on arbitrary
    field structures, and never mis-identifies perturbed matrices."""

    @staticmethod
    def _build(sizes, n, seed):
        return _onehot_csr(n, tuple(sizes), seed=seed)

    @given(
        sizes=st.lists(st.integers(1, 9), min_size=1, max_size=6),
        n=st.integers(2, 40),
        seed=st.integers(0, 10_000),
    )
    @settings(max_examples=60, deadline=None)
    def test_roundtrip_any_structure(self, sizes, n, seed):
        csr = self._build(sizes, n, seed)
        inferred = infer_field_sizes(csr)
        assert inferred is not None
        fo = FieldOnehot.from_scipy(csr, field_sizes=inferred)
        np.testing.assert_array_equal(
            np.asarray(fo.to_dense()), csr.toarray()
        )
        # matvec agrees with dense on the inferred representation
        rng = np.random.default_rng(seed)
        v = rng.standard_normal(csr.shape[1]).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(matvec(fo, jnp.asarray(v))),
            csr.toarray() @ v,
            rtol=1e-4, atol=1e-4,
        )

    @given(
        sizes=st.lists(st.integers(2, 9), min_size=2, max_size=5),
        n=st.integers(3, 30),
        seed=st.integers(0, 10_000),
        knock=st.integers(0, 10_000),
    )
    @settings(max_examples=60, deadline=None)
    def test_perturbed_value_never_misidentified(self, sizes, n, seed, knock):
        """Any non-unit value breaks the structure contract: inference must
        refuse rather than build a representation that drops the value."""
        csr = self._build(sizes, n, seed)
        csr.data[knock % csr.nnz] = 0.5
        assert infer_field_sizes(csr) is None


def test_fields_lanes_matches_scalar_and_scopes_to_matvec():
    """The composed fields x lanes margin lowering (pair tables halve the
    lookup count, lane replication vectorizes each lookup's addressing —
    the two independently-measured v5e wins, tools/profile_sparse.py) must
    agree with the scalar fields path to f32 tolerance, and — like the
    PaddedRows lanes — rewrite only the matvec direction: the scatter
    jaxpr must be identical with the knob on."""
    import jax

    sizes = (7, 3, 5, 1, 8, 2, 11)  # odd count: pairs + a single
    n = 52
    csr = _onehot_csr(n, sizes, seed=9)
    fo = FieldOnehot.from_scipy(csr)
    rng = np.random.default_rng(10)
    v = jnp.asarray(rng.standard_normal(csr.shape[1]).astype(np.float32))
    r = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    base_mv = np.asarray(matvec(fo, v))
    base_rmv = np.asarray(rmatvec(fo, r))
    mv_scalar = str(jax.make_jaxpr(lambda u: matvec(fo, u))(v))
    rmv_scalar = str(jax.make_jaxpr(lambda u: rmatvec(fo, u))(r))
    try:
        for L in (1, 8, 128):
            features.set_sparse_lanes(L)
            np.testing.assert_allclose(
                np.asarray(matvec(fo, v)), base_mv, rtol=1e-5, atol=1e-5
            )
            np.testing.assert_allclose(
                np.asarray(rmatvec(fo, r)), base_rmv, rtol=1e-5, atol=1e-5
            )
        features.set_sparse_lanes(8)
        mv_lanes = str(jax.make_jaxpr(lambda u: matvec(fo, u))(v))
        rmv_lanes = str(jax.make_jaxpr(lambda u: rmatvec(fo, u))(r))
        assert mv_lanes != mv_scalar  # margin takes the lane tables
        assert rmv_lanes == rmv_scalar  # scatter ignores the knob
        # matrix RHS (MLP first layer) keeps the per-field row-gather path
        V = jnp.asarray(rng.standard_normal((csr.shape[1], 4)).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(matvec(fo, V)),
            csr.toarray() @ np.asarray(V),
            rtol=1e-4, atol=1e-4,
        )
    finally:
        features.set_sparse_lanes(None)


def test_fields_lanes_oversized_single_falls_back_to_scalar(monkeypatch):
    """A single field whose lane-replicated [B, L] table would exceed
    LANE_TABLE_BYTES_CAP must be scalar-gathered, not replicated (ADVICE
    r3: singles used to bypass the byte budget entirely — a 200k-category
    field at L=1024 would build an ~800 MB transient). Exercised by
    shrinking the cap so a small field trips it; numerics must still match
    the scalar path exactly-enough."""
    import jax

    sizes = (9, 13)
    n = 40
    csr = _onehot_csr(n, sizes, seed=3)
    fo = FieldOnehot.from_scipy(csr)
    rng = np.random.default_rng(4)
    v = jnp.asarray(rng.standard_normal(csr.shape[1]).astype(np.float32))
    base_mv = np.asarray(matvec(fo, v))
    L = 8
    # cap below 9*L*4 bytes: the plan degenerates to singles AND both
    # singles' replicated tables are over-budget -> pure scalar gathers
    monkeypatch.setattr(features, "LANE_TABLE_BYTES_CAP", 9 * L * 4 - 1)
    try:
        features.set_sparse_lanes(L)
        np.testing.assert_allclose(
            np.asarray(matvec(fo, v)), base_mv, rtol=1e-5, atol=1e-5
        )
        jaxpr = str(jax.make_jaxpr(lambda u: matvec(fo, u))(v))
        assert "optimization_barrier" not in jaxpr  # no replicated tables
        # mixed case: cap exactly fits the 9-field's [9, L] table but not
        # the 13-field's -> one lane table + one scalar gather, still exact
        monkeypatch.setattr(features, "LANE_TABLE_BYTES_CAP", 9 * L * 4)
        np.testing.assert_allclose(
            np.asarray(matvec(fo, v)), base_mv, rtol=1e-5, atol=1e-5
        )
        jaxpr = str(jax.make_jaxpr(lambda u: matvec(fo, u))(v))
        assert jaxpr.count("optimization_barrier") == 1
    finally:
        features.set_sparse_lanes(None)


def test_runconfig_accepts_fields_with_lanes():
    """fields + sparse_lanes is the composed lowering, not an error; auto +
    lanes still pins padded (historical measurement attribution)."""
    from erasurehead_tpu.utils.config import RunConfig

    cfg = RunConfig(
        scheme="approx", n_workers=6, n_stragglers=1, num_collect=4,
        n_rows=60, n_cols=30, sparse_format="fields", sparse_lanes=8,
    )
    assert cfg.sparse_format == "fields" and cfg.sparse_lanes == 8
    cfg2 = RunConfig(
        scheme="approx", n_workers=6, n_stragglers=1, num_collect=4,
        n_rows=60, n_cols=30, sparse_format="auto", sparse_lanes=8,
    )
    assert cfg2.sparse_format == "padded"


def test_lane_aware_pairing_plan_respects_byte_budget():
    """fields_margin_plan shrinks the pair cap by lane width: a pair whose
    [entries, L] replicated table would exceed LANE_TABLE_BYTES_CAP falls
    back to singles, so wide lanes cannot blow the memory budget."""
    from erasurehead_tpu.ops.features import (
        LANE_TABLE_BYTES_CAP, fields_margin_plan,
    )

    sizes = (1292, 1292)  # covtype-like: 1.67M-entry pair table
    assert fields_margin_plan(sizes, None) == (("pair", 0, 1),)
    assert fields_margin_plan(sizes, 8) == (("pair", 0, 1),)  # 53 MB: fits
    # 1.67M x 1024 x 4B ~= 6.8 GB: must fall back to singles
    assert fields_margin_plan(sizes, 1024) == (("single", 0), ("single", 1))
    for L in (1, 8, 128, 1024):
        for e in fields_margin_plan(sizes, L):
            if e[0] == "pair":
                table = sizes[e[1]] * sizes[e[2]]
                assert table * L * 4 <= LANE_TABLE_BYTES_CAP


def test_autodiff_through_lane_path_matches_closed_form():
    """jax.grad through the lane matvec must equal the hand-written
    gradient: the custom_vjp pins the backward pass to the scalar-scatter
    rmatvec (the lane gather's automatic transpose would be a lane-wide
    table scatter — the op the v5e profile measured as a net loss and the
    PAIR_TABLE_CAP budget excludes)."""
    import jax

    from erasurehead_tpu.models.glm import LogisticModel

    sizes = (7, 3, 5, 4, 9)
    n = 44
    csr = _onehot_csr(n, sizes, seed=21)
    fo = FieldOnehot.from_scipy(csr)
    rng = np.random.default_rng(22)
    beta = jnp.asarray(rng.standard_normal(csr.shape[1]).astype(np.float32))
    y = jnp.asarray(np.sign(rng.standard_normal(n)).astype(np.float32))
    m = LogisticModel()
    closed = np.asarray(m.grad_sum(beta, fo, y))
    try:
        features.set_sparse_lanes(8)
        auto = np.asarray(m.grad_sum_auto(beta, fo, y))
        # and the backward jaxpr contains no lane-wide scatter: its only
        # scatter shapes match the scalar path's
        jaxpr_lanes = str(
            jax.make_jaxpr(lambda b: m.grad_sum_auto(b, fo, y))(beta)
        )
    finally:
        features.set_sparse_lanes(None)
    np.testing.assert_allclose(auto, closed, rtol=1e-4, atol=1e-4)
    # structural pin: the backward contains no lane-wide scatter — every
    # scatter in the traced program produces a scalar-path shape (the
    # forward's [entries, 8] arrays come from the barrier table, which is
    # gather-only)
    for line in jaxpr_lanes.splitlines():
        if "scatter" in line:
            assert ",8]" not in line.replace(" ", ""), line


def test_onehot_scatter_matches_pairs_and_dense():
    """set_fields_scatter("onehot") — segment-sum as per-field one-hot MXU
    matmuls — must agree with the pairs scatter and the dense transpose to
    f32 reduction tolerance, cover the chunk-padding edge (n not a
    multiple of the chunk), and leave matrix operands and the margin
    untouched."""
    sizes = (7, 3, 5, 1, 8, 2, 11)
    n = 531  # prime-ish: exercises chunk padding
    csr = _onehot_csr(n, sizes, seed=31)
    fo = FieldOnehot.from_scipy(csr)
    dense = jnp.asarray(csr.toarray())
    rng = np.random.default_rng(32)
    r = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    v = jnp.asarray(rng.standard_normal(csr.shape[1]).astype(np.float32))
    base = np.asarray(rmatvec(fo, r))
    try:
        features.set_fields_scatter("onehot")
        oh = np.asarray(rmatvec(fo, r))
        mv = np.asarray(matvec(fo, v))
        R2 = jnp.asarray(rng.standard_normal((n, 3)).astype(np.float32))
        mat = np.asarray(rmatvec(fo, R2))
    finally:
        features.set_fields_scatter("pairs")
    np.testing.assert_allclose(oh, base, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        oh, np.asarray(rmatvec(dense, r)), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        mv, np.asarray(matvec(dense, v)), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        mat, np.asarray(rmatvec(dense, R2)), rtol=1e-4, atol=1e-4
    )
    with pytest.raises(ValueError):
        features.set_fields_scatter("bogus")


def test_onehot_scatter_trainer_trajectory_matches_pairs():
    """End-to-end: the onehot-scatter run's trajectory must match the
    pairs-scatter run at the canonical W=30 AGC config (flat lowering,
    the production fields path)."""
    from erasurehead_tpu.data.synthetic import generate_onehot
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    W = 30
    data = generate_onehot(2640, 166, n_partitions=W, n_fields=6, seed=3)

    def run(mode):
        cfg = RunConfig(
            scheme="approx", n_workers=W, n_stragglers=2, num_collect=15,
            rounds=8, n_rows=2640, n_cols=166, update_rule="AGD",
            dataset="covtype", add_delay=True, sparse_format="fields",
            fields_scatter=mode, flat_grad="on", seed=0,
        )
        return trainer.train(cfg, data)

    a = run("pairs")
    b = run("onehot")
    pa = np.asarray(a.final_params)
    pb = np.asarray(b.final_params)
    np.testing.assert_allclose(pb, pa, rtol=1e-4, atol=1e-5)


def test_onehot_margin_matches_tables_and_dense():
    """set_fields_margin("onehot") — per-field one-hot MXU matmuls — must
    agree with the pair-table margin and the dense product, and autodiff
    through it (whose transpose is the one-hot scatter form) must match
    the closed-form gradient."""
    import jax

    from erasurehead_tpu.models.glm import LogisticModel

    sizes = (7, 3, 5, 1, 8, 2, 11)
    n = 531
    csr = _onehot_csr(n, sizes, seed=41)
    fo = FieldOnehot.from_scipy(csr)
    dense = jnp.asarray(csr.toarray())
    rng = np.random.default_rng(42)
    v = jnp.asarray(rng.standard_normal(csr.shape[1]).astype(np.float32))
    y = jnp.asarray(np.sign(rng.standard_normal(n)).astype(np.float32))
    base = np.asarray(matvec(fo, v))
    m = LogisticModel()
    closed = np.asarray(m.grad_sum(v, fo, y))
    try:
        features.set_fields_margin("onehot")
        oh = np.asarray(matvec(fo, v))
        auto = np.asarray(m.grad_sum_auto(v, fo, y))
    finally:
        features.set_fields_margin("tables")
    np.testing.assert_allclose(oh, base, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        oh, np.asarray(matvec(dense, v)), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(auto, closed, rtol=1e-4, atol=1e-4)
    with pytest.raises(ValueError):
        features.set_fields_margin("bogus")


def test_full_mxu_fields_trainer_trajectory_matches_baseline():
    """End-to-end: onehot margin + onehot scatter (the no-serialized-
    lookups sparse step) must match the tables+pairs baseline trajectory
    at the canonical W=30 AGC config under the flat lowering."""
    from erasurehead_tpu.data.synthetic import generate_onehot
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    W = 30
    data = generate_onehot(2640, 166, n_partitions=W, n_fields=6, seed=5)

    def run(margin, scatter):
        cfg = RunConfig(
            scheme="approx", n_workers=W, n_stragglers=2, num_collect=15,
            rounds=8, n_rows=2640, n_cols=166, update_rule="AGD",
            dataset="covtype", add_delay=True, sparse_format="fields",
            fields_margin=margin, fields_scatter=scatter, flat_grad="on",
            seed=0,
        )
        return trainer.train(cfg, data)

    a = run("tables", "pairs")
    b = run("onehot", "onehot")
    np.testing.assert_allclose(
        np.asarray(b.final_params), np.asarray(a.final_params),
        rtol=1e-4, atol=1e-5,
    )


def test_lanes_with_onehot_margin_rejected():
    """sparse_lanes has no effect under fields_margin='onehot' (no gathers
    to widen) — the config must reject the combination rather than record
    a lane width that never ran (measurement attribution)."""
    from erasurehead_tpu.utils.config import RunConfig

    with pytest.raises(ValueError, match="sparse_lanes has no effect"):
        RunConfig(
            scheme="approx", n_workers=6, n_stragglers=1, num_collect=4,
            n_rows=60, n_cols=30, sparse_format="fields",
            fields_margin="onehot", sparse_lanes=8,
        )
