"""End-to-end training tests on the 8-device CPU mesh.

The load-bearing equivalences: every *exact* scheme (cyclic MDS, FRC,
partial variants — and AGC at full collection) decodes the identical
full-batch gradient, so their parameter trajectories must coincide with the
uncoded baseline's; and the faithful / deduped compute modes must agree.
A numpy oracle pins the GD/AGD update semantics to the reference formulas.
"""

import jax
import numpy as np
import pytest

from erasurehead_tpu.data.synthetic import generate_gmm, generate_linear
from erasurehead_tpu.models.glm import LogisticModel
from erasurehead_tpu.parallel.mesh import worker_mesh
from erasurehead_tpu.train import evaluate, trainer
from erasurehead_tpu.utils.config import ModelKind, RunConfig, Scheme, UpdateRule

W, ROUNDS = 8, 12
N_ROWS, N_COLS = 512, 24


@pytest.fixture(scope="module")
def gmm():
    return generate_gmm(N_ROWS, N_COLS, n_partitions=W, seed=0)


def _cfg(**kw):
    base = dict(
        scheme=Scheme.NAIVE,
        n_workers=W,
        n_stragglers=1,
        rounds=ROUNDS,
        n_rows=N_ROWS,
        n_cols=N_COLS,
        update_rule=UpdateRule.GD,
        lr_schedule=0.5,
        add_delay=True,
        seed=3,
    )
    base.update(kw)
    return RunConfig(**base)


def _history(res):
    return np.asarray(res.params_history)


# ---------------------------------------------------------------------------


def test_naive_matches_numpy_oracle(gmm):
    """Full-batch GD on device == reference update formula in float64 numpy."""
    cfg = _cfg()
    res = trainer.train(cfg, gmm)
    # oracle: the reference master's update loop (src/naive.py:103-126)
    n = res.n_train
    X, y = gmm.X_train[:n].astype(np.float64), gmm.y_train[:n].astype(np.float64)
    model = LogisticModel()
    beta = np.asarray(
        model.init_params(jax.random.key(cfg.seed), N_COLS), np.float64
    )
    alpha, lr = cfg.effective_alpha, cfg.resolve_lr_schedule()
    hist = []
    for i in range(ROUNDS):
        predy = X @ beta
        g = -X.T @ (y / (np.exp(predy * y) + 1.0))
        beta = (1 - 2 * alpha * lr[i]) * beta - (lr[i] / n) * g
        hist.append(beta.copy())
    ours = _history(res)
    assert np.allclose(ours, np.stack(hist), atol=2e-3), np.abs(
        ours - np.stack(hist)
    ).max()


def test_agd_matches_numpy_oracle(gmm):
    cfg = _cfg(update_rule=UpdateRule.AGD)
    res = trainer.train(cfg, gmm)
    n = res.n_train
    X, y = gmm.X_train[:n].astype(np.float64), gmm.y_train[:n].astype(np.float64)
    model = LogisticModel()
    beta = np.asarray(
        model.init_params(jax.random.key(cfg.seed), N_COLS), np.float64
    )
    u = np.zeros_like(beta)
    alpha, lr = cfg.effective_alpha, cfg.resolve_lr_schedule()
    hist = []
    for i in range(ROUNDS):
        predy = X @ beta
        g = -X.T @ (y / (np.exp(predy * y) + 1.0))
        # src/naive.py:116-122
        theta = 2.0 / (i + 2.0)
        ytmp = (1 - theta) * beta + theta * u
        beta_next = ytmp - (lr[i] / n) * g - 2 * alpha * lr[i] * beta
        u = beta + (beta_next - beta) / theta
        beta = beta_next
        hist.append(beta.copy())
    assert np.allclose(_history(res), np.stack(hist), atol=2e-3)


@pytest.mark.parametrize(
    "scheme,extra",
    [
        (Scheme.CYCLIC_MDS, dict(n_stragglers=2)),
        (Scheme.FRC, dict(n_stragglers=3)),
        (Scheme.APPROX, dict(num_collect=W, n_stragglers=3)),  # full collection => exact
        (Scheme.PARTIAL_CYCLIC, dict(partitions_per_worker=4, n_stragglers=1)),
        (Scheme.PARTIAL_FRC, dict(partitions_per_worker=4, n_stragglers=1)),
    ],
)
def test_exact_schemes_match_naive_trajectory(gmm, scheme, extra):
    if scheme in (Scheme.PARTIAL_CYCLIC, Scheme.PARTIAL_FRC):
        # partial layouts use (n_sep+1)*W = 24 global partitions; pick a row
        # count divisible by both 8 and 24 so naive and partial train on the
        # identical row set
        data = generate_gmm(768, N_COLS, n_partitions=W, seed=0)
    else:
        data = gmm
    base = trainer.train(_cfg(n_rows=data.n_samples), data)
    res = trainer.train(_cfg(scheme=scheme, n_rows=data.n_samples, **extra), data)
    assert np.allclose(_history(res), _history(base), atol=5e-3), (
        scheme,
        np.abs(_history(res) - _history(base)).max(),
    )


def test_faithful_equals_deduped(gmm):
    for scheme, extra in [
        (Scheme.APPROX, dict(num_collect=5)),
        (Scheme.CYCLIC_MDS, {}),
    ]:
        f = trainer.train(_cfg(scheme=scheme, compute_mode="faithful", **extra), gmm)
        d = trainer.train(_cfg(scheme=scheme, compute_mode="deduped", **extra), gmm)
        assert np.allclose(_history(f), _history(d), atol=2e-3), scheme


def test_agc_partial_collection_still_converges(gmm):
    res = trainer.train(
        _cfg(scheme=Scheme.APPROX, num_collect=4, rounds=30), gmm
    )
    ev = evaluate.replay(
        trainer.build_model(res.config),
        res.config.model,
        res.params_history,
        gmm.X_train,
        gmm.y_train,
        gmm.X_test,
        gmm.y_test,
    )
    assert ev.training_loss[-1] < 0.9 * ev.training_loss[0]
    assert ev.auc[-1] > 0.65
    # AGC collects at most num_collect workers per round
    assert (res.collected.sum(axis=1) <= 4).all()


def test_sixteen_workers_on_eight_devices(gmm):
    """More logical workers than devices: 2 workers per chip."""
    data16 = generate_gmm(N_ROWS, N_COLS, n_partitions=16, seed=0)
    res = trainer.train(
        _cfg(n_workers=16, scheme=Scheme.APPROX, num_collect=10, n_stragglers=3),
        data16,
    )
    assert _history(res).shape == (ROUNDS, N_COLS)
    assert np.isfinite(_history(res)).all()


def test_avoidstragg_runs_and_converges(gmm):
    res = trainer.train(
        _cfg(scheme=Scheme.AVOID_STRAGGLERS, rounds=30, update_rule="AGD"), gmm
    )
    ev = evaluate.replay(
        trainer.build_model(res.config),
        res.config.model,
        res.params_history,
        gmm.X_train,
        gmm.y_train,
        gmm.X_test,
        gmm.y_test,
    )
    assert ev.training_loss[-1] < ev.training_loss[0]


def test_linear_model_mse_decreases():
    data = generate_linear(N_ROWS, N_COLS, n_partitions=W, seed=1)
    cfg = _cfg(model=ModelKind.LINEAR, lr_schedule=0.05, rounds=30)
    res = trainer.train(cfg, data)
    ev = evaluate.replay(
        trainer.build_model(cfg),
        cfg.model,
        res.params_history,
        data.X_train[: res.n_train],
        data.y_train[: res.n_train],
        data.X_test,
        data.y_test,
    )
    assert ev.testing_loss[-1] < ev.testing_loss[0]
    assert np.isnan(ev.auc).all()


def test_mlp_trains_under_coding(gmm):
    cfg = _cfg(
        model=ModelKind.MLP,
        scheme=Scheme.APPROX,
        num_collect=6,
        lr_schedule=1.0,
        rounds=20,
    )
    res = trainer.train(cfg, gmm)
    model = trainer.build_model(cfg)
    ev = evaluate.replay(
        model,
        cfg.model,
        res.params_history,
        gmm.X_train,
        gmm.y_train,
        gmm.X_test,
        gmm.y_test,
    )
    assert ev.training_loss[-1] < ev.training_loss[0]


def test_sim_time_ordering(gmm):
    """AGC's simulated clock must beat naive's under the same schedule —
    the reference's headline claim."""
    naive = trainer.train(_cfg(rounds=30), gmm)
    agc = trainer.train(
        _cfg(scheme=Scheme.APPROX, num_collect=4, rounds=30), gmm
    )
    assert agc.sim_total_time < naive.sim_total_time
    # per-round: kth order statistic <= max
    assert (agc.timeset <= naive.timeset + 1e-12).all()


def test_avoidstragg_sim_clock_beats_naive(gmm):
    """Regression: avoidstragg must stop at the first W-s arrivals — its
    simulated clock (kth order statistic) strictly beats naive's max under
    the shared schedule (bug: layout carried n_stragglers=0)."""
    naive = trainer.train(_cfg(rounds=20), gmm)
    av = trainer.train(
        _cfg(scheme=Scheme.AVOID_STRAGGLERS, n_stragglers=2, rounds=20,
             update_rule="AGD"),
        gmm,
    )
    assert av.sim_total_time < naive.sim_total_time
    assert (av.collected.sum(axis=1) == W - 2).all()


def test_bfloat16_data_dtype(gmm):
    """cfg.dtype casts the data only: params/updates stay float32, the run
    stays finite, and the trajectory tracks the f32 run to bf16 precision."""
    import jax.numpy as jnp

    from erasurehead_tpu.utils.config import RunConfig

    hists = {}
    for dt in ("float32", "bfloat16"):
        cfg = RunConfig(
            scheme="approx", n_workers=W, n_stragglers=1, num_collect=6,
            rounds=5, n_rows=N_ROWS, n_cols=N_COLS,
            lr_schedule=1.0, update_rule="AGD", add_delay=True, seed=0,
            dtype=dt,
        )
        res = trainer.train(cfg, gmm, mesh=worker_mesh(4))
        assert np.asarray(res.params_history).dtype == np.float32
        hists[dt] = np.asarray(res.params_history, np.float32)
    assert np.isfinite(hists["bfloat16"]).all()
    rel = np.max(
        np.abs(hists["float32"] - hists["bfloat16"])
        / (np.abs(hists["float32"]) + 1e-6)
    )
    assert rel < 0.15  # bf16 quantization drift, not divergence


def test_dense_margin_cols_trajectory_matches_direct(gmm):
    """cfg.dense_margin_cols (the tileable-matmul margin lowering) is
    exact — column 0 of the replicated-operand matmul is the same dot at
    the same precision — so the trajectory must match the direct lowering
    to f32 reduction tolerance, and the knob must not leak out of the run
    (the _with_run_sparse_lanes scoping)."""
    from erasurehead_tpu.ops import features
    from erasurehead_tpu.utils.config import RunConfig

    hists = {}
    for cols in (None, 8):
        cfg = RunConfig(
            scheme="approx", n_workers=W, n_stragglers=1, num_collect=6,
            rounds=5, n_rows=N_ROWS, n_cols=N_COLS,
            lr_schedule=1.0, update_rule="AGD", add_delay=True, seed=0,
            dense_margin_cols=cols,
        )
        res = trainer.train(cfg, gmm, mesh=worker_mesh(4))
        hists[cols] = np.asarray(res.params_history, np.float32)
    np.testing.assert_allclose(hists[8], hists[None], rtol=1e-5, atol=1e-6)
    assert features.get_dense_margin_cols() is None  # restored after run


class TestDenseFlatLowering:
    """parallel/step.make_flat_grad_fn: the flat-stack closed-form GLM
    lowering is the same math as the per-slot vmap (sum_s w_s(-X_s^T r_s)
    == -Xf^T(w_row*r)) in a different reduction order — grads allclose,
    trajectories allclose, and the knob is rejected off the closed-form
    dense path."""

    def _grad_pair(
        self, scheme="approx", mode="faithful", sparse_format=None, **extra
    ):
        from erasurehead_tpu.parallel import step as step_lib
        from erasurehead_tpu.train.trainer import build_layout, build_model
        from erasurehead_tpu.data.sharding import shard_run_data

        cfg = _cfg(
            scheme=scheme, n_stragglers=1, compute_mode=mode, **extra
        )
        if sparse_format is None:
            data = generate_gmm(N_ROWS, N_COLS, n_partitions=W, seed=0)
        else:
            from erasurehead_tpu.data.synthetic import generate_onehot

            data = generate_onehot(
                N_ROWS, 60, n_partitions=W, n_fields=6, seed=0
            )
        layout = build_layout(cfg)
        model = build_model(cfg)
        mesh = worker_mesh(4)
        sharded = shard_run_data(
            data, layout, mesh, faithful=(mode == "faithful"),
            sparse_format=sparse_format or "padded",
        )
        if mode == "faithful":
            base = step_lib.make_faithful_grad_fn(model, mesh)
            X, y = sharded.Xw, sharded.yw
            w = np.random.default_rng(0).uniform(0.5, 1.5, y.shape[:2])
        else:
            base = step_lib.make_deduped_grad_fn(model, mesh)
            X, y = sharded.Xp, sharded.yp
            w = np.random.default_rng(0).uniform(0.5, 1.5, y.shape[:1])
        flat = step_lib.make_flat_grad_fn(model, mesh)
        n_features = data.X_train.shape[1]
        params = model.init_params(jax.random.key(1), n_features)
        import jax.numpy as jnp

        wj = jnp.asarray(w, jnp.float32)
        return np.asarray(base(params, X, y, wj)), np.asarray(
            flat(params, X, y, wj)
        )

    @pytest.mark.parametrize("mode", ["faithful", "deduped"])
    def test_flat_grad_matches_per_slot(self, mode):
        g0, g1 = self._grad_pair(mode=mode)
        np.testing.assert_allclose(g1, g0, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("fmt", ["padded", "fields"])
    @pytest.mark.parametrize("mode", ["faithful", "deduped"])
    def test_flat_grad_matches_per_slot_sparse(self, mode, fmt):
        """The flat lowering on sparse stacks: one scatter accumulator
        instead of a vmapped per-slot batch of them — same gradient."""
        g0, g1 = self._grad_pair(mode=mode, sparse_format=fmt)
        np.testing.assert_allclose(g1, g0, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("model", ["logistic", "linear"])
    def test_trajectory_matches_per_slot(self, gmm, model):
        data = gmm if model == "logistic" else generate_linear(
            N_ROWS, N_COLS, n_partitions=W, seed=0
        )
        hists = {}
        for flat in ("off", "on"):
            cfg = _cfg(
                scheme=Scheme.APPROX, model=model, n_stragglers=1,
                num_collect=6, flat_grad=flat,
                lr_schedule=0.2 if model == "linear" else 0.5,
            )
            res = trainer.train(cfg, data, mesh=worker_mesh(4))
            hists[flat] = np.asarray(res.params_history, np.float32)
        np.testing.assert_allclose(
            hists["on"], hists["off"], rtol=2e-4, atol=2e-5
        )

    def test_trajectory_matches_per_slot_mds(self, gmm):
        """MDS decode weights (per-message lstsq solutions, not 0/1 masks)
        fold through the flat lowering's per-row scale identically."""
        hists = {}
        for flat in ("off", "on"):
            cfg = _cfg(
                scheme=Scheme.CYCLIC_MDS, n_stragglers=2, flat_grad=flat,
            )
            res = trainer.train(cfg, gmm, mesh=worker_mesh(4))
            hists[flat] = np.asarray(res.params_history, np.float32)
        # looser than the approx/frc case above: MDS decode weights are
        # lstsq solutions with large alternating-sign coefficients, so the
        # flat lowering's different f32 reduction order cancels
        # catastrophically and the 12-round trajectory amplifies it
        # (observed max rel ~2.4e-3 on the CPU backend)
        np.testing.assert_allclose(
            hists["on"], hists["off"], rtol=5e-3, atol=2e-5
        )

    def test_flat_on_bf16_data_trains(self, gmm):
        cfg = _cfg(
            scheme=Scheme.APPROX, n_stragglers=1, num_collect=6,
            flat_grad="on", dtype="bfloat16",
        )
        res = trainer.train(cfg, gmm, mesh=worker_mesh(4))
        assert np.isfinite(np.asarray(res.params_history)).all()

    def test_flat_on_rejects_mlp(self, gmm):
        cfg = _cfg(model="mlp", flat_grad="on", lr_schedule=0.01)
        with pytest.raises(ValueError, match="flat_grad"):
            trainer.train(cfg, gmm, mesh=worker_mesh(4))

    def test_config_validates_values(self):
        with pytest.raises(ValueError, match="flat_grad"):
            _cfg(flat_grad="yes")

    def test_auto_resolution_is_measurement_pinned(self):
        """auto -> flat for FieldOnehot (per-slot measured catastrophic on
        v5e); dense/PaddedRows follow FLAT_GRAD_DEFAULT until their races
        land; autodiff families never resolve flat."""
        import jax.numpy as jnp

        from erasurehead_tpu.models.glm import LogisticModel
        from erasurehead_tpu.models.mlp import MLPModel
        from erasurehead_tpu.ops.features import FieldOnehot, PaddedRows
        from erasurehead_tpu.parallel import step as step_lib

        glm = LogisticModel()
        dense = jnp.zeros((2, 4, 8))
        padded = PaddedRows(
            jnp.zeros((2, 4, 3), jnp.int32), jnp.ones((2, 4, 3)), 8
        )
        fields = FieldOnehot(jnp.zeros((2, 4, 2), jnp.int32), (4, 4), 8)
        assert step_lib.resolve_flat_grad("auto", glm, fields)
        assert (
            step_lib.resolve_flat_grad("auto", glm, dense)
            == step_lib.FLAT_GRAD_DEFAULT
        )
        assert (
            step_lib.resolve_flat_grad("auto", glm, padded)
            == step_lib.FLAT_GRAD_DEFAULT
        )
        assert not step_lib.resolve_flat_grad("off", glm, fields)
        assert step_lib.resolve_flat_grad("on", glm, dense)
        assert not step_lib.resolve_flat_grad("auto", MLPModel(), dense)

    def test_flat_on_conflicts_with_pallas_on(self, gmm):
        cfg = _cfg(flat_grad="on", use_pallas="on")
        with pytest.raises(ValueError, match="mutually exclusive"):
            trainer.train(cfg, gmm, mesh=worker_mesh(4))


def test_adam_trains_mlp(gmm):
    """Adam (beyond-reference rule) on the MLP under AGC coding."""
    cfg = RunConfig(
        scheme="approx", model="mlp", n_workers=W, n_stragglers=1,
        num_collect=6, rounds=25, n_rows=N_ROWS, n_cols=N_COLS,
        lr_schedule=3e-3, update_rule="ADAM", add_delay=True, seed=0,
    )
    res = trainer.train(cfg, gmm, mesh=worker_mesh(4))
    model = trainer.build_model(cfg)
    import jax.numpy as jnp

    Xt, yt = jnp.asarray(gmm.X_test), jnp.asarray(gmm.y_test)
    first = jax.tree.map(lambda l: l[0], res.params_history)
    last = res.final_params
    l0 = float(model.loss_mean(first, Xt, yt))
    l1 = float(model.loss_mean(last, Xt, yt))
    assert np.isfinite(l1) and l1 < l0 * 0.8


def test_attention_model_trains_under_agc():
    """The single-block attention classifier (models/attention.py) trains
    under AGC gradient coding exactly like the GLM/MLP families: pytree
    grads, additive over row shards, loss decreases."""
    import jax
    import jax.numpy as jnp

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.models.attention import AttentionModel
    from erasurehead_tpu.utils.config import RunConfig

    Wa, F = 4, 64  # rows reshape to [8 tokens, 8 dims]
    ds = generate_gmm(64 * Wa, F, n_partitions=Wa, seed=0)
    cfg = RunConfig(
        scheme="approx", model="attention", n_workers=Wa, n_stragglers=1,
        num_collect=3, rounds=20, n_rows=64 * Wa, n_cols=F,
        # lr 0.1: Adam at 0.5 overshoots with CORRECT sharded grads (the
        # step's old per-slot jax.grad-under-vmap path silently mixed
        # workers' slots on multi-device meshes — fixed by
        # step._weighted_loss_grad, pinned in test_step_grads_* below)
        lr_schedule=0.1, update_rule="ADAM", add_delay=True, seed=0,
    )
    res = trainer.train(cfg, ds)
    model = AttentionModel()
    Xt = jnp.asarray(ds.X_train)
    yt = jnp.asarray(ds.y_train)
    first = jax.tree.map(lambda l: l[0], res.params_history)
    last = jax.tree.map(lambda l: l[-1], res.params_history)
    l0 = float(model.loss_mean(first, Xt, yt))
    l1 = float(model.loss_mean(last, Xt, yt))
    assert np.isfinite(l1)
    assert l1 < l0, (l0, l1)


def test_deadline_scheme_trains_and_tolerates_death(gmm):
    """scheme='deadline' end to end: converges under straggling, and a
    permanently dead worker needs NO failover plan — the rule is
    inherently failure-tolerant (it just never collects the dead)."""
    from erasurehead_tpu.parallel import failures

    cfg = RunConfig(
        scheme="deadline", deadline=1.0, n_workers=W, n_stragglers=0,
        rounds=30, n_rows=N_ROWS, n_cols=N_COLS, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    res = trainer.train(cfg, gmm, mesh=worker_mesh(4))
    hist = np.asarray(res.params_history)
    assert np.isfinite(hist).all()
    # under Exp(0.5) delays and deadline 1.0, rounds are capped at 1.0
    assert (res.timeset <= 1.0 + 1e-9).all()
    # a dead worker: the run stays feasible with no plan rewrite
    arrivals = failures.inject_worker_death(
        trainer.default_arrivals(cfg), {W - 1: 3}
    )
    sched, report = failures.plan_run(
        cfg.scheme, trainer.build_layout(cfg), arrivals,
        deadline=cfg.deadline,
    )
    assert report.all_feasible
    res2 = trainer.train(cfg, gmm, arrivals=arrivals, schedule=sched)
    assert not res2.collected[3:, W - 1].any()
    assert np.isfinite(np.asarray(res2.params_history)).all()


def test_ten_thousand_round_run_end_to_end(gmm):
    """Full-trainer scaling: 10,000 rounds through the scan trainer in one
    piece — control plane, schedule build, device scan, and history
    assembly all stay far from O(R)-Python territory (measured ~4.5s
    end-to-end on a dev host; the generous bound rules out regressions)."""
    import time

    cfg = RunConfig(
        scheme="approx", n_workers=W, n_stragglers=1, num_collect=6,
        rounds=10_000, n_rows=N_ROWS, n_cols=N_COLS, lr_schedule=0.5,
        update_rule="AGD", add_delay=True, seed=0,
    )
    t0 = time.perf_counter()
    res = trainer.train(cfg, gmm, mesh=worker_mesh(4), measure=False)
    took = time.perf_counter() - t0
    h = np.asarray(res.params_history)
    assert h.shape[0] == 10_000 and np.isfinite(h).all()
    assert took < 90, took  # ~4.5s measured; huge headroom for loaded CI


def test_step_grads_match_oracle_multidevice():
    """The sharded step's decoded gradient == the host weighted sum of
    per-slot grads, for BOTH model classes, on multi-device meshes.

    Regression pin for a silent-corruption bug: per-slot jax.grad calls
    under vmap inside shard_map psum cotangents of the replicated params
    across the mesh PER SLOT POSITION, so every device got the same mixed
    gradient (device-0-looking values) — closed-form GLM grads were immune,
    autodiff models (MLP/attention) trained on wrong directions whenever
    the worker mesh had >1 device. step._weighted_loss_grad fixes them by
    differentiating ONE weighted scalar loss per device and letting the
    implicit replicated-param psum produce the global decoded gradient."""
    import jax
    import jax.numpy as jnp

    from erasurehead_tpu.models.attention import AttentionModel
    from erasurehead_tpu.models.mlp import MLPModel
    from erasurehead_tpu.parallel import step as step_lib
    from erasurehead_tpu.parallel.mesh import worker_mesh

    W, S, rows, F = 4, 2, 12, 64
    key = jax.random.PRNGKey(0)
    kx, ky, kp, kw = jax.random.split(key, 4)
    Xw = jax.random.normal(kx, (W, S, rows, F), jnp.float32)
    yw = jnp.sign(jax.random.normal(ky, (W, S, rows)))
    wts = jax.random.uniform(kw, (W, S), jnp.float32)
    for model in (MLPModel(), AttentionModel()):
        params = model.init_params(kp, F)
        per = jax.vmap(jax.vmap(lambda X, y: model.grad_sum(params, X, y)))(
            Xw, yw
        )
        want = jax.tree.map(
            lambda G: jnp.einsum("ws,ws...->...", wts, G), per
        )
        for ndev in (1, 2, 4):
            got = step_lib.make_faithful_grad_fn(model, worker_mesh(ndev))(
                params, Xw, yw, wts
            )
            for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                    err_msg=f"{model.name} ndev={ndev}",
                )
        # deduped path: partition-major stacks, folded weights
        pw = jax.random.uniform(kw, (W,), jnp.float32)
        perp = jax.vmap(lambda X, y: model.grad_sum(params, X, y))(
            Xw[:, 0], yw[:, 0]
        )
        wantp = jax.tree.map(lambda G: jnp.einsum("p,p...->...", pw, G), perp)
        gotp = step_lib.make_deduped_grad_fn(model, worker_mesh(4))(
            params, Xw[:, 0], yw[:, 0], pw
        )
        for a, b in zip(jax.tree.leaves(wantp), jax.tree.leaves(gotp)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                err_msg=f"{model.name} deduped",
            )


class TestTensorParallelComposition:
    """TP x DP: the MLP family on a 2-D (workers, model) mesh with its
    hidden dimension Megatron-split (models/mlp._predict_tp, trainer
    tp_shards) — same composition mechanics as the attention family's seq
    mode, pinned the same way."""

    def _cfg(self, tp_shards, **kw):
        base = dict(
            scheme="approx",
            model="mlp",
            n_workers=4,
            n_stragglers=1,
            num_collect=3,
            rounds=5,
            n_rows=192,
            n_cols=24,
            dataset="artificial",
            update_rule="GD",
            lr_schedule=0.5,
            add_delay=True,
            seed=0,
        )
        base.update(kw)
        return RunConfig(**base, tp_shards=tp_shards)

    def _data(self):
        from erasurehead_tpu.data.synthetic import generate_gmm

        return generate_gmm(192, 24, 4, seed=0)

    def test_tp_grads_match_oracle_across_meshes(self):
        """Sharded step gradients == host weighted oracle on every
        (workers x model) mesh shape, both compute modes."""
        import jax.numpy as jnp

        from erasurehead_tpu.models.mlp import MLPModel
        from erasurehead_tpu.parallel import step as step_lib
        from erasurehead_tpu.parallel.mesh import worker_tp_mesh

        W, S, rows, F = 4, 2, 12, 24
        key = jax.random.PRNGKey(0)
        kx, ky, kp, kw = jax.random.split(key, 4)
        Xw = jax.random.normal(kx, (W, S, rows, F), jnp.float32)
        yw = jnp.sign(jax.random.normal(ky, (W, S, rows)))
        wts = jax.random.uniform(kw, (W, S), jnp.float32)
        model = MLPModel(hidden=16)
        params = model.init_params(kp, F)
        per = jax.vmap(
            jax.vmap(lambda X, y: model.grad_sum(params, X, y))
        )(Xw, yw)
        want = jax.tree.map(
            lambda G: jnp.einsum("ws,ws...->...", wts, G), per
        )
        for wd, tp in ((4, 2), (2, 2), (1, 4), (2, 4), (1, 8)):
            mesh = worker_tp_mesh(tp, wd)
            got = step_lib.make_faithful_grad_fn(
                model.for_mesh(mesh), mesh
            )(params, Xw, yw, wts)
            for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                    err_msg=f"{wd}x{tp}",
                )

    @pytest.mark.parametrize("tp_shards", [2, 4])
    def test_training_trajectory_matches_unsharded(self, tp_shards):
        from erasurehead_tpu.train import trainer

        ds = self._data()
        base = trainer.train(self._cfg(1), ds)
        tp = trainer.train(self._cfg(tp_shards), ds)
        for a, b in zip(
            jax.tree.leaves(base.params_history),
            jax.tree.leaves(tp.params_history),
        ):
            np.testing.assert_allclose(
                np.asarray(a)[-1], np.asarray(b)[-1],
                rtol=2e-4, atol=2e-5,
            )

    def test_indivisible_hidden_rejected(self):
        """hidden=64 does not divide over 5 shards... but 5 > devices;
        use a hidden override instead: MLPModel(hidden=6) over 4 shards."""
        import jax.numpy as jnp
        from erasurehead_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from erasurehead_tpu.models.mlp import MLPModel
        from erasurehead_tpu.parallel.mesh import MODEL_AXIS, worker_tp_mesh

        mesh = worker_tp_mesh(4, 1)
        m = MLPModel(hidden=6, tp_axis=MODEL_AXIS)
        params = m.init_params(jax.random.PRNGKey(0), 8)
        X = jnp.ones((4, 8))
        with pytest.raises(ValueError, match="tp shards"):
            shard_map(
                lambda p, x: m.predict(p, x),
                mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            )(params, X)

    def test_tp_requires_mlp_model(self):
        with pytest.raises(ValueError, match="mlp"):
            self._cfg(2, model="logistic")

    def test_tp_and_seq_conflict(self):
        with pytest.raises(ValueError, match="at most one"):
            self._cfg(2, seq_shards=2)


class TestPipelineParallelComposition:
    """PP x DP: the deep-MLP family on a 2-D (workers, pipe) mesh — layers
    split across stages, GPipe microbatches streamed under one lax.scan
    (models/deep_mlp._predict_pp), composed with the coded-DP step."""

    def _cfg(self, pp_shards, **kw):
        base = dict(
            scheme="approx",
            model="deepmlp",
            n_workers=4,
            n_stragglers=1,
            num_collect=3,
            rounds=5,
            n_rows=192,
            n_cols=16,
            dataset="artificial",
            update_rule="GD",
            lr_schedule=0.5,
            add_delay=True,
            seed=0,
        )
        base.update(kw)
        return RunConfig(**base, pp_shards=pp_shards)

    def _data(self):
        from erasurehead_tpu.data.synthetic import generate_gmm

        return generate_gmm(192, 16, 4, seed=0)

    def test_pp_grads_match_oracle_across_meshes(self):
        """Gradients THROUGH the microbatched ppermute pipeline == host
        weighted oracle on every (workers x pipe) mesh shape."""
        import jax.numpy as jnp

        from erasurehead_tpu.models.deep_mlp import DeepMLPModel
        from erasurehead_tpu.parallel import step as step_lib
        from erasurehead_tpu.parallel.mesh import worker_plus_axis_mesh
        from erasurehead_tpu.models.deep_mlp import PIPE_AXIS

        W, S, rows, F = 4, 2, 12, 16
        key = jax.random.PRNGKey(0)
        kx, ky, kp, kw = jax.random.split(key, 4)
        Xw = jax.random.normal(kx, (W, S, rows, F), jnp.float32)
        yw = jnp.sign(jax.random.normal(ky, (W, S, rows)))
        wts = jax.random.uniform(kw, (W, S), jnp.float32)
        model = DeepMLPModel(hidden=8, n_layers=4)
        params = model.init_params(kp, F)
        per = jax.vmap(
            jax.vmap(lambda X, y: model.grad_sum(params, X, y))
        )(Xw, yw)
        want = jax.tree.map(
            lambda G: jnp.einsum("ws,ws...->...", wts, G), per
        )
        for wd, pp in ((4, 2), (2, 2), (1, 4), (2, 4)):
            mesh = worker_plus_axis_mesh(PIPE_AXIS, pp, wd)
            got = step_lib.make_faithful_grad_fn(
                model.for_mesh(mesh), mesh
            )(params, Xw, yw, wts)
            for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
                    err_msg=f"{wd}x{pp}",
                )

    @pytest.mark.parametrize("pp_shards", [2, 4])
    def test_training_trajectory_matches_unsharded(self, pp_shards):
        from erasurehead_tpu.train import trainer

        ds = self._data()
        base = trainer.train(self._cfg(1), ds)
        pp = trainer.train(self._cfg(pp_shards), ds)
        for a, b in zip(
            jax.tree.leaves(base.params_history),
            jax.tree.leaves(pp.params_history),
        ):
            np.testing.assert_allclose(
                np.asarray(a)[-1], np.asarray(b)[-1],
                rtol=5e-4, atol=5e-5,
            )

    def test_sparse_input_through_pipeline(self):
        """PaddedRows features flow through the PP input projection
        (ops/features.matvec embeds up front; the pipeline streams dense
        activations) — trajectory-equal to the unsharded run."""
        from erasurehead_tpu.data.synthetic import generate_onehot
        from erasurehead_tpu.train import trainer

        ds = generate_onehot(192, 24, 4, n_fields=4, seed=0)
        kw = dict(n_cols=24)
        base = trainer.train(self._cfg(1, **kw), ds)
        pp = trainer.train(self._cfg(2, **kw), ds)
        for a, b in zip(
            jax.tree.leaves(base.params_history),
            jax.tree.leaves(pp.params_history),
        ):
            np.testing.assert_allclose(
                np.asarray(a)[-1], np.asarray(b)[-1],
                rtol=5e-4, atol=5e-5,
            )

    def test_indivisible_layers_rejected(self):
        """n_layers=4 cannot split over 3 stages."""
        import jax.numpy as jnp
        from erasurehead_tpu.utils.compat import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from erasurehead_tpu.models.deep_mlp import DeepMLPModel, PIPE_AXIS

        mesh = Mesh(np.asarray(jax.devices()[:3]), (PIPE_AXIS,))
        m = DeepMLPModel(hidden=8, n_layers=4, pp_axis=PIPE_AXIS)
        params = m.init_params(jax.random.PRNGKey(0), 8)
        X = jnp.ones((6, 8))
        with pytest.raises(ValueError, match="pp stages"):
            shard_map(
                lambda p, x: m.predict(p, x),
                mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            )(params, X)

    def test_pp_requires_deepmlp_model(self):
        with pytest.raises(ValueError, match="deepmlp"):
            self._cfg(2, model="logistic")


class TestExpertParallelComposition:
    """EP x DP: the MoE family on a 2-D (workers, expert) mesh — experts
    split contiguously over the expert axis, gate-weighted partial margins
    psum'd (models/moe._predict_ep), composed with the coded-DP step."""

    def _cfg(self, ep_shards, **kw):
        base = dict(
            scheme="approx",
            model="moe",
            n_workers=4,
            n_stragglers=1,
            num_collect=3,
            rounds=5,
            n_rows=192,
            n_cols=16,
            dataset="artificial",
            update_rule="GD",
            lr_schedule=0.5,
            add_delay=True,
            seed=0,
        )
        base.update(kw)
        return RunConfig(**base, ep_shards=ep_shards)

    def _data(self):
        from erasurehead_tpu.data.synthetic import generate_gmm

        return generate_gmm(192, 16, 4, seed=0)

    def test_ep_grads_match_oracle_across_meshes(self):
        import jax.numpy as jnp

        from erasurehead_tpu.models.moe import EXPERT_AXIS, MoEModel
        from erasurehead_tpu.parallel import step as step_lib
        from erasurehead_tpu.parallel.mesh import worker_plus_axis_mesh

        W, S, rows, F = 4, 2, 12, 16
        key = jax.random.PRNGKey(0)
        kx, ky, kp, kw = jax.random.split(key, 4)
        Xw = jax.random.normal(kx, (W, S, rows, F), jnp.float32)
        yw = jnp.sign(jax.random.normal(ky, (W, S, rows)))
        wts = jax.random.uniform(kw, (W, S), jnp.float32)
        model = MoEModel(hidden=8, n_experts=4)
        params = model.init_params(kp, F)
        per = jax.vmap(
            jax.vmap(lambda X, y: model.grad_sum(params, X, y))
        )(Xw, yw)
        want = jax.tree.map(
            lambda G: jnp.einsum("ws,ws...->...", wts, G), per
        )
        for wd, ep in ((4, 2), (2, 2), (1, 4), (2, 4)):
            mesh = worker_plus_axis_mesh(EXPERT_AXIS, ep, wd)
            got = step_lib.make_faithful_grad_fn(
                model.for_mesh(mesh), mesh
            )(params, Xw, yw, wts)
            for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
                    err_msg=f"{wd}x{ep}",
                )

    @pytest.mark.parametrize("ep_shards", [2, 4])
    def test_training_trajectory_matches_unsharded(self, ep_shards):
        from erasurehead_tpu.train import trainer

        ds = self._data()
        base = trainer.train(self._cfg(1), ds)
        ep = trainer.train(self._cfg(ep_shards), ds)
        for a, b in zip(
            jax.tree.leaves(base.params_history),
            jax.tree.leaves(ep.params_history),
        ):
            np.testing.assert_allclose(
                np.asarray(a)[-1], np.asarray(b)[-1],
                rtol=5e-4, atol=5e-5,
            )

    def test_sparse_input_through_experts(self):
        """PaddedRows features flow through the gate and per-expert
        matvecs — trajectory-equal to the unsharded run."""
        from erasurehead_tpu.data.synthetic import generate_onehot
        from erasurehead_tpu.train import trainer

        ds = generate_onehot(192, 24, 4, n_fields=4, seed=0)
        kw = dict(n_cols=24)
        base = trainer.train(self._cfg(1, **kw), ds)
        ep = trainer.train(self._cfg(2, **kw), ds)
        for a, b in zip(
            jax.tree.leaves(base.params_history),
            jax.tree.leaves(ep.params_history),
        ):
            np.testing.assert_allclose(
                np.asarray(a)[-1], np.asarray(b)[-1],
                rtol=5e-4, atol=5e-5,
            )

    def test_indivisible_experts_rejected(self):
        import jax.numpy as jnp
        from erasurehead_tpu.utils.compat import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from erasurehead_tpu.models.moe import EXPERT_AXIS, MoEModel

        mesh = Mesh(np.asarray(jax.devices()[:3]), (EXPERT_AXIS,))
        m = MoEModel(hidden=8, n_experts=4, ep_axis=EXPERT_AXIS)
        params = m.init_params(jax.random.PRNGKey(0), 8)
        X = jnp.ones((6, 8))
        with pytest.raises(ValueError, match="ep shards"):
            shard_map(
                lambda p, x: m.predict(p, x),
                mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            )(params, X)

    def test_ep_requires_moe_model(self):
        with pytest.raises(ValueError, match="moe"):
            self._cfg(2, model="logistic")


@pytest.mark.parametrize(
    "scheme,extra,model,axis_kw",
    [
        # scheme variety x parallelism-axis variety: the decode-weight
        # tensors of every scheme family must compose with the
        # weighted-scalar-loss gradient path of every sharded model axis
        ("cyccoded", dict(n_stragglers=2), "mlp", dict(tp_shards=2)),
        ("repcoded", dict(n_stragglers=1), "deepmlp", dict(pp_shards=2)),
        ("randreg", dict(n_stragglers=1, num_collect=3), "moe",
         dict(ep_shards=2)),
        ("deadline", dict(deadline=1.0), "attention", dict(seq_shards=2)),
        ("avoidstragg", dict(n_stragglers=1), "moe", dict(ep_shards=4)),
        ("approx", dict(n_stragglers=1, num_collect=3), "deepmlp",
         dict(pp_shards=4, compute_mode="deduped")),
        # the two-message partial schemes: two-part decode weights x
        # sharded model axes
        ("partialrepcoded", dict(n_stragglers=1, partitions_per_worker=3),
         "mlp", dict(tp_shards=2)),
        ("partialcyccoded", dict(n_stragglers=1, partitions_per_worker=3),
         "moe", dict(ep_shards=2)),
    ],
)
def test_parallelism_matrix_trajectory_fuzz(scheme, extra, model, axis_kw):
    """Cross-matrix invariant: ANY (scheme x model family x parallelism
    axis) combination must be trajectory-equal to its unsharded run —
    sharding is a lowering decision, never a semantics change."""
    cols = 64 if model == "attention" else 16
    base = dict(
        scheme=scheme, model=model, n_workers=4, rounds=4, n_rows=192,
        n_cols=cols, dataset="artificial", update_rule="GD",
        lr_schedule=0.2, add_delay=True, seed=0, **extra,
    )
    ds = generate_gmm(192, cols, 4, seed=0)
    ref = trainer.train(RunConfig(**base), ds)
    sharded = trainer.train(RunConfig(**base, **axis_kw), ds)
    for a, b in zip(
        jax.tree.leaves(ref.params_history),
        jax.tree.leaves(sharded.params_history),
    ):
        # the FULL per-round history, not just the final iterate
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=f"{scheme}/{model}/{axis_kw}",
        )


class TestMarginFlat:
    """The hybrid dense margin lowering (cfg.margin_flat,
    step._hybrid_margin_flat_grad): flat 2-D margin matmul + batched
    per-slot transpose — trajectory-equal to the per-slot path."""

    def test_resolution_rules(self):
        import jax.numpy as jnp

        from erasurehead_tpu.models.glm import LogisticModel
        from erasurehead_tpu.models.mlp import MLPModel
        from erasurehead_tpu.ops.features import PaddedRows
        from erasurehead_tpu.parallel import step as step_lib

        glm = LogisticModel()
        dense = jnp.zeros((2, 4, 8))
        padded = PaddedRows(
            jnp.zeros((2, 4, 3), jnp.int32), jnp.ones((2, 4, 3)), 8
        )
        assert (
            step_lib.resolve_margin_flat("auto", glm, dense)
            == step_lib.MARGIN_FLAT_DEFAULT
        )
        assert step_lib.resolve_margin_flat("on", glm, dense)
        assert not step_lib.resolve_margin_flat("off", glm, dense)
        # sparse stacks and autodiff models are unsupported -> always False
        assert not step_lib.resolve_margin_flat("on", glm, padded)
        assert not step_lib.resolve_margin_flat("on", MLPModel(), dense)

    @pytest.mark.parametrize("mode", ["faithful", "deduped"])
    def test_trajectory_matches_per_slot(self, gmm, mode):
        base = trainer.train(
            _cfg(scheme="approx", num_collect=3, compute_mode=mode,
                 margin_flat="off"),
            gmm, mesh=worker_mesh(4),
        )
        hyb = trainer.train(
            _cfg(scheme="approx", num_collect=3, compute_mode=mode,
                 margin_flat="on"),
            gmm, mesh=worker_mesh(4),
        )
        np.testing.assert_allclose(
            np.asarray(hyb.final_params), np.asarray(base.final_params),
            rtol=1e-4, atol=1e-6,
        )

    def test_on_rejects_unsupported(self, gmm):
        from erasurehead_tpu.data.synthetic import generate_onehot

        data = generate_onehot(N_ROWS, 40, n_partitions=W, n_fields=4, seed=7)
        cfg = _cfg(scheme="approx", num_collect=3, margin_flat="on",
                   sparse_format="fields")
        with pytest.raises(ValueError, match="margin_flat"):
            trainer.train(cfg, data, mesh=worker_mesh(4))

    def test_on_conflicts_with_flat_on(self):
        with pytest.raises(ValueError, match="at most one"):
            _cfg(margin_flat="on", flat_grad="on")


def test_margin_flat_on_conflicts_with_pallas_on():
    with pytest.raises(ValueError, match="at most one"):
        _cfg(margin_flat="on", use_pallas="on")


def test_scan_unroll_matches_unrolled_one():
    """cfg.scan_unroll is a pure lowering knob: lax.scan semantics are
    identical at any unroll factor; XLA's cross-iteration fusion may
    reassociate f32, so trajectories agree to float tolerance (like the
    other lowering knobs). Queued as the dense_f32_unroll* sweep
    entries."""
    import dataclasses

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    W = 8
    cfg = RunConfig(
        scheme="approx", n_workers=W, n_stragglers=1, num_collect=6,
        rounds=7, n_rows=16 * W, n_cols=24, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=W, seed=0)
    base = trainer.train(cfg, data, measure=False)
    for unroll in (3, 4):  # non-divisor AND divisor of rounds
        u = dataclasses.replace(cfg, scan_unroll=unroll)
        res = trainer.train(u, data, measure=False)
        np.testing.assert_allclose(
            np.asarray(res.params_history),
            np.asarray(base.params_history), rtol=3e-5, atol=1e-6,
        )
    dbase = trainer.train_dynamic(cfg, data)
    dres = trainer.train_dynamic(
        dataclasses.replace(cfg, scan_unroll=4), data
    )
    np.testing.assert_allclose(
        np.asarray(dres.params_history),
        np.asarray(dbase.params_history), rtol=3e-5, atol=1e-6,
    )
    with pytest.raises(ValueError, match="scan_unroll"):
        RunConfig(scheme="naive", n_workers=4, n_rows=32, n_cols=8,
                  scan_unroll=0)
