"""Determinism audit (utils/audit.py): full-run bitwise replayability."""

import numpy as np

from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.parallel.mesh import worker_mesh
from erasurehead_tpu.utils import audit
from erasurehead_tpu.utils.config import RunConfig

W = 8


def _cfg(**kw):
    base = dict(
        scheme="approx", n_workers=W, n_stragglers=1, num_collect=5,
        rounds=5, n_rows=16 * W, n_cols=24, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    base.update(kw)
    return RunConfig(**base)


def test_schedule_replays_bitwise():
    assert audit.audit_schedule_determinism(_cfg())


def test_training_replays_bitwise():
    cfg = _cfg()
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=W, seed=0)
    res = audit.audit_training_determinism(cfg, data, mesh=worker_mesh(4))
    assert res, (res.what, res.max_abs_diff)


def test_audit_detects_divergence():
    a = np.zeros(4)
    b = np.array([0.0, 0.0, 1e-3, 0.0])
    r = audit._compare(a, b, "x")
    assert not r and r.max_abs_diff == 1e-3


def test_schedule_audit_uses_heterogeneous_model(monkeypatch):
    """A heterogeneous-cluster config must audit the same schedule train()
    runs: the audit must pass the config's arrival model through to
    arrival_schedule (not silently audit the homogeneous schedule)."""
    from erasurehead_tpu.parallel import straggler

    cfg = _cfg()
    cfg.compute_time = 2.0
    cfg.worker_speed_spread = 0.5
    expected = straggler.model_from_config(cfg)
    assert expected is not None

    seen = []
    real = straggler.arrival_schedule

    def spy(*args, **kw):
        seen.append(kw.get("arrival_model"))
        return real(*args, **kw)

    monkeypatch.setattr(straggler, "arrival_schedule", spy)
    assert audit.audit_schedule_determinism(cfg)
    assert seen, "audit never built a schedule"
    for model in seen:
        assert model is not None
        np.testing.assert_array_equal(
            model.worker_speed, expected.worker_speed
        )


def test_audit_covers_deadline_scheme():
    """Regression: the determinism audit must handle scheme='deadline'
    (build_schedule needs the deadline threaded through)."""
    from erasurehead_tpu.utils import audit
    from erasurehead_tpu.utils.config import RunConfig

    cfg = RunConfig(
        scheme="deadline", deadline=1.0, n_workers=4, n_stragglers=0,
        rounds=5, n_rows=64, n_cols=8, lr_schedule=1.0, add_delay=True,
        seed=0,
    )
    res = audit.audit_schedule_determinism(cfg)
    assert res.bitwise_equal
