"""Ring attention (parallel/ring.py): sequence-parallel exact attention on
the 8-virtual-device CPU mesh, pinned against the single-device oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from erasurehead_tpu.parallel import ring

T, D = 64, 16


def _seq_mesh(n):
    devs = jax.devices()[:n]
    return Mesh(np.asarray(devs), (ring.SEQ_AXIS,))


@pytest.fixture(scope="module")
def qkv():
    key = jax.random.PRNGKey(7)
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (T, D), jnp.float32),
        jax.random.normal(kk, (T, D), jnp.float32),
        jax.random.normal(kv, (T, D), jnp.float32),
    )


@pytest.mark.parametrize("n_devices", [2, 4, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(qkv, n_devices, causal):
    """The N-step ring (ppermute + online softmax) must reproduce full
    softmax(QK^T/sqrt(d))V for every shard count, causal and not."""
    q, k, v = qkv
    mesh = _seq_mesh(n_devices)
    out = ring.make_ring_attention_fn(mesh, causal=causal)(q, k, v)
    want = ring.reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-6
    )


def test_ring_is_sequence_sharded(qkv):
    """Output keeps the sequence sharding: each device owns T/N rows."""
    q, k, v = qkv
    mesh = _seq_mesh(4)
    out = ring.make_ring_attention_fn(mesh)(q, k, v)
    shard_rows = {s.data.shape[0] for s in out.addressable_shards}
    assert shard_rows == {T // 4}


def test_ring_heads_vmap(qkv):
    """vmap over a heads axis composes with the sharded ring (the
    multi-head form), matching per-head oracles."""
    q, k, v = qkv
    H = 3
    key = jax.random.PRNGKey(11)
    qs = jnp.stack([q * (h + 1) for h in range(H)])
    ks = jnp.stack([k + h for h in range(H)])
    vs = jnp.stack([v - h for h in range(H)])
    mesh = _seq_mesh(4)
    fn = ring.make_ring_attention_fn(mesh, causal=True)
    out = jax.vmap(fn)(qs, ks, vs)
    for h in range(H):
        want = ring.reference_attention(qs[h], ks[h], vs[h], causal=True)
        np.testing.assert_allclose(
            np.asarray(out[h]), np.asarray(want), rtol=2e-5, atol=2e-6
        )


def test_ring_long_sequence_memory_shape():
    """A longer sequence still runs with per-chip score blocks of
    (T/N)^2, not T^2 — the point of the ring. (Shape-level check: the
    jitted program compiles and is finite at T=512 on 8 devices.)"""
    key = jax.random.PRNGKey(3)
    T2 = 512
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (T2, D), jnp.float32)
    k = jax.random.normal(kk, (T2, D), jnp.float32)
    v = jax.random.normal(kv, (T2, D), jnp.float32)
    mesh = _seq_mesh(8)
    out = ring.make_ring_attention_fn(mesh, causal=True)(q, k, v)
    assert np.isfinite(np.asarray(out)).all()
    want = ring.reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-6
    )


@pytest.mark.parametrize("n_devices", [2, 4, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full_attention(n_devices, causal):
    """The all-to-all SP form: two collectives re-shard seq->heads and
    back; must equal the oracle (and therefore the ring) per head."""
    H = 8
    rng = jax.random.PRNGKey(5)
    r1, r2, r3 = jax.random.split(rng, 3)
    qs = jax.random.normal(r1, (T, H, D), jnp.float32)
    ks = jax.random.normal(r2, (T, H, D), jnp.float32)
    vs = jax.random.normal(r3, (T, H, D), jnp.float32)
    mesh = _seq_mesh(n_devices)
    out = ring.make_ulysses_attention_fn(mesh, causal=causal)(qs, ks, vs)
    for h in range(H):
        want = ring.reference_attention(
            qs[:, h], ks[:, h], vs[:, h], causal=causal
        )
        np.testing.assert_allclose(
            np.asarray(out[:, h]), np.asarray(want), rtol=2e-5, atol=2e-6
        )


def test_ulysses_rejects_indivisible_heads():
    mesh = _seq_mesh(4)
    q = jnp.zeros((T, 6, D), jnp.float32)  # 6 heads on 4 devices
    with pytest.raises(ValueError, match="divisible"):
        ring.make_ulysses_attention_fn(mesh)(q, q, q)


def test_ring_preserves_input_dtype(qkv):
    """bf16 in -> bf16 out (mixed-precision pipelines rely on
    dtype-preserving attention); accumulation still runs in f32."""
    q, k, v = (x.astype(jnp.bfloat16) for x in qkv)
    mesh = _seq_mesh(4)
    out = ring.make_ring_attention_fn(mesh)(q, k, v)
    assert out.dtype == jnp.bfloat16
    want = ring.reference_attention(q, k, v)
    assert want.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(want, np.float32),
        rtol=0.05, atol=0.05,
    )


class TestSeqParallelComposition:
    """SP x DP: the attention family trained on a 2-D (workers, seq) mesh
    with its token axis sharded over seq (models/attention._predict_seq,
    trainer seq_shards)."""

    def _cfg(self, seq_shards, **kw):
        from erasurehead_tpu.utils.config import RunConfig

        base = dict(
            scheme="approx",
            model="attention",
            n_workers=4,
            n_stragglers=1,
            num_collect=3,
            rounds=5,
            n_rows=192,
            n_cols=64,  # d_in=8 -> T=8 tokens, divisible by 2 and 4 shards
            dataset="artificial",
            update_rule="GD",
            add_delay=True,
            seed=0,
        )
        base.update(kw)
        return RunConfig(**base, seq_shards=seq_shards)

    def _data(self):
        from erasurehead_tpu.data.synthetic import generate_gmm

        return generate_gmm(192, 64, 4, seed=0)

    def test_seq_grad_matches_oracle(self):
        """grad_sum inside a seq-only shard_map == the unsharded oracle —
        validating the 1/axis_size loss scaling + seq psum recipe for both
        replicated-path (head) and partitioned-path (embed/qkv) leaves."""
        from functools import partial

        from erasurehead_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from erasurehead_tpu.models.attention import AttentionModel

        n, F = 24, 64
        key = jax.random.PRNGKey(3)
        kx, ky, kp = jax.random.split(key, 3)
        X = jax.random.normal(kx, (n, F), jnp.float32)
        y = jnp.sign(jax.random.normal(ky, (n,)))
        oracle_model = AttentionModel()
        params = oracle_model.init_params(kp, F)
        want = oracle_model.grad_sum(params, X, y)

        mesh = _seq_mesh(4)
        sp_model = AttentionModel(seq_axis=ring.SEQ_AXIS)
        got = shard_map(
            partial(sp_model.grad_sum),
            mesh=mesh,
            in_specs=(P(), P(), P()),
            out_specs=P(),
        )(params, X, y)
        for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            )

    @pytest.mark.parametrize("sp_form", ["ring", "ulysses"])
    @pytest.mark.parametrize("seq_shards", [2, 4])
    def test_training_trajectory_matches_unsharded(self, seq_shards, sp_form):
        """Both canonical SP forms must be exactly parity-preserving under
        the coded-DP trainer (n_heads=2 default: ulysses at 4 shards would
        need 4 heads, so skip that cell)."""
        from erasurehead_tpu.train import trainer

        if sp_form == "ulysses" and seq_shards == 4:
            pytest.skip("default n_heads=2 not divisible by 4 seq shards")
        ds = self._data()
        # sp_form is inert at seq_shards=1, so one unsharded baseline
        # serves every parametrized cell
        if not hasattr(TestSeqParallelComposition, "_base_cache"):
            TestSeqParallelComposition._base_cache = trainer.train(
                self._cfg(1), ds
            )
        base = TestSeqParallelComposition._base_cache
        sp = trainer.train(self._cfg(seq_shards, sp_form=sp_form), ds)
        # loose endpoint tolerance: the artificial preset's lr=10 GD
        # amplifies the sharded lowering's f32 reduction-order noise
        # ~geometrically over the 5 rounds (observed ~3% on the scalar
        # bias leaf on the CPU backend); exactness of the per-step
        # gradient itself is pinned tightly by test_seq_grad_matches_oracle
        np.testing.assert_allclose(
            np.asarray(jax.tree.leaves(base.params_history)[0][-1]),
            np.asarray(jax.tree.leaves(sp.params_history)[0][-1]),
            rtol=5e-2, atol=2e-5,
        )

    def test_ulysses_rejects_indivisible_head_count(self):
        from erasurehead_tpu.train import trainer

        with pytest.raises(ValueError, match="divisible"):
            trainer.train(self._cfg(4, sp_form="ulysses"), self._data())

    def test_auto_seq_mesh_shape(self):
        from erasurehead_tpu.train import trainer
        from erasurehead_tpu.parallel.mesh import WORKER_AXIS

        mesh = trainer._auto_2d_mesh(4, ring.SEQ_AXIS, 2)  # 4 workers, 2 seq
        assert dict(mesh.shape) == {WORKER_AXIS: 4, ring.SEQ_AXIS: 2}
        mesh = trainer._auto_2d_mesh(4, ring.SEQ_AXIS, 4)  # 2 devices left per seq
        assert dict(mesh.shape) == {WORKER_AXIS: 2, ring.SEQ_AXIS: 4}

    def test_explicit_mesh_must_match_seq_shards(self):
        """A worker-only mesh with seq_shards>1 must refuse, not silently
        run without sequence parallelism (SP is parity-preserving, so the
        numbers would look right while testing nothing)."""
        from erasurehead_tpu.parallel.mesh import worker_mesh
        from erasurehead_tpu.train import trainer

        with pytest.raises(ValueError, match="'seq' shards"):
            trainer.train(self._cfg(2), self._data(), mesh=worker_mesh(4))

    def test_indivisible_tokens_rejected(self):
        from erasurehead_tpu.train import trainer

        # n_cols=56 -> T=7 tokens, not divisible by 2 seq shards
        ds_cfg = self._cfg(2, n_cols=56, n_rows=112)
        from erasurehead_tpu.data.synthetic import generate_gmm

        ds = generate_gmm(112, 56, 4, seed=0)
        with pytest.raises(ValueError, match="sequence shards"):
            trainer.train(ds_cfg, ds)

    def test_seq_requires_attention_model(self):
        with pytest.raises(ValueError, match="attention"):
            self._cfg(2, model="logistic")

    def test_seq_requires_simulated_arrivals(self):
        with pytest.raises(ValueError, match="simulated"):
            self._cfg(2, arrival_mode="measured")
