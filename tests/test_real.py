"""Real-dataset preparers, no-download paths (ISSUE 15 satellite).

Pins the three preparers that work in a zero-egress sandbox: covtype
from a raw UCI ``covtype.data`` file (the genuine 54-feature +
Cover_Type schema, synthesized tiny here), and the sklearn-bundled
breast_cancer / diabetes sets. Shape arithmetic (80/20 split), label
ranges (±1 classification, O(1) regression target), joint one-hot
encoding (train and test share a feature space), and call-to-call
determinism — the property the sweep journal's dataset digest rests
on."""

from __future__ import annotations

import numpy as np
import pytest
import scipy.sparse as sps

sklearn = pytest.importorskip("sklearn")
pytest.importorskip("pandas")

from erasurehead_tpu.data import real as real_data  # noqa: E402


def _check_prepared(ds, n_rows: int, regression: bool = False) -> None:
    """The invariants every _one_hot_split product satisfies."""
    n_test = int(np.ceil(n_rows * 0.2))  # train_test_split ceils the test
    n_train = n_rows - n_test
    assert sps.issparse(ds.X_train) and sps.issparse(ds.X_test)
    assert ds.X_train.shape[0] == n_train == ds.y_train.shape[0]
    assert ds.X_test.shape[0] == n_test == ds.y_test.shape[0]
    # joint encoder fit: train and test live in ONE feature space
    assert ds.X_train.shape[1] == ds.X_test.shape[1]
    # one-hot rows: every entry is 1, at most one per encoded column
    assert np.all(ds.X_train.data == 1.0)
    if regression:
        y = np.concatenate([ds.y_train, ds.y_test])
        assert np.all(np.isfinite(y))
        assert 0.1 < np.abs(y).max() < 10.0  # O(1)-scaled target
    else:
        assert set(np.unique(ds.y_train)) <= {-1.0, 1.0}
        assert set(np.unique(ds.y_test)) <= {-1.0, 1.0}


def _bitwise_same(a, b) -> bool:
    return (
        (a.X_train != b.X_train).nnz == 0
        and (a.X_test != b.X_test).nnz == 0
        and np.array_equal(a.y_train, b.y_train)
        and np.array_equal(a.y_test, b.y_test)
    )


def _write_raw_covtype(path, n_rows: int = 80) -> int:
    """A tiny file in the genuine UCI covtype.data layout: 10
    quantitative columns, 44 indicator columns, Cover_Type 1..7.
    Returns how many rows survive the preparer's class filter (<=2)."""
    rng = np.random.RandomState(0)
    quant = rng.randint(0, 50, size=(n_rows, 10))
    indic = rng.randint(0, 2, size=(n_rows, 44))
    target = rng.randint(1, 8, size=(n_rows, 1))
    table = np.hstack([quant, indic, target])
    np.savetxt(path, table, fmt="%d", delimiter=",")
    return int((target <= 2).sum())


def test_prepare_covtype_raw_file(tmp_path):
    kept = _write_raw_covtype(str(tmp_path / "covtype.data"))
    assert kept > 10  # the synthetic file exercises the class filter
    ds = real_data.prepare("covtype", str(tmp_path))
    assert ds.name == "covtype"
    _check_prepared(ds, kept)
    # both kept classes survive the {1,2} -> {-1,+1} binarization
    y = np.concatenate([ds.y_train, ds.y_test])
    assert {-1.0, 1.0} == set(np.unique(y))
    assert _bitwise_same(ds, real_data.prepare("covtype", str(tmp_path)))


def test_prepare_covtype_rejects_wrong_schema(tmp_path):
    np.savetxt(
        str(tmp_path / "covtype.data"),
        np.ones((5, 7)), fmt="%d", delimiter=",",
    )
    with pytest.raises(ValueError, match="55 columns"):
        real_data.prepare("covtype", str(tmp_path))


def test_prepare_breast_cancer():
    ds = real_data.prepare("breast_cancer", None)
    assert ds.name == "breast_cancer"
    _check_prepared(ds, 569)  # the bundled set's fixed row count
    assert _bitwise_same(ds, real_data.prepare("breast_cancer", None))


def test_prepare_diabetes():
    ds = real_data.prepare("diabetes", None)
    assert ds.name == "diabetes"
    _check_prepared(ds, 442, regression=True)
    assert _bitwise_same(ds, real_data.prepare("diabetes", None))


def test_prepare_unknown_dataset_is_loud():
    with pytest.raises(ValueError, match="unknown dataset"):
        real_data.prepare("nope", ".")
