"""Test harness: simulate an 8-device TPU pod on CPU.

Tests exercise the full multi-chip sharding path via XLA's forced
host-platform device count — the same mechanism the driver uses for the
multi-chip dry run (SURVEY.md §4).

This environment's sitecustomize force-registers a remote-TPU ("axon") PJRT
plugin and overwrites JAX_PLATFORMS, so merely setting the env var is not
enough: we must override the config after import AND deregister the plugin
factory, otherwise every test process dials the TPU tunnel (and wedges it —
the terminal serves one client at a time).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge as _xb  # noqa: E402

# pop only the tunnel plugin: removing "tpu" would unregister the platform
# name itself, and jax.experimental.pallas then fails at import time
# (checkify registers a lowering rule for platform "tpu")
_xb._backend_factories.pop("axon", None)

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def devices():
    devs = jax.devices()
    assert devs[0].platform == "cpu"
    assert len(devs) == 8, f"expected 8 forced CPU devices, got {len(devs)}"
    return devs


#: Can this jaxlib run a multi-process jax.distributed cluster on the CPU
#: backend? 0.4.x cannot — XLA rejects every cross-process computation with
#: INVALID_ARGUMENT "Multiprocess computations aren't implemented on the
#: CPU backend" — so the virtual-cluster tests (test_multihost, the fleet
#: pod emulation) are structurally unrunnable there, not failing.
CPU_CLUSTER_SUPPORTED = jax.__version_info__ >= (0, 5)


def free_port() -> int:
    """A free localhost TCP port (multi-process cluster tests)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def cpu_cluster_env(local_devices: int = 2, **extra) -> dict:
    """Subprocess env for a virtual-CPU jax.distributed child: pins the
    CPU platform with N local devices and scrubs the axon TPU tunnel (a
    child dialing the relay can wedge a concurrent TPU client)."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={local_devices}",
        **extra,
    }
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env
