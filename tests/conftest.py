"""Test harness: simulate an 8-device TPU pod on CPU.

Must run before any jax import (SURVEY.md §4): tests exercise the full
multi-chip sharding path via XLA's forced host-platform device count, the
same mechanism the driver uses for the multi-chip dry run.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", False)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 forced CPU devices, got {len(devs)}"
    return devs
