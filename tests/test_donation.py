"""Buffer donation (cfg.donate): the memory lever must never touch the
science or the caches.

Donation aliases the scan carry (params + optimizer state) and the
per-round weight tables into the dispatch (jax donate_argnums), freeing
the duplicate HBM copies. The hazards this file pins:

  - use-after-donate against the device-data cache: a donating run must
    never donate a cached stack, and a cache-hit rerun after a donating
    run must be bitwise identical (ISSUE 6 acceptance);
  - the warm-up execution consumes donated buffers — the real run must
    still see live originals (the _donate_copy discipline), including on
    the checkpoint-chunked path where a full-range weight slice ALIASES
    the run's weight table;
  - donation is observation-free math: on/off trajectories are bitwise
    identical, sequential and cohort alike;
  - the OOM-bisection path (experiments._dispatch_cohort +
    cache.drop_data_cache) still works mid-sweep with donation on.
"""

import dataclasses

import jax
import numpy as np
import pytest

from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.train import cache as cache_lib
from erasurehead_tpu.train import experiments, trainer
from erasurehead_tpu.train import journal as journal_lib
from erasurehead_tpu.utils import chaos
from erasurehead_tpu.utils.config import RunConfig

W = 8


@pytest.fixture(scope="module")
def gmm():
    return generate_gmm(W * 8, 16, n_partitions=W, seed=0)


def _cfg(**kw):
    base = dict(
        scheme="approx", n_workers=W, n_stragglers=1, num_collect=4,
        rounds=3, n_rows=W * 8, n_cols=16, lr_schedule=0.5,
        update_rule="AGD", add_delay=True, seed=0,
    )
    base.update(kw)
    return RunConfig(**base)


@pytest.fixture(autouse=True)
def _chaos_clean(monkeypatch):
    monkeypatch.delenv(chaos.CHAOS_ENV, raising=False)
    chaos.reset()
    yield
    chaos.reset()


def _bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


def _cached_stack_leaves():
    """Every jax Array currently pinned by the device-data cache."""
    leaves = []
    for data, _nbytes in cache_lib._data_cache.values():
        for leaf in jax.tree.leaves((data.Xp, data.yp, data.Xw, data.yw)):
            if isinstance(leaf, jax.Array):
                leaves.append(leaf)
    return leaves


def test_donating_run_never_donates_cached_stacks(gmm):
    """After a donating run, every data-cache array is still alive (no
    donated buffer is a cached device array), and a cache-hit rerun is
    bitwise identical — the ISSUE 6 donation regression."""
    cache_lib.clear()
    cfg = _cfg(donate="on")
    first = trainer.train(cfg, gmm)
    assert first.cache_info["donation"] is True
    leaves = _cached_stack_leaves()
    assert leaves, "expected the data cache to hold this run's stacks"
    assert all(not leaf.is_deleted() for leaf in leaves)
    second = trainer.train(cfg, gmm)
    assert second.cache_info["data_hit"]
    assert second.cache_info["exec_hits"] >= 1
    assert _bitwise(first.params_history, second.params_history)
    assert _bitwise(first.final_params, second.final_params)
    # and the cache pins are STILL alive after the second donating run
    assert all(not leaf.is_deleted() for leaf in _cached_stack_leaves())


def test_donation_is_bitwise_invisible(gmm):
    """donate on vs off: identical trajectories (donation is aliasing,
    not math), for the default measure=True warm-up path too."""
    on = trainer.train(_cfg(donate="on"), gmm)
    off = trainer.train(_cfg(donate="off"), gmm)
    assert on.cache_info["donation"] is True
    assert off.cache_info["donation"] is False
    assert _bitwise(on.params_history, off.params_history)
    # donation resolution: auto = DONATE_DEFAULT
    auto = trainer.train(_cfg(), gmm)
    assert auto.cache_info["donation"] is trainer.DONATE_DEFAULT


def test_auto_donation_off_under_persistent_compile_cache():
    """A donating executable deserialized from the persistent compilation
    cache returns a carry whose jax-level alias points at the donated
    input while the real output landed elsewhere — stale or freed memory
    (the warm-cache serve-replica divergence false-positive). "auto" must
    resolve to no-donation whenever the process routes compiles through
    the on-disk cache; explicit "on" stays forceable."""
    from erasurehead_tpu.train import cache as cache_lib

    prev = cache_lib._PERSISTENT_CACHE_DIR
    cache_lib._PERSISTENT_CACHE_DIR = "/tmp/somewhere"
    try:
        assert trainer._resolve_donate(_cfg()) is False
        assert trainer._resolve_donate(_cfg(donate="on")) is True
        assert trainer._resolve_donate(_cfg(donate="off")) is False
    finally:
        cache_lib._PERSISTENT_CACHE_DIR = prev
    assert trainer._resolve_donate(_cfg()) is trainer.DONATE_DEFAULT


def test_donation_checkpoint_chunked_path(gmm, tmp_path):
    """The chunked scan (checkpoint_every) re-slices the weight table per
    chunk; with donation on, consumed chunk slices must never strand a
    later chunk or the saved state. Bitwise vs the non-donating run with
    identical chunking."""
    kw = dict(
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
    )
    on = trainer.train(_cfg(rounds=6, donate="on"), gmm, **kw)
    off = trainer.train(
        _cfg(rounds=6, donate="off"), gmm,
        checkpoint_dir=str(tmp_path / "ck2"), checkpoint_every=2,
    )
    assert _bitwise(on.params_history, off.params_history)
    assert _bitwise(on.final_params, off.final_params)


def test_donation_cohort_bitwise(gmm):
    """Cohort dispatches donate the [B]-stacked carry and the [R, B, ...]
    weight tables; trajectories match the non-donating cohort bitwise and
    the shared data stack survives in the cache."""
    cache_lib.clear()
    cfgs = [
        _cfg(compute_mode="deduped", donate="on", seed=s) for s in (0, 1)
    ]
    on = trainer.train_cohort(cfgs, gmm)
    off = trainer.train_cohort(
        [dataclasses.replace(c, donate="off") for c in cfgs], gmm
    )
    assert on[0].cache_info["donation"] is True
    for a, b in zip(on, off):
        assert _bitwise(a.params_history, b.params_history)
    assert all(not leaf.is_deleted() for leaf in _cached_stack_leaves())
    # a donating cohort rerun off the caches is bitwise identical too
    rerun = trainer.train_cohort(cfgs, gmm)
    assert rerun[0].cache_info["data_hit"]
    for a, b in zip(on, rerun):
        assert _bitwise(a.params_history, b.params_history)


def test_donation_survives_oom_bisection_and_cache_drop(gmm, monkeypatch):
    """Donating sweep + injected cohort OOM: _dispatch_cohort drops the
    data cache's HBM pins (cache.drop_data_cache) and bisects; the
    re-uploaded stacks feed donating retries and every row matches the
    sequential (batch='off') sweep — drop_data_cache still works
    mid-sweep with donation on."""
    configs = {
        f"{s}_d": _cfg(scheme=s, compute_mode="deduped", donate="on",
                       **extra)
        for s, extra in (
            ("naive", {}),
            ("avoidstragg", {}),
            ("approx", {"num_collect": 4}),
            ("cyccoded", {}),
        )
    }
    off_rows = experiments.compare(dict(configs), gmm, batch="off")
    dropped0 = cache_lib._METRICS.counter(
        "sweep_cache.data_dropped_bytes"
    ).value
    monkeypatch.setenv(chaos.CHAOS_ENV, "raise:cohort:1")
    chaos.reset()
    rows = experiments.compare(dict(configs), gmm, batch="on")
    monkeypatch.delenv(chaos.CHAOS_ENV)
    assert (
        cache_lib._METRICS.counter(
            "sweep_cache.data_dropped_bytes"
        ).value
        > dropped0
    ), "the OOM path must have dropped the data cache's pins"
    science = lambda rs: [journal_lib.science_row(s.row()) for s in rs]
    assert science(off_rows) == science(rows)
    # and the post-drop rebuilt cache is healthy: a fresh donating run hits
    again = trainer.train(configs["naive_d"], gmm)
    assert _bitwise(
        again.final_params,
        trainer.train(configs["naive_d"], gmm).final_params,
    )
