"""Per-layer coded training for deep models (ISSUE 9).

The load-bearing invariants:
  - the blockwise layer decode (ops/blocks.py + parallel/step.
    _layer_block_local_body) is BITWISE identical to the monolithic
    treewise decode over the same per-partition gradient pytrees — for
    every exact scheme's zero-straggling weights and for arbitrary
    weights (values are moved, never transformed);
  - deep-model trajectories under layer_coding="on" match the default
    monolithic path to float tolerance, sequential and cohort alike (the
    PR 4 cohort pin, repeated for mlp/attention);
  - MoE expert shards map to individual coded blocks (the expert is the
    partition unit of the blockwise decode);
  - the per-layer gradient-space decode error's cumulative-over-depth
    curve is monotone non-decreasing (obs/decode.block_decode_error);
  - the sparse_graph / expander code families decode the exact full
    gradient at zero straggling (partial decode == full gradient);
  - trace-driven straggler schedules round-trip through files and the
    config/env plumbing.
"""

import dataclasses
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from erasurehead_tpu.data.sharding import partition_stack
from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.obs import decode as obs_decode
from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.ops import blocks as blocks_lib
from erasurehead_tpu.parallel import collect, step as step_lib, straggler
from erasurehead_tpu.train import cache, evaluate, trainer
from erasurehead_tpu.utils.config import RunConfig

W, ROUNDS = 8, 3
N_ROWS, N_COLS = 256, 64


@pytest.fixture(scope="module")
def gmm():
    return generate_gmm(N_ROWS, N_COLS, n_partitions=W, seed=0)


@pytest.fixture(autouse=True)
def fresh_cache():
    cache.clear()
    cache.set_enabled(True)
    yield
    cache.clear()


def _cfg(**kw):
    base = dict(
        scheme="approx",
        model="mlp",
        n_workers=W,
        n_stragglers=1,
        num_collect=6,
        rounds=ROUNDS,
        n_rows=N_ROWS,
        n_cols=N_COLS,
        update_rule="GD",
        lr_schedule=0.1,
        add_delay=True,
        compute_mode="deduped",
        seed=3,
    )
    base.update(kw)
    return RunConfig(**base)


def _close(a_tree, b_tree, rtol=5e-4, atol=5e-5):
    for a, b in zip(jax.tree.leaves(a_tree), jax.tree.leaves(b_tree)):
        np.testing.assert_allclose(
            np.asarray(a, np.float64), np.asarray(b, np.float64),
            rtol=rtol, atol=atol,
        )


# ---------------------------------------------------------------------------
# block tables: round trip + the MoE expert-shard mapping


class TestBlockSpec:
    def test_round_trip_is_exact(self):
        from erasurehead_tpu.models.deep_mlp import DeepMLPModel

        model = DeepMLPModel(hidden=8, n_layers=3)
        params = model.init_params(jax.random.key(0), 16)
        spec = blocks_lib.model_block_spec(model, params)
        table = blocks_lib.tree_to_blocks(params, spec)
        assert table.shape == (spec.n_blocks, spec.width)
        back = blocks_lib.blocks_to_tree(table, spec)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()

    def test_deepmlp_layers_are_individual_blocks(self):
        from erasurehead_tpu.models.deep_mlp import DeepMLPModel

        model = DeepMLPModel(hidden=8, n_layers=5)
        params = model.init_params(jax.random.key(0), 16)
        spec = blocks_lib.model_block_spec(model, params)
        # W [5, H, H] and b [5, H] split per layer; W_in/b_in/w_out/b_out
        # stay one block each
        assert spec.n_blocks == 5 + 5 + 4

    def test_moe_expert_shards_are_the_coded_blocks(self):
        """The MoE partition mapping: every expert-stacked leaf splits
        along the expert axis, so each expert's gradient shard is its own
        coded block — one block per (expert, leaf) pair plus the gate."""
        from erasurehead_tpu.models.moe import MoEModel

        E = 4
        model = MoEModel(hidden=8, n_experts=E)
        params = model.init_params(jax.random.key(0), 16)
        spec = blocks_lib.model_block_spec(model, params)
        # W1/b1/w2/b2 split per expert (4 leaves x E blocks); Wg/bg whole
        assert spec.n_blocks == 4 * E + 2
        leaves, _ = jax.tree_util.tree_flatten_with_path(params)
        keys = [p[0].key for p, _ in leaves]
        split_rows = {
            keys[leaf_idx]: [row for li, row in spec.block_of if li == leaf_idx]
            for leaf_idx in range(len(keys))
        }
        for name in ("W1", "b1", "w2", "b2"):
            assert split_rows[name] == list(range(E)), name
        for name in ("Wg", "bg"):
            assert split_rows[name] == [0], name

    def test_padding_lanes_are_zero(self):
        from erasurehead_tpu.models.moe import MoEModel

        model = MoEModel(hidden=4, n_experts=2)
        params = model.init_params(jax.random.key(1), 8)
        spec = blocks_lib.model_block_spec(model, params)
        table = np.asarray(blocks_lib.tree_to_blocks(params, spec))
        for bi, (li, _) in enumerate(spec.block_of):
            size = spec.sizes_per_leaf[li]
            assert (table[bi, size:] == 0.0).all()


# ---------------------------------------------------------------------------
# the bitwise pin: blockwise decode == monolithic treewise decode


class TestBlockwiseDecodeBitwise:
    EXACT_SCHEMES = ("naive", "cyccoded", "repcoded")

    @pytest.mark.parametrize("model_name", ["deepmlp", "moe", "attention"])
    def test_bitwise_at_zero_straggling_across_exact_schemes(
        self, gmm, model_name
    ):
        for scheme in self.EXACT_SCHEMES:
            cfg = _cfg(scheme=scheme, model=model_name, add_delay=False)
            lay = trainer.build_layout(cfg)
            model = trainer.build_model(cfg)
            params = jax.tree.map(
                lambda x: x.astype(jnp.float32),
                model.init_params(jax.random.key(0), N_COLS),
            )
            spec = blocks_lib.model_block_spec(model, params)
            Xp, yp = partition_stack(gmm, lay.n_partitions)
            per_part = jax.vmap(
                lambda X, y: model.grad_sum(
                    params, jnp.asarray(X), jnp.asarray(y)
                )
            )(jnp.asarray(Xp), jnp.asarray(yp))
            sched = collect.build_schedule(
                cfg.scheme, np.zeros((2, W)), lay,
                num_collect=cfg.num_collect,
            )
            slot_w = np.asarray(
                step_lib.expand_slot_weights(
                    sched.message_weights, lay.coeffs,
                    np.asarray(lay.slot_is_coded),
                )
            )
            pw = jnp.asarray(
                lay.fold_slot_weights(slot_w)[0], jnp.float32
            )
            tree_dec = step_lib._weighted_tree_sum(pw, per_part, "p")
            table = jax.vmap(
                lambda g: blocks_lib.tree_to_blocks(g, spec)
            )(per_part)
            blk = jnp.einsum(
                "p,plk->lk", pw.astype(table.dtype), table,
                precision=lax.Precision.HIGHEST,
            )
            blk_dec = blocks_lib.blocks_to_tree(blk, spec)
            for a, b in zip(
                jax.tree.leaves(tree_dec), jax.tree.leaves(blk_dec)
            ):
                assert (
                    np.asarray(a).tobytes() == np.asarray(b).tobytes()
                ), (scheme, model_name)


# ---------------------------------------------------------------------------
# trajectory equivalence: sequential + cohort, across the deep families


class TestLayerCodedTrajectories:
    @pytest.mark.parametrize(
        "model_name,mode",
        [
            ("mlp", "deduped"),
            ("deepmlp", "faithful"),
            ("moe", "deduped"),
            ("attention", "deduped"),
        ],
    )
    def test_layer_on_matches_monolithic_train(self, gmm, model_name, mode):
        on = trainer.train(
            _cfg(model=model_name, compute_mode=mode, layer_coding="on"),
            gmm,
        )
        off = trainer.train(
            _cfg(model=model_name, compute_mode=mode, layer_coding="off"),
            gmm,
        )
        _close(on.params_history, off.params_history)
        np.testing.assert_array_equal(on.timeset, off.timeset)
        np.testing.assert_array_equal(on.decode_error, off.decode_error)

    @pytest.mark.parametrize("model_name", ["mlp", "attention"])
    def test_deep_cohort_matches_sequential_train(self, gmm, model_name):
        """The PR 4 pin, repeated for the deep families: a cohort member
        equals its own sequential train() to float tolerance with
        IDENTICAL control-plane artifacts."""
        cfgs = [
            _cfg(model=model_name, scheme=s, seed=sd, layer_coding="on",
                 **extra)
            for s, extra in (
                ("approx", {"num_collect": 6}), ("repcoded", {}),
            )
            for sd in (0, 1)
        ]
        results = trainer.train_cohort(cfgs, gmm)
        assert results[0].cache_info["cohort_lowering"] == "layer_block_vmap"
        for cfg, res in zip(cfgs, results):
            single = trainer.train(cfg, gmm)
            _close(res.params_history, single.params_history)
            np.testing.assert_array_equal(res.timeset, single.timeset)
            np.testing.assert_array_equal(res.collected, single.collected)
            np.testing.assert_array_equal(
                res.decode_error, single.decode_error
            )

    def test_layer_ring_bitwise_vs_materialized(self, gmm):
        ring = trainer.train(
            _cfg(model="mlp", scheme="repcoded", compute_mode="faithful",
                 stack_mode="ring", layer_coding="on"),
            gmm,
        )
        mat = trainer.train(
            _cfg(model="mlp", scheme="repcoded", compute_mode="faithful",
                 stack_mode="materialized", layer_coding="on"),
            gmm,
        )
        for a, b in zip(
            jax.tree.leaves(ring.params_history),
            jax.tree.leaves(mat.params_history),
        ):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()

    def test_fused_block_decode_bitwise_vs_treewise(self, gmm):
        """ISSUE 19: the fused per-leaf decode (block_decode="fused",
        ops/kernels.fused_block_decode) is a pure lowering of the treewise
        pack-then-einsum blockwise body — trajectories must be BITWISE
        identical, per model family and compute mode."""
        for model_name, mode in (
            ("deepmlp", "deduped"),
            ("mlp", "faithful"),
            ("moe", "deduped"),
        ):
            fused = trainer.train(
                _cfg(model=model_name, compute_mode=mode,
                     layer_coding="on", block_decode="fused"),
                gmm,
            )
            tree = trainer.train(
                _cfg(model=model_name, compute_mode=mode,
                     layer_coding="on", block_decode="treewise"),
                gmm,
            )
            for a, b in zip(
                jax.tree.leaves(fused.params_history),
                jax.tree.leaves(tree.params_history),
            ):
                assert (
                    np.asarray(a).tobytes() == np.asarray(b).tobytes()
                ), (model_name, mode)
            np.testing.assert_array_equal(
                fused.decode_error, tree.decode_error
            )

    def test_fused_block_decode_bitwise_on_ring(self, gmm):
        """The fused decode composes with ring-streamed faithful stacks
        without perturbing a single bit."""
        runs = {
            bd: trainer.train(
                _cfg(model="mlp", scheme="repcoded",
                     compute_mode="faithful", stack_mode="ring",
                     layer_coding="on", block_decode=bd),
                gmm,
            )
            for bd in ("fused", "treewise")
        }
        for a, b in zip(
            jax.tree.leaves(runs["fused"].params_history),
            jax.tree.leaves(runs["treewise"].params_history),
        ):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()

    def test_fused_block_decode_bitwise_in_cohort(self, gmm):
        """A fused-decode cohort packs into the same vmapped dispatch and
        stays bitwise against the treewise cohort, member by member."""
        def cohort(bd):
            cfgs = [
                _cfg(scheme=s, seed=sd, layer_coding="on",
                     block_decode=bd, **extra)
                for s, extra in (
                    ("approx", {"num_collect": 6}), ("repcoded", {}),
                )
                for sd in (0, 1)
            ]
            return trainer.train_cohort(cfgs, gmm)

        fused, tree = cohort("fused"), cohort("treewise")
        assert fused[0].cache_info["cohort_lowering"] == "layer_block_vmap"
        for f, t in zip(fused, tree):
            for a, b in zip(
                jax.tree.leaves(f.params_history),
                jax.tree.leaves(t.params_history),
            ):
                assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
            np.testing.assert_array_equal(f.collected, t.collected)

    def test_layer_on_refused_with_forced_lowerings(self):
        for kw in (
            {"flat_grad": "on"},
            {"margin_flat": "on"},
            {"use_pallas": "on"},
        ):
            with pytest.raises(ValueError, match="force at most one"):
                _cfg(layer_coding="on", **kw)
        with pytest.raises(ValueError, match="measured"):
            _cfg(
                layer_coding="on", arrival_mode="measured",
                compute_mode="faithful",
            )

    def test_layer_on_refused_with_model_internal_axes(self, gmm):
        cfg = _cfg(
            model="mlp", layer_coding="on", tp_shards=2,
            compute_mode="faithful",
        )
        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 devices for a tp mesh")
        with pytest.raises(ValueError, match="layer_coding"):
            trainer.train(cfg, generate_gmm(64, 16, n_partitions=4, seed=0))


# ---------------------------------------------------------------------------
# models-shelf pin: every family trains 2 rounds and replays finite


@pytest.mark.parametrize("model_name", ["mlp", "deepmlp", "moe", "attention"])
def test_model_shelf_two_round_smoke(gmm, model_name):
    cfg = _cfg(model=model_name, rounds=2)
    res = trainer.train(cfg, gmm)
    leaves = jax.tree.leaves(res.params_history)
    assert leaves and all(int(l.shape[0]) == 2 for l in leaves)
    model = trainer.build_model(cfg)
    ev = evaluate.replay(
        model, cfg.model, res.params_history,
        gmm.X_train[: res.n_train], gmm.y_train[: res.n_train],
        gmm.X_test, gmm.y_test,
    )
    assert np.isfinite(np.asarray(ev.training_loss)).all()


def test_deep_layers_knob_sets_depth(gmm):
    cfg = _cfg(model="deepmlp", deep_layers=6)
    model = trainer.build_model(cfg)
    assert model.n_layers == 6
    assert trainer.build_model(_cfg(model="deepmlp")).n_layers == 4
    with pytest.raises(ValueError, match="deep_layers"):
        _cfg(deep_layers=-1)


# ---------------------------------------------------------------------------
# decode-error-vs-depth telemetry


class TestDecodeErrorVsDepth:
    def _depth_errors(self, gmm, depth):
        cfg = _cfg(
            model="deepmlp", deep_layers=depth, layer_coding="on",
            num_collect=5, rounds=4,
        )
        res = trainer.train(cfg, gmm)
        model = trainer.build_model(cfg)
        spec = blocks_lib.model_block_spec(
            model, model.init_params(jax.random.key(0), N_COLS)
        )
        Xp, yp = partition_stack(gmm, res.layout.n_partitions)
        table = blocks_lib.partition_block_table(
            model, spec, res.final_params, Xp, yp
        )
        sched = collect.build_schedule(
            cfg.scheme, trainer.default_arrivals(cfg), res.layout,
            num_collect=cfg.num_collect,
        )
        return res, obs_decode.block_decode_error(
            res.layout, sched.message_weights, table
        )

    def test_cumulative_error_monotone_in_depth_under_straggling(self, gmm):
        res, errs = self._depth_errors(gmm, depth=6)
        # genuinely approximate rounds exist (AGC erasures under delays)
        assert (errs["per_block"] > 0).any()
        cum = errs["cumulative"]
        assert cum.shape[1] == 6 + 6 + 4
        # monotone non-decreasing along the depth axis, every round
        assert (np.diff(cum, axis=1) >= -1e-12).all()

    def test_exact_rounds_snap_to_zero(self, gmm):
        cfg = _cfg(
            model="deepmlp", scheme="cyccoded", layer_coding="on",
            add_delay=False, rounds=2,
        )
        res = trainer.train(cfg, gmm)
        model = trainer.build_model(cfg)
        spec = blocks_lib.model_block_spec(
            model, model.init_params(jax.random.key(0), N_COLS)
        )
        Xp, yp = partition_stack(gmm, res.layout.n_partitions)
        table = blocks_lib.partition_block_table(
            model, spec, res.final_params, Xp, yp
        )
        sched = collect.build_schedule(
            cfg.scheme, np.zeros((2, W)), res.layout
        )
        errs = obs_decode.block_decode_error(
            res.layout, sched.message_weights, table
        )
        assert (errs["per_block"] == 0.0).all()
        assert (errs["cumulative"] == 0.0).all()

    def test_layer_tagged_decode_events_validate(self, gmm, tmp_path):
        path = str(tmp_path / "events.jsonl")
        res, errs = self._depth_errors(gmm, depth=2)
        with events_lib.capture(path):
            run_id = events_lib.new_run_id()
            events_lib.emit_layer_decode_chunks(
                run_id, errs["per_block"], trajectory="t0"
            )
        assert events_lib.validate_file(path) == []
        recs = [json.loads(l) for l in open(path) if l.strip()]
        layers = {r["layer"] for r in recs if r["type"] == "decode"}
        assert layers == set(range(errs["per_block"].shape[1]))

    def test_validator_rejects_bad_layer_tag(self):
        lines = [
            json.dumps(
                {
                    "type": "decode", "seq": 0, "t": 0.0, "run_id": "r",
                    "first_round": 0, "n_rounds": 1, "error_mean": 0.0,
                    "error_max": 0.0, "exact": True, "layer": -2,
                }
            )
        ]
        errors = events_lib.validate_lines(lines)
        assert any("layer" in e for e in errors)


# ---------------------------------------------------------------------------
# the new code families


class TestNewCodeFamilies:
    @pytest.mark.parametrize("scheme", ["sparsegraph", "expander"])
    def test_partial_decode_equals_full_gradient_at_zero_straggling(
        self, scheme
    ):
        """The standard zero-straggling pin: with every message collected
        the lstsq decode reproduces the exact full gradient (fold
        weights == all-ones, decode error exactly 0)."""
        for Wn, s in ((12, 2), (8, 1), (30, 3)):
            cfg = RunConfig(
                scheme=scheme, n_workers=Wn, n_stragglers=s,
                num_collect=Wn, rounds=2, n_rows=Wn * 8, n_cols=16,
                update_rule="GD", lr_schedule=0.1, add_delay=False,
            )
            lay = trainer.build_layout(cfg)
            # every partition has degree exactly s+1
            E = lay.effective_matrix()
            np.testing.assert_array_equal(E.sum(axis=0), s + 1)
            sched = collect.build_schedule(
                cfg.scheme, np.zeros((3, Wn)), lay, num_collect=Wn
            )
            err = obs_decode.decode_error_series(
                lay, sched.message_weights
            )
            assert (err == 0.0).all(), (scheme, Wn, s)

    def test_registry_flags_and_config_surface(self):
        from erasurehead_tpu import schemes

        for name in ("sparsegraph", "expander"):
            desc = schemes.get(name)
            assert desc.builtin
            assert desc.needs_num_collect
            assert desc.cohort_batchable
            assert desc.optimal_decode is not None
            assert desc.sweep_num_collect(30) == 15
            with pytest.raises(ValueError, match="num_collect"):
                desc.build_schedule(
                    np.zeros((1, 8)), trainer.build_layout(
                        RunConfig(scheme=name, n_workers=8, n_stragglers=1)
                    ),
                )
        assert schemes.get("sparsegraph").seed_dependent_layout is True
        assert schemes.get("expander").seed_dependent_layout is False
        # expander layouts are seed-free: one stack for a whole seed sweep
        a = trainer.build_layout(
            RunConfig(scheme="expander", n_workers=8, n_stragglers=1, seed=0)
        )
        b = trainer.build_layout(
            RunConfig(scheme="expander", n_workers=8, n_stragglers=1, seed=9)
        )
        np.testing.assert_array_equal(a.assignment, b.assignment)

    def test_sparse_graph_ragged_loads_pad_with_zero_coeffs(self):
        lay = trainer.build_layout(
            RunConfig(
                scheme="sparsegraph", n_workers=16, n_stragglers=2,
                num_collect=16, seed=5,
            )
        )
        coeffs = np.asarray(lay.coeffs)
        # padded slots exist (ragged worker loads) and contribute nothing
        assert (coeffs == 0.0).any()
        assert ((coeffs == 0.0) | (coeffs == 1.0)).all()

    @pytest.mark.parametrize("scheme", ["sparsegraph", "expander"])
    def test_trains_and_cohorts(self, gmm, scheme):
        cfg = _cfg(scheme=scheme, model="logistic", num_collect=6)
        res = trainer.train(cfg, gmm)
        assert np.isfinite(
            np.asarray(jax.tree.leaves(res.final_params)[0])
        ).all()
        assert (res.decode_error >= 0).all()
        batch = trainer.train_cohort([cfg], gmm)
        _close(batch[0].params_history, res.params_history, rtol=2e-5,
               atol=1e-6)


# ---------------------------------------------------------------------------
# trace-driven stragglers


class TestArrivalTraces:
    def test_file_round_trip_and_tiling(self, tmp_path):
        rng = np.random.default_rng(0)
        trace = rng.exponential(0.5, (4, W))
        path = str(tmp_path / "trace.npy")
        np.save(path, trace)
        out = straggler.arrival_schedule(
            10, W, add_delay=True, trace=path
        )
        np.testing.assert_array_equal(out[:4], trace)
        np.testing.assert_array_equal(out[4:8], trace)  # tiled
        np.testing.assert_array_equal(out[8:], trace[:2])
        # csv round trip
        cpath = str(tmp_path / "trace.csv")
        np.savetxt(cpath, trace, delimiter=",")
        out_csv = straggler.arrival_schedule(4, W, False, trace=cpath)
        np.testing.assert_allclose(out_csv, trace, rtol=1e-12)

    def test_speed_multiplier_scales_rows(self):
        trace = np.ones((2, 4))
        speed = np.array([1.0, 2.0, 0.5, 1.0])
        out = straggler.arrival_schedule(
            2, 4, False, trace=trace, trace_speed=speed
        )
        np.testing.assert_array_equal(out, np.tile(speed, (2, 1)))

    def test_shape_and_value_validation(self, tmp_path):
        with pytest.raises(ValueError, match="workers"):
            straggler.replay_arrival_trace(np.ones((2, 3)), 4, 8)
        with pytest.raises(ValueError, match="negative"):
            straggler.load_arrival_trace(-np.ones((2, 3)))
        with pytest.raises(ValueError, match="non-empty"):
            straggler.load_arrival_trace(np.zeros((0, 3)))

    def test_config_and_env_plumbing(self, tmp_path, monkeypatch):
        from erasurehead_tpu.utils.config import ARRIVAL_TRACE_ENV

        trace = np.full((2, W), 0.25)
        path = str(tmp_path / "t.npy")
        np.save(path, trace)
        cfg = _cfg(arrival_trace=path)
        arr = trainer.default_arrivals(cfg)
        assert arr.shape == (ROUNDS, W)
        np.testing.assert_array_equal(arr[:2], trace)
        # env var kicks in when the config field is unset
        monkeypatch.setenv(ARRIVAL_TRACE_ENV, path)
        arr_env = trainer.default_arrivals(_cfg())
        np.testing.assert_array_equal(arr_env[:2], trace)
        monkeypatch.delenv(ARRIVAL_TRACE_ENV)
        # worker_speed_spread composes as a seeded multiplier on the rows
        cfg_s = _cfg(arrival_trace=path, worker_speed_spread=0.5)
        arr_s = trainer.default_arrivals(cfg_s)
        rng = np.random.default_rng(cfg_s.seed + 10_007)
        speed = rng.uniform(0.5, 1.5, W)
        np.testing.assert_allclose(arr_s[0], trace[0] * speed, rtol=1e-12)

    def test_trace_trains_end_to_end(self, gmm, tmp_path):
        path = str(tmp_path / "t.npy")
        np.save(path, np.random.default_rng(1).exponential(0.5, (ROUNDS, W)))
        res = trainer.train(_cfg(arrival_trace=path, scheme="deadline",
                                 deadline=1.0), gmm)
        assert res.sim_total_time > 0

    def test_measured_mode_refuses_traces(self):
        with pytest.raises(ValueError, match="measured"):
            _cfg(
                arrival_trace="x.npy", arrival_mode="measured",
                compute_mode="faithful",
            )

    def test_cli_flag_reaches_config(self):
        from erasurehead_tpu import cli as cli_lib

        ns = cli_lib._flags_parser().parse_args(
            ["--arrival-trace", "/tmp/t.npy", "--layer-coding", "on",
             "--deep-layers", "5", "--model", "deepmlp"]
        )
        cfg = cli_lib._flags_to_config(ns)
        assert cfg.arrival_trace == "/tmp/t.npy"
        assert cfg.layer_coding == "on"
        assert cfg.deep_layers == 5
