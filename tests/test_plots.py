"""Figure rendering (train/plots.py): files produced, degenerate inputs ok."""

import os

import numpy as np

from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.train import experiments, plots
from erasurehead_tpu.utils.config import RunConfig

W = 8


def _summaries():
    data = generate_gmm(16 * W, 16, n_partitions=W, seed=0)
    base = dict(
        n_workers=W, n_stragglers=1, rounds=6, n_rows=16 * W, n_cols=16,
        lr_schedule=1.0, update_rule="AGD", add_delay=True, seed=0,
    )
    cfgs = {
        "naive": RunConfig(scheme="naive", **base),
        "approx": RunConfig(scheme="approx", num_collect=5, **base),
    }
    return experiments.compare(cfgs, data)


def test_comparison_figure_renders(tmp_path):
    summaries = _summaries()
    out = str(tmp_path / "cmp.png")
    assert plots.save_comparison_figure(summaries, out, title="t") == out
    assert os.path.getsize(out) > 10_000


def test_comparison_handles_unreached_target(tmp_path):
    summaries = _summaries()
    summaries[0].time_to_target = None
    out = str(tmp_path / "cmp2.png")
    assert plots.save_comparison_figure(summaries, out) == out


def test_sweep_figure_renders(tmp_path):
    summaries = _summaries()
    sweep = {"approx": [s for s in summaries if s.label == "approx"]}
    out = str(tmp_path / "sweep.png")
    assert plots.save_sweep_figure(sweep, out, title="t") == out
    assert os.path.getsize(out) > 5_000


def test_scheme_colors_are_stable():
    """Color follows the scheme entity: filtering must not repaint."""
    assert plots.SCHEME_COLORS["naive"] == "#2a78d6"
    assert len(set(plots.SCHEME_COLORS.values())) == len(plots.SCHEME_COLORS)
