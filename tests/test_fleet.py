"""tools/tpu_fleet.py: command construction (dry-run) and inventory parsing.

The fleet controller replaces the reference's EC2 lifecycle tool
(tools/pytorch_ec2.py:935-948); these tests pin the gcloud command surface
and the get_hosts inventory format (pytorch_ec2.py:689-702 analogue) without
any network access.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import tpu_fleet  # noqa: E402


def make_fleet(**kw):
    return tpu_fleet.Fleet(
        name="eh", zone="us-central2-b", dry_run=True, **kw
    )


def test_launch_command_shape(capsys):
    f = make_fleet(accelerator_type="v4-32", spot=True)
    f.launch()
    assert f.log == [
        "gcloud compute tpus tpu-vm create eh --accelerator-type=v4-32 "
        "--version=tpu-ubuntu2204-base --spot --zone=us-central2-b"
    ]


def test_project_flag_appended():
    f = make_fleet(project="my-proj")
    f.shutdown()
    assert f.log[0].endswith("--zone=us-central2-b --project=my-proj")
    assert "delete eh --quiet" in f.log[0]


def test_run_command_fans_out_to_all_workers():
    f = make_fleet()
    f.run_command("echo hi")
    assert "ssh eh --worker=all" in f.log[0]
    assert "--command=echo hi" in f.log[0]


def test_kill_all_python_is_pkill():
    f = make_fleet()
    f.kill_all_python()
    assert "pkill -9 python" in f.log[0]


def test_sync_repo_scp_recurse():
    f = make_fleet()
    f.sync_repo("/repo")
    assert "scp --recurse /repo" in f.log[0]
    assert "eh:~/erasurehead-tpu" in f.log[0]
    assert "--worker=all" in f.log[0]


def test_launch_run_is_plain_ssh_fanout():
    """The mpirun replacement: the same command on every host, no hostfile."""
    f = make_fleet()
    f.launch_run("python -m erasurehead_tpu.cli --scheme approx")
    assert "--worker=all" in f.log[0]
    assert "erasurehead_tpu.cli" in f.log[0]


def test_hosts_parses_network_endpoints():
    f = make_fleet()
    info = {
        "state": "READY",
        "networkEndpoints": [
            {"ipAddress": "10.0.0.2", "accessConfig": {"externalIp": "34.1.2.3"}},
            {"ipAddress": "10.0.0.3"},
        ],
    }
    hosts = f.hosts(info)
    assert hosts == [
        {"index": 0, "internal_ip": "10.0.0.2", "external_ip": "34.1.2.3"},
        {"index": 1, "internal_ip": "10.0.0.3", "external_ip": None},
    ]


def test_write_hosts_files_reference_format(tmp_path):
    """hosts = 'ip alias' lines, hosts_address = bare ips
    (pytorch_ec2.py:689-702)."""
    f = make_fleet()
    info = {
        "networkEndpoints": [
            {"ipAddress": "10.0.0.2"},
            {"ipAddress": "10.0.0.3"},
        ]
    }
    paths = f.write_hosts_files(info, prefix=str(tmp_path))
    hosts = open(paths[0]).read().splitlines()
    addrs = open(paths[1]).read().splitlines()
    assert hosts == ["10.0.0.2 eh-host0", "10.0.0.3 eh-host1"]
    assert addrs == ["10.0.0.2", "10.0.0.3"]


def test_cli_dry_run_end_to_end(capsys):
    rc = tpu_fleet.main(
        ["--name", "eh", "--zone", "z", "--dry-run", "run_command", "date"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert out.startswith("[dry-run] gcloud compute tpus tpu-vm ssh eh")


def test_cli_status_dry_run(capsys):
    rc = tpu_fleet.main(["--name", "eh", "--zone", "z", "--dry-run", "status"])
    assert rc == 0
    lines = capsys.readouterr().out.splitlines()
    json_text = "\n".join(l for l in lines if not l.startswith("[dry-run]"))
    assert json.loads(json_text) == {"state": None, "hosts": []}


# ---------------------------------------------------------------------------
# k8s JobSet manifest: offline structural validation (the fleet lifecycle's
# k8s path, VERDICT r3 #6)
# ---------------------------------------------------------------------------

import copy

import pytest
import yaml

JOBSET = os.path.join(
    os.path.dirname(__file__), "..", "tools", "k8s", "jobset-v4-32.yaml"
)


def _load():
    with open(JOBSET) as f:
        return yaml.safe_load(f)


def _write(tmp_path, doc):
    p = tmp_path / "jobset.yaml"
    p.write_text(yaml.safe_dump(doc))
    return str(p)


def test_committed_jobset_validates():
    summary = tpu_fleet.validate_jobset(JOBSET)
    assert summary["name"] == "erasurehead-agc"
    assert summary["jobs"] == [
        {"name": "workers", "parallelism": 4, "topology": "2x2x4"}
    ]


def test_jobset_cli_subcommand(capsys):
    rc = tpu_fleet.main(["validate_jobset"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["name"] == "erasurehead-agc"


def _pod(doc):
    return doc["spec"]["replicatedJobs"][0]["template"]["spec"]["template"]["spec"]


def test_jobset_topology_mismatch_rejected(tmp_path):
    doc = _load()
    _pod(doc)["nodeSelector"]["cloud.google.com/gke-tpu-topology"] = "2x2x2"
    with pytest.raises(ValueError, match="topology"):
        tpu_fleet.validate_jobset(_write(tmp_path, doc))


def test_jobset_completions_mismatch_rejected(tmp_path):
    doc = _load()
    doc["spec"]["replicatedJobs"][0]["template"]["spec"]["completions"] = 3
    with pytest.raises(ValueError, match="completions"):
        tpu_fleet.validate_jobset(_write(tmp_path, doc))


def test_jobset_dangling_volume_mount_rejected(tmp_path):
    doc = _load()
    _pod(doc)["volumes"] = []
    with pytest.raises(ValueError, match="volumeMount"):
        tpu_fleet.validate_jobset(_write(tmp_path, doc))


def test_jobset_tpu_requests_limits_mismatch_rejected(tmp_path):
    doc = _load()
    _pod(doc)["containers"][0]["resources"]["limits"]["google.com/tpu"] = 8
    with pytest.raises(ValueError, match="requests must equal limits"):
        tpu_fleet.validate_jobset(_write(tmp_path, doc))


def test_jobset_tpu_quantity_string_accepted(tmp_path):
    """k8s quantities are YAML scalars: "4" and 4 are the same quantity
    and must not spuriously fail the requests==limits check (ADVICE r4)."""
    doc = _load()
    _pod(doc)["containers"][0]["resources"]["limits"]["google.com/tpu"] = "4"
    summary = tpu_fleet.validate_jobset(_write(tmp_path, doc))
    assert summary["jobs"][0]["topology"] == "2x2x4"


def test_jobset_topology_without_tpu_resource_rejected(tmp_path):
    """A pod selecting a TPU topology but declaring no google.com/tpu
    resources would never schedule onto TPU — reject it (ADVICE r4)."""
    doc = _load()
    del _pod(doc)["containers"][0]["resources"]["requests"]["google.com/tpu"]
    del _pod(doc)["containers"][0]["resources"]["limits"]["google.com/tpu"]
    with pytest.raises(ValueError, match="no container declares"):
        tpu_fleet.validate_jobset(_write(tmp_path, doc))


def test_jobset_non_integer_tpu_quantity_rejected(tmp_path):
    doc = _load()
    _pod(doc)["containers"][0]["resources"]["requests"]["google.com/tpu"] = "four"
    with pytest.raises(ValueError, match="not an integer chip count"):
        tpu_fleet.validate_jobset(_write(tmp_path, doc))


def test_jobset_tpu_limits_only_accepted(tmp_path):
    """k8s defaults extended-resource requests to limits — the documented
    GKE TPU pattern declares google.com/tpu under limits only."""
    doc = _load()
    del _pod(doc)["containers"][0]["resources"]["requests"]["google.com/tpu"]
    summary = tpu_fleet.validate_jobset(_write(tmp_path, doc))
    assert summary["jobs"][0]["topology"] == "2x2x4"


def test_jobset_tpu_requests_only_rejected(tmp_path):
    doc = _load()
    del _pod(doc)["containers"][0]["resources"]["limits"]["google.com/tpu"]
    with pytest.raises(ValueError, match="requests only"):
        tpu_fleet.validate_jobset(_write(tmp_path, doc))


def test_jobset_missing_cluster_env_rejected(tmp_path):
    """A training container without JAX_COORDINATOR_ADDRESS would run four
    independent single-process programs instead of one SPMD cluster."""
    doc = _load()
    _pod(doc)["containers"][0]["env"] = []
    with pytest.raises(ValueError, match="JAX_COORDINATOR_ADDRESS"):
        tpu_fleet.validate_jobset(_write(tmp_path, doc))


def test_jobset_num_processes_parallelism_mismatch_rejected(tmp_path):
    doc = _load()
    for ev in _pod(doc)["containers"][0]["env"]:
        if ev["name"] == "JAX_NUM_PROCESSES":
            ev["value"] = "8"
    with pytest.raises(ValueError, match="must equal parallelism"):
        tpu_fleet.validate_jobset(_write(tmp_path, doc))


def test_jobset_nonpositive_tpu_quantity_rejected(tmp_path):
    doc = _load()
    res = _pod(doc)["containers"][0]["resources"]
    res["requests"]["google.com/tpu"] = 0
    res["limits"]["google.com/tpu"] = 0
    with pytest.raises(ValueError, match="must be >= 1"):
        tpu_fleet.validate_jobset(_write(tmp_path, doc))


@pytest.mark.skipif(
    "not __import__('conftest').CPU_CLUSTER_SUPPORTED",
    reason="this jaxlib's CPU backend cannot compile multiprocess "
    "computations (see conftest.CPU_CLUSTER_SUPPORTED)",
)
def test_jobset_command_executes_in_local_pod_emulation(tmp_path):
    """Beyond structural validation (VERDICT r4 weak #6): execute the
    manifest's ACTUAL container command as a local 2-process
    jax.distributed cluster — the JobSet pod lifecycle emulated end to
    end. Each 'pod' gets its own emptyDir-style volume with the prepared
    layout, its rank via k8s's JOB_COMPLETION_INDEX (what a real indexed
    Job injects), and runs the manifest's bash -c script with only the
    environment-bound knobs substituted (image path -> checkout, volume
    path -> tmp dir, 16-host shape -> 2-process scale). Every
    substitution must match exactly once, so manifest drift fails here
    rather than at kubectl apply."""
    import re
    import shutil
    import subprocess

    from conftest import cpu_cluster_env, free_port

    doc = _load()
    cmd = (_pod(doc)["containers"][0])["command"]
    assert cmd[:2] == ["bash", "-c"]
    script = cmd[2]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    W, ROWS, COLS = 4, 32, 16

    def sub(pattern, repl, s):
        s2, n = re.subn(pattern, repl, s)
        assert n == 1, f"manifest drifted: {pattern!r} not found once"
        return s2

    base = sub(r"cd /opt/erasurehead-tpu\b", f"cd {repo}", script)
    base = sub(r"--workers 16\b", f"--workers {W}", base)
    base = sub(r"--stragglers 3\b", "--stragglers 1", base)
    base = sub(r"--num-collect 8\b", "--num-collect 3", base)
    base = sub(r"--rounds 100\b", "--rounds 3", base)
    base = sub(r"--rows 396112\b", f"--rows {ROWS}", base)
    base = sub(r"--cols 100\b", f"--cols {COLS}", base)

    from erasurehead_tpu.data.io import write_reference_layout
    from erasurehead_tpu.data.synthetic import generate_gmm

    data = generate_gmm(ROWS, COLS, n_partitions=W, seed=0)
    layout0 = tmp_path / "pod0" / "artificial-data" / f"{ROWS}x{COLS}" / str(W)
    write_reference_layout(data, str(layout0), W)
    shutil.copytree(tmp_path / "pod0", tmp_path / "pod1")

    # cluster-formation env comes FROM the manifest (not invented here):
    # the JobSet service DNS becomes loopback, the host count becomes the
    # emulation's process count — both presence-asserted so a manifest
    # that drops them fails this test the way it would fail on GKE
    manifest_env = {
        ev["name"]: ev["value"]
        for ev in _pod(doc)["containers"][0].get("env") or []
    }
    assert "JAX_COORDINATOR_ADDRESS" in manifest_env, manifest_env
    assert manifest_env.get("JAX_NUM_PROCESSES") == "4", manifest_env
    env = cpu_cluster_env(
        local_devices=2,
        JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{free_port()}",
        JAX_NUM_PROCESSES="2",
        PYTHONPATH=repo,
    )
    procs = []
    for rank in (0, 1):
        pod = tmp_path / f"pod{rank}"
        pod_script = sub(
            r"--input-dir /data/straggdata", f"--input-dir {pod}", base
        )
        procs.append(subprocess.Popen(
            ["bash", "-c", pod_script],
            env={**env, "JOB_COMPLETION_INDEX": str(rank)},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        ))
    try:
        logs = [p.communicate(timeout=420)[0].decode() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"pod failed:\n{log[-3000:]}"
    # every pod ran the full train -> eval -> artifact pipeline into its
    # own volume, like a real pod writing its emptyDir (default artifact
    # placement is beside the dataset: <input>/<dataset-path>/results)
    for rank in (0, 1):
        results = (tmp_path / f"pod{rank}" / "artificial-data"
                   / f"{ROWS}x{COLS}" / str(W) / "results")
        names = os.listdir(results)
        for part in ("training_loss", "auc", "timeset", "worker_timeset"):
            assert any(part in n for n in names), (rank, part, names)


def test_jobset_embedded_cli_drift_rejected(tmp_path):
    """The manifest's training command is parsed against the REAL CLI
    surface: renaming a flag in cli.py (or typoing one in the yaml) fails
    validation instead of failing at pod runtime."""
    doc = _load()
    c = _pod(doc)["containers"][0]
    c["command"] = ["bash", "-c",
                    "python -m erasurehead_tpu.cli --no-such-flag 1"]
    with pytest.raises(ValueError, match="unknown flags|does not parse"):
        tpu_fleet.validate_jobset(_write(tmp_path, doc))
