"""Sweep-as-a-service: multi-tenant cohort packing with admission control.

The load-bearing invariants:
  - the packer groups by (cohort signature, dataset identity) and NOTHING
    else: same-signature requests from different tenants share a
    dispatch; distinct datasets, memory knobs, or cohort-ineligible
    configs never do;
  - packing is a pure throughput lever: under the daemon's fixed-width
    dispatch, a request packed with strangers and the same request
    dispatched alone produce BITWISE identical science rows;
  - admission control bounds in-flight footprint: an over-footprint
    cohort QUEUES (retried after running dispatches release) rather than
    joining the running cohort's HBM; an impossible-even-alone cohort
    admits alone instead of deadlocking;
  - fault isolation is per-tenant: one request's failure or divergence
    never touches another tenant's results, and per-tenant journals give
    resubmitted requests bitwise rehydration with no dispatch;
  - the journal file survives CONCURRENT WRITERS (threads and processes)
    without a torn line — the serve daemon's whole persistence story
    rests on the O_APPEND single-write emission.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.obs.metrics import REGISTRY
from erasurehead_tpu.serve import admission as admission_lib
from erasurehead_tpu.serve import packer as packer_lib
from erasurehead_tpu.serve import queue as serve_queue
from erasurehead_tpu.serve import server as serve_server
from erasurehead_tpu.serve.client import ServeClient
from erasurehead_tpu.train import cache, experiments
from erasurehead_tpu.train import journal as journal_lib
from erasurehead_tpu.utils.config import (
    RunConfig,
    parse_bytes,
    resolve_serve_budget,
    resolve_serve_max_cohort,
)

W, R = 4, 3
N_ROWS, N_COLS = 64, 8


@pytest.fixture(scope="module")
def gmm():
    return generate_gmm(N_ROWS, N_COLS, n_partitions=W, seed=0)


@pytest.fixture(autouse=True)
def fresh_state():
    cache.clear()
    yield
    cache.clear()


def _cfg(**kw):
    base = dict(
        scheme="naive", n_workers=W, n_stragglers=1, rounds=R,
        n_rows=N_ROWS, n_cols=N_COLS, update_rule="AGD", lr_schedule=0.5,
        add_delay=True, seed=0, compute_mode="deduped",
    )
    base.update(kw)
    return RunConfig(**base)


def _req(gmm, tenant="t", label="naive", **cfg_kw):
    return serve_queue.RunRequest(
        tenant=tenant, label=label, config=_cfg(**cfg_kw), dataset=gmm
    )


def _science(summary) -> str:
    return json.dumps(
        journal_lib.science_row(journal_lib.summary_payload(summary)),
        sort_keys=True,
    )


def _counter(name):
    return REGISTRY.counter(name).value


# ---------------------------------------------------------------------------
# packer


class TestPacker:
    def test_same_signature_packs_across_tenants(self, gmm):
        reqs = [
            _req(gmm, tenant=f"t{k}", label=f"r{k}", seed=k)
            for k in range(4)
        ]
        packs = packer_lib.plan_packs(reqs)
        assert len(packs) == 1 and packs[0].batchable
        assert packs[0].tenants == ["t0", "t1", "t2", "t3"]

    def test_distinct_datasets_never_pack(self, gmm):
        other = generate_gmm(N_ROWS, N_COLS, n_partitions=W, seed=0)
        packs = packer_lib.plan_packs(
            [_req(gmm), _req(other, tenant="u")]
        )
        assert len(packs) == 2

    def test_memory_knobs_never_pack(self, gmm):
        reqs = [
            _req(gmm, tenant="a"),
            _req(gmm, tenant="b", stack_dtype="int8"),
        ]
        packs = packer_lib.plan_packs(reqs)
        assert len(packs) == 2

    def test_ineligible_is_sequential_singleton(self, gmm):
        packs = packer_lib.plan_packs(
            [_req(gmm, arrival_mode="measured", compute_mode="faithful")]
        )
        assert len(packs) == 1 and not packs[0].batchable
        assert packs[0].key is None

    def test_max_cohort_chunks(self, gmm):
        reqs = [_req(gmm, label=f"r{k}", seed=k) for k in range(5)]
        packs = packer_lib.plan_packs(reqs, max_cohort=2)
        assert [len(p.requests) for p in packs] == [2, 2, 1]
        with pytest.raises(ValueError, match="max_cohort"):
            packer_lib.plan_packs(reqs, max_cohort=0)


# ---------------------------------------------------------------------------
# admission controller (unit: no training, real footprint arithmetic)


class TestAdmission:
    def test_over_footprint_queues_until_release(self, gmm):
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        est = admission_lib.estimate_cohort_bytes(cohort)
        ctl = admission_lib.AdmissionController(budget_bytes=est)
        d0 = _counter("serve.deferred")
        assert ctl.try_admit(cohort, "d1")
        # second identical cohort exceeds the budget while d1 is in
        # flight: it must QUEUE (deferred), not join
        assert not ctl.try_admit(cohort, "d2")
        assert _counter("serve.deferred") == d0 + 1
        ctl.release("d1")
        assert ctl.try_admit(cohort, "d2")
        ctl.release("d2")
        assert ctl.in_flight_bytes == 0

    def test_impossible_alone_admits_instead_of_deadlocking(self, gmm):
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        ctl = admission_lib.AdmissionController(budget_bytes=1)
        assert ctl.try_admit(cohort, "d1")  # idle daemon: admit + warn

    def test_eviction_admits_in_the_same_call(self, gmm):
        """Data-cache pins count in the admission inequality, so dropping
        them genuinely changes the post-evict recheck: an idle daemon
        whose cache is the only blocker must evict AND admit in one
        try_admit call — never drop the cache and then strand the cohort
        (nothing else would ever bump the serve loop's generation)."""
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        est = admission_lib.estimate_cohort_bytes(cohort)
        ctl = admission_lib.AdmissionController(budget_bytes=est)
        cache._data_cache["pin"] = (None, 123)  # est fits; est + pins won't
        e0 = _counter("serve.evictions")
        assert ctl.try_admit(cohort, "d1")
        assert _counter("serve.evictions") == e0 + 1
        assert cache.data_cache_bytes() == 0
        ctl.release("d1")

    def test_idle_evicts_then_admits_alone_when_still_over(self, gmm):
        """Over-budget even after eviction, on an idle daemon: evict (the
        oversized dispatch wants every byte) and fall through to the
        admit-alone path in the same call."""
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        ctl = admission_lib.AdmissionController(budget_bytes=1)
        cache._data_cache["pin"] = (None, 999)
        assert ctl.try_admit(cohort, "d1")
        assert cache.data_cache_bytes() == 0
        ctl.release("d1")

    def test_busy_daemon_defers_without_pointless_eviction(self, gmm):
        """When live dispatches (not the cache) are the blocker, defer
        WITHOUT dropping the cache: eviction that cannot change the
        verdict just burns a warm cache for nothing."""
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        est = admission_lib.estimate_cohort_bytes(cohort)
        ctl = admission_lib.AdmissionController(budget_bytes=est)
        assert ctl.try_admit(cohort, "d1")
        cache._data_cache["pin"] = (None, 7)
        assert not ctl.try_admit(cohort, "d2")
        assert cache.data_cache_bytes() == 7  # cache kept warm
        ctl.release("d1")

    def test_admit_events_and_measured_ratchet(self, gmm, tmp_path):
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        est = admission_lib.estimate_cohort_bytes(cohort)
        ctl = admission_lib.AdmissionController(budget_bytes=est)
        path = str(tmp_path / "admit.jsonl")
        with events_lib.capture(path):
            ctl.try_admit(cohort, "d1")
            ctl.try_admit(cohort, "d2")
        recs = [json.loads(l) for l in open(path) if l.strip()]
        admits = [r for r in recs if r["type"] == "admit"]
        assert [a["admitted"] for a in admits] == [True, False]
        assert all(a["est_bytes"] >= 0 for a in admits)
        assert events_lib.validate_file(path) == []
        # measured memory_analysis only ever ratchets the estimate UP
        ctl.observe(cohort, {"memory_analysis": {"argument_bytes": 10}})
        assert ctl.charge_for(cohort) == est
        big = {"argument_bytes": est, "temp_bytes": est}
        ctl.observe(cohort, {"memory_analysis": big})
        assert ctl.charge_for(cohort) == 2 * est

    def test_budget_resolvers(self):
        assert parse_bytes("2g") == 2 << 30
        assert parse_bytes("512m") == 512 << 20
        assert parse_bytes("1024") == 1024
        with pytest.raises(ValueError):
            parse_bytes("lots")
        with pytest.raises(ValueError):
            parse_bytes("-4k")
        assert resolve_serve_budget(None, env="") is None
        assert resolve_serve_budget("1m") == 1 << 20
        assert resolve_serve_budget(None, env="2k") == 2048
        assert resolve_serve_max_cohort(None, env="") == 64
        assert resolve_serve_max_cohort(8) == 8
        assert resolve_serve_max_cohort(None, env="16") == 16
        with pytest.raises(ValueError):
            resolve_serve_max_cohort(0)


# ---------------------------------------------------------------------------
# the serving contract: packing, bitwise invariance, streaming results


class TestServeDispatch:
    def test_concurrent_clients_pack_and_rows_are_bitwise(self, gmm):
        """4 concurrent tenants' same-signature requests share dispatches
        (serve.dispatches < requests) and every row is bitwise identical
        to the same request dispatched ALONE through the daemon — packing
        changes throughput, never bits."""
        specs = [
            (f"t{k}", f"{s}_{k}", dict(scheme=s, seed=k, **extra))
            for k in range(4)
            for s, extra in (
                ("naive", {}),
                ("approx", {"num_collect": 3}),
            )
        ]
        d0 = _counter("serve.dispatches")
        with serve_server.serving(window_s=0.2, max_cohort=8) as srv:
            handles = []
            lock = threading.Lock()

            def client(tenant):
                for tn, label, kw in specs:
                    if tn != tenant:
                        continue
                    h = srv.submit(
                        tenant=tn, label=label, config=_cfg(**kw),
                        dataset=gmm,
                    )
                    with lock:
                        handles.append(h)

            threads = [
                threading.Thread(target=client, args=(f"t{k}",))
                for k in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            packed = {
                h.result(timeout=120).label: h.result() for h in handles
            }
        packed_dispatches = _counter("serve.dispatches") - d0
        assert packed_dispatches < len(specs)
        assert {r.status for r in packed.values()} == {"ok"}

        # one at a time, fresh daemon, same fixed width: bitwise equal
        with serve_server.serving(window_s=0.001, max_cohort=8) as srv:
            for tn, label, kw in specs:
                res = srv.submit(
                    tenant=tn, label=label, config=_cfg(**kw), dataset=gmm
                ).result(timeout=120)
                assert _science(res.summary) == _science(
                    packed[label].summary
                ), f"row {label} changed bits when packed"

    def test_results_match_plain_compare_to_tolerance(self, gmm):
        """Serve rows agree with a local compare() of the same configs to
        float tolerance (widths differ, so tolerance not bitwise), and
        the control-plane columns are identical."""
        cfgs = {
            "naive": _cfg(),
            "agc": _cfg(scheme="approx", num_collect=3),
        }
        arrivals = {
            label: experiments.trainer.default_arrivals(c)
            for label, c in cfgs.items()
        }
        with serve_server.serving(window_s=0.1) as srv:
            rows = {}
            for label, c in cfgs.items():
                rows[label] = srv.submit(
                    tenant="t", label=label, config=c, dataset=gmm,
                    arrivals=arrivals[label],
                ).result(timeout=120).summary
        for label, c in cfgs.items():
            local = experiments.compare(
                {label: c}, gmm, arrivals=arrivals[label], batch="off"
            )[0]
            s = rows[label]
            assert s.sim_total_time == local.sim_total_time
            np.testing.assert_array_equal(s.timeset, local.timeset)
            np.testing.assert_allclose(
                s.training_loss, local.training_loss, rtol=2e-5, atol=1e-6
            )
            assert s.status == local.status == "ok"

    def test_admission_queues_behind_running_cohort(self, gmm, monkeypatch):
        """Integration form of the admission bar: with a budget of one
        cohort, a second (incompatible-signature) request QUEUES while the
        first dispatch runs — serve.deferred increments and its result
        arrives after the first's — instead of dispatching into the
        running cohort's memory."""
        real_dispatch = experiments._dispatch_cohort
        order = []

        def slow_dispatch(labels, configs, dataset, arrivals):
            out = real_dispatch(labels, configs, dataset, arrivals)
            time.sleep(0.4)
            order.append(tuple(labels))
            return out

        monkeypatch.setattr(experiments, "_dispatch_cohort", slow_dispatch)
        one = packer_lib.plan_packs([_req(gmm)])[0]
        budget = admission_lib.estimate_cohort_bytes(one, width=2) + 1
        d0 = _counter("serve.deferred")
        with serve_server.serving(
            budget_bytes=budget, window_s=0.01, max_cohort=2,
        ) as srv:
            h1 = srv.submit(
                tenant="a", label="first", config=_cfg(), dataset=gmm
            )
            time.sleep(0.15)  # first cohort is admitted and in flight
            h2 = srv.submit(
                tenant="b", label="second",
                config=_cfg(scheme="approx", num_collect=3,
                            stack_dtype="bfloat16", dtype="bfloat16"),
                dataset=gmm,
            )
            r1 = h1.result(timeout=120)
            r2 = h2.result(timeout=120)
        assert r1.status == "ok" and r2.status == "ok"
        assert _counter("serve.deferred") > d0
        # tenant a's dispatch finished before tenant b's ever started
        assert order and order[0][0].startswith("a-req")

    def test_divergence_quarantined_per_tenant(self, gmm):
        with serve_server.serving(window_s=0.1) as srv:
            bad = srv.submit(
                tenant="boomer", label="boom",
                config=_cfg(scheme="avoidstragg", lr_schedule=1e12,
                            model="linear"),
                dataset=gmm,
            )
            good = srv.submit(
                tenant="steady", label="fine", config=_cfg(), dataset=gmm
            )
            rb, rg = bad.result(timeout=120), good.result(timeout=120)
        assert rb.status == "diverged"
        assert rg.status == "ok"
        assert np.isfinite(rg.summary.final_train_loss)

    def test_request_error_is_isolated(self, gmm):
        with serve_server.serving(window_s=0.05) as srv:
            broken = srv.submit(
                tenant="t", label="broken",
                config=_cfg(dataset="covtype", is_real_data=True,
                            input_dir="/nonexistent", n_rows=64, n_cols=8),
            )
            rb = broken.result(timeout=60)
            healthy = srv.submit(
                tenant="t", label="ok", config=_cfg(), dataset=gmm
            )
            rh = healthy.result(timeout=120)
        assert rb.status == "error" and "FileNotFoundError" in rb.error
        assert rh.status == "ok"

    def test_per_tenant_journal_resume(self, gmm, tmp_path):
        jdir = str(tmp_path / "serve-journal")
        cfg = _cfg()
        with serve_server.serving(
            window_s=0.05, journal_dir=jdir
        ) as srv:
            first = srv.submit(
                tenant="alice", label="naive", config=cfg, dataset=gmm
            ).result(timeout=120)
        jpath = os.path.join(jdir, "alice", journal_lib.JOURNAL_NAME)
        assert os.path.exists(jpath)
        assert events_lib.validate_file(jpath) == []
        d0 = _counter("serve.dispatches")
        r0 = _counter("serve.resumed")
        with serve_server.serving(
            window_s=0.05, journal_dir=jdir
        ) as srv:
            again = srv.submit(
                tenant="alice", label="naive", config=cfg, dataset=gmm
            ).result(timeout=60)
            # same label, DIFFERENT tenant: bob's journal is empty, his
            # request really dispatches (per-tenant isolation)
            bob = srv.submit(
                tenant="bob", label="naive", config=cfg, dataset=gmm
            ).result(timeout=120)
        assert again.resumed and not bob.resumed
        assert _counter("serve.resumed") == r0 + 1
        assert _counter("serve.dispatches") == d0 + 1
        assert json.dumps(again.row, sort_keys=True) == json.dumps(
            first.row, sort_keys=True
        )


# ---------------------------------------------------------------------------
# socket front


class TestSocketFront:
    def test_submit_roundtrip_and_bad_payload(self, tmp_path):
        sock = str(tmp_path / "eh.sock")
        with serve_server.serving(window_s=0.05) as srv:
            front = serve_server.SocketFront(srv, sock)
            try:
                client = ServeClient(sock)
                rid = client.submit(
                    "wire-tenant", "naive-wire",
                    {
                        "scheme": "naive", "n_workers": W,
                        "n_stragglers": 1, "rounds": R, "n_rows": N_ROWS,
                        "n_cols": N_COLS, "lr_schedule": 0.5,
                        "add_delay": True, "compute_mode": "deduped",
                    },
                )
                res = client.result(timeout=180)
                assert res["request_id"] == rid
                assert res["status"] == "ok"
                assert res["row"]["label"] == "naive-wire"
                # unknown fields are refused loudly, not trained around
                with pytest.raises(RuntimeError, match="unserveable"):
                    client.submit("w", "bad", {"scheme": "naive",
                                               "warp_drive": 9})
                # the daemon must not accept host-path fields over the wire
                with pytest.raises(RuntimeError, match="unserveable"):
                    client.submit("w", "bad2", {"input_dir": "/etc"})
                client.close()
            finally:
                front.close()
        assert not os.path.exists(sock)

    def test_config_from_payload_validates(self):
        cfg = serve_queue.config_from_payload(
            {"scheme": "approx", "n_workers": 8, "num_collect": 4}
        )
        assert cfg.scheme.value == "approx" and cfg.num_collect == 4
        with pytest.raises(ValueError, match="unserveable"):
            serve_queue.config_from_payload({"input_dir": "/x"})
        with pytest.raises(ValueError, match="JSON object"):
            serve_queue.config_from_payload(["not", "a", "dict"])


# ---------------------------------------------------------------------------
# serve event records: validator coverage


class TestServeEventSchema:
    def _validate(self, recs):
        lines = [
            json.dumps({"seq": i, "t": 0.0, **r})
            for i, r in enumerate(recs)
        ]
        return events_lib.validate_lines(lines)

    def test_valid_serve_stream(self):
        assert self._validate([
            {"type": "request", "tenant": "a", "request_id": "a-req-1",
             "label": "agc"},
            {"type": "pack", "n_trajectories": 2, "labels": ["x", "y"],
             "tenants": ["a", "b"]},
            {"type": "admit", "est_bytes": 100, "budget_bytes": None,
             "admitted": True},
            {"type": "admit", "est_bytes": 100, "budget_bytes": 50,
             "admitted": False},
            {"type": "evict", "reason": "data_cache_pressure"},
        ]) == []

    def test_invalid_serve_records_named(self):
        errors = self._validate([
            {"type": "request", "tenant": "", "request_id": "r",
             "label": "l"},
            {"type": "pack", "n_trajectories": 3, "labels": ["x"],
             "tenants": []},
            {"type": "admit", "est_bytes": -5, "budget_bytes": 10},
            {"type": "evict", "reason": ""},
            {"type": "pack", "n_trajectories": 1, "labels": "x",
             "tenants": ["a"]},
        ])
        joined = "\n".join(errors)
        assert "request tenant" in joined
        assert "pack n_trajectories 3 != 1 labels" in joined
        assert "pack tenants must be a non-empty list" in joined
        assert "admit est_bytes" in joined
        assert "evict reason" in joined
        assert "pack labels must be a list" in joined


# ---------------------------------------------------------------------------
# journal under concurrent writers (the satellite contract)


_WRITER_SNIPPET = """
import sys, time
sys.path.insert(0, {root!r})
from erasurehead_tpu.obs import events as events_lib
lg = events_lib.EventLogger({path!r}, mode="a")
for i in range({n}):
    lg.emit(
        "sweep_trajectory",
        key=f"{tag}-{{i}}",
        label=f"{tag}-{{i}}",
        status="ok",
        row={{"writer": {tag!r}, "i": i, "pad": "x" * 256}},
    )
    time.sleep(0.001)
lg.close()
"""


class TestConcurrentJournalWriters:
    def test_interleaved_processes_never_corrupt(self, tmp_path):
        """Several PROCESSES appending to one sweep_journal.jsonl (the
        serve daemon next to a local sweep, or two daemons) interleave
        whole lines, never torn ones: every record every writer emitted
        is present and parseable, and the validator accepts the file."""
        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        path = str(tmp_path / journal_lib.JOURNAL_NAME)
        n, writers = 40, 4
        procs = [
            subprocess.Popen(
                [
                    sys.executable, "-c",
                    _WRITER_SNIPPET.format(
                        root=root, path=path, n=n, tag=f"w{k}"
                    ),
                ],
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            for k in range(writers)
        ]
        for p in procs:
            assert p.wait(timeout=120) == 0
        lines = [l for l in open(path) if l.strip()]
        assert len(lines) == n * writers
        recs = [json.loads(l) for l in lines]  # every line parses whole
        keys = {r["key"] for r in recs}
        assert keys == {
            f"w{k}-{i}" for k in range(writers) for i in range(n)
        }
        assert events_lib.validate_file(path) == []
        # and a resuming journal reads the union
        j = journal_lib.SweepJournal(str(tmp_path), resume=True)
        assert len(j) == n * writers
        j.close()

    def test_interleaved_threads_one_logger(self, tmp_path):
        """Threads sharing one EventLogger (the daemon's dispatch pool)
        keep seq strictly monotonic and lines whole."""
        path = str(tmp_path / "events.jsonl")
        lg = events_lib.EventLogger(path, mode="a")

        def write(tag):
            for i in range(50):
                lg.emit("warning", kind="t", message=f"{tag}-{i}")

        threads = [
            threading.Thread(target=write, args=(f"th{k}",))
            for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        lg.close()
        assert events_lib.validate_file(path) == []
        msgs = [json.loads(l)["message"] for l in open(path)]
        assert len(msgs) == 200 and len(set(msgs)) == 200

    def test_thread_safe_sweep_journal_record(self, gmm, tmp_path):
        """SweepJournal.record from concurrent threads (the dispatch
        pool): every row lands, file validates."""
        rows = experiments.compare({"naive": _cfg()}, gmm, batch="off")
        j = journal_lib.SweepJournal(str(tmp_path), resume=False)

        def rec(k):
            for i in range(20):
                j.record(f"k{k}-{i}", f"l{k}-{i}", rows[0])

        threads = [
            threading.Thread(target=rec, args=(k,)) for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        j.close()
        assert len(j) == 80
        assert events_lib.validate_file(j.path) == []


# ---------------------------------------------------------------------------
# footprint estimate + report section


def test_estimate_stack_bytes_modes(gmm):
    from erasurehead_tpu.train import trainer

    ded = trainer.estimate_stack_bytes(_cfg(), gmm)
    faith = trainer.estimate_stack_bytes(
        _cfg(scheme="cyccoded", compute_mode="faithful"), gmm
    )
    ring = trainer.estimate_stack_bytes(
        _cfg(scheme="cyccoded", compute_mode="faithful",
             stack_mode="ring"), gmm
    )
    # the faithful materialized stack carries the (s+1)x redundancy; the
    # ring stack and the deduped stack are partition-major
    assert faith == 2 * ded
    assert ring == ded
    int8 = trainer.estimate_stack_bytes(_cfg(stack_dtype="int8"), gmm)
    assert int8 < ded  # 1/4 payload + scale tables

    cohort = packer_lib.plan_packs([_req(gmm)])[0]
    assert admission_lib.estimate_cohort_bytes(cohort, width=8) > (
        admission_lib.estimate_cohort_bytes(cohort, width=1)
    )


def test_report_renders_per_tenant_serve_section(gmm, tmp_path, capsys):
    from erasurehead_tpu.obs import report as report_lib

    path = str(tmp_path / "serve_events.jsonl")
    with events_lib.capture(path):
        with serve_server.serving(window_s=0.1) as srv:
            srv.submit(
                tenant="alice", label="ok", config=_cfg(), dataset=gmm
            ).result(timeout=120)
            srv.submit(
                tenant="bob", label="boom",
                config=_cfg(scheme="avoidstragg", lr_schedule=1e12,
                            model="linear"),
                dataset=gmm,
            ).result(timeout=120)
    assert events_lib.validate_file(path) == []
    assert report_lib.main([path, "--validate"]) == 0
    out = capsys.readouterr().out
    assert "serve (multi-tenant cohort packing)" in out
    assert "alice" in out and "bob" in out
    # bob's diverged row is counted in his tenant line
    bob_line = [l for l in out.splitlines() if l.strip().startswith("bob")]
    assert bob_line and bob_line[0].split()[-2] == "1"
