"""Sweep-as-a-service: multi-tenant cohort packing with admission control.

The load-bearing invariants:
  - the packer groups by (cohort signature, dataset identity) and NOTHING
    else: same-signature requests from different tenants share a
    dispatch; distinct datasets, memory knobs, or cohort-ineligible
    configs never do;
  - packing is a pure throughput lever: under the daemon's fixed-width
    dispatch, a request packed with strangers and the same request
    dispatched alone produce BITWISE identical science rows;
  - admission control bounds in-flight footprint: an over-footprint
    cohort QUEUES (retried after running dispatches release) rather than
    joining the running cohort's HBM; an impossible-even-alone cohort
    admits alone instead of deadlocking;
  - fault isolation is per-tenant: one request's failure or divergence
    never touches another tenant's results, and per-tenant journals give
    resubmitted requests bitwise rehydration with no dispatch;
  - the journal file survives CONCURRENT WRITERS (threads and processes)
    without a torn line — the serve daemon's whole persistence story
    rests on the O_APPEND single-write emission.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.obs.metrics import REGISTRY
from erasurehead_tpu.serve import admission as admission_lib
from erasurehead_tpu.serve import packer as packer_lib
from erasurehead_tpu.serve import queue as serve_queue
from erasurehead_tpu.serve import server as serve_server
from erasurehead_tpu.serve.client import ServeClient
from erasurehead_tpu.train import cache, experiments
from erasurehead_tpu.train import journal as journal_lib
from erasurehead_tpu.utils.config import (
    RunConfig,
    parse_bytes,
    resolve_serve_budget,
    resolve_serve_max_cohort,
)

W, R = 4, 3
N_ROWS, N_COLS = 64, 8


@pytest.fixture(scope="module")
def gmm():
    return generate_gmm(N_ROWS, N_COLS, n_partitions=W, seed=0)


@pytest.fixture(autouse=True)
def fresh_state():
    cache.clear()
    yield
    cache.clear()


@pytest.fixture
def restore_jax_compile_cache():
    """The persistent compilation cache is process-global jax config;
    tests that enable it (SweepServer cache_dir=...) must point it back
    off so later tests don't write into a deleted tmp dir."""
    import jax

    prev = {
        name: getattr(jax.config, name)
        for name in (
            "jax_compilation_cache_dir",
            "jax_persistent_cache_min_compile_time_secs",
            "jax_persistent_cache_min_entry_size_bytes",
        )
    }
    yield
    for name, value in prev.items():
        jax.config.update(name, value)
    # the cache module latches its config; drop the latched handle so
    # later compiles re-read the restored (off) config
    from jax.experimental.compilation_cache import (
        compilation_cache as cc,
    )

    cc.reset_cache()
    # also un-latch the repo-side marker, or every later test in this
    # process resolves donate="auto" to off (trainer._resolve_donate)
    cache._PERSISTENT_CACHE_DIR = None


def _cfg(**kw):
    base = dict(
        scheme="naive", n_workers=W, n_stragglers=1, rounds=R,
        n_rows=N_ROWS, n_cols=N_COLS, update_rule="AGD", lr_schedule=0.5,
        add_delay=True, seed=0, compute_mode="deduped",
    )
    base.update(kw)
    return RunConfig(**base)


def _req(gmm, tenant="t", label="naive", **cfg_kw):
    return serve_queue.RunRequest(
        tenant=tenant, label=label, config=_cfg(**cfg_kw), dataset=gmm
    )


def _science(summary) -> str:
    return json.dumps(
        journal_lib.science_row(journal_lib.summary_payload(summary)),
        sort_keys=True,
    )


def _counter(name):
    return REGISTRY.counter(name).value


# ---------------------------------------------------------------------------
# packer


class TestPacker:
    def test_same_signature_packs_across_tenants(self, gmm):
        reqs = [
            _req(gmm, tenant=f"t{k}", label=f"r{k}", seed=k)
            for k in range(4)
        ]
        packs = packer_lib.plan_packs(reqs)
        assert len(packs) == 1 and packs[0].batchable
        assert packs[0].tenants == ["t0", "t1", "t2", "t3"]

    def test_distinct_datasets_never_pack(self, gmm):
        other = generate_gmm(N_ROWS, N_COLS, n_partitions=W, seed=0)
        packs = packer_lib.plan_packs(
            [_req(gmm), _req(other, tenant="u")]
        )
        assert len(packs) == 2

    def test_memory_knobs_never_pack(self, gmm):
        reqs = [
            _req(gmm, tenant="a"),
            _req(gmm, tenant="b", stack_dtype="int8"),
        ]
        packs = packer_lib.plan_packs(reqs)
        assert len(packs) == 2

    def test_ineligible_is_sequential_singleton(self, gmm):
        packs = packer_lib.plan_packs(
            [_req(gmm, arrival_mode="measured", compute_mode="faithful")]
        )
        assert len(packs) == 1 and not packs[0].batchable
        assert packs[0].key is None

    def test_max_cohort_chunks(self, gmm):
        reqs = [_req(gmm, label=f"r{k}", seed=k) for k in range(5)]
        packs = packer_lib.plan_packs(reqs, max_cohort=2)
        assert [len(p.requests) for p in packs] == [2, 2, 1]
        with pytest.raises(ValueError, match="max_cohort"):
            packer_lib.plan_packs(reqs, max_cohort=0)


# ---------------------------------------------------------------------------
# admission controller (unit: no training, real footprint arithmetic)


class TestAdmission:
    def test_over_footprint_queues_until_release(self, gmm):
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        est = admission_lib.estimate_cohort_bytes(cohort)
        ctl = admission_lib.AdmissionController(budget_bytes=est)
        d0 = _counter("serve.deferred")
        assert ctl.try_admit(cohort, "d1")
        # second identical cohort exceeds the budget while d1 is in
        # flight: it must QUEUE (deferred), not join
        assert not ctl.try_admit(cohort, "d2")
        assert _counter("serve.deferred") == d0 + 1
        ctl.release("d1")
        assert ctl.try_admit(cohort, "d2")
        ctl.release("d2")
        assert ctl.in_flight_bytes == 0

    def test_pressure_snapshot(self, gmm):
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        est = admission_lib.estimate_cohort_bytes(cohort)
        ctl = admission_lib.AdmissionController(budget_bytes=est)
        assert ctl.pressure() == {
            "budget_bytes": est, "in_flight_bytes": 0,
            "in_flight_dispatches": 0, "deferred_total": 0,
        }
        assert ctl.try_admit(cohort, "d1")
        assert not ctl.try_admit(cohort, "d2")  # defers
        p = ctl.pressure()
        assert p["in_flight_bytes"] == est
        assert p["in_flight_dispatches"] == 1
        assert p["deferred_total"] == 1
        ctl.release("d1")
        assert ctl.pressure()["in_flight_bytes"] == 0

    def test_impossible_alone_admits_instead_of_deadlocking(self, gmm):
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        ctl = admission_lib.AdmissionController(budget_bytes=1)
        assert ctl.try_admit(cohort, "d1")  # idle daemon: admit + warn

    def test_eviction_admits_in_the_same_call(self, gmm):
        """Data-cache pins count in the admission inequality, so dropping
        them genuinely changes the post-evict recheck: an idle daemon
        whose cache is the only blocker must evict AND admit in one
        try_admit call — never drop the cache and then strand the cohort
        (nothing else would ever bump the serve loop's generation)."""
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        est = admission_lib.estimate_cohort_bytes(cohort)
        ctl = admission_lib.AdmissionController(budget_bytes=est)
        cache._data_cache["pin"] = (None, 123)  # est fits; est + pins won't
        e0 = _counter("serve.evictions")
        assert ctl.try_admit(cohort, "d1")
        assert _counter("serve.evictions") == e0 + 1
        assert cache.data_cache_bytes() == 0
        ctl.release("d1")

    def test_idle_evicts_then_admits_alone_when_still_over(self, gmm):
        """Over-budget even after eviction, on an idle daemon: evict (the
        oversized dispatch wants every byte) and fall through to the
        admit-alone path in the same call."""
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        ctl = admission_lib.AdmissionController(budget_bytes=1)
        cache._data_cache["pin"] = (None, 999)
        assert ctl.try_admit(cohort, "d1")
        assert cache.data_cache_bytes() == 0
        ctl.release("d1")

    def test_busy_daemon_defers_without_pointless_eviction(self, gmm):
        """When live dispatches (not the cache) are the blocker, defer
        WITHOUT dropping the cache: eviction that cannot change the
        verdict just burns a warm cache for nothing."""
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        est = admission_lib.estimate_cohort_bytes(cohort)
        ctl = admission_lib.AdmissionController(budget_bytes=est)
        assert ctl.try_admit(cohort, "d1")
        cache._data_cache["pin"] = (None, 7)
        assert not ctl.try_admit(cohort, "d2")
        assert cache.data_cache_bytes() == 7  # cache kept warm
        ctl.release("d1")

    def test_admit_events_and_measured_ratchet(self, gmm, tmp_path):
        cohort = packer_lib.plan_packs([_req(gmm)])[0]
        est = admission_lib.estimate_cohort_bytes(cohort)
        ctl = admission_lib.AdmissionController(budget_bytes=est)
        path = str(tmp_path / "admit.jsonl")
        with events_lib.capture(path):
            ctl.try_admit(cohort, "d1")
            ctl.try_admit(cohort, "d2")
        recs = [json.loads(l) for l in open(path) if l.strip()]
        admits = [r for r in recs if r["type"] == "admit"]
        assert [a["admitted"] for a in admits] == [True, False]
        assert all(a["est_bytes"] >= 0 for a in admits)
        assert events_lib.validate_file(path) == []
        # measured memory_analysis only ever ratchets the estimate UP
        ctl.observe(cohort, {"memory_analysis": {"argument_bytes": 10}})
        assert ctl.charge_for(cohort) == est
        big = {"argument_bytes": est, "temp_bytes": est}
        ctl.observe(cohort, {"memory_analysis": big})
        assert ctl.charge_for(cohort) == 2 * est

    def test_budget_resolvers(self):
        assert parse_bytes("2g") == 2 << 30
        assert parse_bytes("512m") == 512 << 20
        assert parse_bytes("1024") == 1024
        with pytest.raises(ValueError):
            parse_bytes("lots")
        with pytest.raises(ValueError):
            parse_bytes("-4k")
        assert resolve_serve_budget(None, env="") is None
        assert resolve_serve_budget("1m") == 1 << 20
        assert resolve_serve_budget(None, env="2k") == 2048
        assert resolve_serve_max_cohort(None, env="") == 64
        assert resolve_serve_max_cohort(8) == 8
        assert resolve_serve_max_cohort(None, env="16") == 16
        with pytest.raises(ValueError):
            resolve_serve_max_cohort(0)


# ---------------------------------------------------------------------------
# the serving contract: packing, bitwise invariance, streaming results


class TestServeDispatch:
    def test_concurrent_clients_pack_and_rows_are_bitwise(self, gmm):
        """4 concurrent tenants' same-signature requests share dispatches
        (serve.dispatches < requests) and every row is bitwise identical
        to the same request dispatched ALONE through the daemon — packing
        changes throughput, never bits."""
        specs = [
            (f"t{k}", f"{s}_{k}", dict(scheme=s, seed=k, **extra))
            for k in range(4)
            for s, extra in (
                ("naive", {}),
                ("approx", {"num_collect": 3}),
            )
        ]
        d0 = _counter("serve.dispatches")
        with serve_server.serving(window_s=0.2, max_cohort=8) as srv:
            handles = []
            lock = threading.Lock()

            def client(tenant):
                for tn, label, kw in specs:
                    if tn != tenant:
                        continue
                    h = srv.submit(
                        tenant=tn, label=label, config=_cfg(**kw),
                        dataset=gmm,
                    )
                    with lock:
                        handles.append(h)

            threads = [
                threading.Thread(target=client, args=(f"t{k}",))
                for k in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            packed = {
                h.result(timeout=120).label: h.result() for h in handles
            }
        packed_dispatches = _counter("serve.dispatches") - d0
        assert packed_dispatches < len(specs)
        assert {r.status for r in packed.values()} == {"ok"}

        # one at a time, fresh daemon, same fixed width: bitwise equal
        with serve_server.serving(window_s=0.001, max_cohort=8) as srv:
            for tn, label, kw in specs:
                res = srv.submit(
                    tenant=tn, label=label, config=_cfg(**kw), dataset=gmm
                ).result(timeout=120)
                assert _science(res.summary) == _science(
                    packed[label].summary
                ), f"row {label} changed bits when packed"

    def test_results_match_plain_compare_to_tolerance(self, gmm):
        """Serve rows agree with a local compare() of the same configs to
        float tolerance (widths differ, so tolerance not bitwise), and
        the control-plane columns are identical."""
        cfgs = {
            "naive": _cfg(),
            "agc": _cfg(scheme="approx", num_collect=3),
        }
        arrivals = {
            label: experiments.trainer.default_arrivals(c)
            for label, c in cfgs.items()
        }
        with serve_server.serving(window_s=0.1) as srv:
            rows = {}
            for label, c in cfgs.items():
                rows[label] = srv.submit(
                    tenant="t", label=label, config=c, dataset=gmm,
                    arrivals=arrivals[label],
                ).result(timeout=120).summary
        for label, c in cfgs.items():
            local = experiments.compare(
                {label: c}, gmm, arrivals=arrivals[label], batch="off"
            )[0]
            s = rows[label]
            assert s.sim_total_time == local.sim_total_time
            np.testing.assert_array_equal(s.timeset, local.timeset)
            np.testing.assert_allclose(
                s.training_loss, local.training_loss, rtol=2e-5, atol=1e-6
            )
            assert s.status == local.status == "ok"

    def test_admission_queues_behind_running_cohort(self, gmm, monkeypatch):
        """Integration form of the admission bar: with a budget of one
        cohort, a second (incompatible-signature) request QUEUES while the
        first dispatch runs — serve.deferred increments and its result
        arrives after the first's — instead of dispatching into the
        running cohort's memory."""
        real_dispatch = experiments._dispatch_cohort
        order = []

        def slow_dispatch(labels, configs, dataset, arrivals):
            out = real_dispatch(labels, configs, dataset, arrivals)
            time.sleep(0.4)
            order.append(tuple(labels))
            return out

        monkeypatch.setattr(experiments, "_dispatch_cohort", slow_dispatch)
        one = packer_lib.plan_packs([_req(gmm)])[0]
        budget = admission_lib.estimate_cohort_bytes(one, width=2) + 1
        d0 = _counter("serve.deferred")
        with serve_server.serving(
            budget_bytes=budget, window_s=0.01, max_cohort=2,
        ) as srv:
            h1 = srv.submit(
                tenant="a", label="first", config=_cfg(), dataset=gmm
            )
            time.sleep(0.15)  # first cohort is admitted and in flight
            h2 = srv.submit(
                tenant="b", label="second",
                config=_cfg(scheme="approx", num_collect=3,
                            stack_dtype="bfloat16", dtype="bfloat16"),
                dataset=gmm,
            )
            r1 = h1.result(timeout=120)
            r2 = h2.result(timeout=120)
        assert r1.status == "ok" and r2.status == "ok"
        assert _counter("serve.deferred") > d0
        # tenant a's dispatch finished before tenant b's ever started
        assert order and order[0][0].startswith("a-req")

    def test_divergence_quarantined_per_tenant(self, gmm):
        with serve_server.serving(window_s=0.1) as srv:
            bad = srv.submit(
                tenant="boomer", label="boom",
                config=_cfg(scheme="avoidstragg", lr_schedule=1e12,
                            model="linear"),
                dataset=gmm,
            )
            good = srv.submit(
                tenant="steady", label="fine", config=_cfg(), dataset=gmm
            )
            rb, rg = bad.result(timeout=120), good.result(timeout=120)
        assert rb.status == "diverged"
        assert rg.status == "ok"
        assert np.isfinite(rg.summary.final_train_loss)

    def test_request_error_is_isolated(self, gmm):
        with serve_server.serving(window_s=0.05) as srv:
            broken = srv.submit(
                tenant="t", label="broken",
                config=_cfg(dataset="covtype", is_real_data=True,
                            input_dir="/nonexistent", n_rows=64, n_cols=8),
            )
            rb = broken.result(timeout=60)
            healthy = srv.submit(
                tenant="t", label="ok", config=_cfg(), dataset=gmm
            )
            rh = healthy.result(timeout=120)
        assert rb.status == "error" and "FileNotFoundError" in rb.error
        assert rh.status == "ok"

    def test_per_tenant_journal_resume(self, gmm, tmp_path):
        jdir = str(tmp_path / "serve-journal")
        cfg = _cfg()
        with serve_server.serving(
            window_s=0.05, journal_dir=jdir
        ) as srv:
            first = srv.submit(
                tenant="alice", label="naive", config=cfg, dataset=gmm
            ).result(timeout=120)
        jpath = os.path.join(jdir, "alice", journal_lib.JOURNAL_NAME)
        assert os.path.exists(jpath)
        assert events_lib.validate_file(jpath) == []
        d0 = _counter("serve.dispatches")
        r0 = _counter("serve.resumed")
        with serve_server.serving(
            window_s=0.05, journal_dir=jdir
        ) as srv:
            again = srv.submit(
                tenant="alice", label="naive", config=cfg, dataset=gmm
            ).result(timeout=60)
            # same label, DIFFERENT tenant: bob's journal is empty, his
            # request really dispatches (per-tenant isolation)
            bob = srv.submit(
                tenant="bob", label="naive", config=cfg, dataset=gmm
            ).result(timeout=120)
        assert again.resumed and not bob.resumed
        assert _counter("serve.resumed") == r0 + 1
        assert _counter("serve.dispatches") == d0 + 1
        assert json.dumps(again.row, sort_keys=True) == json.dumps(
            first.row, sort_keys=True
        )


# ---------------------------------------------------------------------------
# socket front


class TestSocketFront:
    def test_submit_roundtrip_and_bad_payload(self, tmp_path):
        sock = str(tmp_path / "eh.sock")
        with serve_server.serving(window_s=0.05) as srv:
            front = serve_server.SocketFront(srv, sock)
            try:
                client = ServeClient(sock)
                rid = client.submit(
                    "wire-tenant", "naive-wire",
                    {
                        "scheme": "naive", "n_workers": W,
                        "n_stragglers": 1, "rounds": R, "n_rows": N_ROWS,
                        "n_cols": N_COLS, "lr_schedule": 0.5,
                        "add_delay": True, "compute_mode": "deduped",
                    },
                )
                res = client.result(timeout=180)
                assert res["request_id"] == rid
                assert res["status"] == "ok"
                assert res["row"]["label"] == "naive-wire"
                # unknown fields are refused loudly, not trained around
                with pytest.raises(RuntimeError, match="unserveable"):
                    client.submit("w", "bad", {"scheme": "naive",
                                               "warp_drive": 9})
                # the daemon must not accept host-path fields over the wire
                with pytest.raises(RuntimeError, match="unserveable"):
                    client.submit("w", "bad2", {"input_dir": "/etc"})
                client.close()
            finally:
                front.close()
        assert not os.path.exists(sock)

    def test_config_from_payload_validates(self):
        cfg = serve_queue.config_from_payload(
            {"scheme": "approx", "n_workers": 8, "num_collect": 4}
        )
        assert cfg.scheme.value == "approx" and cfg.num_collect == 4
        with pytest.raises(ValueError, match="unserveable"):
            serve_queue.config_from_payload({"input_dir": "/x"})
        with pytest.raises(ValueError, match="JSON object"):
            serve_queue.config_from_payload(["not", "a", "dict"])


# ---------------------------------------------------------------------------
# serve event records: validator coverage


class TestServeEventSchema:
    def _validate(self, recs):
        lines = [
            json.dumps({"seq": i, "t": 0.0, **r})
            for i, r in enumerate(recs)
        ]
        return events_lib.validate_lines(lines)

    def test_valid_serve_stream(self):
        assert self._validate([
            {"type": "request", "tenant": "a", "request_id": "a-req-1",
             "label": "agc"},
            {"type": "pack", "n_trajectories": 2, "labels": ["x", "y"],
             "tenants": ["a", "b"]},
            {"type": "admit", "est_bytes": 100, "budget_bytes": None,
             "admitted": True},
            {"type": "admit", "est_bytes": 100, "budget_bytes": 50,
             "admitted": False},
            {"type": "evict", "reason": "data_cache_pressure"},
        ]) == []

    def test_invalid_serve_records_named(self):
        errors = self._validate([
            {"type": "request", "tenant": "", "request_id": "r",
             "label": "l"},
            {"type": "pack", "n_trajectories": 3, "labels": ["x"],
             "tenants": []},
            {"type": "admit", "est_bytes": -5, "budget_bytes": 10},
            {"type": "evict", "reason": ""},
            {"type": "pack", "n_trajectories": 1, "labels": "x",
             "tenants": ["a"]},
        ])
        joined = "\n".join(errors)
        assert "request tenant" in joined
        assert "pack n_trajectories 3 != 1 labels" in joined
        assert "pack tenants must be a non-empty list" in joined
        assert "admit est_bytes" in joined
        assert "evict reason" in joined
        assert "pack labels must be a list" in joined


# ---------------------------------------------------------------------------
# journal under concurrent writers (the satellite contract)


_WRITER_SNIPPET = """
import sys, time
sys.path.insert(0, {root!r})
from erasurehead_tpu.obs import events as events_lib
lg = events_lib.EventLogger({path!r}, mode="a")
for i in range({n}):
    lg.emit(
        "sweep_trajectory",
        key=f"{tag}-{{i}}",
        label=f"{tag}-{{i}}",
        status="ok",
        row={{"writer": {tag!r}, "i": i, "pad": "x" * 256}},
    )
    time.sleep(0.001)
lg.close()
"""


class TestConcurrentJournalWriters:
    def test_interleaved_processes_never_corrupt(self, tmp_path):
        """Several PROCESSES appending to one sweep_journal.jsonl (the
        serve daemon next to a local sweep, or two daemons) interleave
        whole lines, never torn ones: every record every writer emitted
        is present and parseable, and the validator accepts the file."""
        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        path = str(tmp_path / journal_lib.JOURNAL_NAME)
        n, writers = 40, 4
        procs = [
            subprocess.Popen(
                [
                    sys.executable, "-c",
                    _WRITER_SNIPPET.format(
                        root=root, path=path, n=n, tag=f"w{k}"
                    ),
                ],
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            for k in range(writers)
        ]
        for p in procs:
            assert p.wait(timeout=120) == 0
        lines = [l for l in open(path) if l.strip()]
        assert len(lines) == n * writers
        recs = [json.loads(l) for l in lines]  # every line parses whole
        keys = {r["key"] for r in recs}
        assert keys == {
            f"w{k}-{i}" for k in range(writers) for i in range(n)
        }
        assert events_lib.validate_file(path) == []
        # and a resuming journal reads the union
        j = journal_lib.SweepJournal(str(tmp_path), resume=True)
        assert len(j) == n * writers
        j.close()

    def test_interleaved_threads_one_logger(self, tmp_path):
        """Threads sharing one EventLogger (the daemon's dispatch pool)
        keep seq strictly monotonic and lines whole."""
        path = str(tmp_path / "events.jsonl")
        lg = events_lib.EventLogger(path, mode="a")

        def write(tag):
            for i in range(50):
                lg.emit("warning", kind="t", message=f"{tag}-{i}")

        threads = [
            threading.Thread(target=write, args=(f"th{k}",))
            for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        lg.close()
        assert events_lib.validate_file(path) == []
        msgs = [json.loads(l)["message"] for l in open(path)]
        assert len(msgs) == 200 and len(set(msgs)) == 200

    def test_thread_safe_sweep_journal_record(self, gmm, tmp_path):
        """SweepJournal.record from concurrent threads (the dispatch
        pool): every row lands, file validates."""
        rows = experiments.compare({"naive": _cfg()}, gmm, batch="off")
        j = journal_lib.SweepJournal(str(tmp_path), resume=False)

        def rec(k):
            for i in range(20):
                j.record(f"k{k}-{i}", f"l{k}-{i}", rows[0])

        threads = [
            threading.Thread(target=rec, args=(k,)) for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        j.close()
        assert len(j) == 80
        assert events_lib.validate_file(j.path) == []


# ---------------------------------------------------------------------------
# footprint estimate + report section


def test_estimate_stack_bytes_modes(gmm):
    from erasurehead_tpu.train import trainer

    ded = trainer.estimate_stack_bytes(_cfg(), gmm)
    faith = trainer.estimate_stack_bytes(
        _cfg(scheme="cyccoded", compute_mode="faithful"), gmm
    )
    ring = trainer.estimate_stack_bytes(
        _cfg(scheme="cyccoded", compute_mode="faithful",
             stack_mode="ring"), gmm
    )
    # the faithful materialized stack carries the (s+1)x redundancy; the
    # ring stack and the deduped stack are partition-major
    assert faith == 2 * ded
    assert ring == ded
    int8 = trainer.estimate_stack_bytes(_cfg(stack_dtype="int8"), gmm)
    assert int8 < ded  # 1/4 payload + scale tables

    cohort = packer_lib.plan_packs([_req(gmm)])[0]
    assert admission_lib.estimate_cohort_bytes(cohort, width=8) > (
        admission_lib.estimate_cohort_bytes(cohort, width=1)
    )


def test_report_renders_per_tenant_serve_section(gmm, tmp_path, capsys):
    from erasurehead_tpu.obs import report as report_lib

    path = str(tmp_path / "serve_events.jsonl")
    with events_lib.capture(path):
        with serve_server.serving(window_s=0.1) as srv:
            srv.submit(
                tenant="alice", label="ok", config=_cfg(), dataset=gmm
            ).result(timeout=120)
            srv.submit(
                tenant="bob", label="boom",
                config=_cfg(scheme="avoidstragg", lr_schedule=1e12,
                            model="linear"),
                dataset=gmm,
            ).result(timeout=120)
    assert events_lib.validate_file(path) == []
    assert report_lib.main([path, "--validate"]) == 0
    out = capsys.readouterr().out
    assert "serve (multi-tenant cohort packing)" in out
    assert "alice" in out and "bob" in out
    # bob's diverged row is counted in his tenant line (columns: tenant
    # requests rows diverged errors rejects retried)
    bob_line = [l for l in out.splitlines() if l.strip().startswith("bob")]
    assert bob_line and bob_line[0].split()[3] == "1"


# ---------------------------------------------------------------------------
# weighted-fair packing (PR 13): round-robin windows, quotas, priorities


class TestFairPacking:
    def _flood(self, gmm):
        """The starvation pattern: tenant a's 6-deep backlog arrives
        before b's and c's 2 each."""
        return (
            [_req(gmm, tenant="a", label=f"a{k}", seed=k)
             for k in range(6)]
            + [_req(gmm, tenant="b", label=f"b{k}", seed=10 + k)
               for k in range(2)]
            + [_req(gmm, tenant="c", label=f"c{k}", seed=20 + k)
               for k in range(2)]
        )

    def test_round_robin_interleaves_tenants(self, gmm):
        """FIFO would give the flooder the first 6 of 8 window slots;
        fair windows alternate tenants, so b and c ride the FIRST
        dispatch instead of queueing behind a's backlog."""
        packs = packer_lib.plan_packs(self._flood(gmm), max_cohort=4)
        labels = [[r.label for r in p.requests] for p in packs]
        assert labels[0] == ["a0", "b0", "c0", "a1"]
        assert labels[1] == ["a2", "b1", "c1", "a3"]
        assert labels[2] == ["a4", "a5"]

    def test_fifo_mode_preserves_arrival_order(self, gmm):
        packs = packer_lib.plan_packs(
            self._flood(gmm), max_cohort=4, fair=False
        )
        labels = [[r.label for r in p.requests] for p in packs]
        assert labels[0] == ["a0", "a1", "a2", "a3"]  # the starvation

    def test_tenant_quota_is_a_hard_cap(self, gmm):
        """quota=1: once every backlogged tenant holds its one slot the
        window closes SHORT; the lone tenant's overflow waits for later
        windows instead of monopolizing this one."""
        packs = packer_lib.plan_packs(
            self._flood(gmm), max_cohort=4, tenant_quota=1
        )
        labels = [[r.label for r in p.requests] for p in packs]
        assert labels[0] == ["a0", "b0", "c0"]
        assert labels[1] == ["a1", "b1", "c1"]
        assert labels[2:] == [["a2"], ["a3"], ["a4"], ["a5"]]
        with pytest.raises(ValueError, match="tenant_quota"):
            packer_lib.plan_packs(self._flood(gmm), tenant_quota=0)

    def test_priority_orders_within_tenant_only(self, gmm):
        """Priority is intra-tenant: a's P5 request jumps a's own queue
        but cannot displace b's share of the window."""
        reqs = [
            _req(gmm, tenant="a", label="a0", seed=0),
            _req(gmm, tenant="a", label="a_hot", seed=1),
            _req(gmm, tenant="a", label="a2", seed=2),
            _req(gmm, tenant="b", label="b0", seed=3),
        ]
        reqs[1].priority = 5
        packs = packer_lib.plan_packs(reqs, max_cohort=2)
        labels = [[r.label for r in p.requests] for p in packs]
        assert labels[0] == ["a_hot", "b0"]
        assert labels[1] == ["a0", "a2"]  # FIFO within the P0 class

    def test_lone_tenant_fills_whole_windows(self, gmm):
        """Fairness costs nothing under no contention: one tenant's
        requests chunk exactly as FIFO did."""
        reqs = [_req(gmm, label=f"r{k}", seed=k) for k in range(5)]
        fair = packer_lib.plan_packs(reqs, max_cohort=2)
        fifo = packer_lib.plan_packs(reqs, max_cohort=2, fair=False)
        assert [[r.label for r in p.requests] for p in fair] == (
            [[r.label for r in p.requests] for p in fifo]
        )


# ---------------------------------------------------------------------------
# backpressure: high-water mark, reject events, retry-after, client backoff


class TestBackpressure:
    def test_max_pending_rejects_with_retry_after(self, gmm, tmp_path,
                                                  monkeypatch):
        """Past the high-water mark submit() raises ServeOverloadedError
        carrying a positive retry-after, a `reject` event lands, and the
        queue drains back below the mark afterwards."""
        real_dispatch = experiments._dispatch_cohort
        release = threading.Event()

        def gated(labels, configs, dataset, arrivals):
            release.wait(timeout=30)
            return real_dispatch(labels, configs, dataset, arrivals)

        monkeypatch.setattr(experiments, "_dispatch_cohort", gated)
        path = str(tmp_path / "reject.jsonl")
        with events_lib.capture(path):
            with serve_server.serving(
                window_s=0.01, max_pending=1, max_cohort=2
            ) as srv:
                h1 = srv.submit(
                    tenant="a", label="one", config=_cfg(), dataset=gmm
                )
                # h1 sits in intake/pending (dispatch gated); the mark
                # is 1, so the next submit must bounce
                deadline = time.monotonic() + 5
                rejected = None
                while time.monotonic() < deadline:
                    try:
                        srv.submit(
                            tenant="b", label="two",
                            config=_cfg(seed=1), dataset=gmm,
                        )
                        time.sleep(0.01)  # h1 already dispatched; retry
                    except serve_queue.ServeOverloadedError as e:
                        rejected = e
                        break
                assert rejected is not None, "high-water mark never hit"
                assert rejected.retry_after_s > 0
                release.set()
                assert h1.result(timeout=120).status == "ok"
        recs = [json.loads(l) for l in open(path) if l.strip()]
        rejects = [r for r in recs if r["type"] == "reject"]
        assert rejects and rejects[0]["tenant"] == "b"
        assert rejects[0]["reason"] == "overloaded"
        assert rejects[0]["retry_after_s"] > 0
        assert events_lib.validate_file(path) == []

    def test_socket_client_retries_on_rejected(self, gmm, tmp_path,
                                               monkeypatch):
        """A 'rejected' reply with max_retries>0 is retried on the
        capped-exponential schedule until accepted — the submission
        ultimately lands exactly once (no accepted-then-lost, no dup)."""
        real_dispatch = experiments._dispatch_cohort

        def slow(labels, configs, dataset, arrivals):
            time.sleep(0.3)
            return real_dispatch(labels, configs, dataset, arrivals)

        monkeypatch.setattr(experiments, "_dispatch_cohort", slow)
        sock = str(tmp_path / "eh.sock")
        payload = {
            "scheme": "naive", "n_workers": W, "n_stragglers": 1,
            "rounds": R, "n_rows": N_ROWS, "n_cols": N_COLS,
            "lr_schedule": 0.5, "add_delay": True,
            "compute_mode": "deduped",
        }
        with serve_server.serving(
            window_s=0.01, max_pending=1, max_cohort=1
        ) as srv:
            front = serve_server.SocketFront(srv, sock)
            try:
                client = ServeClient(sock)
                rids = []
                for k in range(3):
                    rids.append(client.submit(
                        "t", f"r{k}", {**payload, "seed": k},
                        max_retries=20,
                    ))
                assert client.rejected_total > 0, (
                    "the mark never rejected — the test lost its teeth"
                )
                assert client.retried_total == client.rejected_total
                got = {client.result(timeout=120)["request_id"]
                       for _ in range(3)}
                assert got == set(rids)  # each exactly once
                client.close()
            finally:
                front.close()

    def test_backoff_schedule_is_deterministic(self):
        from erasurehead_tpu.serve.client import backoff_s

        # the daemon's quote wins when longer; the exponential floor
        # wins when the quote is stale-low; the cap bounds the tail
        assert backoff_s(0, 5.0) == 5.0
        assert backoff_s(0, None) == pytest.approx(0.1)
        assert backoff_s(3, 0.2) == pytest.approx(0.8)
        assert backoff_s(30, 0.0) == 10.0
        assert [backoff_s(a, 0.0) for a in range(4)] == [
            pytest.approx(x) for x in (0.1, 0.2, 0.4, 0.8)
        ]

    def test_retry_after_scales_with_queue_depth(self, gmm):
        srv = serve_server.SweepServer(max_cohort=4)
        srv._dispatch_ewma_s = 2.0
        assert srv.retry_after_s() == pytest.approx(2.0)
        with srv._state_lock:
            srv._queued = 12  # 4 windows ahead (ceil(13/4))
        assert srv.retry_after_s() == pytest.approx(8.0)
        srv._dispatch_ewma_s = 100.0
        assert srv.retry_after_s() == 60.0  # clamped


# ---------------------------------------------------------------------------
# request timeouts: a stalled dispatch becomes a TYPED error, never a
# silent queue.Empty (the serve/server.py:151 satellite)


class TestRequestTimeout:
    def test_chaos_stalled_dispatch_times_out_typed(self, gmm, tmp_path,
                                                    monkeypatch):
        from erasurehead_tpu.utils import chaos

        monkeypatch.setenv(chaos.CHAOS_ENV, "stall:serve_dispatch:1:3")
        chaos.reset()
        path = str(tmp_path / "timeout.jsonl")
        with events_lib.capture(path):
            with serve_server.serving(
                window_s=0.01, request_timeout_s=0.4
            ) as srv:
                h = srv.submit(
                    tenant="t", label="stalled", config=_cfg(),
                    dataset=gmm,
                )
                res = h.result(timeout=30)
        assert res.status == "error"
        assert "RequestTimeout" in res.error
        assert "0.4" in res.error  # names the knob's value
        recs = [json.loads(l) for l in open(path) if l.strip()]
        warn = [r for r in recs if r["type"] == "warning"
                and r.get("kind") == "request_timeout"]
        assert warn and "stalled" in warn[0]["message"]
        assert events_lib.validate_file(path) == []

    def test_late_dispatch_loses_the_deliver_once_race(self, gmm,
                                                       monkeypatch):
        """The dispatch that eventually lands after a timeout must not
        deliver a second result; its row still journals."""
        real_dispatch = experiments._dispatch_cohort

        def slow(labels, configs, dataset, arrivals):
            time.sleep(0.8)
            return real_dispatch(labels, configs, dataset, arrivals)

        monkeypatch.setattr(experiments, "_dispatch_cohort", slow)
        r0 = _counter("serve.results")
        with serve_server.serving(
            window_s=0.01, request_timeout_s=0.2
        ) as srv:
            h = srv.submit(
                tenant="t", label="late", config=_cfg(), dataset=gmm
            )
            res = h.result(timeout=30)
            assert res.status == "error"
        # exactly ONE result counted for the request despite the late
        # dispatch landing during drain
        assert _counter("serve.results") == r0 + 1

    def test_validates_knob(self):
        with pytest.raises(ValueError, match="request_timeout_s"):
            serve_server.SweepServer(request_timeout_s=0.0)
        with pytest.raises(ValueError, match="max_pending"):
            serve_server.SweepServer(max_pending=0)


# ---------------------------------------------------------------------------
# typed daemon-death errors (ServeUnavailableError satellite)


class TestServeUnavailable:
    def test_connect_refused_is_typed(self, tmp_path):
        from erasurehead_tpu.serve.client import ServeUnavailableError

        missing = str(tmp_path / "nope.sock")
        with pytest.raises(ServeUnavailableError, match="nope.sock"):
            ServeClient(missing)

    def test_daemon_death_translates_queue_empty(self, gmm, tmp_path):
        """A client waiting on result() when the daemon dies gets the
        typed error naming the socket path and last event seen — never a
        raw queue.Empty."""
        from erasurehead_tpu.serve.client import ServeUnavailableError

        sock = str(tmp_path / "eh.sock")
        srv = serve_server.SweepServer(window_s=0.05).start()
        front = serve_server.SocketFront(srv, sock)
        client = ServeClient(sock)
        rid = client.submit(
            "t", "ok",
            {"scheme": "naive", "n_workers": W, "n_stragglers": 1,
             "rounds": R, "n_rows": N_ROWS, "n_cols": N_COLS,
             "lr_schedule": 0.5, "add_delay": True,
             "compute_mode": "deduped"},
        )
        res = client.result(timeout=120)
        assert res["request_id"] == rid
        front.close()  # the daemon goes away mid-session
        srv.stop()
        with pytest.raises(ServeUnavailableError) as ei:
            client.result(timeout=30)
        assert sock in str(ei.value)
        assert ei.value.last_event == "result"  # names what it last saw
        with pytest.raises(ServeUnavailableError):
            client.submit("t", "again", {"scheme": "naive",
                                         "n_workers": W, "rounds": R})
        client.close()


# ---------------------------------------------------------------------------
# intake WAL + warm restart (the crash-safety tentpole)


class TestIntakeWAL:
    def test_append_dedupes_by_digest(self, tmp_path):
        from erasurehead_tpu.serve import wal as wal_lib

        w = wal_lib.IntakeWAL(str(tmp_path))
        rec = dict(
            tenant="t", request_id="t-req-1", label="l", digest="d1",
            config_payload={"scheme": "naive"},
        )
        assert w.append(**rec)
        assert not w.append(**{**rec, "request_id": "t-req-2"})
        assert w.seen("d1") and not w.seen("d2")
        assert len(w.replay()) == 1
        w.close()
        # a fresh WAL over the same file rereads the digests
        w2 = wal_lib.IntakeWAL(str(tmp_path))
        assert w2.seen("d1") and len(w2) == 1
        w2.close()

    def test_torn_final_line_tolerated(self, tmp_path):
        from erasurehead_tpu.serve import wal as wal_lib

        w = wal_lib.IntakeWAL(str(tmp_path))
        w.append(tenant="t", request_id="r1", label="l", digest="d1",
                 config_payload={"scheme": "naive"})
        w.close()
        with open(w.path, "a") as f:
            f.write('{"type": "request", "digest": "d2", "conf')  # torn
        w2 = wal_lib.IntakeWAL(str(tmp_path))
        assert len(w2.replay()) == 1  # the whole line survives, torn dies
        w2.close()

    def test_config_payload_round_trip(self):
        cfg = _cfg(scheme="approx", num_collect=3, seed=7)
        payload = serve_queue.config_payload(cfg)
        assert payload is not None
        rebuilt = serve_queue.config_from_payload(payload)
        assert events_lib.config_hash(rebuilt) == (
            events_lib.config_hash(cfg)
        )
        # unserveable fields make the config non-WAL-replayable: None
        bad = _cfg(is_real_data=True, input_dir="/x", dataset="covtype")
        assert serve_queue.config_payload(bad) is None

    def test_digest_coalesces_inflight_resubmission(self, gmm, tmp_path,
                                                    monkeypatch):
        """An idempotent resubmission of an in-flight request rides the
        original dispatch (one dispatch, two replies) instead of
        double-dispatching."""
        real_dispatch = experiments._dispatch_cohort

        def slow(labels, configs, dataset, arrivals):
            time.sleep(0.5)
            return real_dispatch(labels, configs, dataset, arrivals)

        monkeypatch.setattr(experiments, "_dispatch_cohort", slow)
        d0 = _counter("serve.dispatches")
        c0 = _counter("serve.coalesced")
        cfg = _cfg()
        with serve_server.serving(
            window_s=0.01, journal_dir=str(tmp_path / "j")
        ) as srv:
            h1 = srv.submit(tenant="t", label="same", config=cfg)
            time.sleep(0.2)  # h1 in flight
            h2 = srv.submit(tenant="t", label="same", config=cfg)
            r1 = h1.result(timeout=120)
            r2 = h2.result(timeout=120)
        assert r1.status == r2.status == "ok"
        assert r2.resumed  # the follower's reply is marked resumed
        assert _counter("serve.dispatches") == d0 + 1
        assert _counter("serve.coalesced") == c0 + 1
        assert json.dumps(r1.row, sort_keys=True) == json.dumps(
            r2.row, sort_keys=True
        )


class TestWarmRestart:
    def test_restart_rehydrates_bitwise_with_zero_recompiles(
        self, tmp_path, monkeypatch, restore_jax_compile_cache
    ):
        """The tier-1 restart-under-load pin (in-process; the REAL
        process-kill variant is `make serve-chaos-smoke` / the slow
        test below): warm one signature, fail a dispatch mid-flight via
        chaos, 'restart' on the same journal+cache dirs with the
        in-process caches cleared, and assert (a) the WAL replays the
        working set, (b) every resubmission rehydrates bitwise, (c) the
        on-disk compilation cache gains ZERO entries."""
        from erasurehead_tpu.train.cache import persistent_cache_entries
        from erasurehead_tpu.utils import chaos

        jdir = str(tmp_path / "journal")
        cdir = str(tmp_path / "xla")
        cfgs = {f"r{k}": _cfg(seed=k) for k in range(3)}

        # leg 1: warm r0's signature, then chaos-fail r1/r2's dispatch
        # (accepted + WAL'd, no rows journaled — the working set)
        with serve_server.serving(
            window_s=0.05, journal_dir=jdir, cache_dir=cdir
        ) as srv:
            first = srv.submit(
                tenant="t", label="r0", config=cfgs["r0"]
            ).result(timeout=120)
            assert first.status == "ok"
            monkeypatch.setenv(
                chaos.CHAOS_ENV, "raise:serve_dispatch:1+"
            )
            chaos.reset()
            hs = [
                srv.submit(tenant="t", label=l, config=cfgs[l])
                for l in ("r1", "r2")
            ]
            for h in hs:
                assert h.result(timeout=120).status == "error"
        monkeypatch.delenv(chaos.CHAOS_ENV)
        chaos.reset()
        entries_before = persistent_cache_entries(cdir)
        assert entries_before > 0  # the warm leg hit the disk cache

        # leg 2: cold-process proxy — in-process exec/data caches gone,
        # only the disk survives (what a real restart sees)
        cache.clear()
        path = str(tmp_path / "restart.jsonl")
        with events_lib.capture(path):
            with serve_server.serving(
                window_s=0.05, journal_dir=jdir, cache_dir=cdir
            ) as srv:
                rows = {
                    l: srv.submit(
                        tenant="t", label=l, config=cfgs[l]
                    ).result(timeout=120)
                    for l in ("r0", "r1", "r2")
                }
        assert all(r.status == "ok" for r in rows.values())
        assert all(r.resumed for r in rows.values()), (
            "resubmission must rehydrate (journal or coalesced replay), "
            "never recompute"
        )
        assert rows["r0"].row == first.row  # bitwise, incl. loss arrays
        assert persistent_cache_entries(cdir) == entries_before, (
            "warm restart recompiled a warm signature"
        )
        recs = [json.loads(l) for l in open(path) if l.strip()]
        restart = [r for r in recs if r["type"] == "restart"]
        assert restart and restart[0]["wal_records"] == 3
        assert restart[0]["rehydrated"] >= 1  # r0 straight from journal
        assert restart[0]["resubmitted"] == 2  # r1/r2 re-dispatched
        assert events_lib.validate_file(path) == []

    @pytest.mark.slow
    def test_restart_under_load_with_real_kills(self):
        """The full subprocess cycle (`make serve-chaos-smoke`): daemon
        DIES via os._exit mid-dispatch, restarts, WAL replays, rows
        rehydrate bitwise vs an uninterrupted baseline, zero new
        on-disk compile-cache entries. Slow-marked: three jax boots."""
        import subprocess
        import sys as sys_mod

        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        p = subprocess.run(
            [sys_mod.executable,
             os.path.join(root, "tools", "serve_chaos_smoke.py")],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=900,
        )
        assert p.returncode == 0, p.stdout + p.stderr
        assert '"status": "PASS"' in p.stdout


# ---------------------------------------------------------------------------
# new serve event kinds: validator coverage


class TestNewServeEventSchema:
    def _validate(self, recs):
        lines = [
            json.dumps({"seq": i, "t": 0.0, **r})
            for i, r in enumerate(recs)
        ]
        return events_lib.validate_lines(lines)

    def test_valid_reject_stream_restart(self):
        assert self._validate([
            {"type": "reject", "tenant": "a", "reason": "overloaded",
             "retry_after_s": 1.5},
            {"type": "reject", "tenant": "unknown",
             "reason": "unauthorized"},
            {"type": "stream", "tenant": "a", "event": "open"},
            {"type": "stream", "tenant": "a", "event": "overflow",
             "dropped": 7},
            {"type": "stream", "tenant": "a", "event": "close",
             "dropped": 7},
            {"type": "restart", "wal_records": 3, "resubmitted": 2,
             "rehydrated": 1},
        ]) == []

    def test_invalid_records_named(self):
        errors = self._validate([
            {"type": "reject", "tenant": "", "reason": "overloaded"},
            {"type": "reject", "tenant": "a", "reason": "bored"},
            {"type": "reject", "tenant": "a", "reason": "overloaded",
             "retry_after_s": -1},
            {"type": "stream", "tenant": "a", "event": "explode"},
            {"type": "stream", "tenant": "a", "event": "overflow",
             "dropped": -2},
            {"type": "restart", "wal_records": -1, "resubmitted": 0,
             "rehydrated": 0},
            {"type": "restart", "wal_records": 1, "resubmitted": 0},
        ])
        joined = "\n".join(errors)
        assert "reject tenant" in joined
        assert "reject reason" in joined
        assert "retry_after_s" in joined
        assert "stream event" in joined
        assert "stream dropped" in joined
        assert "restart wal_records" in joined
        assert "missing required ['rehydrated']" in joined
