"""Ring-streamed faithful stack mode (stack_mode="ring").

The load-bearing claims, each pinned here:
  - trajectories are BITWISE identical to materialized faithful across
    every scheme at the canonical W=30 fold (the transport moves values,
    never transforms them, and the slot contraction order is unchanged);
  - device data bytes drop by the layout's storage overhead — (s+1)x for
    the plain coded schemes — visible in the recorded stack_bytes and
    memory_analysis telemetry (the ISSUE's >= 2x acceptance at s=2);
  - the hop planner covers every slot exactly once, needs only
    1 + ceil(s / Pl) fill steps for ring-local assignments, and degrades
    to at most a full rotation for arbitrary ones.
"""

import dataclasses

import jax
import numpy as np
import pytest

from erasurehead_tpu.data import sharding
from erasurehead_tpu.data.synthetic import generate_gmm, generate_onehot
from erasurehead_tpu.ops import codes
from erasurehead_tpu.parallel.mesh import ring_order_devices, worker_mesh
from erasurehead_tpu.train import cache as cache_lib, trainer
from erasurehead_tpu.utils.config import RunConfig


def _bitwise_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


def _cfg(**kw):
    base = dict(
        scheme="naive",
        n_workers=8,
        n_stragglers=1,
        rounds=3,
        n_rows=64,
        n_cols=16,
        lr_schedule=0.5,
        update_rule="AGD",
        add_delay=True,
        seed=0,
    )
    base.update(kw)
    return RunConfig(**base)


# ---------------------------------------------------------------------------
# bitwise identity, canonical W=30 fold (6 of the 8 CPU devices)
# ---------------------------------------------------------------------------

W30 = 30
ROWS30 = W30 * 8  # also divisible by the partial schemes' 2*W partitions


@pytest.fixture(scope="module")
def gmm30():
    return generate_gmm(ROWS30, 16, n_partitions=W30, seed=0)


@pytest.mark.parametrize(
    "scheme,extra",
    [
        ("naive", {}),
        ("cyccoded", dict(n_stragglers=2)),
        ("repcoded", dict(n_stragglers=2)),
        ("approx", dict(n_stragglers=2, num_collect=15)),
        ("avoidstragg", dict(n_stragglers=2)),
        ("partialcyccoded", dict(n_stragglers=2, partitions_per_worker=4)),
        ("partialrepcoded", dict(n_stragglers=2, partitions_per_worker=4)),
    ],
)
def test_ring_bitwise_identical_w30(gmm30, scheme, extra):
    """All seven reference schemes at the canonical W=30 shape: the ring
    transport must reproduce the materialized trajectory bit for bit —
    under BOTH transport schedules (ring_pipeline off and on; the
    double-buffered form moves the same blocks in the same fill order,
    so pipelining is a pure lowering knob)."""
    cfg = _cfg(scheme=scheme, n_workers=W30, n_rows=ROWS30, rounds=2, **extra)
    m = trainer.train(cfg, gmm30)
    r = trainer.train(
        dataclasses.replace(cfg, stack_mode="ring", ring_pipeline="off"),
        gmm30,
    )
    p = trainer.train(
        dataclasses.replace(cfg, stack_mode="ring", ring_pipeline="on"),
        gmm30,
    )
    assert m.cache_info["stack_mode"] == "materialized"
    assert r.cache_info["stack_mode"] == "ring"
    assert r.cache_info["ring_pipeline"] == "sequential"
    assert p.cache_info["ring_pipeline"] == "pipelined"
    assert _bitwise_equal(m.params_history, r.params_history), scheme
    assert _bitwise_equal(m.final_params, r.final_params), scheme
    assert _bitwise_equal(m.params_history, p.params_history), scheme
    assert _bitwise_equal(m.final_params, p.final_params), scheme


def test_ring_bitwise_beyond_reference_schemes(gmm30):
    """The two beyond-reference schemes ride the same transport."""
    for scheme, extra in (
        ("randreg", dict(n_stragglers=2)),
        ("deadline", dict(deadline=1.0)),
    ):
        cfg = _cfg(
            scheme=scheme, n_workers=W30, n_rows=ROWS30, rounds=2, **extra
        )
        m = trainer.train(cfg, gmm30)
        r = trainer.train(dataclasses.replace(cfg, stack_mode="ring"), gmm30)
        assert _bitwise_equal(m.params_history, r.params_history), scheme


def test_ring_bitwise_other_paths(gmm30):
    """Lowering swaps (flat / margin-flat), bf16 data, and the autodiff
    (grads-via-loss) family all compose with the ring transport without
    breaking bit identity — the local grad body is shared, only the
    transport differs."""
    for tag, extra in (
        ("flat", dict(flat_grad="on")),
        ("marginflat", dict(margin_flat="on")),
        ("bf16", dict(dtype="bfloat16")),
        ("mlp", dict(model="mlp", update_rule="GD")),
    ):
        cfg = _cfg(
            scheme="approx", n_workers=12, n_stragglers=2, num_collect=6,
            n_rows=96, rounds=2, **extra,
        )
        m = trainer.train(cfg, gmm12())
        r = trainer.train(dataclasses.replace(cfg, stack_mode="ring"), gmm12())
        assert _bitwise_equal(m.params_history, r.params_history), tag


_GMM12 = None


def gmm12():
    global _GMM12
    if _GMM12 is None:
        _GMM12 = generate_gmm(96, 16, n_partitions=12, seed=0)
    return _GMM12


def test_ring_bitwise_sparse(gmm30):
    """PaddedRows and FieldOnehot stacks: the fill is a generic pytree
    gather, so integer index leaves ride the same hops."""
    data = generate_onehot(96, 16, n_partitions=12, n_fields=4, seed=0)
    for fmt in ("padded", "fields"):
        cfg = _cfg(
            scheme="approx", n_workers=12, n_stragglers=2, num_collect=6,
            n_rows=96, rounds=2, sparse_format=fmt,
        )
        m = trainer.train(cfg, data)
        r = trainer.train(dataclasses.replace(cfg, stack_mode="ring"), data)
        assert _bitwise_equal(m.params_history, r.params_history), fmt


def test_ring_dynamic_trainer(gmm30):
    cfg = _cfg(
        scheme="approx", n_workers=12, n_stragglers=2, num_collect=6,
        n_rows=96, rounds=2,
    )
    m = trainer.train_dynamic(cfg, gmm12())
    r = trainer.train_dynamic(
        dataclasses.replace(cfg, stack_mode="ring"), gmm12()
    )
    assert _bitwise_equal(m.params_history, r.params_history)


def test_ring_batch_trainer(gmm30):
    cfg = _cfg(scheme="repcoded", n_workers=12, n_stragglers=2, n_rows=96,
               rounds=2)
    m = trainer.train_batch(cfg, gmm12(), seeds=[0, 1])
    r = trainer.train_batch(
        dataclasses.replace(cfg, stack_mode="ring"), gmm12(), seeds=[0, 1]
    )
    for mm, rr in zip(m, r):
        assert _bitwise_equal(mm.params_history, rr.params_history)
    assert r[0].cache_info["stack_mode"] == "ring"


# ---------------------------------------------------------------------------
# the (s+1)x memory claim, by numbers (ISSUE acceptance: >= 2x at s=2)
# ---------------------------------------------------------------------------


def test_ring_memory_telemetry_s2():
    """FRC at ppw = s+1 = 3: materialized device data bytes must be >= 2x
    (exactly 3x for the stacks) the ring mode's, visible in BOTH recorded
    telemetry channels — stack_bytes (resident stacks) and the compiled
    executable's argument bytes (what each dispatch binds)."""
    W = 12
    data = generate_gmm(W * 64, 32, n_partitions=W, seed=0)
    cfg = _cfg(
        scheme="repcoded", n_workers=W, n_stragglers=2, n_rows=W * 64,
        n_cols=32, rounds=2,
    )
    cache_lib.clear()
    m = trainer.train(cfg, data)
    r = trainer.train(dataclasses.replace(cfg, stack_mode="ring"), data)
    sb_m, sb_r = m.cache_info["stack_bytes"], r.cache_info["stack_bytes"]
    assert sb_m >= 2 * sb_r, (sb_m, sb_r)
    # the stacks themselves shrink by exactly the storage overhead (3x)
    assert sb_m == 3 * sb_r, (sb_m, sb_r)
    ma_m = m.cache_info["memory_analysis"]
    ma_r = r.cache_info["memory_analysis"]
    if ma_m is not None and ma_r is not None:  # backend-dependent
        assert ma_m["argument_bytes"] >= 2 * ma_r["argument_bytes"], (
            ma_m, ma_r,
        )
    # ring runs re-key the data cache on partition content (like deduped):
    # a deduped run of the same shape reuses the ring upload outright
    d = trainer.train(dataclasses.replace(cfg, compute_mode="deduped"), data)
    assert d.cache_info["data_hit"], d.cache_info


def test_ring_cached_rerun_bitwise():
    """Second ring run of the same signature comes from the executable +
    data caches and stays bitwise identical (the sweep-engine contract)."""
    W = 12
    data = generate_gmm(W * 8, 16, n_partitions=W, seed=0)
    cfg = _cfg(
        scheme="approx", n_workers=W, n_stragglers=2, num_collect=6,
        n_rows=W * 8, stack_mode="ring",
    )
    cache_lib.clear()
    first = trainer.train(cfg, data)
    second = trainer.train(cfg, data)
    assert second.cache_info["data_hit"]
    assert second.cache_info["exec_hits"] >= 1
    assert _bitwise_equal(first.params_history, second.params_history)


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------


def _covers_every_slot_once(plan, layout, n_devices):
    W, S = layout.n_workers, layout.n_slots
    Wl = W // n_devices
    Pl = layout.n_partitions // n_devices
    filled = (plan.sel >= 0).sum(axis=1)  # [D, Wl, S]
    assert (filled == 1).all(), "each slot filled exactly once"
    # and with the RIGHT partition: reconstruct assignment from the plan
    got = np.zeros((W, S), dtype=np.int64)
    for d in range(n_devices):
        for h in range(plan.n_hops):
            owner = (d + h) % n_devices
            for wl in range(Wl):
                for s in range(S):
                    p_local = plan.sel[d, h, wl, s]
                    if p_local >= 0:
                        got[d * Wl + wl, s] = owner * Pl + p_local
    assert np.array_equal(got, np.asarray(layout.assignment))


def test_plan_cyclic_is_ring_local():
    layout = codes.cyclic_mds_layout(12, 2)
    plan = sharding.plan_ring_transport(layout, 4)  # Pl = 3
    assert plan.n_hops == 2  # 1 + ceil(s/Pl) = 1 + ceil(2/3)
    _covers_every_slot_once(plan, layout, 4)


def test_plan_frc_is_block_local():
    layout = codes.frc_layout(12, 2)  # groups of 3 == device blocks
    plan = sharding.plan_ring_transport(layout, 4)
    assert plan.n_hops == 1  # every group lives inside one device block
    _covers_every_slot_once(plan, layout, 4)


def test_plan_general_fallback_covers_arbitrary_assignments():
    """Non-ring-local assignments (randreg's random graph, the partial
    schemes' split partition spaces) still plan correctly — just with
    more hops, never more than a full rotation."""
    for layout in (
        codes.random_regular_layout(12, 3, seed=7),
        codes.partial_cyclic_layout(12, 4, 2),
        codes.partial_frc_layout(12, 4, 2),
    ):
        for D in (2, 4, 6):
            plan = sharding.plan_ring_transport(layout, D)
            assert 1 <= plan.n_hops <= D, (layout.name, D, plan.n_hops)
            _covers_every_slot_once(plan, layout, D)


def test_plan_divisibility_guard():
    layout = codes.cyclic_mds_layout(12, 2)
    with pytest.raises(ValueError, match="divisible"):
        sharding.plan_ring_transport(layout, 5)


# ---------------------------------------------------------------------------
# auto resolution + config validation
# ---------------------------------------------------------------------------


def test_auto_resolves_by_footprint(monkeypatch):
    layout = codes.frc_layout(8, 1)
    data = generate_gmm(64, 16, n_partitions=8, seed=0)
    args = ("auto", layout, data, 4, np.float32)
    # tiny test shapes stay materialized under the production threshold
    assert not sharding.resolve_ring_stack(*args)
    # past the footprint gate, auto flips to ring
    monkeypatch.setattr(sharding, "RING_AUTO_MIN_BYTES", 1)
    assert sharding.resolve_ring_stack(*args)
    # unless the path has no ring body (measured mode passes supported=False)
    assert not sharding.resolve_ring_stack(*args, supported=False)
    # or there is no redundancy to stream (uncoded layout)
    assert not sharding.resolve_ring_stack(
        "auto", codes.uncoded_layout(8), data, 4, np.float32
    )
    # explicit "ring" always wins the resolution
    assert sharding.resolve_ring_stack(
        "ring", codes.uncoded_layout(8), data, 4, np.float32
    )


def test_auto_end_to_end_flips_with_threshold(monkeypatch):
    W = 8
    data = generate_gmm(W * 8, 16, n_partitions=W, seed=0)
    cfg = _cfg(scheme="approx", num_collect=4, stack_mode="auto")
    assert trainer.train(cfg, data).cache_info["stack_mode"] == "materialized"
    monkeypatch.setattr(sharding, "RING_AUTO_MIN_BYTES", 1)
    assert trainer.train(cfg, data).cache_info["stack_mode"] == "ring"


def test_config_validation():
    with pytest.raises(ValueError, match="stack_mode"):
        _cfg(stack_mode="banana")
    with pytest.raises(ValueError, match="redundancy to stream"):
        _cfg(stack_mode="ring", compute_mode="deduped")
    with pytest.raises(ValueError, match="measured"):
        _cfg(stack_mode="ring", arrival_mode="measured")
    with pytest.raises(ValueError, match="ring"):
        _cfg(stack_mode="ring", use_pallas="on")
    # auto composes with everything (resolution backs off where needed)
    _cfg(stack_mode="auto", use_pallas="on")
    _cfg(stack_mode="auto", compute_mode="deduped")


def test_ring_pipeline_resolution_and_exec_key():
    """resolve_ring_pipeline: on/off force, auto follows the
    measurement-pinned default; a pipelined and a sequential ring run of
    otherwise identical configs never share a compiled executable (the
    scan structure differs — the resolved schedule is in the ring
    signature)."""
    from erasurehead_tpu.parallel import step as step_lib

    assert step_lib.resolve_ring_pipeline("on") is True
    assert step_lib.resolve_ring_pipeline("off") is False
    assert (
        step_lib.resolve_ring_pipeline("auto")
        is step_lib.RING_PIPELINE_DEFAULT
    )
    W = 12
    data = generate_gmm(W * 8, 16, n_partitions=W, seed=0)
    cache_lib.clear()
    base = _cfg(
        scheme="approx", n_workers=W, n_stragglers=2, num_collect=6,
        n_rows=W * 8, stack_mode="ring",
    )
    trainer.train(dataclasses.replace(base, ring_pipeline="off"), data)
    p = trainer.train(dataclasses.replace(base, ring_pipeline="on"), data)
    assert p.cache_info["exec_misses"] >= 1  # no false hit
    assert p.cache_info["data_hit"]  # same upload serves both schedules


def test_ring_pipeline_cohort_and_dynamic_bitwise():
    """The double-buffered transport composes with the trajectory-cohort
    dispatch and the on-device dynamic trainer without breaking bit
    identity against the sequential schedule."""
    data = gmm12()
    cfg = _cfg(
        scheme="repcoded", n_workers=12, n_stragglers=2, n_rows=96,
        rounds=2, stack_mode="ring",
    )
    seq = trainer.train_batch(cfg, data, seeds=[0, 1])
    pipe = trainer.train_batch(
        dataclasses.replace(cfg, ring_pipeline="on"), data, seeds=[0, 1]
    )
    for s, p in zip(seq, pipe):
        assert _bitwise_equal(s.params_history, p.params_history)
    dcfg = _cfg(
        scheme="approx", n_workers=12, n_stragglers=2, num_collect=6,
        n_rows=96, rounds=2, stack_mode="ring",
    )
    d_seq = trainer.train_dynamic(dcfg, data)
    d_pipe = trainer.train_dynamic(
        dataclasses.replace(dcfg, ring_pipeline="on"), data
    )
    assert _bitwise_equal(d_seq.params_history, d_pipe.params_history)


def test_exec_cache_keys_on_resolved_ring():
    """A materialized and a ring run of otherwise identical configs must
    never share a compiled executable (their arg shapes AND programs
    differ) — the resolved flag is part of the signature."""
    W = 8
    data = generate_gmm(W * 8, 16, n_partitions=W, seed=0)
    cache_lib.clear()
    trainer.train(_cfg(scheme="approx", num_collect=4), data)
    r = trainer.train(
        _cfg(scheme="approx", num_collect=4, stack_mode="ring"), data
    )
    assert r.cache_info["exec_misses"] >= 1  # no false hit


# ---------------------------------------------------------------------------
# mesh ring alignment
# ---------------------------------------------------------------------------


class _FakeDev:
    def __init__(self, coords, core=0):
        self.coords = coords
        self.core_on_chip = core

    def __repr__(self):
        return f"dev{self.coords}"


def test_ring_order_devices_snake_adjacency():
    """On coordinate-bearing devices, consecutive ring positions must be
    physical neighbors (manhattan distance 1 over the torus axes), and
    the order must be a permutation of the input."""
    grid = [
        _FakeDev((x, y, 0)) for x in range(4) for y in range(4)
    ]
    rng = np.random.default_rng(0)
    shuffled = [grid[i] for i in rng.permutation(len(grid))]
    ordered = ring_order_devices(shuffled)
    assert sorted(d.coords for d in ordered) == sorted(
        d.coords for d in grid
    )
    for a, b in zip(ordered[:-1], ordered[1:]):
        dist = sum(abs(i - j) for i, j in zip(a.coords, b.coords))
        assert dist == 1, (a, b)


def test_ring_order_devices_cpu_passthrough():
    """Backends without coords (the CPU test mesh) keep the given order —
    the alignment must never reshuffle semantics-bearing device lists."""
    devs = jax.devices()
    assert ring_order_devices(devs) == list(devs)
    mesh = worker_mesh(4)
    assert list(np.asarray(mesh.devices).flat) == list(devs[:4])
