"""Collection-rule tests: each rule is checked against an independent
event-by-event simulation of the reference's master Waitany loop, plus
scheme-specific exactness properties."""

import numpy as np
import pytest

from erasurehead_tpu.ops import codes
from erasurehead_tpu.parallel import collect, straggler
from erasurehead_tpu.utils.config import Scheme

R, W, S = 20, 12, 2  # rounds, workers, stragglers; W % (S+1) == 0


@pytest.fixture(scope="module")
def arrivals():
    return straggler.arrival_schedule(R, W, add_delay=True)


def _oracle_master_loop(t_row, stop_fn, use_fn):
    """Replay of the reference master pattern: process arrivals in order,
    stamping each, until stop_fn says the wait loop exits.

    Returns (stamped worker_times, used mask, exit time). ``use_fn(w, state)``
    says whether an arrival's gradient is added to g.
    """
    order = np.lexsort((np.arange(len(t_row)), t_row))
    wt = np.full(len(t_row), collect.NEVER)
    used = np.zeros(len(t_row), dtype=bool)
    state = {}
    for j, w in enumerate(order):
        wt[w] = t_row[w]
        used[w] = use_fn(w, state)
        if stop_fn(j + 1, state):
            return wt, used, t_row[w]
    return wt, used, t_row[order[-1]]


# ---------------------------------------------------------------------------


def test_naive(arrivals):
    sched = collect.collect_all(arrivals)
    assert (sched.message_weights == 1.0).all()
    assert np.allclose(sched.sim_time, arrivals.max(axis=1))
    assert sched.collected.all()
    assert np.array_equal(sched.worker_times, arrivals)


def test_first_k_mds_against_oracle(arrivals):
    B = codes.cyclic_generator_matrix(W, S, seed=0)
    sched = collect.collect_first_k_mds(arrivals, B, S)
    k = W - S
    for r in range(R):
        wt, _, exit_t = _oracle_master_loop(
            arrivals[r],
            stop_fn=lambda n, st: n >= k,
            use_fn=lambda w, st: True,
        )
        assert np.array_equal(sched.worker_times[r], wt)
        assert sched.sim_time[r] == exit_t
        assert sched.collected[r].sum() == k
    # decode exactness on every round
    assert np.abs(sched.message_weights @ B - 1.0).max() < 1e-8


def test_frc_against_oracle(arrivals):
    lay = codes.frc_layout(W, S)
    sched = collect.collect_frc(arrivals, lay.groups)
    n_groups = lay.n_groups
    for r in range(R):
        def use(w, st, r=r):
            g = lay.groups[w]
            if g not in st.setdefault("covered", set()):
                st["covered"].add(g)
                return True
            return False

        wt, used, exit_t = _oracle_master_loop(
            arrivals[r],
            stop_fn=lambda n, st: len(st.get("covered", ())) >= n_groups,
            use_fn=use,
        )
        assert np.array_equal(sched.worker_times[r], wt)
        assert np.array_equal(sched.message_weights[r] > 0, used)
        assert sched.sim_time[r] == exit_t
    # one winner per group, unit weight => decode == full gradient for FRC
    E = lay.effective_matrix()
    decoded = sched.message_weights @ E
    assert np.allclose(decoded, 1.0)


@pytest.mark.parametrize("num_collect", [4, 6, 9, 12])
def test_agc_against_oracle(arrivals, num_collect):
    lay = codes.frc_layout(W, S)
    sched = collect.collect_agc(arrivals, lay.groups, num_collect)
    n_groups = lay.n_groups
    for r in range(R):
        def use(w, st):
            g = lay.groups[w]
            st["workers"] = st.get("workers", 0) + 1
            if g not in st.setdefault("covered", set()):
                st["covered"].add(g)
                return True
            return False

        def stop(n, st):
            # reference: while (cnt_workers < num_collect) and
            # (cnt_groups < n_groups)   (src/approximate_coding.py:144)
            return st["workers"] >= num_collect or len(st["covered"]) >= n_groups

        wt, used, exit_t = _oracle_master_loop(arrivals[r], stop, use)
        assert np.array_equal(sched.worker_times[r], wt), r
        assert np.array_equal(sched.message_weights[r] > 0, used), r
        assert sched.sim_time[r] == exit_t, r


def test_agc_full_collect_equals_frc(arrivals):
    """With num_collect >= W, AGC keeps collecting until all groups are
    covered — identical gradient to FRC."""
    lay = codes.frc_layout(W, S)
    agc = collect.collect_agc(arrivals, lay.groups, num_collect=W)
    frc = collect.collect_frc(arrivals, lay.groups)
    assert np.array_equal(agc.message_weights, frc.message_weights)
    assert np.allclose(agc.sim_time, frc.sim_time)


def test_agc_erasure_fraction(arrivals):
    """With small num_collect, some groups are erased: decoded weight vector
    covers covered groups exactly, erased groups get zero."""
    lay = codes.frc_layout(W, S)
    sched = collect.collect_agc(arrivals, lay.groups, num_collect=4)
    E = lay.effective_matrix()
    decoded = sched.message_weights @ E  # [R, n_partitions] in {0, 1}
    assert set(np.unique(decoded)).issubset({0.0, 1.0})
    # at most num_collect workers collected per round
    assert (sched.collected.sum(axis=1) <= 4).all()


def test_avoidstragg(arrivals):
    sched = collect.collect_avoidstragg(arrivals, S)
    k = W - S
    assert (sched.collected.sum(axis=1) == k).all()
    # rescale: sum of weights == W (unbiasedness in expectation)
    assert np.allclose(sched.message_weights.sum(axis=1), W)
    kth = np.sort(arrivals, axis=1)[:, k - 1]
    assert np.allclose(sched.sim_time, kth)


@pytest.mark.parametrize("variant,make", [
    ("mds", lambda: codes.partial_cyclic_layout(W, 4, S // 2, seed=0)),
    ("frc", lambda: codes.partial_frc_layout(W, 4, S // 2)),
])
def test_partial_decodes_full_gradient(arrivals, variant, make):
    lay = make()
    sched = collect.collect_partial(arrivals, lay, variant)
    # full decode: separate slots (weight 1) + weighted coded messages
    rng = np.random.default_rng(0)
    G = rng.standard_normal((lay.n_partitions, 3))
    n_sep_partitions = int((~lay.slot_is_coded).sum()) * W
    E = lay.effective_matrix()  # coded-band scatter
    for r in range(R):
        decoded = G[:n_sep_partitions].sum(axis=0) + (
            sched.message_weights[r] @ E
        ) @ G
        assert np.allclose(decoded, G.sum(axis=0), atol=1e-8), (variant, r)
    # master always waits for every worker's uncoded part
    n_sep = int((~lay.slot_is_coded).sum())
    frac = n_sep / lay.n_slots
    assert (sched.sim_time >= frac * arrivals.max(axis=1) - 1e-12).all()


def test_build_schedule_dispatch(arrivals):
    for scheme, lay, kw in [
        (Scheme.NAIVE, codes.uncoded_layout(W), {}),
        (Scheme.CYCLIC_MDS, codes.cyclic_mds_layout(W, S), {}),
        (Scheme.FRC, codes.frc_layout(W, S), {}),
        (Scheme.APPROX, codes.frc_layout(W, S), dict(num_collect=6)),
        (Scheme.AVOID_STRAGGLERS, codes.uncoded_layout(W), {}),
        (Scheme.PARTIAL_CYCLIC, codes.partial_cyclic_layout(W, 4, 1), {}),
        (Scheme.PARTIAL_FRC, codes.partial_frc_layout(W, 4, 1), {}),
    ]:
        sched = collect.build_schedule(scheme, arrivals, lay, **kw)
        assert sched.message_weights.shape == (R, W)
        assert sched.sim_time.shape == (R,)
        # sim_time is a realized arrival time (or max thereof)
        assert (sched.sim_time <= arrivals.max(axis=1) + 1e-12).all()


def test_zero_delay_ties_deterministic():
    """add_delay=0: all arrivals zero; rules degrade to worker-index order."""
    t = np.zeros((3, W))
    lay = codes.frc_layout(W, S)
    sched = collect.collect_agc(t, lay.groups, num_collect=5)
    # first 5 workers by index are collected
    expect = np.zeros(W, dtype=bool)
    expect[:5] = True
    assert np.array_equal(sched.collected[0], expect)


def test_reference_delay_schedule_parity():
    """Bit-exact with the reference's np.random.seed(i) global-RNG draws
    (src/naive.py:141-147)."""
    sched = straggler.reference_delay_schedule(5, W)
    for i in range(5):
        np.random.seed(i)
        expect = np.random.exponential(0.5, W)
        assert np.array_equal(sched[i], expect)


def test_reference_delay_schedule_seed_offset():
    """seed_offset=0 is the reference's exact schedule; a nonzero offset
    is an independent universe with the same MT19937 construction (the
    variance study's knob, tools/flagship_variance.py)."""
    base = straggler.reference_delay_schedule(4, W)
    assert np.array_equal(
        base, straggler.reference_delay_schedule(4, W, seed_offset=0)
    )
    other = straggler.reference_delay_schedule(4, W, seed_offset=1_000_003)
    assert not np.array_equal(base, other)
    for i in range(4):
        np.random.seed(i + 1_000_003)
        assert np.array_equal(other[i], np.random.exponential(0.5, W))


def test_heterogeneous_arrival_model():
    """compute_time + worker_speed_spread shift arrivals per worker; the
    pure-delay reference regime (0/0) is unchanged."""
    from erasurehead_tpu.utils.config import RunConfig

    cfg = RunConfig(
        scheme="naive", n_workers=W, n_stragglers=0, rounds=R,
        compute_time=2.0, worker_speed_spread=0.5, seed=3,
    )
    model = straggler.model_from_config(cfg)
    assert model is not None and model.worker_speed.shape == (W,)
    assert (model.worker_speed >= 0.5).all() and (model.worker_speed <= 1.5).all()
    base = straggler.arrival_schedule(R, W, add_delay=True)
    het = straggler.arrival_schedule(R, W, add_delay=True, arrival_model=model)
    np.testing.assert_allclose(
        het - base, np.tile(2.0 * model.worker_speed, (R, 1))
    )
    # default config -> None (reference regime)
    cfg0 = RunConfig(scheme="naive", n_workers=W, n_stragglers=0, rounds=R)
    assert straggler.model_from_config(cfg0) is None
    # deterministic per seed
    m2 = straggler.model_from_config(cfg)
    np.testing.assert_array_equal(model.worker_speed, m2.worker_speed)


def test_control_plane_scales_to_10k_rounds():
    """The collection rules are batched (argsort + prefix scans, deduped
    lstsq) — no per-round Python. A 10,000-round schedule for every rule
    must build in well under a second each on this class of host."""
    import time

    R10 = 10_000
    t = straggler.arrival_schedule(R10, W, add_delay=True)
    lay_frc = codes.frc_layout(W, S)
    lay_pfrc = codes.partial_frc_layout(W, 6, S)
    lay_pmds = codes.partial_cyclic_layout(W, 6, S, seed=0)
    B = codes.cyclic_generator_matrix(W, S, seed=0)
    rules = {
        "agc": lambda: collect.collect_agc(t, lay_frc.groups, W // 2),
        "mds": lambda: collect.collect_first_k_mds(t, B, S),
        "partial_frc": lambda: collect.collect_partial(t, lay_pfrc, "frc"),
        "partial_mds": lambda: collect.collect_partial(t, lay_pmds, "mds"),
    }
    for name, fn in rules.items():
        t0 = time.perf_counter()
        sched = fn()
        took = time.perf_counter() - t0
        assert sched.sim_time.shape == (R10,)
        # measured ~0.2s/rule on a dev host; 5s still rules out O(R)-Python
        # regressions while leaving headroom for loaded CI machines
        assert took < 5.0, f"{name} control plane took {took:.2f}s at R={R10}"


def test_deadline_collection_rule():
    """Deadline scheme (beyond the reference): collect what arrived by the
    cutoff, unbiased W/collected rescale; early stop only when everyone
    arrived; zero-arrival rounds apply a zero gradient at full deadline
    cost; dead workers (inf) never make the cutoff."""
    t = np.array([
        [0.1, 0.2, 0.3, 0.4],   # all in by 1.0 -> stop at 0.4
        [0.1, 0.2, 5.0, 9.0],   # two in -> rescale 4/2, sim = deadline
        [3.0, 5.0, 7.0, 9.0],   # none in -> zero gradient, sim = deadline
        [0.1, np.inf, 0.5, np.inf],  # dead workers never collected
    ])
    s = collect.collect_deadline(t, deadline=1.0)
    assert np.allclose(s.sim_time, [0.4, 1.0, 1.0, 1.0])
    assert s.collected.tolist() == [
        [True, True, True, True],
        [True, True, False, False],
        [False, False, False, False],
        [True, False, True, False],
    ]
    assert np.allclose(s.message_weights[0], 1.0)
    assert np.allclose(s.message_weights[1], [2.0, 2.0, 0.0, 0.0])
    assert np.allclose(s.message_weights[2], 0.0)
    assert np.allclose(s.message_weights[3], [2.0, 0.0, 2.0, 0.0])
    # unbiasedness: weights sum to W over collected rounds
    assert np.allclose(s.message_weights[1].sum(), 4.0)
    # -1 sentinel for uncollected
    assert s.worker_times[1, 2] == collect.NEVER
    # dispatch path
    from erasurehead_tpu.ops import codes
    s2 = collect.build_schedule(
        Scheme.DEADLINE, t, codes.uncoded_layout(4), deadline=1.0
    )
    assert np.allclose(s2.message_weights, s.message_weights)
    with pytest.raises(ValueError, match="deadline"):
        collect.build_schedule(Scheme.DEADLINE, t, codes.uncoded_layout(4))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_all_schemes_schedule_invariants(seed):
    """Structural invariants every scheme's schedule must satisfy, fuzzed
    over random arrival matrices: collected workers carry their true
    arrival stamp, uncollected carry the -1 sentinel and zero decode
    weight, and the round clock is at least the latest collected arrival
    (the master cannot finish before its last used message)."""
    rng = np.random.default_rng(seed)
    Wf = 12
    t = rng.exponential(0.5, size=(8, Wf))
    cases = [
        (Scheme.NAIVE, codes.uncoded_layout(Wf), {}),
        (Scheme.AVOID_STRAGGLERS, codes.uncoded_layout(Wf, n_stragglers=2), {}),
        (Scheme.CYCLIC_MDS, codes.cyclic_mds_layout(Wf, 2, seed=0), {}),
        (Scheme.FRC, codes.frc_layout(Wf, 2), {}),
        (Scheme.APPROX, codes.frc_layout(Wf, 2), dict(num_collect=7)),
        (Scheme.RANDOM_REGULAR, codes.random_regular_layout(Wf, 2, seed=0),
         dict(num_collect=8)),
        (Scheme.DEADLINE, codes.uncoded_layout(Wf), dict(deadline=0.7)),
        (Scheme.PARTIAL_CYCLIC, codes.partial_cyclic_layout(Wf, 4, 2, seed=0), {}),
        (Scheme.PARTIAL_FRC, codes.partial_frc_layout(Wf, 4, 2), {}),
    ]
    for scheme, layout, kw in cases:
        s = collect.build_schedule(scheme, t, layout, **kw)
        col = s.collected
        # stamps: true arrival where collected, NEVER where not
        np.testing.assert_allclose(
            s.worker_times, np.where(col, t, collect.NEVER), err_msg=scheme
        )
        # no decode weight on uncollected messages
        assert (np.asarray(s.message_weights)[~col] == 0).all(), scheme
        # the clock cannot precede the last collected arrival — including
        # partial schemes, where "collected" means the coded second part
        # (at time t) was processed at or before the stop event
        last_used = np.where(col, t, -np.inf).max(axis=1)
        assert (s.sim_time >= last_used - 1e-9).all(), scheme
