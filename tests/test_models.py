"""Model & metrics tests: closed-form gradients vs autodiff, numpy oracles
for the reference's formulas, sparse/dense equivalence, AUC vs sklearn."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sps

from erasurehead_tpu.models import metrics
from erasurehead_tpu.models.glm import LinearModel, LogisticModel
from erasurehead_tpu.models.mlp import MLPModel
from erasurehead_tpu.ops.features import PaddedRows, matvec, rmatvec


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((64, 10)).astype(np.float32)
    y = np.where(rng.standard_normal(64) > 0, 1.0, -1.0).astype(np.float32)
    beta = rng.standard_normal(10).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(y), jnp.asarray(beta)


def test_logistic_grad_matches_reference_formula(data):
    X, y, beta = data
    m = LogisticModel()
    g = m.grad_sum(beta, X, y)
    # reference closed form: -X^T (y / (exp((X beta)*y) + 1)), src/naive.py:137-139
    Xn, yn, bn = map(np.asarray, (X, y, beta))
    predy = Xn @ bn
    expect = -Xn.T @ (yn / (np.exp(predy * yn) + 1.0))
    assert np.allclose(g, expect, atol=1e-4)


def test_logistic_grad_matches_autodiff(data):
    X, y, beta = data
    m = LogisticModel()
    assert np.allclose(m.grad_sum(beta, X, y), m.grad_sum_auto(beta, X, y), atol=1e-3)


def test_linear_grad_matches_reference_formula(data):
    X, y, beta = data
    m = LinearModel()
    g = m.grad_sum(beta, X, y)
    Xn, yn, bn = map(np.asarray, (X, y, beta))
    expect = -2.0 * Xn.T @ (yn - Xn @ bn)  # src/naive.py:341-346
    assert np.allclose(g, expect, atol=1e-3)
    assert np.allclose(g, m.grad_sum_auto(beta, X, y), atol=1e-3)


def test_grad_additivity_over_shards(data):
    """The property gradient coding rests on: sum-gradients add over
    row-disjoint shards."""
    X, y, beta = data
    for m in (LogisticModel(), LinearModel()):
        whole = m.grad_sum(beta, X, y)
        parts = m.grad_sum(beta, X[:32], y[:32]) + m.grad_sum(beta, X[32:], y[32:])
        assert np.allclose(whole, parts, atol=1e-4)


def test_logistic_loss_matches_reference_formula(data):
    X, y, beta = data
    m = LogisticModel()
    loss = m.loss_mean(beta, X, y)
    Xn, yn, bn = map(np.asarray, (X, y, beta))
    expect = np.sum(np.log(1 + np.exp(-yn * (Xn @ bn)))) / 64  # src/util.py:136-137
    assert np.allclose(loss, expect, atol=1e-5)


def test_logistic_loss_stable_at_large_margins():
    m = LogisticModel()
    X = jnp.ones((2, 1)) * 1000.0
    y = jnp.array([1.0, -1.0])
    beta = jnp.ones(1)
    loss = m.loss_mean(beta, X, y)
    assert np.isfinite(loss)  # reference's literal form overflows here


def test_mlp_gradients_and_pytree(data):
    X, y, _ = data
    m = MLPModel(hidden=8)
    params = m.init_params(jax.random.key(0), 10)
    g = m.grad_sum(params, X, y)
    assert set(g) == {"W1", "b1", "w2", "b2"}
    assert g["W1"].shape == (10, 8)
    # additivity holds for the MLP too
    parts = jax.tree.map(
        lambda a, b: a + b,
        m.grad_sum(params, X[:32], y[:32]),
        m.grad_sum(params, X[32:], y[32:]),
    )
    assert all(
        np.allclose(parts[k], g[k], atol=1e-3) for k in g
    )


# ---------------------------------------------------------------------------
# sparse features
# ---------------------------------------------------------------------------


def test_padded_rows_matvec_rmatvec_match_dense():
    rng = np.random.default_rng(1)
    dense = sps.random(50, 40, density=0.1, random_state=2, format="csr")
    P = PaddedRows.from_scipy(dense)
    Xd = jnp.asarray(dense.toarray())
    v = jnp.asarray(rng.standard_normal(40).astype(np.float32))
    r = jnp.asarray(rng.standard_normal(50).astype(np.float32))
    assert np.allclose(matvec(P, v), matvec(Xd, v), atol=1e-4)
    assert np.allclose(rmatvec(P, r), rmatvec(Xd, r), atol=1e-4)
    # matrix right-hand sides (MLP first layer)
    V = jnp.asarray(rng.standard_normal((40, 7)).astype(np.float32))
    Rm = jnp.asarray(rng.standard_normal((50, 7)).astype(np.float32))
    assert np.allclose(matvec(P, V), matvec(Xd, V), atol=1e-4)
    assert np.allclose(rmatvec(P, Rm), rmatvec(Xd, Rm), atol=1e-4)
    assert np.allclose(P.to_dense(), dense.toarray(), atol=1e-6)


def test_models_work_on_padded_rows(data):
    _, y, beta = data
    rng = np.random.default_rng(3)
    dense = sps.random(64, 10, density=0.3, random_state=3, format="csr")
    P = PaddedRows.from_scipy(dense)
    Xd = jnp.asarray(dense.toarray())
    m = LogisticModel()
    assert np.allclose(m.grad_sum(beta, P, y), m.grad_sum(beta, Xd, y), atol=1e-4)
    assert np.allclose(m.loss_mean(beta, P, y), m.loss_mean(beta, Xd, y), atol=1e-5)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def test_auc_matches_sklearn():
    rng = np.random.default_rng(4)
    y = np.where(rng.standard_normal(200) > 0, 1.0, -1.0)
    scores = rng.standard_normal(200) + 0.8 * y
    ours = float(metrics.auc(jnp.asarray(y), jnp.asarray(scores)))
    skl = metrics.auc_sklearn(y, scores)
    assert abs(ours - skl) < 1e-6


def test_auc_with_ties_matches_sklearn():
    rng = np.random.default_rng(5)
    y = np.where(rng.standard_normal(300) > 0, 1.0, -1.0)
    scores = np.round(rng.standard_normal(300) + 0.5 * y, 1)  # heavy ties
    ours = float(metrics.auc(jnp.asarray(y), jnp.asarray(scores)))
    skl = metrics.auc_sklearn(y, scores)
    assert abs(ours - skl) < 1e-5


def test_auc_jittable():
    rng = np.random.default_rng(6)
    y = jnp.asarray(np.where(rng.standard_normal(100) > 0, 1.0, -1.0))
    s = jnp.asarray(rng.standard_normal(100))
    assert np.isclose(jax.jit(metrics.auc)(y, s), metrics.auc(y, s))


def test_sparse_lanes_matches_scalar_path():
    """The lane-replicated gather/scatter lowering (features.set_sparse_lanes,
    the TPU scalar-gather workaround) must agree with the scalar path to
    f32 reduction tolerance at every lane width. (Not bit-exact: the lane
    reduction itself is an exact exponent shift over identical lanes, but
    XLA may reassociate the row contraction differently per shape.)"""
    from erasurehead_tpu.ops import features

    rng = np.random.default_rng(5)
    dense = sps.random(60, 45, density=0.15, random_state=3, format="csr")
    P = PaddedRows.from_scipy(dense)
    v = jnp.asarray(rng.standard_normal(45).astype(np.float32))
    r = jnp.asarray(rng.standard_normal(60).astype(np.float32))
    base_mv = np.asarray(matvec(P, v))
    base_rmv = np.asarray(rmatvec(P, r))
    try:
        for L in (1, 8, 128):
            features.set_sparse_lanes(L)
            assert np.allclose(matvec(P, v), base_mv, atol=1e-5), L
            assert np.allclose(rmatvec(P, r), base_rmv, atol=1e-5), L
        # matrix RHS keeps the scalar path regardless of the knob
        V = jnp.asarray(rng.standard_normal((45, 3)).astype(np.float32))
        features.set_sparse_lanes(8)
        assert np.allclose(matvec(P, V), matvec(jnp.asarray(dense.toarray()), V),
                           atol=1e-4)
    finally:
        features.set_sparse_lanes(None)
    with pytest.raises(ValueError):
        features.set_sparse_lanes(12)  # not a power of two
    with pytest.raises(ValueError):
        features.set_sparse_lanes(2048)


def test_sparse_lanes_scope_to_matvec_only():
    """Lanes rewrite the margin gather but NOT the scatter: the v5e profile
    measured the lane gather at 2.6x the scalar margin and the lane scatter
    as a net loss (tools/profile_sparse.py, BASELINE.md round-3 window 1),
    so set_sparse_lanes must change matvec's lowering while rmatvec's stays
    the scalar scatter-add. Pinned structurally via the traced jaxprs."""
    from erasurehead_tpu.ops import features

    rng = np.random.default_rng(7)
    dense = sps.random(40, 30, density=0.2, random_state=4, format="csr")
    P = PaddedRows.from_scipy(dense)
    v = jnp.asarray(rng.standard_normal(30).astype(np.float32))
    r = jnp.asarray(rng.standard_normal(40).astype(np.float32))
    mv_scalar = str(jax.make_jaxpr(lambda u: matvec(P, u))(v))
    rmv_scalar = str(jax.make_jaxpr(lambda u: rmatvec(P, u))(r))
    try:
        features.set_sparse_lanes(8)
        mv_lanes = str(jax.make_jaxpr(lambda u: matvec(P, u))(v))
        rmv_lanes = str(jax.make_jaxpr(lambda u: rmatvec(P, u))(r))
    finally:
        features.set_sparse_lanes(None)
    assert mv_lanes != mv_scalar  # gather direction takes the lane table
    assert rmv_lanes == rmv_scalar  # scatter direction ignores the knob


def test_dense_margin_cols_matches_direct_path():
    """The margin_cols matvec lowering (features.set_dense_margin_cols —
    the candidate fix for the measured TPU cross-lane-reduction bound,
    VERDICT r2 item 2) must agree with the direct matvec in both f32 and
    bf16 data modes, under vmap (the per-slot production shape), and must
    leave matrix RHS and sparse inputs on their own paths."""
    import jax

    from erasurehead_tpu.ops import features

    rng = np.random.default_rng(9)
    X = jnp.asarray(rng.standard_normal((6, 40, 32)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal(32).astype(np.float32))
    direct = np.asarray(jax.vmap(lambda Xs: matvec(Xs, v))(X))
    direct_bf = np.asarray(
        jax.vmap(lambda Xs: matvec(Xs, v))(X.astype(jnp.bfloat16))
    )
    try:
        for C in (8, 128):
            features.set_dense_margin_cols(C)
            got = np.asarray(jax.vmap(lambda Xs: matvec(Xs, v))(X))
            np.testing.assert_allclose(got, direct, rtol=1e-6, atol=1e-6)
            got_bf = np.asarray(
                jax.vmap(lambda Xs: matvec(Xs, v))(X.astype(jnp.bfloat16))
            )
            np.testing.assert_allclose(got_bf, direct_bf, rtol=1e-5,
                                       atol=1e-5)
        # matrix RHS keeps the plain matmul path
        V = jnp.asarray(rng.standard_normal((32, 3)).astype(np.float32))
        features.set_dense_margin_cols(8)
        np.testing.assert_allclose(
            np.asarray(matvec(X[0], V)),
            np.asarray(jnp.matmul(X[0], V)), rtol=1e-6, atol=1e-6,
        )
        # sparse inputs ignore the dense knob
        dense = sps.random(30, 32, density=0.2, random_state=1, format="csr")
        P = PaddedRows.from_scipy(dense)
        np.testing.assert_allclose(
            np.asarray(matvec(P, v)),
            np.asarray(dense.toarray() @ np.asarray(v)), atol=1e-5,
        )
    finally:
        features.set_dense_margin_cols(None)
    with pytest.raises(ValueError):
        features.set_dense_margin_cols(1)
    with pytest.raises(ValueError):
        features.set_dense_margin_cols(256)


def test_attention_model_grad_additivity():
    """grad_sum additivity over row-disjoint shards — the property all
    gradient coding rests on — holds for the attention-classifier pytree
    (models/attention.py) like the GLM/MLP families above."""
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.models.attention import AttentionModel

    model = AttentionModel()
    ds = generate_gmm(32, 64, n_partitions=2, seed=1)
    X = jnp.asarray(ds.X_train)
    y = jnp.asarray(ds.y_train)
    params = model.init_params(jax.random.key(0), 64)
    g_full = model.grad_sum(params, X, y)
    g_split = jax.tree.map(
        lambda a, b: a + b,
        model.grad_sum(params, X[:16], y[:16]),
        model.grad_sum(params, X[16:], y[16:]),
    )
    for a, b in zip(jax.tree.leaves(g_full), jax.tree.leaves(g_split)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_attention_model_rejects_bad_feature_dim():
    from erasurehead_tpu.models.attention import AttentionModel

    with pytest.raises(ValueError, match="divisible"):
        AttentionModel(d_in=8).init_params(jax.random.key(0), 60)
