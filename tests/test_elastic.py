"""Elastic membership (ISSUE 11): online death detection from telemetry,
mid-run re-layout, worker join, adversarial targeted straggler attacks,
chaos worker_death/worker_revive sites, and the kill->resume row
rehydration contract.

The controller must decide membership from what the run itself observed
(the -1 never-collected sentinel, detect_dead timeout trips) — never from
the scripted ground truth the tests construct the world with.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from erasurehead_tpu import elastic
from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.elastic.controller import (
    ElasticConfig,
    MembershipController,
)
from erasurehead_tpu.obs import events as obs_events
from erasurehead_tpu.ops import codes
from erasurehead_tpu.parallel import collect, straggler
from erasurehead_tpu.utils import chaos as chaos_lib
from erasurehead_tpu.utils.config import RunConfig

W, R, CHUNK = 8, 30, 5


@pytest.fixture(autouse=True)
def _reset_chaos():
    chaos_lib.reset()
    yield
    chaos_lib.reset()


@pytest.fixture(scope="module")
def ds():
    return generate_gmm(32 * W, 16, n_partitions=W, seed=0)


def _cfg(**kw):
    base = dict(
        scheme="naive", n_workers=W, n_stragglers=0, rounds=R,
        n_rows=32 * W, n_cols=16, lr_schedule=1.0, update_rule="AGD",
        add_delay=True, seed=0,
    )
    base.update(kw)
    return RunConfig(**base)


def _ecfg(**kw):
    base = dict(chunk_rounds=CHUNK, death_rounds=3, timeout=4.0)
    base.update(kw)
    return ElasticConfig(**base)


# ---------------------------------------------------------------------------
# controller unit behavior


def test_controller_streaks_and_k_rule():
    """K CONSECUTIVE suspect rounds declare death; a single arrival resets
    the streak (the all--1 vs transiently-slow distinction)."""
    ctl = MembershipController(4, _ecfg(death_rounds=3))
    # worker 3 silent all 3 rounds; worker 2 silent twice then arrives
    wt = np.array([
        [0.1, 0.2, -1.0, -1.0],
        [0.1, 0.2, -1.0, -1.0],
        [0.1, 0.2, 0.3, -1.0],
    ])
    obs = ctl.observe_chunk(0, wt)
    assert obs.deaths == (3,)
    change = ctl.commit(3)
    assert change.dead == (3,) and change.n_workers_after == 3
    assert ctl.active == (0, 1, 2)
    # streaks carry ACROSS chunks: two more silent rounds finish worker 2
    ctl2 = MembershipController(4, _ecfg(death_rounds=3))
    ctl2.observe_chunk(0, wt[:2])  # streaks: w2=2, w3=2
    obs2 = ctl2.observe_chunk(2, np.array([[0.1, 0.2, -1.0, -1.0]]))
    assert set(obs2.deaths) == {2, 3}


def test_controller_timeout_trip_counts_as_suspect():
    """A finite arrival beyond the master's patience suspects the worker
    exactly like the sentinel (failures.detect_dead semantics)."""
    ctl = MembershipController(3, _ecfg(death_rounds=2, timeout=1.0))
    wt = np.array([[0.1, 0.2, 50.0], [0.1, 0.2, 60.0]])
    obs = ctl.observe_chunk(0, wt)
    assert obs.deaths == (2,)


def test_controller_evidential_gate_blocks_early_stop_sentinels():
    """The false-eviction regression found at the canonical W=30 AGC
    collect=15 config: a sentinel in a round the master ended EARLY
    (sim < window) is 'stopped listening', not death evidence — the
    streak must not advance on it, while a full-window round advances it
    and an in-patience arrival still resets everything."""
    ctl = MembershipController(
        3, _ecfg(death_rounds=2, timeout=4.0, absence_rounds=100)
    )
    # worker 2 uncollected 4 rounds straight, but every round stopped
    # early (the AGC first-k pattern): NEVER declared dead
    wt = np.array([[0.1, 0.2, -1.0]] * 4)
    obs = ctl.observe_chunk(0, wt, sim_time=np.full(4, 0.3), window=4.0)
    assert obs.deaths == ()
    assert ctl._streaks[2] == 0 and ctl._absence[2] == 4
    # two full-window rounds with the sentinel: now it IS evidence
    obs = ctl.observe_chunk(
        4, wt[:2], sim_time=np.full(2, 4.0), window=4.0
    )
    assert obs.deaths == (2,)
    death = next(d for d in ctl.decisions if d["action"] == "death")
    assert death["rule"] == "streak"


def test_controller_absence_backstop():
    """A scheme with slack never produces evidential rounds for a dead
    worker (AGC keeps ending early on the survivors); the long-window
    absence rule catches it anyway, and an occasional collection resets
    the window so rotating early-stop policies never false-positive."""
    ctl = MembershipController(
        3, _ecfg(death_rounds=2, timeout=4.0, absence_rounds=6)
    )
    cheap = np.full(3, 0.3)
    # healthy worker 1: uncollected often but arrives sometimes
    w1 = [[0.1, -1.0, -1.0], [0.1, -1.0, -1.0], [0.1, 0.2, -1.0]]
    obs = ctl.observe_chunk(0, np.array(w1), sim_time=cheap, window=4.0)
    assert obs.deaths == ()
    # worker 2 stays absent: 3 + 3 = 6 consecutive rounds -> absence rule
    w2 = [[0.1, 0.3, -1.0]] * 3
    obs = ctl.observe_chunk(3, np.array(w2), sim_time=cheap, window=4.0)
    assert obs.deaths == (2,)
    death = next(d for d in ctl.decisions if d["action"] == "death")
    assert death["rule"] == "absence" and death["absent"] == 6


def test_online_detection_no_false_positives_under_agc(ds):
    """Driver-level pin of the same regression: an AGC run collecting
    half the cluster every round must evict ONLY the genuinely dead
    workers (via the absence backstop), never the healthy ones the stop
    rule left uncollected."""
    cfg = _cfg(scheme="approx", n_stragglers=1, num_collect=4, rounds=40)
    res = elastic.train_elastic_online(
        cfg, ds,
        elastic=_ecfg(chunk_rounds=8, death_rounds=3),
        deaths={6: 5, 7: 5},
    )
    dead = sorted(
        d["worker"] for d in res.decisions if d["action"] == "death"
    )
    assert dead == [6, 7], res.decisions
    assert all(
        d["rule"] == "absence"
        for d in res.decisions
        if d["action"] == "death"
    )
    assert res.epochs[-1]["n_workers"] == 6


def test_controller_collapse_probe_corroborates():
    """A collapsed arrival regime (shift_factor jump) halves the streak
    threshold: a half-streak suspect is promoted at the probe."""
    ctl = MembershipController(3, _ecfg(death_rounds=4, shift_factor=2.0))
    ctl.observe_chunk(0, np.array([[0.1, 0.2, -1.0], [0.1, 0.2, -1.0]]))
    assert not ctl._pending_deaths  # streak 2 < K=4
    # arrival mean jumps 10x -> collapse; streak 4 >= ceil(4/2)=2 anyway,
    # but a FRESH half-streak worker is also promoted
    obs = ctl.observe_chunk(
        2, np.array([[3.0, 2.0, -1.0], [1.5, 2.5, -1.0]])
    )
    assert obs.collapse
    assert 2 in obs.deaths
    assert any(d["action"] == "probe" for d in ctl.decisions)


def test_controller_join_and_min_workers():
    ctl = MembershipController(3, _ecfg(death_rounds=1, min_workers=2))
    # both 1 and 2 silent -> both suspected; the floor keeps one
    ctl.observe_chunk(0, np.array([[0.1, -1.0, -1.0]]))
    change = ctl.commit(1)
    assert change.n_workers_after == 2  # floor held
    assert len(change.dead) == 1
    # the kept suspect stays pending; a join restores headroom and it goes
    dead_w = change.dead[0]
    kept = ({1, 2} - {dead_w}).pop()
    assert ctl.request_join(dead_w, round=2)  # rejoin offer for the dead one
    change2 = ctl.commit(2)
    assert dead_w in change2.joined
    assert kept in change2.dead  # pending suspect finally applied
    # double-join offers are ignored
    assert not ctl.request_join(0)


def test_controller_snapshot_round_trip():
    ctl = MembershipController(4, _ecfg())
    ctl.observe_chunk(0, np.array([[0.1, 0.2, -1.0, 5.0]] * 2))
    ctl.request_join(3) if 3 not in ctl.active else None
    snap = json.loads(json.dumps(ctl.snapshot()))  # through JSON like aux
    back = MembershipController.restore(snap, _ecfg())
    assert back.active == ctl.active
    assert back._streaks == ctl._streaks
    assert back._pending_deaths == ctl._pending_deaths
    assert back.decisions == ctl.decisions


def test_elastic_config_validation():
    with pytest.raises(ValueError, match="death_rounds"):
        ElasticConfig(death_rounds=0)
    with pytest.raises(ValueError, match="finite"):
        ElasticConfig(timeout=np.inf)
    with pytest.raises(ValueError, match="min_workers"):
        ElasticConfig(min_workers=0)


# ---------------------------------------------------------------------------
# targeted straggler attacks (arXiv:1901.08166 — satellite)


def test_targeted_workers_frc_group():
    layout = codes.frc_layout(12, 2)
    assert straggler.targeted_workers(layout, 0) == (0, 1, 2)
    assert straggler.targeted_workers(layout, 4) == (3, 4, 5)  # partition 4


def test_targeted_attack_hurts_frc_more_than_uniform():
    """The 1901.08166 FRC worst case, pinned: slowing ALL replicas of one
    partition group stalls every round (the group's first arrival IS the
    attack), while the same total slowdown budget spread over workers in
    distinct groups leaves every group a fast member."""
    Wt, S, Rt = 12, 2, 30
    layout = codes.frc_layout(Wt, S)
    delays = straggler.reference_delay_schedule(Rt, Wt)
    shift = straggler.RegimeShift(
        kind="targeted", round=10, group=0, slowdown=5.0
    )
    tw = straggler.targeted_workers(layout, 0)
    t_targeted = straggler.apply_regime_shift(delays, shift, workers=tw)
    # equal budget: len(tw) workers x 5 s, one attacked worker per group
    t_uniform = np.array(delays, copy=True)
    t_uniform[10:, [0, 3, 6]] += 5.0
    st = collect.collect_frc(t_targeted, layout.groups)
    su = collect.collect_frc(t_uniform, layout.groups)
    # pre-shift rounds identical; post-shift the targeted attack costs
    # ~slowdown EVERY round, the uniform attack almost nothing
    np.testing.assert_array_equal(st.sim_time[:10], su.sim_time[:10])
    assert st.sim_time[10:].sum() > 2.0 * su.sim_time[10:].sum()
    assert (st.sim_time[10:] >= 5.0).all()


def test_targeted_regime_env_plumbing(tmp_path):
    """ERASUREHEAD_REGIME=targeted:... resolves the attacked set from the
    run's own layout inside trainer.default_arrivals."""
    from erasurehead_tpu.train import trainer

    s = chaos_lib.parse_regime("targeted:10:1:3.5")
    assert (s.kind, s.round, s.group, s.slowdown) == ("targeted", 10, 1, 3.5)
    cfg = _cfg(scheme="repcoded", n_stragglers=1, rounds=12)
    layout = codes.frc_layout(W, 1)
    os.environ["ERASUREHEAD_REGIME"] = "targeted:6:0:3.5"
    try:
        arr = trainer.default_arrivals(cfg)
    finally:
        del os.environ["ERASUREHEAD_REGIME"]
    expect = straggler.apply_regime_shift(
        straggler.reference_delay_schedule(12, W),
        straggler.RegimeShift(
            kind="targeted", round=6, group=0, slowdown=3.5
        ),
        workers=straggler.targeted_workers(layout, 0),
    )
    np.testing.assert_allclose(arr, expect)


def test_targeted_needs_resolved_workers():
    shift = straggler.RegimeShift(kind="targeted", round=0, group=0)
    with pytest.raises(ValueError, match="targeted_workers"):
        straggler.apply_regime_shift(np.zeros((4, 4)), shift)


# ---------------------------------------------------------------------------
# chaos grammar: multi-spec + membership sites (satellite)


def test_chaos_multi_spec_and_membership_grammar():
    specs = chaos_lib.parse_specs(
        "3:worker_death:2,3:worker_revive:6,kill:elastic:4"
    )
    assert [s.site for s in specs] == [
        "worker_death", "worker_revive", "elastic"
    ]
    assert specs[0].mode == "member" and specs[0].worker == 3
    assert specs[2].mode == "kill" and specs[2].worker is None
    with pytest.raises(ValueError, match="worker id"):
        chaos_lib.parse_spec("kill:worker_death:2")
    with pytest.raises(ValueError, match="site"):
        chaos_lib.parse_spec("kill:nonsite:2")


def test_chaos_membership_fires_is_pure():
    os.environ[chaos_lib.CHAOS_ENV] = "5:worker_death:2,1:worker_death:3+"
    try:
        assert chaos_lib.membership_fires("worker_death", 1) == ()
        assert chaos_lib.membership_fires("worker_death", 2) == (5,)
        assert chaos_lib.membership_fires("worker_death", 4) == (1,)  # sticky
        # pure: repeated queries at the same invocation agree
        assert chaos_lib.membership_fires("worker_death", 2) == (5,)
        # counter-based form walks the sequence
        assert chaos_lib.fire_membership("worker_death") == ()
        assert chaos_lib.fire_membership("worker_death") == (5,)
        with pytest.raises(ValueError, match="not one of"):
            chaos_lib.fire_membership("trajectory")
    finally:
        del os.environ[chaos_lib.CHAOS_ENV]


def test_chaos_process_sites_ignore_membership_specs():
    """maybe_fire must never kill/raise on a membership spec, and the
    historical single-spec grammar still parses via active()."""
    os.environ[chaos_lib.CHAOS_ENV] = "3:worker_death:1+"
    try:
        chaos_lib.maybe_fire("worker_death")  # no-op, never raises
        assert chaos_lib.active().mode == "member"
    finally:
        del os.environ[chaos_lib.CHAOS_ENV]


# ---------------------------------------------------------------------------
# the driver: detection -> re-layout -> join, replay, journal


def test_online_death_detection_and_relayout(ds):
    res = elastic.train_elastic_online(
        _cfg(), ds, elastic=_ecfg(), deaths={6: 7, 7: 7}
    )
    deaths = [d for d in res.decisions if d["action"] == "death"]
    assert sorted(d["worker"] for d in deaths) == [6, 7]
    relayouts = [d for d in res.decisions if d["action"] == "relayout"]
    assert len(relayouts) == 1 and relayouts[0]["n_workers"] == 6
    # the re-layout lands at the first chunk boundary after K=3 silent
    # rounds (death at 7 -> streak complete at 9 -> boundary 10)
    assert relayouts[0]["round"] == 10
    hist = np.asarray(res.result.params_history)
    assert hist.shape[0] == R and np.isfinite(hist).all()
    # dead columns carry the -1 sentinel after the re-layout, original ids
    assert (res.result.worker_times[10:, 6:] == -1.0).all()
    assert not res.result.collected[10:, 6:].any()
    # detection rounds were priced at the timeout, survivor rounds are not
    assert (res.result.timeset[7:10] == 4.0).all()
    # loss keeps improving through the whole membership change
    from erasurehead_tpu.models.glm import LogisticModel

    model = LogisticModel()
    losses = [
        float(model.loss_mean(hist[r], ds.X_train, ds.y_train))
        for r in (0, 9, R - 1)
    ]
    assert losses[2] < losses[1] < losses[0]


def test_online_join_scales_back_up(ds):
    res = elastic.train_elastic_online(
        _cfg(rounds=40), ds, elastic=_ecfg(),
        deaths={7: 6}, revives={7: 21},
    )
    widths = [e["n_workers"] for e in res.epochs]
    assert widths == [W, W - 1, W], widths
    joins = [d for d in res.decisions if d["action"] == "join"]
    assert [d["worker"] for d in joins] == [7]
    # the rejoined worker's clocks are real again in the final epoch
    start = res.epochs[-1]["start_round"]
    assert (res.result.worker_times[start:, 7] > -1.0).any()


def test_chaos_driven_membership(ds):
    os.environ[chaos_lib.CHAOS_ENV] = "3:worker_death:2,3:worker_revive:5"
    try:
        res = elastic.train_elastic_online(
            _cfg(rounds=40), ds, elastic=_ecfg()
        )
    finally:
        del os.environ[chaos_lib.CHAOS_ENV]
    widths = [e["n_workers"] for e in res.epochs]
    assert widths == [W, W - 1, W], widths
    assert [d["worker"] for d in res.decisions
            if d["action"] == "death"] == [3]


def test_replay_is_bitwise(ds):
    import jax

    kw = dict(elastic=_ecfg(), deaths={6: 7, 7: 7})
    a = elastic.train_elastic_online(_cfg(), ds, **kw)
    b = elastic.train_elastic_online(_cfg(), ds, **kw)
    assert a.decisions == b.decisions
    assert [elastic.science_fields(r) for r in a.rows] == [
        elastic.science_fields(r) for r in b.rows
    ]
    for x, y in zip(
        jax.tree.leaves(a.result.params_history),
        jax.tree.leaves(b.result.params_history),
    ):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_membership_events_validate(tmp_path, ds):
    events_path = str(tmp_path / "events.jsonl")
    with obs_events.capture(events_path):
        res = elastic.train_elastic_online(
            _cfg(), ds, elastic=_ecfg(), deaths={7: 7},
            journal_dir=str(tmp_path),
        )
    for path in (events_path, res.journal_path):
        errors = obs_events.validate_file(path)
        assert not errors, f"{path}:\n" + "\n".join(errors)
    recs = [
        json.loads(line) for line in open(res.journal_path)
    ]
    actions = [r["action"] for r in recs if r["type"] == "membership"]
    assert "death" in actions and "relayout" in actions
    assert actions.count("chunk") == len(res.rows)
    # report renders the section
    from erasurehead_tpu.obs import report as report_lib

    rendered = report_lib.render([res.journal_path])
    assert "elastic membership:" in rendered


def test_membership_validator_rejects_malformed():
    def rec(seq, **kw):
        base = {"type": "membership", "seq": seq, "t": 0.0}
        base.update(kw)
        return json.dumps(base)

    lines = [
        rec(0, round=0, action="relayout", n_workers=4),  # valid
        rec(1, round=-1, action="death", n_workers=4),  # bad round
        rec(2, round=1, action="resurrect", n_workers=4),  # bad action
        rec(3, round=2, action="join", n_workers=0),  # bad count
        rec(4, round=3, action="chunk", n_workers=4,
            workers=[1, -2]),  # bad worker id list
    ]
    errors = obs_events.validate_lines(lines)
    assert len(errors) == 4
    assert "round" in errors[0]
    assert "action" in errors[1]
    assert "n_workers" in errors[2]
    assert "workers" in errors[3]


def test_resume_rehydrates_rows_bitwise(tmp_path, ds):
    """An interrupted elastic run (here: a shorter first horizon standing
    in for the chaos kill the smoke drives with real process death)
    resumes from checkpoint+aux, REHYDRATES completed rows from the
    journal, and matches the uninterrupted baseline bitwise."""
    base = elastic.train_elastic_online(
        _cfg(rounds=40), ds, elastic=_ecfg(), deaths={6: 7, 7: 7},
    )
    part_dir = str(tmp_path / "part")
    os.makedirs(part_dir)
    # leg 1: same world, stopped at round 20 (checkpoint + journal live)
    elastic.train_elastic_online(
        _cfg(rounds=20), ds, elastic=_ecfg(), deaths={6: 7, 7: 7},
        journal_dir=part_dir, checkpoint_dir=os.path.join(part_dir, "ck"),
    )
    # leg 2: resume to the full horizon
    res = elastic.train_elastic_online(
        _cfg(rounds=40), ds, elastic=_ecfg(), deaths={6: 7, 7: 7},
        journal_dir=part_dir, checkpoint_dir=os.path.join(part_dir, "ck"),
        resume=True,
    )
    assert res.resumed_from == 20
    assert [elastic.science_fields(r) for r in res.rows] == [
        elastic.science_fields(r) for r in base.rows
    ]
    # control-plane arrays cover the FULL horizon on the resumed run
    np.testing.assert_array_equal(
        res.result.timeset, base.result.timeset
    )
    np.testing.assert_array_equal(
        res.result.worker_times, base.result.worker_times
    )
    # resumed history covers [start_round, R) per the trainer convention
    assert res.result.start_round == 20
    hist = np.asarray(res.result.params_history)
    base_hist = np.asarray(base.result.params_history)
    np.testing.assert_array_equal(hist, base_hist[20:])


def test_adapt_composition_reseeds_per_epoch(ds):
    from erasurehead_tpu import adapt

    arms = [adapt.Arm("naive"), adapt.Arm("avoidstragg")]
    res = elastic.train_elastic_online(
        _cfg(n_stragglers=1, compute_mode="deduped"), ds,
        elastic=_ecfg(), deaths={7: 7}, adapt_arms=arms,
    )
    assert res.arm_decisions, "bandit never chose an arm"
    epochs_seen = {d["epoch"] for d in res.arm_decisions}
    assert epochs_seen == {0, 1}
    # the epoch-1 bandit restarted its warmup: fresh values per layout
    first_epoch1 = next(
        d for d in res.arm_decisions if d["epoch"] == 1
    )
    assert first_epoch1["reason"] in ("warmup", "regime_shift")
    # replay invariance holds with the bandit composed
    res2 = elastic.train_elastic_online(
        _cfg(n_stragglers=1, compute_mode="deduped"), ds,
        elastic=_ecfg(), deaths={7: 7}, adapt_arms=arms,
    )
    assert res.arm_decisions == res2.arm_decisions


def test_driver_refuses_partial_and_measured(ds):
    with pytest.raises(ValueError, match="partial"):
        elastic.train_elastic_online(
            _cfg(scheme="partialrepcoded", n_stragglers=1,
                 partitions_per_worker=4),
            ds, elastic=_ecfg(),
        )
    with pytest.raises(ValueError, match="measured"):
        elastic.train_elastic_online(
            _cfg(arrival_mode="measured"), ds, elastic=_ecfg()
        )
    with pytest.raises(ValueError, match="checkpoint_dir"):
        elastic.train_elastic_online(
            _cfg(), ds, elastic=_ecfg(), resume=True
        )


def test_auto_survivor_config_shrinks_stragglers(ds):
    """repcoded at W'=5 violates (s+1)|W' for s=1; the online controller
    auto-shrinks to the largest valid s instead of dying mid-run."""
    cfg = _cfg(scheme="repcoded", n_stragglers=1)
    shrunk = elastic.auto_survivor_config(cfg, 5)
    assert shrunk.n_workers == 5 and shrunk.n_stragglers == 0
    # an EXPLICIT override is honored as-is — including its failure
    with pytest.raises(ValueError, match="survivor_overrides"):
        elastic.auto_survivor_config(
            cfg, 5, survivor_overrides={"n_stragglers": 1}
        )
