"""CLI integration at the reference's canonical scale (W=30).

Small-W toys hide conditioning/scale bugs (the fp32-decode issue class),
so this drives the real entry point at 30 workers end-to-end: train ->
eval replay -> the five reference artifacts on disk. Deduped compute mode
keeps it fast on the CPU mesh.
"""

import os

import numpy as np
import pytest

from erasurehead_tpu import cli

W = 30


@pytest.mark.parametrize(
    "scheme,extra",
    [
        ("approx", ["--num-collect", "15"]),
        ("cyccoded", []),
        ("randreg", ["--num-collect", "20"]),
    ],
)
def test_cli_canonical_scale(tmp_path, scheme, extra):
    data_dir = str(tmp_path / "data")
    rc = cli.main(
        [
            "--scheme", scheme, "--workers", str(W), "--stragglers", "2",
            "--rounds", "5", "--rows", str(60 * W), "--cols", "24",
            "--update-rule", "AGD", "--lr", "1.0", "--add-delay",
            "--compute-mode", "deduped", "--input-dir", data_dir, "--quiet",
        ]
        + extra
    )
    assert rc == 0
    results = os.path.join(
        data_dir, "artificial-data", f"{60 * W}x24", str(W), "results"
    )
    files = os.listdir(results)
    for kind in (
        "training_loss", "testing_loss", "auc", "timeset", "worker_timeset"
    ):
        assert any(kind in f for f in files), (kind, files)
    # the loss curve is finite and decreasing overall
    loss_file = next(f for f in files if "training_loss" in f)
    losses = np.loadtxt(os.path.join(results, loss_file))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_cli_legacy_13_args(tmp_path):
    """The reference's exact positional calling convention (main.py:20-27):
    n_procs n_rows n_cols input_dir is_real dataset is_coded n_stragglers
    partitions coded_ver num_collect add_delay update_rule."""
    data_dir = str(tmp_path / "legacy")
    rc = cli.main(
        [
            "31", "1860", "16", data_dir, "0", "artificial", "1", "2",
            "0", "3", "15", "1", "AGD",
        ]
    )
    assert rc == 0
