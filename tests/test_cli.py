"""CLI integration at the reference's canonical scale (W=30).

Small-W toys hide conditioning/scale bugs (the fp32-decode issue class),
so this drives the real entry point at 30 workers end-to-end: train ->
eval replay -> the five reference artifacts on disk. Deduped compute mode
keeps it fast on the CPU mesh.
"""

import json
import os

import numpy as np
import pytest

from erasurehead_tpu import cli

W = 30


@pytest.mark.parametrize(
    "scheme,extra",
    [
        ("approx", ["--num-collect", "15"]),
        ("cyccoded", []),
        ("randreg", ["--num-collect", "20"]),
    ],
)
def test_cli_canonical_scale(tmp_path, scheme, extra):
    data_dir = str(tmp_path / "data")
    rc = cli.main(
        [
            "--scheme", scheme, "--workers", str(W), "--stragglers", "2",
            "--rounds", "5", "--rows", str(60 * W), "--cols", "24",
            "--update-rule", "AGD", "--lr", "1.0", "--add-delay",
            "--compute-mode", "deduped", "--input-dir", data_dir, "--quiet",
        ]
        + extra
    )
    assert rc == 0
    results = os.path.join(
        data_dir, "artificial-data", f"{60 * W}x24", str(W), "results"
    )
    files = os.listdir(results)
    for kind in (
        "training_loss", "testing_loss", "auc", "timeset", "worker_timeset"
    ):
        assert any(kind in f for f in files), (kind, files)
    # the loss curve is finite and decreasing overall
    loss_file = next(f for f in files if "training_loss" in f)
    losses = np.loadtxt(os.path.join(results, loss_file))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_cli_stack_mode_ring(tmp_path):
    """--stack-mode ring drives the full entry point at W=30: faithful
    science from the partition-major stack + ring transport, artifacts on
    disk, loss decreasing — the CLI face of tests/test_ring_stack.py."""
    data_dir = str(tmp_path / "data")
    rc = cli.main(
        [
            "--scheme", "approx", "--workers", str(W), "--stragglers", "2",
            "--num-collect", "15", "--rounds", "5", "--rows", str(60 * W),
            "--cols", "24", "--update-rule", "AGD", "--lr", "1.0",
            "--add-delay", "--stack-mode", "ring", "--input-dir", data_dir,
            "--quiet",
        ]
    )
    assert rc == 0
    results = os.path.join(
        data_dir, "artificial-data", f"{60 * W}x24", str(W), "results"
    )
    files = os.listdir(results)
    loss_file = next(f for f in files if "training_loss" in f)
    losses = np.loadtxt(os.path.join(results, loss_file))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_cli_legacy_13_args(tmp_path):
    """The reference's exact positional calling convention (main.py:20-27):
    n_procs n_rows n_cols input_dir is_real dataset is_coded n_stragglers
    partitions coded_ver num_collect add_delay update_rule."""
    data_dir = str(tmp_path / "legacy")
    rc = cli.main(
        [
            "31", "1860", "16", data_dir, "0", "artificial", "1", "2",
            "0", "3", "15", "1", "AGD",
        ]
    )
    assert rc == 0


def test_cli_checkpoint_resume(tmp_path):
    """Interrupt-and-resume through the CLI: a checkpointed run stopped at
    round 6 (latest checkpoint at round 3 — the completed run's final state
    is deliberately not checkpointed), resumed to 10, must produce
    artifacts covering the resumed window [3, 10) whose loss values equal
    the corresponding tail of an uninterrupted 10-round run (the
    control-plane clocks are deterministic, so the resumed trajectory is
    aligned)."""
    data_dir = str(tmp_path / "data")
    ck = str(tmp_path / "ck")
    base = [
        "--scheme", "approx", "--workers", "6", "--stragglers", "1",
        "--num-collect", "4", "--rows", "240", "--cols", "16",
        "--update-rule", "AGD", "--lr", "1.0", "--add-delay",
        "--input-dir", data_dir, "--quiet",
    ]
    # full uninterrupted run -> reference loss curve
    assert cli.main(base + ["--rounds", "10"]) == 0
    results = os.path.join(data_dir, "artificial-data", "240x16", "6", "results")
    loss_file = next(
        f for f in os.listdir(results) if "training_loss" in f
    )
    full = np.loadtxt(os.path.join(results, loss_file))
    # checkpointed run stopped at 6 rounds, then resumed to 10
    assert cli.main(
        base + ["--rounds", "6", "--checkpoint-dir", ck,
                "--checkpoint-every", "3"]
    ) == 0
    assert cli.main(
        base + ["--rounds", "10", "--checkpoint-dir", ck,
                "--checkpoint-every", "3", "--resume"]
    ) == 0
    resumed = np.loadtxt(os.path.join(results, loss_file))
    # resumed artifacts cover [3, 10): 7 rows matching the full run's tail
    assert resumed.shape[0] == 7
    assert np.allclose(resumed, full[3:], atol=1e-6)


@pytest.mark.parametrize(
    "argv,msg",
    [
        (["--resume"], "--resume requires"),
        (["--checkpoint-dir", "ck"], "--checkpoint-every"),
        (["--checkpoint-dir", "ck", "--checkpoint-every", "0"], ">= 1"),
        (["--checkpoint-dir", "ck", "--checkpoint-every", "2",
          "--arrival-mode", "measured"], None),
    ],
)
def test_cli_checkpoint_flag_validation(capsys, argv, msg):
    """Interdependent checkpoint flags fail fast as argparse errors (exit
    code 2) before any backend init or dataset load."""
    with pytest.raises(SystemExit) as e:
        cli.main(["--scheme", "naive", "--rows", "64", "--cols", "8"] + argv)
    assert e.value.code == 2
    if msg:
        assert msg in capsys.readouterr().err


def test_cli_checkpoint_every_requires_dir(capsys):
    with pytest.raises(SystemExit) as e:
        cli.main(["--scheme", "naive", "--rows", "64", "--cols", "8",
                  "--checkpoint-every", "2"])
    assert e.value.code == 2
    assert "--checkpoint-dir" in capsys.readouterr().err


def test_cli_kill_workers_elastic(tmp_path):
    """Fault injection through the CLI: two workers die, elastic recovery
    re-shards onto the survivors, and the artifacts cover every round."""
    data_dir = str(tmp_path / "data")
    rc = cli.main([
        "--scheme", "approx", "--workers", "8", "--stragglers", "1",
        "--num-collect", "6", "--rounds", "12", "--rows", "384",
        "--cols", "16", "--lr", "1.0", "--add-delay",
        "--kill-workers", "6:5,7:5", "--on-death", "elastic",
        "--input-dir", data_dir, "--quiet",
    ])
    assert rc == 0
    results = os.path.join(data_dir, "artificial-data", "384x16", "8", "results")
    loss_file = next(f for f in os.listdir(results) if "training_loss" in f)
    losses = np.loadtxt(os.path.join(results, loss_file))
    assert losses.shape[0] == 12 and np.isfinite(losses).all()
    wt_file = next(f for f in os.listdir(results) if "worker_timeset" in f)
    wt = np.loadtxt(os.path.join(results, wt_file))
    assert wt.shape == (12, 8)
    assert (wt[5:, 6:] == -1.0).all()  # dead columns carry the -1 sentinel


def test_cli_kill_workers_failover(tmp_path):
    """Failover mode degrades the infeasible rounds' decode instead of
    resharding; requires a finite --death-timeout."""
    data_dir = str(tmp_path / "data")
    rc = cli.main([
        "--scheme", "avoidstragg", "--workers", "6", "--stragglers", "1",
        "--rounds", "8", "--rows", "240", "--cols", "12", "--lr", "1.0",
        "--add-delay", "--kill-workers", "4:2,5:2", "--on-death", "failover",
        "--death-timeout", "10.0", "--input-dir", data_dir, "--quiet",
    ])
    assert rc == 0


def test_cli_kill_workers_error_mode_raises(tmp_path):
    """Default on-death=error raises where the reference's master would
    block in Waitany forever (naive needs all workers)."""
    from erasurehead_tpu.parallel.failures import InfeasibleRunError

    with pytest.raises(InfeasibleRunError):
        cli.main([
            "--scheme", "naive", "--workers", "4", "--rounds", "6",
            "--rows", "64", "--cols", "8", "--lr", "1.0", "--add-delay",
            "--kill-workers", "3:2",
            "--input-dir", str(tmp_path / "d"), "--quiet",
        ])


def test_cli_kill_workers_validation():
    from erasurehead_tpu.utils.config import RunConfig

    with pytest.raises(ValueError, match="death-timeout"):
        cli.run(
            RunConfig(scheme="naive", n_workers=4, rounds=4, n_rows=64,
                      n_cols=8, lr_schedule=1.0),
            kill_workers="1:2", on_death="failover", quiet=True,
        )
    with pytest.raises(ValueError, match="worker:round"):
        cli._parse_deaths("1-2")


def test_cli_kill_workers_more_validation():
    from erasurehead_tpu.utils.config import RunConfig

    base = RunConfig(scheme="naive", n_workers=4, rounds=4, n_rows=64,
                     n_cols=8, lr_schedule=1.0)
    with pytest.raises(ValueError, match="twice"):
        cli._parse_deaths("6:10,6:3")
    with pytest.raises(ValueError, match="requires kill_workers"):
        cli.run(base, on_death="elastic", quiet=True)
    with pytest.raises(ValueError, match="only applies"):
        cli.run(base, kill_workers="1:2", death_timeout=5.0, quiet=True)
    with pytest.raises(ValueError, match="outside"):
        cli.run(base, kill_workers="9:2", quiet=True)


def test_cli_elastic_online(tmp_path):
    """--elastic on: online membership through the CLI — two scripted
    deaths are DETECTED from telemetry and the run re-layouts, with the
    membership journal landing beside the events log under telemetry."""
    data_dir = str(tmp_path / "d")
    rc = cli.main([
        "--scheme", "naive", "--workers", "8", "--stragglers", "0",
        "--rounds", "18", "--rows", "256", "--cols", "8", "--lr", "1.0",
        "--add-delay", "--kill-workers", "6:4,7:4", "--elastic", "on",
        "--elastic-chunk", "6", "--death-rounds", "2",
        "--death-timeout", "4.0", "--telemetry", "on",
        "--input-dir", data_dir,
        "--output-dir", str(tmp_path / "out"), "--quiet",
    ])
    assert rc == 0
    journal = tmp_path / "out" / "elastic_journal.jsonl"
    assert journal.exists()
    from erasurehead_tpu.obs import events as events_lib

    assert not events_lib.validate_file(str(journal))
    recs = [json.loads(line) for line in open(journal)]
    assert any(r.get("action") == "relayout" for r in recs)


def test_cli_elastic_flag_validation():
    parser = cli._flags_parser()
    base = [
        "--scheme", "naive", "--workers", "4", "--rounds", "4",
        "--rows", "64", "--cols", "8",
    ]
    for extra, msg in (
        (["--elastic", "on", "--adapt", "on"], "adapt"),
        (["--elastic", "on", "--on-death", "failover",
          "--kill-workers", "1:2", "--death-timeout", "2.0"], "on-death"),
        (["--elastic", "on", "--checkpoint-dir", "/tmp/x",
          "--checkpoint-every", "2"], "checkpoint"),
        (["--elastic-chunk", "0"], "elastic-chunk"),
        (["--death-rounds", "0"], "death-rounds"),
        (["--death-timeout", "2.0"], "death-timeout"),
    ):
        ns = parser.parse_args(base + extra)
        with pytest.raises(SystemExit):
            cli._validate_checkpoint_flags(parser, ns)


def test_cli_dense_margin_cols_validation():
    """The margin-cols lowering knob validates through RunConfig (shared
    rule: features.validate_margin_cols) for both config and CLI values."""
    from erasurehead_tpu.utils.config import RunConfig

    for bad in (1, 0, 256, -8):
        with pytest.raises(ValueError, match="margin cols"):
            RunConfig(scheme="naive", n_workers=4, rounds=2, n_rows=64,
                      n_cols=8, lr_schedule=1.0, dense_margin_cols=bad)
    cfg = RunConfig(scheme="naive", n_workers=4, rounds=2, n_rows=64,
                    n_cols=8, lr_schedule=1.0, dense_margin_cols="8")
    assert cfg.dense_margin_cols == 8  # normalized to int


def _telemetry_base(data_dir, workers=4):
    return [
        "--scheme", "approx", "--workers", str(workers), "--stragglers",
        "1", "--num-collect", "3", "--rounds", "3", "--rows",
        str(60 * workers), "--cols", "8", "--lr", "1.0", "--add-delay",
        "--compute-mode", "deduped", "--input-dir", data_dir, "--quiet",
    ]


def test_cli_telemetry_on_writes_and_validates_events(tmp_path):
    """--telemetry on: events.jsonl lands beside the artifacts, passes the
    schema validator, and carries the run bracket + the CLI's eval record;
    `erasurehead-tpu report` renders it."""
    from erasurehead_tpu.obs import events as events_lib

    out_dir = str(tmp_path / "out")
    rc = cli.main(
        _telemetry_base(str(tmp_path / "data"))
        + ["--telemetry", "on", "--output-dir", out_dir]
    )
    assert rc == 0
    path = os.path.join(out_dir, "events.jsonl")
    assert os.path.exists(path)
    assert events_lib.validate_file(path) == []
    import json

    types = [
        json.loads(line)["type"] for line in open(path) if line.strip()
    ]
    for required in ("run_start", "compile", "rounds", "decode", "eval",
                     "run_end"):
        assert required in types, (required, types)
    assert cli.main(["report", path]) == 0


def test_cli_telemetry_auto_follows_output_dir(tmp_path, monkeypatch):
    """auto = on exactly when --output-dir was given (and the env var
    fills in when the flag is absent — the --sweep-cache precedence)."""
    monkeypatch.delenv("ERASUREHEAD_TELEMETRY", raising=False)
    out_dir = str(tmp_path / "out")
    rc = cli.main(
        _telemetry_base(str(tmp_path / "d1"))
        + ["--telemetry", "auto", "--output-dir", out_dir]
    )
    assert rc == 0
    assert os.path.exists(os.path.join(out_dir, "events.jsonl"))

    # auto WITHOUT an explicit output dir: off — no events.jsonl anywhere
    data_dir = str(tmp_path / "d2")
    rc = cli.main(_telemetry_base(data_dir) + ["--telemetry", "auto"])
    assert rc == 0
    results = os.path.join(
        data_dir, "artificial-data", "240x8", "4", "results"
    )
    assert os.path.isdir(results)
    assert "events.jsonl" not in os.listdir(results)


def test_cli_telemetry_env_resolution(tmp_path, monkeypatch):
    """ERASUREHEAD_TELEMETRY=on enables the log with no flag; an explicit
    --telemetry off beats the env."""
    monkeypatch.setenv("ERASUREHEAD_TELEMETRY", "on")
    data_dir = str(tmp_path / "d1")
    rc = cli.main(_telemetry_base(data_dir))
    assert rc == 0
    results = os.path.join(
        data_dir, "artificial-data", "240x8", "4", "results"
    )
    assert "events.jsonl" in os.listdir(results)

    data_dir = str(tmp_path / "d2")
    rc = cli.main(_telemetry_base(data_dir) + ["--telemetry", "off"])
    assert rc == 0
    results = os.path.join(
        data_dir, "artificial-data", "240x8", "4", "results"
    )
    assert "events.jsonl" not in os.listdir(results)


def test_cli_deadline_scheme_artifacts(tmp_path):
    """scheme=deadline end to end through the CLI: artifacts carry the
    scheme's own prefix (regression: run_prefix lacked the new scheme)."""
    data_dir = str(tmp_path / "data")
    rc = cli.main([
        "--scheme", "deadline", "--deadline", "1.0", "--workers", "6",
        "--rounds", "6", "--rows", "240", "--cols", "12", "--lr", "1.0",
        "--add-delay", "--input-dir", data_dir, "--quiet",
    ])
    assert rc == 0
    results = os.path.join(data_dir, "artificial-data", "240x12", "6", "results")
    files = os.listdir(results)
    assert any(f.startswith("deadline_acc") for f in files), files
    ts = np.loadtxt(os.path.join(
        results, next(f for f in files if "timeset" in f and "worker" not in f)
    ))
    assert (ts <= 1.0 + 1e-9).all()
