"""Native text parser (data/native) vs np.loadtxt, value-for-value."""

import numpy as np
import pytest

from erasurehead_tpu.data import native


@pytest.fixture(scope="module")
def lib_available():
    if native.get_lib() is None:
        pytest.skip("no C++ toolchain; np.loadtxt fallback covers this")


def _roundtrip(tmp_path, m, fmt="%.18g"):
    p = str(tmp_path / "m.dat")
    np.savetxt(p, np.atleast_2d(m), fmt=fmt)
    want = np.loadtxt(p, dtype=np.float64)
    got = native.load_dense_text_native(p)
    assert got is not None
    assert got.shape == want.shape
    np.testing.assert_array_equal(got, want)  # bitwise: same strtod grammar


def test_matrix_roundtrip(tmp_path, lib_available):
    rng = np.random.default_rng(0)
    _roundtrip(tmp_path, rng.standard_normal((37, 11)) * 10.0 ** rng.integers(-30, 30, (37, 11)))


def test_label_vector_roundtrip(tmp_path, lib_available):
    _roundtrip(tmp_path, np.asarray([1.0, -1.0, -1.0, 1.0]))


def test_single_row_squeeze(tmp_path, lib_available):
    _roundtrip(tmp_path, np.asarray([[1.5, 2.5, 3.5]]))


def test_special_values(tmp_path, lib_available):
    _roundtrip(tmp_path, np.asarray([[np.inf, -np.inf], [1e-300, 1e300]]))


def test_reference_save_format(tmp_path, lib_available):
    """The %5.3f style the reference writes (src/util.py:32-36)."""
    _roundtrip(tmp_path, np.asarray([[0.123456, -7.5], [42.0, 0.001]]), fmt="%5.3f")


def test_ragged_file_falls_back(tmp_path, lib_available):
    p = str(tmp_path / "ragged.dat")
    with open(p, "w") as f:
        f.write("1 2 3\n4 5\n")
    assert native.load_dense_text_native(p) is None


def test_non_numeric_falls_back(tmp_path, lib_available):
    p = str(tmp_path / "bad.dat")
    with open(p, "w") as f:
        f.write("1 2\nfoo 4\n")
    assert native.load_dense_text_native(p) is None


def test_missing_file_returns_none(tmp_path, lib_available):
    assert native.load_dense_text_native(str(tmp_path / "nope.dat")) is None


def test_io_integration(tmp_path, lib_available):
    """load_dense_text routes through the native parser on cold load and
    the .npy sidecar afterwards; all three agree."""
    from erasurehead_tpu.data import io as data_io

    rng = np.random.default_rng(1)
    m = rng.standard_normal((23, 7))
    p = str(tmp_path / "x.dat")
    data_io.save_dense_text(p, m)
    cold = data_io.load_dense_text(p)
    warm = data_io.load_dense_text(p)  # .npy sidecar
    np.testing.assert_allclose(cold, m, rtol=0, atol=0)
    np.testing.assert_array_equal(cold, warm)


def test_1x1_scalar_squeeze(tmp_path, lib_available):
    """np.loadtxt returns a 0-d array for a 1x1 file; so must we."""
    _roundtrip(tmp_path, np.asarray([[3.25]]))
