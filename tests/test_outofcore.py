"""Out-of-core streamed partition stacks (ISSUE 15).

Pins the contract the ``stack_residency`` tentpole rests on:

- shard-store round trips (f32 bitwise; int8 write-time quantization
  identical to the resident quantizer) and journal/cache key parity
  between a store-rehydrated dataset and its in-memory source;
- streamed single-window trajectories BITWISE identical to resident
  across the f32/int8 x exact(repcoded)/AGC(approx) x ring on/off
  matrix;
- the multi-window block trainer: deterministic run-to-run, prefetch
  telemetry present, refusals loud (faithful, checkpointing, cohorts);
- admission estimates: streamed runs charged their double-buffered
  window, and the int8 worker-stack estimate counts the per-partition
  scale tables (the satellite bugfix), pinned against the REAL sharded
  stack's device bytes and the compiled memory_analysis;
- serve packing: residency rides the static signature / payload
  allowlist, and streamed requests never pack into a resident cohort;
- data/io.py mmap warm loads bitwise-identical to eager loads.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

from erasurehead_tpu.data import io as data_io
from erasurehead_tpu.data import sharding
from erasurehead_tpu.data import store as store_lib
from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.ops.features import QuantizedStack
from erasurehead_tpu.train import cache as cache_lib
from erasurehead_tpu.train import journal as journal_lib
from erasurehead_tpu.train import trainer
from erasurehead_tpu.utils.config import RunConfig

W = 4
P = 4  # every scheme below lays out 4 partitions at W=4
ROWS = P * 32
COLS = 8


def _cfg(**kw):
    base = dict(
        scheme="repcoded", n_workers=W, n_stragglers=1,
        partitions_per_worker=2, rounds=2, n_rows=ROWS, n_cols=COLS,
        lr_schedule=0.5, update_rule="GD", add_delay=True, seed=0,
    )
    base.update(kw)
    # kw=None drops the key back to the RunConfig default
    return RunConfig(**{k: v for k, v in base.items() if v is not None})


def _gmm():
    return generate_gmm(ROWS, COLS, n_partitions=P, seed=0)


@pytest.fixture()
def gmm():
    return _gmm()


def _bitwise(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


# ---------------------------------------------------------------------------
# shard store round trips


def test_store_roundtrip_f32(gmm, tmp_path):
    st = store_lib.write_store(gmm, str(tmp_path / "s"), P)
    rows = ROWS // P
    assert st.n_partitions == P and st.rows_per_partition == rows
    X, y = st.read_window(0, P)
    assert np.array_equal(X.reshape(ROWS, -1), gmm.X_train)
    assert np.array_equal(y.reshape(ROWS), np.asarray(gmm.y_train))
    # a sub-window straddling shard boundaries reads the same rows
    Xw, yw = st.read_window(1, 3)
    assert np.array_equal(Xw, X[1:3]) and np.array_equal(yw, y[1:3])
    # identity: reopening keys exactly like the in-memory source
    st2 = store_lib.open_store(str(tmp_path / "s"))
    assert st2.digest == st.digest == journal_lib.dataset_digest(gmm)
    assert st2.cache_token == ("shard-store", st.digest, "float32")
    ds = st2.dataset()
    assert np.array_equal(ds.X_train, gmm.X_train)
    assert journal_lib.dataset_digest(ds) == journal_lib.dataset_digest(gmm)
    assert cache_lib.dataset_token(ds) == st2.cache_token
    lab = np.dtype(st.meta["label_dtype"]).itemsize
    src = np.dtype(st.meta["source_dtype"]).itemsize
    assert st.partition_bytes() == rows * COLS * src + rows * lab


def test_store_roundtrip_int8(gmm, tmp_path):
    st = store_lib.write_store(gmm, str(tmp_path / "q"), P,
                               stack_dtype="int8")
    rows = ROWS // P
    qs, y = st.read_window(0, P)
    assert isinstance(qs, QuantizedStack)
    # write-time quantization IS the resident quantizer, partition-local
    ref = QuantizedStack.quantize(
        np.ascontiguousarray(
            np.asarray(gmm.X_train).reshape(P, rows, COLS)
        )
    )
    assert np.array_equal(np.asarray(qs.q), np.asarray(ref.q))
    assert np.array_equal(np.asarray(qs.scale), np.asarray(ref.scale))
    ds = st.dataset()
    pre = getattr(ds, "_store_prequantized", None)
    assert pre is not None  # shard_run_data reuses the stored tables
    assert np.array_equal(np.asarray(pre.q), np.asarray(ref.q))
    lab = np.dtype(st.meta["label_dtype"]).itemsize
    assert st.partition_bytes() == rows * COLS + COLS * 4 + rows * lab


def test_store_refusals(gmm, tmp_path):
    with pytest.raises(ValueError, match="stack_dtype"):
        store_lib.write_store(gmm, str(tmp_path / "x"), P,
                              stack_dtype="int4")
    with pytest.raises(ValueError, match="cannot fill"):
        store_lib.write_store(gmm, str(tmp_path / "x"), ROWS + 1)
    with pytest.raises(FileNotFoundError, match="shard store"):
        store_lib.open_store(str(tmp_path / "nope"))
    # a quantized store refuses to feed a run that would silently train
    # on the lossy dequantized reconstruction
    st = store_lib.write_store(gmm, str(tmp_path / "q"), P,
                               stack_dtype="int8")
    ds = st.dataset()
    with pytest.raises(ValueError, match="quantized"):
        trainer.train(_cfg(stack_residency="streamed"), ds)


# ---------------------------------------------------------------------------
# streamed single-window == resident, bitwise


@pytest.mark.parametrize("ring", [False, True], ids=["noring", "ring"])
@pytest.mark.parametrize("scheme,extra", [
    ("repcoded", {}),
    ("approx", {"num_collect": 2}),
], ids=["exact", "agc"])
@pytest.mark.parametrize("stack_dtype", ["float32", "int8"])
def test_streamed_single_window_bitwise(stack_dtype, scheme, extra, ring):
    cfg = _cfg(scheme=scheme, stack_dtype=stack_dtype, **extra)
    if ring:
        cfg = dataclasses.replace(cfg, stack_mode="ring")
    r = trainer.train(cfg, _gmm())
    s = trainer.train(
        dataclasses.replace(cfg, stack_residency="streamed"), _gmm()
    )
    assert r.cache_info["residency"] == "resident"
    assert s.cache_info["residency"] == "streamed"
    assert _bitwise(r.params_history, s.params_history)
    assert _bitwise(r.final_params, s.final_params)


# ---------------------------------------------------------------------------
# the multi-window block trainer


def test_streamed_multi_window_deterministic(gmm):
    cfg = _cfg(compute_mode="deduped", rounds=4,
               stack_residency="streamed", stream_window=1)
    a = trainer.train(cfg, gmm)
    ci = a.cache_info
    assert ci["residency"] == "streamed"
    assert ci["stream_window"] == 1 and ci["n_windows"] == P
    pf = ci["prefetch"]
    assert pf["windows"] >= P and pf["bytes"] > 0
    assert 0.0 <= pf["overlap_efficiency"] <= 1.0
    b = trainer.train(cfg, _gmm())
    assert _bitwise(a.params_history, b.params_history)
    assert _bitwise(a.final_params, b.final_params)


def test_streamed_multi_window_refusals(gmm, tmp_path):
    multi = _cfg(compute_mode="deduped", stack_residency="streamed",
                 stream_window=1)
    # faithful mode needs the whole worker stack resident
    with pytest.raises(ValueError, match="faithful"):
        trainer.train(_cfg(stack_residency="streamed", stream_window=1),
                      gmm)
    # checkpointing composes with resident scan chunks only
    with pytest.raises(ValueError, match="checkpoint"):
        trainer.train(multi, gmm, checkpoint_dir=str(tmp_path / "ck"),
                      checkpoint_every=1)
    # cohorts share ONE resident stack
    assert not trainer.cohort_eligible(multi)
    assert trainer.cohort_signature(multi) is None
    with pytest.raises(ValueError, match="resident"):
        trainer.train_cohort([multi], gmm)


# ---------------------------------------------------------------------------
# admission estimates (incl. the satellite-6 int8 scale-table fix)


def test_estimate_charges_streamed_window(gmm):
    ded = _cfg(compute_mode="deduped")
    res = trainer.estimate_stack_bytes(ded, gmm)
    win = trainer.estimate_stack_bytes(
        dataclasses.replace(ded, stack_residency="streamed",
                            stream_window=1), gmm
    )
    # charged two windows (compute + prefetch double buffer) of four
    assert win == res // 2
    # a window covering the whole stack charges exactly the resident run
    full = trainer.estimate_stack_bytes(
        dataclasses.replace(ded, stack_residency="streamed",
                            stream_window=P), gmm
    )
    assert full == res


def test_worker_stack_estimate_counts_int8_scales(gmm):
    cfg = _cfg(scheme="cyccoded", partitions_per_worker=None,
               compute_mode="faithful", stack_dtype="int8")
    layout = trainer.build_layout(cfg)
    est = sharding.estimate_worker_stack_bytes(gmm, layout, np.int8)
    rows = gmm.n_samples // layout.n_partitions
    Wl, S = layout.n_workers, layout.n_slots
    # payload + one f32 scale row per slot block — the satellite bugfix
    assert est == Wl * S * rows * COLS + Wl * S * COLS * 4
    # pinned against the REAL sharded stack's device bytes — estimate
    # and accounting agree exactly, so an admission decision made from
    # the host-side arithmetic matches what the dispatch will pin
    mesh = trainer._auto_mesh(layout.n_workers)
    sd = sharding.shard_run_data(gmm, layout, mesh, faithful=True,
                                 quantize=True)
    assert est == cache_lib.device_nbytes(sd.Xw)
    # the run's stack telemetry (stack + labels) can only be larger
    r = trainer.train(cfg, gmm)
    assert int(r.cache_info["stack_bytes"]) >= est


# ---------------------------------------------------------------------------
# serve: residency in the payload allowlist, never packed across


def test_streamed_never_packs_with_resident(gmm):
    from erasurehead_tpu.serve import packer as packer_lib
    from erasurehead_tpu.serve import queue as serve_queue

    assert "stack_residency" in serve_queue.CONFIG_PAYLOAD_FIELDS
    assert "stream_window" in serve_queue.CONFIG_PAYLOAD_FIELDS
    ded = _cfg(compute_mode="deduped")
    streamed = dataclasses.replace(
        ded, stack_residency="streamed", stream_window=1
    )
    # residency rides the static signature...
    assert ded.static_signature() != streamed.static_signature()
    # ...and a multi-window streamed request is a sequential singleton
    reqs = [
        serve_queue.RunRequest(tenant="a", label="r", config=ded,
                               dataset=gmm),
        serve_queue.RunRequest(tenant="b", label="s", config=streamed,
                               dataset=gmm),
        serve_queue.RunRequest(tenant="c", label="r2", config=ded,
                               dataset=gmm),
    ]
    assert packer_lib.pack_key(reqs[1]) is None
    cohorts = packer_lib.plan_packs(reqs)
    by_label = {
        tuple(sorted(r.label for r in c.requests)) for c in cohorts
    }
    assert ("r", "r2") in by_label and ("s",) in by_label


def test_residency_round_trips_the_serve_payload(gmm):
    from erasurehead_tpu.serve import queue as serve_queue

    streamed = _cfg(compute_mode="deduped", stack_residency="streamed",
                    stream_window=2)
    payload = serve_queue.config_payload(streamed)
    assert payload["stack_residency"] == "streamed"
    assert payload["stream_window"] == 2
    back = serve_queue.config_from_payload(payload)
    assert back.stack_residency == "streamed"
    assert back.stream_window == 2


# ---------------------------------------------------------------------------
# data/io.py mmap warm loads


def test_mmap_load_bitwise_identical(tmp_path):
    rng = np.random.default_rng(0)
    m = rng.normal(size=(16, 5))
    path = str(tmp_path / "mat.txt")
    data_io.save_dense_text(path, m)
    cold = data_io.load_dense_text(path)  # builds the .npy sidecar
    warm_mmap = data_io.load_dense_text(path, mmap=True)
    warm_eager = data_io.load_dense_text(path, mmap=False)
    assert isinstance(warm_mmap, np.memmap)
    assert not isinstance(warm_eager, np.memmap)
    assert np.array_equal(np.asarray(warm_mmap), warm_eager)
    assert np.array_equal(np.asarray(cold), warm_eager)
