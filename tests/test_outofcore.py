"""Out-of-core streamed partition stacks (ISSUE 15 + the ISSUE 17
composition tentpole).

Pins the contract the ``stack_residency`` tentpole rests on:

- shard-store round trips (f32 bitwise; int8 write-time quantization
  identical to the resident quantizer) and journal/cache key parity
  between a store-rehydrated dataset and its in-memory source;
- streamed single-window trajectories BITWISE identical to resident
  across the f32/int8 x exact(repcoded)/AGC(approx) x ring on/off
  matrix;
- the multi-window block trainer: deterministic run-to-run, prefetch
  telemetry present, refusals loud — and NARROW (ISSUE 17): only the
  knobs with genuinely no windowed body refuse (forced pallas, forced
  blockwise decode, model-parallel meshes, non-window-uniform
  assignments), each naming the remedy knob the caller actually used;
- composed streaming (ISSUE 17): full-cover streamed+ring BITWISE
  identical to resident+ring (f32 and int8), windowed faithful/ring
  runs carry their assignment-aware window plan (halo, slot-group,
  ring-hop ranges) through cache_info and the typed prefetch events,
  and a streamed COHORT's per-trajectory rows match the sequential
  streamed runs (full-cover cohort: bitwise vs the resident cohort);
- the wedged ``Prefetcher.close`` regression: a hung stage can no
  longer spin the drain loop forever — close() observes its deadline
  and reports the leaked thread (counter + typed warning event);
- kill→resume: ``ERASUREHEAD_CHAOS=kill:prefetch:N`` mid-cohort dies
  with KILL_EXIT and the resumed journaled sweep reproduces the
  baseline rows;
- admission estimates: streamed runs charged their double-buffered
  STAGED window (ring halo included), and the int8 worker-stack
  estimate counts the per-partition scale tables, pinned against the
  REAL sharded stack's device bytes and the compiled memory_analysis;
- serve packing: residency rides the static signature / payload
  allowlist — streamed packs WITH streamed, never with resident;
- data/io.py mmap warm loads bitwise-identical to eager loads.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

from erasurehead_tpu.data import io as data_io
from erasurehead_tpu.data import sharding
from erasurehead_tpu.data import store as store_lib
from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.ops.features import QuantizedStack
from erasurehead_tpu.train import cache as cache_lib
from erasurehead_tpu.train import journal as journal_lib
from erasurehead_tpu.train import trainer
from erasurehead_tpu.utils.config import RunConfig

W = 4
P = 4  # every scheme below lays out 4 partitions at W=4
ROWS = P * 32
COLS = 8


def _cfg(**kw):
    base = dict(
        scheme="repcoded", n_workers=W, n_stragglers=1,
        partitions_per_worker=2, rounds=2, n_rows=ROWS, n_cols=COLS,
        lr_schedule=0.5, update_rule="GD", add_delay=True, seed=0,
    )
    base.update(kw)
    # kw=None drops the key back to the RunConfig default
    return RunConfig(**{k: v for k, v in base.items() if v is not None})


def _gmm():
    return generate_gmm(ROWS, COLS, n_partitions=P, seed=0)


@pytest.fixture()
def gmm():
    return _gmm()


def _bitwise(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


# ---------------------------------------------------------------------------
# shard store round trips


def test_store_roundtrip_f32(gmm, tmp_path):
    st = store_lib.write_store(gmm, str(tmp_path / "s"), P)
    rows = ROWS // P
    assert st.n_partitions == P and st.rows_per_partition == rows
    X, y = st.read_window(0, P)
    assert np.array_equal(X.reshape(ROWS, -1), gmm.X_train)
    assert np.array_equal(y.reshape(ROWS), np.asarray(gmm.y_train))
    # a sub-window straddling shard boundaries reads the same rows
    Xw, yw = st.read_window(1, 3)
    assert np.array_equal(Xw, X[1:3]) and np.array_equal(yw, y[1:3])
    # identity: reopening keys exactly like the in-memory source
    st2 = store_lib.open_store(str(tmp_path / "s"))
    assert st2.digest == st.digest == journal_lib.dataset_digest(gmm)
    assert st2.cache_token == ("shard-store", st.digest, "float32")
    ds = st2.dataset()
    assert np.array_equal(ds.X_train, gmm.X_train)
    assert journal_lib.dataset_digest(ds) == journal_lib.dataset_digest(gmm)
    assert cache_lib.dataset_token(ds) == st2.cache_token
    lab = np.dtype(st.meta["label_dtype"]).itemsize
    src = np.dtype(st.meta["source_dtype"]).itemsize
    assert st.partition_bytes() == rows * COLS * src + rows * lab


def test_store_roundtrip_int8(gmm, tmp_path):
    st = store_lib.write_store(gmm, str(tmp_path / "q"), P,
                               stack_dtype="int8")
    rows = ROWS // P
    qs, y = st.read_window(0, P)
    assert isinstance(qs, QuantizedStack)
    # write-time quantization IS the resident quantizer, partition-local
    ref = QuantizedStack.quantize(
        np.ascontiguousarray(
            np.asarray(gmm.X_train).reshape(P, rows, COLS)
        )
    )
    assert np.array_equal(np.asarray(qs.q), np.asarray(ref.q))
    assert np.array_equal(np.asarray(qs.scale), np.asarray(ref.scale))
    ds = st.dataset()
    pre = getattr(ds, "_store_prequantized", None)
    assert pre is not None  # shard_run_data reuses the stored tables
    assert np.array_equal(np.asarray(pre.q), np.asarray(ref.q))
    lab = np.dtype(st.meta["label_dtype"]).itemsize
    assert st.partition_bytes() == rows * COLS + COLS * 4 + rows * lab


def test_store_refusals(gmm, tmp_path):
    with pytest.raises(ValueError, match="stack_dtype"):
        store_lib.write_store(gmm, str(tmp_path / "x"), P,
                              stack_dtype="int4")
    with pytest.raises(ValueError, match="cannot fill"):
        store_lib.write_store(gmm, str(tmp_path / "x"), ROWS + 1)
    with pytest.raises(FileNotFoundError, match="shard store"):
        store_lib.open_store(str(tmp_path / "nope"))
    # a quantized store refuses to feed a run that would silently train
    # on the lossy dequantized reconstruction
    st = store_lib.write_store(gmm, str(tmp_path / "q"), P,
                               stack_dtype="int8")
    ds = st.dataset()
    with pytest.raises(ValueError, match="quantized"):
        trainer.train(_cfg(stack_residency="streamed"), ds)


# ---------------------------------------------------------------------------
# streamed single-window == resident, bitwise


@pytest.mark.parametrize("ring", [False, True], ids=["noring", "ring"])
@pytest.mark.parametrize("scheme,extra", [
    ("repcoded", {}),
    ("approx", {"num_collect": 2}),
], ids=["exact", "agc"])
@pytest.mark.parametrize("stack_dtype", ["float32", "int8"])
def test_streamed_single_window_bitwise(stack_dtype, scheme, extra, ring):
    cfg = _cfg(scheme=scheme, stack_dtype=stack_dtype, **extra)
    if ring:
        cfg = dataclasses.replace(cfg, stack_mode="ring")
    r = trainer.train(cfg, _gmm())
    s = trainer.train(
        dataclasses.replace(cfg, stack_residency="streamed"), _gmm()
    )
    assert r.cache_info["residency"] == "resident"
    assert s.cache_info["residency"] == "streamed"
    assert _bitwise(r.params_history, s.params_history)
    assert _bitwise(r.final_params, s.final_params)


# ---------------------------------------------------------------------------
# the multi-window block trainer


def test_streamed_multi_window_deterministic(gmm):
    cfg = _cfg(compute_mode="deduped", rounds=4,
               stack_residency="streamed", stream_window=1)
    a = trainer.train(cfg, gmm)
    ci = a.cache_info
    assert ci["residency"] == "streamed"
    assert ci["stream_window"] == 1 and ci["n_windows"] == P
    pf = ci["prefetch"]
    assert pf["windows"] >= P and pf["bytes"] > 0
    assert 0.0 <= pf["overlap_efficiency"] <= 1.0
    b = trainer.train(cfg, _gmm())
    assert _bitwise(a.params_history, b.params_history)
    assert _bitwise(a.final_params, b.final_params)


def test_streamed_multi_window_refusals(gmm, tmp_path, monkeypatch):
    multi = _cfg(compute_mode="deduped", stack_residency="streamed",
                 stream_window=1)
    # the refusal surface is NARROW (ISSUE 17): faithful windows now
    # stream (assignment-aware plans), so only the knobs with genuinely
    # no windowed body refuse — each naming the remedy knob the caller
    # actually used (--stream-window here, since that is what was set)
    with pytest.raises(ValueError, match=r"(?s)use_pallas.*stream_window"):
        trainer.train(
            dataclasses.replace(multi, use_pallas="on"), gmm
        )
    with pytest.raises(ValueError, match="layer_coding"):
        trainer.train(
            dataclasses.replace(multi, layer_coding="on"), gmm
        )
    # a caller routed here by the env byte budget is told about the
    # BUDGET, not a --stream-window they never passed
    monkeypatch.setenv("ERASUREHEAD_STREAM_WINDOW", "1")
    with pytest.raises(ValueError, match="ERASUREHEAD_STREAM_WINDOW"):
        trainer.train(
            _cfg(compute_mode="deduped", stack_residency="streamed",
                 use_pallas="on"), gmm
        )
    monkeypatch.delenv("ERASUREHEAD_STREAM_WINDOW")
    # non-window-uniform assignments (random-regular scatter) refuse at
    # the planner: no single hop table serves every window
    rr = RunConfig(
        scheme="randreg", n_workers=6, n_stragglers=2, rounds=2,
        lr_schedule=0.5, update_rule="GD", add_delay=True, seed=0,
        stack_residency="streamed", stream_window=3,
    )
    rr_data = generate_gmm(6 * 32, COLS, n_partitions=6, seed=0)
    with pytest.raises(ValueError, match="window-uniform"):
        trainer.train(rr, rr_data)
    # checkpointing composes with resident scan chunks only
    with pytest.raises(ValueError, match="checkpoint"):
        trainer.train(multi, gmm, checkpoint_dir=str(tmp_path / "ck"),
                      checkpoint_every=1)
    # streamed cohorts are ELIGIBLE now (one windowed scan serves the
    # batch); only the no-windowed-body knobs stay sequential
    assert trainer.cohort_eligible(multi)
    assert trainer.cohort_signature(multi) is not None
    assert not trainer.cohort_eligible(
        dataclasses.replace(multi, layer_coding="on")
    )


# ---------------------------------------------------------------------------
# ISSUE 17: composed streaming — assignment-aware windows x ring x cohorts.
# Geometry: W=P=6 cyclic s=2 -> stream_window=3 gives two slot-groups of 3
# workers whose assignments reach 2 partitions past their window (halo=2,
# staged=5, wraparound ranges on window 1).


def _cfg6(**kw):
    base = dict(
        scheme="cyccoded", n_workers=6, n_stragglers=2, rounds=8,
        lr_schedule=0.5, update_rule="GD", add_delay=True, seed=0,
    )
    base.update(kw)
    return RunConfig(**{k: v for k, v in base.items() if v is not None})


def _store6(tmp_path, **kw):
    src = generate_gmm(6 * 32, COLS, n_partitions=6, seed=0)
    st = store_lib.write_store(src, str(tmp_path / "s6"), 6, **kw)
    return st, st.dataset()


@pytest.mark.parametrize("stack_dtype", ["float32", "int8"])
def test_streamed_ring_full_cover_bitwise(tmp_path, stack_dtype):
    """The composition pin: a full-cover window plan localizes to the
    identity, so the streamed+ring body is the SAME program as
    resident+ring — bitwise, not allclose, f32 and int8 alike."""
    st, ds = _store6(
        tmp_path,
        stack_dtype="int8" if stack_dtype == "int8" else "float32",
    )
    cfg = _cfg6(stack_mode="ring", stack_dtype=stack_dtype)
    r = trainer.train(cfg, ds)
    s = trainer._train_streamed(
        dataclasses.replace(cfg, stack_residency="streamed"),
        ds, st, window=6,
    )
    assert r.cache_info["stack_mode"] == "ring"
    assert s.cache_info["stack_mode"] == "ring"
    assert s.cache_info["stream_halo"] == 0  # full cover degenerates
    assert _bitwise(r.params_history, s.params_history)
    assert _bitwise(r.final_params, s.final_params)


def test_streamed_materialized_full_cover_bitwise(tmp_path):
    st, ds = _store6(tmp_path)
    cfg = _cfg6()  # faithful + materialized (the defaults)
    r = trainer.train(cfg, ds)
    s = trainer._train_streamed(
        dataclasses.replace(cfg, stack_residency="streamed"),
        ds, st, window=6,
    )
    assert s.cache_info["stack_mode"] == "materialized"
    assert _bitwise(r.params_history, s.params_history)
    assert _bitwise(r.final_params, s.final_params)


@pytest.mark.parametrize("mode", ["ring", "materialized"])
def test_streamed_windowed_faithful_carries_plan(tmp_path, mode):
    """Sub-full faithful windows run (the old blanket refusal is gone)
    and carry the assignment-aware plan through cache_info; the block
    trainer stays deterministic run-to-run."""
    st, ds = _store6(tmp_path)
    cfg = _cfg6(stack_mode=mode if mode == "ring" else None,
                stack_residency="streamed", stream_window=3)
    a = trainer.train(cfg, ds)
    ci = a.cache_info
    assert ci["residency"] == "streamed" and ci["stack_mode"] == mode
    assert ci["stream_window"] == 3 and ci["n_windows"] == 2
    assert ci["stream_halo"] == 2 and ci["stream_group_workers"] == 3
    assert ci["prefetch"]["windows"] >= 2
    b = trainer.train(cfg, st.dataset())
    assert _bitwise(a.params_history, b.params_history)
    assert _bitwise(a.final_params, b.final_params)


def test_stream_group_decode_weights():
    """Sub-full faithful windows decode PER SLOT-GROUP. The resident
    decode's [R, W] weights cancel across workers (cyccoded's
    telescoping), so slicing them to one group's rows reconstructs an
    arbitrary signed mixture of staged partitions — the non-convergent
    windowed runs a W=30 CLI drive caught. The per-group least-squares
    weights (a) never reconstruct the window's partition indicator
    worse than the sliced weights (the slice is a feasible point of the
    group's lstsq), (b) beat them decisively somewhere, and (c) put no
    weight on uncollected workers."""
    from erasurehead_tpu.data.sharding import plan_stream_windows
    from erasurehead_tpu.parallel import collect
    from erasurehead_tpu.parallel import step as step_lib

    cfg = _cfg6(stack_residency="streamed", stream_window=3)
    lay = trainer.build_layout(cfg)
    plan = plan_stream_windows(lay, 3, mode="ring")
    arr = trainer.default_arrivals(cfg)
    sched = collect.build_schedule(
        cfg.scheme, arr, lay, num_collect=cfg.num_collect,
        deadline=cfg.deadline, decode=cfg.decode,
    )
    gsw = trainer._stream_group_slot_weights(lay, plan, sched)
    assert gsw.shape == (
        cfg.rounds, plan.n_windows, plan.group_workers,
        int(plan.local_assignment.shape[1]),
    )
    slot_w = np.asarray(
        step_lib.expand_slot_weights(
            sched.message_weights, lay.coeffs,
            np.asarray(lay.slot_is_coded),
        )
    )
    la = np.asarray(plan.local_assignment)
    staged, gw = plan.staged_partitions, plan.group_workers
    target = (np.arange(staged) < plan.window).astype(float)

    def recon(w):  # [gw, S] slot weights -> decoded partition sums
        out = np.zeros(staged)
        np.add.at(out, la, w)
        return out

    news, olds = [], []
    for k in range(plan.n_windows):
        for r in range(cfg.rounds):
            new = np.linalg.norm(recon(gsw[r, k]) - target)
            old = np.linalg.norm(
                recon(slot_w[r, k * gw:(k + 1) * gw]) - target
            )
            assert new <= old + 1e-9
            news.append(new)
            olds.append(old)
        sub = sched.collected[:, k * gw:(k + 1) * gw]
        assert not np.any(np.abs(gsw[:, k][~sub]) > 0)
    assert max(olds) > 5 * max(news)  # the slice was garbage somewhere


def test_streamed_window_plan_events(tmp_path):
    """Every staged window's prefetch event carries the window-plan
    fields (ranges in ring-hop order, plan_mode/halo/group_workers) and
    the whole stream passes the shared validator — the same contract
    `erasurehead-tpu lint` enforces at emit sites."""
    import json

    from erasurehead_tpu.obs import events as events_lib

    st, ds = _store6(tmp_path)
    cfg = _cfg6(stack_mode="ring", stack_residency="streamed",
                stream_window=3)
    path = str(tmp_path / "ev.jsonl")
    with events_lib.capture(path):
        trainer.train(cfg, ds)
    raw = [ln for ln in open(path).read().splitlines() if ln.strip()]
    assert events_lib.validate_lines(raw) == []
    pre = [
        r for r in map(json.loads, raw)
        if (r.get("type") or r.get("event")) == "prefetch"
    ]
    assert len(pre) >= 2
    for r in pre:
        assert r["plan_mode"] == "ring"
        assert r["halo"] == 2 and r["group_workers"] == 3
        spans = [hi - lo for lo, hi in r["ranges"]]
        assert sum(spans) == 5  # staged = window + halo
    # window 1's halo wraps: two ranges
    assert any(len(r["ranges"]) == 2 for r in pre)


def test_cohort_streamed_matches_sequential(tmp_path):
    """A streamed cohort's per-trajectory rows match the sequential
    streamed runs to float tolerance (the batched lowering changes only
    the reduction order), and the cohort really was ONE dispatch."""
    from erasurehead_tpu.obs.metrics import REGISTRY

    st, ds = _store6(tmp_path)
    cfgs = [
        _cfg6(stack_mode="ring", stack_residency="streamed",
              stream_window=3, seed=s)
        for s in (0, 1, 2)
    ]
    seq = [trainer._train_streamed(c, ds, st, window=3) for c in cfgs]
    before = REGISTRY.snapshot().get("cohort.dispatches", 0)
    res = trainer.train_cohort(cfgs, ds)
    assert REGISTRY.snapshot()["cohort.dispatches"] == before + 1
    assert len(res) == 3
    for r_seq, r_co in zip(seq, res):
        np.testing.assert_allclose(
            np.asarray(r_seq.params_history),
            np.asarray(r_co.params_history),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(r_seq.final_params),
            np.asarray(r_co.final_params),
            rtol=1e-5, atol=1e-5,
        )
    ci = res[0].cache_info
    assert ci["cohort_size"] == 3 and ci["cohort_dispatches"] == 1
    assert ci["residency"] == "streamed" and ci["stack_mode"] == "ring"
    assert ci["stream_window"] == 3 and ci["stream_halo"] == 2


def test_cohort_streamed_full_cover_bitwise(tmp_path):
    """At full cover the windowed cohort engine IS the resident cohort
    engine — bitwise, per trajectory."""
    st, ds = _store6(tmp_path)
    res_cfgs = [
        _cfg6(stack_mode="ring", seed=s) for s in (0, 1)
    ]
    str_cfgs = [
        dataclasses.replace(c, stack_residency="streamed")
        for c in res_cfgs
    ]
    r_res = trainer.train_cohort(res_cfgs, ds)
    r_str = trainer._train_cohort_streamed(
        str_cfgs[0], ds, st, 6, str_cfgs, None, None, True
    )
    for a, b in zip(r_res, r_str):
        assert _bitwise(a.params_history, b.params_history)
        assert _bitwise(a.final_params, b.final_params)


# ---------------------------------------------------------------------------
# the wedged Prefetcher.close regression (ISSUE 17 satellite)


def test_prefetcher_close_bounds_wedged_stage(tmp_path):
    """A stage that never finishes (hung shard read) can no longer spin
    close()'s drain loop forever: the deadline bounds drain+join and the
    leaked daemon thread is reported — counter + typed warning event."""
    import json
    import threading
    import time

    from erasurehead_tpu.data.prefetch import Prefetcher
    from erasurehead_tpu.obs import events as events_lib
    from erasurehead_tpu.obs.metrics import REGISTRY

    release = threading.Event()

    class WedgedStore:
        def read_ranges(self, ranges, out=None):
            release.wait()  # a hung NFS read
            return (np.zeros((1, 2, 2), np.float32),
                    np.zeros((1, 2), np.float32))

    pf = Prefetcher(
        WedgedStore(), [(0, 1)], lambda X, y: (X, y), run_id="t"
    )
    before = REGISTRY.snapshot().get("prefetch.join_timeout", 0)
    path = str(tmp_path / "ev.jsonl")
    t0 = time.monotonic()
    with events_lib.capture(path):
        pf.close(join_timeout_s=0.3)
    assert time.monotonic() - t0 < 5.0  # bounded, not forever
    assert REGISTRY.snapshot()["prefetch.join_timeout"] == before + 1
    recs = [
        json.loads(ln) for ln in open(path).read().splitlines()
        if ln.strip()
    ]
    warn = [
        r for r in recs
        if (r.get("type") or r.get("event")) == "warning"
        and r.get("kind") == "prefetch_join_timeout"
    ]
    assert warn and "did not exit" in warn[0]["message"]
    release.set()  # let the daemon thread finish before teardown


# ---------------------------------------------------------------------------
# kill→resume across the cohort-streamed path (ISSUE 17 satellite)


def test_cohort_streamed_kill_resume(tmp_path):
    """ERASUREHEAD_CHAOS=kill:prefetch:2 preempts the process while the
    streamed COHORT dispatch stages its second window; the resumed
    journaled sweep reproduces the uninterrupted baseline's science rows
    exactly. Cohort batching is the default dispatch for these streamed
    trajectories (they share residency, window, and the deduped stack),
    so the kill lands mid-cohort — nothing journaled — and resume
    re-trains the whole cohort."""
    import json
    import os
    import subprocess
    import sys

    from erasurehead_tpu.data import store as store_lib_
    from erasurehead_tpu.train import experiments
    from erasurehead_tpu.train import journal as journal_lib_
    from erasurehead_tpu.utils.chaos import KILL_EXIT

    store_dir = str(tmp_path / "store")
    src = generate_gmm(ROWS, COLS, n_partitions=P, seed=0)
    store = store_lib_.write_store(src, store_dir, P)
    data = store.dataset()
    base_kw = dict(
        scheme="repcoded", n_workers=W, n_stragglers=1,
        partitions_per_worker=2, rounds=4, n_rows=ROWS, n_cols=COLS,
        lr_schedule=0.5, update_rule="GD", add_delay=True, seed=0,
        compute_mode="deduped", stack_residency="streamed",
        stream_window=1,
    )
    base = RunConfig(**base_kw)
    sweep = {"naive": [0], "cyccoded": [1], "avoidstragg": [1]}

    def run_sweep(journal_dir, resume):
        journal = journal_lib_.SweepJournal(journal_dir, resume=resume)
        try:
            return experiments.straggler_sweep(base, data, sweep,
                                               journal=journal)
        finally:
            journal.close()

    rows_base = [
        journal_lib_.science_row(s.row())
        for s in run_sweep(str(tmp_path / "jbase"), False)
    ]

    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    child = (
        "import sys\n"
        f"sys.path.insert(0, {repr(repo_root)})\n"
        "from erasurehead_tpu.data import store as store_lib\n"
        "from erasurehead_tpu.train import experiments\n"
        "from erasurehead_tpu.train import journal as journal_lib\n"
        "from erasurehead_tpu.utils.config import RunConfig\n"
        f"store = store_lib.open_store({repr(store_dir)})\n"
        "data = store.dataset()\n"
        f"base = RunConfig(**{repr(base_kw)})\n"
        f"journal = journal_lib.SweepJournal({repr(str(tmp_path / 'jkill'))})\n"
        "try:\n"
        "    experiments.straggler_sweep(\n"
        f"        base, data, {repr(sweep)}, journal=journal)\n"
        "finally:\n"
        "    journal.close()\n"
    )
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        ERASUREHEAD_CHAOS="kill:prefetch:2",
    )
    p = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True)
    assert p.returncode == KILL_EXIT, (p.returncode, p.stderr[-2000:])

    rows_res = [
        journal_lib_.science_row(s.row())
        for s in run_sweep(str(tmp_path / "jkill"), True)
    ]
    assert rows_res == rows_base


# ---------------------------------------------------------------------------
# admission estimates (incl. the satellite-6 int8 scale-table fix)


def test_estimate_charges_streamed_window(gmm):
    ded = _cfg(compute_mode="deduped")
    res = trainer.estimate_stack_bytes(ded, gmm)
    win = trainer.estimate_stack_bytes(
        dataclasses.replace(ded, stack_residency="streamed",
                            stream_window=1), gmm
    )
    # charged two windows (compute + prefetch double buffer) of four
    assert win == res // 2
    # a window covering the whole stack charges exactly the resident run
    full = trainer.estimate_stack_bytes(
        dataclasses.replace(ded, stack_residency="streamed",
                            stream_window=P), gmm
    )
    assert full == res


def test_worker_stack_estimate_counts_int8_scales(gmm):
    cfg = _cfg(scheme="cyccoded", partitions_per_worker=None,
               compute_mode="faithful", stack_dtype="int8")
    layout = trainer.build_layout(cfg)
    est = sharding.estimate_worker_stack_bytes(gmm, layout, np.int8)
    rows = gmm.n_samples // layout.n_partitions
    Wl, S = layout.n_workers, layout.n_slots
    # payload + one f32 scale row per slot block — the satellite bugfix
    assert est == Wl * S * rows * COLS + Wl * S * COLS * 4
    # pinned against the REAL sharded stack's device bytes — estimate
    # and accounting agree exactly, so an admission decision made from
    # the host-side arithmetic matches what the dispatch will pin
    mesh = trainer._auto_mesh(layout.n_workers)
    sd = sharding.shard_run_data(gmm, layout, mesh, faithful=True,
                                 quantize=True)
    assert est == cache_lib.device_nbytes(sd.Xw)
    # the run's stack telemetry (stack + labels) can only be larger
    r = trainer.train(cfg, gmm)
    assert int(r.cache_info["stack_bytes"]) >= est


# ---------------------------------------------------------------------------
# serve: residency in the payload allowlist, never packed across


def test_streamed_never_packs_with_resident(gmm):
    from erasurehead_tpu.serve import packer as packer_lib
    from erasurehead_tpu.serve import queue as serve_queue

    assert "stack_residency" in serve_queue.CONFIG_PAYLOAD_FIELDS
    assert "stream_window" in serve_queue.CONFIG_PAYLOAD_FIELDS
    ded = _cfg(compute_mode="deduped")
    streamed = dataclasses.replace(
        ded, stack_residency="streamed", stream_window=1
    )
    # residency rides the static signature...
    assert ded.static_signature() != streamed.static_signature()
    # ...so streamed packs WITH streamed (one windowed cohort scan,
    # ISSUE 17) and never with resident
    assert packer_lib.pack_key(
        serve_queue.RunRequest(tenant="b", label="s", config=streamed,
                               dataset=gmm)
    ) is not None
    reqs = [
        serve_queue.RunRequest(tenant="a", label="r", config=ded,
                               dataset=gmm),
        serve_queue.RunRequest(tenant="b", label="s", config=streamed,
                               dataset=gmm),
        serve_queue.RunRequest(tenant="c", label="r2", config=ded,
                               dataset=gmm),
        serve_queue.RunRequest(tenant="d", label="s2", config=streamed,
                               dataset=gmm),
    ]
    cohorts = packer_lib.plan_packs(reqs)
    by_label = {
        tuple(sorted(r.label for r in c.requests)) for c in cohorts
    }
    assert ("r", "r2") in by_label and ("s", "s2") in by_label
    # differing windows key differing plans — never one scan
    other = dataclasses.replace(streamed, stream_window=2)
    assert (
        packer_lib.pack_key(
            serve_queue.RunRequest(tenant="e", label="w2", config=other,
                                   dataset=gmm)
        )
        != packer_lib.pack_key(reqs[1])
    )


def test_residency_round_trips_the_serve_payload(gmm):
    from erasurehead_tpu.serve import queue as serve_queue

    streamed = _cfg(compute_mode="deduped", stack_residency="streamed",
                    stream_window=2)
    payload = serve_queue.config_payload(streamed)
    assert payload["stack_residency"] == "streamed"
    assert payload["stream_window"] == 2
    back = serve_queue.config_from_payload(payload)
    assert back.stack_residency == "streamed"
    assert back.stream_window == 2


# ---------------------------------------------------------------------------
# data/io.py mmap warm loads


def test_mmap_load_bitwise_identical(tmp_path):
    rng = np.random.default_rng(0)
    m = rng.normal(size=(16, 5))
    path = str(tmp_path / "mat.txt")
    data_io.save_dense_text(path, m)
    cold = data_io.load_dense_text(path)  # builds the .npy sidecar
    warm_mmap = data_io.load_dense_text(path, mmap=True)
    warm_eager = data_io.load_dense_text(path, mmap=False)
    assert isinstance(warm_mmap, np.memmap)
    assert not isinstance(warm_eager, np.memmap)
    assert np.array_equal(np.asarray(warm_mmap), warm_eager)
    assert np.array_equal(np.asarray(cold), warm_eager)
