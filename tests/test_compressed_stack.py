"""Compressed feature stacks (cfg.stack_dtype): storage-side compression
with measured — never assumed — fidelity.

stack_dtype="int8" quantizes the partition-major stack at upload
(ops/features.QuantizedStack: int8 payload + per-partition-per-feature
f32 scale tables) and dequantizes inside the per-device grad body
(parallel/step._dq). Pinned here:

  - the quantizer's error bound and exact-zero reconstruction;
  - bytes accounting: the resident int8 stack is ~4x smaller than f32
    (payload exactly 4x; scale tables are the small remainder);
  - transport invariance: int8 materialized == int8 ring == int8
    ring-pipelined BITWISE (all three consume the identical quantized
    values — the loss happened once, at upload);
  - the data cache re-keys on (content, stack_dtype) — an int8 and an
    f32 run never share an upload; int8 reruns hit;
  - cohort dispatches and lowering swaps compose; sparse stacks and
    measured mode refuse loudly.
"""

import dataclasses

import jax
import numpy as np
import pytest

from erasurehead_tpu.data.synthetic import generate_gmm, generate_onehot
from erasurehead_tpu.ops.features import QuantizedStack, maybe_dequantize
from erasurehead_tpu.train import cache as cache_lib, trainer
from erasurehead_tpu.utils.config import RunConfig

W = 12


@pytest.fixture(scope="module")
def gmm():
    return generate_gmm(W * 8, 16, n_partitions=W, seed=0)


def _cfg(**kw):
    base = dict(
        scheme="approx", n_workers=W, n_stragglers=2, num_collect=6,
        rounds=3, n_rows=W * 8, n_cols=16, lr_schedule=0.5,
        update_rule="AGD", add_delay=True, seed=0,
    )
    base.update(kw)
    return RunConfig(**base)


def _bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


# ---------------------------------------------------------------------------
# quantizer


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(5, 32, 7)).astype(np.float32) * rng.uniform(
        0.1, 10.0, size=(5, 1, 7)
    ).astype(np.float32)
    qs = QuantizedStack.quantize(X)
    assert qs.q.dtype == np.int8 and qs.scale.dtype == np.float32
    assert qs.q.shape == X.shape and qs.scale.shape == (5, 7)
    rec = np.asarray(qs.q, dtype=np.float64) * qs.scale[:, None, :]
    # symmetric rounding: |err| <= scale/2 = absmax/254 per (block, col)
    bound = np.abs(X).max(axis=1, keepdims=True) / 254.0 + 1e-12
    assert (np.abs(rec - X) <= bound).all()


def test_quantize_zero_columns_and_dequantize_helper():
    X = np.zeros((2, 4, 3), dtype=np.float32)
    X[0, :, 1] = 2.0
    qs = QuantizedStack.quantize(X)
    # all-zero columns reconstruct to exact zeros (scale pinned to 1)
    rec = np.asarray(maybe_dequantize(qs))
    assert np.array_equal(rec[:, :, 0], np.zeros((2, 4)))
    assert np.allclose(rec[0, :, 1], 2.0)
    # identity for plain arrays
    arr = np.ones((3, 3), np.float32)
    assert maybe_dequantize(arr) is arr
    with pytest.raises(ValueError, match="float"):
        QuantizedStack.quantize(np.ones((2, 3, 4), dtype=np.int32))


# ---------------------------------------------------------------------------
# training: bytes, fidelity, transport invariance


def test_int8_stack_bytes_and_fidelity(gmm):
    """The resident int8 stack is ~4x smaller than f32 (payload exactly
    4x; y and the scale tables make up the remainder), and the trained
    params stay close to the f32 run — lossy, but bounded."""
    cache_lib.clear()
    f32 = trainer.train(_cfg(), gmm)
    q = trainer.train(_cfg(stack_dtype="int8"), gmm)
    assert q.cache_info["stack_dtype"] == "int8"
    assert f32.cache_info["stack_dtype"] == "float32"
    rows, F, S = 8, 16, 3  # rows/partition, features, slots (s+1)
    x_f32 = W * S * rows * F * 4
    x_q = W * S * rows * F * 1
    scale = W * S * F * 4
    y_b = W * S * rows * 4
    assert f32.cache_info["stack_bytes"] == x_f32 + y_b
    assert q.cache_info["stack_bytes"] == x_q + scale + y_b
    assert f32.cache_info["stack_bytes"] > 2 * q.cache_info["stack_bytes"]
    pf = np.asarray(jax.tree.leaves(f32.final_params)[0], np.float64)
    pq = np.asarray(jax.tree.leaves(q.final_params)[0], np.float64)
    assert np.isfinite(pq).all()
    rel = np.linalg.norm(pq - pf) / np.linalg.norm(pf)
    assert rel < 0.05, rel  # ~2e-3 measured; generous CI headroom


def test_int8_transport_invariance(gmm):
    """Materialized, ring, and ring-pipelined int8 runs consume the same
    quantized values — bitwise-identical trajectories (quantization
    happens once, per partition, BEFORE any worker-major gather)."""
    m = trainer.train(_cfg(stack_dtype="int8"), gmm)
    r = trainer.train(
        _cfg(stack_dtype="int8", stack_mode="ring"), gmm
    )
    p = trainer.train(
        _cfg(stack_dtype="int8", stack_mode="ring", ring_pipeline="on"),
        gmm,
    )
    assert _bitwise(m.params_history, r.params_history)
    assert _bitwise(m.params_history, p.params_history)
    # ring telemetry: the int8 ring stack is the compressed partition stack
    assert r.cache_info["stack_mode"] == "ring"
    assert r.cache_info["stack_bytes"] < m.cache_info["stack_bytes"]


def test_int8_composes_with_lowerings_and_deduped(gmm):
    """The dequantizing body sits under every lowering swap: forced flat
    and margin-flat runs train on the identical dequantized values as the
    per-slot body (allclose — reduction order differs), and deduped mode
    compresses its partition stack too."""
    base = trainer.train(_cfg(stack_dtype="int8"), gmm)
    for tag, extra in (
        ("flat", dict(flat_grad="on")),
        ("marginflat", dict(margin_flat="on")),
        ("deduped", dict(compute_mode="deduped")),
    ):
        res = trainer.train(_cfg(stack_dtype="int8", **extra), gmm)
        a = np.asarray(jax.tree.leaves(base.final_params)[0])
        b = np.asarray(jax.tree.leaves(res.final_params)[0])
        assert np.allclose(a, b, rtol=1e-4, atol=1e-5), tag


def test_int8_cohort_matches_sequential(gmm):
    cfgs = [
        _cfg(stack_dtype="int8", compute_mode="deduped", seed=s)
        for s in (0, 1)
    ]
    cohort = trainer.train_cohort(cfgs, gmm)
    assert cohort[0].cache_info["stack_dtype"] == "int8"
    for c, res in zip(cfgs, cohort):
        seq = trainer.train(c, gmm)
        a = np.asarray(jax.tree.leaves(seq.final_params)[0])
        b = np.asarray(jax.tree.leaves(res.final_params)[0])
        assert np.allclose(a, b, rtol=1e-4, atol=1e-5)


def test_data_cache_rekeys_on_stack_dtype(gmm):
    """(content, stack_dtype) keys the upload: f32 -> int8 misses, int8
    rerun hits, and the exec cache never serves an f32 program to an int8
    run (leaf dtypes differ in the data_tree signature)."""
    cache_lib.clear()
    f32 = trainer.train(_cfg(), gmm)
    assert not f32.cache_info["data_hit"]
    q = trainer.train(_cfg(stack_dtype="int8"), gmm)
    assert not q.cache_info["data_hit"]
    assert q.cache_info["exec_misses"] >= 1
    q2 = trainer.train(_cfg(stack_dtype="int8"), gmm)
    assert q2.cache_info["data_hit"]
    assert q2.cache_info["exec_hits"] >= 1
    assert _bitwise(q.params_history, q2.params_history)


def test_stack_dtype_bfloat16_equals_data_dtype_bf16(gmm):
    """Explicit stack_dtype='bfloat16' is the same lever as
    dtype='bfloat16' for the training stacks — bitwise."""
    a = trainer.train(_cfg(dtype="bfloat16"), gmm)
    b = trainer.train(_cfg(stack_dtype="bfloat16"), gmm)
    assert b.cache_info["stack_dtype"] == "bfloat16"
    assert _bitwise(a.params_history, b.params_history)


# ---------------------------------------------------------------------------
# refusals and validation


def test_config_validation():
    with pytest.raises(ValueError, match="stack_dtype"):
        _cfg(stack_dtype="int4")
    with pytest.raises(ValueError, match="ring_pipeline"):
        _cfg(ring_pipeline="banana")
    with pytest.raises(ValueError, match="donate"):
        _cfg(donate="maybe")
    with pytest.raises(ValueError, match="measured"):
        _cfg(stack_dtype="int8", arrival_mode="measured")
    with pytest.raises(ValueError, match="use_pallas"):
        _cfg(stack_dtype="int8", use_pallas="on")
    # resolution: auto follows the data dtype
    assert _cfg().resolve_stack_dtype() == "float32"
    assert _cfg(dtype="bfloat16").resolve_stack_dtype() == "bfloat16"
    assert _cfg(stack_dtype="int8").resolve_stack_dtype() == "int8"
    assert (
        _cfg(dtype="bfloat16", stack_dtype="float32").resolve_stack_dtype()
        == "float32"
    )


def test_int8_refuses_sparse_stacks():
    data = generate_onehot(96, 16, n_partitions=12, n_fields=4, seed=0)
    with pytest.raises(ValueError, match="dense"):
        trainer.train(
            _cfg(stack_dtype="int8", sparse_format="padded"), data
        )
