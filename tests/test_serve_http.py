"""HTTP/1.1 JSONL front + client: the serve daemon on the network.

The network-robustness contracts (PR 13):
  - per-tenant bearer auth: the token NAMES the tenant — a body tenant
    cannot impersonate, a bad token is a 401 (+ a reject event), and
    with auth off the trusted-localhost body tenant is used verbatim;
  - backpressure is a first-class reply: past the high-water mark the
    front answers 429 with a Retry-After header plus the exact
    ``retry_after_s``, and the client's deterministic capped-exponential
    backoff lands the request on a later attempt — accepted exactly
    once, never lost, never duplicated;
  - result streaming is chunked JSONL as journal rows land, with a
    BOUNDED per-connection outbox: a slow reader sheds rows
    (drop-and-journal + an in-stream overflow marker + a ``stream``
    event) instead of backing pressure into the dispatch pool.
"""

import http.client
import json
import queue as queue_lib
import threading
import time

import pytest

from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.serve import server as serve_server
from erasurehead_tpu.serve.client import (
    HttpServeClient,
    ServeRejectedError,
    ServeUnavailableError,
)
from erasurehead_tpu.serve.http_front import (
    HttpFront,
    StreamHub,
    parse_hostport,
)
from erasurehead_tpu.serve.queue import ServeResult
from erasurehead_tpu.train import cache, experiments

W, R = 4, 2
CFG = {
    "scheme": "naive", "n_workers": W, "n_stragglers": 1, "rounds": R,
    "n_rows": 64, "n_cols": 8, "lr_schedule": 0.5, "add_delay": True,
    "compute_mode": "deduped",
}


@pytest.fixture(autouse=True)
def fresh_state():
    cache.clear()
    yield
    cache.clear()


def _get(host, port, path, token=None):
    conn = http.client.HTTPConnection(host, port, timeout=30)
    headers = {}
    if token is not None:
        headers["Authorization"] = f"Bearer {token}"
    conn.request("GET", path, headers=headers)
    resp = conn.getresponse()
    body = json.loads(resp.read() or b"{}")
    conn.close()
    return resp, body


def _post(host, port, path, payload, token=None):
    conn = http.client.HTTPConnection(host, port, timeout=30)
    headers = {"Content-Type": "application/json"}
    if token is not None:
        headers["Authorization"] = f"Bearer {token}"
    conn.request("POST", path, body=json.dumps(payload), headers=headers)
    resp = conn.getresponse()
    body = json.loads(resp.read() or b"{}")
    header_retry = resp.getheader("Retry-After")
    conn.close()
    return resp, body, header_retry


class TestHttpFront:
    def test_auth_token_names_the_tenant(self, tmp_path):
        """A valid token submits AS ITS tenant (the body's tenant field
        cannot impersonate); a bad/missing token is 401 + reject event;
        the stream delivers the row to the token's tenant."""
        path = str(tmp_path / "ev.jsonl")
        with events_lib.capture(path):
            with serve_server.serving(window_s=0.05) as srv:
                front = HttpFront(srv, tokens={"tok-a": "alice"})
                try:
                    client = HttpServeClient(
                        front.host, front.port, "alice", token="tok-a"
                    )
                    rid = client.submit("mine", CFG)
                    res = client.result(timeout=180)
                    assert res["request_id"] == rid
                    assert res["tenant"] == "alice"
                    assert res["status"] == "ok"
                    # body tenant is ignored under auth: still alice's
                    resp, body, _ = _post(
                        front.host, front.port, "/v1/submit",
                        {"tenant": "mallory", "label": "steal",
                         "config": CFG},
                        token="tok-a",
                    )
                    assert resp.status == 202
                    res2 = client.result(timeout=180)
                    assert res2["tenant"] == "alice"
                    # bad token: 401, WWW-Authenticate, reject event
                    resp, body, _ = _post(
                        front.host, front.port, "/v1/submit",
                        {"label": "x", "config": CFG}, token="nope",
                    )
                    assert resp.status == 401
                    resp, body = _get(
                        front.host, front.port, "/v1/stream"
                    )
                    assert resp.status == 401
                    client.close()
                finally:
                    front.close()
        recs = [json.loads(l) for l in open(path) if l.strip()]
        rejects = [r for r in recs if r["type"] == "reject"]
        assert rejects and all(
            r["reason"] == "unauthorized" for r in rejects
        )
        streams = [r for r in recs if r["type"] == "stream"]
        assert {s["event"] for s in streams} >= {"open", "close"}
        assert events_lib.validate_file(path) == []

    def test_healthz_and_routes(self):
        with serve_server.serving(window_s=0.05) as srv:
            front = HttpFront(srv)
            try:
                resp, body = _get(front.host, front.port, "/healthz")
                assert resp.status == 200 and body["status"] == "ok"
                assert body["queued"] == 0 and body["in_flight"] == 0
                assert body["admission"]["in_flight_bytes"] == 0
                assert body["admission"]["deferred_total"] == 0
                resp, body = _get(front.host, front.port, "/nope")
                assert resp.status == 404
                resp, body, _ = _post(
                    front.host, front.port, "/v1/submit",
                    {"tenant": "t", "label": "bad",
                     "config": {"warp_drive": 9}},
                )
                assert resp.status == 400
                assert "unserveable" in body["message"]
                # stream without auth wants an explicit tenant
                resp, body = _get(front.host, front.port, "/v1/stream")
                assert resp.status == 400
            finally:
                front.close()

    def test_429_retry_after_then_client_backoff_lands(self, monkeypatch):
        """Past the high-water mark: 429 with a Retry-After header >= 1
        and the exact quote in the body; an HttpServeClient with retries
        enabled lands the same request on a later attempt — exactly one
        result, no duplicates."""
        real_dispatch = experiments._dispatch_cohort
        release = threading.Event()

        def gated(labels, configs, dataset, arrivals):
            release.wait(timeout=60)
            return real_dispatch(labels, configs, dataset, arrivals)

        monkeypatch.setattr(experiments, "_dispatch_cohort", gated)
        with serve_server.serving(
            window_s=0.01, max_pending=1
        ) as srv:
            front = HttpFront(srv)
            try:
                client = HttpServeClient(
                    front.host, front.port, "t"
                )
                rid1 = client.submit("first", CFG)
                # the daemon holds one outstanding request; the next
                # submit must bounce with the retry-after contract
                resp, body, header_retry = _post(
                    front.host, front.port, "/v1/submit",
                    {"tenant": "t", "label": "second",
                     "config": {**CFG, "seed": 1}},
                )
                assert resp.status == 429
                assert body["type"] == "rejected"
                assert body["retry_after_s"] > 0
                assert int(header_retry) >= 1
                with pytest.raises(ServeRejectedError):
                    client.submit("second", {**CFG, "seed": 1})

                # with retries armed, release capacity mid-backoff: the
                # client's schedule lands the request
                def free():
                    time.sleep(0.3)
                    release.set()

                threading.Thread(target=free, daemon=True).start()
                rid2 = client.submit(
                    "second", {**CFG, "seed": 1}, max_retries=20,
                    backoff_base=0.05, backoff_cap=0.5,
                )
                assert client.rejected_total >= 2
                got = {client.result(timeout=180)["request_id"]
                       for _ in range(2)}
                assert got == {rid1, rid2}
                client.close()
            finally:
                front.close()

    def test_dead_front_raises_typed_unavailable(self):
        with serve_server.serving(window_s=0.05) as srv:
            front = HttpFront(srv)
            host, port = front.host, front.port
            client = HttpServeClient(host, port, "t")
            front.close()
        with pytest.raises(ServeUnavailableError, match=f"{port}"):
            client.submit("x", CFG)
        with pytest.raises(ServeUnavailableError):
            client.result(timeout=10)
        client.close()

    def test_parse_hostport(self):
        assert parse_hostport("0.0.0.0:8080") == ("0.0.0.0", 8080)
        assert parse_hostport("8080") == ("127.0.0.1", 8080)
        assert parse_hostport(":0") == ("127.0.0.1", 0)
        with pytest.raises(ValueError, match="HOST:PORT"):
            parse_hostport("nope:port")


class TestStreamHub:
    def _result(self, k: int) -> ServeResult:
        return ServeResult(
            request_id=f"t-req-{k}", tenant="t", label=f"r{k}",
            status="ok", row={"k": k},
        )

    def test_bounded_outbox_sheds_and_journals(self, tmp_path):
        """A slow reader's outbox fills; further rows are SHED (counted,
        one `stream` overflow event per burst) — publish never blocks.
        Other tenants' subscriptions are untouched."""
        path = str(tmp_path / "ev.jsonl")
        hub = StreamHub(outbox_limit=2)
        with events_lib.capture(path):
            sid, sub = hub.subscribe("t")
            _, other = hub.subscribe("other")
            for k in range(5):
                hub.publish(self._result(k))
            assert sub.q.qsize() == 2
            assert sub.dropped == 3 and sub.total_dropped == 3
            assert other.q.qsize() == 0  # tenant-scoped fan-out
            hub.unsubscribe(sid)
        recs = [json.loads(l) for l in open(path) if l.strip()]
        overflows = [r for r in recs if r["type"] == "stream"
                     and r["event"] == "overflow"]
        assert len(overflows) == 1  # one event per burst, not per row
        closes = [r for r in recs if r["type"] == "stream"
                  and r["event"] == "close"]
        assert closes and closes[0]["dropped"] == 3
        assert events_lib.validate_file(path) == []

    def test_publish_never_blocks(self):
        hub = StreamHub(outbox_limit=1)
        hub.subscribe("t")
        t0 = time.monotonic()
        for k in range(1000):
            hub.publish(self._result(k))
        assert time.monotonic() - t0 < 1.0  # shed, not blocked

    def test_overflow_marker_after_drain(self):
        """The in-stream overflow marker rides AFTER the queued rows
        drain, telling the reader exactly where the gap is (the shed
        rows are journaled — re-fetch by resubmitting)."""
        hub = StreamHub(outbox_limit=1)
        _, sub = hub.subscribe("t")
        hub.publish(self._result(0))
        hub.publish(self._result(1))  # shed
        assert sub.q.get_nowait()["label"] == "r0"
        with sub.lock:
            dropped, sub.dropped = sub.dropped, 0
        assert dropped == 1
        with pytest.raises(queue_lib.Empty):
            sub.q.get_nowait()


class TestLoadgenUnits:
    def test_percentile(self):
        from erasurehead_tpu.serve.loadgen import percentile

        assert percentile([], 50) is None
        assert percentile([3.0], 99) == 3.0
        xs = [float(x) for x in range(1, 101)]
        assert percentile(xs, 50) == 51.0  # nearest rank on 100 items
        assert percentile(xs, 99) == 99.0
        assert percentile(xs, 0) == 1.0
        assert percentile(xs, 100) == 100.0
