"""Serve-fleet contracts (serve/router.py, serve/fleet.py, serve/wal.py
adoption, the clients' endpoint-list failover): the pieces `make
fleet-smoke` drives end-to-end, pinned at unit scale —

  - consistent hashing: minimal remap on membership change (every moved
    key moves TO the new member, and only ~1/N of the space moves),
    deterministic failover ring order;
  - (tenant, cohort_signature) affinity: packable load never splits a
    tenant's cohort across replicas;
  - client failover: deterministic rotation order, per-endpoint
    Retry-After embargo, and no duplicate submit when failing over;
  - WAL adoption: O_EXCL sentinel race (exactly one winner), owner-alive
    refusal, digest dedup;
  - evidential-streak death: a replica is declared dead after K
    consecutive evidential misses, NEVER fewer, and the fleet event
    validator refuses a declare_dead record that claims otherwise.
"""

import json
import os
import socket
import threading
import time

import pytest

from erasurehead_tpu.elastic.controller import ProbeStreakDetector
from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.obs.metrics import REGISTRY as _METRICS
from erasurehead_tpu.serve.client import (
    HttpServeClient,
    ServeClient,
    ServeUnavailableError,
    _normalize_endpoints,
)
from erasurehead_tpu.serve.router import (
    VNODES,
    FleetRouter,
    HashRing,
    affinity_key,
)
from erasurehead_tpu.serve.wal import (
    ADOPT_SENTINEL_SUFFIX,
    IntakeWAL,
    WalAdoptionError,
)

CFG = {
    "scheme": "naive", "n_workers": 4, "n_stragglers": 1, "rounds": 2,
    "n_rows": 64, "n_cols": 8, "lr_schedule": 0.5,
    "compute_mode": "deduped",
}


# ---- consistent hashing --------------------------------------------------


def _keys(n=1000):
    return [f"tenant{i % 7}:key{i}" for i in range(n)]


def test_ring_minimal_remap_on_add():
    """Adding a 4th member to a 3-member ring moves ~1/4 of the key
    space — never the wholesale reshuffle a modulo hash would do — and
    every key that moves, moves TO the new member (consistency: no key
    swaps between two surviving members)."""
    before = HashRing(["r0", "r1", "r2"])
    after = HashRing(["r0", "r1", "r2", "r3"])
    keys = _keys()
    moved = [
        k for k in keys if before.lookup(k) != after.lookup(k)
    ]
    frac = len(moved) / len(keys)
    # ideal 0.25; VNODES=64 keeps the share smooth
    assert 0.10 <= frac <= 0.40, f"remap fraction {frac}"
    assert all(after.lookup(k) == "r3" for k in moved), (
        "a moved key landed on a SURVIVING member — not consistent "
        "hashing"
    )


def test_ring_minimal_remap_on_remove():
    """Removing a member re-homes ONLY its keys; everyone else's
    assignment is untouched (what makes a deploy bounce flush one
    replica's cache, not all of them)."""
    before = HashRing(["r0", "r1", "r2"])
    after = HashRing(["r0", "r2"])
    for k in _keys():
        owner = before.lookup(k)
        if owner != "r1":
            assert after.lookup(k) == owner
        else:
            assert after.lookup(k) in ("r0", "r2")


def test_ring_order_deterministic_failover():
    """ring_order(key) is the failover sequence: starts at lookup(key),
    contains every member exactly once, and is identical across
    independently-built rings (every client/supervisor walks the SAME
    ring)."""
    a = HashRing(["r0", "r1", "r2"])
    b = HashRing(["r2", "r0", "r1"])  # insertion order must not matter
    for k in _keys(64):
        order = a.ring_order(k)
        assert order[0] == a.lookup(k)
        assert sorted(order) == ["r0", "r1", "r2"]
        assert b.ring_order(k) == order


def test_ring_vnodes_spread():
    """VNODES keeps member shares smooth: with 3 members no member owns
    more than half the key space."""
    ring = HashRing(["r0", "r1", "r2"], vnodes=VNODES)
    keys = _keys()
    counts = {}
    for k in keys:
        counts[ring.lookup(k)] = counts.get(ring.lookup(k), 0) + 1
    assert max(counts.values()) / len(keys) < 0.5, counts


def test_affinity_zero_cross_replica_cohort_splits():
    """The ISSUE's packable-load pin: 4 tenants, each submitting
    same-signature configs (seed is NOT in the cohort signature), on a
    2-replica ring — every tenant's whole cohort routes to ONE replica.
    A split cohort would halve packing efficiency exactly where the
    daemon is supposed to amortize dispatches."""
    ring = HashRing(["r0", "r1"])
    for tenant in ("t0", "t1", "t2", "t3"):
        owners = {
            ring.lookup(affinity_key(tenant, {**CFG, "seed": s}))
            for s in range(8)
        }
        assert len(owners) == 1, (
            f"tenant {tenant} cohort split across {owners}"
        )


def test_affinity_key_falls_back_to_tenant():
    """A payload that cannot resolve to a config still routes (by tenant
    alone) — the router must never 500 on a routing key."""
    good = affinity_key("alice", {**CFG, "seed": 0})
    bad = affinity_key("alice", {"scheme": "no-such-scheme"})
    assert json.loads(bad)[0] == "alice"
    assert good != bad  # the signature really participates


# ---- ServeClient endpoint-list failover ----------------------------------


class _FakeDaemon:
    """Minimal line-protocol daemon on a unix socket: replies 'accepted'
    (or 'rejected' with a retry_after quote) and records every submit
    line it saw."""

    def __init__(self, path, reply="accepted", retry_after=1.5):
        self.path = path
        self.reply = reply
        self.retry_after = retry_after
        self.seen = []
        self._conns = []
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(path)
        self._srv.listen(8)
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            self._conns.append(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn):
        buf = b""
        with conn:
            while True:
                try:
                    chunk = conn.recv(1 << 16)
                except OSError:
                    return
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    raw, buf = buf.split(b"\n", 1)
                    if not raw.strip():
                        continue
                    msg = json.loads(raw)
                    self.seen.append(msg)
                    if self.reply == "accepted":
                        out = {
                            "type": "accepted",
                            "request_id": f"rid-{len(self.seen)}",
                        }
                    else:
                        out = {
                            "type": "rejected",
                            "retry_after_s": self.retry_after,
                        }
                    try:
                        conn.sendall(
                            (json.dumps(out) + "\n").encode()
                        )
                    except OSError:
                        return

    def close(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass
        for conn in self._conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


def test_serve_client_single_path_back_compat(tmp_path):
    """A plain string path keeps the historical single-endpoint
    behavior; `.path` names it."""
    p = str(tmp_path / "a.sock")
    d = _FakeDaemon(p)
    try:
        c = ServeClient(p, timeout=5.0)
        assert c.paths == [p] and c.path == p
        rid = c.submit("alice", "j", CFG)
        assert rid and len(d.seen) == 1
        c.close()
    finally:
        d.close()


def test_serve_client_rotation_order_and_no_duplicate_submit(tmp_path):
    """Endpoint-list failover: with the first endpoint dead, the client
    rotates to the NEXT in list order (deterministic), the submission is
    delivered exactly once (no duplicate submit: only unacknowledged
    sends re-send), and failovers_total counts the rotation."""
    dead = str(tmp_path / "dead.sock")  # never bound
    live = str(tmp_path / "live.sock")
    d = _FakeDaemon(live)
    try:
        c = ServeClient([dead, live], timeout=5.0)
        # _connect already walked past the dead endpoint
        assert c.path == live
        rid = c.submit("alice", "j", CFG)
        assert rid
        assert [m["label"] for m in d.seen] == ["j"]  # exactly once
        c.close()
    finally:
        d.close()


def test_serve_client_failover_mid_session(tmp_path):
    """A daemon dying BETWEEN submits: the next submit fails over to the
    peer and is delivered exactly once there."""
    a = str(tmp_path / "a.sock")
    b = str(tmp_path / "b.sock")
    da, db = _FakeDaemon(a), _FakeDaemon(b)
    try:
        c = ServeClient([a, b], timeout=5.0)
        assert c.submit("alice", "one", CFG)
        da.close()
        os.unlink(a)
        time.sleep(0.05)
        assert c.submit("alice", "two", CFG)
        assert c.failovers_total >= 1
        assert [m["label"] for m in da.seen] == ["one"]
        assert [m["label"] for m in db.seen] == ["two"]
        c.close()
    finally:
        da.close()
        db.close()


def test_serve_client_all_endpoints_down_raises(tmp_path):
    with pytest.raises(ServeUnavailableError):
        ServeClient(
            [str(tmp_path / "x.sock"), str(tmp_path / "y.sock")],
            timeout=1.0,
        )


def test_serve_client_embargo_deprioritizes_rejecting_endpoint(tmp_path):
    """A 429 quote embargoes THAT endpoint: the failover walk tries
    un-embargoed peers first, so one overloaded replica never stalls
    submission to its peers."""
    busy = str(tmp_path / "busy.sock")
    calm = str(tmp_path / "calm.sock")
    d_busy = _FakeDaemon(busy, reply="rejected", retry_after=60.0)
    d_calm = _FakeDaemon(calm)
    try:
        c = ServeClient([busy, calm], timeout=5.0)
        # first submit eats the 429 from `busy` and embargoes it …
        with pytest.raises(Exception):
            c.submit("alice", "j0", CFG, max_retries=0)
        assert c._not_before.get(busy, 0.0) > time.monotonic()
        # … so a reconnect walk prefers `calm` even though `busy` is
        # earlier in list order
        c._idx = 0
        c._connect()
        assert c.path == calm
        assert c.submit("alice", "j1", CFG)
        assert [m["label"] for m in d_calm.seen] == ["j1"]
        c.close()
    finally:
        d_busy.close()
        d_calm.close()


# ---- HttpServeClient endpoint lists --------------------------------------


def test_normalize_endpoints_forms():
    assert _normalize_endpoints("h", 1, None) == [("h", 1)]
    assert _normalize_endpoints(None, None, [("a", 1), ("b", 2)]) == [
        ("a", 1), ("b", 2),
    ]
    assert _normalize_endpoints(None, None, ["a:1", "b:2"]) == [
        ("a", 1), ("b", 2),
    ]
    # host-as-list is the endpoints form too
    assert _normalize_endpoints(["a:1"], None, None) == [("a", 1)]
    with pytest.raises(ValueError):
        _normalize_endpoints(None, None, [])
    with pytest.raises(ValueError):
        _normalize_endpoints(None, None, None)


class _FakeHttpFront:
    """Counts /v1/submit POSTs; can answer 202 or 429+Retry-After."""

    def __init__(self, status=202, retry_after=30.0):
        import http.server

        front = self

        class H(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
                front.seen.append(body)
                if front.status == 202:
                    out = json.dumps(
                        {"type": "accepted",
                         "request_id": f"rid-{len(front.seen)}"}
                    ).encode()
                    self.send_response(202)
                else:
                    out = json.dumps(
                        {"type": "rejected",
                         "retry_after_s": front.retry_after}
                    ).encode()
                    self.send_response(429)
                    self.send_header(
                        "Retry-After", str(int(front.retry_after))
                    )
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def do_GET(self):  # /v1/stream — hold the stream open
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.end_headers()

            def log_message(self, *a):
                pass

        self.status = status
        self.retry_after = retry_after
        self.seen = []
        self._srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self._srv.server_address[1]
        threading.Thread(
            target=self._srv.serve_forever, daemon=True
        ).start()

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


def test_http_client_failover_rotation_no_duplicate():
    """First endpoint dead -> the submit rotates to the live peer in
    list order and is delivered exactly once; failovers_total pins the
    rotation count."""
    live = _FakeHttpFront()
    # a dead endpoint: bind-then-close leaves a refused port
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    try:
        c = HttpServeClient(
            tenant="alice",
            endpoints=[("127.0.0.1", dead_port),
                       ("127.0.0.1", live.port)],
        )
        rid = c.submit("j", CFG)
        assert rid
        assert [m["label"] for m in live.seen] == ["j"]
        assert c.failovers_total == 1
        c.close()
    finally:
        live.close()


def test_http_client_per_endpoint_retry_after_embargo():
    """A 429 from one endpoint embargoes only that endpoint: the same
    pass continues to the peer, which accepts — no sleep, no global
    stall, and the busy endpoint's quote is remembered."""
    busy = _FakeHttpFront(status=429, retry_after=60.0)
    calm = _FakeHttpFront()
    try:
        c = HttpServeClient(
            tenant="alice",
            endpoints=[("127.0.0.1", busy.port),
                       ("127.0.0.1", calm.port)],
        )
        t0 = time.monotonic()
        rid = c.submit("j", CFG)
        assert rid and time.monotonic() - t0 < 5.0
        assert len(busy.seen) == 1 and len(calm.seen) == 1
        assert c._not_before.get(0, 0.0) > time.monotonic()
        # the next submit skips the embargoed endpoint outright
        assert c.submit("j2", CFG)
        assert len(busy.seen) == 1  # never bothered again
        assert [m["label"] for m in calm.seen] == ["j", "j2"]
        c.close()
    finally:
        busy.close()
        calm.close()


def test_http_client_result_dedups_by_request_id():
    """Exactly-once delivery: a row replayed by WAL adoption (same
    request_id, different stream) is absorbed client-side."""
    live = _FakeHttpFront()
    try:
        c = HttpServeClient(
            tenant="alice", endpoints=[("127.0.0.1", live.port)]
        )
        for _ in range(2):  # the same result arriving twice
            c._results.put(
                {"type": "result", "request_id": "r1", "tenant": "alice",
                 "label": "j", "status": "ok", "row": {}}
            )
        c._results.put(
            {"type": "result", "request_id": "r2", "tenant": "alice",
             "label": "k", "status": "ok", "row": {}}
        )
        got = [c.result(timeout=1.0)["request_id"] for _ in range(2)]
        assert got == ["r1", "r2"]  # the duplicate r1 was swallowed
        c.close()
    finally:
        live.close()


# ---- WAL adoption --------------------------------------------------------


def _seed_wal(dirpath, n=3):
    wal = IntakeWAL(str(dirpath))
    for i in range(n):
        wal.append(
            tenant="alice", request_id=f"req-{i}", label=f"j{i}",
            digest=f"digest-{i}", config_payload={**CFG, "seed": i},
            data_seed=0, target_loss=None, priority=0,
        )
    return wal


def test_adopt_replays_dedups_and_sentinels(tmp_path):
    dead = tmp_path / "dead"
    wal = _seed_wal(dead)
    # a duplicate acceptance (client retry) must collapse
    wal.append(
        tenant="alice", request_id="req-0b", label="j0",
        digest="digest-0", config_payload={**CFG, "seed": 0},
        data_seed=0, target_loss=None, priority=0,
    )
    adopter = IntakeWAL(str(tmp_path / "peer"))
    records = adopter.adopt(str(dead / "intake_wal.jsonl"))
    assert [r["digest"] for r in records] == [
        "digest-0", "digest-1", "digest-2",
    ]
    assert os.path.exists(
        str(dead / "intake_wal.jsonl") + ADOPT_SENTINEL_SUFFIX
    )


def test_double_adoption_race_exactly_one_winner(tmp_path):
    """The regression the ISSUE names: two replicas declaring the same
    peer dead concurrently — the O_EXCL sentinel guarantees exactly one
    adopter; the loser gets WalAdoptionError, never a double replay."""
    dead = tmp_path / "dead"
    _seed_wal(dead)
    path = str(dead / "intake_wal.jsonl")
    outcomes = {}
    barrier = threading.Barrier(2)

    def race(name):
        adopter = IntakeWAL(str(tmp_path / name))
        barrier.wait()
        try:
            outcomes[name] = ("won", adopter.adopt(path))
        except WalAdoptionError as e:
            outcomes[name] = ("lost", str(e))

    threads = [
        threading.Thread(target=race, args=(n,)) for n in ("p1", "p2")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    verdicts = sorted(v[0] for v in outcomes.values())
    assert verdicts == ["lost", "won"], outcomes
    winner = next(v for v in outcomes.values() if v[0] == "won")
    assert len(winner[1]) == 3


def test_adopt_refuses_when_owner_answers_healthz(tmp_path):
    """A replica that still answers /healthz is NOT dead — adopting its
    WAL would double-dispatch its working set."""
    dead = tmp_path / "alive-actually"
    _seed_wal(dead)
    adopter = IntakeWAL(str(tmp_path / "peer"))
    with pytest.raises(WalAdoptionError, match="healthz|alive|answers"):
        adopter.adopt(
            str(dead / "intake_wal.jsonl"), owner_alive=lambda: True
        )
    # no sentinel was dropped — a later, legitimate adoption must win
    assert not os.path.exists(
        str(dead / "intake_wal.jsonl") + ADOPT_SENTINEL_SUFFIX
    )
    assert adopter.adopt(
        str(dead / "intake_wal.jsonl"), owner_alive=lambda: False
    )


def test_adopt_skips_digests_already_seen(tmp_path):
    """Digest dedup across WALs: acceptances the adopter already owns
    (client failover re-submitted them there) do not replay twice."""
    dead = tmp_path / "dead"
    _seed_wal(dead, n=3)
    adopter = IntakeWAL(str(tmp_path / "peer"))
    adopter.append(
        tenant="alice", request_id="mine", label="j1",
        digest="digest-1", config_payload={**CFG, "seed": 1},
        data_seed=0, target_loss=None, priority=0,
    )
    records = adopter.adopt(str(dead / "intake_wal.jsonl"))
    assert [r["digest"] for r in records] == ["digest-0", "digest-2"]


# ---- evidential-streak death ---------------------------------------------


def test_death_only_after_k_evidential_misses():
    """The acceptance criterion pinned at the detector: k-1 misses never
    declare death; the kth does; a success resets the streak; and
    non-evidential misses (a deliberate deploy bounce) never count."""
    det = ProbeStreakDetector(["r0"], k=3)
    for _ in range(2):
        det.observe("r0", ok=False)
    assert not det.is_dead("r0")
    det.observe("r0", ok=True)  # success resets
    assert det.streak("r0") == 0
    # a deploy bounce: misses observed while deliberately down
    for _ in range(10):
        det.observe("r0", ok=False, evidential=False)
    assert not det.is_dead("r0")
    for _ in range(3):
        det.observe("r0", ok=False)
    assert det.is_dead("r0")
    assert det.streak("r0") >= 3


def test_fleet_event_validator_rejects_premature_death(tmp_path):
    """A declare_dead record with streak < k is exactly the bug the
    evidential rule exists to prevent; the validator refuses it."""
    p = tmp_path / "ev.jsonl"
    with events_lib.capture(str(p)):
        events_lib.emit(
            "fleet", action="declare_dead", replica="r1", streak=2, k=3
        )
    errs = events_lib.validate_lines(open(p))
    assert errs and any("never fewer" in e for e in errs)

    good = tmp_path / "good.jsonl"
    with events_lib.capture(str(good)):
        events_lib.emit("fleet", action="probe", replica="r1", ok=True)
        events_lib.emit(
            "fleet", action="suspect", replica="r1", streak=1, k=3
        )
        events_lib.emit(
            "fleet", action="declare_dead", replica="r1", streak=3, k=3
        )
        events_lib.emit(
            "fleet", action="adopt", replica="r1", records=4,
            adopter="r0",
        )
        events_lib.emit(
            "fleet", action="deploy_phase", replica="r0", phase="drain"
        )
    assert events_lib.validate_lines(open(good)) == []


def test_fleet_event_validator_rejects_unknown_action(tmp_path):
    p = tmp_path / "bad.jsonl"
    with events_lib.capture(str(p)):
        events_lib.emit("fleet", action="resurrect", replica="r1")
    errs = events_lib.validate_lines(open(p))
    assert errs and any("action" in e for e in errs)


# ---- router membership + gauges ------------------------------------------


def test_router_membership_and_fleet_gauges():
    """set_alive toggles ring membership without forgetting the replica;
    fleet_view/fleet_gauges expose what /metrics renders."""
    router = FleetRouter(port=0)
    try:
        router.add_replica("r0", "127.0.0.1", 1111)
        router.add_replica("r1", "127.0.0.1", 2222)
        assert sorted(router.ring.members) == ["r0", "r1"]
        router.set_alive("r1", False)
        assert router.ring.members == ["r0"]
        assert set(router.replicas) == {"r0", "r1"}
        router.set_alive("r1", True, pressure=0.5)
        assert sorted(router.ring.members) == ["r0", "r1"]

        view = router.fleet_view()
        assert view["replicas"]["r1"]["pressure"] == 0.5
        gauges = router.fleet_gauges()
        by_name = {k.split("{")[0]: v for k, v in gauges.items()}
        live = next(
            k for k in by_name if k.endswith("fleet_replicas_live")
        )
        known = next(
            k for k in by_name if k.endswith("fleet_replicas_known")
        )
        assert by_name[live] == 2.0
        assert by_name[known] == 2.0
    finally:
        router.close()


def test_router_routes_by_affinity_and_fails_over():
    """The ring decides the primary; with the primary marked dead the
    same key resolves to the survivor (deterministic failover)."""
    router = FleetRouter(port=0)
    try:
        router.add_replica("r0", "127.0.0.1", 1111)
        router.add_replica("r1", "127.0.0.1", 2222)
        key = affinity_key("alice", {**CFG, "seed": 0})
        primary = router.ring.lookup(key)
        order = router.ring.ring_order(key)
        assert order[0] == primary and len(order) == 2
        router.set_alive(primary, False)
        assert router.ring.lookup(key) == order[1]
    finally:
        router.close()


def test_wait_front_parses_only_this_incarnations_log(tmp_path):
    """A bounced replica APPENDS to its log, so the first "http front
    on" line names the dead pre-bounce port. _wait_front must parse only
    lines written after the latest spawn (rep.log_offset) — the
    rolling-deploy wedge regression: probing the stale port forever."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from erasurehead_tpu.serve import fleet as fleet_lib

    class _Healthz(BaseHTTPRequestHandler):
        def do_GET(self):
            body = b'{"status": "ok"}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # noqa: D102 — quiet test server
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Healthz)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    live_port = httpd.server_address[1]
    try:
        log = tmp_path / "r0.log"
        stale = "serve: http front on 127.0.0.1:1 (auth off)\n"
        log.write_text(stale)
        rep = fleet_lib.Replica(
            name="r0", journal_dir=str(tmp_path / "r0"),
            cache_dir=str(tmp_path / "cache"), events_path=None,
            log_path=str(log),
        )
        rep.log_offset = len(stale)  # what spawn() records on a bounce

        class _LiveProc:
            def poll(self):
                return None

        rep.proc = _LiveProc()
        with open(log, "a") as f:
            f.write(
                f"serve: http front on 127.0.0.1:{live_port} (auth off)\n"
            )
        fleet_lib.FleetSupervisor._wait_front(None, rep, timeout=10)
        assert rep.port == live_port, (
            f"parsed stale port {rep.port} instead of {live_port}"
        )
    finally:
        httpd.shutdown()
        httpd.server_close()
