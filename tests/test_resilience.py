"""Resilient sweep runner: journaled resume, cohort OOM bisection,
divergence quarantine, checkpoint-corruption fallback, chaos hook.

The sweep engine produces the paper's central artifact; these tests pin
the contract that no single failure — preemption, cohort OOM, transient
runtime error, diverging trajectory, torn checkpoint — can destroy it:

  - a sweep interrupted by the chaos hook after trajectory N, then resumed
    from its journal, produces summary rows IDENTICAL (labels, simulated
    clocks, losses bitwise-equal, decode-error columns) to the
    uninterrupted sweep, across batch-trajectories on/off/auto;
  - a forced cohort dispatch failure degrades through bisection to
    sequential without losing any trajectory, with the cohort.split /
    cohort.retry counters and warning events asserting the path taken;
  - a seeded diverging trajectory yields a status=diverged row while every
    other row matches the sweep run without it.
"""

import glob
import json
import os

import numpy as np
import pytest

from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.obs.metrics import REGISTRY
from erasurehead_tpu.train import experiments, trainer
from erasurehead_tpu.train import journal as journal_lib
from erasurehead_tpu.utils import chaos
from erasurehead_tpu.utils.config import RunConfig

W = 4
R = 6


@pytest.fixture(scope="module")
def gmm():
    return generate_gmm(64, 8, n_partitions=W, seed=0)


def _base(**kw):
    # deduped: the partition-major stack is scheme-independent, so all
    # four schemes form ONE cohort under batch-trajectories — the shape
    # the bisection and kill->resume invariance contracts are about
    d = dict(
        scheme="naive", n_workers=W, n_stragglers=1, rounds=R,
        n_rows=64, n_cols=8, update_rule="AGD", lr_schedule=1.0,
        add_delay=True, seed=0, compute_mode="deduped",
    )
    d.update(kw)
    return RunConfig(**d)


def _configs():
    return {
        "naive": _base(),
        "avoid_s1": _base(scheme="avoidstragg"),
        "agc": _base(scheme="approx", num_collect=3),
        "cyc": _base(scheme="cyccoded"),
    }


@pytest.fixture(autouse=True)
def _chaos_clean(monkeypatch):
    """Every test starts and ends with the chaos hook unarmed and its
    invocation counters zeroed."""
    monkeypatch.delenv(chaos.CHAOS_ENV, raising=False)
    chaos.reset()
    yield
    chaos.reset()


def _counter(name):
    return REGISTRY.counter(name).value


def _science(rows):
    return [journal_lib.science_row(s.row()) for s in rows]


# ---------------------------------------------------------------------------
# chaos hook


def test_chaos_spec_parsing():
    s = chaos.parse_spec("kill:trajectory:2")
    assert (s.mode, s.site, s.count, s.sticky) == (
        "kill", "trajectory", 2, False,
    )
    s = chaos.parse_spec("raise:cohort:1+:UNAVAILABLE")
    assert s.sticky and s.message == "UNAVAILABLE"
    for bad in ("boom", "kill:nowhere:1", "raise:cohort:x", "raise:cohort:0"):
        with pytest.raises(ValueError):
            chaos.parse_spec(bad)


def test_chaos_hook_fires_at_count(monkeypatch):
    monkeypatch.setenv(chaos.CHAOS_ENV, "raise:trajectory:2:BOOM")
    chaos.reset()
    chaos.maybe_fire("trajectory")  # invocation 1: below count
    chaos.maybe_fire("cohort")  # other site: never fires
    with pytest.raises(chaos.ChaosInjection, match="BOOM"):
        chaos.maybe_fire("trajectory")  # invocation 2
    chaos.maybe_fire("trajectory")  # invocation 3: non-sticky, done


# ---------------------------------------------------------------------------
# sweep journal + kill->resume invariance (the tentpole contract)


@pytest.mark.parametrize("batch", ["off", "auto", "on"])
def test_kill_resume_rows_identical(gmm, tmp_path, monkeypatch, batch):
    """A sweep interrupted after its 2nd journaled trajectory, resumed
    from the journal, yields rows row-for-row identical (losses bitwise)
    to the uninterrupted sweep — across all dispatch modes."""
    baseline = experiments.compare(_configs(), gmm, batch=batch)

    jdir = str(tmp_path / f"journal_{batch}")
    monkeypatch.setenv(chaos.CHAOS_ENV, "raise:trajectory:2")
    chaos.reset()
    j = journal_lib.SweepJournal(jdir, resume=False)
    with pytest.raises(chaos.ChaosInjection):
        experiments.compare(_configs(), gmm, batch=batch, journal=j)
    j.close()
    monkeypatch.delenv(chaos.CHAOS_ENV)
    chaos.reset()

    j2 = journal_lib.SweepJournal(jdir, resume=True)
    assert len(j2) == 2  # exactly the pre-kill trajectories persisted
    resumed_before = _counter("sweep_journal.resumed")
    resumed = experiments.compare(_configs(), gmm, batch=batch, journal=j2)
    j2.close()
    assert _counter("sweep_journal.resumed") - resumed_before == 2

    assert _science(baseline) == _science(resumed)
    for a, b in zip(baseline, resumed):
        assert np.array_equal(
            np.asarray(a.training_loss), np.asarray(b.training_loss)
        )
        assert a.training_loss.dtype == b.training_loss.dtype
        np.testing.assert_array_equal(a.timeset, b.timeset)
    # the journal is a valid events.jsonl (same validator as every log)
    errors = events_lib.validate_file(j2.path)
    assert errors == [], errors


def test_resume_key_rejects_changed_inputs(gmm, tmp_path):
    """The journal key pins config + data + arrivals: change the arrival
    schedule and NOTHING resumes — stale rows must never leak into a
    different experiment."""
    from erasurehead_tpu.parallel import straggler

    configs = {"naive": _base(), "avoid": _base(scheme="avoidstragg")}
    arr1 = straggler.arrival_schedule(R, W, add_delay=True, mean=0.5)
    arr2 = straggler.arrival_schedule(R, W, add_delay=True, mean=0.9)
    jdir = str(tmp_path / "j")
    j = journal_lib.SweepJournal(jdir, resume=False)
    experiments.compare(dict(configs), gmm, arrivals=arr1, journal=j)
    j.close()
    j2 = journal_lib.SweepJournal(jdir, resume=True)
    before = _counter("sweep_journal.resumed")
    experiments.compare(dict(configs), gmm, arrivals=arr2, journal=j2)
    assert _counter("sweep_journal.resumed") == before
    # identical inputs DO resume
    j3 = journal_lib.SweepJournal(jdir, resume=True)
    experiments.compare(dict(configs), gmm, arrivals=arr1, journal=j3)
    assert _counter("sweep_journal.resumed") == before + 2
    j3.close()


def test_ambient_env_journal(gmm, tmp_path, monkeypatch):
    """ERASUREHEAD_SWEEP_JOURNAL enables journaling with no plumbing —
    any compare() call picks up the ambient journal."""
    from erasurehead_tpu.utils.config import (
        RESUME_SWEEP_ENV,
        SWEEP_JOURNAL_ENV,
    )

    jdir = str(tmp_path / "ambient")
    monkeypatch.setenv(SWEEP_JOURNAL_ENV, jdir)
    journal_lib.reset_env_journal()
    try:
        first = experiments.compare({"naive": _base()}, gmm)
        assert os.path.exists(os.path.join(jdir, "sweep_journal.jsonl"))
        monkeypatch.setenv(RESUME_SWEEP_ENV, "1")
        journal_lib.reset_env_journal()
        before = _counter("sweep_journal.resumed")
        again = experiments.compare({"naive": _base()}, gmm)
        assert _counter("sweep_journal.resumed") == before + 1
        assert _science(first) == _science(again)
    finally:
        journal_lib.reset_env_journal()


# ---------------------------------------------------------------------------
# graceful cohort degradation


def test_cohort_oom_bisects_once(gmm, tmp_path, monkeypatch):
    """First cohort dispatch OOMs -> one bisection, both halves succeed,
    no trajectory lost; the warning events name the path taken."""
    monkeypatch.setenv(chaos.CHAOS_ENV, "raise:cohort:1")
    chaos.reset()
    split0, fall0 = _counter("cohort.split"), _counter(
        "cohort.sequential_fallback"
    )
    epath = str(tmp_path / "events.jsonl")
    with events_lib.capture(epath):
        rows = experiments.compare(_configs(), gmm, batch="on")
    assert [s.label for s in rows] == list(_configs())
    assert _counter("cohort.split") - split0 == 1
    assert _counter("cohort.sequential_fallback") - fall0 == 0
    kinds = [
        rec["kind"]
        for rec in map(json.loads, open(epath))
        if rec["type"] == "warning"
    ]
    assert "cohort_dispatch" in kinds and "cohort_split" in kinds
    msgs = " ".join(
        rec["message"]
        for rec in map(json.loads, open(epath))
        if rec["type"] == "warning"
    )
    # the warning names the failed cohort composition
    assert "naive" in msgs and "cyc" in msgs


def test_cohort_sticky_failure_degrades_to_sequential(gmm, monkeypatch):
    """Every cohort dispatch fails -> full bisection down to sequential
    train(); rows are bitwise identical to batch='off' (sequential IS the
    off path), and the counters record 3 splits + 4 fallbacks for a
    4-trajectory cohort."""
    off_rows = experiments.compare(_configs(), gmm, batch="off")
    monkeypatch.setenv(chaos.CHAOS_ENV, "raise:cohort:1+")
    chaos.reset()
    split0, fall0 = _counter("cohort.split"), _counter(
        "cohort.sequential_fallback"
    )
    rows = experiments.compare(_configs(), gmm, batch="on")
    assert _counter("cohort.split") - split0 == 3  # 4 -> 2+2 -> 1+1+1+1
    assert _counter("cohort.sequential_fallback") - fall0 == 4
    assert _science(off_rows) == _science(rows)
    for a, b in zip(off_rows, rows):
        assert np.array_equal(
            np.asarray(a.training_loss), np.asarray(b.training_loss)
        )


def test_cohort_transient_retries_with_backoff(gmm, monkeypatch):
    """A transient (UNAVAILABLE) dispatch failure retries the SAME cohort
    with backoff instead of bisecting."""
    monkeypatch.setattr(experiments, "COHORT_BACKOFF_S", 0.001)
    monkeypatch.setenv(chaos.CHAOS_ENV, "raise:cohort:1:UNAVAILABLE")
    chaos.reset()
    retry0, split0 = _counter("cohort.retry"), _counter("cohort.split")
    rows = experiments.compare(_configs(), gmm, batch="on")
    assert len(rows) == 4
    assert _counter("cohort.retry") - retry0 == 1
    assert _counter("cohort.split") - split0 == 0


def test_guard_ignores_non_runtime_errors(gmm):
    """The guard only classifies runtime/OOM/transient failures; a config
    error from validation propagates untouched (no retry, no bisect)."""
    bad = {"m": _base(arrival_mode="measured", compute_mode="faithful")}
    with pytest.raises(ValueError, match="measured"):
        experiments._dispatch_cohort(["m"], bad, gmm, None)


# ---------------------------------------------------------------------------
# divergence quarantine


def test_divergence_quarantine(gmm, tmp_path):
    """A diverging trajectory (lr blown up) yields a status=diverged row;
    the sweep completes, downstream aggregation survives, and every other
    row matches the sweep run without it."""
    without = experiments.compare(_configs(), gmm, batch="off")
    configs = _configs()
    configs["boom"] = _base(scheme="avoidstragg", lr_schedule=1e12)
    div0 = _counter("sweep.diverged")
    epath = str(tmp_path / "events.jsonl")
    with events_lib.capture(epath):
        rows = experiments.compare(configs, gmm, batch="off")
    assert _counter("sweep.diverged") - div0 == 1
    by = {s.label: s for s in rows}
    assert by["boom"].status == "diverged"
    assert by["boom"].time_to_target is None
    # diverged row renders distinctly and serializes as STRICT json
    assert "diverged" in experiments.format_table(rows)
    path = str(tmp_path / "rows.json")
    experiments.save_summaries(rows, path)

    def _no_nan(tok):
        raise AssertionError(f"non-strict JSON token {tok!r}")

    loaded = json.load(open(path), parse_constant=_no_nan)
    boom_row = [r for r in loaded if r["label"] == "boom"][0]
    assert boom_row["status"] == "diverged"
    assert boom_row["final_train_loss"] is None
    # quarantine: every other row identical to the sweep without boom
    base_by = {s.label: s for s in without}
    for label in base_by:
        assert journal_lib.science_row(
            base_by[label].row()
        ) == journal_lib.science_row(by[label].row())
    # the divergence was announced on the warning channel
    kinds = [
        rec["kind"]
        for rec in map(json.loads, open(epath))
        if rec["type"] == "warning"
    ]
    assert "divergence" in kinds


def test_diverged_rows_resume_as_diverged(gmm, tmp_path):
    """Divergence is deterministic under the journal key: a resumed sweep
    rehydrates the diverged row instead of re-burning the rounds."""
    configs = {"boom": _base(scheme="avoidstragg", lr_schedule=1e12),
               "naive": _base()}
    jdir = str(tmp_path / "j")
    j = journal_lib.SweepJournal(jdir, resume=False)
    first = experiments.compare(dict(configs), gmm, batch="off", journal=j)
    j.close()
    j2 = journal_lib.SweepJournal(jdir, resume=True)
    before = _counter("sweep_journal.resumed")
    again = experiments.compare(dict(configs), gmm, batch="off", journal=j2)
    assert _counter("sweep_journal.resumed") == before + 2
    assert [s.status for s in again] == [s.status for s in first]
    assert _science(first) == _science(again)
    j2.close()


def test_baseline_suite_target_survives_divergence():
    """The suite-4 shared-target min() must quarantine diverged rows
    instead of propagating NaN into every time_to_target (and must not
    crash when rows diverge)."""
    s_ok = experiments.RunSummary(
        label="a", config=_base(), sim_total_time=1.0,
        sim_steps_per_sec=1.0, real_steps_per_sec=1.0,
        final_train_loss=0.5, final_test_loss=0.5, final_auc=0.9,
        time_to_target=None, training_loss=np.array([1.0, 0.5]),
        timeset=np.array([1.0, 1.0]),
    )
    s_bad = experiments.RunSummary(
        label="b", config=_base(), sim_total_time=1.0,
        sim_steps_per_sec=1.0, real_steps_per_sec=1.0,
        final_train_loss=float("nan"), final_test_loss=float("nan"),
        final_auc=float("nan"), time_to_target=None,
        training_loss=np.array([1.0, np.nan]),
        timeset=np.array([1.0, 1.0]), status="diverged",
    )
    target = experiments._default_target_loss({"a": s_ok, "b": s_bad})
    assert target is not None and np.isfinite(target)
    assert experiments._default_target_loss({"b": s_bad}) is None


# ---------------------------------------------------------------------------
# compare() shape validation (satellite: asserts vanish under python -O)


def test_compare_shape_mismatch_names_labels(gmm):
    configs = {"a": _base(rounds=6), "b": _base(rounds=9)}
    with pytest.raises(ValueError) as ei:
        experiments.compare(configs, gmm)
    msg = str(ei.value)
    assert "'a'" in msg and "'b'" in msg
    assert "rounds=6" in msg and "rounds=9" in msg
    with pytest.raises(ValueError, match="at least one config"):
        experiments.compare({}, gmm)
    with pytest.raises(ValueError, match="at least one"):
        experiments.straggler_sweep(_base(), gmm, {})


# ---------------------------------------------------------------------------
# checkpoint hardening (satellite: torn round_N directories)


def test_truncated_checkpoint_falls_back(gmm, tmp_path):
    """A corrupt newest checkpoint (truncated mid-save) must not kill the
    resume: restore_latest falls back to the next-older valid checkpoint,
    with a warning event and a checkpoint.invalid count."""
    from erasurehead_tpu.train import checkpoint

    cfg = _base(rounds=12, n_stragglers=0, compute_mode="faithful")
    full = trainer.train(cfg, gmm)
    ckdir = str(tmp_path / "ck")
    trainer.train(cfg, gmm, checkpoint_dir=ckdir, checkpoint_every=4)
    assert checkpoint.latest(ckdir).endswith("round_8")
    # torn DATA: the layout is committed but the manifest is truncated
    for p in glob.glob(os.path.join(ckdir, "round_8", "manifest.ocdbt")):
        with open(p, "r+b") as f:
            f.truncate(3)
    inv0 = _counter("checkpoint.invalid")
    epath = str(tmp_path / "events.jsonl")
    with events_lib.capture(epath):
        resumed = trainer.train(
            cfg, gmm, checkpoint_dir=ckdir, checkpoint_every=4, resume=True
        )
    assert resumed.start_round == 4
    assert _counter("checkpoint.invalid") > inv0
    kinds = [
        rec["kind"]
        for rec in map(json.loads, open(epath))
        if rec["type"] == "warning"
    ]
    assert "checkpoint_invalid" in kinds
    # the fallback resume reproduces the uninterrupted run's tail
    np.testing.assert_allclose(
        np.asarray(resumed.params_history),
        np.asarray(full.params_history)[4:],
        atol=1e-5,
    )
    # structural tear: no commit marker -> latest() skips it entirely
    os.remove(os.path.join(ckdir, "round_8", "_CHECKPOINT_METADATA"))
    assert checkpoint.latest(ckdir).endswith("round_4")


# ---------------------------------------------------------------------------
# telemetry must not fail silently (satellite: trainer._memory_analysis)


def test_memory_analysis_failure_counted_and_warned_once(capsys):
    from erasurehead_tpu.obs import metrics as metrics_lib

    class RaisingSink:
        def memory_analysis(self):
            raise RuntimeError("backend says no")

    metrics_lib.reset_warnings()
    before = _counter("telemetry.emit_errors")
    assert trainer._memory_analysis(RaisingSink()) is None
    assert trainer._memory_analysis(RaisingSink()) is None
    assert _counter("telemetry.emit_errors") - before == 2
    err = capsys.readouterr().err
    assert err.count("memory_analysis unavailable") == 1


# ---------------------------------------------------------------------------
# journal file <-> obs tooling


def test_journal_validator_catches_bad_records(tmp_path):
    path = str(tmp_path / "sweep_journal.jsonl")
    good = {
        "type": "sweep_trajectory", "seq": 0, "t": 0.0, "key": "abc",
        "label": "x", "status": "ok", "row": {"final_train_loss": 0.1},
    }
    bad_status = dict(good, seq=1, status="exploded")
    bad_row = dict(good, seq=2, row=[1, 2])
    bad_key = dict(good, seq=3, key="")
    with open(path, "w") as f:
        for rec in (good, bad_status, bad_row, bad_key):
            f.write(json.dumps(rec) + "\n")
    errors = events_lib.validate_file(path)
    assert len(errors) == 3
    assert any("status" in e for e in errors)
    assert any("row" in e for e in errors)
    assert any("key" in e for e in errors)


def test_report_renders_journal_rows(gmm, tmp_path, capsys):
    from erasurehead_tpu.obs import report

    configs = {"naive": _base(),
               "boom": _base(scheme="avoidstragg", lr_schedule=1e12)}
    jdir = str(tmp_path / "j")
    j = journal_lib.SweepJournal(jdir, resume=False)
    experiments.compare(configs, gmm, batch="off", journal=j)
    j.close()
    out = report.render([j.path])
    assert "sweep journal: 2 trajectory record(s), 1 DIVERGED" in out
    assert "boom" in out and "diverged" in out


def test_cli_sweep_subcommand_dispatches(monkeypatch):
    from erasurehead_tpu import cli
    from erasurehead_tpu.train import experiments as experiments_mod

    seen = {}

    def fake_main(argv):
        seen["argv"] = list(argv)
        return 0

    monkeypatch.setattr(experiments_mod, "main", fake_main)
    assert cli.main(["sweep", "--rounds", "3"]) == 0
    assert seen["argv"] == ["--rounds", "3"]


@pytest.mark.slow
def test_chaos_smoke_subprocess():
    """The full kill->resume cycle with REAL process deaths (what `make
    chaos-smoke` runs); slow-marked — three jax subprocess boots."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "chaos_sweep.py")],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=600,
    )
    assert p.returncode == 0, p.stdout + p.stderr
    assert '"status": "PASS"' in p.stdout
