"""Experiment-harness and checkpoint/resume tests."""

import dataclasses
import json
import os

import numpy as np
import pytest

from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.train import checkpoint, experiments, trainer
from erasurehead_tpu.utils.config import RunConfig

W = 8


@pytest.fixture(scope="module")
def gmm():
    return generate_gmm(512, 24, n_partitions=W, seed=0)


def _base(**kw):
    d = dict(
        scheme="naive", n_workers=W, n_stragglers=1, rounds=15,
        n_rows=512, n_cols=24, update_rule="AGD", lr_schedule=2.0,
        add_delay=True, seed=3,
    )
    d.update(kw)
    return RunConfig(**d)


def test_compare_pairs_schemes_on_one_schedule(gmm, tmp_path):
    configs = {
        "naive": _base(),
        "agc_c4": _base(scheme="approx", num_collect=4),
        "egc_mds": _base(scheme="cyccoded", n_stragglers=2),
    }
    summaries = experiments.compare(configs, gmm)
    by = {s.label: s for s in summaries}
    # paired schedule: AGC's simulated clock strictly beats naive's
    assert by["agc_c4"].sim_total_time < by["naive"].sim_total_time
    assert by["egc_mds"].sim_total_time <= by["naive"].sim_total_time
    # exact schemes converge to the same loss
    assert abs(by["egc_mds"].final_train_loss - by["naive"].final_train_loss) < 1e-3
    # time-to-target exists for the baseline by construction
    assert by["naive"].time_to_target is not None
    # serialization + table
    path = str(tmp_path / "summary.json")
    experiments.save_summaries(summaries, path)
    rows = json.load(open(path))
    assert len(rows) == 3 and rows[0]["sim_steps_per_sec"] > 0
    table = experiments.format_table(summaries)
    assert "naive" in table and "agc_c4" in table


def test_straggler_sweep(gmm):
    base = _base(rounds=10)
    summaries = experiments.straggler_sweep(
        base, gmm,
        {"avoidstragg": [1, 2], "approx": [1, 3]},
    )
    labels = {s.label for s in summaries}
    assert labels == {"avoidstragg_s1", "avoidstragg_s2", "approx_s1", "approx_s3"}
    # more stragglers ignored => faster simulated iterations for avoidstragg
    by = {s.label: s for s in summaries}
    assert (
        by["avoidstragg_s2"].sim_total_time <= by["avoidstragg_s1"].sim_total_time
    )


def test_time_to_target_loss():
    loss = np.array([1.0, 0.5, 0.2, 0.1])
    times = np.array([1.0, 1.0, 1.0, 1.0])
    assert experiments.time_to_target_loss(loss, times, 0.5) == 2.0
    assert experiments.time_to_target_loss(loss, times, 0.05) is None


# ---------------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------------


def test_checkpointed_run_matches_single_scan(gmm, tmp_path):
    cfg = _base(rounds=12)
    plain = trainer.train(cfg, gmm)
    ckdir = str(tmp_path / "ck")
    chunked = trainer.train(
        cfg, gmm, checkpoint_dir=ckdir, checkpoint_every=5
    )
    assert np.allclose(
        np.asarray(plain.params_history),
        np.asarray(chunked.params_history),
        atol=1e-6,
    )
    # checkpoints at rounds 5 and 10 exist
    assert checkpoint.latest(ckdir).endswith("round_10")


def test_resume_from_checkpoint(gmm, tmp_path):
    cfg = _base(rounds=12)
    full = trainer.train(cfg, gmm)
    ckdir = str(tmp_path / "ck2")
    trainer.train(cfg, gmm, checkpoint_dir=ckdir, checkpoint_every=4)
    # wipe the last chunk's knowledge: resume from round 8 checkpoint
    resumed = trainer.train(
        cfg, gmm, checkpoint_dir=ckdir, checkpoint_every=4, resume=True
    )
    # resumed history covers rounds 8..12 and matches the full run's tail
    hist = np.asarray(resumed.params_history)
    assert hist.shape[0] == 4
    assert np.allclose(
        hist, np.asarray(full.params_history)[8:], atol=1e-5
    )


def test_checkpoint_roundtrip(tmp_path):
    from erasurehead_tpu.train.optimizer import OptState, init_state
    import jax.numpy as jnp

    state = init_state({"w": jnp.arange(4.0), "b": jnp.ones(())})
    path = str(tmp_path / "ck3" / "round_3")
    checkpoint.save(path, state, 3)
    back, rnd = checkpoint.restore(path, state)
    assert rnd == 3
    assert np.allclose(back.params["w"], state.params["w"])


def test_resumed_artifacts_stay_aligned(gmm, tmp_path):
    """A resumed run's five artifacts must all cover the same window
    [start_round, rounds) — the clocks are sliced to match the eval curves
    and the manifest records the offset (so nobody mistakes a resumed loss
    curve for a full one)."""
    from erasurehead_tpu.models.glm import LogisticModel
    from erasurehead_tpu.train import artifacts, evaluate
    from erasurehead_tpu.utils.config import ModelKind

    cfg = _base(rounds=12)
    ckdir = str(tmp_path / "ck3")
    trainer.train(cfg, gmm, checkpoint_dir=ckdir, checkpoint_every=4)
    resumed = trainer.train(
        cfg, gmm, checkpoint_dir=ckdir, checkpoint_every=4, resume=True
    )
    assert resumed.start_round == 8
    n = resumed.n_train
    ev = evaluate.replay(
        LogisticModel(), ModelKind.LOGISTIC, resumed.params_history,
        gmm.X_train[:n], gmm.y_train[:n], gmm.X_test, gmm.y_test,
    )
    out = str(tmp_path / "res")
    paths = artifacts.write_run_artifacts(resumed, ev, out)
    lens = {
        name: np.atleast_1d(np.loadtxt(paths[name])).shape[0]
        for name in ("training_loss", "testing_loss", "auc",
                     "timeset", "worker_timeset")
    }
    assert set(lens.values()) == {4}, lens
    manifest = json.load(open(paths["manifest"]))
    assert manifest["start_round"] == 8
    # the sliced timeset rows are the full schedule's tail
    full_t = trainer.train(cfg, gmm).timeset
    np.testing.assert_allclose(np.loadtxt(paths["timeset"]), full_t[8:],
                               atol=5e-4)  # save_vector writes %5.3f-ish


def test_resume_from_checkpoint_pytree_model(gmm, tmp_path):
    """Checkpoint/resume with pytree params (MLP): optimizer-state leaves
    restore structurally and the resumed tail bit-matches the full run —
    the orbax path must be model-agnostic, not beta-vector-shaped."""
    import jax

    cfg = _base(rounds=12, model="mlp", update_rule="GD", lr_schedule=0.5)
    full = trainer.train(cfg, gmm)
    ckdir = str(tmp_path / "ckm")
    trainer.train(cfg, gmm, checkpoint_dir=ckdir, checkpoint_every=4)
    resumed = trainer.train(
        cfg, gmm, checkpoint_dir=ckdir, checkpoint_every=4, resume=True
    )
    assert resumed.start_round == 8
    for a, b in zip(
        jax.tree.leaves(full.params_history),
        jax.tree.leaves(resumed.params_history),
    ):
        assert np.asarray(b).shape[0] == 4
        np.testing.assert_allclose(
            np.asarray(a)[8:], np.asarray(b), atol=1e-5
        )
