"""Scheme-registry tests (ISSUE 8): round-trip equivalence of every
builtin descriptor against the rule/factory functions the old if/elif
spines called, the grep-enforced no-dispatch-outside-schemes/ contract,
entry-point discovery of third-party schemes, and the registry-level
optimal decoder (decode=fixed|optimal)."""

import dataclasses
import os
import re

import numpy as np
import pytest

from erasurehead_tpu import schemes
from erasurehead_tpu.ops import codes
from erasurehead_tpu.parallel import collect, failures, straggler
from erasurehead_tpu.utils.config import ExtensionScheme, RunConfig, Scheme

R, W, S = 12, 6, 1  # rounds, workers, stragglers ((S+1) | W for FRC)


@pytest.fixture(scope="module")
def arrivals():
    return straggler.arrival_schedule(R, W, add_delay=True)


def _cfg(scheme, **kw):
    base = dict(
        scheme=scheme, n_workers=W, n_stragglers=S, rounds=R,
        n_rows=96, n_cols=8, lr_schedule=1.0, seed=0,
    )
    base.update(kw)
    return RunConfig(**base)


#: every builtin: (scheme name, config overrides, direct layout factory,
#: direct collection rule) — the exact calls the pre-registry dispatch made
def _builtin_cases():
    return [
        ("naive", {},
         lambda c: codes.uncoded_layout(W),
         lambda t, lay, c: collect.collect_all(t)),
        ("cyccoded", {},
         lambda c: codes.cyclic_mds_layout(W, S, seed=0),
         lambda t, lay, c: collect.collect_first_k_mds(t, lay.B, S)),
        ("repcoded", {},
         lambda c: codes.frc_layout(W, S),
         lambda t, lay, c: collect.collect_frc(t, lay.groups)),
        ("approx", {"num_collect": 4},
         lambda c: codes.frc_layout(W, S),
         lambda t, lay, c: collect.collect_agc(t, lay.groups, 4)),
        ("avoidstragg", {},
         lambda c: codes.uncoded_layout(W, n_stragglers=S),
         lambda t, lay, c: collect.collect_avoidstragg(t, S)),
        ("randreg", {"num_collect": 4},
         lambda c: codes.random_regular_layout(W, S, seed=0),
         lambda t, lay, c: collect.collect_first_k_optimal(t, lay.B, 4)),
        ("deadline", {"deadline": 0.8},
         lambda c: codes.uncoded_layout(W),
         lambda t, lay, c: collect.collect_deadline(t, 0.8)),
        ("partialcyccoded", {"partitions_per_worker": S + 2},
         lambda c: codes.partial_cyclic_layout(W, S + 2, S, seed=0),
         lambda t, lay, c: collect.collect_partial(t, lay, "mds")),
        ("partialrepcoded", {"partitions_per_worker": S + 2},
         lambda c: codes.partial_frc_layout(W, S + 2, S),
         lambda t, lay, c: collect.collect_partial(t, lay, "frc")),
    ]


@pytest.mark.parametrize(
    "scheme,kw,layout_fn,rule_fn",
    _builtin_cases(),
    ids=[c[0] for c in _builtin_cases()],
)
def test_registry_round_trip_bitwise(scheme, kw, layout_fn, rule_fn, arrivals):
    """Descriptor path == direct-call path, bitwise: layout arrays and the
    full collection schedule (the old dispatch's exact outputs)."""
    from erasurehead_tpu.train import trainer

    cfg = _cfg(scheme, **kw)
    lay_reg = trainer.build_layout(cfg)
    lay_dir = layout_fn(cfg)
    assert np.array_equal(lay_reg.assignment, lay_dir.assignment)
    assert np.array_equal(lay_reg.coeffs, lay_dir.coeffs)
    assert np.array_equal(lay_reg.slot_is_coded, lay_dir.slot_is_coded)
    if lay_dir.B is not None:
        assert np.array_equal(lay_reg.B, lay_dir.B)
    if lay_dir.groups is not None:
        assert np.array_equal(lay_reg.groups, lay_dir.groups)

    sched_reg = collect.build_schedule(
        cfg.scheme, arrivals, lay_reg, num_collect=cfg.num_collect,
        deadline=cfg.deadline,
    )
    sched_dir = rule_fn(arrivals, lay_dir, cfg)
    assert np.array_equal(sched_reg.message_weights, sched_dir.message_weights)
    assert np.array_equal(sched_reg.sim_time, sched_dir.sim_time)
    assert np.array_equal(sched_reg.worker_times, sched_dir.worker_times)
    assert np.array_equal(sched_reg.collected, sched_dir.collected)


#: the retired grep body of the dispatch test — kept to PROVE the AST
#: checker is strictly stronger (the regression fixture below matches
#: zero lines against it)
_OLD_GREP = re.compile(r"^\s*(?:el)?if\b.*\bscheme\b\s*(?:==|!=|\bin\b)")


def test_no_scheme_dispatch_outside_schemes_package():
    """Acceptance criterion, AST-grade (ISSUE 10): zero scheme-dispatch
    sites outside erasurehead_tpu/schemes/ — now via the
    registry-dispatch checker (erasurehead_tpu/analysis/dispatch.py),
    which also sees the string-compare, dict-keyed, ternary and
    match-statement forms the old grep body of this test could not."""
    from erasurehead_tpu.analysis import runner as lint_runner

    pkg_root = os.path.dirname(
        os.path.dirname(os.path.abspath(schemes.__file__))
    )
    report = lint_runner.lint_paths(
        [pkg_root], checkers=["registry-dispatch"]
    )
    offenders = [f.render() for f in report.findings if not f.suppressed]
    assert not offenders, (
        "scheme dispatch outside schemes/ (use the registry):\n"
        + "\n".join(offenders)
    )


def test_dispatch_checker_catches_what_the_grep_missed():
    """Regression fixture: dict-keyed and `.value ==` ternary dispatch
    (the exact forms train/artifacts.py shipped with for 7 PRs) match
    ZERO lines of the old grep pattern but are flagged by the checker."""
    from erasurehead_tpu.analysis import runner as lint_runner

    fixture = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "fixtures", "lint", "dispatch_grep_miss.py",
    )
    with open(fixture) as f:
        grep_hits = [line for line in f if _OLD_GREP.search(line)]
    assert grep_hits == [], "fixture no longer evades the old grep"
    report = lint_runner.lint_paths(
        [fixture], checkers=["registry-dispatch"]
    )
    findings = [f for f in report.findings if not f.suppressed]
    assert len(findings) >= 3, report.render()


def test_every_builtin_registered_and_flagged():
    names = schemes.names()
    n_builtin = len(list(Scheme))
    assert {s.value for s in Scheme} == set(names[:n_builtin])
    for s in Scheme:
        desc = schemes.get(s)
        assert desc.builtin
        assert desc.name == s.value
        caps = desc.capabilities()
        assert isinstance(caps["exact"], bool)
    # capability spot checks the rest of the framework relies on
    assert schemes.get("partialcyccoded").supports_measured is False
    assert schemes.get("partialrepcoded").partial is True
    assert schemes.get("approx").needs_num_collect is True
    assert schemes.get("deadline").needs_deadline is True
    assert schemes.get("cyccoded").exact is True
    assert schemes.get("cyccoded").seed_dependent_layout is True
    assert schemes.get("approx").optimal_decode is not None
    assert schemes.get("partialcyccoded").optimal_decode is None


def test_unknown_scheme_error_names_registry():
    with pytest.raises(ValueError, match="registered schemes"):
        RunConfig(scheme="definitely-not-a-scheme")
    with pytest.raises(ValueError, match="registered schemes"):
        schemes.get("definitely-not-a-scheme")


def test_register_refuses_silent_shadowing():
    with pytest.raises(ValueError, match="already registered"):
        schemes.register(
            schemes.SchemeDescriptor(
                name="naive",
                build_layout=lambda cfg: codes.uncoded_layout(cfg.n_workers),
                build_schedule=lambda t, lay, **kw: collect.collect_all(t),
            )
        )
    with pytest.raises(ValueError, match="builtin"):
        schemes.unregister("naive")


# ---------------------------------------------------------------------------
# third-party schemes: direct registration + entry-point discovery
# ---------------------------------------------------------------------------


def _toy_descriptor(name):
    """A minimal but complete third-party scheme: uncoded layout, collect
    everyone (a registered alias of naive, structurally)."""
    return schemes.SchemeDescriptor(
        name=name,
        summary="toy third-party scheme (tests)",
        build_layout=lambda cfg: codes.uncoded_layout(cfg.n_workers),
        build_schedule=lambda t, lay, **kw: collect.collect_all(t),
        feasibility=lambda lay, dead, **kw: (
            (~dead).all(axis=1), "needs all W workers"
        ),
        optimal_decode=collect.optimal_decode_schedule,
        exact=True,
    )


def test_third_party_scheme_registers_and_trains():
    name = "toyuniform"
    schemes.register(_toy_descriptor(name))
    try:
        cfg = _cfg(name)
        assert isinstance(cfg.scheme, ExtensionScheme)
        assert cfg.scheme.value == name  # quacks like the enum
        from erasurehead_tpu.data.synthetic import generate_gmm
        from erasurehead_tpu.train import experiments, trainer

        lay = trainer.build_layout(cfg)
        assert lay.n_partitions == W
        ds = generate_gmm(96, 8, W, seed=0)
        rows = experiments.compare(
            {"toy": _cfg(name, rounds=3), "naive": _cfg("naive", rounds=3)},
            ds,
        )
        by_label = {s.label: s for s in rows}
        # structurally identical to naive: identical losses under the
        # shared arrival schedule
        assert by_label["toy"].final_train_loss == pytest.approx(
            by_label["naive"].final_train_loss
        )
    finally:
        schemes.unregister(name)
    with pytest.raises(ValueError, match="registered schemes"):
        RunConfig(scheme=name)


def test_entry_point_scheme_shows_up_in_cli_choices(monkeypatch):
    """The satellite contract: a scheme published under the
    erasurehead_tpu.schemes entry-point group appears in registry names,
    CLI --scheme choices, and trains through compare()."""
    import importlib.metadata as md

    name = "toyep"

    class FakeEP:
        def load(self):
            return lambda: _toy_descriptor(name)  # factory form

    FakeEP.name = name

    class FakeEPS:
        def select(self, group=None):
            return [FakeEP()] if group == schemes.ENTRY_POINT_GROUP else []

    monkeypatch.setattr(md, "entry_points", lambda: FakeEPS())
    added = schemes.load_entry_points(force=True)
    try:
        assert name in added
        assert name in schemes.names()
        from erasurehead_tpu import cli

        parser = cli._flags_parser()
        choices = next(
            a.choices for a in parser._actions if a.dest == "scheme"
        )
        assert name in choices
        from erasurehead_tpu.data.synthetic import generate_gmm
        from erasurehead_tpu.train import experiments

        ds = generate_gmm(96, 8, W, seed=0)
        rows = experiments.compare({name: _cfg(name, rounds=3)}, ds)
        assert rows[0].status == "ok"
    finally:
        schemes.unregister(name)


def test_broken_entry_point_is_isolated(monkeypatch):
    import importlib.metadata as md

    class BadEP:
        name = "broken"

        def load(self):
            raise RuntimeError("boom")

    class FakeEPS:
        def select(self, group=None):
            return [BadEP()] if group == schemes.ENTRY_POINT_GROUP else []

    monkeypatch.setattr(md, "entry_points", lambda: FakeEPS())
    assert schemes.load_entry_points(force=True) == []
    assert "broken" not in schemes.names()


# ---------------------------------------------------------------------------
# decode=optimal (arXiv:2006.09638)
# ---------------------------------------------------------------------------


def _decode_errors(scheme, kw, arrivals, decode):
    from erasurehead_tpu.obs import decode as obs_decode
    from erasurehead_tpu.train import trainer

    cfg = _cfg(scheme, **kw)
    lay = trainer.build_layout(cfg)
    sched = collect.build_schedule(
        cfg.scheme, arrivals, lay, num_collect=cfg.num_collect,
        deadline=cfg.deadline, decode=decode,
    )
    return obs_decode.decode_error_series(lay, sched.message_weights)


@pytest.mark.parametrize(
    "scheme,kw",
    [
        ("approx", {"num_collect": 4}),
        ("randreg", {"num_collect": 4}),
        ("avoidstragg", {}),
        ("deadline", {"deadline": 0.8}),
    ],
)
def test_optimal_decode_error_leq_fixed_on_approximate(scheme, kw, arrivals):
    fixed = _decode_errors(scheme, kw, arrivals, "fixed")
    opt = _decode_errors(scheme, kw, arrivals, "optimal")
    assert (opt <= fixed + 1e-9).all()


def test_optimal_decode_strictly_improves_rescale_schemes(arrivals):
    """avoidstragg/deadline decode with a uniform W/collected rescale; the
    lstsq fit is strictly tighter whenever any worker is missing."""
    for scheme, kw in (("avoidstragg", {}), ("deadline", {"deadline": 0.8})):
        fixed = _decode_errors(scheme, kw, arrivals, "fixed")
        opt = _decode_errors(scheme, kw, arrivals, "optimal")
        straggling = fixed > 0
        assert straggling.any()  # the schedule genuinely straggles
        assert (opt[straggling] < fixed[straggling]).all()


@pytest.mark.parametrize("scheme,kw", [
    ("naive", {}),
    ("cyccoded", {}),
    ("repcoded", {}),
])
def test_optimal_decode_zero_delta_on_exact(scheme, kw, arrivals):
    fixed = _decode_errors(scheme, kw, arrivals, "fixed")
    opt = _decode_errors(scheme, kw, arrivals, "optimal")
    assert (fixed == 0.0).all()
    assert (opt == 0.0).all()


def test_optimal_decode_noop_on_partial(arrivals):
    """Partial schemes carry no optimal_decode hook: the schedule is
    byte-for-byte the fixed one."""
    cfg = _cfg("partialrepcoded", partitions_per_worker=S + 2)
    from erasurehead_tpu.train import trainer

    lay = trainer.build_layout(cfg)
    f = collect.build_schedule(cfg.scheme, arrivals, lay)
    o = collect.build_schedule(cfg.scheme, arrivals, lay, decode="optimal")
    assert np.array_equal(f.message_weights, o.message_weights)


def test_decode_field_validation():
    with pytest.raises(ValueError, match="decode must be fixed/optimal"):
        _cfg("naive", decode="bogus")
    with pytest.raises(ValueError, match="decode must be fixed/optimal"):
        collect.build_schedule(
            "naive",
            np.zeros((2, W)),
            codes.uncoded_layout(W),
            decode="bogus",
        )


def test_train_dynamic_refuses_optimal_decode():
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import trainer

    ds = generate_gmm(96, 8, W, seed=0)
    with pytest.raises(ValueError, match="decode='optimal'"):
        trainer.train_dynamic(_cfg("naive", rounds=2, decode="optimal"), ds)


def test_optimal_decode_improves_trained_decode_error_column():
    """End-to-end: train() with decode=optimal reports a decode_error
    series <= the fixed run's, round for round, on the same arrivals."""
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import trainer

    ds = generate_gmm(96, 8, W, seed=0)
    arr = straggler.arrival_schedule(4, W, add_delay=True)
    res_f = trainer.train(
        _cfg("avoidstragg", rounds=4), ds, arrivals=arr, measure=False
    )
    res_o = trainer.train(
        _cfg("avoidstragg", rounds=4, decode="optimal"), ds, arrivals=arr,
        measure=False,
    )
    assert (res_o.decode_error <= res_f.decode_error + 1e-9).all()
    assert res_o.decode_error.sum() < res_f.decode_error.sum()
    # the stop condition is untouched: identical clocks and collected sets
    assert np.array_equal(res_o.timeset, res_f.timeset)
    assert np.array_equal(res_o.collected, res_f.collected)


def test_cohort_signature_consults_descriptor_batchability():
    from erasurehead_tpu.train import trainer

    cfg = _cfg("naive", compute_mode="deduped")
    assert trainer.cohort_signature(cfg) is not None
    name = "toyunbatchable"
    desc = dataclasses.replace(_toy_descriptor(name), cohort_batchable=False)
    schemes.register(desc)
    try:
        assert trainer.cohort_signature(_cfg(name)) is None
    finally:
        schemes.unregister(name)
