"""Adaptive-controller tests (ISSUE 8): regime-shift injection, bandit
decision determinism and kill→resume replay invariance under
ERASUREHEAD_CHAOS, adapt-event journaling + validation, and arm
compatibility (no-re-upload) enforcement."""

import json
import os

import numpy as np
import pytest

from erasurehead_tpu import adapt
from erasurehead_tpu.adapt.controller import (
    AdaptiveController,
    Arm,
    ChunkStats,
    ControllerConfig,
)
from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu.parallel import straggler
from erasurehead_tpu.utils import chaos as chaos_lib
from erasurehead_tpu.utils.config import RunConfig

W, R = 6, 40


@pytest.fixture(autouse=True)
def _clean_chaos(monkeypatch):
    monkeypatch.delenv(chaos_lib.CHAOS_ENV, raising=False)
    monkeypatch.delenv(chaos_lib.REGIME_ENV, raising=False)
    chaos_lib.reset()
    yield
    chaos_lib.reset()


def _cfg(**kw):
    base = dict(
        scheme="naive", n_workers=W, n_stragglers=1, rounds=R,
        n_rows=96, n_cols=8, lr_schedule=1.0, add_delay=True,
        compute_mode="deduped", update_rule="GD", seed=0,
    )
    base.update(kw)
    return RunConfig(**base)


def _arms():
    return [
        Arm("naive"),
        Arm("avoidstragg"),
        Arm("deadline", deadline=1.5),
    ]


def _shifted_arrivals(rounds=R, shift_round=R // 2, slowdown=8.0):
    shift = straggler.RegimeShift(
        kind="adversary", round=shift_round, worker=0, slowdown=slowdown
    )
    return straggler.arrival_schedule(
        rounds, W, add_delay=True, regime=shift
    )


# ---------------------------------------------------------------------------
# regime-shift injection (parallel/straggler.py + utils/chaos.py)
# ---------------------------------------------------------------------------


def test_regime_shift_deterministic_and_localized():
    base = straggler.reference_delay_schedule(R, W)
    shift = straggler.RegimeShift(kind="heavytail", round=20, alpha=1.2)
    a = straggler.apply_regime_shift(base, shift)
    b = straggler.apply_regime_shift(base, shift)
    assert np.array_equal(a, b)  # seeded per round, fully deterministic
    assert np.array_equal(a[:20], base[:20])  # pre-shift untouched
    assert not np.array_equal(a[20:], base[20:])
    # heavy tail: the post-shift max delay dwarfs the exponential stream's
    assert a[20:].max() > 2 * base.max()


def test_adversary_regime_applies_without_delays():
    shift = straggler.RegimeShift(
        kind="adversary", round=5, worker=2, slowdown=4.0
    )
    arr = straggler.arrival_schedule(10, W, add_delay=False, regime=shift)
    assert (arr[:5] == 0).all()
    assert (arr[5:, 2] == 4.0).all()
    assert (np.delete(arr[5:], 2, axis=1) == 0).all()


def test_regime_spec_parsing():
    s = chaos_lib.parse_regime("heavytail:30:1.5")
    assert (s.kind, s.round, s.alpha) == ("heavytail", 30, 1.5)
    s = chaos_lib.parse_regime("adversary:10:3:2.5")
    assert (s.kind, s.round, s.worker, s.slowdown) == ("adversary", 10, 3, 2.5)
    for bad in ("heavytail", "nope:3", "adversary:x"):
        with pytest.raises(ValueError):
            chaos_lib.parse_regime(bad)
    with pytest.raises(ValueError):
        straggler.RegimeShift(kind="nope", round=1)


def test_regime_env_threads_into_default_arrivals(monkeypatch):
    from erasurehead_tpu.train import trainer

    cfg = _cfg(rounds=10)
    plain = trainer.default_arrivals(cfg)
    monkeypatch.setenv(chaos_lib.REGIME_ENV, "adversary:4:1:9")
    shifted = trainer.default_arrivals(cfg)
    assert np.array_equal(shifted[:4], plain[:4])
    assert np.allclose(shifted[4:, 1], plain[4:, 1] + 9.0)
    monkeypatch.delenv(chaos_lib.REGIME_ENV)
    # unset -> byte-for-byte the stationary reference stream
    assert np.array_equal(trainer.default_arrivals(cfg), plain)


# ---------------------------------------------------------------------------
# controller unit behavior
# ---------------------------------------------------------------------------


def _stats(sim_per_round, err=0.0, mean=0.5):
    return ChunkStats(
        n_rounds=5, sim_time=5 * sim_per_round, decode_error_mean=err,
        arrival_mean=mean, arrival_p90=mean * 2,
    )


def test_controller_warmup_then_exploit():
    ctl = AdaptiveController(_arms(), ControllerConfig(epsilon=0.0, seed=0))
    rewards = {0: 2.0, 1: 0.5, 2: 1.0}  # sim/round: arm 1 is fastest
    for _ in range(6):
        idx, _reason = ctl.choose()
        ctl.observe(idx, _stats(rewards[idx]))
    reasons = [d["reason"] for d in ctl.decisions]
    assert reasons[:3] == ["warmup", "warmup", "warmup"]
    assert all(r == "exploit" for r in reasons[3:])
    assert all(d["arm"] == "avoidstragg" for d in ctl.decisions[3:])


def test_controller_decisions_deterministic():
    def run():
        ctl = AdaptiveController(
            _arms(), ControllerConfig(epsilon=0.3, seed=7)
        )
        for i in range(12):
            idx, _ = ctl.choose()
            ctl.observe(idx, _stats(1.0 + idx, err=0.01 * idx))
        return ctl.decisions

    assert run() == run()


def test_controller_regime_shift_resets_values():
    ctl = AdaptiveController(_arms(), ControllerConfig(epsilon=0.0, seed=0))
    for _ in range(4):
        idx, _ = ctl.choose()
        ctl.observe(idx, _stats(1.0, mean=0.5))
    idx, _ = ctl.choose()
    shift = ctl.observe(idx, _stats(9.0, mean=5.0))  # 10x arrival jump
    assert shift == "regime_shift"
    snap = ctl.snapshot()
    # all arms but the observed one restart from scratch
    assert sum(1 for w in snap["weights"] if w > 0) == 1
    # the next choices re-explore (warm-up pass tagged regime_shift)
    idx2, reason2 = ctl.choose()
    assert reasons_ok(reason2)


def reasons_ok(reason):
    from erasurehead_tpu.obs.events import ADAPT_REASONS

    return reason in ADAPT_REASONS


def test_controller_rejects_bad_config():
    with pytest.raises(ValueError):
        ControllerConfig(chunk_rounds=0)
    with pytest.raises(ValueError):
        ControllerConfig(discount=1.5)
    with pytest.raises(ValueError):
        ControllerConfig(shift_factor=1.0)
    with pytest.raises(ValueError):
        ControllerConfig(prior_weight=0.0)
    with pytest.raises(ValueError):
        AdaptiveController([], ControllerConfig())
    with pytest.raises(ValueError, match="duplicate"):
        AdaptiveController([Arm("naive"), Arm("naive")], ControllerConfig())
    with pytest.raises(ValueError, match="unknown arms"):
        AdaptiveController(
            _arms(), ControllerConfig(), priors={"nonesuch": -1.0}
        )


def test_priors_skip_cold_start_exploration():
    """The ISSUE 12 cold-start regression pin: unprimed, the first
    len(arms) chunks are burned on warm-up — one forced visit per arm,
    including arms the registry's simulation could already rule out
    under the observed regime. With what-if priors, warm-up shrinks to
    exactly the arms the surface could NOT rank (zero when it ranked
    them all) and the first free decision exploits the simulated best
    arm."""
    arms = _arms()
    rewards = {0: 2.0, 1: 0.5, 2: 1.0}  # arm 1 (avoidstragg) is best

    def run(priors):
        ctl = AdaptiveController(
            arms, ControllerConfig(epsilon=0.0, seed=0), priors=priors
        )
        for _ in range(6):
            idx, _ = ctl.choose()
            ctl.observe(idx, _stats(rewards[idx]))
        return ctl.decisions

    cold = run(None)
    # priors in the controller's own time_error units (reward of _stats)
    primed = run(
        {"naive": -2.0, "avoidstragg": -0.5, "deadline:d1.5": -1.0}
    )
    warmups = lambda ds: sum(d["reason"] == "warmup" for d in ds)  # noqa: E731
    assert warmups(cold) == len(arms)
    assert warmups(primed) == 0  # the regression: no exploration burned
    assert primed[0]["reason"] == "exploit"
    assert all(d["arm"] == "avoidstragg" for d in primed)
    # partially-ranked surface: warm-up only visits the unranked arm
    partial = run({"naive": -2.0, "deadline:d1.5": -1.0})
    assert warmups(partial) == 1
    assert partial[0]["arm"] == "avoidstragg"  # the unranked one, first


def test_priors_state_roundtrip_and_shift_reset():
    """Primed values survive the state_dict round-trip bitwise, and a
    regime shift wipes them exactly like learned values — the priors
    were conditioned on the regime that just ended."""
    priors = {"naive": -2.0, "avoidstragg": -0.5, "deadline:d1.5": -1.0}
    ctl = AdaptiveController(
        _arms(), ControllerConfig(epsilon=0.0, seed=0), priors=priors
    )
    clone = AdaptiveController(_arms(), ControllerConfig(epsilon=0.0, seed=0))
    clone.load_state_dict(ctl.state_dict())
    assert clone.snapshot() == ctl.snapshot()
    idx, _ = ctl.choose()
    ctl.observe(idx, _stats(1.0, mean=0.5))
    idx, _ = ctl.choose()
    shift = ctl.observe(idx, _stats(9.0, mean=50.0))  # huge arrival jump
    assert shift == "regime_shift"
    snap = ctl.snapshot()
    assert sum(1 for w in snap["weights"] if w > 0) == 1


# ---------------------------------------------------------------------------
# the driver: switching, events, replay invariance
# ---------------------------------------------------------------------------


def test_train_adaptive_switches_on_regime_shift(tmp_path):
    from erasurehead_tpu.obs import events as obs_events

    rounds = 60  # enough chunks for post-shift exploitation to settle
    ds = generate_gmm(96, 8, W, seed=0)
    arr = _shifted_arrivals(rounds=rounds, shift_round=30)
    path = str(tmp_path / "events.jsonl")
    with obs_events.capture(path):
        res = adapt.train_adaptive(
            _cfg(rounds=rounds), ds, arms=_arms(),
            controller=ControllerConfig(chunk_rounds=5, seed=0),
            arrivals=arr,
        )
    reasons = [d["reason"] for d in res.decisions]
    assert "regime_shift" in reasons
    # pre-shift the bandit exploits wait-for-all (cheap + exact); after
    # the shift's re-exploration, exploit decisions abandon it (the
    # adversary makes every naive round pay the slowdown)
    shift_at = reasons.index("regime_shift")
    pre_exploits = [
        d["arm"] for d in res.decisions[:shift_at] if d["reason"] == "exploit"
    ]
    post_exploits = [
        d["arm"] for d in res.decisions[shift_at:] if d["reason"] == "exploit"
    ]
    assert pre_exploits and set(pre_exploits) == {"naive"}
    # the first post-shift exploitation abandons wait-for-all (later
    # decisions may wander once every arm's progress floors at
    # convergence — the reward signal is legitimately flat there)
    assert post_exploits and post_exploits[0] != "naive"
    # merged result covers the full horizon with stitched telemetry
    assert res.result.timeset.shape == (rounds,)
    assert res.result.decode_error.shape == (rounds,)
    assert res.result.sim_total_time > 0
    leaves = __import__("jax").tree.leaves(res.result.params_history)
    assert int(leaves[0].shape[0]) == rounds
    # every decision journaled as a typed, schema-valid adapt event
    with open(path) as f:
        lines = f.readlines()
    errors = obs_events.validate_lines(lines)
    assert errors == []
    adapt_recs = [
        json.loads(l) for l in lines if json.loads(l)["type"] == "adapt"
    ]
    assert len(adapt_recs) == len(res.decisions)
    assert [a["arm"] for a in adapt_recs] == [
        d["arm"] for d in res.decisions
    ]
    assert any(a["regime_shift"] for a in adapt_recs)


def test_train_adaptive_decision_replay_bitwise():
    """Rerunning the same (seed, arrivals) replays decisions AND the
    trained parameters bitwise — the determinism that makes kill→resume
    replay-invariant."""
    import jax

    ds = generate_gmm(96, 8, W, seed=0)
    arr = _shifted_arrivals()

    def go():
        return adapt.train_adaptive(
            _cfg(), ds, arms=_arms(),
            controller=ControllerConfig(chunk_rounds=5, seed=0),
            arrivals=arr,
        )

    a, b = go(), go()
    assert a.decisions == b.decisions
    for la, lb in zip(
        jax.tree.leaves(a.result.final_params),
        jax.tree.leaves(b.result.final_params),
    ):
        assert np.array_equal(np.asarray(la), np.asarray(lb))


def test_train_adaptive_chaos_kill_resume_replays_decisions(
    tmp_path, monkeypatch
):
    """ERASUREHEAD_CHAOS=raise:adapt:3 interrupts the run at the third
    chunk boundary; the journaled decision prefix is bitwise the
    uninterrupted baseline's, and the rerun (resume-from-scratch — the
    decisions are a pure function of seed + telemetry) reproduces the
    full sequence."""
    from erasurehead_tpu.obs import events as obs_events

    ds = generate_gmm(96, 8, W, seed=0)
    arr = _shifted_arrivals()
    kw = dict(
        arms=_arms(), controller=ControllerConfig(chunk_rounds=5, seed=0),
        arrivals=arr,
    )
    baseline = adapt.train_adaptive(_cfg(), ds, **kw)

    killed_path = str(tmp_path / "killed.jsonl")
    monkeypatch.setenv(chaos_lib.CHAOS_ENV, "raise:adapt:3:PREEMPTED")
    chaos_lib.reset()
    with pytest.raises(chaos_lib.ChaosInjection):
        with obs_events.capture(killed_path):
            adapt.train_adaptive(_cfg(), ds, **kw)
    monkeypatch.delenv(chaos_lib.CHAOS_ENV)
    chaos_lib.reset()
    with open(killed_path) as f:
        killed = [
            json.loads(l) for l in f if json.loads(l)["type"] == "adapt"
        ]
    assert len(killed) == 2  # chunks 0 and 1 committed before the fault
    for rec, d in zip(killed, baseline.decisions):
        assert rec["arm"] == d["arm"]
        assert rec["reason"] == d["reason"]
        assert rec["round"] == d["chunk"] * 5

    rerun = adapt.train_adaptive(_cfg(), ds, **kw)
    assert rerun.decisions == baseline.decisions


def test_train_adaptive_validates_arms():
    ds = generate_gmm(96, 8, W, seed=0)
    with pytest.raises(ValueError, match="partial"):
        adapt.train_adaptive(
            _cfg(rounds=4), ds,
            arms=[Arm("naive"), Arm("partialrepcoded")],
        )
    # faithful mode: cyccoded's worker-major stack differs from naive's
    with pytest.raises(ValueError, match="different device data stack"):
        adapt.train_adaptive(
            _cfg(rounds=4, compute_mode="faithful"), ds,
            arms=[Arm("naive"), Arm("cyccoded")],
        )
    with pytest.raises(ValueError, match="measured"):
        adapt.train_adaptive(
            _cfg(rounds=4, arrival_mode="measured"), ds, arms=[Arm("naive")]
        )


def test_default_arms_cover_base_policy():
    cfg = _cfg(scheme="approx", num_collect=4)
    arms = adapt.default_arms(cfg)
    labels = [a.label for a in arms]
    assert labels[0] == "approx:c4"
    assert "naive" in labels and "avoidstragg" in labels


def test_adaptive_beats_static_naive_under_regime_shift():
    """The headline property at test scale: under an adversarial mid-run
    slowdown, the adaptive run's total simulated time beats the static
    wait-for-all baseline (which pays the slow worker every post-shift
    round)."""
    from erasurehead_tpu.train import trainer

    ds = generate_gmm(96, 8, W, seed=0)
    arr = _shifted_arrivals()
    ares = adapt.train_adaptive(
        _cfg(), ds, arms=_arms(),
        controller=ControllerConfig(chunk_rounds=5, seed=0),
        arrivals=arr,
    )
    static = trainer.train(_cfg(), ds, arrivals=arr, measure=False)
    assert ares.result.sim_total_time < static.sim_total_time
