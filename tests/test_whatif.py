"""What-if engine tests (ISSUE 12): grid feasibility filtering (infeasible
points recorded with a reason, never dispatched), on-device Monte-Carlo
arrival sampling determinism, the surface artifact's bitwise
save/load/rerun contract, the paper's AGC-vs-exact expected-time-to-target
crossover reproduced from simulation alone, typed `whatif` event
emission + validation, and the two consumers — adapt cold-start priors
and the serve daemon's admission-time ETA quote."""

import json
import os

import numpy as np
import pytest

from erasurehead_tpu import adapt
from erasurehead_tpu.obs import events as obs_events
from erasurehead_tpu.whatif import (
    GridSpec,
    PolicySpec,
    RegimeSpec,
    Surface,
    enumerate_points,
    run_whatif,
    sample_arrivals,
)
from erasurehead_tpu.whatif.spec import (
    parse_policies,
    parse_regimes,
)

W, R = 6, 10


def _tiny_spec(**kw):
    base = dict(
        policies=(
            PolicySpec("naive"),
            PolicySpec("approx", num_collect=4),
        ),
        n_workers=(W,),
        n_stragglers=(1,),
        regimes=(RegimeSpec(mean=0.5),),
        n_seeds=3,
        rounds=R,
        n_rows=96,
        n_cols=8,
    )
    base.update(kw)
    return GridSpec(**base)


# ---------------------------------------------------------------------------
# grid enumeration + feasibility filtering (whatif/spec.py)
# ---------------------------------------------------------------------------


def test_enumeration_covers_the_product_in_order():
    spec = _tiny_spec(n_stragglers=(1, 2))
    points = enumerate_points(spec)
    assert len(points) == spec.n_points == 4
    assert [p.label for p in points] == [
        "naive@W6s1/exp0.5",
        "naive@W6s2/exp0.5",
        "approx:c4@W6s1/exp0.5",
        "approx:c4@W6s2/exp0.5",
    ]


def test_infeasible_points_recorded_with_validator_reason():
    """Each descriptor's own validation decides feasibility: the FRC
    divisibility guard ((s+1) | W fails at s=3, W=6), the needs_deadline
    contract, a num_collect past the worker set, and the partial
    partition-count rule — all recorded, none raising."""
    spec = _tiny_spec(
        policies=(
            PolicySpec("repcoded"),       # (3+1) does not divide 6
            PolicySpec("deadline"),       # no deadline given
            PolicySpec("approx", num_collect=9),  # collect > W
            PolicySpec("partialrepcoded"),  # partitions_per_worker unset
            PolicySpec("naive"),          # the one feasible policy
        ),
        n_stragglers=(3,),
    )
    points = enumerate_points(spec)
    by_scheme = {p.policy.scheme: p for p in points}
    assert by_scheme["naive"].feasible
    for scheme, marker in (
        ("repcoded", "n_stragglers+1"),
        ("deadline", "deadline"),
        ("approx", "num_collect"),
        ("partialrepcoded", "partitions_per_worker"),
    ):
        p = by_scheme[scheme]
        assert not p.feasible
        assert p.config is None
        assert marker in p.reason, (scheme, p.reason)


def test_infeasible_points_never_dispatched(monkeypatch):
    """The engine hands ONLY feasible labels to the sweep dispatch path;
    infeasible rows come back with reason and no science columns."""
    from erasurehead_tpu.train import experiments

    spec = _tiny_spec(
        policies=(
            PolicySpec("naive"),
            PolicySpec("repcoded"),  # infeasible at s=3
        ),
        n_stragglers=(3,),
    )
    dispatched: list = []
    real = experiments._run_configs

    def spy(configs, dataset, arrivals, batch, on_result=None):
        dispatched.extend(configs)
        return real(configs, dataset, arrivals, batch, on_result=on_result)

    monkeypatch.setattr(experiments, "_run_configs", spy)
    surf = run_whatif(spec)
    assert dispatched and all(l.startswith("naive@") for l in dispatched)
    bad = [r for r in surf.rows if r["scheme"] == "repcoded"]
    assert len(bad) == 1 and not bad[0]["feasible"]
    assert "n_stragglers+1" in bad[0]["reason"]
    assert bad[0]["expected_time_to_target"] is None
    assert bad[0]["n_seeds"] == 0


def test_policy_and_regime_parsing():
    pols = parse_policies("naive,approx:c4,deadline:d1.5,randreg:f0.5")
    assert [p.scheme for p in pols] == [
        "naive", "approx", "deadline", "randreg",
    ]
    assert pols[1].num_collect == 4
    assert pols[2].deadline == 1.5
    assert pols[3].collect_frac == 0.5
    assert pols[3].resolve_num_collect(8) == 4
    regs = parse_regimes("exp:0.1,heavytail:1.2:0.5,adversary:5:2,exp+c0.3xslots")
    assert [r.kind for r in regs] == [
        "exp", "heavytail", "adversary", "exp",
    ]
    assert regs[0].mean == 0.1
    assert regs[1].alpha == 1.2 and regs[1].mean == 0.5
    assert regs[2].slowdown == 5.0 and regs[2].worker == 2
    assert regs[3].compute_time == 0.3 and regs[3].compute_slots
    with pytest.raises(ValueError, match="bad policy field"):
        parse_policies("approx:x9")
    with pytest.raises(ValueError, match="forms:"):
        parse_regimes("pareto:1.2")


def test_spec_hash_stable_and_sensitive():
    a, b = _tiny_spec(), _tiny_spec()
    assert a.spec_hash() == b.spec_hash()
    assert a.spec_hash() != _tiny_spec(n_seeds=4).spec_hash()


def test_pipeline_axis_tau0_no_drift():
    """The staleness axis (ISSUE 16) defaults to (0,) and is
    hash-invisible there: a spec written before the axis existed and an
    explicit pipeline_depths=(0,) spec hash AND enumerate identically —
    so every saved surface (the 336-run grid included) rehydrates
    unchanged. tau=1 points ride the existing feasibility filter:
    exact-decode policies surface as infeasible with the typed refusal
    reason, never dispatched."""
    a, b = _tiny_spec(), _tiny_spec(pipeline_depths=(0,))
    assert a.spec_hash() == b.spec_hash()
    assert [p.label for p in enumerate_points(a)] == [
        p.label for p in enumerate_points(b)
    ]
    assert all(p.pipeline_depth == 0 for p in enumerate_points(a))

    c = _tiny_spec(pipeline_depths=(0, 1))
    assert c.spec_hash() != a.spec_hash()
    assert c.n_points == 2 * a.n_points
    tau1 = [p for p in enumerate_points(c) if p.pipeline_depth == 1]
    naive1 = [p for p in tau1 if p.policy.scheme == "naive"]
    assert naive1 and not naive1[0].feasible
    assert "exactness contract" in naive1[0].reason
    approx1 = [p for p in tau1 if p.policy.scheme == "approx"]
    assert approx1 and approx1[0].feasible
    assert approx1[0].label.endswith("/tau1")
    with pytest.raises(ValueError, match="pipeline_depths"):
        _tiny_spec(pipeline_depths=(2,))


def test_pipeline_axis_tau0_surface_rows_identical(tmp_path):
    """Simulating the SAME grid through a default spec and an explicit
    pipeline_depths=(0,) spec produces identical surface rows — the
    tau=0 no-drift pin at the artifact level, not just the hash."""
    spec_a = _tiny_spec(n_seeds=2, target_loss=0.6)
    spec_b = _tiny_spec(n_seeds=2, target_loss=0.6, pipeline_depths=(0,))
    surf_a = run_whatif(spec_a)
    surf_b = run_whatif(spec_b)
    assert surf_a.rows == surf_b.rows
    assert all(r["pipeline_depth"] == 0 for r in surf_a.rows)


# ---------------------------------------------------------------------------
# Monte-Carlo arrival sampling (whatif/sampler.py)
# ---------------------------------------------------------------------------


def test_sampler_deterministic_and_seed_independent():
    reg = RegimeSpec(mean=0.5)
    a = sample_arrivals(reg, R, W, [0, 1, 2])
    b = sample_arrivals(reg, R, W, [0, 1, 2])
    assert a.shape == (3, R, W)
    assert np.array_equal(a, b)  # bitwise-identical redraw
    assert not np.array_equal(a[0], a[1])  # seeds are independent draws
    assert (a >= 0).all()


def test_sampler_regime_kinds():
    base = sample_arrivals(RegimeSpec(mean=0.5), R, W, [0])[0]
    heavy = sample_arrivals(
        RegimeSpec(kind="heavytail", alpha=0.8, mean=0.5), R, W, [0]
    )[0]
    assert heavy.max() > 2 * base.max()  # the tail is the point
    adv = sample_arrivals(
        RegimeSpec(kind="adversary", slowdown=9.0, worker=2, shift_round=4),
        R, W, [0],
    )[0]
    assert np.array_equal(adv[:4], base[:4])  # pre-shift untouched
    # f32 device add, so the slowdown lands to float tolerance
    np.testing.assert_allclose(adv[4:, 2] - base[4:, 2], 9.0, rtol=1e-5)
    assert np.array_equal(
        np.delete(adv, 2, axis=1), np.delete(base, 2, axis=1)
    )
    shifted = sample_arrivals(
        RegimeSpec(mean=0.5, compute_time=0.25), R, W, [0]
    )[0]
    np.testing.assert_allclose(shifted, base + 0.25)


def test_targeted_regime_needs_layout():
    with pytest.raises(ValueError, match="layout"):
        sample_arrivals(
            RegimeSpec(kind="targeted", slowdown=5.0), R, W, [0]
        )


def test_trace_regime_rotates_per_seed(tmp_path):
    trace = np.arange(R * W, dtype=float).reshape(R, W)
    path = os.path.join(tmp_path, "trace.npy")
    np.save(path, trace)
    out = sample_arrivals(RegimeSpec(kind="trace", trace=path), R, W, [0, 1])
    assert np.array_equal(out[0], trace)  # seed 0 = the raw replay
    assert np.array_equal(out[1], np.roll(trace, -1, axis=0))


# ---------------------------------------------------------------------------
# engine + surface artifact (whatif/engine.py, whatif/surface.py)
# ---------------------------------------------------------------------------


def test_surface_roundtrip_and_bitwise_rerun(tmp_path):
    spec = _tiny_spec()
    a_dir = os.path.join(tmp_path, "a")
    b_dir = os.path.join(tmp_path, "b")
    surf = run_whatif(spec, out_dir=a_dir)
    assert surf.stats["n_trajectories"] == 2 * spec.n_seeds

    # load round-trip: rows identical, header metadata preserved
    loaded = Surface.load(a_dir)
    assert loaded.rows == surf.rows
    assert loaded.spec_hash == spec.spec_hash()
    assert loaded.target_loss == surf.target_loss

    # rehydration: an identical spec is served from the artifact
    rehydrated = run_whatif(spec, out_dir=a_dir)
    assert rehydrated.stats is None
    assert rehydrated.rows == surf.rows

    # bitwise rerun: forced re-simulation reproduces both files exactly
    run_whatif(spec, out_dir=b_dir, rehydrate=False)
    for name in ("surface_rows.jsonl", "surface.npz"):
        with open(os.path.join(a_dir, name), "rb") as f:
            a_bytes = f.read()
        with open(os.path.join(b_dir, name), "rb") as f:
            b_bytes = f.read()
        assert a_bytes == b_bytes, name

    # the npz mirror stays np.load-readable
    with np.load(os.path.join(a_dir, "surface.npz")) as z:
        assert list(z["labels"]) == [r["label"] for r in surf.rows]
        assert z["expected_time_to_target"].shape == (len(surf.rows),)


def test_paired_sampling_shares_streams_across_policies():
    """All policies at the same (W, regime, seed) coordinate read the
    same arrival stream — naive (wait-for-all) must therefore clock the
    per-round max of exactly the draw approx saw."""
    spec = _tiny_spec(n_seeds=2)
    surf = run_whatif(spec)
    rows = {r["scheme"]: r for r in surf.feasible_rows()}
    # same streams => naive's per-round time >= approx's, every time
    assert (
        rows["naive"]["sim_time_per_round"]
        > rows["approx"]["sim_time_per_round"]
    )


def test_whatif_events_emitted_and_valid(tmp_path):
    spec = _tiny_spec(
        policies=(PolicySpec("naive"), PolicySpec("deadline")),
    )
    events_path = os.path.join(tmp_path, "events.jsonl")
    with obs_events.capture(events_path):
        surf = run_whatif(spec)
    assert obs_events.validate_file(events_path) == []
    with open(events_path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    whatif = [r for r in recs if r["type"] == "whatif"]
    kinds = [r["kind"] for r in whatif]
    assert kinds[0] == "grid"
    assert kinds.count("point") == len(surf.rows)
    grid = whatif[0]
    assert grid["n_points"] == 2 and grid["n_infeasible"] == 1
    assert all(r["spec_hash"] == spec.spec_hash() for r in whatif)
    point = next(r for r in whatif if r["kind"] == "point")
    assert isinstance(r_label := point["label"], str) and r_label


def test_whatif_validator_rejects_malformed_records():
    lines = [
        json.dumps({"type": "whatif", "seq": 0, "t": 0.0,
                    "spec_hash": "", "kind": "grid"}),
        json.dumps({"type": "whatif", "seq": 1, "t": 0.0,
                    "spec_hash": "abc", "kind": "nope"}),
        json.dumps({"type": "whatif", "seq": 2, "t": 0.0,
                    "spec_hash": "abc", "kind": "point",
                    "feasible": "yes"}),
    ]
    errors = obs_events.validate_lines(lines)
    text = "\n".join(errors)
    assert "spec_hash" in text
    assert "kind" in text
    assert "feasible" in text and "label" in text


# ---------------------------------------------------------------------------
# the acceptance pin: the AGC-vs-exact crossover from simulation alone
# ---------------------------------------------------------------------------


def test_agc_vs_exact_crossover_reproduced():
    """ErasureHead's central figure family, from simulation alone: under
    a mild compute-dominated regime the exact code (cyccoded, zero decode
    error) reaches the target first; under heavy straggling AGC's
    earlier stop rule wins despite its decode error — and the surface's
    crossover finder locates the flip. Grid + target verified stable
    across seed counts before pinning."""
    spec = GridSpec(
        policies=(
            PolicySpec("cyccoded"),
            PolicySpec("approx", num_collect=4),
        ),
        n_workers=(W,),
        n_stragglers=(1,),
        regimes=(
            RegimeSpec(mean=0.05, compute_time=0.3),  # mild straggling
            RegimeSpec(mean=2.0),                     # heavy straggling
        ),
        n_seeds=3,
        rounds=60,
        n_rows=96,
        n_cols=8,
        target_loss=0.145,
    )
    surf = run_whatif(spec)
    x = surf.crossover("approx", "cyccoded", axis="regime")
    winners = {v: winner for v, _a, _b, winner in x["points"]}
    assert winners["exp0.05+c0.3"] == "cyccoded"  # exact wins mild
    assert winners["exp2"] == "approx"            # AGC wins heavy
    assert x["crossover"] == "exp2"               # the flip is located
    table = surf.format_crossover_table("approx", "cyccoded", "regime")
    assert "<- crossover" in table


# ---------------------------------------------------------------------------
# consumers: adapt priors + serve ETA
# ---------------------------------------------------------------------------


def _surface_fixture(tmp_path):
    spec = _tiny_spec(
        policies=(
            PolicySpec("naive"),
            PolicySpec("avoidstragg"),
            PolicySpec("approx", num_collect=4),
        ),
    )
    return run_whatif(spec, out_dir=os.path.join(tmp_path, "surf"))


def test_surface_lookup_and_eta(tmp_path):
    from erasurehead_tpu.utils.config import RunConfig

    surf = _surface_fixture(tmp_path)
    row = surf.lookup("approx", n_workers=W, n_stragglers=1, num_collect=4)
    assert row is not None and row["scheme"] == "approx"
    assert surf.lookup("cyccoded") is None  # not on this surface
    cfg = RunConfig(
        scheme="approx", n_workers=W, n_stragglers=1, num_collect=4,
        rounds=R, n_rows=96, n_cols=8, compute_mode="deduped",
    )
    eta = surf.eta(cfg)
    assert eta == row["expected_time_to_target"] and eta > 0


def test_surface_adapt_priors_units(tmp_path):
    surf = _surface_fixture(tmp_path)
    arms = [
        adapt.Arm("naive"),
        adapt.Arm("avoidstragg"),
        adapt.Arm("approx", num_collect=4),
        adapt.Arm("deadline", deadline=1.0),  # no row -> omitted
    ]
    priors = surf.adapt_priors(arms, n_workers=W, n_stragglers=1)
    assert set(priors) == {"naive", "avoidstragg", "approx:c4"}
    # time_error units: minus sim-seconds-per-round, error-inflated
    naive_row = surf.lookup("naive", n_workers=W, n_stragglers=1)
    assert priors["naive"] == pytest.approx(
        -naive_row["sim_time_per_round"]
    )
    assert all(v < 0 for v in priors.values())


def test_serve_quotes_surface_eta(tmp_path):
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.serve.server import SweepServer
    from erasurehead_tpu.utils.config import RunConfig

    surf = _surface_fixture(tmp_path)
    cfg = RunConfig(
        scheme="approx", n_workers=W, n_stragglers=1, num_collect=4,
        rounds=R, n_rows=96, n_cols=8, lr_schedule=1.0, add_delay=True,
        compute_mode="deduped", update_rule="GD", seed=0,
    )
    ds = generate_gmm(96, 8, W, seed=0)
    with SweepServer(eta_surface=surf) as srv:
        h = srv.submit(tenant="t", label="agc", config=cfg, dataset=ds)
        assert h.eta_s == surf.eta(cfg) and h.eta_s > 0
        res = h.result(timeout=300)
    assert res.status == "ok"
    # without a surface the quote stays None (quoting off, serving on)
    with SweepServer() as srv:
        h = srv.submit(tenant="t", label="agc2", config=cfg, dataset=ds)
        assert h.eta_s is None
        assert h.result(timeout=300).status == "ok"


def test_cli_whatif_subcommand(tmp_path):
    from erasurehead_tpu import cli

    out = os.path.join(tmp_path, "surface")
    rc = cli.main([
        "whatif",
        "--policies", "naive,approx:c4",
        "--workers", str(W), "--stragglers", "1",
        "--regimes", "exp:0.5", "--seeds", "2", "--rounds", "8",
        "--rows", "96", "--cols", "8",
        "--out", out, "--crossover", "approx,naive", "--quiet",
    ])
    assert rc == 0
    assert os.path.exists(os.path.join(out, "surface_rows.jsonl"))
    assert os.path.exists(os.path.join(out, "surface.npz"))
    assert obs_events.validate_file(
        os.path.join(out, "events.jsonl")
    ) == []
