"""Random-regular AGC with optimal decoding (beyond the reference).

Properties pinned: d-regularity of the assignment, least-squares
optimality of the decode, strictly-better expected decode error than
FRC-AGC at equal storage/collection budget on the shared schedule
(arXiv 1711.06771 / 2006.09638 via PAPERS.md), and end-to-end training.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from erasurehead_tpu.ops import codes
from erasurehead_tpu.parallel import collect, dynamic, failures, straggler
from erasurehead_tpu.utils.config import RunConfig, Scheme

R, W, S = 12, 12, 2


@pytest.fixture(scope="module")
def arrivals():
    return straggler.arrival_schedule(R, W, add_delay=True)


@pytest.mark.parametrize("W_,s", [(6, 1), (12, 2), (12, 5), (8, 7)])
def test_layout_is_d_regular(W_, s):
    layout = codes.random_regular_layout(W_, s, seed=3)
    d = s + 1
    assert layout.assignment.shape == (W_, d)
    # every worker holds d DISTINCT partitions
    for w in range(W_):
        assert len(set(layout.assignment[w])) == d
    # every partition sits on exactly d workers
    counts = np.bincount(layout.assignment.ravel(), minlength=W_)
    assert (counts == d).all()
    assert layout.storage_overhead == d
    np.testing.assert_array_equal(layout.B.sum(axis=1), np.full(W_, d))


def test_layout_deterministic_per_seed():
    a = codes.random_regular_layout(W, S, seed=7).assignment
    b = codes.random_regular_layout(W, S, seed=7).assignment
    c = codes.random_regular_layout(W, S, seed=8).assignment
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_decode_is_least_squares_optimal(arrivals):
    """No other weight vector on the collected support reconstructs the
    all-ones vector with smaller error."""
    layout = codes.random_regular_layout(W, S, seed=0)
    sched = collect.collect_first_k_optimal(arrivals, layout.B, num_collect=7)
    rng = np.random.default_rng(0)
    ones = np.ones(W)
    for r in range(R):
        mask = sched.collected[r]
        w_opt = sched.message_weights[r]
        err_opt = np.linalg.norm(w_opt @ layout.B - ones)
        assert (w_opt[~mask] == 0).all()
        for _ in range(20):  # random perturbations on the support only
            w_alt = w_opt + np.where(mask, rng.standard_normal(W) * 0.1, 0.0)
            assert np.linalg.norm(w_alt @ layout.B - ones) >= err_opt - 1e-9


def test_optimal_beats_uniform_decode(arrivals):
    """The lstsq decode dominates the naive uniform 1/d weighting of the
    same collected messages (2006.09638's point: decoding, not the code,
    is where AGC leaves accuracy on the table)."""
    d = S + 1
    rr = codes.random_regular_layout(W, S, seed=0)
    ones = np.ones(W)
    for k in (5, 7, 9):
        sched = collect.collect_first_k_optimal(arrivals, rr.B, num_collect=k)
        for r in range(R):
            mask = sched.collected[r]
            err_opt = np.linalg.norm(sched.message_weights[r] @ rr.B - ones)
            err_uni = np.linalg.norm((mask / d) @ rr.B - ones)
            assert err_opt <= err_uni + 1e-9


def test_decode_error_shrinks_with_budget_and_vanishes_at_full(arrivals):
    rr = codes.random_regular_layout(W, S, seed=0)
    ones = np.ones(W)
    means = []
    for k in (4, 7, 10, W):
        sched = collect.collect_first_k_optimal(arrivals, rr.B, num_collect=k)
        means.append(
            np.mean([
                np.linalg.norm(sched.message_weights[r] @ rr.B - ones)
                for r in range(R)
            ])
        )
    assert means == sorted(means, reverse=True)
    # (1/d) * sum of ALL rows == ones exactly: full collection decodes exact
    assert means[-1] < 1e-8


def test_dynamic_rule_matches_host(arrivals):
    layout = codes.random_regular_layout(W, S, seed=0)
    ref = collect.collect_first_k_optimal(arrivals, layout.B, num_collect=7)
    B = jnp.asarray(layout.B, jnp.float32)
    for r in range(R):
        rs = dynamic._first_k_lstsq_jnp(
            jnp.asarray(arrivals[r], jnp.float32), B, 7
        )
        np.testing.assert_array_equal(np.asarray(rs.collected), ref.collected[r])
        np.testing.assert_allclose(
            np.asarray(rs.message_weights), ref.message_weights[r], atol=5e-3
        )


def test_feasibility_rule(arrivals):
    layout = codes.random_regular_layout(W, S, seed=0)
    t = failures.inject_worker_death(arrivals, {i: 0 for i in range(6)})
    rep = failures.analyze(
        Scheme.RANDOM_REGULAR, layout, t, num_collect=7
    )
    assert not rep.all_feasible  # only 6 alive < 7 to collect
    rep2 = failures.analyze(
        Scheme.RANDOM_REGULAR, layout, t, num_collect=6
    )
    assert rep2.all_feasible


def test_trains_end_to_end():
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.models.glm import LogisticModel
    from erasurehead_tpu.parallel.mesh import worker_mesh
    from erasurehead_tpu.train import trainer

    cfg = RunConfig(
        scheme="randreg", n_workers=W, n_stragglers=S, num_collect=8,
        rounds=12, n_rows=24 * W, n_cols=16, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    data = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=W, seed=0)
    res = trainer.train(cfg, data, mesh=worker_mesh(4))
    hist = np.asarray(res.params_history)
    assert np.isfinite(hist).all()
    model = LogisticModel()
    Xt, yt = jnp.asarray(data.X_test), jnp.asarray(data.y_test)
    first = float(model.loss_mean(jnp.asarray(hist[0]), Xt, yt))
    last = float(model.loss_mean(jnp.asarray(hist[-1]), Xt, yt))
    assert last < first * 0.7
