"""Benchmark: flagship AGC logistic regression at the reference's canonical
run shape, on real TPU — hardened so it ALWAYS emits one valid JSON line.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Architecture (why there are two processes): this image's TPU is reached
through a remote-relay PJRT plugin that a sitecustomize dials at interpreter
start. When the relay is healthy, `import jax` takes ~2s; when it is wedged,
`import jax` HANGS INDEFINITELY in every process (observed for hours), so no
amount of in-process exception handling can save the benchmark. The parent
process therefore never imports jax itself: it (1) probes the backend in a
subprocess under a hard timeout, (2) runs the real bench in a subprocess
under a hard timeout, retrying once, and (3) on any failure falls back to a
CPU run with the relay env scrubbed (the sitecustomize skips dialing when
PALLAS_AXON_POOL_IPS is unset), which is immune to the relay's state. The
emitted JSON carries an explicit "platform" field so a fallback can never
masquerade as a TPU number.

What is measured: real on-device steps/sec of the full coded training step
(worker-sharded gradient stacks, slot-weighted decode contraction, psum, AGD
update) over the canonical configuration from run_approx_coding.sh:2-9 —
30 workers, s=3 stragglers, num_collect=15, AGD, 100 rounds, seeded
Exponential(0.5) straggler schedule.

What vs_baseline compares against: the reference's effective iteration rate
under its own measurement protocol on the same schedule. In the reference,
every iteration's wall-clock is the arrival time of the worker that satisfies
the AGC stop rule — the injected sleeps are real time there
(src/approximate_coding.py:136-175, src/naive.py:141-148). Our control plane
computes exactly that per-iteration simulated clock from the identical delay
streams; baseline steps/sec = rounds / sum(simulated timeset). The TPU run
does the same *science* (same gradients, same decode, same loss curve, same
timing artifacts) without spending wall-clock on sleeping, which is precisely
the framework's value proposition.

Roofline extras (see BASELINE.md "Hardware roofline model"): the GLM
gradient step is HBM-bandwidth-bound — per iteration XLA streams the feature
stack X twice (margin matvec + transpose matvec), so
  bytes_per_step  = 2 * nbytes(X)    (+ O(rows + features) small terms)
  flops_per_step  = 4 * M * R * F    (2 matvecs x 2 flops/elem)
  achieved_gbps   = bytes_per_step * steps_per_sec / 1e9
  pct_roofline    = achieved_gbps / platform HBM peak (v5e: 819 GB/s)
pct_roofline is null off-TPU (a host's memory roofline is not the claim).

Sweep extras (the ErasureHead artifact is a multi-scheme sweep, not one
run): ``sweep7`` measures a trajectory-batched 7-scheme x 2-seed deduped
cohort (trainer.train_cohort — ONE compiled scan; the margin lowers as a
[N, F] x [F, B] matmul) against the sequential cached path. Batched
accounting counts the X stream ONCE PER COHORT PASS, not once per
trajectory: per round the cohort moves the same 2*nbytes(X) as a single
run while retiring B trajectory-steps, so
  aggregate_steps_per_sec       = B * rounds / cohort_wall
  aggregate_achieved_gbps       = 2*nbytes(X) * rounds/cohort_wall / 1e9
  per_trajectory_achieved_gbps  = aggregate_achieved_gbps / B
and the arithmetic intensity (flops/byte) rises B-fold — the roofline
lever batching moves and kernel fusion could not (BASELINE.md).

``deep_cohort`` repeats the cohort race off the convex GLMs: a 7-scheme
x 4-seed DEEP-MODEL cohort (the autodiff margin families — one vmapped-
forward dispatch with per-trajectory weight tables) against the
sequential cached path, bar >= 3x aggregate trajectories/sec on CPU. It
also emits a decode-error-vs-depth series: blockwise-coded deepmlp runs
(layer_coding="on", ops/blocks.py) measure each layer block's
gradient-space decode error against the model's own partition gradients
(obs/decode.block_decode_error) and write layer-tagged decode chunk
streams into the events capture.

Serve extras (the multi-tenant layer over the same engine): ``serve_pack``
races SERVE_CLIENTS concurrent clients submitting same-signature 7-scheme
sweeps to the serve daemon (erasurehead_tpu/serve/ — bin-packed cohort
dispatches under admission control) against the identical requests
dispatched sequentially one singleton cohort at a time. Aggregate
throughput = trajectories/sec across all clients; the packed and
sequential science rows must agree BITWISE (completion order aside),
because a cohort's per-trajectory results are independent of its width.
``serve_load`` is the robustness twin: closed-loop HTTP clients
(serve/http_front.py + serve/loadgen.py) reporting p50/p99
time-to-first-row and time-to-last-row plus the packed-dispatch ratio,
backpressure correctness at 2x-capacity offered load (zero
accepted-then-lost, zero duplicates, 429s retried to success on the
daemon's retry-after schedule), goodput fairness under one flooding
tenant (bar >= 0.5x solo), and a warm-restart phase pinning bitwise
rehydration with zero new on-disk compile-cache entries.
``fleet`` replicates the daemon (ISSUE 20): real subprocess replicas
behind the consistent-hash router, measuring goodput scaling from one
to two replicas on the same 4-tenant packable load and the
rolling-deploy ledger — every replica bounced under load with zero
accepted-then-lost rows, zero duplicates, and the under-deploy TTFR
p99 against steady state.
"""

import json
import os
import subprocess
import sys
import time

ROUNDS = 100
# run_approx_coding.sh:2-9 sets W=30, s=3, collect=15 — but AGC requires
# (s+1) | W in the reference as well (src/approximate_coding.py:25-27), and
# 30 % 4 != 0, so the canonical script's own AGC config is unrunnable there
# too. s=2 is the nearest valid setting (10 FRC groups of 3).
W, S, COLLECT = 30, 2, 15
N_COLS = 128

# Per-chip HBM peak bandwidths in GB/s (public specs), matched by
# substring against ``jax.devices()[0].device_kind`` — ordered so the
# more specific marker wins ("v5p" before the v5e/v5-lite catch-all).
# Unrecognized kinds fall back to the v5e figure WITH a peak_source field
# saying so, so pct_roofline is never silently computed against the wrong
# roof on non-v5e silicon.
DEVICE_KIND_PEAKS = (
    ("v6", 1640.0),  # v6e / Trillium
    ("v5p", 2765.0),
    ("v5", 819.0),  # v5e ("v5 lite" / "v5litepod" kinds)
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)
FALLBACK_PEAK_GBPS = 819.0  # v5e — the fleet this repo's captures ran on


def _hbm_peak(platform: str, device_kind: str):
    """(peak_gbps, peak_source) for this accelerator, or (None, None) on
    hosts — a host's memory roofline is not the claim (module docstring)."""
    if platform not in ("tpu", "axon"):
        return None, None
    dk = (device_kind or "").lower()
    for marker, peak in DEVICE_KIND_PEAKS:
        if marker in dk:
            return peak, f"device_kind:{device_kind}"
    return (
        FALLBACK_PEAK_GBPS,
        f"fallback:v5e (unrecognized device_kind {device_kind!r})",
    )

PROBE_TIMEOUT = int(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
RUN_TIMEOUT = int(os.environ.get("BENCH_RUN_TIMEOUT", "900"))
RETRY_TIMEOUT = int(os.environ.get("BENCH_RETRY_TIMEOUT", "420"))

# data dtype sweep knob; validated up front so a typo can't burn every
# timed attempt before failing deep inside the child
_DTYPE_ITEMSIZE = {"float32": 4, "bfloat16": 2}
DATA_DTYPE = os.environ.get("BENCH_DTYPE", "float32")
# suffix only for KNOWN non-f32 dtypes: an invalid value's failure record
# keeps the bare canonical metric name (not a garbage-derived one)
METRIC_SUFFIX = (
    f"_{DATA_DTYPE}"
    if DATA_DTYPE in _DTYPE_ITEMSIZE and DATA_DTYPE != "float32"
    else ""
)
# margin-lowering sweep knob (ops/features.set_dense_margin_cols): tag the
# metric so sweep entries with different lowerings never collide. Validated
# up front like BENCH_DTYPE — a malformed value must fail HERE, not after
# burning the probe/run/retry timeouts inside every child, and must never
# produce a garbage-derived metric name.
_MARGIN_COLS_ENV = os.environ.get("BENCH_MARGIN_COLS", "")
MARGIN_COLS: "int | None" = None
if _MARGIN_COLS_ENV:
    try:
        MARGIN_COLS = int(_MARGIN_COLS_ENV)
    except ValueError:
        MARGIN_COLS = -1  # flagged invalid; failure record keeps bare name
    if MARGIN_COLS is not None and 2 <= MARGIN_COLS <= 128:
        METRIC_SUFFIX += f"_margincols{MARGIN_COLS}"
# compute-mode knob: "deduped" computes each partition once instead of the
# faithful (s+1)-replicated slot stack — bit-compatible gradients at
# 1/(s+1) the HBM traffic (the framework's optimization; the faithful mode
# stays the reference-protocol canonical). Validated up front like the
# other knobs.
COMPUTE_MODE = os.environ.get("BENCH_MODE", "faithful")
if COMPUTE_MODE == "deduped":
    METRIC_SUFFIX += "_deduped"
# stack-transport knob (utils/config.stack_mode): "ring" keeps only the
# partition-major stack and streams the faithful redundancy over ppermute
# neighbor hops inside the step — the memory-side counterpart of deduped
# mode, with bitwise-identical trajectories. Tagged so ring entries never
# collide with the canonical materialized captures.
STACK_MODE = os.environ.get("BENCH_STACK", "materialized")
if STACK_MODE == "ring":
    METRIC_SUFFIX += "_ring"
# ring transport scheduling (utils/config.ring_pipeline): "on" double-
# buffers the hops (ppermute for hop t+1 in flight under hop t's fill —
# bitwise-identical trajectories, same bytes on the wire); "off" forces
# the sequential transport. Unset = cfg default ("auto").
RING_PIPELINE = os.environ.get("BENCH_RING_PIPELINE", "")
if RING_PIPELINE and RING_PIPELINE in ("on", "off"):
    METRIC_SUFFIX += f"_ringpipe{RING_PIPELINE}"
# compressed-stack knob (utils/config.stack_dtype): "int8" streams a
# quantized stack (per-partition scale tables, dequantized in the device
# grad body) — ~4x fewer bytes on the bandwidth-bound pass, LOSSY; the
# fidelity extra below reports the eval-loss delta vs the f32 stack.
# Unset = cfg default ("auto" = follow BENCH_DTYPE).
STACK_DTYPE = os.environ.get("BENCH_STACK_DTYPE", "")
_STACK_ITEMSIZE = {"float32": 4, "bfloat16": 2, "int8": 1}
if STACK_DTYPE and STACK_DTYPE in _STACK_ITEMSIZE:
    if STACK_DTYPE == "int8":
        METRIC_SUFFIX += "_int8"
    elif STACK_DTYPE != DATA_DTYPE:
        METRIC_SUFFIX += f"_stack{STACK_DTYPE}"
# buffer-donation knob (utils/config.donate): "off" disables donation of
# the scan carry + weight tables — the before/after lever for the
# donation BASELINE rows. Unset = cfg default ("auto" = on).
DONATE = os.environ.get("BENCH_DONATE", "")
if DONATE == "off":
    METRIC_SUFFIX += "_nodonate"
# flat-stack lowering knob (parallel/step.make_flat_grad_fn): "on"/"off"
# force the flat vs per-slot closed-form lowering; unset = cfg default
# ("auto", step.resolve_flat_grad's per-stack-kind rules). Tagged so sweep entries
# with different lowerings never collide.
FLAT_GRAD = os.environ.get("BENCH_FLAT", "")
if FLAT_GRAD and FLAT_GRAD in ("on", "off"):
    METRIC_SUFFIX += f"_flat{FLAT_GRAD}"

# hybrid dense margin lowering (parallel/step._hybrid_margin_flat_grad):
# flat 2-D margin matmul + batched per-slot transpose
MARGIN_FLAT = os.environ.get("BENCH_MARGIN_FLAT", "")
if MARGIN_FLAT and MARGIN_FLAT in ("on", "off"):
    METRIC_SUFFIX += f"_marginflat{MARGIN_FLAT}"

# lax.scan unroll factor: >1 lets XLA fuse/overlap consecutive rounds —
# the candidate fix for the in-scan bandwidth gap (126 GB/s in-scan vs
# 819 peak, BASELINE.md round-3 window 2). Identical math at any value.
_UNROLL_ENV = os.environ.get("BENCH_UNROLL", "")
SCAN_UNROLL = 1
if _UNROLL_ENV:
    try:
        SCAN_UNROLL = int(_UNROLL_ENV)
    except ValueError:
        SCAN_UNROLL = -1  # flagged invalid; validated in __main__
if SCAN_UNROLL > 1:
    METRIC_SUFFIX += f"_unroll{SCAN_UNROLL}"

# out-of-core residency knob (utils/config.stack_residency): "streamed"
# runs the canonical scan over windowed partition stacks behind the
# double-buffered prefetch pipeline (data/prefetch.py), composing with
# BENCH_STACK=ring (assignment-aware slot-group windows staged in
# ring-hop order). BENCH_STREAM_WINDOW picks the partitions resident at
# once (must divide the layout's partition count and be window-uniform
# for the scheme — the canonical approx/W=30 layout accepts 6 or 15).
# Tagged so streamed entries never collide with the resident captures.
RESIDENCY = os.environ.get("BENCH_RESIDENCY", "")
if RESIDENCY == "streamed":
    METRIC_SUFFIX += "_streamed"
_STREAM_WINDOW_ENV = os.environ.get("BENCH_STREAM_WINDOW", "")
STREAM_WINDOW = 0
if _STREAM_WINDOW_ENV:
    try:
        STREAM_WINDOW = int(_STREAM_WINDOW_ENV)
    except ValueError:
        STREAM_WINDOW = -1  # flagged invalid; validated in __main__
if STREAM_WINDOW > 0:
    METRIC_SUFFIX += f"_w{STREAM_WINDOW}"


def _failure_record(error: str) -> dict:
    """A valid one-line JSON payload for any can't-measure outcome — the
    module's hard contract is ONE parseable line, never a traceback."""
    return {
        "metric": f"AGC_logistic_steps_per_sec_30w_s2_collect15{METRIC_SUFFIX}",
        "value": 0.0,
        "unit": "iterations/sec",
        "vs_baseline": 0.0,
        "platform": "none",
        "dtype": DATA_DTYPE,
        "mode": COMPUTE_MODE,
        "error": error,
    }


def _cpu_env() -> dict:
    """Env that bypasses the remote-TPU relay entirely (sitecustomize skips
    dialing when PALLAS_AXON_POOL_IPS is unset)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _probe(env: dict, timeout: int) -> bool:
    """Can this env even initialize a jax backend? Cheap subprocess check so
    a wedged relay costs one probe timeout, not a full run timeout."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        print(f"bench: backend probe timed out after {timeout}s", file=sys.stderr)
        return False


def _run_child(env: dict, timeout: int):
    """Run the bench child under a hard timeout; return its parsed JSON
    payload or None. Child stderr is relayed for debugging."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        print(f"bench: child timed out after {timeout}s", file=sys.stderr)
        return None
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        print(f"bench: child rc={proc.returncode}", file=sys.stderr)
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            payload = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(payload, dict) and "metric" in payload:
            return payload
    print("bench: child produced no JSON line", file=sys.stderr)
    return None


def _attempts():
    """(name, env, run timeout) for each bench attempt, in order: the live
    env twice (one retry), then the relay-scrubbed CPU env — unless the live
    env already IS that (relay var unset and platform pinned to cpu)."""
    live = dict(os.environ)
    yield "live", live, RUN_TIMEOUT
    yield "live-retry", live, RETRY_TIMEOUT
    if (
        "PALLAS_AXON_POOL_IPS" in os.environ
        or os.environ.get("JAX_PLATFORMS") != "cpu"
    ):
        yield "cpu-fallback", _cpu_env(), RUN_TIMEOUT


_LAST_TPU_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU_LAST.json"
)


def _record_or_annotate(payload: dict) -> dict:
    """On a TPU result: persist it as the committed last-known-TPU artifact.
    On a fallback: attach that artifact (clearly labeled as a PRIOR
    measurement, never substituted into value/platform) so a wedged relay
    doesn't erase the evidence that a TPU number exists."""
    on_tpu = payload.get("platform") in ("tpu", "axon")
    # canonical = the unmodified flagship config: variant knobs (bf16 data,
    # margin-cols / flat / margin-flat lowerings, deduped mode) are real
    # TPU numbers but must not replace the canonical last-known-TPU
    # artifact (a BENCH_FLAT=on run overwrote it in round 3 — restored
    # from git, and the check now covers every variant knob)
    canonical = (
        payload.get("dtype", "float32") == "float32"
        and not _MARGIN_COLS_ENV
        and COMPUTE_MODE == "faithful"
        and STACK_MODE == "materialized"
        and not FLAT_GRAD
        and not MARGIN_FLAT
        and not RING_PIPELINE
        and not STACK_DTYPE
        and not DONATE
    )
    try:
        if on_tpu and canonical:
            record = dict(payload)
            record["recorded_unix"] = int(time.time())
            # atomic replace: a bench killed mid-write (the wedged-relay
            # timeouts this script defends against) must not leave a
            # truncated artifact poisoning later fallback runs
            tmp = _LAST_TPU_PATH + ".tmp"
            with open(tmp, "w") as f:
                json.dump(record, f)
                f.write("\n")
            os.replace(tmp, _LAST_TPU_PATH)
        elif not on_tpu and os.path.exists(_LAST_TPU_PATH):
            with open(_LAST_TPU_PATH) as f:
                payload["last_tpu_result"] = json.load(f)
        # (a non-canonical TPU run, e.g. BENCH_DTYPE=bfloat16, is a real TPU
        # number: neither recorded as the canonical artifact nor annotated)
    except (OSError, ValueError) as e:  # ValueError covers JSONDecodeError
        print(f"bench: last-TPU artifact io failed: {e}", file=sys.stderr)
    return payload


def main() -> None:
    # Each attempt: cheap backend probe first (so a hung relay costs
    # PROBE_TIMEOUT, not RUN_TIMEOUT), then the real run under its timeout.
    payload = None
    for name, env, timeout in _attempts():
        if not _probe(env, PROBE_TIMEOUT):
            print(f"bench: {name}: backend probe failed", file=sys.stderr)
            continue
        payload = _run_child(env, timeout)
        if payload is not None:
            break
        print(f"bench: {name}: run failed", file=sys.stderr)
    # 3) never a traceback: emit an explicit failure record as valid JSON
    if payload is None:
        payload = _failure_record("all bench attempts failed or timed out")
    print(json.dumps(_record_or_annotate(payload)))


#: sweep7 cohort extra: rounds per trajectory and seeds per scheme (kept
#: short — the extra rides inside the child's hard timeout)
SWEEP7_ROUNDS = 30
SWEEP7_SEEDS = (0, 1)


def _sweep7_extra(data, n_rows: int, peak) -> dict:
    """Trajectory-batched 7-scheme sweep throughput vs the sequential
    cached path, with cohort-correct roofline accounting (X bytes counted
    once per cohort pass — see module docstring)."""
    import time as _time

    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    common = dict(
        n_workers=W, n_stragglers=S, rounds=SWEEP7_ROUNDS, n_rows=n_rows,
        n_cols=N_COLS, update_rule="AGD", lr_schedule=1.0, add_delay=True,
        dtype=DATA_DTYPE, compute_mode="deduped", seed=0,
        stack_dtype=STACK_DTYPE or "auto", donate=DONATE or "auto",
    )
    schemes = [
        ("naive", {}),
        ("cyccoded", {}),
        ("repcoded", {}),
        ("approx", {"num_collect": COLLECT}),
        ("avoidstragg", {}),
        ("randreg", {"num_collect": COLLECT}),
        ("deadline", {"deadline": 1.0}),
    ]
    cfgs = [
        RunConfig(**{**common, **extra, "scheme": s, "seed": sd})
        for s, extra in schemes
        for sd in SWEEP7_SEEDS
    ]
    B = len(cfgs)
    # one cohort dispatch: compile + warm-up are inside train_cohort's
    # compile step, so wall_time is the steady-state scan
    cohort = trainer.train_cohort(cfgs, data)
    cohort_wall = cohort[0].wall_time
    # sequential cached path: deduped schemes share one executable, so the
    # first pass pays the single compile and the second measures what a
    # cached sequential sweep costs per run
    for c in cfgs:
        trainer.train(c, data)
    seq_wall = sum(trainer.train(c, data).wall_time for c in cfgs)

    # cohort-correct roofline: the partition-major X streams ONCE per
    # cohort pass (2x for margin + transpose) and serves all B
    # trajectories; per-trajectory numbers are the per-stream share.
    # Bytes at the stack's STORAGE dtype (int8 adds its scale tables).
    stack_dtype = (STACK_DTYPE or DATA_DTYPE)
    x_bytes = (n_rows // W) * W * N_COLS * _STACK_ITEMSIZE[stack_dtype]
    if stack_dtype == "int8":
        x_bytes += W * N_COLS * 4  # per-partition scale tables
    cohort_bytes_per_step = 2 * x_bytes
    cohort_flops_per_step = 4 * B * (n_rows // W) * W * N_COLS
    agg_rate = B * SWEEP7_ROUNDS / cohort_wall if cohort_wall > 0 else 0.0
    seq_rate = B * SWEEP7_ROUNDS / seq_wall if seq_wall > 0 else 0.0
    agg_gbps = (
        cohort_bytes_per_step * (SWEEP7_ROUNDS / cohort_wall) / 1e9
        if cohort_wall > 0
        else 0.0
    )
    return {
        "sweep7_aggregate_steps_per_sec": round(agg_rate, 3),
        "sweep7": {
            "n_trajectories": B,
            "n_schemes": len(schemes),
            "n_seeds": len(SWEEP7_SEEDS),
            "rounds": SWEEP7_ROUNDS,
            "dispatches": cohort[0].cache_info.get("cohort_dispatches"),
            "lowering": cohort[0].cache_info.get("cohort_lowering"),
            "aggregate_steps_per_sec": round(agg_rate, 3),
            "sequential_cached_steps_per_sec": round(seq_rate, 3),
            "speedup_vs_sequential_cached": (
                round(seq_wall / cohort_wall, 3) if cohort_wall > 0 else 0.0
            ),
            "cohort_wall_s": round(cohort_wall, 4),
            "sequential_cached_wall_s": round(seq_wall, 4),
            # X counted once per cohort pass, not once per trajectory
            "cohort_bytes_per_step": cohort_bytes_per_step,
            "cohort_flops_per_step": cohort_flops_per_step,
            "arithmetic_intensity_flops_per_byte": round(
                cohort_flops_per_step / cohort_bytes_per_step, 3
            ),
            "aggregate_achieved_gbps": round(agg_gbps, 2),
            "per_trajectory_achieved_gbps": round(agg_gbps / B, 4),
            "pct_roofline": (
                round(100.0 * agg_gbps / peak, 2) if peak else None
            ),
        },
    }


#: serve_pack extra: concurrent clients racing the serve daemon against
#: the same requests dispatched sequentially (one singleton cohort each —
#: the bitwise-comparable baseline; packing never changes bits, only
#: dispatch count)
SERVE_CLIENTS = 4


def _serve_pack_extra(data, n_rows: int) -> dict:
    """Sweep-as-a-service throughput: SERVE_CLIENTS concurrent clients
    submit same-signature 7-scheme sweeps to an in-process serve daemon
    (erasurehead_tpu/serve/), racing the identical requests dispatched
    sequentially. The daemon bin-packs all clients' trajectories into
    shared cohort dispatches, so aggregate throughput scales with packed
    dispatches/sec; rows are checked BITWISE against the sequential run
    (science columns; completion order tolerated)."""
    import json as json_lib
    import threading
    import time as _time

    from erasurehead_tpu.obs.metrics import REGISTRY
    from erasurehead_tpu.serve import queue as serve_queue
    from erasurehead_tpu.serve import server as serve_server
    from erasurehead_tpu.train import journal as journal_lib
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    common = dict(
        n_workers=W, n_stragglers=S, rounds=SWEEP7_ROUNDS, n_rows=n_rows,
        n_cols=N_COLS, update_rule="AGD", lr_schedule=1.0, add_delay=True,
        dtype=DATA_DTYPE, compute_mode="deduped",
        stack_dtype=STACK_DTYPE or "auto", donate=DONATE or "auto",
    )
    schemes = [
        ("naive", {}),
        ("cyccoded", {}),
        ("repcoded", {}),
        ("approx", {"num_collect": COLLECT}),
        ("avoidstragg", {}),
        ("randreg", {"num_collect": COLLECT}),
        ("deadline", {"deadline": 1.0}),
    ]
    # one request set per client: same signature everywhere (they pack),
    # per-client seeds (the trajectory axis), deterministic arrivals
    # shared between the packed and sequential paths
    requests = []
    for k in range(SERVE_CLIENTS):
        for s, extra in schemes:
            cfg = RunConfig(**{**common, **extra, "scheme": s, "seed": k})
            requests.append(
                (k, f"c{k}_{s}", cfg, trainer.default_arrivals(cfg))
            )
    n_traj = len(requests)

    def science(summary):
        return json_lib.dumps(
            journal_lib.science_row(journal_lib.summary_payload(summary)),
            sort_keys=True,
        )

    # the daemon dispatches at FIXED width (serve/server.py pad_cohorts):
    # one compiled executable per signature, and a request's bits are
    # independent of how it happened to pack — which is what makes the
    # packed-vs-sequential rows bitwise comparable at all
    width = max(
        serve_server.DEFAULT_MAX_COHORT,
        1 << (n_traj - 1).bit_length(),  # next pow2 >= n_traj
    )

    def run_daemon(submit_concurrently: bool):
        """The same requests through the daemon: all clients at once
        (packed), or strictly one at a time (the sequential baseline —
        what N clients arriving back-to-back would cost without packing).
        Returns (wall_s, sorted science rows, dispatches)."""
        disp_before = REGISTRY.counter("serve.dispatches").value
        handles: list = []
        hlock = threading.Lock()
        with serve_server.serving(
            window_s=0.1 if submit_concurrently else 0.001,
            max_cohort=width,
        ) as srv:
            t0 = _time.perf_counter()
            if submit_concurrently:

                def client(k: int) -> None:
                    for kk, label, cfg, arr in requests:
                        if kk != k:
                            continue
                        h = srv.submit(
                            tenant=f"client{k}", label=label, config=cfg,
                            dataset=data, arrivals=arr,
                        )
                        with hlock:
                            handles.append(h)

                threads = [
                    threading.Thread(target=client, args=(k,))
                    for k in range(SERVE_CLIENTS)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                rows = sorted(
                    science(h.result(timeout=600).summary) for h in handles
                )
            else:
                rows = []
                for k, label, cfg, arr in requests:
                    h = srv.submit(
                        tenant=f"client{k}", label=label, config=cfg,
                        dataset=data, arrivals=arr,
                    )
                    rows.append(science(h.result(timeout=600).summary))
                rows = sorted(rows)
            wall = _time.perf_counter() - t0
        return wall, rows, (
            REGISTRY.counter("serve.dispatches").value - disp_before
        )

    # warm the fixed-width executable + data upload + replay scan once, so
    # the race measures the daemon's steady state (dispatch throughput),
    # not one-time compiles
    run_daemon(submit_concurrently=True)

    deferred_before = REGISTRY.counter("serve.deferred").value
    packed_wall, packed_rows, dispatches = run_daemon(
        submit_concurrently=True
    )
    deferred = REGISTRY.counter("serve.deferred").value - deferred_before
    seq_wall, seq_rows, seq_dispatches = run_daemon(
        submit_concurrently=False
    )

    # informational: the same requests as bare singleton cohort dispatches
    # (no daemon, natural width B=1 — the pre-serve status quo; bits differ
    # from the fixed-width rows, so no bitwise claim on this pair)
    t0 = _time.perf_counter()
    for k, label, cfg, arr in requests:
        res = trainer.train_cohort([cfg], data, arrivals=[arr])[0]
        req = serve_queue.RunRequest(
            tenant=f"client{k}", label=label, config=cfg, dataset=data,
            arrivals=arr,
        )
        serve_server._summarize(req, res)
    unpadded_wall = _time.perf_counter() - t0

    return {
        "serve_pack_speedup": (
            round(seq_wall / packed_wall, 3) if packed_wall > 0 else 0.0
        ),
        "serve_pack": {
            "clients": SERVE_CLIENTS,
            "trajectories": n_traj,
            "rounds": SWEEP7_ROUNDS,
            "dispatch_width": width,
            "dispatches": dispatches,
            "sequential_dispatches": seq_dispatches,
            "deferred_by_admission": deferred,
            "packed_wall_s": round(packed_wall, 4),
            "sequential_wall_s": round(seq_wall, 4),
            "aggregate_trajectories_per_sec": (
                round(n_traj / packed_wall, 3) if packed_wall > 0 else 0.0
            ),
            "sequential_trajectories_per_sec": (
                round(n_traj / seq_wall, 3) if seq_wall > 0 else 0.0
            ),
            "speedup_vs_sequential": (
                round(seq_wall / packed_wall, 3) if packed_wall > 0 else 0.0
            ),
            # science rows must agree bitwise, completion order aside —
            # under fixed-width dispatch, packing is a throughput lever,
            # never a numerics knob
            "rows_bitwise_identical": packed_rows == seq_rows,
            # no-daemon reference: bare B=1 cohort dispatches (different
            # compiled width, so informational only)
            "unpadded_singleton_wall_s": round(unpadded_wall, 4),
        },
    }


#: serve_load extra shape: closed-loop tenants x requests over the HTTP
#: front (each request is a config-resolvable trajectory the daemon packs
#: by signature), one flooding tenant for the fairness phase, and a
#: warm-restart phase against the on-disk compilation cache
SERVE_LOAD_TENANTS = 6
SERVE_LOAD_REQUESTS = 8
SERVE_LOAD_FLOOD = 48
SERVE_LOAD_WORKERS = 4
SERVE_LOAD_ROUNDS = 3
SERVE_LOAD_ROWS = 256


def _serve_load_extra() -> dict:
    """The robustness twin of serve_pack: hundreds of requests from
    concurrent HTTP clients driven closed-loop through the network front
    (serve/http_front.py + serve/loadgen.py). Reports p50/p99
    time-to-first-row and time-to-last-row, the packed-dispatch ratio,
    backpressure correctness under 2x-capacity offered load (zero
    accepted-then-lost, zero duplicate rows, 429'd clients succeeding on
    their retry-after schedule), goodput fairness under one flooding
    tenant (bar: >= 0.5x solo), and a warm-restart phase (bitwise
    rehydration, zero new on-disk compile-cache entries)."""
    import shutil
    import tempfile

    from erasurehead_tpu.obs.metrics import REGISTRY
    from erasurehead_tpu.serve import loadgen
    from erasurehead_tpu.serve import server as serve_server
    from erasurehead_tpu.serve.http_front import HttpFront

    base = tempfile.mkdtemp(prefix="eh-serve-load-")
    journal_dir = os.path.join(base, "journal")
    cache_dir = os.path.join(base, "xla-cache")
    common = dict(
        scheme="naive", n_workers=SERVE_LOAD_WORKERS, n_stragglers=1,
        rounds=SERVE_LOAD_ROUNDS, n_rows=SERVE_LOAD_ROWS, n_cols=N_COLS,
        update_rule="AGD", lr_schedule=0.5, add_delay=True,
        compute_mode="deduped",
    )

    def jobs_for(tenant: str, n: int, seed0: int = 0):
        # per-request seeds: distinct trajectories (and distinct
        # idempotency digests), one shared signature — they all pack
        return [
            (f"{tenant}-r{k}", {**common, "seed": seed0 + k})
            for k in range(n)
        ]

    def make_front(**server_kw):
        kw = dict(
            window_s=0.05, journal_dir=journal_dir, cache_dir=cache_dir,
            max_cohort=16,
        )
        kw.update(server_kw)
        srv = serve_server.SweepServer(**kw).start()
        front = HttpFront(srv)

        def close():
            front.close()
            srv.stop()

        return srv, front, front.host, front.port, close

    out: dict = {}

    # ---- phase 1: closed-loop latency + packed-dispatch ratio ----------
    d0 = REGISTRY.counter("serve.dispatches").value
    _srv, _front, host, port, close = make_front()
    try:
        fleet = loadgen.run_fleet(
            host, port,
            {
                f"tenant{k}": jobs_for(f"tenant{k}", SERVE_LOAD_REQUESTS,
                                       seed0=100 * k)
                for k in range(SERVE_LOAD_TENANTS)
            },
            concurrency=4,
        )
    finally:
        close()
    dispatches = REGISTRY.counter("serve.dispatches").value - d0
    n_requests = SERVE_LOAD_TENANTS * SERVE_LOAD_REQUESTS
    out["closed_loop"] = {
        "tenants": SERVE_LOAD_TENANTS,
        "requests": n_requests,
        "dispatches": dispatches,
        "packed_ratio": (
            round(n_requests / dispatches, 2) if dispatches else None
        ),
        "ttfr_p50_s": loadgen.percentile(
            [x for led in fleet["tenants"].values()
             for x in led["latencies_s"]], 50,
        ),
        "ttfr_p99_s": fleet["latency_p99_s"],
        "ttlr_p99_s": fleet["ttlr_p99_s"],
        "lost": fleet["lost"],
        "duplicates": fleet["duplicates"],
    }

    # ---- phase 2: backpressure at 2x capacity --------------------------
    # max_pending well under the offered burst: 429s must flow, retries
    # must land every job, and nothing may be accepted-then-lost
    _srv, _front, host, port, close = make_front(max_pending=8)
    try:
        pressured = loadgen.run_fleet(
            host, port,
            {
                f"burst{k}": jobs_for(f"burst{k}", SERVE_LOAD_REQUESTS,
                                      seed0=1000 + 100 * k)
                for k in range(2 * SERVE_LOAD_TENANTS)
            },
            concurrency=8,
            max_retries=10,
        )
    finally:
        close()
    out["backpressure"] = {
        "offered_requests": 2 * SERVE_LOAD_TENANTS * SERVE_LOAD_REQUESTS,
        "rejected_429s": pressured["rejected_429s"],
        "retries": pressured["retries"],
        "lost": pressured["lost"],
        "duplicates": pressured["duplicates"],
        "all_jobs_landed": all(
            led["rows"] == led["jobs"] - led["rejected_final"]
            for led in pressured["tenants"].values()
        ),
    }

    # ---- phase 3: fairness under one flooding tenant -------------------
    # journal OFF here: rehydrating the solo phase's rows would fake the
    # contended goodput (signatures are warm from the phases above, so
    # this measures scheduling, not compiles)
    import functools

    fair = loadgen.fairness_run(
        functools.partial(make_front, journal_dir=None),
        victim_jobs={
            f"victim{k}": jobs_for(f"victim{k}", 4, seed0=5000 + 100 * k)
            for k in range(2)
        },
        flood_jobs=jobs_for("flood", SERVE_LOAD_FLOOD, seed0=9000),
        flood_concurrency=SERVE_LOAD_FLOOD,
    )
    out["fairness"] = {
        "flood_requests": SERVE_LOAD_FLOOD,
        "goodput_ratio": fair["goodput_ratio"],
        "min_goodput_ratio": fair["min_goodput_ratio"],
        "bar_met": (
            fair["min_goodput_ratio"] is not None
            and fair["min_goodput_ratio"] >= 0.5
        ),
    }

    # ---- phase 4: warm restart -----------------------------------------
    # fresh seeds: the first pass must genuinely dispatch (and write the
    # on-disk cache) so the bounce proves rehydration, not journal reuse
    restart = loadgen.restart_run(
        make_front,
        {
            f"rst{k}": jobs_for(f"rst{k}", SERVE_LOAD_REQUESTS,
                                seed0=7000 + 100 * k)
            for k in range(2)
        },
        cache_dir=cache_dir,
        concurrency=4,
    )
    out["restart"] = {
        "rows_first": restart["rows_first"],
        "rows_resubmitted": restart["rows_resubmitted"],
        "resumed": restart["resumed"],
        "bitwise_mismatches": restart["bitwise_mismatches"],
        "new_compile_cache_entries": restart["new_compile_cache_entries"],
        "restart_wall_s": restart["restart_wall_s"],
    }
    shutil.rmtree(base, ignore_errors=True)
    return {"serve_load": out}


def _fleet_extra() -> dict:
    """Replicated-serve extra (ISSUE 20): the fleet's two headline
    figures, measured with replicas as REAL subprocesses behind the
    consistent-hash router. (1) goodput scaling 1 -> 2 replicas on the
    same 4-tenant packable load (distinct seeds per leg so every row is
    a genuine dispatch, never a journal hit); (2) the rolling-deploy
    ledger — every replica bounced under that load with zero
    accepted-then-lost rows, zero duplicates, and the under-deploy TTFR
    p99 against the steady-state p99 on the same bounced fleet."""
    import shutil
    import tempfile
    import threading

    from erasurehead_tpu.serve import loadgen
    from erasurehead_tpu.serve.fleet import FleetSupervisor

    common = dict(
        scheme="naive", n_workers=4, n_stragglers=1, rounds=2,
        n_rows=64, n_cols=8, lr_schedule=0.5, add_delay=True,
        compute_mode="deduped",
    )
    base = tempfile.mkdtemp(prefix="eh-fleet-bench-")
    cache_dir = os.path.join(base, "xla-cache")

    def run_load(sup, seed_base, jobs_per_tenant=4, concurrency=2):
        jobs = {
            f"t{i}": [
                (f"j{i}_{j}", {**common, "seed": seed_base + i * 64 + j})
                for j in range(jobs_per_tenant)
            ]
            for i in range(4)
        }
        t0 = time.perf_counter()
        led = loadgen.run_fleet(
            sup.router.host, sup.router.port, jobs,
            concurrency=concurrency, max_retries=12, timeout=600,
        )
        elapsed = time.perf_counter() - t0
        rows = sum(t.get("rows", 0) for t in led["tenants"].values())
        led["goodput_rows_per_s"] = (
            round(rows / elapsed, 4) if elapsed > 0 else None
        )
        return led

    def fleet(n, tag):
        return FleetSupervisor(
            n=n, base_dir=os.path.join(base, tag), k=3,
            probe_interval_s=0.3, cache_dir=cache_dir,
            extra_args=("--dispatch-workers", "1"),
        )

    out: dict = {}

    # ---- leg 1: single-replica goodput (the scaling denominator) -------
    sup1 = fleet(1, "one")
    sup1.start()
    try:
        solo = run_load(sup1, seed_base=10)
    finally:
        sup1.stop()
    goodput_1 = solo["goodput_rows_per_s"]
    out["one_replica"] = {
        "goodput_rows_per_s": goodput_1,
        "lost": solo["lost"],
        "duplicates": solo["duplicates"],
    }

    # ---- leg 2: two replicas — rolling deploy under load, then steady --
    sup2 = fleet(2, "two")
    sup2.start()
    try:
        ledger: dict = {}

        def deploy():
            time.sleep(1.5)  # let the load establish before draining
            ledger.update(sup2.rolling_deploy())

        t = threading.Thread(target=deploy)
        t.start()
        under_deploy = run_load(sup2, seed_base=1000, jobs_per_tenant=6)
        t.join(timeout=300)
        steady = run_load(sup2, seed_base=2000)
    finally:
        sup2.stop()
    goodput_2 = steady["goodput_rows_per_s"]
    deploy_p99 = under_deploy.get("latency_p99_s")
    steady_p99 = steady.get("latency_p99_s")
    out["rolling_deploy"] = {
        "replicas_bounced": len(ledger),
        "lost": under_deploy["lost"],
        "duplicates": under_deploy["duplicates"],
        "latency_p99_s": deploy_p99,
        "steady_latency_p99_s": steady_p99,
        "p99_deploy_over_steady": (
            round(deploy_p99 / steady_p99, 3)
            if deploy_p99 and steady_p99 else None
        ),
    }
    out["two_replicas"] = {
        "goodput_rows_per_s": goodput_2,
        "goodput_scaling_1_to_2": (
            round(goodput_2 / goodput_1, 3)
            if goodput_1 and goodput_2 else None
        ),
    }

    shutil.rmtree(base, ignore_errors=True)
    return {"fleet": out}


#: adapt extra scenario (ISSUE 8): W=4 non-iid (label-sorted) partitions,
#: exponential delays turning adversarial (worker 0 +8 s) at round 40 of
#: 80, small lr so the target needs near-full-horizon progress. The
#: naive-anchored target sits below the biased arms' post-shift floors
#: (they deterministically exclude the same skewed partition), so only
#: policy SWITCHING reaches it cheaply: the controller trains exact
#: pre-shift and abandons wait-for-all post-shift.
ADAPT_ROUNDS = 80
ADAPT_SHIFT_ROUND = 40
ADAPT_WORKERS = 4
ADAPT_CHUNK = 5
ADAPT_OVERHEAD_BAR_PCT = 2.0  # controller decisions < 2% of run wall


def _adapt_extra() -> dict:
    """Regime-shift adaptive-collection extra: controller overhead per
    chunk (bar: < 2% of the adaptive run's wall-clock) and time-to-target
    vs every static (scheme, collect, deadline) arm under the shift."""
    import dataclasses as _dc

    import numpy as _np

    from erasurehead_tpu import adapt as adapt_lib
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel import straggler
    from erasurehead_tpu.train import evaluate, experiments, trainer
    from erasurehead_tpu.utils.config import RunConfig

    Wa, R = ADAPT_WORKERS, ADAPT_ROUNDS
    ds0 = generate_gmm(960, 16, Wa, seed=0)
    # non-iid partitions: label-sorted rows make each contiguous
    # partition class-skewed, so a policy that deterministically excludes
    # one partition (avoidstragg/deadline under a fixed adversary) has a
    # genuinely biased gradient — the regime the adaptive controller
    # exists for (arXiv:1901.08166's fixed-straggler worst case)
    order = _np.argsort(ds0.y_train, kind="stable")
    ds = _dc.replace(
        ds0, X_train=ds0.X_train[order], y_train=ds0.y_train[order]
    )
    base = RunConfig(
        scheme="naive", n_workers=Wa, n_stragglers=1, rounds=R,
        n_rows=960, n_cols=16, update_rule="GD", lr_schedule=0.1,
        add_delay=True, compute_mode="deduped", seed=0,
    )
    shift = straggler.RegimeShift(
        kind="adversary", round=ADAPT_SHIFT_ROUND, worker=0, slowdown=8.0
    )
    arr = straggler.arrival_schedule(R, Wa, True, regime=shift)
    arms = [
        adapt_lib.Arm("naive"),
        adapt_lib.Arm("avoidstragg"),
        adapt_lib.Arm("deadline", deadline=1.5),
    ]

    def curve(res):
        model = trainer.build_model(base)
        ev = evaluate.replay(
            model, base.model, res.params_history, ds.X_train, ds.y_train,
            ds.X_test, ds.y_test,
        )
        return _np.asarray(ev.training_loss, dtype=_np.float64)

    statics = {}
    for arm in arms:
        cfg = _dc.replace(base, **arm.overrides())
        res = trainer.train(cfg, ds, arrivals=arr, measure=False)
        statics[arm.label] = (curve(res), res.timeset)
    ares = adapt_lib.train_adaptive(
        base, ds, arms=arms,
        controller=adapt_lib.ControllerConfig(
            chunk_rounds=ADAPT_CHUNK, seed=0
        ),
        arrivals=arr,
    )
    adaptive_curve = curve(ares.result)
    target = 1.02 * float(statics["naive"][0][-1])
    t2t = {
        k: experiments.time_to_target_loss(c, t, target)
        for k, (c, t) in statics.items()
    }
    t2t_adaptive = experiments.time_to_target_loss(
        adaptive_curve, ares.result.timeset, target
    )
    beats_all = t2t_adaptive is not None and all(
        v is None or t2t_adaptive < v for v in t2t.values()
    )
    best_static = min((v for v in t2t.values() if v is not None), default=None)
    n_chunks = max(len(ares.decisions), 1)
    overhead_pct = (
        100.0 * ares.decision_overhead_s / ares.total_wall_s
        if ares.total_wall_s > 0
        else 0.0
    )
    switches = sum(
        1
        for a, b in zip(ares.decisions, ares.decisions[1:])
        if a["arm"] != b["arm"]
    )
    return {
        "adapt_overhead_pct": round(overhead_pct, 3),
        "adapt": {
            "rounds": R,
            "shift_round": ADAPT_SHIFT_ROUND,
            "chunk_rounds": ADAPT_CHUNK,
            "arms": [a.label for a in arms],
            "decisions": len(ares.decisions),
            "arm_switches": switches,
            "regime_shift_detected": any(
                d["reason"] == "regime_shift" for d in ares.decisions
            ),
            "controller_overhead_ms_per_chunk": round(
                1000.0 * ares.decision_overhead_s / n_chunks, 3
            ),
            # bar: the controller's own math must cost < 2% of the run
            "controller_overhead_pct": round(overhead_pct, 3),
            "controller_overhead_bar_pct": ADAPT_OVERHEAD_BAR_PCT,
            "target_loss": round(target, 6),
            "time_to_target_static": {
                k: (round(v, 2) if v is not None else None)
                for k, v in t2t.items()
            },
            "time_to_target_adaptive": (
                round(t2t_adaptive, 2) if t2t_adaptive is not None else None
            ),
            "time_to_target_ratio_vs_best_static": (
                round(best_static / t2t_adaptive, 3)
                if t2t_adaptive and best_static
                else None
            ),
            "adaptive_beats_every_static_arm": beats_all,
        },
    }


#: elastic extra scenario (ISSUE 11): W=8 workers, 25% (2 workers) killed
#: at round 12 of 36, master patience (timeout) 4 s vs 0.5 s mean delays.
#: Three recoveries race to the same loss target on the same world:
#:   (a) elastic    — the online membership controller (elastic/):
#:                    detection costs ~death_rounds timeout-priced rounds,
#:                    then a W'=6 re-layout trains at full speed with every
#:                    partition contributing;
#:   (b) limping    — the static run keeps the dead workers in the layout
#:                    for the whole horizon (failover decode), paying the
#:                    timeout EVERY post-kill round — the reference's
#:                    hang-forever, priced instead of infinite;
#:   (c) restart    — notice the death and relaunch on the survivors from
#:                    SCRATCH: pre-kill progress is thrown away and the
#:                    loss curve re-pays it.
#: Bar: elastic time-to-target < both.
ELASTIC_WORKERS = 8
ELASTIC_ROUNDS = 36
ELASTIC_KILL_ROUND = 12
ELASTIC_DEAD = (6, 7)  # 25% of the cluster
ELASTIC_TIMEOUT = 4.0
ELASTIC_CHUNK = 6
ELASTIC_DEATH_ROUNDS = 2


def _elastic_extra() -> dict:
    """Time-to-target under a mid-run 25% worker loss: the elastic
    membership controller vs keep-limping vs restart-from-scratch."""
    import dataclasses as _dc

    import numpy as _np

    from erasurehead_tpu import elastic as elastic_lib
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.parallel import failures
    from erasurehead_tpu.train import evaluate, experiments, trainer
    from erasurehead_tpu.utils.config import RunConfig

    We, R = ELASTIC_WORKERS, ELASTIC_ROUNDS
    kill = ELASTIC_KILL_ROUND
    ds = generate_gmm(64 * We, 16, We, seed=0)
    cfg = RunConfig(
        scheme="naive", n_workers=We, n_stragglers=1, rounds=R,
        n_rows=64 * We, n_cols=16, update_rule="AGD", lr_schedule=1.0,
        add_delay=True, seed=0,
    )
    deaths = {w: kill for w in ELASTIC_DEAD}
    world = failures.inject_worker_death(
        trainer.default_arrivals(cfg), deaths
    )

    def curve(result):
        model = trainer.build_model(cfg)
        n = result.n_train
        ev = evaluate.replay(
            model, cfg.model, result.params_history,
            ds.X_train[:n], ds.y_train[:n], ds.X_test, ds.y_test,
        )
        return _np.asarray(ev.training_loss, dtype=_np.float64)

    # (a) elastic: online detection + W'=6 re-layout
    eres = elastic_lib.train_elastic_online(
        cfg, ds,
        elastic=elastic_lib.ElasticConfig(
            chunk_rounds=ELASTIC_CHUNK,
            death_rounds=ELASTIC_DEATH_ROUNDS,
            timeout=ELASTIC_TIMEOUT,
        ),
        deaths=deaths,
    )
    curve_a, time_a = curve(eres.result), eres.result.timeset

    # (b) keep limping: dead workers stay in the layout; every infeasible
    # round degrades to the failover decode at the full timeout price
    layout = trainer.build_layout(cfg)
    sched, _rep = failures.plan_run(
        cfg.scheme, layout, world, num_collect=cfg.num_collect,
        timeout=ELASTIC_TIMEOUT, on_infeasible="failover",
    )
    limp = trainer.train(
        cfg, ds, arrivals=world, schedule=sched, measure=False
    )
    curve_b, time_b = curve(limp), limp.timeset

    # (c) restart from scratch on the survivors. A scratch restart pays
    # the SAME detection latency the controller did (nobody can restart
    # before noticing the death — the timeout-priced rounds up to the
    # re-layout boundary come straight from the elastic run's own
    # decisions), then relaunches a fresh W'=6 run from init: identical
    # clock prefix, but the pre-kill progress is thrown away. The
    # comparison therefore isolates exactly the controller's value: the
    # carried-over optimizer state.
    relayout_round = next(
        d["round"] for d in eres.decisions if d["action"] == "relayout"
    )
    survivors = [w for w in range(We) if w not in ELASTIC_DEAD]
    cfg_scratch = failures.survivor_config(cfg, len(survivors))
    scratch = trainer.train(
        cfg_scratch, ds,
        arrivals=world[:, survivors], measure=False,
    )
    curve_c = _np.concatenate(
        [curve_a[:relayout_round], curve(scratch)]
    )
    time_c = _np.concatenate(
        [time_a[:relayout_round], scratch.timeset]
    )

    # shared target: reachable by every contender (2% above the WORST
    # final loss), so the comparison is about time, not attainability
    target = 1.02 * float(
        max(curve_a[-1], curve_b[-1], curve_c[-1])
    )
    t2t = {
        "elastic": experiments.time_to_target_loss(curve_a, time_a, target),
        "limping": experiments.time_to_target_loss(curve_b, time_b, target),
        "restart": experiments.time_to_target_loss(curve_c, time_c, target),
    }
    t_el = t2t["elastic"]
    beats_limping = t_el is not None and (
        t2t["limping"] is None or t_el < t2t["limping"]
    )
    beats_restart = t_el is not None and (
        t2t["restart"] is None or t_el < t2t["restart"]
    )
    relayouts = [
        d for d in eres.decisions if d["action"] == "relayout"
    ]
    return {
        "elastic": {
            "workers": We,
            "rounds": R,
            "kill_round": kill,
            "killed_workers": list(ELASTIC_DEAD),
            "killed_fraction": round(len(ELASTIC_DEAD) / We, 3),
            "timeout_s": ELASTIC_TIMEOUT,
            "chunk_rounds": ELASTIC_CHUNK,
            "death_rounds": ELASTIC_DEATH_ROUNDS,
            "relayouts": len(relayouts),
            "detected_dead": sorted(
                w for d in relayouts for w in d.get("dead", [])
            ),
            "target_loss": round(target, 6),
            "time_to_target_s": {
                k: (round(v, 2) if v is not None else None)
                for k, v in t2t.items()
            },
            # the acceptance bars: elastic beats BOTH baselines
            "elastic_beats_limping": beats_limping,
            "elastic_beats_restart": beats_restart,
            "speedup_vs_limping": (
                round(t2t["limping"] / t_el, 3)
                if t_el and t2t["limping"]
                else None
            ),
            "speedup_vs_restart": (
                round(t2t["restart"] / t_el, 3)
                if t_el and t2t["restart"]
                else None
            ),
        },
    }


#: deep_cohort extra: a 7-scheme x 4-seed DEEP-MODEL cohort at W=30
#: racing the sequential cached path (the PR 4 amortization win, repeated
#: off the convex GLMs), plus a decode-error-vs-depth series from
#: blockwise-coded deepmlp runs (obs/decode.block_decode_error). Shapes
#: are sweep-shaped on purpose: many small trajectories is the workload
#: the cohort engine exists for, and per-run dispatch overhead is what
#: the single dispatch amortizes away on CPU (BASELINE.md "Deep-model
#: cohorts" carries the measured rows).
DEEP_MODEL = "mlp"  # the autodiff margin family (grads_via_loss path)
DEEP_ROUNDS = 4
DEEP_SEEDS = (0, 1, 2, 3)
DEEP_ROWS, DEEP_COLS = 60, 32
DEEP_DEPTHS = (2, 4, 8)  # deepmlp hidden-layer counts for the err-vs-depth series


def _deep_cohort_extra() -> dict:
    """Deep-model trajectory-batched sweep vs the sequential cached path
    (bar >= 3x, same shape as sweep7), plus the decode-error-vs-depth
    series emitted through obs/decode + the events capture."""
    import jax

    from erasurehead_tpu.data.sharding import partition_stack
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.obs import decode as obs_decode
    from erasurehead_tpu.obs import events as obs_events
    from erasurehead_tpu.ops import blocks as blocks_lib
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    data = generate_gmm(DEEP_ROWS, DEEP_COLS, n_partitions=W, seed=0)
    common = dict(
        model=DEEP_MODEL, n_workers=W, n_stragglers=S, rounds=DEEP_ROUNDS,
        n_rows=DEEP_ROWS, n_cols=DEEP_COLS, update_rule="GD",
        lr_schedule=0.1, add_delay=True, compute_mode="deduped",
    )
    schemes = [
        ("naive", {}),
        ("cyccoded", {}),
        ("repcoded", {}),
        ("approx", {"num_collect": COLLECT}),
        ("avoidstragg", {}),
        ("randreg", {"num_collect": COLLECT}),
        ("deadline", {"deadline": 1.0}),
    ]
    cfgs = [
        RunConfig(**{**common, **extra, "scheme": s, "seed": sd})
        for s, extra in schemes
        for sd in DEEP_SEEDS
    ]
    B = len(cfgs)
    # steady-state race: one warm pass per path (compile + program load),
    # then min over repeats — walls are milliseconds here, so single-shot
    # numbers would measure scheduler noise, not the dispatch structure
    cohort = trainer.train_cohort(cfgs, data)
    cohort_wall = min(
        min(r.wall_time for r in trainer.train_cohort(cfgs, data))
        for _ in range(3)
    )
    for c in cfgs:
        trainer.train(c, data)
    seq_wall = min(
        sum(trainer.train(c, data).wall_time for c in cfgs)
        for _ in range(2)
    )
    agg_rate = B * DEEP_ROUNDS / cohort_wall if cohort_wall > 0 else 0.0
    seq_rate = B * DEEP_ROUNDS / seq_wall if seq_wall > 0 else 0.0

    # ---- decode-error-vs-depth: blockwise-coded deepmlp under real
    # straggling; per-layer gradient-space error from the model's own
    # partition grad blocks at the trained params, emitted as
    # layer-tagged decode chunk streams into the bench events capture
    Wd = 8
    depth_data = generate_gmm(128, 32, n_partitions=Wd, seed=1)
    depth_rows = {}
    for depth in DEEP_DEPTHS:
        dcfg = RunConfig(
            scheme="approx", model="deepmlp", deep_layers=depth,
            layer_coding="on", n_workers=Wd, n_stragglers=1, num_collect=5,
            rounds=6, n_rows=128, n_cols=32, update_rule="GD",
            lr_schedule=0.1, add_delay=True, compute_mode="deduped",
        )
        res = trainer.train(dcfg, depth_data)
        model = trainer.build_model(dcfg)
        spec = blocks_lib.model_block_spec(
            model, model.init_params(jax.random.key(0), 32)
        )
        Xp, yp = partition_stack(depth_data, res.layout.n_partitions)
        table = blocks_lib.partition_block_table(
            model, spec, res.final_params, Xp, yp
        )
        from erasurehead_tpu.parallel import collect

        sched = collect.build_schedule(
            dcfg.scheme, trainer.default_arrivals(dcfg), res.layout,
            num_collect=dcfg.num_collect, deadline=dcfg.deadline,
            decode=dcfg.decode,
        )
        errs = obs_decode.block_decode_error(
            res.layout, sched.message_weights, table
        )
        run_id = res.run_id or obs_events.new_run_id()
        obs_events.emit_layer_decode_chunks(
            run_id, errs["per_block"], trajectory=f"depth{depth}"
        )
        depth_rows[str(depth)] = {
            "n_blocks": int(errs["per_block"].shape[1]),
            "mean_block_error": round(float(errs["per_block"].mean()), 8),
            "max_cumulative_error": round(
                float(errs["cumulative"][:, -1].max()), 8
            ),
        }
    return {
        "deep_cohort_speedup": (
            round(seq_wall / cohort_wall, 3) if cohort_wall > 0 else 0.0
        ),
        "deep_cohort": {
            "model": DEEP_MODEL,
            "n_trajectories": B,
            "n_schemes": len(schemes),
            "n_seeds": len(DEEP_SEEDS),
            "rounds": DEEP_ROUNDS,
            "rows": DEEP_ROWS,
            "cols": DEEP_COLS,
            "dispatches": cohort[0].cache_info.get("cohort_dispatches"),
            "lowering": cohort[0].cache_info.get("cohort_lowering"),
            "aggregate_trajectories_per_sec": (
                round(B / cohort_wall, 2) if cohort_wall > 0 else 0.0
            ),
            "aggregate_steps_per_sec": round(agg_rate, 2),
            "sequential_cached_steps_per_sec": round(seq_rate, 2),
            "speedup_vs_sequential_cached": (
                round(seq_wall / cohort_wall, 3) if cohort_wall > 0 else 0.0
            ),
            "cohort_wall_s": round(cohort_wall, 5),
            "sequential_cached_wall_s": round(seq_wall, 5),
            "decode_error_vs_depth": depth_rows,
        },
    }


#: whatif extra grid shape: 7 policies x 1 (W, s) x 1 regime x
#: WHATIF_SEEDS Monte-Carlo seeds — hundreds of simulated runs that the
#: engine rides through a handful of cohort dispatches, raced against a
#: SAMPLED sequential single-run simulation (per-run train + eval replay,
#: extrapolated over the grid; measuring all of them sequentially would
#: dominate the bench's own timeout — which is exactly the point)
WHATIF_WORKERS = 6
WHATIF_ROUNDS = 20
WHATIF_SEEDS = 48
WHATIF_SEQ_SAMPLE = 6
WHATIF_SPEEDUP_BAR = 100.0
#: bandit-regret measurement: chunks of the pure-controller drive, and
#: the per-chunk environment's Monte-Carlo seed base
WHATIF_REGRET_CHUNKS = 12


def _whatif_extra() -> dict:
    """What-if engine extra: simulated-runs/sec of the Monte-Carlo grid
    engine (steady-state; the cold first pass is reported alongside) vs
    sequential single-run simulation at a fixed grid (bar: >=
    WHATIF_SPEEDUP_BAR x), plus measured bandit regret with
    surface-derived priors on vs off (bar: lower with priors)."""
    import time as _time

    import numpy as _np

    from erasurehead_tpu import adapt as adapt_lib
    from erasurehead_tpu.parallel import collect as collect_lib
    from erasurehead_tpu.train import evaluate, trainer
    from erasurehead_tpu.whatif import (
        GridSpec,
        PolicySpec,
        RegimeSpec,
        run_whatif,
        sample_arrivals,
    )

    Ww, R, S = WHATIF_WORKERS, WHATIF_ROUNDS, WHATIF_SEEDS
    spec = GridSpec(
        policies=(
            PolicySpec("naive"),
            PolicySpec("cyccoded"),
            PolicySpec("repcoded"),
            PolicySpec("approx", num_collect=3),
            PolicySpec("avoidstragg"),
            PolicySpec("randreg", num_collect=3),
            PolicySpec("deadline", deadline=1.0),
        ),
        n_workers=(Ww,), n_stragglers=(1,),
        regimes=(RegimeSpec(mean=0.5),),
        n_seeds=S, rounds=R, n_rows=96, n_cols=8,
    )
    # cold pass (pays the one-time jit compiles of the sampler, the
    # cohort scan and the batched replay), then a warm pass of the SAME
    # spec — the steady-state rate a re-primed bandit / refreshed serve
    # surface actually runs at, and the rate the >=100x bar is on
    t0 = _time.perf_counter()
    surf = run_whatif(spec)
    cold_wall = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    surf = run_whatif(spec)
    engine_wall = _time.perf_counter() - t0
    n_runs = surf.stats["n_trajectories"]
    cold_rate = n_runs / cold_wall if cold_wall > 0 else 0.0
    engine_rate = n_runs / engine_wall if engine_wall > 0 else 0.0

    # sequential baseline: SINGLE-RUN simulation — each (point, seed)
    # dispatched on its own, paying its own trace + compile + upload +
    # scan + replay, exactly what N independent single-run invocations
    # (the pre-engine way to build a surface) pay per run. The sweep
    # caches are this repo's own in-process feature, so they are OFF for
    # the baseline (a cached sequential sweep is measured separately by
    # the sweep7 extra); a sample of WHATIF_SEQ_SAMPLE runs extrapolates
    # over the grid — a full cold sequential sweep would dominate the
    # bench timeout, which is the point being measured.
    from erasurehead_tpu.train import cache as cache_lib
    from erasurehead_tpu.whatif import enumerate_points

    points = [p for p in enumerate_points(spec) if p.feasible]
    from erasurehead_tpu.data.synthetic import generate_gmm

    ds = generate_gmm(96, 8, Ww, seed=spec.data_seed)
    sample: list = []
    cache_lib.set_enabled(False)
    try:
        for i in range(WHATIF_SEQ_SAMPLE):
            p = points[i % len(points)]
            arr = sample_arrivals(
                p.regime, R, Ww, [i], layout=trainer.build_layout(p.config)
            )[0]
            t1 = _time.perf_counter()
            res = trainer.train(p.config, ds, arrivals=arr, measure=False)
            model = trainer.build_model(p.config)
            evaluate.replay(
                model, p.config.model, res.params_history,
                ds.X_train[: res.n_train], ds.y_train[: res.n_train],
                ds.X_test, ds.y_test,
            )
            sample.append(_time.perf_counter() - t1)
    finally:
        cache_lib.set_enabled(True)
    seq_per_run = float(_np.mean(sample))
    seq_rate = 1.0 / seq_per_run if seq_per_run > 0 else 0.0
    speedup = engine_rate / seq_rate if seq_rate > 0 else 0.0

    # bandit regret, priors on vs off: drive the controller against a
    # deterministic simulated environment — per-chunk per-arm rewards
    # computed from each arm's own collection schedule over ONE sampled
    # arrival stream (the controller's time_error reward, the same units
    # the surface priors are in). Regret per chunk = best arm's reward
    # minus the chosen arm's.
    arms = [
        adapt_lib.Arm("naive"),
        adapt_lib.Arm("avoidstragg"),
        adapt_lib.Arm("approx", num_collect=3),
        adapt_lib.Arm("cyccoded"),
    ]
    chunk = R
    horizon = WHATIF_REGRET_CHUNKS
    env = sample_arrivals(
        spec.regimes[0], chunk * horizon, Ww, [10_007]
    )[0]
    arm_stats: dict = {}
    for arm in arms:
        import dataclasses as _dc

        acfg = _dc.replace(
            points[0].config, rounds=chunk * horizon, **arm.overrides()
        )
        layout = trainer.build_layout(acfg)
        sched = collect_lib.build_schedule(
            acfg.scheme, env, layout,
            num_collect=acfg.num_collect, deadline=acfg.deadline,
        )
        err = surf.lookup(
            arm.scheme, n_workers=Ww, n_stragglers=1,
            num_collect=arm.num_collect, deadline=arm.deadline,
        )
        err_mean = float((err or {}).get("decode_error_mean") or 0.0)
        arm_stats[arm.label] = [
            adapt_lib.ChunkStats(
                n_rounds=chunk,
                sim_time=float(sched.sim_time[c * chunk:(c + 1) * chunk].sum()),
                decode_error_mean=err_mean,
                arrival_mean=float(env[c * chunk:(c + 1) * chunk].mean()),
                arrival_p90=None,
            )
            for c in range(horizon)
        ]

    def drive(priors):
        ctl = adapt_lib.AdaptiveController(
            arms,
            adapt_lib.ControllerConfig(
                chunk_rounds=chunk, reward_mode="time_error", seed=0
            ),
            priors=priors,
        )
        regret = 0.0
        for c in range(horizon):
            rewards = {
                a.label: ctl.reward(arm_stats[a.label][c]) for a in arms
            }
            idx, _reason = ctl.choose()
            chosen = arms[idx].label
            ctl.observe(idx, arm_stats[chosen][c])
            regret += max(rewards.values()) - rewards[chosen]
        return regret

    priors = surf.adapt_priors(arms, n_workers=Ww, n_stragglers=1)
    regret_off = drive(None)
    regret_on = drive(priors)

    return {
        "whatif_simulated_runs_per_sec": round(engine_rate, 2),
        "whatif": {
            "grid_points": len(surf.rows),
            "feasible_points": len(points),
            "n_seeds": S,
            "rounds": R,
            "simulated_runs": n_runs,
            "engine_cold_wall_s": round(cold_wall, 4),
            "engine_cold_runs_per_sec": round(cold_rate, 2),
            "engine_wall_s": round(engine_wall, 4),
            "simulated_runs_per_sec": round(engine_rate, 2),
            "sequential_run_s": round(seq_per_run, 4),
            "sequential_runs_per_sec": round(seq_rate, 3),
            # the baseline is a SAMPLE extrapolated over the grid (this
            # many timed cold single-run dispatches, sweep caches off —
            # what N independent invocations pay), not a full sweep
            "sequential_sampled_runs": WHATIF_SEQ_SAMPLE,
            "sequential_mode": "cold single-run dispatch (caches off)",
            "speedup_vs_sequential": round(speedup, 1),
            "speedup_bar": WHATIF_SPEEDUP_BAR,
            "speedup_bar_met": bool(speedup >= WHATIF_SPEEDUP_BAR),
            "regret_chunks": horizon,
            "regret_arms": [a.label for a in arms],
            "priors": {k: round(v, 6) for k, v in priors.items()},
            "bandit_regret_priors_off": round(regret_off, 6),
            "bandit_regret_priors_on": round(regret_on, 6),
            "priors_reduce_regret": bool(regret_on < regret_off),
        },
    }


#: pipeline extra scenario (ISSUE 16): sync vs pipelined (tau=1)
#: time-to-target under exp(2.0) straggling, W=8 s=1 avoidstragg on the
#: 256x16 GMM. Avoidstragg is where the overlap win is big: the
#: synchronous round pays the (W-s)th order statistic of exp(2.0) every
#: round, while the pipelined round overlaps round t+1's dispatch with
#: round t's drain. lr_schedule is EXPLICIT: the default schedule sits at
#: GD's stability edge and tau=1 staleness shrinks the stable region.
PIPELINE_WORKERS = 8
PIPELINE_STRAGGLERS = 1
PIPELINE_ROUNDS = 80
PIPELINE_ROWS = 256
PIPELINE_COLS = 16
PIPELINE_DELAY_MEAN = 2.0
PIPELINE_TARGET_LOSS = 0.15
PIPELINE_SEEDS = (3, 4, 5)
PIPELINE_SPEEDUP_BAR = 1.5


def _pipeline_extra() -> dict:
    """Pipelined-training extra: sync vs tau=1 pipelined time-to-target
    (simulated seconds, identical arrival draws) under exp(2.0)
    straggling, over PIPELINE_SEEDS straggler worlds (bar: min speedup >=
    PIPELINE_SPEEDUP_BAR x). The extra params slot the pipelined carry
    threads is recorded from cache_info (pipeline_params_slot_bytes — the
    +1 slot serve admission charges), and the staleness-vs-coding error
    decomposition (obs/decode.emit_staleness_split) rides along for the
    last seed."""
    import numpy as _np

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.obs import decode as decode_lib
    from erasurehead_tpu.train import evaluate, experiments, trainer
    from erasurehead_tpu.utils.config import RunConfig

    ds = generate_gmm(
        PIPELINE_ROWS, PIPELINE_COLS,
        n_partitions=PIPELINE_WORKERS, seed=0,
    )
    common = dict(
        scheme="avoidstragg",
        n_workers=PIPELINE_WORKERS,
        n_stragglers=PIPELINE_STRAGGLERS,
        rounds=PIPELINE_ROUNDS,
        n_rows=PIPELINE_ROWS,
        n_cols=PIPELINE_COLS,
        update_rule="GD",
        compute_mode="deduped",
        add_delay=True,
        delay_mean=PIPELINE_DELAY_MEAN,
        lr_schedule=1.0,
    )

    def t2t(result):
        model = trainer.build_model(result.config)
        n = result.n_train
        ev = evaluate.replay(
            model, result.config.model, result.params_history,
            ds.X_train[:n], ds.y_train[:n], ds.X_test, ds.y_test,
        )
        loss = _np.asarray(ev.training_loss, dtype=_np.float64)
        return experiments.time_to_target_loss(
            loss, result.timeset, PIPELINE_TARGET_LOSS
        ), float(loss[-1])

    races, speedups = [], []
    slot_bytes = None
    split = None
    for sd in PIPELINE_SEEDS:
        sync = trainer.train(
            RunConfig(**common, seed=sd), ds, measure=False
        )
        pipe = trainer.train(
            RunConfig(**common, seed=sd, pipeline_depth=1),
            ds, measure=False,
        )
        t_sync, loss_sync = t2t(sync)
        t_pipe, loss_pipe = t2t(pipe)
        speedup = (
            round(t_sync / t_pipe, 3) if t_sync and t_pipe else None
        )
        if speedup is not None:
            speedups.append(speedup)
        slot_bytes = (pipe.cache_info or {}).get(
            "pipeline_params_slot_bytes"
        )
        split = decode_lib.emit_staleness_split("bench-pipeline", pipe, ds)
        races.append({
            "seed": sd,
            "sync_time_to_target_s": (
                round(t_sync, 3) if t_sync is not None else None
            ),
            "pipelined_time_to_target_s": (
                round(t_pipe, 3) if t_pipe is not None else None
            ),
            "sync_final_loss": round(loss_sync, 6),
            "pipelined_final_loss": round(loss_pipe, 6),
            "speedup": speedup,
        })
    min_speedup = min(speedups) if speedups else None
    return {
        "pipeline": {
            "scheme": common["scheme"],
            "workers": PIPELINE_WORKERS,
            "stragglers": PIPELINE_STRAGGLERS,
            "rounds": PIPELINE_ROUNDS,
            "delay": f"exp({PIPELINE_DELAY_MEAN})",
            "target_loss": PIPELINE_TARGET_LOSS,
            "races": races,
            "min_speedup": min_speedup,
            "speedup_bar": PIPELINE_SPEEDUP_BAR,
            "speedup_bar_met": bool(
                min_speedup is not None
                and min_speedup >= PIPELINE_SPEEDUP_BAR
            ),
            # memory honesty (BASELINE.md): the pipelined carry's extra
            # params slot, as charged to serve admission
            "pipeline_params_slot_bytes": slot_bytes,
            # staleness-vs-coding error decomposition of the last race's
            # pipelined run (obs/decode.py)
            "staleness_split": {
                k: v for k, v in (split or {}).items()
                if k.endswith("_mean") or k == "staleness_share"
            },
        },
    }


#: live-telemetry-plane extra (ISSUE 18): the full plane armed (events
#: capture + attached streaming reducer) vs dark, at the flagship W=30
#: worker count on a CPU-sized row budget. The plane is host-side and
#: outside jit by construction, so the bar is tight: fastest armed wall
#: within OBS_OVERHEAD_BAR_PCT of the fastest dark wall, trajectories
#: bitwise-identical (median paired armed-minus-dark delta over the
#: fastest dark wall).
#: the PR-3 telemetry-overhead methodology (BASELINE.md "Run telemetry
#: overhead"): the flagship CPU slice, cache-warm, median of repeats —
#: at smaller shapes the fixed ~35 us/round host emission dominates and
#: the percentage is meaningless. Min-of-OBS_REPEATS interleaved walls.
OBS_WORKERS = 30
OBS_STRAGGLERS = 2
OBS_ROUNDS = 100
OBS_ROWS = 13200  # 440 rows/worker — the bench.py CPU slice
OBS_COLS = 128
OBS_REPEATS = 9
OBS_OVERHEAD_BAR_PCT = 2.0
OBS_REGIME_SEEDS = (0, 1, 2)
OBS_REGIME_BUDGET_ROUNDS = 4  # detect_rounds: short-window length


def _obs_extra() -> dict:
    """Live-telemetry-plane extra: wall overhead of training with the
    full plane armed (JSONL capture + attached streaming reducer +
    critical-path attribution) vs dark (bar: min-of-N overhead <=
    OBS_OVERHEAD_BAR_PCT%, trajectories bitwise), plus the regime
    estimator's detection latency and post-shift classification on an
    injected exp(0.05) -> Pareto(1.2) heavy-tail shift."""
    import tempfile as _tempfile

    import numpy as _np

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.obs import events as obs_events
    from erasurehead_tpu.obs import regime as regime_lib
    from erasurehead_tpu.obs.timeseries import TimeseriesReducer
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    cfg = RunConfig(
        scheme="approx", n_workers=OBS_WORKERS,
        n_stragglers=OBS_STRAGGLERS, num_collect=COLLECT,
        rounds=OBS_ROUNDS, n_rows=OBS_ROWS, n_cols=OBS_COLS,
        update_rule="GD", lr_schedule=1.0, add_delay=True,
        compute_mode="deduped", seed=0,
    )
    ds = generate_gmm(OBS_ROWS, OBS_COLS, OBS_WORKERS, seed=0)
    tmpdir = _tempfile.mkdtemp(prefix="eh-bench-obs-")

    def run_dark():
        t0 = time.perf_counter()
        res = trainer.train(cfg, ds, measure=False)
        return time.perf_counter() - t0, res

    def run_armed(idx):
        red = TimeseriesReducer()
        handle = red.attach()
        path = os.path.join(tmpdir, f"events_{idx}.jsonl")
        try:
            t0 = time.perf_counter()
            with obs_events.capture(path):
                res = trainer.train(cfg, ds, measure=False)
            wall = time.perf_counter() - t0
        finally:
            handle.detach()
        return wall, res, path, red

    # warm BOTH paths out of the measurement (exec/data caches, module
    # imports on the armed side), then interleave timed dark/armed pairs;
    # the overhead estimate is the MEDIAN PAIRED delta — back-to-back
    # pairs see the same host load, so slow drift cancels, and the median
    # discards pairs where a preemption burst hit one member
    run_dark()
    run_armed(-1)
    dark_walls, armed_walls = [], []
    ref = events_n = None
    cp_ok = reducer_rounds = None
    bitwise = True
    for i in range(OBS_REPEATS):
        dw, dres = run_dark()
        aw, ares, path, red = run_armed(i)
        dark_walls.append(dw)
        armed_walls.append(aw)
        if ref is None:
            ref = dres
        for a, b in zip(
            _jax_leaves(dres.params_history),
            _jax_leaves(ares.params_history),
        ):
            if not _np.array_equal(_np.asarray(a), _np.asarray(b)):
                bitwise = False
        if i == OBS_REPEATS - 1:
            with open(path) as f:
                recs = [json.loads(line) for line in f if line.strip()]
            events_n = len(recs)
            cps = [r for r in recs if r["type"] == "critical_path"]
            cp_ok = bool(
                len(cps) == 1
                and not obs_events.validate_file(path)
            )
            snap = red.snapshot()
            reducer_rounds = sum(w["rounds"] for w in snap["windows"])
    dark_med = min(dark_walls)
    armed_med = min(armed_walls)
    deltas = sorted(a - d for d, a in zip(dark_walls, armed_walls))
    delta_med = deltas[len(deltas) // 2]
    overhead_pct = (
        100.0 * delta_med / dark_med if dark_med > 0 else 0.0
    )

    # regime detection latency: rounds from an injected exp -> heavy-tail
    # shift to the estimator's verdict, and whether the post-shift window
    # is actually CLASSIFIED heavytail (Hill index under 2)
    latencies, kinds_after = [], []
    for sd in OBS_REGIME_SEEDS:
        rng = _np.random.default_rng(sd)
        est = regime_lib.ArrivalRegimeEstimator(
            detect_rounds=OBS_REGIME_BUDGET_ROUNDS
        )
        est.update_rounds(0, rng.exponential(0.05, (20, OBS_WORKERS)))
        post = rng.pareto(1.2, (40, OBS_WORKERS)) + 1.0
        first = None
        for r in range(40):
            if est.update(20 + r, post[r]).shifted and first is None:
                first = r
        if first is not None:
            latencies.append(first)
        kinds_after.append(est.estimate().kind)
    detect_ok = (
        len(latencies) == len(OBS_REGIME_SEEDS)
        and max(latencies) < OBS_REGIME_BUDGET_ROUNDS
        and all(k == "heavytail" for k in kinds_after)
    )
    return {
        "obs_overhead_pct": round(overhead_pct, 3),
        "obs": {
            "workers": OBS_WORKERS,
            "rounds": OBS_ROUNDS,
            "repeats": OBS_REPEATS,
            "dark_wall_s": round(dark_med, 4),
            "armed_wall_s": round(armed_med, 4),
            "paired_delta_ms": round(1000.0 * delta_med, 3),
            "overhead_bar_pct": OBS_OVERHEAD_BAR_PCT,
            # bar: the armed plane costs <= 2% of the dark run's wall
            "overhead_ok": overhead_pct <= OBS_OVERHEAD_BAR_PCT,
            # the observation-only contract, re-pinned at bench shape
            "bitwise_identical": bitwise,
            "events_per_run": events_n,
            "critical_path_ok": cp_ok,
            "reducer_rounds_seen": reducer_rounds,
            "regime_detect_latency_rounds": {
                "per_seed": latencies,
                "budget": OBS_REGIME_BUDGET_ROUNDS,
                "post_shift_kind": kinds_after,
                "ok": detect_ok,
            },
        },
    }


def _tune_extra() -> dict:
    """Autotuning-plane extra (erasurehead_tpu/tune/): the cost ledger of
    the measured-decision ladder. Races the blockwise-cohort decode pair
    (fused per-leaf contraction vs treewise pack-then-einsum, the
    resolve_block_decode knob) cold into a fresh decision cache, then
    times the warm cached resolution the training path actually pays
    (bar: < 1 ms — resolution must be free, racing is the explicit
    one-time spend). The two candidates are bitwise-identical
    trajectories, so the race is purely about time; the recorded CPU
    verdict lands beside the PR 9 blockwise row in BASELINE.md."""
    import tempfile as _tempfile

    from erasurehead_tpu import tune as tune_lib
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.tune import races as tune_races
    from erasurehead_tpu.utils.config import RunConfig

    cache_path = os.path.join(
        _tempfile.mkdtemp(prefix="eh-bench-tune-"), "decisions.json"
    )
    prev = os.environ.get(tune_lib.ENV_PATH)
    os.environ[tune_lib.ENV_PATH] = cache_path
    tune_lib.reset()
    tune_lib.reset_emitted()
    try:
        cfg = RunConfig(
            scheme="approx", model="deepmlp", n_workers=8,
            n_stragglers=1, num_collect=6, rounds=8, n_rows=512,
            n_cols=64, update_rule="AGD", lr_schedule=0.5,
            add_delay=True, seed=0, layer_coding="on",
        )
        ds = generate_gmm(
            cfg.n_rows, cfg.n_cols, n_partitions=cfg.n_workers, seed=0
        )
        t0 = time.perf_counter()
        res = tune_races.race_block_decode(cfg, ds, reps=3)
        race_wall = time.perf_counter() - t0
        # the warm path: the dict lookup every later run resolves through
        model, X = trainer.resolved_stack(cfg, ds)
        sig = tune_lib.run_shape_signature(model, X)
        reps = 50
        t0 = time.perf_counter()
        for _ in range(reps):
            tune_lib.lookup("block_decode", sig)
        warm_s = (time.perf_counter() - t0) / reps
        return {
            "tune": {
                "race": res.race,
                "shape": res.shape,
                "device_kind": res.device_kind,
                "choice": res.choice,
                "decisive": res.decisive,
                "timings_ms": {
                    k: round(v * 1e3, 3)
                    for k, v in sorted(res.timings.items())
                },
                "fused_vs_treewise": round(
                    res.timings["treewise"] / res.timings["fused"], 3
                ),
                "race_wall_s": round(race_wall, 3),
                "warm_resolve_ms": round(warm_s * 1e3, 4),
                # bar: warm resolution costs nothing a step would notice
                "warm_resolve_ok": warm_s < 1e-3,
            }
        }
    finally:
        if prev is None:
            os.environ.pop(tune_lib.ENV_PATH, None)
        else:
            os.environ[tune_lib.ENV_PATH] = prev
        tune_lib.reset()
        tune_lib.reset_emitted()


def _jax_leaves(tree):
    import jax

    return jax.tree.leaves(tree)


def _fidelity_extra(cfg, data, result) -> dict:
    """Fidelity evidence for a lossy/compressed stack: final train/test
    loss of this run vs an f32-stack reference run of the IDENTICAL
    config and schedule (exec/data caches make the reference cheap on
    repeat captures). The eval replays on the full-precision host data,
    so the deltas measure what the compressed gradient pass actually cost
    the science — the knob ships with numbers, not vibes."""
    import dataclasses

    import jax

    from erasurehead_tpu.train import evaluate, trainer

    ref = trainer.train(
        dataclasses.replace(cfg, stack_dtype="float32", dtype="float32"),
        data,
    )
    model = trainer.build_model(cfg)

    def final_losses(res):
        last = jax.tree.map(lambda l: l[-1:], res.params_history)
        n = res.n_train
        ev = evaluate.replay(
            model, cfg.model, last, data.X_train[:n], data.y_train[:n],
            data.X_test, data.y_test,
        )
        return float(ev.training_loss[-1]), float(ev.testing_loss[-1])

    train_loss, test_loss = final_losses(result)
    ref_train, ref_test = final_losses(ref)
    return {
        "fidelity": {
            "stack_dtype": cfg.resolve_stack_dtype(),
            "final_train_loss": round(train_loss, 8),
            "f32_final_train_loss": round(ref_train, 8),
            "eval_loss_delta": round(train_loss - ref_train, 8),
            "final_test_loss": round(test_loss, 8),
            "f32_final_test_loss": round(ref_test, 8),
            "eval_test_loss_delta": round(test_loss - ref_test, 8),
            "mean_decode_error": (
                round(
                    float(sum(result.decode_error))
                    / max(len(result.decode_error), 1),
                    8,
                )
                if result.decode_error is not None
                else None
            ),
        }
    }


#: outofcore extra shape knobs (small enough for the CPU fallback; the
#: claim structure — fixed resident window, 100x the rows — is identical
#: on an accelerator, just bigger)
OUTOFCORE_WORKERS = int(os.environ.get("BENCH_OUTOFCORE_WORKERS", "8"))
OUTOFCORE_ROUNDS = int(os.environ.get("BENCH_OUTOFCORE_ROUNDS", "24"))
OUTOFCORE_SCALE = int(os.environ.get("BENCH_OUTOFCORE_SCALE", "100"))
#: rows-per-worker for the overhead comparison: large enough that chunk
#: compute amortizes the fixed staging cost (at tiny shapes everything
#: is staging and the ratio measures noise, not the pipeline)
OUTOFCORE_COMP_ROWS_PW = int(
    os.environ.get("BENCH_OUTOFCORE_COMP_ROWS_PW", "2048")
)
OUTOFCORE_COMP_COLS = int(os.environ.get("BENCH_OUTOFCORE_COMP_COLS", "64"))
#: streamed-vs-resident wall overhead bar where BOTH fit (<= 15%), and
#: the prefetch pipeline's steady-state overlap bar (>= 50% of transfer
#: time hidden behind compute)
OUTOFCORE_OVERHEAD_BAR = 1.15
OUTOFCORE_OVERLAP_BAR = 0.5


def _outofcore_extra() -> dict:
    """Out-of-core streaming extra (stack_residency="streamed").

    Three claims, measured:
      1. overhead: at a size where resident and streamed BOTH fit, the
         windowed streamed run's steady-state wall stays within
         OUTOFCORE_OVERHEAD_BAR of resident (each measured on its second,
         exec-cache-warm run);
      2. overlap: the double-buffered prefetcher hides >=
         OUTOFCORE_OVERLAP_BAR of steady-state transfer time behind
         compute (Prefetcher.stats overlap_efficiency);
      3. scale: OUTOFCORE_SCALE x the rows trains to completion while
         only a fixed partition window (a quarter of the stack) is ever
         device-resident — the run the resident path would need the full
         stack's HBM for.
    """
    import dataclasses as _dc
    import time as _time

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    Wo, R = OUTOFCORE_WORKERS, OUTOFCORE_ROUNDS
    rows, cols = Wo * 256, 32
    cfg = RunConfig(
        scheme="naive", n_workers=Wo, n_stragglers=0, rounds=R,
        n_rows=rows, n_cols=cols, lr_schedule=0.5, update_rule="GD",
        add_delay=True, seed=0, compute_mode="deduped",
    )
    P = trainer.build_layout(cfg).n_partitions
    window = max(1, P // 4)

    def best_wall(c, d):
        # second run is the steady-state one (exec caches warm); keep the
        # better of the two so a one-off stall can't fail the bar
        r1 = trainer.train(c, d)
        r2 = trainer.train(c, d)
        return min(r1.wall_time, r2.wall_time), r2

    # overhead comparison at a compute-heavy shape where both fit
    comp_rows = Wo * OUTOFCORE_COMP_ROWS_PW
    cfg_c = _dc.replace(cfg, n_rows=comp_rows, n_cols=OUTOFCORE_COMP_COLS)
    ds_c = generate_gmm(comp_rows, OUTOFCORE_COMP_COLS, P, seed=0)
    res_wall, _ = best_wall(cfg_c, ds_c)
    cfg_s = _dc.replace(
        cfg_c, stack_residency="streamed", stream_window=window
    )
    str_wall, r_str = best_wall(cfg_s, ds_c)
    ci = r_str.cache_info
    overhead = str_wall / res_wall if res_wall > 0 else 0.0
    eff = float(ci["prefetch"]["overlap_efficiency"])

    # scale phase: OUTOFCORE_SCALE x rows, same fixed window partition
    # count — the resident fraction shrinks to window*2/P of a stack that
    # is SCALE x the comparison stack
    rows_big = rows * OUTOFCORE_SCALE
    ds_big = generate_gmm(rows_big, cols, P, seed=1)
    cfg_big = _dc.replace(
        cfg, n_rows=rows_big, stack_residency="streamed",
        stream_window=window,
    )
    t0 = _time.perf_counter()
    r_big = trainer.train(cfg_big, ds_big)
    big_total = _time.perf_counter() - t0
    ci_big = r_big.cache_info
    full_bytes = trainer.estimate_stack_bytes(cfg, ds_big)  # resident cost
    return {
        "outofcore": {
            "rows": rows,
            "comp_rows": comp_rows,
            "comp_cols": OUTOFCORE_COMP_COLS,
            "rows_big": rows_big,
            "scale": OUTOFCORE_SCALE,
            "n_partitions": P,
            "stream_window": window,
            "resident_wall_s": round(res_wall, 4),
            "streamed_wall_s": round(str_wall, 4),
            "overhead_ratio": round(overhead, 4),
            "overhead_bar": OUTOFCORE_OVERHEAD_BAR,
            "overhead_ok": bool(overhead <= OUTOFCORE_OVERHEAD_BAR),
            "overlap_efficiency": round(eff, 4),
            "overlap_bar": OUTOFCORE_OVERLAP_BAR,
            "overlap_ok": bool(eff >= OUTOFCORE_OVERLAP_BAR),
            "big_completed": True,
            "big_wall_s": round(float(r_big.wall_time), 4),
            "big_total_s": round(big_total, 4),
            "big_window_device_bytes": ci_big["stack_bytes"],
            "big_full_stack_bytes": int(full_bytes),
            "big_resident_fraction": round(
                2.0 * ci_big["stack_bytes"] / max(1, full_bytes), 4
            ),
            "big_prefetch": ci_big["prefetch"],
        }
    }


#: composed-streaming extra knobs (ISSUE 17): cohort width for the
#: trajectory-batched windowed scan, and its throughput bar vs the
#: sequential streamed trajectories (same bar as the sweep7 cohort)
OUTOFCORE_COHORT_SIZE = int(os.environ.get("BENCH_OUTOFCORE_COHORT", "6"))
OUTOFCORE_COHORT_BAR = 3.0


def _outofcore_composed_extra() -> dict:
    """Composed streaming extra (ISSUE 17): the window planner, ring
    transport and cohort batching measured TOGETHER.

    Three claims, measured:
      1. streamed+ring overhead: a windowed faithful stream whose
         slot-group windows stage their assignment halo in ring-hop
         order stays within OUTOFCORE_OVERHEAD_BAR of the resident ring
         run (both exec-cache warm, best of two);
      2. window memory: the streamed run's device stack is the STAGED
         window's fraction of the resident ring stack — bounded by two
         staged windows (compute + prefetch double buffer);
      3. cohort throughput: OUTOFCORE_COHORT_SIZE streamed trajectories
         dispatched as ONE windowed cohort scan sustain >=
         OUTOFCORE_COHORT_BAR x the sequential streamed trajectory
         rate (the staging pipeline runs once per cohort, not once per
         trajectory).
    """
    import dataclasses as _dc
    import time as _time

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    Wo, R = OUTOFCORE_WORKERS, OUTOFCORE_ROUNDS
    rows = Wo * OUTOFCORE_COMP_ROWS_PW // 2
    cols = OUTOFCORE_COMP_COLS
    cfg = RunConfig(
        scheme="cyccoded", n_workers=Wo, n_stragglers=2, rounds=R,
        n_rows=rows, n_cols=cols, lr_schedule=0.5, update_rule="GD",
        add_delay=True, seed=0, stack_mode="ring",
    )
    P = trainer.build_layout(cfg).n_partitions
    window = max(1, P // 4)
    ds = generate_gmm(rows, cols, P, seed=0)

    def best_wall(c, d):
        r1 = trainer.train(c, d)
        r2 = trainer.train(c, d)
        return min(r1.wall_time, r2.wall_time), r2

    res_wall, r_res = best_wall(cfg, ds)
    cfg_s = _dc.replace(
        cfg, stack_residency="streamed", stream_window=window
    )
    str_wall, r_str = best_wall(cfg_s, ds)
    ci = r_str.cache_info
    overhead = str_wall / res_wall if res_wall > 0 else 0.0
    eff = float(ci["prefetch"]["overlap_efficiency"])
    staged = int(ci["stream_window"]) + int(ci["stream_halo"])
    res_stack = int(r_res.cache_info["stack_bytes"])
    str_stack = int(ci["stack_bytes"])
    # stack_bytes reports one staged window's buffers; the double buffer
    # bounds the true peak at twice that — both must stay under two
    # staged windows' fraction of the resident ring stack
    window_bytes_ok = str_stack * P <= 2 * staged * res_stack

    # cohort: B streamed trajectories (differing seeds share the static
    # signature and the window plan) as ONE windowed scan vs the same
    # trajectories run sequentially, both timed exec-cache warm
    B = OUTOFCORE_COHORT_SIZE
    cfgs = [_dc.replace(cfg_s, seed=k) for k in range(B)]

    def seq_pass():
        t0 = _time.perf_counter()
        for c in cfgs:
            trainer.train(c, ds)
        return _time.perf_counter() - t0

    def cohort_pass():
        t0 = _time.perf_counter()
        out = trainer.train_cohort(cfgs, ds)
        return _time.perf_counter() - t0, out

    seq_pass()  # warm: compile once, prime the exec/data caches
    seq_wall = seq_pass()
    cohort_pass()
    cohort_wall, cohort_res = cohort_pass()
    speedup = seq_wall / cohort_wall if cohort_wall > 0 else 0.0
    ci_co = cohort_res[0].cache_info
    return {
        "outofcore_composed": {
            "rows": rows,
            "cols": cols,
            "n_partitions": P,
            "stream_window": window,
            "stream_halo": int(ci["stream_halo"]),
            "staged_partitions": staged,
            "ring_resident_wall_s": round(res_wall, 4),
            "ring_streamed_wall_s": round(str_wall, 4),
            "overhead_ratio": round(overhead, 4),
            "overhead_bar": OUTOFCORE_OVERHEAD_BAR,
            "overhead_ok": bool(overhead <= OUTOFCORE_OVERHEAD_BAR),
            "overlap_efficiency": round(eff, 4),
            "overlap_bar": OUTOFCORE_OVERLAP_BAR,
            "overlap_ok": bool(eff >= OUTOFCORE_OVERLAP_BAR),
            "resident_stack_bytes": res_stack,
            "streamed_stack_bytes": str_stack,
            "window_bytes_ok": bool(window_bytes_ok),
            "cohort_size": B,
            "cohort_dispatches": ci_co.get("cohort_dispatches"),
            "cohort_lowering": ci_co.get("cohort_lowering"),
            "seq_wall_s": round(seq_wall, 4),
            "cohort_wall_s": round(cohort_wall, 4),
            "seq_traj_per_s": round(B / seq_wall, 4) if seq_wall else 0.0,
            "cohort_traj_per_s": round(
                B / cohort_wall, 4
            ) if cohort_wall else 0.0,
            "cohort_speedup": round(speedup, 4),
            "cohort_bar": OUTOFCORE_COHORT_BAR,
            "cohort_ok": bool(speedup >= OUTOFCORE_COHORT_BAR),
        }
    }


def child() -> None:
    import jax

    platform = jax.devices()[0].platform
    # device-kind-aware roofline: v5e's 819 GB/s was hard-coded for every
    # TPU before; now the kind picks its own public peak and peak_source
    # records how it was chosen, so pct_roofline is honest off-v5e
    device_kind = str(getattr(jax.devices()[0], "device_kind", ""))
    peak, peak_source = _hbm_peak(platform, device_kind)
    # size the problem to the platform: full canonical rows on an
    # accelerator, a light slice on CPU fallback so the bench terminates
    on_accel = platform not in ("cpu",)
    n_rows = 132_000 if on_accel else 13_200

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    cfg = RunConfig(
        scheme="approx",
        n_workers=W,
        n_stragglers=S,
        num_collect=COLLECT,
        rounds=ROUNDS,
        n_rows=n_rows,
        n_cols=N_COLS,
        update_rule="AGD",
        lr_schedule=1.0,
        add_delay=True,
        dtype=DATA_DTYPE,  # BENCH_DTYPE: bf16 data halves HBM traffic
        # BENCH_MARGIN_COLS: measure the production path under the
        # margin_cols lowering before deciding its default (VERDICT r2 #2)
        dense_margin_cols=MARGIN_COLS,
        # BENCH_MODE=deduped: per-partition compute, 1/(s+1) the traffic
        compute_mode=COMPUTE_MODE,
        # BENCH_STACK=ring: partition-major stack + ppermute hop transport
        stack_mode=STACK_MODE,
        # BENCH_RING_PIPELINE: double-buffered vs sequential hop schedule
        ring_pipeline=RING_PIPELINE or "auto",
        # BENCH_STACK_DTYPE=int8: quantized stack, dequantized in-body
        stack_dtype=STACK_DTYPE or "auto",
        # BENCH_DONATE=off: keep the duplicate carry/weight-table HBM
        donate=DONATE or "auto",
        # BENCH_FLAT: force the flat-stack closed-form lowering on/off
        # (unset = "auto", step.resolve_flat_grad decides per stack kind)
        flat_grad=FLAT_GRAD or "auto",
        margin_flat=MARGIN_FLAT or "auto",
        scan_unroll=SCAN_UNROLL,
        # BENCH_RESIDENCY=streamed + BENCH_STREAM_WINDOW: windowed
        # out-of-core stacks on the canonical run (ISSUE 17)
        stack_residency=RESIDENCY or "resident",
        stream_window=STREAM_WINDOW if STREAM_WINDOW > 0 else None,
        seed=0,
    )
    print(
        f"bench: platform={platform} rows={n_rows} cols={N_COLS} "
        f"W={W} s={S} collect={COLLECT} rounds={ROUNDS}",
        file=sys.stderr,
    )
    data = generate_gmm(n_rows, N_COLS, n_partitions=W, seed=0)

    # ---- run-telemetry capture (obs/): events.jsonl beside the repo's
    # bench artifacts. Observation-only (emission is host-side, after the
    # timed scan) and never allowed to break the one-JSON-line contract.
    import contextlib

    events_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "artifacts", "bench_events.jsonl",
    )
    try:
        from erasurehead_tpu.obs import events as events_lib

        capture = events_lib.capture(events_path)
    except Exception as e:  # noqa: BLE001
        print(f"bench: telemetry capture unavailable: {e}", file=sys.stderr)
        events_path = None
        capture = contextlib.nullcontext()

    with capture:
        t0 = time.perf_counter()
        result = trainer.train(cfg, data)  # compiles, then times the scan
        total = time.perf_counter() - t0

        # ---- sweep-engine extra: wall-clock of a CACHED rerun -------------
        # The sweep engine (train/cache.py) makes the Nth run of this
        # signature skip trace+compile+upload; a second identical train()
        # call measures exactly what a 7-scheme compare() pays per
        # additional run. Never let the extra break the one-JSON-line
        # contract.
        sweep_extra = {}
        try:
            t1 = time.perf_counter()
            rerun = trainer.train(cfg, data)
            sweep_extra = {
                "sweep_cached_run_s": round(time.perf_counter() - t1, 4),
                "sweep_first_run_s": round(total, 4),
                "sweep_cache": rerun.cache_info,
            }
        except Exception as e:  # noqa: BLE001 — extras must never kill bench
            print(f"bench: sweep-engine extra failed: {e}", file=sys.stderr)

        # ---- trajectory-batched sweep extra (train_cohort) ----------------
        # the paper's actual workload is a multi-scheme sweep; this measures
        # the 7-scheme x 2-seed deduped cohort as ONE dispatch against the
        # sequential cached path, with X counted once per cohort pass
        sweep7_extra = {}
        try:
            sweep7_extra = _sweep7_extra(data, n_rows, peak)
        except Exception as e:  # noqa: BLE001 — extras must never kill bench
            print(f"bench: sweep7 cohort extra failed: {e}", file=sys.stderr)

        # ---- deep_cohort extra: the models/ shelf as the second headline
        # workload — a 7-scheme x 4-seed deep-model cohort racing the
        # sequential cached path (bar >= 3x), plus the blockwise-coded
        # decode-error-vs-depth series into the events capture
        deep_extra = {}
        try:
            deep_extra = _deep_cohort_extra()
        except Exception as e:  # noqa: BLE001 — extras must never kill bench
            print(f"bench: deep_cohort extra failed: {e}", file=sys.stderr)

        # ---- serve_pack extra: N concurrent clients vs N sequential
        # sweeps through the serve daemon (multi-tenant cohort packing) —
        # the "heavy traffic" throughput claim, with the bitwise
        # packed-vs-sequential row check riding along
        serve_extra = {}
        try:
            serve_extra = _serve_pack_extra(data, n_rows)
        except Exception as e:  # noqa: BLE001 — extras must never kill bench
            print(f"bench: serve_pack extra failed: {e}", file=sys.stderr)

        # ---- serve_load extra: the robustness twin — closed-loop HTTP
        # load (p50/p99 time-to-first/last-row, packed ratio), 2x-capacity
        # backpressure correctness, goodput fairness under a flooding
        # tenant, and the warm-restart (WAL + on-disk compile cache) phase
        serve_load_extra = {}
        try:
            serve_load_extra = _serve_load_extra()
        except Exception as e:  # noqa: BLE001 — extras must never kill bench
            print(f"bench: serve_load extra failed: {e}", file=sys.stderr)

        # ---- fleet extra: replicated serve (real subprocess replicas
        # behind the consistent-hash router) — goodput scaling 1 -> 2
        # replicas and the rolling-deploy ledger (zero lost / zero dup,
        # under-deploy p99 vs steady)
        fleet_extra = {}
        try:
            fleet_extra = _fleet_extra()
        except Exception as e:  # noqa: BLE001 — extras must never kill bench
            print(f"bench: fleet extra failed: {e}", file=sys.stderr)

        # ---- adapt extra: the online straggler-adaptive controller under
        # a deterministic regime shift — controller overhead per chunk
        # (bar < 2% of run wall) and time-to-target vs every static arm
        adapt_extra = {}
        try:
            adapt_extra = _adapt_extra()
        except Exception as e:  # noqa: BLE001 — extras must never kill bench
            print(f"bench: adapt extra failed: {e}", file=sys.stderr)

        # ---- elastic extra: time-to-target under a mid-run 25% worker
        # kill — the online membership controller vs keep-limping vs
        # restart-from-scratch (bar: elastic beats both)
        elastic_extra = {}
        try:
            elastic_extra = _elastic_extra()
        except Exception as e:  # noqa: BLE001 — extras must never kill bench
            print(f"bench: elastic extra failed: {e}", file=sys.stderr)

        # ---- fidelity extra: the compressed-stack knob ships with evidence
        # (eval-loss delta vs an f32-stack reference run of the same
        # schedule), not vibes — only measured when a lossy/compressed
        # stack dtype is actually in play
        fidelity_extra = {}
        try:
            if cfg.resolve_stack_dtype() != "float32":
                fidelity_extra = _fidelity_extra(cfg, data, result)
        except Exception as e:  # noqa: BLE001 — extras must never kill bench
            print(f"bench: fidelity extra failed: {e}", file=sys.stderr)

        # ---- outofcore extra: streamed partition stacks — overhead vs
        # resident where both fit, prefetch overlap efficiency, and the
        # 100x-rows-on-a-fixed-window completion run (inside the capture:
        # the prefetch/io event stream is part of the evidence)
        outofcore_extra = {}
        try:
            outofcore_extra = _outofcore_extra()
        except Exception as e:  # noqa: BLE001 — extras must never kill bench
            print(f"bench: outofcore extra failed: {e}", file=sys.stderr)

        # ---- composed-streaming extra: window planner x ring transport
        # x cohort batching measured together (ISSUE 17) — streamed+ring
        # vs resident+ring wall, staged-window device bytes, and the
        # one-windowed-scan cohort vs sequential streamed trajectories
        outofcore_composed_extra = {}
        try:
            outofcore_composed_extra = _outofcore_composed_extra()
        except Exception as e:  # noqa: BLE001 — extras must never kill bench
            print(
                f"bench: outofcore composed extra failed: {e}",
                file=sys.stderr,
            )

    # ---- whatif extra: the Monte-Carlo policy-search engine — grid
    # simulated-runs/sec vs sequential single-run simulation (bar >=
    # 100x) and bandit regret with surface priors on vs off. Runs OUTSIDE
    # the events capture (like the lint/telemetry extras): the throughput
    # claim is the engine's, not the telemetry writer's — per-trajectory
    # event emission is measured separately (PR 3 overhead numbers)
    whatif_extra = {}
    try:
        whatif_extra = _whatif_extra()
    except Exception as e:  # noqa: BLE001 — extras must never kill bench
        print(f"bench: whatif extra failed: {e}", file=sys.stderr)

    # ---- pipeline extra: sync vs tau=1 pipelined time-to-target under
    # exp(2.0) straggling (bar >= 1.5x), with the extra params-slot bytes
    # and the staleness-vs-coding error split riding along
    pipeline_extra = {}
    try:
        pipeline_extra = _pipeline_extra()
    except Exception as e:  # noqa: BLE001 — extras must never kill bench
        print(f"bench: pipeline extra failed: {e}", file=sys.stderr)

    # ---- obs extra: the live telemetry plane armed vs dark at the
    # flagship worker count — wall overhead (bar <= 2%), bitwise
    # trajectories, and the regime estimator's detection latency
    obs_extra = {}
    try:
        obs_extra = _obs_extra()
    except Exception as e:  # noqa: BLE001 — extras must never kill bench
        print(f"bench: obs extra failed: {e}", file=sys.stderr)

    # ---- tune extra: the autotuning plane's cost ledger — cold race vs
    # the warm cached resolution every later run pays (bar < 1 ms), plus
    # the re-raced blockwise fused-vs-treewise verdict at bench shape
    tune_extra = {}
    try:
        tune_extra = _tune_extra()
    except Exception as e:  # noqa: BLE001 — extras must never kill bench
        print(f"bench: tune extra failed: {e}", file=sys.stderr)

    # ---- lint extra: the AST invariant analyzer rides the tier-1 loop -----
    # (erasurehead_tpu/analysis/), so its wall time is a budgeted quantity:
    # the full-tree run must stay under 5 s on CPU (lint_budget_ok)
    lint_extra = {}
    try:
        from erasurehead_tpu.analysis import runner as lint_runner

        pkg_dir = os.path.dirname(
            os.path.abspath(lint_runner.__file__)
        )
        tree = os.path.dirname(pkg_dir)  # erasurehead_tpu/
        t_lint = time.perf_counter()
        lint_report = lint_runner.lint_paths([tree])
        lint_wall = time.perf_counter() - t_lint
        lint_extra = {
            "lint": {
                "wall_s": round(lint_wall, 4),
                "budget_s": 5.0,
                "lint_budget_ok": lint_wall < 5.0,
                "files": lint_report.n_files,
                "findings": len(lint_report.unsuppressed),
                "suppressed": len(lint_report.suppressed),
            }
        }
    except Exception as e:  # noqa: BLE001 — extras must never kill bench
        print(f"bench: lint extra failed: {e}", file=sys.stderr)

    # ---- telemetry extra: the same fields the event log carries -----------
    telemetry_extra = {}
    try:
        from erasurehead_tpu.train import cache as cache_lib

        stats = cache_lib.stats().snapshot()
        telemetry_extra = {
            "telemetry": {
                # total seconds this process spent compiling (misses) and
                # the seconds the exec cache saved on hits
                "compile_seconds_saved": round(
                    stats["compile_seconds_saved"], 4
                ),
                "exec_cache": {
                    "hits": stats["exec_hits"],
                    "misses": stats["exec_misses"],
                },
                "data_cache": {
                    "hits": stats["data_hits"],
                    "misses": stats["data_misses"],
                },
                "mean_decode_error": (
                    round(float(sum(result.decode_error))
                          / max(len(result.decode_error), 1), 8)
                    if result.decode_error is not None
                    else None
                ),
                "events_path": events_path,
            }
        }
    except Exception as e:  # noqa: BLE001 — extras must never kill the bench
        print(f"bench: telemetry extra failed: {e}", file=sys.stderr)

    # ---- memory telemetry (the stack_mode=ring (s+1)x claim, by numbers) --
    mem_extra = {}
    if result.cache_info:
        mem_extra = {
            "stack_mode": result.cache_info.get("stack_mode"),
            "stack_bytes": result.cache_info.get("stack_bytes"),
            "memory_analysis": result.cache_info.get("memory_analysis"),
        }

    steps_per_sec = result.steps_per_sec
    # reference-protocol effective rate on the identical straggler schedule
    ref_steps_per_sec = ROUNDS / result.sim_total_time

    # ---- hardware roofline (see module docstring + BASELINE.md) ----------
    # faithful mode streams the [W, s+1, rows/W, F] slot stack twice/step;
    # deduped streams the [P, rows/W, F] partition stack (1/(s+1) of it).
    # Bytes are counted at the stack's STORAGE dtype (stack_dtype): the
    # whole point of bf16/int8 stacks is fewer bytes per step at the same
    # FLOPs — so the flops/byte intensity rises and achieved_gbps is the
    # bytes actually streamed. int8 adds its per-partition scale tables
    # ([blocks, F] f32, read alongside the payload in both passes).
    slot_rows = n_rows // W
    replicas = (S + 1) if COMPUTE_MODE == "faithful" else 1
    stack_dtype = cfg.resolve_stack_dtype()
    stack_itemsize = _STACK_ITEMSIZE[stack_dtype]
    x_bytes = W * replicas * slot_rows * N_COLS * stack_itemsize
    if stack_dtype == "int8":
        x_bytes += W * replicas * N_COLS * 4  # scale tables
    bytes_per_step = 2 * x_bytes
    flops_per_step = 4 * W * replicas * slot_rows * N_COLS
    achieved_gbps = bytes_per_step * steps_per_sec / 1e9
    pct_roofline = (
        round(100.0 * achieved_gbps / peak, 2) if peak else None
    )

    print(
        f"bench: wall(total incl. compile)={total:.1f}s scan={result.wall_time:.3f}s "
        f"sim_total={result.sim_total_time:.1f}s "
        f"ref_rate={ref_steps_per_sec:.3f} it/s ours={steps_per_sec:.1f} it/s "
        f"achieved={achieved_gbps:.1f} GB/s roofline={pct_roofline}%",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": (
                    f"AGC_logistic_steps_per_sec_30w_s2_collect15"
                    f"{METRIC_SUFFIX}"
                ),
                "value": round(float(steps_per_sec), 3),
                "unit": "iterations/sec",
                "vs_baseline": round(float(steps_per_sec / ref_steps_per_sec), 3),
                "platform": platform,
                "dtype": DATA_DTYPE,
                "stack_dtype": stack_dtype,
                "mode": COMPUTE_MODE,
                "n_rows": n_rows,
                "wall_time_s": round(float(result.wall_time), 4),
                "flops_per_step": flops_per_step,
                "bytes_per_step": bytes_per_step,
                "achieved_gbps": round(float(achieved_gbps), 2),
                "pct_roofline": pct_roofline,
                "hbm_peak_gbps": peak,
                "peak_source": peak_source,
                **mem_extra,
                **sweep_extra,
                **sweep7_extra,
                **deep_extra,
                **serve_extra,
                **serve_load_extra,
                **fleet_extra,
                **adapt_extra,
                **elastic_extra,
                **whatif_extra,
                **pipeline_extra,
                **obs_extra,
                **fidelity_extra,
                **outofcore_extra,
                **outofcore_composed_extra,
                **tune_extra,
                **lint_extra,
                **telemetry_extra,
            }
        )
    )


if __name__ == "__main__":
    if DATA_DTYPE not in _DTYPE_ITEMSIZE:
        print(
            json.dumps(
                _failure_record(
                    f"BENCH_DTYPE must be one of "
                    f"{sorted(_DTYPE_ITEMSIZE)}, got {DATA_DTYPE!r}"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if MARGIN_COLS is not None and not (2 <= MARGIN_COLS <= 128):
        print(
            json.dumps(
                _failure_record(
                    f"BENCH_MARGIN_COLS must be an int in [2, 128], "
                    f"got {_MARGIN_COLS_ENV!r}"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if COMPUTE_MODE not in ("faithful", "deduped"):
        print(
            json.dumps(
                _failure_record(
                    f"BENCH_MODE must be faithful or deduped, "
                    f"got {COMPUTE_MODE!r}"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if STACK_MODE not in ("materialized", "ring", "auto"):
        print(
            json.dumps(
                _failure_record(
                    f"BENCH_STACK must be materialized, ring, or auto, "
                    f"got {STACK_MODE!r}"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if STACK_MODE == "ring" and COMPUTE_MODE == "deduped":
        print(
            json.dumps(
                _failure_record(
                    "BENCH_STACK=ring streams the faithful stack; it does "
                    "not compose with BENCH_MODE=deduped"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if RING_PIPELINE not in ("", "on", "off"):
        print(
            json.dumps(
                _failure_record(
                    f"BENCH_RING_PIPELINE must be on or off, "
                    f"got {RING_PIPELINE!r}"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if STACK_DTYPE not in ("",) + tuple(_STACK_ITEMSIZE):
        print(
            json.dumps(
                _failure_record(
                    f"BENCH_STACK_DTYPE must be one of "
                    f"{sorted(_STACK_ITEMSIZE)}, got {STACK_DTYPE!r}"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if DONATE not in ("", "on", "off"):
        print(
            json.dumps(
                _failure_record(
                    f"BENCH_DONATE must be on or off, got {DONATE!r}"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if _UNROLL_ENV and SCAN_UNROLL < 1:
        print(
            json.dumps(
                _failure_record(
                    f"BENCH_UNROLL must be an int >= 1, "
                    f"got {_UNROLL_ENV!r}"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if FLAT_GRAD not in ("", "on", "off"):
        print(
            json.dumps(
                _failure_record(
                    f"BENCH_FLAT must be on or off, got {FLAT_GRAD!r}"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if RESIDENCY not in ("", "resident", "streamed", "auto"):
        print(
            json.dumps(
                _failure_record(
                    f"BENCH_RESIDENCY must be resident, streamed, or "
                    f"auto, got {RESIDENCY!r}"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if _STREAM_WINDOW_ENV and STREAM_WINDOW < 1:
        print(
            json.dumps(
                _failure_record(
                    f"BENCH_STREAM_WINDOW must be an int >= 1, "
                    f"got {_STREAM_WINDOW_ENV!r}"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if STREAM_WINDOW > 0 and RESIDENCY not in ("streamed", "auto"):
        print(
            json.dumps(
                _failure_record(
                    "BENCH_STREAM_WINDOW sizes the streamed window; set "
                    "BENCH_RESIDENCY=streamed (or auto) with it"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if MARGIN_FLAT not in ("", "on", "off"):
        print(
            json.dumps(
                _failure_record(
                    f"BENCH_MARGIN_FLAT must be on or off, got {MARGIN_FLAT!r}"
                )
            )
        )
        sys.exit(0 if "--child" not in sys.argv else 1)
    if "--child" in sys.argv:
        child()
    else:
        main()
