"""Benchmark: flagship AGC logistic regression at the reference's canonical
run shape, on real TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

What is measured: real on-device steps/sec of the full coded training step
(worker-sharded gradient stacks, slot-weighted decode contraction, psum, AGD
update) over the canonical configuration from run_approx_coding.sh:2-9 —
30 workers, s=3 stragglers, num_collect=15, AGD, 100 rounds, seeded
Exponential(0.5) straggler schedule.

What vs_baseline compares against: the reference's effective iteration rate
under its own measurement protocol on the same schedule. In the reference,
every iteration's wall-clock is the arrival time of the worker that satisfies
the AGC stop rule — the injected sleeps are real time there
(src/approximate_coding.py:136-175, src/naive.py:141-148). Our control plane
computes exactly that per-iteration simulated clock from the identical delay
streams; baseline steps/sec = rounds / sum(simulated timeset). The TPU run
does the same *science* (same gradients, same decode, same loss curve, same
timing artifacts) without spending wall-clock on sleeping, which is precisely
the framework's value proposition.
"""

import json
import sys
import time

import numpy as np

ROUNDS = 100
# run_approx_coding.sh:2-9 sets W=30, s=3, collect=15 — but AGC requires
# (s+1) | W in the reference as well (src/approximate_coding.py:25-27), and
# 30 % 4 != 0, so the canonical script's own AGC config is unrunnable there
# too. s=2 is the nearest valid setting (10 FRC groups of 3).
W, S, COLLECT = 30, 2, 15
N_COLS = 128


def main() -> None:
    import jax

    platform = jax.devices()[0].platform
    # size the problem to the platform: full canonical rows on an
    # accelerator, a light slice on CPU fallback so the bench terminates
    n_rows = 132_000 if platform != "cpu" else 13_200

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    cfg = RunConfig(
        scheme="approx",
        n_workers=W,
        n_stragglers=S,
        num_collect=COLLECT,
        rounds=ROUNDS,
        n_rows=n_rows,
        n_cols=N_COLS,
        update_rule="AGD",
        lr_schedule=1.0,
        add_delay=True,
        seed=0,
    )
    print(
        f"bench: platform={platform} rows={n_rows} cols={N_COLS} "
        f"W={W} s={S} collect={COLLECT} rounds={ROUNDS}",
        file=sys.stderr,
    )
    data = generate_gmm(n_rows, N_COLS, n_partitions=W, seed=0)

    t0 = time.perf_counter()
    result = trainer.train(cfg, data)  # compiles, then times the scan
    total = time.perf_counter() - t0

    steps_per_sec = result.steps_per_sec
    # reference-protocol effective rate on the identical straggler schedule
    ref_steps_per_sec = ROUNDS / result.sim_total_time

    print(
        f"bench: wall(total incl. compile)={total:.1f}s scan={result.wall_time:.3f}s "
        f"sim_total={result.sim_total_time:.1f}s "
        f"ref_rate={ref_steps_per_sec:.3f} it/s ours={steps_per_sec:.1f} it/s",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "AGC_logistic_steps_per_sec_30w_s2_collect15",
                "value": round(float(steps_per_sec), 3),
                "unit": "iterations/sec",
                "vs_baseline": round(float(steps_per_sec / ref_steps_per_sec), 3),
            }
        )
    )


if __name__ == "__main__":
    main()
