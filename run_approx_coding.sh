#!/usr/bin/env bash
# Canonical AGC-vs-baselines run script — the TPU equivalent of the
# reference's run_approx_coding.sh (run_approx_coding.sh:2-49), which doubles
# as the canonical config record: 30 workers, s=3, num_collect=15, AGD,
# 100 iterations, per-dataset shape blocks.
#
# Usage:  bash run_approx_coding.sh [dataset] [scheme]
#   dataset ∈ artificial | covtype | amazon-dataset | kc_house_data  (default artificial)
#   scheme  ∈ approx | cyccoded | repcoded | naive | avoidstragg     (default approx)
#
# Real datasets must first be prepared into $DATA_DIR with
#   make arrange_real_data DATASET=<name> SOURCE=<raw dir>
set -euo pipefail

DATASET="${1:-artificial}"
SCHEME="${2:-approx}"

N_WORKERS="${N_WORKERS:-30}"
# the reference script's s=3 violates its own FRC guard (s+1) | W
# (src/replication.py:24-26; 30 % 4 != 0) — s=2 is the nearest valid setting
N_STRAGGLERS="${N_STRAGGLERS:-2}"
N_COLLECT="${N_COLLECT:-15}"
ROUNDS="${ROUNDS:-100}"
UPDATE_RULE="${UPDATE_RULE:-AGD}"
DATA_DIR="${DATA_DIR:-./straggdata}"

# dataset shape blocks (run_approx_coding.sh:26-36)
case "$DATASET" in
  covtype)        N_ROWS=396112; N_COLS=15509 ;;
  amazon-dataset) N_ROWS=26210;  N_COLS=241915 ;;
  kc_house_data)  N_ROWS=17290;  N_COLS=27654 ;;
  artificial)     N_ROWS=54000;  N_COLS=100 ;;
  *) echo "unknown dataset: $DATASET" >&2; exit 2 ;;
esac

ARGS=(--scheme "$SCHEME" --workers "$N_WORKERS" --stragglers "$N_STRAGGLERS"
      --rounds "$ROUNDS" --update-rule "$UPDATE_RULE"
      --rows "$N_ROWS" --cols "$N_COLS" --dataset "$DATASET"
      --input-dir "$DATA_DIR" --add-delay)
if [[ "$SCHEME" == approx ]]; then ARGS+=(--num-collect "$N_COLLECT"); fi

exec python -m erasurehead_tpu.cli "${ARGS[@]}"
