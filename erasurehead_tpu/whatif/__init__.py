"""What-if engine: Monte-Carlo policy search over the scheme x regime grid.

The simulator already vmaps trajectory batches (trainer.train_cohort) and
the scheme registry makes every collection policy a data object — this
package composes them into a policy-search engine (ROADMAP item 5):

  - :mod:`spec` enumerates (scheme, W, s, num_collect, deadline, decode,
    arrival-regime) grid points from registry descriptors, with
    per-point feasibility filtered through each descriptor's own config
    validation (infeasible points are recorded with a reason, never
    dispatched);
  - :mod:`sampler` vmaps seeded arrival-time draws on-device (exp /
    heavytail / adversary / targeted regimes, plus trace replay), so one
    cohort dispatch simulates hundreds of (policy, seed) trajectories;
  - :mod:`engine` groups grid points into cohort dispatches through the
    existing sweep degradation/journal path and reduces trajectories into
    expected-time-to-target surfaces;
  - :mod:`surface` holds the reduced artifact (.npz + JSONL rows): the
    ErasureHead Fig. 4-6 family reproduced from simulation alone, plus
    the two consumers that make it load-bearing — cold-start priors for
    the adapt/ bandit and admission-time ETAs for the serve/ daemon.

Entry point: ``erasurehead-tpu whatif`` (engine.main).
"""

from erasurehead_tpu.whatif.sampler import RegimeSpec, sample_arrivals
from erasurehead_tpu.whatif.spec import (
    GridPoint,
    GridSpec,
    PolicySpec,
    enumerate_points,
)
from erasurehead_tpu.whatif.surface import Surface
from erasurehead_tpu.whatif.engine import run_whatif

__all__ = [
    "GridPoint",
    "GridSpec",
    "PolicySpec",
    "RegimeSpec",
    "Surface",
    "enumerate_points",
    "run_whatif",
    "sample_arrivals",
]
