"""Seeded Monte-Carlo arrival sampling for the what-if engine.

The reference's delay model is one stream: i.i.d. Exponential(0.5) per
(round, worker), re-seeded per round (parallel/straggler.
reference_delay_schedule). A what-if surface needs MANY independent draws
of MANY regimes — the straggler-regime families the retrieved papers
analyze (heavy Pareto tails, fixed adversaries and targeted replica-group
attacks from arXiv:1901.08166) plus recorded-trace replay — so this
module batches the draw itself: one vmapped, jitted function produces the
whole ``[n_seeds, rounds, workers]`` arrival block on-device, and the
engine feeds each seed's slice to the host collection rules exactly as a
single run's schedule.

Determinism contract: every draw is a pure function of (seed, regime,
shape) through JAX's counter-based threefry PRNG — rerunning an identical
grid spec redraws identical arrivals, which is what makes a what-if
surface bitwise-rehydratable (tools/whatif_smoke.py pins it). The drawn
streams are the sampler's OWN universe (threefry, not the reference's
MT19937): what-if surfaces are comparable to each other, and the paired-
comparison contract holds because every policy at the same (W, regime,
seed) grid coordinate reads the same slice.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import numpy as np

#: the arrival-regime families a grid point may run under
REGIME_KINDS = ("exp", "heavytail", "adversary", "targeted", "trace")


@dataclasses.dataclass(frozen=True)
class RegimeSpec:
    """One straggler regime a grid axis enumerates.

    ``kind``:

      - ``"exp"``       — the reference's stationary stream: i.i.d.
        Exponential(``mean``) delays every round;
      - ``"heavytail"`` — Exponential through round ``shift_round``-1,
        then Pareto(``alpha``)-tailed delays scaled by ``mean`` (small
        alpha = heavier tail; alpha <= 1 has infinite mean);
      - ``"adversary"`` — Exponential plus ``slowdown`` extra seconds on
        worker ``worker`` from round ``shift_round`` on (the fixed-
        straggler worst case of arXiv:1901.08166);
      - ``"targeted"``  — Exponential plus ``slowdown`` on EVERY replica
        of coded partition group ``group`` from ``shift_round`` on
        (1901.08166's fractional-repetition worst case; the attacked
        worker set is layout-resolved per grid point, straggler.
        targeted_workers);
      - ``"trace"``     — replay a recorded [R?, W] arrival trace
        (straggler.replay_arrival_trace), rotated by a seeded round
        offset per Monte-Carlo seed so seeds stay independent draws.

    ``compute_time`` adds a uniform per-round compute cost on top of the
    delay draw — with ``compute_slots=True`` it scales by each worker's
    SLOT COUNT from the grid point's layout, so coded redundancy costs
    (s+1)x compute per round exactly as it did on the reference cluster
    (the axis the AGC-vs-exact crossover lives on).
    """

    kind: str = "exp"
    mean: float = 0.5
    alpha: float = 1.2
    shift_round: int = 0
    worker: int = 0
    slowdown: float = 5.0
    group: int = 0
    trace: Optional[str] = None
    compute_time: float = 0.0
    compute_slots: bool = False

    def __post_init__(self):
        if self.kind not in REGIME_KINDS:
            raise ValueError(
                f"regime kind must be one of {REGIME_KINDS}, got "
                f"{self.kind!r}"
            )
        if self.mean < 0:
            raise ValueError(f"regime mean must be >= 0, got {self.mean}")
        if self.kind == "heavytail" and self.alpha <= 0:
            raise ValueError(
                f"heavytail alpha must be > 0, got {self.alpha}"
            )
        if self.kind in ("adversary", "targeted") and self.slowdown < 0:
            raise ValueError(
                f"{self.kind} slowdown must be >= 0, got {self.slowdown}"
            )
        if self.kind == "trace" and not self.trace:
            raise ValueError("trace regime needs a trace path/array")
        if self.shift_round < 0:
            raise ValueError(
                f"shift_round must be >= 0, got {self.shift_round}"
            )
        if self.compute_time < 0:
            raise ValueError(
                f"compute_time must be >= 0, got {self.compute_time}"
            )

    @property
    def tag(self) -> str:
        """Short label for surface rows / grid-point names."""
        if self.kind == "exp":
            base = f"exp{self.mean:g}"
        elif self.kind == "heavytail":
            base = f"heavytail{self.alpha:g}x{self.mean:g}"
        elif self.kind == "adversary":
            base = f"adversary{self.slowdown:g}"
        elif self.kind == "targeted":
            base = f"targeted{self.slowdown:g}g{self.group}"
        else:
            base = "trace"
        if self.compute_time:
            base += f"+c{self.compute_time:g}"
            if self.compute_slots:
                base += "xslots"
        return base

    def payload(self) -> dict:
        """JSON form for the spec hash / saved surface header."""
        out = {"kind": self.kind, "mean": self.mean}
        if self.kind == "heavytail":
            out["alpha"] = self.alpha
        if self.kind in ("adversary", "targeted"):
            out["slowdown"] = self.slowdown
        if self.kind == "adversary":
            out["worker"] = self.worker
        if self.kind == "targeted":
            out["group"] = self.group
        if self.kind == "trace":
            out["trace"] = str(self.trace)
        if self.shift_round:
            out["shift_round"] = self.shift_round
        if self.compute_time:
            out["compute_time"] = self.compute_time
            out["compute_slots"] = self.compute_slots
        return out


@functools.lru_cache(maxsize=None)
def _batch_draw_fn(kind: str, rounds: int, n_workers: int):
    """The jitted batched draw for one (kind, shape): seeds -> [S, R, W].

    The seed axis is a vmap, the per-round keys a fold_in — every (seed,
    round, worker) cell is an independent counter-PRNG draw, so the whole
    Monte-Carlo block is ONE device dispatch instead of S x R host draws.
    Shape/kind are static (cached per combination); mean/alpha/slowdown/
    the attacked-worker mask are traced arguments, so regime parameter
    sweeps share the compiled draw.
    """
    import jax
    import jax.numpy as jnp

    def draw_one(seed, mean, alpha, shift_round, slowdown, worker_mask):
        key = jax.random.PRNGKey(seed)
        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
            jnp.arange(rounds)
        )
        # standard-exponential base draw; everything else is a transform
        e = jax.vmap(
            lambda k: jax.random.exponential(k, (n_workers,))
        )(keys)
        out = mean * e
        shifted = (jnp.arange(rounds) >= shift_round)[:, None]
        if kind == "heavytail":
            # Pareto(alpha) via the exponential inverse-CDF transform:
            # U = exp(-E) uniform, X = U^(-1/alpha) - 1 = expm1(E/alpha)
            out = jnp.where(shifted, mean * jnp.expm1(e / alpha), out)
        elif kind in ("adversary", "targeted"):
            # the attacked worker set rides in as a traced [W] mask (one
            # worker for adversary, a layout-resolved replica group for
            # targeted), so the compiled draw is shared across targets
            out = out + slowdown * shifted * worker_mask[None, :]
        return out

    return jax.jit(
        jax.vmap(draw_one, in_axes=(0, None, None, None, None, None))
    )


def sample_arrivals(
    regime: RegimeSpec,
    rounds: int,
    n_workers: int,
    seeds,
    layout=None,
    slots_per_worker: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Draw the regime's full Monte-Carlo arrival block: ``[len(seeds),
    rounds, n_workers]`` float64 arrival times, one deterministic draw per
    seed.

    ``layout`` resolves the ``"targeted"`` kind's attacked worker set
    (straggler.targeted_workers — only the layout knows which workers
    replicate the attacked group) and, with ``compute_slots``, each
    worker's slot count; ``slots_per_worker`` overrides the latter.
    """
    from erasurehead_tpu.parallel import straggler

    seeds = np.asarray(list(seeds), dtype=np.int64)
    if seeds.ndim != 1 or seeds.size == 0:
        raise ValueError(f"seeds must be a non-empty 1-D list, got {seeds!r}")

    if regime.kind == "trace":
        base = straggler.replay_arrival_trace(
            regime.trace, rounds, n_workers
        )
        # independent per-seed draws from one recorded stream: rotate the
        # replay window by a seeded round offset (seed 0 = the raw trace)
        out = np.stack(
            [np.roll(base, -(int(s) % rounds), axis=0) for s in seeds]
        ).astype(np.float64)
    else:
        mask = np.zeros(n_workers, dtype=np.float64)
        if regime.kind == "adversary":
            mask[regime.worker % n_workers] = 1.0
        elif regime.kind == "targeted":
            if layout is None:
                raise ValueError(
                    "targeted regime needs the grid point's layout to "
                    "resolve the attacked replica group "
                    "(straggler.targeted_workers)"
                )
            for w in straggler.targeted_workers(layout, regime.group):
                mask[w % n_workers] = 1.0
        fn = _batch_draw_fn(regime.kind, int(rounds), int(n_workers))
        out = np.asarray(
            fn(
                seeds,
                float(regime.mean),
                float(regime.alpha),
                int(regime.shift_round),
                float(regime.slowdown),
                mask,
            ),
            dtype=np.float64,
        )

    if regime.compute_time:
        per_worker = np.full(n_workers, float(regime.compute_time))
        if regime.compute_slots:
            if slots_per_worker is None:
                if layout is None:
                    raise ValueError(
                        "compute_slots needs the grid point's layout (or "
                        "an explicit slots_per_worker) to price each "
                        "worker's redundant compute"
                    )
                slots_per_worker = slot_counts(layout)
            per_worker = per_worker * np.asarray(
                slots_per_worker, dtype=np.float64
            )
        out = out + per_worker[None, None, :]
    return out


def slot_counts(layout) -> np.ndarray:
    """[W] slots (partition copies) each worker computes per round — the
    faithful compute price of the layout's redundancy ((s+1) for the
    replication/MDS families, ragged for sparse-graph codes)."""
    assignment = np.asarray(layout.assignment)
    return (assignment >= 0).sum(axis=1).astype(np.float64)
