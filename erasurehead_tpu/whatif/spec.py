"""Grid specification for the what-if engine.

A :class:`GridSpec` is the declarative question: which (scheme, W, s,
num_collect, deadline, decode, arrival-regime, pipeline-staleness) points
to simulate, over how many Monte-Carlo seeds, at what problem shape. Enumeration
(:func:`enumerate_points`) builds each point's RunConfig and filters
feasibility through the SAME validation the real entry points use — the
registry descriptor's ``validate_config`` hook via RunConfig's own
``__post_init__`` — so a point the CLI would refuse (FRC divisibility,
missing num_collect/deadline, partial partition counts) is excluded with
its reason recorded on the surface row, never dispatched.

The spec is a pure data object: ``payload()`` is its canonical JSON form
and :func:`spec_hash` its identity — the key that makes a saved surface
rehydratable (engine.run_whatif loads instead of re-simulating when the
artifact's hash matches) and what-if events attributable.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
from typing import Optional, Sequence

from erasurehead_tpu.whatif.sampler import RegimeSpec


@dataclasses.dataclass(frozen=True)
class PolicySpec:
    """One collection policy column of the grid: a scheme plus its
    scheme-specific knobs. ``num_collect=None`` on a first-k scheme
    defaults per grid point to the descriptor's ``sweep_num_collect``
    hook (the "interesting regime collects fewer than all" rule the
    straggler sweep uses); ``collect_frac`` instead derives it as
    ``round(frac * W)`` per point."""

    scheme: str
    num_collect: Optional[int] = None
    collect_frac: Optional[float] = None
    deadline: Optional[float] = None
    partitions_per_worker: int = 0

    def __post_init__(self):
        if self.num_collect is not None and self.collect_frac is not None:
            raise ValueError(
                f"policy {self.scheme!r}: num_collect and collect_frac "
                "both given; pick one"
            )
        if self.collect_frac is not None and not (
            0.0 < self.collect_frac <= 1.0
        ):
            raise ValueError(
                f"collect_frac must be in (0, 1], got {self.collect_frac}"
            )

    @property
    def label(self) -> str:
        parts = [self.scheme]
        if self.num_collect is not None:
            parts.append(f"c{self.num_collect}")
        if self.collect_frac is not None:
            parts.append(f"f{self.collect_frac:g}")
        if self.deadline is not None:
            parts.append(f"d{self.deadline:g}")
        if self.partitions_per_worker:
            parts.append(f"p{self.partitions_per_worker}")
        return ":".join(parts)

    def resolve_num_collect(self, n_workers: int) -> Optional[int]:
        """The point-level num_collect for a W-column of the grid."""
        if self.num_collect is not None:
            return self.num_collect
        if self.collect_frac is not None:
            return max(1, round(self.collect_frac * n_workers))
        from erasurehead_tpu import schemes

        desc = schemes.get(self.scheme)
        if desc.needs_num_collect and desc.sweep_num_collect is not None:
            return desc.sweep_num_collect(n_workers)
        return None

    def payload(self) -> dict:
        out: dict = {"scheme": self.scheme}
        for k in ("num_collect", "collect_frac", "deadline"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        if self.partitions_per_worker:
            out["partitions_per_worker"] = self.partitions_per_worker
        return out


@dataclasses.dataclass(frozen=True)
class GridSpec:
    """The full what-if question (module docstring)."""

    policies: tuple
    n_workers: tuple = (8,)
    n_stragglers: tuple = (1,)
    regimes: tuple = (RegimeSpec(),)
    #: Monte-Carlo seeds per grid point (one simulated trajectory each)
    n_seeds: int = 8
    rounds: int = 30
    n_rows: int = 256
    n_cols: int = 16
    model: str = "logistic"
    update_rule: str = "GD"
    lr: Optional[float] = 1.0
    decode: str = "fixed"
    #: loss the time-to-target reduction anchors on; None = 1.05x the
    #: worst converged final loss across the grid (compare()'s rule)
    target_loss: Optional[float] = None
    #: model-init / layout-generator seed — FIXED across the grid's
    #: Monte-Carlo axis (only the arrival draw varies per seed)
    model_seed: int = 0
    data_seed: int = 0
    #: staleness axis: pipeline depths to enumerate per coordinate
    #: (cfg.pipeline_depth; parallel/pipeline.py). Default (0,) — the
    #: synchronous grid, and the axis is then OMITTED from the payload so
    #: every pre-existing spec hash (and its saved surface) is unchanged.
    #: Adding 1 grows the grid with tau=1 points; pipelining-refused
    #: combinations (exact schemes, non-GD update rules) surface as
    #: infeasible rows with the typed reason, exactly like any other
    #: validator refusal — how policy search locates the regime where the
    #: staleness win is largest without tripping over unsound corners.
    pipeline_depths: tuple = (0,)

    def __post_init__(self):
        object.__setattr__(self, "policies", tuple(self.policies))
        object.__setattr__(
            self, "n_workers", tuple(int(w) for w in self.n_workers)
        )
        object.__setattr__(
            self, "n_stragglers", tuple(int(s) for s in self.n_stragglers)
        )
        object.__setattr__(self, "regimes", tuple(self.regimes))
        object.__setattr__(
            self,
            "pipeline_depths",
            tuple(int(d) for d in self.pipeline_depths),
        )
        if not self.policies:
            raise ValueError("grid spec needs at least one policy")
        if not self.n_workers or not self.n_stragglers or not self.regimes:
            raise ValueError(
                "grid spec needs at least one n_workers, n_stragglers and "
                "regime value"
            )
        if not self.pipeline_depths or any(
            d not in (0, 1) for d in self.pipeline_depths
        ):
            raise ValueError(
                "pipeline_depths must be a non-empty subset of {0, 1} "
                f"(bounded staleness tau=1 is the only pipelined mode), "
                f"got {self.pipeline_depths!r}"
            )
        if self.n_seeds < 1:
            raise ValueError(f"n_seeds must be >= 1, got {self.n_seeds}")
        if self.rounds < 1:
            raise ValueError(f"rounds must be >= 1, got {self.rounds}")

    @property
    def n_points(self) -> int:
        return (
            len(self.policies)
            * len(self.n_workers)
            * len(self.n_stragglers)
            * len(self.regimes)
            * len(self.pipeline_depths)
        )

    def payload(self) -> dict:
        """Canonical JSON form (stable field order — the hash input)."""
        out = {
            "policies": [p.payload() for p in self.policies],
            "n_workers": list(self.n_workers),
            "n_stragglers": list(self.n_stragglers),
            "regimes": [r.payload() for r in self.regimes],
            "n_seeds": self.n_seeds,
            "rounds": self.rounds,
            "n_rows": self.n_rows,
            "n_cols": self.n_cols,
            "model": self.model,
            "update_rule": self.update_rule,
            "lr": self.lr,
            "decode": self.decode,
            "target_loss": self.target_loss,
            "model_seed": self.model_seed,
            "data_seed": self.data_seed,
        }
        # omitted at the default, like RegimeSpec's optional fields: every
        # synchronous spec keeps its pre-staleness-axis hash, so saved
        # surfaces stay rehydratable (the tau=0 no-drift contract)
        if self.pipeline_depths != (0,):
            out["pipeline_depths"] = list(self.pipeline_depths)
        return out

    def spec_hash(self) -> str:
        blob = json.dumps(self.payload(), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


@dataclasses.dataclass
class GridPoint:
    """One enumerated grid coordinate: a policy under a regime at (W, s).
    ``config`` is the fully-validated RunConfig for feasible points;
    infeasible points carry ``feasible=False`` and the validator's own
    ``reason`` instead — the surface records them, the engine never
    dispatches them."""

    label: str
    policy: PolicySpec
    n_workers: int
    n_stragglers: int
    regime: RegimeSpec
    config: Optional[object] = None
    feasible: bool = True
    reason: Optional[str] = None
    #: the point's staleness coordinate (0 = synchronous)
    pipeline_depth: int = 0


def point_config(
    spec: GridSpec, policy: PolicySpec, W: int, s: int,
    pipeline_depth: int = 0,
):
    """The RunConfig for one grid coordinate — raising ValueError exactly
    where any real entry point would (RunConfig.__post_init__ delegates to
    the registry descriptor's validate hook, which is also where a
    pipelined coordinate on an exact-decode scheme refuses)."""
    from erasurehead_tpu.utils.config import RunConfig

    num_collect = policy.resolve_num_collect(W)
    if num_collect is not None and num_collect > W:
        raise ValueError(
            f"num_collect {num_collect} exceeds n_workers {W}; a stop "
            "count past the worker set never fires"
        )
    return RunConfig(
        scheme=policy.scheme,
        model=spec.model,
        n_workers=W,
        n_stragglers=s,
        num_collect=num_collect,
        deadline=policy.deadline,
        decode=spec.decode,
        rounds=spec.rounds,
        n_rows=spec.n_rows,
        n_cols=spec.n_cols,
        update_rule=spec.update_rule,
        lr_schedule=spec.lr,
        add_delay=True,
        partitions_per_worker=policy.partitions_per_worker,
        compute_mode="deduped",
        seed=spec.model_seed,
        pipeline_depth=pipeline_depth,
    )


def enumerate_points(spec: GridSpec) -> list:
    """Every grid coordinate in deterministic order, feasibility-filtered
    (module docstring). Infeasible points come back with the validator's
    reason, never a config — including PipelineRefusal'd staleness
    coordinates (exact-decode schemes, non-GD update rules), which is how
    the surface records WHERE tau=1 is unsound rather than crashing the
    sweep."""
    points: list = []
    for policy, W, s, regime, depth in itertools.product(
        spec.policies, spec.n_workers, spec.n_stragglers, spec.regimes,
        spec.pipeline_depths,
    ):
        label = f"{policy.label}@W{W}s{s}/{regime.tag}"
        if depth:
            label += f"/tau{depth}"
        try:
            cfg = point_config(spec, policy, W, s, pipeline_depth=depth)
        except ValueError as e:
            points.append(
                GridPoint(
                    label=label, policy=policy, n_workers=W,
                    n_stragglers=s, regime=regime, config=None,
                    feasible=False, reason=str(e), pipeline_depth=depth,
                )
            )
            continue
        points.append(
            GridPoint(
                label=label, policy=policy, n_workers=W, n_stragglers=s,
                regime=regime, config=cfg, pipeline_depth=depth,
            )
        )
    return points


# ---------------------------------------------------------------------------
# CLI parsing: the comma-separated forms `erasurehead-tpu whatif` accepts

def parse_policies(text: str) -> tuple:
    """'naive,approx:c4,deadline:d1.5,approx:f0.5' -> PolicySpecs
    (cN = num_collect, fFRAC = collect fraction of W, dSECS = deadline,
    pN = partitions_per_worker — the adapt --adapt-arms syntax plus the
    grid-only fraction/partition forms)."""
    out = []
    for part in text.split(","):
        fields = part.strip().split(":")
        if not fields or not fields[0]:
            raise ValueError(f"bad policy entry {part!r}")
        kw: dict = {}
        for f in fields[1:]:
            try:
                if f.startswith("c"):
                    kw["num_collect"] = int(f[1:])
                elif f.startswith("f"):
                    kw["collect_frac"] = float(f[1:])
                elif f.startswith("d"):
                    kw["deadline"] = float(f[1:])
                elif f.startswith("p"):
                    kw["partitions_per_worker"] = int(f[1:])
                else:
                    raise ValueError
            except ValueError:
                raise ValueError(
                    f"bad policy field {f!r} in {part!r}; want cN / fFRAC "
                    "/ dSECS / pN"
                ) from None
        out.append(PolicySpec(fields[0], **kw))
    return tuple(out)


def parse_regimes(text: str) -> tuple:
    """'exp:0.5,heavytail:1.2,adversary:5,targeted:5:2,trace:PATH' ->
    RegimeSpecs. Forms: exp[:MEAN], heavytail[:ALPHA[:MEAN]],
    adversary[:SLOWDOWN[:WORKER]], targeted[:SLOWDOWN[:GROUP]],
    trace:PATH. A '+cSECS' suffix on any form adds per-round compute
    time; '+cSECSxslots' scales it by each worker's slot count (the
    faithful redundant-compute price)."""
    out = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        compute_time, compute_slots = 0.0, False
        if "+c" in part:
            part, _, suffix = part.partition("+c")
            if suffix.endswith("xslots"):
                compute_slots = True
                suffix = suffix[: -len("xslots")]
            try:
                compute_time = float(suffix)
            except ValueError:
                raise ValueError(
                    f"bad compute suffix '+c{suffix}' (want +cSECS or "
                    "+cSECSxslots)"
                ) from None
        fields = part.split(":")
        kind = fields[0]
        kw: dict = {
            "compute_time": compute_time, "compute_slots": compute_slots,
        }
        try:
            if kind == "exp":
                if len(fields) > 1:
                    kw["mean"] = float(fields[1])
            elif kind == "heavytail":
                if len(fields) > 1:
                    kw["alpha"] = float(fields[1])
                if len(fields) > 2:
                    kw["mean"] = float(fields[2])
            elif kind == "adversary":
                if len(fields) > 1:
                    kw["slowdown"] = float(fields[1])
                if len(fields) > 2:
                    kw["worker"] = int(fields[2])
            elif kind == "targeted":
                if len(fields) > 1:
                    kw["slowdown"] = float(fields[1])
                if len(fields) > 2:
                    kw["group"] = int(fields[2])
            elif kind == "trace":
                if len(fields) < 2 or not fields[1]:
                    raise ValueError
                kw["trace"] = ":".join(fields[1:])  # paths may hold ':'
            else:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"bad regime entry {part!r}; forms: exp[:MEAN], "
                "heavytail[:ALPHA[:MEAN]], adversary[:SLOWDOWN[:WORKER]], "
                "targeted[:SLOWDOWN[:GROUP]], trace:PATH"
            ) from None
        out.append(RegimeSpec(kind=kind, **kw))
    if not out:
        raise ValueError(f"no regimes in {text!r}")
    return tuple(out)


def parse_ints(text: str) -> tuple:
    try:
        return tuple(int(v) for v in text.split(",") if v.strip())
    except ValueError:
        raise ValueError(
            f"want a comma-separated int list, got {text!r}"
        ) from None
