"""Expected-time-to-target surfaces: the what-if engine's artifact.

A :class:`Surface` is the reduced form of a Monte-Carlo grid run — one
row per grid point carrying the point's coordinates, its feasibility
verdict (infeasible points keep the validator's reason), and the
reductions over the point's seed axis: expected time-to-target, reach
fraction, simulated seconds per round, decode-error mean, final-loss
mean. It is the ErasureHead Fig. 4-6 family as a data object, and the
substrate both downstream consumers read:

  - :meth:`adapt_priors` turns rows into cold-start arm values for the
    adapt/ bandit (the controller's ``time_error`` reward computed from
    simulated quantities instead of zeros);
  - :meth:`eta` quotes an admission-time expected-time-to-target for a
    RunConfig (serve/admission.EtaQuoter).

Persistence is DETERMINISTIC byte-for-byte: ``surface_rows.jsonl`` is
the canonical artifact (a header record then one row per line, stable
key order, repr-round-trip floats) and ``surface.npz`` the columnar
mirror (written through a fixed-timestamp zip so identical surfaces are
identical files). Rerunning an identical spec therefore rehydrates the
surface bitwise — pinned in tools/whatif_smoke.py and tests.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import zipfile
from typing import Optional

import numpy as np

#: stable row field order (the JSONL key order and the npz column set)
ROW_FIELDS = (
    "label", "scheme", "n_workers", "n_stragglers", "num_collect",
    "deadline", "decode", "regime", "pipeline_depth", "feasible",
    "reason", "n_seeds",
    "n_diverged", "reach_fraction", "expected_time_to_target",
    "time_to_target_std", "sim_time_per_round", "decode_error_mean",
    "final_loss_mean",
)

#: numeric columns mirrored into surface.npz (None -> NaN)
_NPZ_COLUMNS = (
    "n_workers", "n_stragglers", "num_collect", "deadline",
    "pipeline_depth", "n_seeds",
    "n_diverged", "reach_fraction", "expected_time_to_target",
    "time_to_target_std", "sim_time_per_round", "decode_error_mean",
    "final_loss_mean",
)

ROWS_FILENAME = "surface_rows.jsonl"
NPZ_FILENAME = "surface.npz"


def _write_deterministic_npz(path: str, arrays: dict) -> None:
    """np.load-compatible .npz with pinned zip metadata (fixed timestamp,
    stored not deflated, sorted member order) — identical arrays produce
    identical bytes, which is what lets a rerun be compared bitwise at
    the file level."""
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
        for name in sorted(arrays):
            buf = io.BytesIO()
            np.lib.format.write_array(
                buf, np.asarray(arrays[name]), allow_pickle=False
            )
            info = zipfile.ZipInfo(
                name + ".npy", date_time=(1980, 1, 1, 0, 0, 0)
            )
            zf.writestr(info, buf.getvalue())


@dataclasses.dataclass
class Surface:
    """One reduced what-if grid (module docstring)."""

    spec_payload: dict
    spec_hash: str
    target_loss: Optional[float]
    rows: list
    #: engine-run statistics (trajectory counts, wall seconds) — runtime
    #: telemetry only, deliberately EXCLUDED from the saved artifact so
    #: the bitwise-rehydration contract covers science, not clocks
    stats: Optional[dict] = None

    # ---- persistence -----------------------------------------------------

    def save(self, out_dir: str) -> dict:
        """Write ``surface_rows.jsonl`` + ``surface.npz`` under
        ``out_dir``; returns the paths. Deterministic bytes (module
        docstring)."""
        os.makedirs(out_dir, exist_ok=True)
        rows_path = os.path.join(out_dir, ROWS_FILENAME)
        npz_path = os.path.join(out_dir, NPZ_FILENAME)
        header = {
            "type": "whatif_surface",
            "spec_hash": self.spec_hash,
            "target_loss": self.target_loss,
            "spec": self.spec_payload,
        }
        lines = [json.dumps(header, sort_keys=True)]
        for row in self.rows:
            lines.append(
                json.dumps(
                    {k: row.get(k) for k in ROW_FIELDS}, sort_keys=False
                )
            )
        with open(rows_path, "w") as f:
            f.write("\n".join(lines) + "\n")
        arrays: dict = {
            "labels": np.asarray([r["label"] for r in self.rows]),
            "schemes": np.asarray([r["scheme"] for r in self.rows]),
            "regimes": np.asarray([r["regime"] for r in self.rows]),
            "feasible": np.asarray(
                [bool(r["feasible"]) for r in self.rows]
            ),
        }
        for col in _NPZ_COLUMNS:
            arrays[col] = np.asarray(
                [
                    float(r[col]) if r.get(col) is not None else np.nan
                    for r in self.rows
                ],
                dtype=np.float64,
            )
        _write_deterministic_npz(npz_path, arrays)
        return {"rows": rows_path, "npz": npz_path}

    @classmethod
    def load(cls, out_dir: str) -> "Surface":
        """Rehydrate a saved surface from its JSONL rows (the canonical
        artifact; the npz is the columnar mirror)."""
        rows_path = os.path.join(out_dir, ROWS_FILENAME)
        with open(rows_path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        if not lines:
            raise ValueError(f"empty surface artifact {rows_path!r}")
        header = json.loads(lines[0])
        if header.get("type") != "whatif_surface":
            raise ValueError(
                f"{rows_path!r} is not a what-if surface artifact "
                f"(header type {header.get('type')!r})"
            )
        rows = [json.loads(ln) for ln in lines[1:]]
        return cls(
            spec_payload=header.get("spec") or {},
            spec_hash=header.get("spec_hash") or "",
            target_loss=header.get("target_loss"),
            rows=rows,
        )

    @staticmethod
    def saved_hash(out_dir: str) -> Optional[str]:
        """The spec hash of the surface saved under ``out_dir`` (None if
        no readable artifact) — the engine's cheap rehydration probe."""
        rows_path = os.path.join(out_dir, ROWS_FILENAME)
        try:
            with open(rows_path) as f:
                header = json.loads(f.readline())
        except (OSError, json.JSONDecodeError):
            return None
        if header.get("type") != "whatif_surface":
            return None
        return header.get("spec_hash")

    # ---- queries ---------------------------------------------------------

    def feasible_rows(self) -> list:
        return [r for r in self.rows if r.get("feasible")]

    def lookup(
        self,
        scheme: str,
        n_workers: Optional[int] = None,
        n_stragglers: Optional[int] = None,
        num_collect: Optional[int] = None,
        deadline: Optional[float] = None,
        regime: Optional[str] = None,
    ) -> Optional[dict]:
        """Best-matching feasible row for a policy coordinate: exact
        scheme match required, then each optional coordinate narrows the
        candidate set only when it actually discriminates (a surface
        swept over one regime answers for any regime). None = the
        surface cannot speak for this policy."""
        cands = [
            r for r in self.feasible_rows() if r["scheme"] == scheme
        ]
        for key, want in (
            ("n_workers", n_workers),
            ("n_stragglers", n_stragglers),
            ("num_collect", num_collect),
            ("deadline", deadline),
            ("regime", regime),
        ):
            if want is None:
                continue
            narrowed = [r for r in cands if r.get(key) == want]
            if narrowed:
                cands = narrowed
        if not cands:
            return None
        # deterministic tie-break: the best (smallest) expected time wins,
        # unreached rows last, then label order
        def rank(r):
            t = r.get("expected_time_to_target")
            return (t is None, t if t is not None else 0.0, r["label"])

        return min(cands, key=rank)

    def eta(self, cfg, regime: Optional[str] = None) -> Optional[float]:
        """Expected time-to-target (simulated seconds) the surface
        predicts for a RunConfig's policy coordinate — the serve
        daemon's admission-time quote. None when the surface has no
        matching feasible row or the matched row never reached target."""
        row = self.lookup(
            scheme=cfg.scheme.value,
            n_workers=cfg.n_workers,
            n_stragglers=cfg.n_stragglers,
            num_collect=cfg.num_collect,
            deadline=cfg.deadline,
            regime=regime,
        )
        if row is None:
            return None
        return row.get("expected_time_to_target")

    def adapt_priors(
        self,
        arms,
        n_workers: Optional[int] = None,
        n_stragglers: Optional[int] = None,
        regime: Optional[str] = None,
        error_penalty: float = 25.0,
    ) -> dict:
        """Cold-start arm values for the adapt/ bandit, computed from the
        surface's simulated quantities in the controller's own
        ``time_error`` reward units: ``-(sim seconds per round) * (1 +
        error_penalty * decode_error_mean^2)``. Arms without a matching
        feasible row are omitted (the controller warm-up still visits
        them once). Returns {arm label: prior value}."""
        priors: dict = {}
        for arm in arms:
            row = self.lookup(
                scheme=arm.scheme,
                n_workers=n_workers,
                n_stragglers=n_stragglers,
                num_collect=arm.num_collect,
                deadline=arm.deadline,
                regime=regime,
            )
            if row is None or row.get("sim_time_per_round") is None:
                continue
            err = float(row.get("decode_error_mean") or 0.0)
            priors[arm.label] = -float(row["sim_time_per_round"]) * (
                1.0 + error_penalty * err * err
            )
        return priors

    # ---- rendering -------------------------------------------------------

    def crossover(
        self, scheme_a: str, scheme_b: str, axis: str = "regime"
    ) -> dict:
        """Where does the winner flip between two schemes along a grid
        axis? Returns {"axis", "points": [(axis value, tta_a, tta_b,
        winner), ...], "crossover": first axis value where the winner
        changed (None = no flip)} — the AGC-vs-exact crossover check.
        Axis values keep enumeration (spec) order; expected times average
        over the rows sharing the axis value (None = never reached, which
        loses to any finite time)."""
        if axis not in ("regime", "n_stragglers", "n_workers"):
            raise ValueError(
                f"crossover axis must be regime/n_stragglers/n_workers, "
                f"got {axis!r}"
            )

        def times_by_axis(scheme):
            out: dict = {}
            for r in self.feasible_rows():
                if r["scheme"] != scheme:
                    continue
                out.setdefault(r[axis], []).append(
                    r.get("expected_time_to_target")
                )
            return {
                k: (
                    float(np.mean([t for t in v if t is not None]))
                    if any(t is not None for t in v)
                    else None
                )
                for k, v in out.items()
            }

        ta, tb = times_by_axis(scheme_a), times_by_axis(scheme_b)
        axis_values = [
            r[axis]
            for r in self.rows
            if r[axis] in ta and r[axis] in tb
        ]
        seen: list = []
        for v in axis_values:
            if v not in seen:
                seen.append(v)
        points = []
        crossover = None
        prev_winner = None
        for v in seen:
            a, b = ta[v], tb[v]
            if a is None and b is None:
                winner = None
            elif b is None or (a is not None and a <= b):
                winner = scheme_a
            else:
                winner = scheme_b
            points.append((v, a, b, winner))
            if (
                winner is not None
                and prev_winner is not None
                and winner != prev_winner
                and crossover is None
            ):
                crossover = v
            if winner is not None:
                prev_winner = winner
        return {
            "axis": axis,
            "scheme_a": scheme_a,
            "scheme_b": scheme_b,
            "points": points,
            "crossover": crossover,
        }

    def format_crossover_table(
        self, scheme_a: str, scheme_b: str, axis: str = "regime"
    ) -> str:
        x = self.crossover(scheme_a, scheme_b, axis=axis)

        def fmt(t):
            return f"{t:10.3f}" if t is not None else "         -"

        header = (
            f"{x['axis']:>14s} {scheme_a:>12s} {scheme_b:>12s}  winner"
        )
        lines = [header, "-" * len(header)]
        for v, a, b, winner in x["points"]:
            mark = " <- crossover" if v == x["crossover"] else ""
            lines.append(
                f"{str(v):>14s} {fmt(a)} {fmt(b)}  "
                f"{winner or '-'}{mark}"
            )
        return "\n".join(lines)

    def format_table(self) -> str:
        header = (
            f"{'point':40s} {'t->target':>10s} {'reach':>6s} "
            f"{'s/round':>8s} {'dec err':>9s} {'final loss':>11s}"
        )
        lines = [header, "-" * len(header)]
        for r in self.rows:
            if not r.get("feasible"):
                lines.append(
                    f"{r['label']:40s} infeasible: {r.get('reason')}"
                )
                continue
            t = r.get("expected_time_to_target")
            lines.append(
                f"{r['label']:40s} "
                + (f"{t:10.3f}" if t is not None else "         -")
                + f" {r.get('reach_fraction', 0.0):6.2f}"
                + f" {r.get('sim_time_per_round', 0.0):8.4f}"
                + (
                    f" {r['decode_error_mean']:9.5f}"
                    if r.get("decode_error_mean") is not None
                    else "         -"
                )
                + (
                    f" {r['final_loss_mean']:11.6f}"
                    if r.get("final_loss_mean") is not None
                    else "           -"
                )
            )
        return "\n".join(lines)
