"""The what-if engine: grid spec -> cohort dispatches -> surface.

One :func:`run_whatif` call turns a :class:`~erasurehead_tpu.whatif.spec.
GridSpec` into a :class:`~erasurehead_tpu.whatif.surface.Surface`:

  1. **Enumerate + filter** — spec.enumerate_points builds each grid
     coordinate's RunConfig through the registry's own validation;
     infeasible points (FRC divisibility, missing num_collect/deadline,
     partial partition counts) become surface rows with the validator's
     reason and are NEVER dispatched.
  2. **Sample** — sampler.sample_arrivals draws every point's Monte-Carlo
     arrival block on-device (one vmapped dispatch per (regime, W)); all
     policies at the same (W, regime, seed) coordinate share the same
     stream, the paired-comparison contract compare() uses.
  3. **Dispatch** — (point, seed) trajectories group by cohort signature
     (experiments.plan_cohorts keys on the layout-stack signature) and
     run through the existing guarded cohort engine
     (experiments._run_configs -> _dispatch_cohort), inheriting its whole
     degradation ladder: transient retry, OOM bisection, sequential
     fallback. Hundreds of simulated runs ride a handful of compiled
     scans.
  4. **Reduce** — per-trajectory loss curves (evaluate.replay) reduce
     over the seed axis into expected-time-to-target / reach-fraction /
     decode-error rows; the surface saves as deterministic
     ``surface_rows.jsonl`` + ``surface.npz``.

Every phase emits a typed ``whatif`` event (obs/events.py), and an
out_dir whose saved artifact already matches the spec hash REHYDRATES
instead of re-simulating — rerunning an identical spec is bitwise
idempotent (tools/whatif_smoke.py).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from erasurehead_tpu.whatif import sampler as sampler_lib
from erasurehead_tpu.whatif import spec as spec_lib
from erasurehead_tpu.whatif import surface as surface_lib


def _emit(kind: str, spec_hash: str, **fields) -> None:
    from erasurehead_tpu.obs import events as obs_events

    obs_events.emit("whatif", spec_hash=spec_hash, kind=kind, **fields)


def _dataset_for(spec, n_workers: int):
    """The W-column's dataset: partitions must match the worker count, so
    each W gets its own generation at the spec's shape (rows are padded
    up to the nearest multiple of W — the same rule the suite uses)."""
    from erasurehead_tpu.data.synthetic import generate_gmm, generate_linear

    rows = max(n_workers, spec.n_rows)
    rows = n_workers * max(1, -(-rows // n_workers))  # ceil to multiple
    maker = generate_linear if spec.model == "linear" else generate_gmm
    return maker(rows, spec.n_cols, n_workers, seed=spec.data_seed)


def _trajectory_label(point_label: str, seed: int) -> str:
    return f"{point_label}#{seed}"


def run_whatif(
    spec: "spec_lib.GridSpec",
    out_dir: Optional[str] = None,
    rehydrate: bool = True,
    batch: Optional[str] = None,
) -> "surface_lib.Surface":
    """Run (or rehydrate) one what-if grid; returns its Surface.

    ``out_dir``: save the surface artifact there (and rehydrate from it
    when its saved spec hash matches — pass ``rehydrate=False`` to force
    re-simulation). ``batch`` is the cohort dispatch mode threaded into
    the sweep engine ('on'/'off'/'auto'; None = the ambient default).
    """
    from erasurehead_tpu.train import evaluate, experiments, trainer
    from erasurehead_tpu.utils.config import resolve_batch_trajectories

    spec_hash = spec.spec_hash()
    if out_dir is not None and rehydrate:
        saved = surface_lib.Surface.saved_hash(out_dir)
        if saved == spec_hash:
            surf = surface_lib.Surface.load(out_dir)
            _emit("rehydrate", spec_hash, n_rows=len(surf.rows))
            return surf

    t0 = time.perf_counter()
    points = spec_lib.enumerate_points(spec)
    feasible = [p for p in points if p.feasible]
    _emit(
        "grid",
        spec_hash,
        n_points=len(points),
        n_feasible=len(feasible),
        n_infeasible=len(points) - len(feasible),
        n_seeds=spec.n_seeds,
    )

    seeds = list(range(spec.n_seeds))
    datasets = {W: _dataset_for(spec, W) for W in spec.n_workers}

    # per-trajectory config + arrival maps, grouped per W (a cohort never
    # spans worker counts: the data stack is per-W). The arrival block for
    # one (regime, W) is drawn ONCE and shared by every policy at that
    # coordinate — the paired-comparison contract.
    curves: dict = {}
    timesets: dict = {}
    decode_means: dict = {}
    n_trajectories = 0
    for W in spec.n_workers:
        w_points = [p for p in feasible if p.n_workers == W]
        if not w_points:
            continue
        dataset = datasets[W]
        arrival_blocks: dict = {}
        configs: dict = {}
        arrivals: dict = {}
        point_of: dict = {}
        for p in w_points:
            key = (p.regime, W)
            block = arrival_blocks.get(key)
            if block is None:
                layout = trainer.build_layout(p.config)
                block = sampler_lib.sample_arrivals(
                    p.regime, spec.rounds, W, seeds, layout=layout
                )
                # layout-DEPENDENT regimes (targeted replica groups,
                # slot-scaled compute) draw per point, not per regime
                if p.regime.kind == "targeted" or p.regime.compute_slots:
                    key = (p.regime, W, p.label)
                arrival_blocks[key] = block
            for i, seed in enumerate(seeds):
                label = _trajectory_label(p.label, seed)
                configs[label] = p.config
                arrivals[label] = block[i]
                point_of[label] = p
        n_trajectories += len(configs)

        raw: dict = {}

        def _finish(label, res):
            raw[label] = res
            timesets[label] = np.asarray(res.timeset, dtype=np.float64)
            decode_means[label] = (
                float(np.mean(res.decode_error))
                if res.decode_error is not None and len(res.decode_error)
                else None
            )

        experiments._run_configs(
            configs,
            dataset,
            arrivals,
            resolve_batch_trajectories(batch),
            on_result=_finish,
        )

        # reduction replay, trajectory-batched per point: the seed axis
        # rides one vmapped scan (evaluate.replay_batch) instead of one
        # replay dispatch per Monte-Carlo trajectory
        import jax

        for p in w_points:
            labels = [_trajectory_label(p.label, s) for s in seeds]
            model = trainer.build_model(p.config)
            n = raw[labels[0]].n_train
            histories = jax.tree.map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]),
                *[raw[l].params_history for l in labels],
            )
            ev = evaluate.replay_batch(
                model,
                p.config.model,
                histories,
                dataset.X_train[:n],
                dataset.y_train[:n],
                dataset.X_test,
                dataset.y_test,
            )
            for i, label in enumerate(labels):
                curves[label] = np.asarray(
                    ev.training_loss[i], dtype=np.float64
                )
        raw.clear()

    # one shared loss target across the whole grid (compare()'s rule when
    # the spec does not pin one): 1.05x the worst converged final loss, so
    # every non-diverged point can reach it and times stay comparable
    target = spec.target_loss
    if target is None:
        finals = [
            float(c[-1])
            for c in curves.values()
            if np.isfinite(c[-1])
        ]
        target = 1.05 * max(finals) if finals else None

    rows = []
    for p in points:
        row = {
            "label": p.label,
            "scheme": p.policy.scheme,
            "n_workers": p.n_workers,
            "n_stragglers": p.n_stragglers,
            "num_collect": (
                p.config.num_collect if p.config is not None else None
            ),
            "deadline": p.policy.deadline,
            "decode": spec.decode,
            "regime": p.regime.tag,
            "pipeline_depth": p.pipeline_depth,
            "feasible": p.feasible,
            "reason": p.reason,
            "n_seeds": spec.n_seeds if p.feasible else 0,
        }
        if p.feasible:
            labels = [_trajectory_label(p.label, s) for s in seeds]
            ok = [
                l for l in labels if np.isfinite(curves[l][-1])
            ]
            ttts = [
                experiments.time_to_target_loss(
                    curves[l], timesets[l], target
                )
                for l in ok
            ] if target is not None else []
            reached = [t for t in ttts if t is not None]
            derrs = [
                decode_means[l] for l in ok if decode_means[l] is not None
            ]
            row.update(
                n_diverged=len(labels) - len(ok),
                reach_fraction=(
                    round(len(reached) / len(labels), 6) if labels else 0.0
                ),
                expected_time_to_target=(
                    round(float(np.mean(reached)), 6) if reached else None
                ),
                time_to_target_std=(
                    round(float(np.std(reached)), 6) if reached else None
                ),
                sim_time_per_round=(
                    round(
                        float(
                            np.mean(
                                [timesets[l].sum() for l in ok]
                            )
                        )
                        / spec.rounds,
                        6,
                    )
                    if ok
                    else None
                ),
                decode_error_mean=(
                    round(float(np.mean(derrs)), 8) if derrs else None
                ),
                final_loss_mean=(
                    round(
                        float(np.mean([curves[l][-1] for l in ok])), 6
                    )
                    if ok
                    else None
                ),
            )
        else:
            row.update(
                n_diverged=0,
                reach_fraction=0.0,
                expected_time_to_target=None,
                time_to_target_std=None,
                sim_time_per_round=None,
                decode_error_mean=None,
                final_loss_mean=None,
            )
        _emit(
            "point",
            spec_hash,
            label=p.label,
            feasible=p.feasible,
            reason=p.reason,
            expected_time_to_target=row["expected_time_to_target"],
            reach_fraction=row["reach_fraction"],
        )
        rows.append(row)

    wall = time.perf_counter() - t0
    surf = surface_lib.Surface(
        spec_payload=spec.payload(),
        spec_hash=spec_hash,
        target_loss=target,
        rows=rows,
        stats={
            "n_trajectories": n_trajectories,
            "wall_s": round(wall, 4),
            "runs_per_sec": (
                round(n_trajectories / wall, 3) if wall > 0 else None
            ),
        },
    )
    if out_dir is not None:
        paths = surf.save(out_dir)
        _emit(
            "surface",
            spec_hash,
            n_rows=len(rows),
            path=paths["rows"],
        )
    return surf


# ---------------------------------------------------------------------------
# CLI: `erasurehead-tpu whatif`

def main(argv=None) -> int:
    """Grid spec flags -> surface artifact -> rendered crossover table."""
    import argparse
    import contextlib
    import os

    p = argparse.ArgumentParser(
        prog="erasurehead-tpu whatif",
        description=(
            "Monte-Carlo policy search over the scheme x regime grid: "
            "simulate every feasible (policy, W, s, regime) point over "
            "n seeds as batched cohort dispatches and reduce to an "
            "expected-time-to-target surface"
        ),
    )
    p.add_argument("--policies", default="naive,cyccoded,approx",
                   help="comma-separated policy specs "
                        "'scheme[:cN][:fFRAC][:dSECS][:pN]' (cN = "
                        "num_collect, fFRAC = collect fraction of W, "
                        "dSECS = deadline, pN = partitions_per_worker)")
    p.add_argument("--workers", default="8",
                   help="comma-separated worker counts (grid axis)")
    p.add_argument("--stragglers", default="1",
                   help="comma-separated straggler counts (grid axis)")
    p.add_argument("--regimes", default="exp:0.5",
                   help="comma-separated regime specs: exp[:MEAN], "
                        "heavytail[:ALPHA[:MEAN]], "
                        "adversary[:SLOWDOWN[:WORKER]], "
                        "targeted[:SLOWDOWN[:GROUP]], trace:PATH; a "
                        "'+cSECS[xslots]' suffix adds per-round compute "
                        "time (xslots scales it by each worker's slot "
                        "count — the faithful redundant-compute price)")
    p.add_argument("--seeds", type=int, default=8,
                   help="Monte-Carlo seeds per grid point")
    p.add_argument("--rounds", type=int, default=30)
    p.add_argument("--rows", type=int, default=256)
    p.add_argument("--cols", type=int, default=16)
    p.add_argument("--model", default="logistic",
                   choices=["logistic", "linear"])
    p.add_argument("--update-rule", default="GD",
                   choices=["GD", "AGD", "ADAM"])
    p.add_argument("--lr", type=float, default=1.0)
    p.add_argument("--decode", default="fixed",
                   choices=["fixed", "optimal"])
    p.add_argument("--target-loss", type=float, default=None,
                   help="time-to-target anchor; default 1.05x the worst "
                        "converged final loss across the grid")
    p.add_argument("--pipeline-depths", default="0",
                   help="comma-separated staleness axis (subset of 0,1): "
                        "1 adds bounded-staleness pipelined points "
                        "(tau=1, --pipeline-depth) per coordinate; "
                        "pipelining-refused combinations surface as "
                        "infeasible rows with the typed reason")
    p.add_argument("--out", default=None, metavar="DIR",
                   help="save surface_rows.jsonl + surface.npz (and the "
                        "events.jsonl run log) here; reruns of an "
                        "identical spec rehydrate from it bitwise")
    p.add_argument("--no-rehydrate", action="store_true",
                   help="re-simulate even when --out already holds this "
                        "spec's surface")
    p.add_argument("--crossover", default=None, metavar="A,B[,AXIS]",
                   help="render the A-vs-B crossover table along AXIS "
                        "(regime | n_stragglers | n_workers; default "
                        "regime), e.g. 'approx,cyccoded,n_stragglers'")
    p.add_argument("--batch-trajectories", default=None,
                   choices=["on", "off", "auto"])
    p.add_argument("--quiet", action="store_true")
    ns = p.parse_args(argv)

    try:
        grid = spec_lib.GridSpec(
            policies=spec_lib.parse_policies(ns.policies),
            n_workers=spec_lib.parse_ints(ns.workers),
            n_stragglers=spec_lib.parse_ints(ns.stragglers),
            regimes=spec_lib.parse_regimes(ns.regimes),
            n_seeds=ns.seeds,
            rounds=ns.rounds,
            n_rows=ns.rows,
            n_cols=ns.cols,
            model=ns.model,
            update_rule=ns.update_rule,
            lr=ns.lr,
            decode=ns.decode,
            target_loss=ns.target_loss,
            pipeline_depths=spec_lib.parse_ints(ns.pipeline_depths),
        )
    except ValueError as e:
        p.error(str(e))

    from erasurehead_tpu.obs import events as events_lib
    from erasurehead_tpu.parallel.backend import initialize_distributed

    initialize_distributed()
    capture = (
        events_lib.capture(os.path.join(ns.out, "events.jsonl"))
        if ns.out
        else contextlib.nullcontext()
    )
    with capture:
        surf = run_whatif(
            grid,
            out_dir=ns.out,
            rehydrate=not ns.no_rehydrate,
            batch=ns.batch_trajectories,
        )
    if not ns.quiet:
        print(f"spec {surf.spec_hash}: {len(surf.rows)} grid points", end="")
        if surf.stats:
            print(
                f", {surf.stats['n_trajectories']} simulated runs in "
                f"{surf.stats['wall_s']}s "
                f"({surf.stats['runs_per_sec']} runs/s)"
            )
        else:
            print(" (rehydrated)")
        print(surf.format_table())
        if ns.crossover:
            fields = [f.strip() for f in ns.crossover.split(",")]
            if len(fields) not in (2, 3):
                p.error("--crossover wants 'schemeA,schemeB[,axis]'")
            axis = fields[2] if len(fields) == 3 else "regime"
            print()
            print(
                surf.format_crossover_table(fields[0], fields[1], axis)
            )
        if ns.out:
            print(f"\nsurface -> {ns.out}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
