"""HTTP/1.1 JSONL front: the serve daemon goes on the network.

Stdlib only (``http.server`` threads — no new deps), riding the same
queue model as the AF_UNIX front (serve/queue.py), so the HTTP surface
can never accept a config the in-process surface would refuse:

  - ``POST /v1/submit`` — one JSON request body (``label``, ``config``,
    optional ``target_loss``/``data_seed``/``priority``/``retry``; the
    tenant comes from the bearer token when auth is on, the body when
    off). Replies: 202 ``{"request_id", "eta_s"}`` on acceptance, 400 on
    a refused payload, 401 on a bad token, and 429 with a ``Retry-After``
    header (plus the exact ``retry_after_s`` in the body) when the
    daemon's intake queue is at its high-water mark — backpressure is a
    first-class reply, never a hang.
  - ``GET /v1/stream`` — chunked transfer encoding, one JSON line per
    finished result for the authenticated tenant, written AS JOURNAL ROWS
    LAND. Each connection owns a BOUNDED outbox: a slow or wedged reader
    sheds rows (``{"type": "overflow", "dropped": n}`` marks the gap and
    a ``stream`` event journals it) instead of backing pressure up into
    the dispatch pool — the rows are journaled per tenant, so the client
    re-fetches by resubmitting (idempotent; rehydrates bitwise).
    ``{"type": "ping"}`` heartbeats flow when idle so half-open
    connections die at the writer, not in the kernel.
  - ``GET /healthz`` — queue depth, in-flight dispatches, uptime; the
    load generator and restart harnesses poll it for readiness.
  - ``GET /metrics`` — Prometheus text exposition (obs/exporter.py) of
    the process metrics registry plus the front's live timeseries
    gauges (obs/timeseries.py, attached in-process to the event
    stream). Unauthenticated like /healthz: it is the scrape surface.
  - ``GET /v1/stats`` — per-tenant JSON stats (requests, completed
    rows, rejects, SLO burn rate) for the authenticated tenant (or
    ``?tenant=`` with auth off).

Auth is per-tenant bearer tokens (a JSON ``{token: tenant}`` map): the
token *names* the tenant, so a client can only submit into — and stream
from — its own journal namespace. With auth off (trusted localhost, the
default for `make serve-load-smoke`), the body/query tenant is used
verbatim, matching the AF_UNIX front's filesystem-permission trust.
"""

from __future__ import annotations

import json
import queue as queue_lib
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.obs.metrics import REGISTRY as _METRICS
from erasurehead_tpu.serve.queue import (
    ServeOverloadedError,
    ServeResult,
    config_from_payload,
)
from erasurehead_tpu.serve.wal import WalAdoptionError

#: default bound on one stream connection's outbox (result lines queued
#: for a reader that hasn't drained them); beyond it rows are shed —
#: drop-and-journal, never block the dispatch pool
DEFAULT_OUTBOX_LIMIT = 256


def healthz_answers(hostport: str, timeout: float = 1.0) -> bool:
    """One /healthz probe of ``"host:port"``: True iff the daemon
    answered 200 within ``timeout``. The adoption guard (POST /v1/adopt
    with an ``owner``) and the fleet supervisor's membership probes both
    ride this — a refused connection, a timeout, or a non-200 all read
    as "did not answer", never as an exception."""
    import http.client

    host, port = parse_hostport(hostport)
    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("GET", "/healthz")
            return conn.getresponse().status == 200
        finally:
            conn.close()
    except OSError:
        return False


def parse_hostport(spec: str) -> tuple[str, int]:
    """``"HOST:PORT"`` (or bare ``"PORT"``) -> (host, port); port 0 asks
    the kernel for a free one."""
    host, sep, port = str(spec).rpartition(":")
    if not sep:
        host, port = "127.0.0.1", port or "0"
    try:
        return (host or "127.0.0.1", int(port))
    except ValueError:
        raise ValueError(
            f"--http wants HOST:PORT (or PORT), got {spec!r}"
        ) from None


class _QuietThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that doesn't traceback-spam on the normal
    fate of a streaming connection: the reader hangs up mid-write."""

    daemon_threads = True
    # socketserver's default accept backlog is 5 — a closed-loop load
    # burst (hundreds of concurrent clients) overflows it and the kernel
    # RESETS connections, which reads as daemon death. Size it for the
    # front's actual job.
    request_queue_size = 128

    def handle_error(self, request, client_address):
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            return
        super().handle_error(request, client_address)


class _Subscription:
    """One stream connection's bounded outbox + overflow accounting."""

    def __init__(self, tenant: str, limit: int):
        self.tenant = tenant
        self.q: "queue_lib.Queue[dict]" = queue_lib.Queue(maxsize=limit)
        self.dropped = 0  # rows shed since the last overflow marker
        self.total_dropped = 0
        self.lock = threading.Lock()


class StreamHub:
    """Fan-out of delivered results to per-connection bounded outboxes.

    ``publish`` is the server's result listener: it runs on the dispatch
    pool and NEVER blocks — a full outbox sheds the row (counted, marked
    in-stream, journaled as a ``stream`` overflow event) rather than
    slowing anyone else's dispatch."""

    def __init__(self, outbox_limit: int = DEFAULT_OUTBOX_LIMIT):
        self.outbox_limit = int(outbox_limit)
        self._subs: dict[int, _Subscription] = {}
        self._ids = 0
        self._lock = threading.Lock()

    def subscribe(self, tenant: str) -> tuple[int, _Subscription]:
        with self._lock:
            self._ids += 1
            sid = self._ids
            sub = _Subscription(tenant, self.outbox_limit)
            self._subs[sid] = sub
        events_lib.emit("stream", tenant=tenant, event="open")
        return sid, sub

    def unsubscribe(self, sid: int) -> None:
        with self._lock:
            sub = self._subs.pop(sid, None)
        if sub is not None:
            events_lib.emit(
                "stream",
                tenant=sub.tenant,
                event="close",
                dropped=sub.total_dropped,
            )

    def publish(self, result: ServeResult) -> None:
        line = {
            "type": "result",
            "request_id": result.request_id,
            "tenant": result.tenant,
            "label": result.label,
            "status": result.status,
            "row": result.row,
            "error": result.error,
            "resumed": result.resumed,
        }
        with self._lock:
            subs = [
                s for s in self._subs.values() if s.tenant == result.tenant
            ]
        for sub in subs:
            try:
                sub.q.put_nowait(line)
            except queue_lib.Full:
                with sub.lock:
                    first_of_burst = sub.dropped == 0
                    sub.dropped += 1
                    sub.total_dropped += 1
                _METRICS.counter("serve.stream_dropped").inc()
                if first_of_burst:
                    # one event per burst, not per shed row — the marker
                    # line carries the exact count once the reader drains
                    events_lib.emit(
                        "stream",
                        tenant=sub.tenant,
                        event="overflow",
                        dropped=sub.total_dropped,
                    )


class HttpFront:
    """HTTP listener bridging network clients onto a SweepServer."""

    def __init__(
        self,
        server,
        host: str = "127.0.0.1",
        port: int = 0,
        tokens: Optional[dict] = None,
        outbox_limit: int = DEFAULT_OUTBOX_LIMIT,
        slo_ttlr_s: Optional[float] = None,
        slo_budget: float = 0.1,
    ):
        from erasurehead_tpu.obs import exporter as exporter_lib
        from erasurehead_tpu.obs.timeseries import TimeseriesReducer

        self.server = server
        #: token -> tenant; None = auth off (trusted-localhost mode)
        self.tokens = dict(tokens) if tokens else None
        self.hub = StreamHub(outbox_limit)
        server.add_result_listener(self.hub.publish)
        # the live plane: a timeseries reducer rides the in-process event
        # stream (request/pack/admit/reject + any training capture) so
        # GET /metrics answers from windowed state, no file tail needed
        self.reducer = TimeseriesReducer()
        self._reducer_detach = self.reducer.attach()
        self.slo = (
            exporter_lib.SloTracker(
                slo_ttlr_s, budget=slo_budget
            )
            if slo_ttlr_s
            else None
        )
        if self.slo is not None:
            events_lib.add_observer(self.slo.observe)
        self._exporter = exporter_lib
        self._started = time.monotonic()
        front = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = "erasurehead-serve"

            def log_message(self, fmt, *args):  # noqa: D102 — quiet
                pass

            def _reply(self, code: int, obj: dict, headers=()):
                body = (json.dumps(obj) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _tenant(self) -> Optional[str]:
                """The authenticated tenant, or None after a 401 reply
                (auth on + bad/missing token). With auth off, the caller
                falls back to body/query tenant."""
                if front.tokens is None:
                    return ""
                auth = self.headers.get("Authorization", "")
                token = auth[7:] if auth.startswith("Bearer ") else None
                tenant = front.tokens.get(token) if token else None
                if tenant is None:
                    _METRICS.counter("serve.rejected").inc()
                    events_lib.emit(
                        "reject", tenant="unknown", reason="unauthorized"
                    )
                    self._reply(
                        401,
                        {"type": "error",
                         "message": "missing or unknown bearer token"},
                        headers=[("WWW-Authenticate", "Bearer")],
                    )
                    return None
                return tenant

            def do_POST(self):  # noqa: N802 — http.server API
                if self.path == "/v1/adopt":
                    self._adopt()
                    return
                if self.path != "/v1/submit":
                    self._reply(404, {"type": "error",
                                      "message": f"no route {self.path}"})
                    return
                tenant = self._tenant()
                if tenant is None:
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    msg = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(msg, dict):
                        raise ValueError("request body must be an object")
                    cfg = config_from_payload(msg.get("config") or {})
                    handle = front.server.submit(
                        tenant=tenant or msg.get("tenant"),
                        label=msg.get("label"),
                        config=cfg,
                        target_loss=msg.get("target_loss"),
                        data_seed=int(msg.get("data_seed", 0)),
                        priority=int(msg.get("priority", 0)),
                        retry=int(msg.get("retry", 0)),
                    )
                except ServeOverloadedError as e:
                    # delta-seconds must be >= 1 for the header; the body
                    # carries the exact quote for backoff arithmetic
                    self._reply(
                        429,
                        {"type": "rejected",
                         "retry_after_s": e.retry_after_s,
                         "message": str(e)},
                        headers=[(
                            "Retry-After",
                            str(max(1, int(e.retry_after_s + 0.999))),
                        )],
                    )
                    return
                except Exception as e:  # noqa: BLE001 — per-request
                    self._reply(
                        400,
                        {"type": "error",
                         "message": f"{type(e).__name__}: {e}"},
                    )
                    return
                self._reply(
                    202,
                    {"type": "accepted",
                     "request_id": handle.request_id,
                     "eta_s": handle.eta_s},
                )

            def _adopt(self) -> None:
                """``POST /v1/adopt`` — fleet seam (serve/fleet.py): the
                supervisor asks THIS replica to adopt a declared-dead
                peer's intake WAL. Body: ``{"path": <wal path>,
                "replica": <dead peer's name>, "owner": <"host:port" or
                null>}``. When ``owner`` is given, the adoption re-probes
                the owner's /healthz first and refuses if it answers —
                the final guard against adopting a live daemon's working
                set. 409 on refusal (already adopted / owner alive)."""
                if self._tenant() is None:
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    msg = json.loads(self.rfile.read(n) or b"{}")
                    path = msg.get("path")
                    if not isinstance(path, str) or not path:
                        raise ValueError("adopt body wants a WAL 'path'")
                    owner = msg.get("owner")
                    owner_alive = (
                        (lambda: healthz_answers(owner))
                        if owner
                        else None
                    )
                    out = front.server.adopt_wal(
                        path,
                        owner_alive=owner_alive,
                        dead_replica=str(
                            msg.get("replica") or "unknown"
                        ),
                    )
                except WalAdoptionError as e:
                    self._reply(
                        409,
                        {"type": "refused", "message": str(e)},
                    )
                    return
                except Exception as e:  # noqa: BLE001 — per-request
                    self._reply(
                        400,
                        {"type": "error",
                         "message": f"{type(e).__name__}: {e}"},
                    )
                    return
                self._reply(202, {"type": "adopted", **out})

            def do_GET(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                if path == "/healthz":
                    with front.server._state_lock:
                        in_flight = front.server._in_flight
                    body = {
                        "status": "ok",
                        "queued": front.server.queued_depth(),
                        "in_flight": in_flight,
                        "admission": (
                            front.server.admission.pressure()
                        ),
                        "uptime_s": round(
                            time.monotonic() - front._started, 3
                        ),
                    }
                    # fleet gossip: who this replica is, where its WAL
                    # lives (the path a peer adopts on death), and how
                    # many peers' WALs it has adopted so far
                    if front.server.replica_name is not None:
                        body["replica"] = front.server.replica_name
                    if front.server.wal is not None:
                        body["wal_path"] = front.server.wal.path
                    body["adoptions"] = front.server.adoptions_total
                    self._reply(200, body)
                    return
                if path == "/metrics":
                    # the scrape surface: SLO windows are re-scored on
                    # scrape (emitting slo events the reducer folds in),
                    # then the registry + live gauges render as one
                    # deterministic text exposition
                    if front.slo is not None:
                        front.slo.evaluate()
                    body = front._exporter.render_prometheus(
                        _METRICS, front.reducer.gauges()
                    ).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", front._exporter.PROM_CONTENT_TYPE
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path not in ("/v1/stream", "/v1/stats"):
                    self._reply(404, {"type": "error",
                                      "message": f"no route {path}"})
                    return
                tenant = self._tenant()
                if tenant is None:
                    return
                if not tenant:
                    params = dict(
                        kv.partition("=")[::2]
                        for kv in query.split("&")
                        if kv
                    )
                    tenant = params.get("tenant", "")
                    if not tenant:
                        self._reply(
                            400,
                            {"type": "error",
                             "message": f"{path[4:]} wants ?tenant= "
                                        f"(or auth)"},
                        )
                        return
                if path == "/v1/stats":
                    self._reply(200, front.tenant_stats(tenant))
                    return
                self._stream(tenant)

            def _chunk(self, obj: dict) -> None:
                data = (json.dumps(obj) + "\n").encode()
                self.wfile.write(
                    f"{len(data):x}\r\n".encode() + data + b"\r\n"
                )
                self.wfile.flush()

            def _stream(self, tenant: str) -> None:
                sid, sub = front.hub.subscribe(tenant)
                try:
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/jsonlines"
                    )
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    last_beat = time.monotonic()
                    while not front._closing:
                        try:
                            line = sub.q.get(timeout=0.2)
                        except queue_lib.Empty:
                            line = None
                        # the overflow marker rides AFTER the queue
                        # drains: the reader knows exactly where the gap
                        # is and how many rows to re-fetch
                        if line is None:
                            with sub.lock:
                                dropped, sub.dropped = sub.dropped, 0
                            if dropped:
                                self._chunk(
                                    {"type": "overflow",
                                     "dropped": dropped}
                                )
                                continue
                            if time.monotonic() - last_beat > 5.0:
                                self._chunk({"type": "ping"})
                                last_beat = time.monotonic()
                            continue
                        self._chunk(line)
                        last_beat = time.monotonic()
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # reader went away; rows are journaled
                finally:
                    front.hub.unsubscribe(sid)

        self._closing = False
        self._httpd = _QuietThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="eh-serve-http",
            daemon=True,
        )
        self._thread.start()

    def tenant_stats(self, tenant: str) -> dict:
        """One tenant's live stats from the windowed reducer state: the
        ``GET /v1/stats`` body. Sums the retained windows (bounded, so
        this is a rolling horizon, not all-time) plus the latest SLO
        window if the tracker is armed."""
        snap = self.reducer.snapshot()
        totals = {"requests": 0, "rows_ok": 0, "done": 0, "rejects": 0}
        goodput = 0.0
        for w in snap["windows"]:
            tv = w["tenants"].get(tenant)
            if tv:
                for k in totals:
                    totals[k] += tv.get(k, 0)
        if snap["windows"]:
            last = snap["windows"][-1]["tenants"].get(tenant)
            if last:
                goodput = last["rows_ok"] / self.reducer.window_s
        out = {
            "tenant": tenant,
            "window_s": self.reducer.window_s,
            "horizon_s": self.reducer.window_s * len(snap["windows"]),
            **totals,
            "goodput_rows_per_sec": round(goodput, 4),
            "queued": self.server.queued_depth(),
        }
        slo_rec = (snap.get("slo") or {}).get(tenant)
        if self.slo is not None:
            rows = [r for r in self.slo.evaluate() if r["tenant"] == tenant]
            slo_rec = rows[0] if rows else slo_rec
        if slo_rec is not None:
            out["slo"] = {
                k: slo_rec[k]
                for k in (
                    "slo_s", "window_requests", "breaches", "burn_rate"
                )
                if k in slo_rec
            }
        return out

    def close(self) -> None:
        self._closing = True
        self._reducer_detach.detach()
        if self.slo is not None:
            events_lib.remove_observer(self.slo.observe)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
