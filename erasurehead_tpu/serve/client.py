"""Clients for the serve daemon's network fronts.

Two transports, one contract:

  - :class:`ServeClient` — newline-delimited JSON over the AF_UNIX
    socket (see server.SocketFront): one ``submit`` line per request,
    streamed ``result`` lines back as the daemon's packed dispatches
    land. A reader thread demultiplexes the responses, so any number of
    submissions may be in flight on one connection; results arrive in
    COMPLETION order — match them up by ``request_id`` (or ``label``).
  - :class:`HttpServeClient` — the HTTP/1.1 JSONL front
    (serve/http_front.py): ``POST /v1/submit`` per request plus one
    long-lived chunked ``GET /v1/stream`` connection the reader thread
    drains. Auth is a per-tenant bearer token.

Failure taxonomy (the part the reference's mpirun-and-pray lifecycle
never had):

  - **daemon death** raises :class:`ServeUnavailableError` naming the
    endpoint and the last event seen on the wire — never a raw
    ``queue.Empty`` or socket errno;
  - **backpressure** (socket ``rejected`` line / HTTP 429) raises
    :class:`ServeRejectedError` carrying the daemon's ``retry_after_s``
    quote — or, with ``max_retries > 0``, is retried in-client on a
    DETERMINISTIC capped-exponential schedule that honors the quote
    (``wait = max(retry_after_s, min(cap, base * 2**attempt))``, no
    jitter: a rejected request's resubmission is idempotent by digest,
    so synchronized retries cost duplicate 429s, not duplicate rows);
  - **a client-side wait timeout** stays ``queue.Empty`` (the daemon is
    alive, the result genuinely isn't ready); the server-side
    ``request_timeout_s`` knob turns a stalled dispatch into a typed
    error *result* instead.
"""

from __future__ import annotations

import json
import queue as queue_lib
import socket
import threading
import time
from typing import Optional


class ServeUnavailableError(RuntimeError):
    """The daemon went away (connect refused, connection dropped, or the
    reader hit EOF) — distinguishable from a result that merely isn't
    ready yet. ``endpoint`` names the socket path or URL; ``last_event``
    is the last wire message type seen before the drop (None = the
    connection never spoke)."""

    def __init__(self, endpoint: str, last_event: Optional[str],
                 detail: str = ""):
        self.endpoint = endpoint
        self.last_event = last_event
        msg = (
            f"serve daemon unavailable at {endpoint} "
            f"(last event seen: {last_event or 'none'})"
        )
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class ServeRejectedError(RuntimeError):
    """Backpressure: the daemon answered 429/"rejected" instead of
    accepting. ``retry_after_s`` is the schedule quote to honor."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


def backoff_s(
    attempt: int,
    retry_after_s: Optional[float],
    base: float = 0.1,
    cap: float = 10.0,
) -> float:
    """The deterministic capped-exponential wait before retry number
    ``attempt`` (0-based): the daemon's retry-after quote wins when it is
    the longer, the exponential floor keeps a client whose quotes are
    stale from hammering, and the cap bounds the tail."""
    exp = min(cap, base * (2.0 ** attempt))
    return max(float(retry_after_s or 0.0), exp)


class ServeClient:
    """One connection to a serve daemon's unix socket — or a FLEET of
    them. ``path`` may be a single socket path or a list of paths: the
    client connects to the first reachable one in list order and, when
    the daemon behind it dies (:class:`ServeUnavailableError`), fails
    over to the NEXT endpoint in list order, wrapping — deterministic,
    so every client walks the same ring. An endpoint that quoted
    backpressure is embargoed for its own ``retry_after_s`` and
    deprioritized while the embargo holds (Retry-After is per endpoint:
    one overloaded replica never stalls submission to its peers). A
    submission is only re-sent when its ``accepted`` reply never
    arrived; acceptance is idempotent by request digest server-side, so
    failover cannot double-dispatch."""

    def __init__(self, path, timeout: Optional[float] = None):
        if isinstance(path, (str, bytes)):
            self.paths = [str(path)]
        else:
            self.paths = [str(p) for p in path]
        if not self.paths:
            raise ValueError("ServeClient wants at least one socket path")
        self._idx = 0
        self._timeout = timeout
        self.last_event: Optional[str] = None
        self._wlock = threading.Lock()
        self._accepted: "queue_lib.Queue[dict]" = queue_lib.Queue()
        self._results: "queue_lib.Queue[dict]" = queue_lib.Queue()
        self.rejected_total = 0  # 429/"rejected" replies seen
        self.retried_total = 0  # submissions re-sent after a rejection
        self.failovers_total = 0  # endpoint rotations after a drop
        #: endpoint -> monotonic instant before which its own 429 quote
        #: says not to bother it again
        self._not_before: dict[str, float] = {}
        self._sock: Optional[socket.socket] = None
        self._closed = threading.Event()
        self._closed.set()
        self._connect()

    @property
    def path(self) -> str:
        """The endpoint currently connected (or next to be tried)."""
        return self.paths[self._idx]

    def _connect(self) -> None:
        """Connect to an endpoint, walking the list in order from the
        current index (wrapping) — embargoed endpoints are tried LAST.
        Deterministic: the same list and the same failures produce the
        same walk. Raises when no endpoint is reachable."""
        order = [
            (self._idx + s) % len(self.paths)
            for s in range(len(self.paths))
        ]
        now = time.monotonic()
        ready = [
            i for i in order
            if self._not_before.get(self.paths[i], 0.0) <= now
        ]
        embargoed = [i for i in order if i not in ready]
        last_err: Optional[Exception] = None
        for idx in ready + embargoed:
            p = self.paths[idx]
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self._timeout)
            try:
                sock.connect(p)
            except OSError as e:
                sock.close()
                last_err = e
                continue
            closed = threading.Event()
            self._sock, self._closed, self._idx = sock, closed, idx
            threading.Thread(
                target=self._read_loop, args=(sock, closed),
                name="eh-serve-client", daemon=True,
            ).start()
            return
        raise ServeUnavailableError(
            ", ".join(self.paths),
            self.last_event,
            str(last_err) if last_err else "no reachable endpoint",
        )

    def _read_loop(self, sock: socket.socket,
                   closed: threading.Event) -> None:
        buf = b""
        try:
            while True:
                try:
                    chunk = sock.recv(1 << 16)
                except OSError:
                    return
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    raw, buf = buf.split(b"\n", 1)
                    if not raw.strip():
                        continue
                    try:
                        msg = json.loads(raw)
                    except json.JSONDecodeError:
                        continue
                    self.last_event = msg.get("type")
                    if msg.get("type") == "result":
                        self._results.put(msg)
                    else:  # accepted / rejected / error — submit replies
                        self._accepted.put(msg)
        finally:
            closed.set()

    def _unavailable(self, detail: str = "") -> ServeUnavailableError:
        return ServeUnavailableError(self.path, self.last_event, detail)

    def _send_await(self, line: str, timeout: Optional[float]) -> dict:
        """Send one submit line and await its accepted/rejected reply.
        The lock spans the send AND the reply: replies correlate purely
        by submit order, so two concurrent submitters must not each read
        the other's request_id."""
        with self._wlock:
            if self._closed.is_set():
                raise self._unavailable("connection closed")
            try:
                self._sock.sendall(line.encode())
            except OSError as e:
                raise self._unavailable(str(e)) from e
            deadline = (
                None if timeout is None else time.monotonic() + timeout
            )
            while True:
                try:
                    return self._accepted.get(timeout=0.2)
                except queue_lib.Empty:
                    if self._closed.is_set():
                        raise self._unavailable(
                            "connection closed while awaiting the "
                            "accepted reply"
                        ) from None
                    if deadline is not None and (
                        time.monotonic() >= deadline
                    ):
                        raise

    def submit(
        self,
        tenant: str,
        label: str,
        config: dict,
        target_loss: Optional[float] = None,
        data_seed: int = 0,
        timeout: Optional[float] = 30.0,
        priority: int = 0,
        max_retries: int = 0,
        backoff_base: float = 0.1,
        backoff_cap: float = 10.0,
    ) -> str:
        """Submit one trajectory request; returns its request_id.

        Raises RuntimeError when the daemon refuses the payload,
        :class:`ServeRejectedError` on backpressure once ``max_retries``
        deterministic capped-exponential attempts (honoring the daemon's
        retry-after quotes) are exhausted, and
        :class:`ServeUnavailableError` when the daemon is gone. Thread-
        safe: the accepted reply is correlated purely by submit order, so
        the lock spans the send AND the reply — two concurrent
        submitters must not each read the other's request_id."""
        for attempt in range(max_retries + 1):
            line = json.dumps(
                {
                    "op": "submit",
                    "tenant": tenant,
                    "label": label,
                    "config": config,
                    "target_loss": target_loss,
                    "data_seed": data_seed,
                    "priority": priority,
                    "retry": attempt,
                }
            ) + "\n"
            # failover ring: an unacknowledged submission re-sends to the
            # next endpoint in list order; one that WAS accepted returns
            # before ever reaching this loop again — no duplicate submit
            for hop in range(len(self.paths)):
                try:
                    reply = self._send_await(line, timeout)
                    break
                except ServeUnavailableError:
                    if hop == len(self.paths) - 1:
                        raise
                    self._idx = (self._idx + 1) % len(self.paths)
                    self.failovers_total += 1
                    self._connect()
            rtype = reply.get("type")
            if rtype == "accepted":
                # what-if ETA quote (daemon --eta-surface; None without
                # one): exposed on the client rather than the return
                # value so existing submit() callers keep their
                # request_id contract
                self.last_eta_s = reply.get("eta_s")
                return reply["request_id"]
            if rtype == "rejected":
                retry_after = float(reply.get("retry_after_s") or 0.0)
                # the quote embargoes THIS endpoint; a later failover
                # walk tries un-embargoed peers first
                self._not_before[self.path] = (
                    time.monotonic() + retry_after
                )
                self.rejected_total += 1
                if attempt < max_retries:
                    self.retried_total += 1
                    time.sleep(
                        backoff_s(
                            attempt, retry_after,
                            base=backoff_base, cap=backoff_cap,
                        )
                    )
                    continue
                raise ServeRejectedError(
                    reply.get("message", "serve daemon rejected the "
                              "request (overloaded)"),
                    retry_after_s=retry_after,
                )
            raise RuntimeError(
                f"serve daemon refused the request: "
                f"{reply.get('message', reply)}"
            )
        raise AssertionError("unreachable")  # loop always returns/raises

    def result(self, timeout: Optional[float] = None) -> dict:
        """The next finished trajectory (completion order, any of this
        connection's requests): {"request_id", "tenant", "label",
        "status", "row", "error", "resumed"}. Raises ``queue.Empty`` on
        a live-daemon timeout and :class:`ServeUnavailableError` when
        the daemon died with results still owed."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while True:
            try:
                return self._results.get(timeout=0.2)
            except queue_lib.Empty:
                if self._closed.is_set() and self._results.empty():
                    raise self._unavailable(
                        "connection closed with results still owed "
                        "(rows are journaled; resubmit to re-fetch)"
                    ) from None
                if deadline is not None and time.monotonic() >= deadline:
                    raise

    def close(self) -> None:
        if self._sock is None:
            return
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


def _normalize_endpoints(host, port, endpoints) -> list:
    """``(host, port)`` or a LIST of endpoints -> ``[(host, port), ...]``.
    List elements may be ``(host, port)`` tuples or ``"host:port"``
    strings; a bare ``host`` that is itself a list is treated as the
    endpoint list (so ``HttpServeClient([...], tenant=...)`` reads
    naturally)."""
    if endpoints is None and not isinstance(host, (str, bytes)) and (
        host is not None
    ):
        endpoints, host = host, None
    if endpoints is not None:
        out = []
        for ep in endpoints:
            if isinstance(ep, (tuple, list)):
                h, p = ep
            else:
                h, _, p = str(ep).rpartition(":")
            out.append((str(h), int(p)))
        if not out:
            raise ValueError("HttpServeClient wants at least one endpoint")
        return out
    if host is None or port is None:
        raise ValueError(
            "HttpServeClient wants (host, port) or endpoints=[...]"
        )
    return [(str(host), int(port))]


class HttpServeClient:
    """One tenant's connection to the HTTP JSONL front — or a FLEET of
    fronts.

    ``submit`` POSTs per request (a fresh connection each time — the
    submit path is stateless, so daemon restarts are invisible to it
    beyond a retriable :class:`ServeUnavailableError`); ``result`` drains
    the long-lived chunked ``/v1/stream`` connections the reader threads
    own. Timing hooks for the load generator: ``on_line(msg)`` fires on
    every stream line as it is read.

    With ``endpoints=[...]`` (or the router's fleet view) the client
    holds ONE stream per endpoint — results land on whichever replica
    dispatched them — and ``submit`` fails over deterministically in
    list order on :class:`ServeUnavailableError`, honoring each
    endpoint's own Retry-After embargo. ``result`` deduplicates by
    request_id, so a row replayed by a WAL adoption is delivered exactly
    once."""

    def __init__(
        self,
        host=None,
        port=None,
        tenant: str = "",
        token: Optional[str] = None,
        timeout: float = 30.0,
        on_line=None,
        endpoints=None,
    ):
        self.endpoints = _normalize_endpoints(host, port, endpoints)
        self._ep_idx = 0
        self.host, self.port = self.endpoints[0]
        self.tenant = tenant
        self.token = token
        self.timeout = float(timeout)
        self.last_event: Optional[str] = None
        self.overflow_dropped = 0  # rows the daemon shed on our stream
        self._on_line = on_line
        self.rejected_total = 0  # 429 replies seen
        self.retried_total = 0  # submissions re-sent after a 429
        self.failovers_total = 0  # endpoint rotations after a drop
        #: endpoint index -> monotonic instant before which its own 429
        #: quote says not to bother it again
        self._not_before: dict[int, float] = {}
        self._results: "queue_lib.Queue[dict]" = queue_lib.Queue()
        self._delivered: set = set()  # request_ids handed to the caller
        self._closed = threading.Event()
        self._stop = False
        self._live_readers = len(self.endpoints)
        self._reader_lock = threading.Lock()
        self._stream_resps: list = [None] * len(self.endpoints)
        self._readers = []
        for i, (h, p) in enumerate(self.endpoints):
            t = threading.Thread(
                target=self._stream_loop, args=(i, h, p),
                name=f"eh-serve-http-client-{i}", daemon=True,
            )
            t.start()
            self._readers.append(t)

    @property
    def endpoint(self) -> str:
        """The URL of the endpoint currently preferred for submission."""
        h, p = self.endpoints[self._ep_idx]
        return f"http://{h}:{p}"

    # ---- submit ----------------------------------------------------------

    def _headers(self) -> dict:
        h = {"Content-Type": "application/json"}
        if self.token is not None:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def submit(
        self,
        label: str,
        config: dict,
        target_loss: Optional[float] = None,
        data_seed: int = 0,
        priority: int = 0,
        max_retries: int = 0,
        backoff_base: float = 0.1,
        backoff_cap: float = 10.0,
    ) -> str:
        """POST one request; returns its request_id. 429s retry on the
        deterministic capped-exponential schedule honoring Retry-After
        (see :func:`backoff_s`); exhausted retries raise
        :class:`ServeRejectedError`; a dead daemon raises
        :class:`ServeUnavailableError` — unless a peer endpoint is
        configured, in which case the submission fails over to the next
        endpoint in list order (a request is only ever re-sent when no
        endpoint acknowledged it, and acceptance is idempotent by digest
        server-side, so failover cannot double-submit). Each endpoint's
        429 quote embargoes THAT endpoint; embargoed peers are skipped
        while the embargo holds."""
        import http.client

        for attempt in range(max_retries + 1):
            body = json.dumps(
                {
                    "tenant": self.tenant,
                    "label": label,
                    "config": config,
                    "target_loss": target_loss,
                    "data_seed": data_seed,
                    "priority": priority,
                    "retry": attempt,
                }
            )
            # one deterministic pass over the endpoint ring, starting at
            # the currently preferred endpoint
            last_exc = None
            pass_retry_after: Optional[float] = None
            saw_rejection = False
            for _hop in range(len(self.endpoints)):
                idx = self._ep_idx
                host, port = self.endpoints[idx]
                embargo = self._not_before.get(idx, 0.0) - time.monotonic()
                if embargo > 0 and len(self.endpoints) > 1:
                    # its own quote says not yet — try the next peer
                    pass_retry_after = (
                        embargo
                        if pass_retry_after is None
                        else min(pass_retry_after, embargo)
                    )
                    self._ep_idx = (idx + 1) % len(self.endpoints)
                    continue
                conn = http.client.HTTPConnection(
                    host, port, timeout=self.timeout
                )
                try:
                    conn.request(
                        "POST", "/v1/submit", body=body,
                        headers=self._headers(),
                    )
                    resp = conn.getresponse()
                    payload = json.loads(resp.read() or b"{}")
                except (OSError, http.client.HTTPException) as e:
                    # a reset/refused under burst load is transient
                    # (accept backlog, front mid-restart): rotate to the
                    # next endpoint — submission is idempotent by
                    # digest, so a resent acceptance can't
                    # double-dispatch
                    last_exc = e
                    if len(self.endpoints) > 1:
                        self._ep_idx = (idx + 1) % len(self.endpoints)
                        self.failovers_total += 1
                        continue
                    if attempt < max_retries and isinstance(
                        e, (ConnectionError, TimeoutError)
                    ):
                        break  # next attempt after the backoff below
                    raise ServeUnavailableError(
                        self.endpoint, self.last_event, str(e)
                    ) from e
                finally:
                    conn.close()
                if resp.status == 202:
                    self.last_eta_s = payload.get("eta_s")
                    return payload["request_id"]
                if resp.status == 429:
                    retry_after = float(
                        payload.get("retry_after_s")
                        or resp.getheader("Retry-After")
                        or 0.0
                    )
                    # the quote embargoes THIS endpoint only
                    self._not_before[idx] = (
                        time.monotonic() + retry_after
                    )
                    pass_retry_after = (
                        retry_after
                        if pass_retry_after is None
                        else min(pass_retry_after, retry_after)
                    )
                    self.rejected_total += 1
                    saw_rejection = True
                    if len(self.endpoints) > 1:
                        self._ep_idx = (idx + 1) % len(self.endpoints)
                        continue
                    break  # single endpoint: back off below
                raise RuntimeError(
                    f"serve daemon refused the request "
                    f"(HTTP {resp.status}): "
                    f"{payload.get('message', payload)}"
                )
            # the whole ring failed this pass: back off and re-walk, or
            # surface the typed error once attempts are exhausted
            if attempt < max_retries:
                if saw_rejection:
                    self.retried_total += 1
                time.sleep(
                    backoff_s(
                        attempt, pass_retry_after,
                        base=backoff_base, cap=backoff_cap,
                    )
                )
                continue
            if saw_rejection or (
                last_exc is None and pass_retry_after is not None
            ):
                raise ServeRejectedError(
                    "serve daemon rejected the request (overloaded)",
                    retry_after_s=pass_retry_after or 0.0,
                )
            raise ServeUnavailableError(
                self.endpoint,
                self.last_event,
                str(last_exc) if last_exc else "no reachable endpoint",
            ) from last_exc
        raise AssertionError("unreachable")

    # ---- result stream ---------------------------------------------------

    def _stream_loop(self, idx: int, host: str, port: int) -> None:
        """One endpoint's stream reader: all readers feed the one result
        queue (``result`` dedups by request_id). ``_closed`` is only set
        once EVERY endpoint's stream is dead — one dying replica doesn't
        strand a fleet client that still owes results from its peers."""
        import http.client

        try:
            path = "/v1/stream"
            if self.token is None:
                path += f"?tenant={self.tenant}"
            conn = http.client.HTTPConnection(
                host, port, timeout=max(self.timeout, 10.0)
            )
            conn.request("GET", path, headers=self._headers())
            resp = conn.getresponse()
            self._stream_resps[idx] = conn
            if resp.status != 200:
                return
            while not self._stop:
                raw = resp.readline()  # chunked decoding is transparent
                if not raw:
                    return
                try:
                    msg = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                self.last_event = msg.get("type")
                if self._on_line is not None:
                    self._on_line(msg)
                if msg.get("type") == "result":
                    self._results.put(msg)
                elif msg.get("type") == "overflow":
                    # the daemon shed rows our reader was too slow for;
                    # they are journaled — re-fetch by resubmitting
                    self.overflow_dropped += int(msg.get("dropped", 0))
        except Exception:  # noqa: BLE001 — reader thread must not crash
            return
        finally:
            with self._reader_lock:
                self._live_readers -= 1
                if self._live_readers <= 0:
                    self._closed.set()

    def result(self, timeout: Optional[float] = None) -> dict:
        """The next finished trajectory off the stream(s); ``queue.Empty``
        on a live timeout, :class:`ServeUnavailableError` once every
        stream is dead and drained. Exactly-once per request_id: a row
        that reaches the client twice (WAL adoption replayed it on a
        peer whose stream we also hold) is delivered once."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while True:
            try:
                msg = self._results.get(timeout=0.2)
            except queue_lib.Empty:
                if self._closed.is_set() and self._results.empty():
                    raise ServeUnavailableError(
                        self.endpoint, self.last_event,
                        "stream closed with results still owed (rows "
                        "are journaled; resubmit to re-fetch)",
                    ) from None
                if deadline is not None and time.monotonic() >= deadline:
                    raise
                continue
            rid = msg.get("request_id")
            if rid is not None:
                if rid in self._delivered:
                    continue  # duplicate via a second stream — drop
                self._delivered.add(rid)
            return msg

    def close(self) -> None:
        self._stop = True
        for conn in self._stream_resps:
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
